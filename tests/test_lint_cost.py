"""apexcost (apex_tpu.lint.cost): donation-aware liveness on
hand-built fixture jaxprs, ledger round-trip + tolerance-band edges,
the card-vs-card diff gate (an injected regression must be NAMED),
the three-way --write-baseline target contract, the ddp telemetry
cross-check, the perf_gate ledger rows, and the --cost wall-clock
budget.

Suite `run_lint_cost` in tests/run_test.py.
"""

import json
import os
import subprocess
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.lint import cost
from apex_tpu.lint.cost import cards as cost_cards
from apex_tpu.lint.cost import ledger as cost_ledger
from apex_tpu.lint.cost import liveness
from apex_tpu.lint.semantic import registry

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
LEDGER = os.path.join(REPO, "apex_tpu", "lint", "cost", "ledger.json")


# ---------------------------------------------------------------------------
# donation-aware liveness on hand-built fixtures (satellite 3)
# ---------------------------------------------------------------------------

N = 1024
S = N * 4   # buffer bytes (f32)


def _peak(fn, args, donate=()):
    jaxpr = jax.make_jaxpr(fn)(*args)
    donated = liveness.donated_flat_indices(args, donate)
    return liveness.analyze(jaxpr, donated)


def test_donated_in_place_update_does_not_bump_peak():
    """The collapse rule: a donated x.at[i].set(v) reuses the dying
    donated buffer in place, so donating shaves EXACTLY one buffer
    size off the undonated peak of the same program."""
    def update(x):
        return x.at[3].set(1.0)

    x = (jnp.zeros((N,), jnp.float32),)
    donated = _peak(update, x, donate=(0,))
    undonated = _peak(update, x, donate=())
    assert undonated.peak_bytes - donated.peak_bytes == S, \
        (donated.peak_bytes, undonated.peak_bytes)


def test_defensive_copy_bumps_peak_by_exactly_the_buffer_size():
    """A deliberately inserted defensive copy (the pre-update value
    saved as a live program output) must cost exactly one buffer: the
    donated input can no longer die into the update."""
    def clean(x):
        return x.at[3].set(1.0)

    def copying(x):
        saved = x + 0.0          # defensive copy, kept live as output
        return x.at[3].set(1.0), saved

    x = (jnp.zeros((N,), jnp.float32),)
    p_clean = _peak(clean, x, donate=(0,))
    p_copy = _peak(copying, x, donate=(0,))
    assert p_copy.peak_bytes - p_clean.peak_bytes == S, \
        (p_clean.peak_bytes, p_copy.peak_bytes)


def test_caller_owned_inputs_live_for_the_whole_program():
    """Non-donated inputs never collapse: even when the input's last
    read is the first equation, its bytes stay in every later peak."""
    def f(x):
        y = x * 2.0
        return y.at[0].set(1.0)

    rep = _peak(f, (jnp.zeros((N,), jnp.float32),), donate=())
    # x (caller-owned) + y's storage live simultaneously
    assert rep.peak_bytes >= 2 * S


def test_peak_buffers_name_shape_dtype_and_producer():
    rep = _peak(lambda x: x * 2.0 + 1.0,
                (jnp.zeros((8, 8), jnp.float32),))
    labels = [b["label"] for b in rep.peak_buffers]
    assert any("float32[8,8]" in l for l in labels), labels
    assert any(l.startswith("in0:") for l in labels), labels


def test_bytes_moved_multiplies_scan_bodies_by_trip_count():
    def body_once(c, _):
        return c * 2.0, None

    def scanned(c):
        out, _ = jax.lax.scan(body_once, c, None, length=16)
        return out

    one = _peak(lambda c: body_once(c, None)[0],
                (jnp.zeros((N,), jnp.float32),))
    many = _peak(scanned, (jnp.zeros((N,), jnp.float32),))
    # the scan body's traffic is paid `length` times; the outer scan
    # eqn adds its own operand/result pass on top
    assert many.bytes_moved >= 16 * one.bytes_moved


def test_extended_prng_key_dtype_does_not_crash_sizing():
    def f(key):
        return jax.random.normal(key, (4,))

    rep = _peak(f, (jax.random.key(0),))
    assert rep.peak_bytes > 0


# ---------------------------------------------------------------------------
# collective payloads: the static twin vs ddp telemetry (satellite 6)
# ---------------------------------------------------------------------------

def _psum_payload_of(reduce_fn, bufs):
    """Static psum payload bytes of a shard_map'd reduction."""
    from jax.sharding import Mesh, PartitionSpec as P
    from apex_tpu import comm
    mesh = Mesh(np.array(jax.devices()[:1]), (comm.AXIS_DATA,))
    fn = comm.shard_map(lambda b: reduce_fn(b), mesh,
                        in_specs=(P(),), out_specs=P())
    rep = _peak(fn, (bufs,))
    return rep.collective_payloads.get("psum", 0)


def _traced_telemetry(reduce_fn, bufs, monkeypatch):
    """ddp/bytes_allreduced exactly as distributed.py emits it.

    Shapes are static, so the figure is a concrete Python float at
    the emit call site; we spy there (rather than reading the tape
    after the trace) because tape values become trace-local arrays —
    the production reader is the instrument wrapper INSIDE the same
    trace."""
    from jax.sharding import Mesh, PartitionSpec as P
    from apex_tpu import comm
    from apex_tpu.telemetry import _tape
    captured = []
    real_emit = _tape.emit
    def spy(name, value, reduce="last"):
        if name == "ddp/bytes_allreduced":
            captured.append(float(value))
        return real_emit(name, value, reduce=reduce)
    monkeypatch.setattr(_tape, "emit", spy)
    mesh = Mesh(np.array(jax.devices()[:1]), (comm.AXIS_DATA,))
    fn = comm.shard_map(lambda b: reduce_fn(b), mesh,
                        in_specs=(P(),), out_specs=P())
    tape = _tape.push()    # emit is a no-op without an active tape
    try:
        jax.make_jaxpr(fn)(bufs)
    finally:
        _tape.pop()
    assert captured, "reduce path never emitted ddp/bytes_allreduced"
    return sum(captured)   # the reduce="sum" fold, host-side


def test_static_collective_bytes_agree_with_ddp_telemetry_flat(monkeypatch):
    """Flat-buffer path, f32: both sides must report the same wire
    bytes — (256 + 128) f32 elements x 4B."""
    from apex_tpu import comm
    from apex_tpu.parallel.distributed import all_reduce_flat_buffers

    def reduce(bufs):
        return tuple(all_reduce_flat_buffers(list(bufs),
                                             comm.AXIS_DATA))

    bufs = (jnp.ones((256,), jnp.float32), jnp.ones((128,), jnp.float32))
    static = _psum_payload_of(reduce, bufs)
    traced = _traced_telemetry(reduce, bufs, monkeypatch)
    assert static == (256 + 128) * 4
    assert traced == static, (traced, static)


def test_static_collective_bytes_agree_with_ddp_telemetry_per_leaf(monkeypatch):
    """Per-leaf path with a bf16 leaf: the collective operand is cast
    to f32 BEFORE the psum, so the wire payload is 4 B/elt regardless
    of storage dtype.  The telemetry used to count input-dtype bytes
    (2 B for bf16) and under-reported by half — this cross-check pins
    the reconciled figure on both sides."""
    from apex_tpu import comm
    from apex_tpu.parallel.distributed import all_reduce_gradients

    def reduce(bufs):
        return all_reduce_gradients(list(bufs), comm.AXIS_DATA,
                                    average=False)

    bufs = (jnp.ones((256,), jnp.bfloat16), jnp.ones((128,), jnp.float32))
    static = _psum_payload_of(reduce, bufs)
    traced = _traced_telemetry(reduce, bufs, monkeypatch)
    assert static == (256 + 128) * 4   # f32 on the wire, NOT 2B bf16
    assert traced == static, (traced, static)


def test_ddp_card_extras_match_the_budget_row():
    """The committed ledger's ddp card carries the static payload the
    perf-budget row extra.ddp_collective_bytes_per_step defends."""
    doc = cost_ledger.load(LEDGER)
    card = doc["cards"]["ddp.all_reduce_flat_buffers"]
    assert card["extras"]["ddp_collective_bytes_per_step"] == 1536
    budget = json.load(open(os.path.join(REPO, "tools",
                                         "perf_budget.json")))
    row = budget["metrics"]["extra.ddp_collective_bytes_per_step"]
    assert row["source"] == "ledger"
    assert row["ceiling"] == 1536 and row["noise_pct"] == 0.0


# ---------------------------------------------------------------------------
# ledger: round-trip, tolerance edges, card-vs-card diff (satellite 3)
# ---------------------------------------------------------------------------

def _card(peak=1000, coll=0, xfer=0, moved=5000, bufs=None, **kw):
    c = {"peak_bytes": peak, "collective_bytes": coll,
         "transfers": xfer, "bytes_moved": moved,
         "collective_payloads": {}, "peak_buffers": bufs or [],
         "flops": None}
    c.update(kw)
    return c


def test_ledger_round_trip_preserves_tolerance(tmp_path):
    path = str(tmp_path / "ledger.json")
    cost_ledger.save(path, {"spec.a": _card()})
    doc = cost_ledger.load(path)
    assert doc["schema"] == cost_ledger.SCHEMA_VERSION
    # hand-set a tolerance band; regeneration must keep it
    doc["cards"]["spec.a"]["tolerance_pct"] = 7.5
    json.dump(doc, open(path, "w"))
    cost_ledger.save(path, {"spec.a": _card(peak=2000)})
    doc2 = cost_ledger.load(path)
    assert doc2["cards"]["spec.a"]["tolerance_pct"] == 7.5
    assert doc2["cards"]["spec.a"]["peak_bytes"] == 2000


def test_ledger_diff_tolerance_band_edges(tmp_path):
    path = str(tmp_path / "ledger.json")
    cost_ledger.save(path, {"spec.a": _card(peak=1000)})
    doc = cost_ledger.load(path)
    doc["cards"]["spec.a"]["tolerance_pct"] = 10.0

    # exactly AT the band: 1100 vs 1000 @ 10% — not a regression
    gating, _ = cost_ledger.diff({"spec.a": _card(peak=1100)}, doc)
    assert not gating
    # one byte beyond the band gates
    gating, _ = cost_ledger.diff({"spec.a": _card(peak=1101)}, doc)
    assert len(gating) == 1 and "peak_bytes grew" in gating[0][1]
    # zero tolerance: +1 byte gates
    doc["cards"]["spec.a"]["tolerance_pct"] = 0.0
    gating, _ = cost_ledger.diff({"spec.a": _card(peak=1001)}, doc)
    assert len(gating) == 1
    # equality never gates
    gating, _ = cost_ledger.diff({"spec.a": _card(peak=1000)}, doc)
    assert not gating


def test_ledger_diff_names_the_offending_buffers(tmp_path):
    path = str(tmp_path / "ledger.json")
    old = _card(peak=1000,
                bufs=[{"label": "in0:float32[256]", "bytes": 1024}])
    cost_ledger.save(path, {"spec.a": old})
    new = _card(peak=5096, bufs=[
        {"label": "in0:float32[256]", "bytes": 1024},
        {"label": "concatenate:float32[1024]", "bytes": 4096}])
    gating, _ = cost_ledger.diff({"spec.a": new},
                                 cost_ledger.load(path))
    assert len(gating) == 1
    name, msg = gating[0]
    assert name == "spec.a"
    assert "concatenate:float32[1024]" in msg and "4096" in msg


def test_ledger_diff_collective_growth_and_missing_entry(tmp_path):
    path = str(tmp_path / "ledger.json")
    cost_ledger.save(path, {"spec.a": _card(
        coll=512, collective_payloads={"psum": 512})})
    doc = cost_ledger.load(path)
    # grown payload names the per-prim delta
    gating, _ = cost_ledger.diff(
        {"spec.a": _card(coll=1024,
                         collective_payloads={"psum": 1024})}, doc)
    assert len(gating) == 1 and "psum 512B -> 1024B" in gating[0][1]
    # an unenrolled entry point gates too
    gating, _ = cost_ledger.diff(
        {"spec.a": _card(coll=512, collective_payloads={"psum": 512}),
         "spec.new": _card()}, doc)
    assert [n for n, _ in gating] == ["spec.new"]
    # shrinkage and stale entries are notes, never gates
    gating, notes = cost_ledger.diff(
        {"spec.b": _card(coll=0)}, doc)
    assert [n for n, _ in gating] == ["spec.b"]
    assert any("stale" in n for n in notes)


def test_ledger_validate_rejects_hand_edits(tmp_path):
    doc = {"schema": cost_ledger.SCHEMA_VERSION,
           "cards": {"a": _card()}}
    assert not cost_ledger.validate(doc)
    assert cost_ledger.validate({"schema": 99, "cards": {"a": _card()}})
    assert cost_ledger.validate({"schema": 1, "cards": {}})
    bad = {"schema": 1, "cards": {"a": _card(peak="big")}}
    assert any("peak_bytes" in e for e in cost_ledger.validate(bad))
    bad = {"schema": 1, "cards": {"a": _card(tolerance_pct=-1)}}
    assert any("tolerance_pct" in e for e in cost_ledger.validate(bad))


# ---------------------------------------------------------------------------
# the acceptance gate: shipped tree is green; an injected
# materialization fails with the entry point NAMED
# ---------------------------------------------------------------------------

def test_shipped_ledger_covers_every_registered_spec():
    doc = cost_ledger.load(LEDGER)
    names = {s.name for s in registry.all_specs()}
    assert set(doc["cards"]) == names
    assert len(names) >= 31


def test_injected_regression_fails_the_gate_naming_the_spec(monkeypatch):
    """THE acceptance test: register a scratch spec, enroll it in a
    copy of the ledger, grow its collective payload, and the cost
    tier must gate with an APX903 finding naming that entry point and
    the payload diff."""
    from jax.sharding import Mesh, PartitionSpec as P
    from apex_tpu import comm
    # FLOPs are report-only and cost an XLA compile per card — skip
    # them here, the gate under test reads only the liveness fields
    monkeypatch.setattr(cost_cards, "_spec_flops", lambda env: None)

    def small(bufs):
        return jax.lax.psum(bufs, comm.AXIS_DATA)

    def grown(bufs):
        # same program plus an extra materialized copy AND a second
        # collective — both peak and payload regress
        extra = jax.lax.psum(bufs * 2.0, comm.AXIS_DATA)
        return jax.lax.psum(bufs, comm.AXIS_DATA) + extra

    mesh = Mesh(np.array(jax.devices()[:1]), (comm.AXIS_DATA,))

    def builder_for(fn):
        wrapped = comm.shard_map(fn, mesh, in_specs=(P(),),
                                 out_specs=P())
        return lambda: {"fn": wrapped,
                        "args": (jnp.ones((64,), jnp.float32),),
                        "expect": {"no_f64": True}}

    name = "scratch.cost_regression"
    registry.register_spec(name, anchor="apex_tpu/lint/cost/cards.py")(
        builder_for(small))
    try:
        import tempfile
        with tempfile.TemporaryDirectory() as td:
            path = os.path.join(td, "ledger.json")
            n, errors = cost.write_ledger(path, names=[name])
            assert n == 1 and not errors

            # same program: green
            findings, _, _, _ = cost.run_cost(names=[name],
                                              ledger_path=path)
            assert not findings, [f.message for f in findings]

            # regressed program: APX903 naming the spec
            # (register_spec replaces idempotently)
            registry.register_spec(name,
                                   anchor="apex_tpu/lint/cost/cards.py",
                                   )(builder_for(grown))
            findings, _, _, _ = cost.run_cost(names=[name],
                                              ledger_path=path)
            msgs = [f.message for f in findings
                    if f.rule_id == "APX903"]
            assert msgs, findings
            assert any(name in m and "collective_bytes grew" in m
                       for m in msgs), msgs
            assert any("psum" in m for m in msgs), msgs
    finally:
        registry._REGISTRY.pop(name, None)


def test_serving_decode_peak_fits_its_arena_geometry():
    """The ledger cross-check the tentpole names: the decode window's
    peak stays strictly below inputs + one extra arena generation —
    the donated KV arena is never double-buffered."""
    doc = cost_ledger.load(LEDGER)
    card = doc["cards"]["serving.decode_step"]
    arena = card["extras"]["arena_bytes"]
    assert arena > 0
    assert card["peak_bytes"] < card["input_bytes"] + arena
    assert card["extras"]["serving_hbm_bytes_per_slot"] == \
        card["donated_bytes"] // 2   # fixture geometry: 2 slots


def test_cost_build_error_reports_apx904():
    name = "scratch.cost_broken"
    registry.register_spec(name, anchor="apex_tpu/lint/cost/cards.py")(
        lambda: (_ for _ in ()).throw(RuntimeError("boom")))
    try:
        findings, cards_out, _, _ = cost.run_cost(
            names=[name], ledger_path=LEDGER)
        assert name not in cards_out
        assert any(f.rule_id == "APX904" and "boom" in f.message
                   for f in findings)
    finally:
        registry._REGISTRY.pop(name, None)


# ---------------------------------------------------------------------------
# perf_gate ledger rows
# ---------------------------------------------------------------------------

def _load_perf_gate():
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "perf_gate", os.path.join(REPO, "tools", "perf_gate.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_perf_gate_grades_ledger_rows_structurally():
    pg = _load_perf_gate()
    doc = cost_ledger.load(LEDGER)
    spec = {"ceiling": 2456, "direction": "lower", "noise_pct": 0.0,
            "source": "ledger", "ledger_entry": "serving.decode_step",
            "ledger_field": "extras.serving_hbm_bytes_per_slot"}
    v = pg._check_ledger("extra.serving_hbm_bytes_per_slot", spec, doc)
    assert v["status"] == "ok" and v["newest"] == 2456
    # one byte over the zero-noise ceiling regresses
    tight = dict(spec, ceiling=2455)
    v = pg._check_ledger("x", tight, doc)
    assert v["status"] == "regression"
    # vanished field grades stale (gating), not silently green
    gone = dict(spec, ledger_field="extras.nope")
    assert pg._check_ledger("x", gone, doc)["status"] == "stale"
    assert pg._check_ledger("x", spec, None)["status"] == "stale"


def test_perf_gate_structural_rows_gate_even_report_only_mode():
    """A ledger-row regression exits 1 even when the BENCH trajectory
    keeps the gate in report-only auto mode (only --report waives)."""
    pg = _load_perf_gate()
    import tempfile
    with tempfile.TemporaryDirectory() as td:
        budget = os.path.join(td, "budget.json")
        json.dump({"stamped_at": "2026-07-31T00:00:00Z", "metrics": {
            "extra.serving_hbm_bytes_per_slot": {
                "ceiling": 1, "direction": "lower", "noise_pct": 0.0,
                "source": "ledger",
                "ledger_entry": "serving.decode_step",
                "ledger_field": "extras.serving_hbm_bytes_per_slot"}}},
            open(budget, "w"))
        # empty BENCH root: no rounds at all, still gates
        assert pg.main(["--budget", budget, "--root", td]) == 1
        assert pg.main(["--budget", budget, "--root", td,
                        "--report"]) == 0


# ---------------------------------------------------------------------------
# CLI: --cost rendering, --write-ledger, three-way --write-baseline
# ---------------------------------------------------------------------------

def _cli(args, **kw):
    return subprocess.run([sys.executable, "-m", "apex_tpu.lint"]
                          + args, capture_output=True, text=True,
                          cwd=REPO, timeout=240, **kw)


def test_write_baseline_pairwise_targets_exit_2():
    """Exactly-one-target contract across all THREE tiers, pairwise —
    in-process (the ambiguity check runs before any linting, so these
    are cheap)."""
    from apex_tpu.lint import cli
    pairs = [["--semantic", "--concurrency"],
             ["--semantic", "--cost"],
             ["--concurrency", "--cost"],
             ["--semantic", "--concurrency", "--cost"]]
    for tiers in pairs:
        rc = cli.main(tiers + ["--write-baseline", "apex_tpu/lint/"])
        assert rc == 2, tiers
    # no tier and no file still refuses (late, after linting)
    assert cli.main(["--write-baseline",
                     "apex_tpu/lint/findings.py"]) == 2


def test_write_baseline_cost_targets_the_ledger(tmp_path, monkeypatch,
                                                capsys):
    """--write-baseline --cost (and --write-ledger) regenerate the
    ledger without touching the other tiers' baselines."""
    from apex_tpu.lint import cli
    sem_default = os.path.join(REPO, "apex_tpu", "lint", "semantic",
                               "baseline.json")
    conc_default = os.path.join(REPO, "apex_tpu", "lint",
                                "concurrency", "baseline.json")
    before = (open(sem_default).read(), open(conc_default).read())
    target = str(tmp_path / "ledger.json")
    monkeypatch.setattr(cost.ledger, "DEFAULT_LEDGER", target)
    # skip the report-only FLOPs (one XLA compile per card) — this
    # test is about target routing, and tier-1 wall-clock is budgeted
    monkeypatch.setattr(cost_cards, "_spec_flops", lambda env: None)
    rc = cli.main(["--write-baseline", "--cost"])
    out = capsys.readouterr().out
    assert rc == 0 and os.path.exists(target)
    doc = cost_ledger.load(target)
    assert len(doc["cards"]) >= 31
    assert "cost card" in out
    after = (open(sem_default).read(), open(conc_default).read())
    assert before == after


def test_cost_full_pass_wall_clock_budget():
    """One full --cost pass (all 31 specs, green vs the committed
    ledger) renders the card table AND stays inside the same <60 s
    one-process budget the semantic gate lives under (tools/check.sh
    runs both)."""
    t0 = time.monotonic()
    proc = _cli(["--cost", "apex_tpu/lint/cost/"])
    elapsed = time.monotonic() - t0
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "apexcost:" in proc.stdout
    assert "serving.decode_step" in proc.stdout
    assert elapsed < 60.0, f"--cost pass took {elapsed:.1f}s"


def test_cost_cli_json_payload(monkeypatch, capsys):
    """--cost --json carries the full card set; in-process with the
    report-only FLOPs skipped (they are not in the JSON contract's
    gated surface and cost one XLA compile per card)."""
    from apex_tpu.lint import cli
    monkeypatch.setattr(cost_cards, "_spec_flops", lambda env: None)
    rc = cli.main(["--cost", "--json",
                   os.path.join(REPO, "apex_tpu", "lint", "cost")])
    out = capsys.readouterr().out
    assert rc == 0, out
    payload = json.loads(out)
    assert payload["cost_cards_checked"] >= 31
    card = payload["cost_cards"]["ddp.all_reduce_flat_buffers"]
    assert card["collective_bytes"] == 1536


# ---------------------------------------------------------------------------
# bench + spec census plumbing (satellites 1 and 5)
# ---------------------------------------------------------------------------

def test_bench_cost_extract_smoke():
    from apex_tpu.lint.cost.bench import bench_cost_extract
    r = bench_cost_extract(limit=2)
    assert r["cost_specs"] == 2 and r["cost_errors"] == 0
    assert r["cost_extract_ms"] > 0
    assert r["cost_total_ms"] >= r["cost_extract_ms"]


def test_check_sh_derives_spec_census_from_list_specs(capsys):
    """The gate script counts non-indented --list-specs lines instead
    of a hand-bumped literal, and keeps a committed floor."""
    src = open(os.path.join(REPO, "tools", "check.sh")).read()
    assert "--list-specs" in src
    assert "SPEC_FLOOR" in src
    assert "assert n == 31" not in src
    # the derivation rule matches reality: one non-indented line per
    # registered spec
    from apex_tpu.lint import cli
    assert cli.main(["--list-specs"]) == 0
    out = capsys.readouterr().out
    n = sum(1 for l in out.splitlines()
            if l and not l.startswith(" "))
    assert n == len(list(registry.all_specs()))
    assert n >= 31


def test_check_sh_runs_the_cost_tier():
    src = open(os.path.join(REPO, "tools", "check.sh")).read()
    assert "--cost" in src
