"""Fused softmax + RoPE kernels vs XLA oracles, values and grads
(reference models: tests/L0/run_transformer fused softmax tests)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.ops import rope as rope_ops
from apex_tpu.ops import softmax as sm
from apex_tpu.transformer.enums import AttnMaskType
from apex_tpu.transformer.functional import FusedScaleMaskSoftmax


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_scaled_masked_softmax_matches_ref(dtype):
    b, h, sq, sk = 2, 3, 16, 128
    x = jax.random.normal(jax.random.key(0), (b, h, sq, sk),
                          jnp.float32).astype(dtype)
    mask = (jax.random.uniform(jax.random.key(1), (b, 1, sq, sk))
            < 0.3).astype(jnp.int32)
    y = sm.scaled_masked_softmax(x, mask, 0.5)
    want = sm.scaled_masked_softmax_ref(x, mask, 0.5)
    np.testing.assert_allclose(np.asarray(y, np.float32),
                               np.asarray(want, np.float32),
                               rtol=2e-2 if dtype == jnp.bfloat16 else 1e-5,
                               atol=1e-3 if dtype == jnp.bfloat16 else 1e-6)


def test_scaled_masked_softmax_no_mask():
    x = jax.random.normal(jax.random.key(2), (2, 2, 8, 256))
    y = sm.scaled_masked_softmax(x, None, 1.7)
    want = sm.scaled_masked_softmax_ref(x, None, 1.7)
    np.testing.assert_allclose(y, want, rtol=1e-5, atol=1e-6)


def test_scaled_masked_softmax_grads():
    x = jax.random.normal(jax.random.key(3), (2, 2, 8, 128))
    mask = (jax.random.uniform(jax.random.key(4), (2, 1, 8, 128))
            < 0.2).astype(jnp.int32)

    def f(x):
        return jnp.sum(sm.scaled_masked_softmax(x, mask, 0.9) ** 2)

    def fr(x):
        return jnp.sum(sm.scaled_masked_softmax_ref(x, mask, 0.9) ** 2)

    np.testing.assert_allclose(jax.grad(f)(x), jax.grad(fr)(x),
                               rtol=1e-4, atol=1e-6)


def test_causal_softmax_matches_ref_and_grads():
    ab, sq = 4, 128
    x = jax.random.normal(jax.random.key(5), (ab, sq, sq))
    y = sm.scaled_upper_triang_masked_softmax(x, 0.7)
    want = sm.scaled_upper_triang_masked_softmax_ref(x, 0.7)
    np.testing.assert_allclose(y, want, rtol=1e-5, atol=1e-6)
    # strictly-upper entries are (numerically) zero
    assert float(jnp.abs(jnp.triu(y[0], k=1)).max()) < 1e-4

    def f(x):
        return jnp.sum(sm.scaled_upper_triang_masked_softmax(x, 0.7) ** 2)

    def fr(x):
        return jnp.sum(
            sm.scaled_upper_triang_masked_softmax_ref(x, 0.7) ** 2)

    np.testing.assert_allclose(jax.grad(f)(x), jax.grad(fr)(x),
                               rtol=1e-4, atol=1e-5)


def test_fused_scale_mask_softmax_module():
    fsm = FusedScaleMaskSoftmax(attn_mask_type=AttnMaskType.causal,
                                scale=0.5)
    x = jax.random.normal(jax.random.key(6), (2, 2, 64, 64))
    y = fsm(x)
    want = sm.scaled_upper_triang_masked_softmax_ref(
        x.reshape(-1, 64, 64), 0.5).reshape(x.shape)
    np.testing.assert_allclose(y, want, rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("interleaved", [False, True])
@pytest.mark.parametrize("rot_frac", [1.0, 0.5])
def test_rope_matches_ref_and_grads(interleaved, rot_frac):
    s, b, h, d = 10, 2, 3, 16
    rot = int(d * rot_frac)
    t = jax.random.normal(jax.random.key(7), (s, b, h, d))
    freqs = jax.random.normal(jax.random.key(8), (s, 1, 1, rot))
    y = rope_ops.fused_apply_rotary_pos_emb(t, freqs, interleaved)
    want = rope_ops.rope_ref(t, freqs, interleaved)
    np.testing.assert_allclose(y, want, rtol=1e-5, atol=1e-6)

    def f(t):
        return jnp.sum(
            rope_ops.fused_apply_rotary_pos_emb(t, freqs, interleaved)
            * jnp.arange(t.size).reshape(t.shape))

    def fr(t):
        return jnp.sum(rope_ops.rope_ref(t, freqs, interleaved)
                       * jnp.arange(t.size).reshape(t.shape))

    np.testing.assert_allclose(jax.grad(f)(t), jax.grad(fr)(t),
                               rtol=1e-4, atol=1e-5)


def test_fully_masked_rows_output_zeros():
    """Reference kernel semantics: all-masked rows -> zeros, not 1/sk."""
    b, h, sq, sk = 1, 2, 8, 128
    x = jax.random.normal(jax.random.key(9), (b, h, sq, sk))
    mask = jnp.zeros((b, 1, sq, sk), jnp.int32).at[:, :, 0, :].set(1)
    y = sm.scaled_masked_softmax(x, mask, 1.0)
    yr = sm.scaled_masked_softmax_ref(x, mask, 1.0)
    assert float(jnp.abs(y[:, :, 0, :]).max()) == 0.0
    assert float(jnp.abs(yr[:, :, 0, :]).max()) == 0.0
    # grads through a zero row are zero, finite elsewhere
    g = jax.grad(lambda x: jnp.sum(
        sm.scaled_masked_softmax(x, mask, 1.0) ** 2))(x)
    assert float(jnp.abs(g[:, :, 0, :]).max()) == 0.0
    assert bool(jnp.all(jnp.isfinite(g)))


def test_causal_requires_square():
    from apex_tpu.transformer.functional import FusedScaleMaskSoftmax
    fsm = FusedScaleMaskSoftmax(attn_mask_type=AttnMaskType.causal)
    x = jax.random.normal(jax.random.key(10), (1, 1, 1, 128))
    with pytest.raises(AssertionError):
        fsm(x)
