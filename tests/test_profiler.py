"""The performance observatory (apex_tpu.telemetry.profiler +
tools/perf_gate.py): trace parsing, attribution buckets, overlap math,
cost-model MFU, report rendering, perf counters through the session
JSONL, and the BENCH-trajectory regression gate — all CPU-only.

The checked-in fixture (tests/profiler_fixtures/) is hand-built so
every bucket is exactly computable; its README tabulates the math the
assertions below pin."""

import gzip
import importlib.util
import io
import json
import os
import shutil

import pytest

from apex_tpu.telemetry import profiler
from apex_tpu.telemetry.profiler import attribution, events

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURE = os.path.join(_ROOT, "tests", "profiler_fixtures")


def _load_path(name, path):
    spec = importlib.util.spec_from_file_location(name, path)
    m = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(m)
    return m


perf_gate = _load_path("perf_gate",
                       os.path.join(_ROOT, "tools", "perf_gate.py"))


# ---------------------------------------------------------------------------
# parser


def test_fixture_parses_device_thread_only():
    evs = events.load_device_events(FIXTURE)
    # 8 device rows; the python host thread (frame + PjitFunction
    # range) is never device work
    assert len(evs) == 8
    assert {e.thread for e in evs} == {"XLA Ops"}
    assert all(e.hlo_module == "jit_train_step" for e in evs)
    names = [e.name for e in evs]
    assert "PjitFunction(train_step)" not in names
    # rows come back time-sorted with end_us derived
    assert names[0] in ("copy-start.5", "fusion.1")
    assert evs[-1].name == "all-reduce.2"
    assert evs[-1].end_us == pytest.approx(2400.0)


def test_gzip_and_plain_json_parse_identically(tmp_path):
    src = os.path.join(FIXTURE, "synthetic.trace.json")
    d = tmp_path / "plugins" / "profile" / "run1"
    d.mkdir(parents=True)
    with open(src, "rb") as f, gzip.open(d / "host.trace.json.gz",
                                         "wb") as g:
        g.write(f.read())
    assert (events.load_device_events(str(tmp_path))
            == events.load_device_events(FIXTURE))


def test_cpu_fallback_selects_xla_executor_threads(tmp_path):
    # no /device:* process at all: the tf_XLA* pools under /host:CPU
    # stand in (the shape jax's CPU backend actually writes)
    doc = {"traceEvents": [
        {"ph": "M", "pid": 7, "name": "process_name",
         "args": {"name": "/host:CPU"}},
        {"ph": "M", "pid": 7, "tid": 1, "name": "thread_name",
         "args": {"name": "tf_XLAEigen/12"}},
        {"ph": "M", "pid": 7, "tid": 2, "name": "thread_name",
         "args": {"name": "python"}},
        {"ph": "X", "pid": 7, "tid": 1, "name": "dot.4",
         "ts": 10, "dur": 5, "args": {"hlo_op": "dot.4"}},
        {"ph": "X", "pid": 7, "tid": 1,
         "name": "ThreadpoolListener::StartRegion", "ts": 11, "dur": 1},
        {"ph": "X", "pid": 7, "tid": 2, "name": "host_thing",
         "ts": 10, "dur": 5},
    ]}
    (tmp_path / "x.trace.json").write_text(json.dumps(doc))
    evs = events.load_device_events(str(tmp_path))
    assert [e.name for e in evs] == ["dot.4"]   # infra + host excluded


def test_newest_capture_wins_by_mtime(tmp_path):
    import time
    now = time.time()
    for name, op, mtime in (("old", "stale.1", now - 500),
                            ("new", "fresh.2", now)):
        d = tmp_path / "plugins" / "profile" / name
        d.mkdir(parents=True)
        doc = {"traceEvents": [
            {"ph": "M", "pid": 1, "name": "process_name",
             "args": {"name": "/device:TPU:0"}},
            {"ph": "M", "pid": 1, "tid": 1, "name": "thread_name",
             "args": {"name": "XLA Ops"}},
            {"ph": "X", "pid": 1, "tid": 1, "name": op, "ts": 1,
             "dur": 2}]}
        p = d / "t.trace.json.gz"
        with gzip.open(p, "wt") as f:
            json.dump(doc, f)
        os.utime(p, (mtime, mtime))
    assert [e.name for e in
            events.load_device_events(str(tmp_path))] == ["fresh.2"]


def test_xplane_and_json_paths_agree_on_real_capture(tmp_path):
    """Capture a real (tiny) CPU trace and parse BOTH formats: same
    op set, same durations — the stdlib fallback must not diverge
    from the proto path."""
    import jax
    import jax.numpy as jnp

    f = jax.jit(lambda x: jnp.sin(x) @ x.T)
    x = jnp.ones((64, 64), jnp.float32)
    f(x).block_until_ready()
    with profiler.trace(str(tmp_path)):
        f(x).block_until_ready()
    js = events.load_device_events(str(tmp_path), prefer="json")
    xp = events.load_device_events(str(tmp_path), prefer="xplane")
    assert js, "capture produced no device events"
    assert {(e.name, round(e.dur_us, 1)) for e in js} \
        == {(e.name, round(e.dur_us, 1)) for e in xp}
    # hlo stats survive the proto's ref_value indirection
    assert any(e.hlo_module for e in xp)


# ---------------------------------------------------------------------------
# attribution buckets + overlap math


def test_classify_buckets():
    assert attribution.classify("fusion.123") == "compute"
    assert attribution.classify("dot.4") == "compute"
    assert attribution.classify("all-reduce.7") == "collective"
    assert attribution.classify("all-gather-start.2") == "collective"
    assert attribution.classify("reduce-scatter.1") == "collective"
    assert attribution.classify("collective-permute.9") == "collective"
    assert attribution.classify("infeed.1") == "transfer"
    assert attribution.classify("MemcpyD2H") == "transfer"
    assert attribution.classify("copy-start.3") == "transfer"
    # a device-local copy fusion is compute, not host traffic
    assert attribution.classify("copy.17") == "compute"


def test_fixture_breakdown_exact():
    bd = attribution.attribute(events.load_device_events(FIXTURE),
                               steps=2)
    assert bd.window_ms == pytest.approx(1.4)
    assert bd.compute_ms == pytest.approx(1.0)
    assert bd.collective_ms == pytest.approx(0.7)
    assert bd.transfer_ms == pytest.approx(0.06)
    assert bd.idle_ms == pytest.approx(0.05)
    assert bd.collective_hidden_ms == pytest.approx(0.35)
    assert bd.collective_exposed_ms == pytest.approx(0.35)
    assert bd.overlap_pct == pytest.approx(50.0)
    assert bd.step_ms == pytest.approx(0.7)
    assert bd.n_events == 8


def _ev(name, ts, dur):
    return events.DeviceEvent(name=name, start_us=ts, dur_us=dur)


def test_overlap_fully_hidden_vs_fully_trailing():
    # hidden: the collective runs entirely under concurrent compute
    hidden = attribution.attribute([
        _ev("fusion.1", 0, 100),
        _ev("all-reduce.1", 20, 50),
    ])
    assert hidden.overlap_pct == pytest.approx(100.0)
    assert hidden.collective_exposed_ms == pytest.approx(0.0)
    # trailing: the collective lands after backward finished — the
    # exact failure mode ROADMAP item 2 exists to fix
    trailing = attribution.attribute([
        _ev("fusion.1", 0, 100),
        _ev("all-reduce.1", 100, 50),
    ])
    assert trailing.overlap_pct == pytest.approx(0.0)
    assert trailing.collective_exposed_ms == pytest.approx(0.05)
    assert trailing.collective_hidden_ms == pytest.approx(0.0)


def test_overlap_async_pair_spans_inflight_gap():
    # start [0,10], compute [10,90], done [90,100]: the in-flight gap
    # counts as collective time and is fully hidden by the compute
    bd = attribution.attribute([
        _ev("all-reduce-start.1", 0, 10),
        _ev("fusion.1", 10, 80),
        _ev("all-reduce-done.1", 90, 10),
    ])
    assert bd.collective_ms == pytest.approx(0.1)
    assert bd.collective_hidden_ms == pytest.approx(0.08)
    assert bd.idle_ms == pytest.approx(0.0)


def test_no_collectives_reports_none_not_zero():
    bd = attribution.attribute([_ev("fusion.1", 0, 10)])
    assert bd.overlap_pct is None
    assert bd.collective_ms == 0.0


def test_empty_events():
    bd = attribution.attribute([])
    assert bd.window_ms == 0.0 and bd.n_events == 0
    assert bd.step_ms is None


def test_top_ops_table():
    rows = attribution.top_ops(events.load_device_events(FIXTURE),
                               top=3)
    assert [r["op"] for r in rows] == ["fusion.1", "fusion.2",
                                      "fusion.3"]
    assert rows[0]["category"] == "compute"
    assert rows[0]["total_ms"] == pytest.approx(0.4)


# ---------------------------------------------------------------------------
# MFU chip table


def test_chip_table_lookup():
    assert profiler.chip_spec("TPU v5 lite").bf16_flops == 197e12
    assert profiler.chip_spec("TPU v5e").name == "TPU v5e"
    assert profiler.chip_spec("TPU v5p").bf16_flops == 459e12
    assert profiler.chip_spec("TPU v4").bf16_flops == 275e12
    assert profiler.chip_spec("TPU v6e").bf16_flops == 918e12
    assert profiler.chip_spec("Tesla A100") is None
    assert profiler.chip_spec("") is None


def test_mfu_arithmetic_and_refusals():
    # 1e12 flops in 10 ms on a 1e15-peak chip = 0.1
    assert profiler.mfu(1e12, 0.01, 1e15) == pytest.approx(0.1)
    assert profiler.mfu(None, 0.01, 1e15) is None
    assert profiler.mfu(1e12, None, 1e15) is None
    assert profiler.mfu(1e12, 0.01, None) is None
    assert profiler.mfu(1e12, 0.0, 1e15) is None


def test_step_flops_from_cost_analysis():
    import jax
    import jax.numpy as jnp

    f = jax.jit(lambda a, b: a @ b)
    a = jnp.ones((64, 64), jnp.float32)
    flops = profiler.step_flops(f, a, a)
    # 2*M*N*K = 524288 when the backend reports; None is the
    # documented refusal, not a wrong number
    if flops is not None:
        assert flops == pytest.approx(2 * 64 ** 3, rel=0.5)


# ---------------------------------------------------------------------------
# report + CLI


def test_report_on_fixture_matches_readme():
    rep = profiler.build_report(FIXTURE)
    assert rep["steps"] == 2
    assert rep["step_ms"] == pytest.approx(0.7)
    assert rep["overlap_pct"] == pytest.approx(50.0)
    assert rep["mfu"] == pytest.approx(0.25)
    assert rep["mfu_source"] == "cost_analysis"
    bd = rep["breakdown"]
    assert (bd["compute_ms"], bd["collective_ms"], bd["transfer_ms"],
            bd["idle_ms"]) == (1.0, 0.7, 0.06, 0.05)


def test_profile_cli_json_and_text(capsys):
    from apex_tpu.telemetry import cli
    assert cli.main(["profile", FIXTURE, "--json"]) == 0
    rep = json.loads(capsys.readouterr().out)
    assert rep["overlap_pct"] == 50.0
    assert rep["mfu"] == 0.25
    assert {"compute_ms", "collective_ms", "transfer_ms",
            "idle_ms"} <= set(rep["breakdown"])

    assert cli.main(["profile", FIXTURE]) == 0
    out = capsys.readouterr().out
    assert "collective overlap: 50.0% hidden" in out
    assert "MFU: 0.2500" in out
    assert "fusion.1" in out


def test_profile_cli_empty_dir_exits_1(tmp_path, capsys):
    from apex_tpu.telemetry import cli
    assert cli.main(["profile", str(tmp_path)]) == 1
    assert "no device op events" in capsys.readouterr().out
    assert cli.main(["profile", str(tmp_path), "--json"]) == 1
    assert "error" in json.loads(capsys.readouterr().out)


def test_steps_override_beats_sidecar(tmp_path):
    shutil.copy(os.path.join(FIXTURE, "synthetic.trace.json"),
                tmp_path / "synthetic.trace.json")
    # no sidecar: no steps, no mfu — but the breakdown still renders
    rep = profiler.build_report(str(tmp_path))
    assert rep["steps"] is None and rep["mfu"] is None
    rep = profiler.build_report(str(tmp_path), steps=4)
    assert rep["step_ms"] == pytest.approx(0.35)


def test_perf_counters_land_in_session_jsonl(tmp_path):
    """emit_perf_counters -> hostmetrics -> session flush ->
    summarize's perf section, text and --json: the headline numbers
    ride the run's own telemetry."""
    import jax.numpy as jnp

    from apex_tpu import telemetry
    from apex_tpu.telemetry import cli

    run_dir = tmp_path / "run"
    tel = telemetry.Telemetry(str(run_dir), window=4, retrace=False)
    try:
        rep = profiler.build_report(FIXTURE)
        profiler.emit_perf_counters(rep)
        tel.record({"loss": jnp.float32(1.0)}, 0)
    finally:
        tel.close()

    buf = io.StringIO()
    assert cli.summarize(str(run_dir), as_json=True, out=buf) == 0
    doc = json.loads(buf.getvalue())
    assert doc["perf"]["overlap_pct"] == 50.0
    assert doc["perf"]["mfu"] == 0.25
    assert doc["perf"]["step_ms"] == pytest.approx(0.7)

    buf = io.StringIO()
    assert cli.summarize(str(run_dir), out=buf) == 0
    assert "perf (profiler capture)" in buf.getvalue()


def test_profile_window_end_to_end(tmp_path):
    """Real (CPU) capture through profile_window: sidecar written,
    report renders, flops recorded from cost analysis — and the
    perf/* headline counters published to an active session."""
    import jax
    import jax.numpy as jnp

    from apex_tpu import telemetry

    f = jax.jit(lambda x: (jnp.tanh(x @ x.T),))
    x = jnp.ones((64, 64), jnp.float32)
    tel = telemetry.Telemetry(run_dir=None, window=4, retrace=False)
    try:
        meta = profiler.profile_window(f, x, steps=2,
                                       outdir=str(tmp_path / "tr"))
    finally:
        counters = {r["name"] for r in tel.counters.records()}
        tel.close()
    assert meta["steps"] == 2
    assert meta["flops_per_step"] and meta["mfu_source"] \
        == "cost_analysis"
    assert os.path.isfile(tmp_path / "tr" / "profile_meta.json")
    # the capture published its own headline counters (no manual
    # build_report + emit_perf_counters chain needed)
    assert {"perf/step_ms", "perf/compute_ms"} <= counters
    rep = profiler.build_report(str(tmp_path / "tr"))
    assert not rep.get("error")
    assert rep["steps"] == 2
    assert rep["breakdown"]["compute_ms"] > 0


def test_profile_window_threads_donated_state(tmp_path):
    import jax
    import jax.numpy as jnp

    donating = jax.jit(lambda s: (s + 1.0,), donate_argnums=(0,))
    meta = profiler.profile_window(
        donating, jnp.zeros((8,), jnp.float32), steps=3,
        outdir=str(tmp_path), thread_state=True)
    assert meta["steps"] == 3


def test_annotate_step_is_free():
    """The profiler-capable wrapper adds NOTHING to the program (the
    apexverify spec profiler.annotated_step holds the full flat-AMP
    step to this; here the minimal case pins jaxpr equality)."""
    import jax
    import jax.numpy as jnp

    def f(x):
        return jnp.sin(x) * 2.0

    x = jnp.ones((4,), jnp.float32)
    plain = jax.make_jaxpr(f)(x)
    wrapped = jax.make_jaxpr(profiler.annotate_step(f))(x)
    assert [str(e.primitive) for e in plain.eqns] \
        == [str(e.primitive) for e in wrapped.eqns]


def test_profiler_overhead_bench_smoke():
    from apex_tpu.telemetry.bench import bench_profiler_overhead
    out = bench_profiler_overhead(layers=2, hidden=16, iters=2, reps=1)
    assert out["profiler_on_ms"] > 0 and out["profiler_off_ms"] > 0
    assert "profiler_overhead_pct" in out


# ---------------------------------------------------------------------------
# pyprof mixed host+device summary (satellite)


def test_pyprof_merges_host_ranges_with_device_ops():
    from apex_tpu.pyprof import prof
    rows = prof.summarize_ops(FIXTURE)
    where = {r[1] for r in rows}
    assert where == {"device", "host"}
    host_rows = [r for r in rows if r[1] == "host"]
    # the named Pjit range is a host row; the $frame python-tracer row
    # is not
    assert [r[0] for r in host_rows] == ["PjitFunction(train_step)"]
    assert host_rows[0][3] == pytest.approx(100.0)   # share of host side
    dev = [r for r in rows if r[1] == "device"]
    assert dev[0][0] == "fusion.1"


def test_pyprof_main_renders_mixed_and_device_only(capsys):
    from apex_tpu.pyprof import prof
    assert prof.main([FIXTURE]) == 0
    out = capsys.readouterr().out
    assert "PjitFunction(train_step)" in out and "host" in out
    assert prof.main([FIXTURE, "--device-only"]) == 0
    out = capsys.readouterr().out
    assert "PjitFunction(train_step)" not in out
    assert prof.main([FIXTURE, "--json"]) == 0
    rows = json.loads(capsys.readouterr().out)
    assert {"op", "where", "total_ms", "pct"} <= set(rows[0])


# ---------------------------------------------------------------------------
# perf_gate (pass / fail / noise band / trajectory)


def _write_round(root, n, backend, value, extra=None, parsed=True):
    doc = {"n": n}
    if parsed:
        doc["parsed"] = {"backend": backend, "value": value,
                         "extra": extra or {}}
    with open(os.path.join(root, f"BENCH_r{n:02d}.json"), "w") as f:
        json.dump(doc, f)


def _budget(metrics):
    return {"metrics": metrics}


def test_gate_passes_at_floor_and_within_noise(tmp_path):
    _write_round(str(tmp_path), 1, "tpu", 2000.0)
    _write_round(str(tmp_path), 2, "tpu", 1960.0)    # -2%: inside band
    verdicts = perf_gate.evaluate(
        _budget({"value": {"floor": 2000.0, "noise_pct": 5.0}}),
        perf_gate.load_rounds(str(tmp_path)))
    assert [v["status"] for v in verdicts] == ["ok"]


def test_gate_fails_above_noise_budget_breach(tmp_path):
    _write_round(str(tmp_path), 1, "tpu", 1800.0)    # -10% vs floor
    verdicts = perf_gate.evaluate(
        _budget({"value": {"floor": 2000.0, "noise_pct": 5.0}}),
        perf_gate.load_rounds(str(tmp_path)))
    assert verdicts[0]["status"] == "regression"
    assert "floor" in verdicts[0]["detail"]


def test_gate_trajectory_regression_within_budget_slack(tmp_path):
    # floor is generous (1000) but the newest round slid >5% vs the
    # best prior hardware round — the trajectory check catches it
    _write_round(str(tmp_path), 1, "tpu", 2108.0)
    _write_round(str(tmp_path), 2, "tpu", 1900.0)
    verdicts = perf_gate.evaluate(
        _budget({"value": {"floor": 1000.0, "noise_pct": 5.0}}),
        perf_gate.load_rounds(str(tmp_path)))
    assert verdicts[0]["status"] == "regression"
    assert "best prior" in verdicts[0]["detail"]


def test_gate_lower_is_better_ceiling(tmp_path):
    _write_round(str(tmp_path), 1, "tpu", 2000.0,
                 {"bert_step_ms": 140.0})
    verdicts = perf_gate.evaluate(
        _budget({"extra.bert_step_ms": {
            "ceiling": 133.0, "direction": "lower", "noise_pct": 5.0}}),
        perf_gate.load_rounds(str(tmp_path)))
    assert verdicts[0]["status"] == "ok"          # within 5% of ceiling
    _write_round(str(tmp_path), 2, "tpu", 2000.0,
                 {"bert_step_ms": 160.0})
    verdicts = perf_gate.evaluate(
        _budget({"extra.bert_step_ms": {
            "ceiling": 133.0, "direction": "lower", "noise_pct": 5.0}}),
        perf_gate.load_rounds(str(tmp_path)))
    assert verdicts[0]["status"] == "regression"


def test_gate_ignores_cpu_fallback_and_unparsed_rounds(tmp_path):
    _write_round(str(tmp_path), 1, "tpu", 2100.0)
    _write_round(str(tmp_path), 2, "cpu-fallback", 4.0)  # proxy line
    _write_round(str(tmp_path), 3, "tpu", 0.0)           # failed child
    _write_round(str(tmp_path), 4, "tpu", 2100.0, parsed=False)
    rounds = perf_gate.load_rounds(str(tmp_path))
    assert [n for n, _ in perf_gate.hardware_rounds(rounds)] == [1]
    verdicts = perf_gate.evaluate(
        _budget({"value": {"floor": 2000.0, "noise_pct": 5.0}}), rounds)
    assert verdicts[0]["status"] == "ok"
    assert verdicts[0]["rounds"] == [1]


def test_gate_stale_metric_fails_when_newest_round_drops_it(tmp_path):
    # r01 measured the metric, r02 (a valid hardware round) lost the
    # leg: grading r01's old value against the floor would mask the
    # failure — the verdict is stale and it gates
    _write_round(str(tmp_path), 1, "tpu", 2100.0, {"mfu": 0.3})
    _write_round(str(tmp_path), 2, "tpu", 2100.0)
    verdicts = perf_gate.evaluate(
        _budget({"extra.mfu": {"floor": 0.25, "noise_pct": 5.0}}),
        perf_gate.load_rounds(str(tmp_path)))
    assert verdicts[0]["status"] == "stale"
    budget = tmp_path / "budget.json"
    budget.write_text(json.dumps(
        _budget({"extra.mfu": {"floor": 0.25, "noise_pct": 5.0}})))
    assert perf_gate.main(["--budget", str(budget),
                           "--root", str(tmp_path), "--gate"]) == 1
    assert perf_gate.main(["--budget", str(budget),
                           "--root", str(tmp_path), "--report"]) == 0
    # auto mode cannot prove these stamp-less synthetic rounds postdate
    # the budget, so it reports without gating (the full auto-mode
    # date matrix lives in tests/test_autotune.py)
    assert perf_gate.main(["--budget", str(budget),
                           "--root", str(tmp_path)]) == 0


def test_gate_non_numeric_value_skips_round_not_crashes(tmp_path):
    _write_round(str(tmp_path), 1, "tpu", 2100.0)
    _write_round(str(tmp_path), 2, "tpu", "n/a")   # hand-edited artifact
    rounds = perf_gate.load_rounds(str(tmp_path))
    assert [n for n, _ in perf_gate.hardware_rounds(rounds)] == [1]


def test_gate_no_data_metric(tmp_path):
    _write_round(str(tmp_path), 1, "tpu", 2100.0)
    verdicts = perf_gate.evaluate(
        _budget({"extra.never_measured": {"floor": 1.0}}),
        perf_gate.load_rounds(str(tmp_path)))
    assert verdicts[0]["status"] == "no-data"


def test_gate_empty_trajectory_grades_no_rounds(tmp_path, capsys):
    """An EMPTY BENCH trajectory is its own explicit verdict: one
    ``no-rounds`` line with the reason, exit 0 in auto/report mode —
    never the generic metric-by-metric cannot-compare chorus.  A
    forced --gate exits 1 (nothing on record can defend a budget)."""
    budget = tmp_path / "budget.json"
    budget.write_text(json.dumps(
        _budget({"value": {"floor": 2000.0, "noise_pct": 5.0}})))
    assert perf_gate.main(["--budget", str(budget),
                           "--root", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "no-rounds" in out and "empty" in out
    assert "no hardware round reports" not in out   # not the chorus
    assert out.count("\n") == 1                     # one line, done
    assert perf_gate.main(["--budget", str(budget),
                           "--root", str(tmp_path), "--report"]) == 0
    capsys.readouterr()
    assert perf_gate.main(["--budget", str(budget),
                           "--root", str(tmp_path), "--gate"]) == 1
    capsys.readouterr()
    assert perf_gate.main(["--budget", str(budget),
                           "--root", str(tmp_path), "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["status"] == "no-rounds" and doc["verdicts"] == []


def test_gate_main_exit_codes_and_report_mode(tmp_path, capsys):
    budget = tmp_path / "budget.json"
    budget.write_text(json.dumps(
        _budget({"value": {"floor": 2000.0, "noise_pct": 5.0}})))
    _write_round(str(tmp_path), 1, "tpu", 1500.0)    # regression
    assert perf_gate.main(["--budget", str(budget),
                           "--root", str(tmp_path), "--gate"]) == 1
    capsys.readouterr()
    # --report: same verdicts, never gates
    assert perf_gate.main(["--budget", str(budget),
                           "--root", str(tmp_path), "--report"]) == 0
    assert "regression" in capsys.readouterr().out
    # --json stays parseable (and carries the chosen mode)
    assert perf_gate.main(["--budget", str(budget),
                           "--root", str(tmp_path), "--gate",
                           "--json"]) == 1
    doc = json.loads(capsys.readouterr().out)
    assert doc["regressions"] == 1
    assert doc["gating"] and "forced" in doc["mode_reason"]
    # missing budget: usage error, not a crash
    assert perf_gate.main(["--budget", str(tmp_path / "no.json"),
                           "--root", str(tmp_path)]) == 2


def test_gate_clean_on_committed_trajectory():
    """The acceptance criterion: zero exit on the repo's own BENCH
    trajectory with the shipped budget."""
    assert perf_gate.main(["--json"]) == 0


# ---------------------------------------------------------------------------
# bench.py structured errors (satellite)


def test_bench_structured_errors_and_renderer():
    bench = _load_path("bench_mod", os.path.join(_ROOT, "bench.py"))
    e = bench._err("resnet50", "train_bench", "OOM at b256")
    assert e == {"leg": "resnet50", "stage": "train_bench",
                 "error": "OOM at b256"}
    assert bench._err_str(e) == "resnet50[train_bench]: OOM at b256"
    assert bench._err_str("legacy string") == "legacy string"


def test_bench_cached_result_stubs_dict_errors(tmp_path):
    bench = _load_path("bench_mod", os.path.join(_ROOT, "bench.py"))
    p = tmp_path / "bench_tpu.json"
    p.write_text(json.dumps({
        "metric": "m", "value": 2108.2, "backend": "tpu",
        "errors": [{"leg": "flash_8192", "stage": "fwd_bwd",
                    "error": "x" * 500}],
        "extra": {}}))
    c = bench._cached_tpu_result(str(p))
    assert c["errors"][0].startswith("captured: flash_8192[fwd_bwd]: ")
    assert len(c["errors"][0]) <= len("captured: ") + 150
