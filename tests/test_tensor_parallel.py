"""Tensor-parallel layers/mappings/cross-entropy vs dense oracles
(reference models: tests/L0/run_transformer/test_layers.py,
test_mappings.py, cross-entropy tests — SURVEY.md §4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu import comm
from apex_tpu.transformer import tensor_parallel as tp
from apex_tpu.transformer.tensor_parallel import mappings

IN, OUT = 16, 32


def tp_mesh():
    return comm.initialize(data=2, model=4)


def col_specs():
    return {"params": {"weight": P(None, comm.AXIS_MODEL),
                       "bias": P(comm.AXIS_MODEL)}}


def row_specs():
    return {"params": {"weight": P(comm.AXIS_MODEL, None),
                       "bias": P()}}


def init_sharded(mesh, module, x_spec, x, param_specs):
    def init_fn(key, xx):
        return module.init(key, xx)
    return jax.jit(comm.shard_map(init_fn, mesh, in_specs=(P(), x_spec),
                             out_specs=param_specs))(jax.random.key(0), x)


def test_column_parallel_matches_dense():
    mesh = tp_mesh()
    col = tp.ColumnParallelLinear(IN, OUT, gather_output=True)
    x = jax.random.normal(jax.random.key(1), (6, IN))
    params = init_sharded(mesh, col, P(), x, col_specs())

    y = jax.jit(comm.shard_map(lambda p, xx: col.apply(p, xx), mesh,
                          in_specs=(col_specs(), P()),
                          out_specs=P()))(params, x)
    w = params["params"]["weight"]   # assembled (IN, OUT)
    b = params["params"]["bias"]
    want = x @ w + b
    np.testing.assert_allclose(np.asarray(y), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_row_parallel_matches_dense():
    mesh = tp_mesh()
    row = tp.RowParallelLinear(IN, OUT, input_is_parallel=False)
    x = jax.random.normal(jax.random.key(2), (6, IN))
    params = init_sharded(mesh, row, P(), x, row_specs())

    y = jax.jit(comm.shard_map(lambda p, xx: row.apply(p, xx), mesh,
                          in_specs=(row_specs(), P()),
                          out_specs=P()))(params, x)
    w = params["params"]["weight"]
    b = params["params"]["bias"]
    np.testing.assert_allclose(np.asarray(y), np.asarray(x @ w + b),
                               rtol=1e-5, atol=1e-5)


class TwoLayer:
    """Column(no-gather) -> Row(parallel-in): the canonical Megatron MLP
    pairing with exactly one psum."""

    def __init__(self, sequence_parallel=False):
        self.col = tp.ColumnParallelLinear(
            IN, OUT, gather_output=False,
            sequence_parallel_enabled=sequence_parallel)
        self.row = tp.RowParallelLinear(
            OUT, IN, input_is_parallel=True,
            sequence_parallel_enabled=sequence_parallel)

    def init(self, key, x):
        k1, k2 = jax.random.split(key)
        tp_size = comm.model_parallel_size()
        h_local_dim = OUT // tp_size
        h_shape = x.shape[:-1] + (h_local_dim,)
        if self.col.sequence_parallel_enabled:
            # column output under SP carries the FULL (gathered) sequence
            h_shape = (x.shape[0] * tp_size,) + h_shape[1:]
        h = jnp.zeros(h_shape, x.dtype)
        return {"col": self.col.init(k1, x), "row": self.row.init(k2, h)}

    def apply(self, params, x):
        h = self.col.apply(params["col"], x)
        h = jax.nn.gelu(h)
        return self.row.apply(params["row"], h)

    def specs(self):
        return {"col": col_specs(), "row": row_specs()}


def dense_oracle(params, x):
    w1 = params["col"]["params"]["weight"]
    b1 = params["col"]["params"]["bias"]
    w2 = params["row"]["params"]["weight"]
    b2 = params["row"]["params"]["bias"]
    return jax.nn.gelu(x @ w1 + b1) @ w2 + b2


def test_tp_mlp_forward_and_grads_match_dense():
    mesh = tp_mesh()
    model = TwoLayer()
    x = jax.random.normal(jax.random.key(3), (8, IN))

    params = jax.jit(comm.shard_map(model.init, mesh,
                               in_specs=(P(), P()),
                               out_specs=model.specs()))(
        jax.random.key(0), x)

    def loss(p, xx):
        return jnp.sum(model.apply(p, xx) ** 2)

    def dense_loss(p, xx):
        return jnp.sum(dense_oracle(p, xx) ** 2)

    l_tp, g_tp = jax.jit(comm.shard_map(
        jax.value_and_grad(loss), mesh,
        in_specs=(model.specs(), P()),
        out_specs=(P(), model.specs())))(params, x)
    l_ref, g_ref = jax.value_and_grad(dense_loss)(params, x)
    np.testing.assert_allclose(float(l_tp), float(l_ref), rtol=1e-4)
    for k1 in ("col", "row"):
        for k2 in ("weight", "bias"):
            np.testing.assert_allclose(
                np.asarray(g_tp[k1]["params"][k2]),
                np.asarray(g_ref[k1]["params"][k2]),
                rtol=1e-4, atol=1e-4,
                err_msg=f"{k1}.{k2}")


def test_sequence_parallel_mlp_matches_dense():
    """SP: activations sharded on the sequence dim between TP regions;
    all_gather before column, reduce_scatter after row."""
    mesh = tp_mesh()
    model = TwoLayer(sequence_parallel=True)
    S = 8  # sequence length, sharded 4-way
    x = jax.random.normal(jax.random.key(4), (S, 2, IN))

    params = jax.jit(comm.shard_map(model.init, mesh,
                               in_specs=(P(), P(comm.AXIS_MODEL)),
                               out_specs=model.specs()))(
        jax.random.key(0), x)

    y = jax.jit(comm.shard_map(model.apply, mesh,
                          in_specs=(model.specs(), P(comm.AXIS_MODEL)),
                          out_specs=P(comm.AXIS_MODEL)))(params, x)
    want = dense_oracle(params, x)
    np.testing.assert_allclose(np.asarray(y), np.asarray(want),
                               rtol=1e-4, atol=1e-4)


def test_vocab_parallel_embedding_matches_take():
    mesh = tp_mesh()
    V, D = 64, 16
    emb = tp.VocabParallelEmbedding(V, D)
    ids = jax.random.randint(jax.random.key(5), (4, 7), 0, V)
    especs = {"params": {"weight": P(comm.AXIS_MODEL, None)}}
    params = jax.jit(comm.shard_map(lambda k, i: emb.init(k, i), mesh,
                               in_specs=(P(), P()),
                               out_specs=especs))(jax.random.key(0), ids)
    y = jax.jit(comm.shard_map(lambda p, i: emb.apply(p, i), mesh,
                          in_specs=(especs, P()),
                          out_specs=P()))(params, ids)
    want = jnp.take(params["params"]["weight"], ids, axis=0)
    np.testing.assert_allclose(np.asarray(y), np.asarray(want),
                               rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("smoothing", [0.0, 0.1])
def test_vocab_parallel_cross_entropy(smoothing):
    mesh = tp_mesh()
    V = 32
    logits = jax.random.normal(jax.random.key(6), (5, V)) * 3
    target = jax.random.randint(jax.random.key(7), (5,), 0, V)

    def f(lg, t):
        return tp.vocab_parallel_cross_entropy(lg, t,
                                               label_smoothing=smoothing)

    loss = jax.jit(comm.shard_map(f, mesh,
                             in_specs=(P(None, comm.AXIS_MODEL), P()),
                             out_specs=P()))(logits, target)
    want = tp.cross_entropy_ref(logits, target, label_smoothing=smoothing)
    np.testing.assert_allclose(np.asarray(loss), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_vocab_parallel_cross_entropy_grads():
    mesh = tp_mesh()
    V = 32
    logits = jax.random.normal(jax.random.key(8), (5, V))
    target = jax.random.randint(jax.random.key(9), (5,), 0, V)

    def f(lg, t):
        return jnp.mean(tp.vocab_parallel_cross_entropy(lg, t))

    g = jax.jit(comm.shard_map(jax.grad(f), mesh,
                          in_specs=(P(None, comm.AXIS_MODEL), P()),
                          out_specs=P(None, comm.AXIS_MODEL)))(
        logits, target)
    want = jax.grad(lambda lg: jnp.mean(
        tp.cross_entropy_ref(lg, target)))(logits)
    np.testing.assert_allclose(np.asarray(g), np.asarray(want),
                               rtol=1e-4, atol=1e-5)


def test_mappings_roundtrip():
    mesh = comm.initialize(data=1, model=8)
    x = jnp.arange(32.0).reshape(4, 8)

    def f(xx):
        s = mappings.scatter_to_tensor_model_parallel_region(xx)
        return mappings.gather_from_tensor_model_parallel_region(s)

    y = jax.jit(comm.shard_map(f, mesh, in_specs=P(), out_specs=P()))(x)
    np.testing.assert_array_equal(np.asarray(y), np.asarray(x))


def test_rng_tracker_forks_differ():
    tr = tp.RNGStatesTracker()
    tr.add("model-parallel-rng", 123)
    with tr.fork() as k1:
        a = jax.random.normal(k1, (4,))
    with tr.fork() as k2:
        b = jax.random.normal(k2, (4,))
    assert not np.allclose(a, b)
    with pytest.raises(Exception):
        tr.add("model-parallel-rng", 5)


def test_row_parallel_skip_bias_add_sp_bias_grad_synced():
    """skip_bias_add + sequence_parallel (the fused bias-dropout-add
    idiom): the RETURNED bias must carry the f/g grad sync, so a caller
    adding it to the sequence-sharded output gets the full bias grad,
    not 1/tp of it."""
    mesh = comm.initialize(data=2, model=4)
    IN = OUT = 16
    S, B = 8, 2
    row = tp.RowParallelLinear(IN, OUT, input_is_parallel=True,
                               sequence_parallel_enabled=True,
                               skip_bias_add=True)
    x = jax.random.normal(jax.random.key(0), (S, B, IN))
    w_full = jax.random.normal(jax.random.key(1), (IN, OUT)) * 0.2
    bias = jax.random.normal(jax.random.key(2), (OUT,)) * 0.1

    def loss_sharded(w_local, bias, x_in):
        y, b = row.apply(
            {"params": {"weight": w_local, "bias": bias}}, x_in)
        return jnp.sum((y + b) ** 2)     # caller-side bias add

    # with SP, each rank's loss term covers only its sequence shard;
    # the f/g sync inside the layer must make each rank's bias grad
    # ALREADY the total — so the oracle comparison uses NO outer psum
    def step(w_full, bias, x_full):
        rank = jax.lax.axis_index(comm.AXIS_MODEL)
        w_local = jax.lax.dynamic_slice_in_dim(
            w_full, rank * (IN // 4), IN // 4, axis=0)
        x_local = jax.lax.dynamic_slice_in_dim(
            x_full, rank * (IN // 4), IN // 4, axis=2)
        return jax.grad(loss_sharded, argnums=1)(w_local, bias, x_local)

    g = jax.jit(comm.shard_map(
        step, mesh, in_specs=(P(), P(), P()), out_specs=P()))(
        w_full, bias, x)

    # oracle: dense layer, full sequence
    y_ref = jnp.einsum("sbi,io->sbo", x, w_full)
    g_ref = jax.grad(
        lambda b_: jnp.sum((y_ref + b_) ** 2))(bias)
    np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref),
                               rtol=1e-4, atol=1e-5)
