"""L1 integration tier (reference: tests/L1/common/main_amp.py +
compare.py — short trainings across opt-levels, loss TRAJECTORIES
compared within tolerance; training-dynamics equivalence rather than
exact numerics).

The model is UNMODIFIED f32 flax; each opt level's precision comes
entirely from amp.initialize + AmpState.wrap_forward (O1: the op-list
jaxpr rewriter; O2/O3: input casting over bf16-cast params), and O2
exercises the full master-weights machinery through FusedSGD
(master_weights=True with per-step f32-master -> bf16-model copy-back
— the apex/amp/_process_optimizer.py contract).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import amp
from apex_tpu.models import resnet18
from apex_tpu.optimizers import FusedSGD

STEPS = 12
BATCH, SIZE = 8, 32


def _train(opt_level, loss_scale=None, seed=0, lr=0.01,
           return_opt=False):
    model = resnet18(num_classes=10)
    x0 = jnp.zeros((BATCH, SIZE, SIZE, 3))
    variables = model.init(jax.random.PRNGKey(seed), x0, train=False)
    params, bstats = variables["params"], variables["batch_stats"]
    params, amp_state = amp.initialize(params, opt_level=opt_level,
                                       loss_scale=loss_scale)
    # O2: masters + copy-back inside FusedSGD (reference master_weights
    # contract); O0/O1/O3 step the model params directly
    opt = FusedSGD(params, lr=lr, momentum=0.9,
                   master_weights=bool(amp_state.properties.master_weights),
                   masters=amp_state.master_params)

    def loss_fn(p, bs, x, y):
        out, upd = model.apply({"params": p, "batch_stats": bs},
                               x, train=True, mutable=["batch_stats"])
        logp = jax.nn.log_softmax(out.astype(jnp.float32))
        return -jnp.mean(jnp.take_along_axis(logp, y[:, None], axis=1)), \
            upd["batch_stats"]

    # the amp mechanism under test: no hand-casts anywhere in loss_fn
    wrapped = amp_state.wrap_forward(loss_fn, cast_argnums=(2,))

    @jax.jit
    def jstep(p, bs, scaler, x, y):
        return amp.scaled_value_and_grad(wrapped, scaler, p, bs, x, y,
                                         has_aux=True)

    # ONE fixed batch (the reference's L1 compares short stable
    # trainings; a fixed batch gives smooth comparable descent)
    x = jax.random.normal(jax.random.PRNGKey(100),
                          (BATCH, SIZE, SIZE, 3))
    y = jax.random.randint(jax.random.PRNGKey(101), (BATCH,), 0, 10)
    losses = []
    for i in range(STEPS):
        (loss, bstats), grads, found_inf = jstep(
            opt.params, bstats, amp_state.scaler, x, y)
        # branch-free overflow skip: found_inf stays on device (the
        # old `if int(found_inf) == 0` concretized the flag — one
        # host sync per step, apexlint APX102's exact hazard)
        opt.step(grads, found_inf=found_inf)
        amp_state = amp.update_scaler(amp_state, found_inf)
        losses.append(float(loss))
    if return_opt:
        return np.asarray(losses), opt
    return np.asarray(losses)


@pytest.fixture(scope="module")
def fp32_traj():
    return _train("O0")


@pytest.mark.parametrize("opt_level", ["O1", "O2", "O3"])
def test_amp_trajectory_tracks_fp32(opt_level, fp32_traj):
    """The reference's compare.py criterion: mixed-precision training
    must follow the fp32 loss trajectory within tolerance (looser for
    O3 = pure half)."""
    traj = _train(opt_level)
    tol = 0.15 if opt_level != "O3" else 0.30
    np.testing.assert_allclose(traj, fp32_traj, rtol=tol, atol=tol)
    # and it must actually train
    assert traj[-1] < traj[0]


def test_O1_casts_ops_not_params():
    """O1 contract: params stay f32, GEMMs run bf16 — visible in the
    wrapped jaxpr of the UNMODIFIED model (reference: the monkey-patch
    engine + FP16_FUNCS list, apex/amp/wrap.py + lists/)."""
    model = resnet18(num_classes=10)
    x0 = jnp.zeros((2, SIZE, SIZE, 3))
    variables = model.init(jax.random.PRNGKey(0), x0, train=False)
    params, amp_state = amp.initialize(variables["params"], "O1")
    assert all(l.dtype == jnp.float32
               for l in jax.tree_util.tree_leaves(params)
               if jnp.issubdtype(l.dtype, jnp.floating))

    fwd = amp_state.wrap_forward(
        lambda p, x: model.apply({"params": p,
                                  "batch_stats": variables["batch_stats"]},
                                 x, train=False))
    jaxpr = jax.make_jaxpr(fwd)(params, x0)
    convs = [e for e in jaxpr.jaxpr.eqns
             if e.primitive.name == "conv_general_dilated"]
    assert convs, "expected convs in the rewritten jaxpr"
    for e in convs:
        for v in e.invars:
            assert str(v.aval.dtype) == "bfloat16"
    # reductions (BN statistics) pinned f32
    sums = [e for e in jaxpr.jaxpr.eqns
            if e.primitive.name == "reduce_sum"]
    for e in sums:
        for v in e.invars:
            assert str(v.aval.dtype) == "float32"


def test_O2_masters_stay_f32(fp32_traj):
    """VERDICT r1 #8: O2's whole point is that updates accumulate in f32
    masters.  With a small lr the per-step delta is below the bf16 ulp of
    many weights — the masters must drift from the rounded bf16 params,
    proving updates are NOT round-tripped through bf16."""
    _, opt = _train("O2", lr=1e-4, return_opt=True)
    assert opt.masters is not None
    m_leaves = jax.tree_util.tree_leaves(opt.masters)
    p_leaves = jax.tree_util.tree_leaves(opt.params)
    assert all(m.dtype == jnp.float32 for m in m_leaves
               if jnp.issubdtype(m.dtype, jnp.floating))
    assert all(p.dtype == jnp.bfloat16 for p in p_leaves
               if jnp.issubdtype(p.dtype, jnp.floating))
    # masters carry sub-bf16 precision: recasting them to bf16 and back
    # must lose information for at least some leaves
    lost = any(
        bool(jnp.any(m != m.astype(jnp.bfloat16).astype(jnp.float32)))
        for m in m_leaves if jnp.issubdtype(m.dtype, jnp.floating))
    assert lost, "masters are bf16-representable: no f32 accumulation"
    # and the model params are exactly the bf16 image of the masters
    for m, p in zip(m_leaves, p_leaves):
        if jnp.issubdtype(p.dtype, jnp.floating):
            np.testing.assert_array_equal(
                np.asarray(m.astype(jnp.bfloat16), np.float32),
                np.asarray(p, np.float32))


def test_fp32_deterministic(fp32_traj):
    """SURVEY.md §5 race-detection stand-in: same seed + topology ->
    bitwise-identical trajectory (XLA static scheduling)."""
    again = _train("O0")
    np.testing.assert_array_equal(again, fp32_traj)


def test_static_loss_scale_matches_dynamic_when_clean(fp32_traj):
    """bf16 never overflows on this workload: static scale 128 and
    dynamic scaling must give the same O2 trajectory."""
    a = _train("O2", loss_scale=128.0)
    b = _train("O2", loss_scale="dynamic")
    np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-5)
