"""L1 integration tier (reference: tests/L1/common/main_amp.py +
compare.py — short trainings across opt-levels, loss TRAJECTORIES
compared within tolerance; training-dynamics equivalence rather than
exact numerics)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import amp
from apex_tpu.models import resnet18
from apex_tpu.optimizers import FusedSGD

STEPS = 12
BATCH, SIZE = 8, 32


def _train(opt_level, loss_scale=None, seed=0):
    model = resnet18(num_classes=10)
    x0 = jnp.zeros((BATCH, SIZE, SIZE, 3))
    variables = model.init(jax.random.PRNGKey(seed), x0, train=False)
    params, bstats = variables["params"], variables["batch_stats"]
    params, amp_state = amp.initialize(params, opt_level=opt_level,
                                       loss_scale=loss_scale)
    half = (jnp.bfloat16 if opt_level in ("O1", "O2", "O3")
            else jnp.float32)
    opt = FusedSGD(params, lr=0.01, momentum=0.9)

    def loss_fn(p, bs, x, y):
        out, upd = model.apply({"params": p, "batch_stats": bs},
                               x.astype(half), train=True,
                               mutable=["batch_stats"])
        logp = jax.nn.log_softmax(out.astype(jnp.float32))
        return -jnp.mean(jnp.take_along_axis(logp, y[:, None], axis=1)), \
            upd["batch_stats"]

    @jax.jit
    def jstep(p, bs, scaler, x, y):
        return amp.scaled_value_and_grad(loss_fn, scaler, p, bs, x, y,
                                         has_aux=True)

    # ONE fixed batch (the reference's L1 compares short stable
    # trainings; a fixed batch gives smooth comparable descent)
    x = jax.random.normal(jax.random.PRNGKey(100),
                          (BATCH, SIZE, SIZE, 3))
    y = jax.random.randint(jax.random.PRNGKey(101), (BATCH,), 0, 10)
    losses = []
    for i in range(STEPS):
        (loss, bstats), grads, found_inf = jstep(
            opt.params, bstats, amp_state.scaler, x, y)
        if int(found_inf) == 0:
            opt.step(grads)
        amp_state = amp.update_scaler(amp_state, found_inf)
        losses.append(float(loss))
    return np.asarray(losses)


@pytest.fixture(scope="module")
def fp32_traj():
    return _train("O0")


@pytest.mark.parametrize("opt_level", ["O1", "O2", "O3"])
def test_amp_trajectory_tracks_fp32(opt_level, fp32_traj):
    """The reference's compare.py criterion: mixed-precision training
    must follow the fp32 loss trajectory within tolerance (looser for
    O3 = pure half)."""
    traj = _train(opt_level)
    tol = 0.15 if opt_level != "O3" else 0.30
    np.testing.assert_allclose(traj, fp32_traj, rtol=tol, atol=tol)
    # and it must actually train
    assert traj[-1] < traj[0]


def test_fp32_deterministic(fp32_traj):
    """SURVEY.md §5 race-detection stand-in: same seed + topology ->
    bitwise-identical trajectory (XLA static scheduling)."""
    again = _train("O0")
    np.testing.assert_array_equal(again, fp32_traj)


def test_static_loss_scale_matches_dynamic_when_clean(fp32_traj):
    """bf16 never overflows on this workload: static scale 128 and
    dynamic scaling must give the same O2 trajectory."""
    a = _train("O2", loss_scale=128.0)
    b = _train("O2", loss_scale="dynamic")
    np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-5)
