"""Bucketed flat-parameter optimizer path (ISSUE 2 tentpole).

Contracts:
  * every fused optimizer steps through the bucketed flat kernels BY
    DEFAULT and matches the per-leaf oracle path (f32 and bf16+masters,
    per-dtype tolerances);
  * params/masters/opt_state stay packed between steps — the per-leaf
    view is a lazy property;
  * state_dict layout is unchanged: old per-leaf checkpoints load into
    bucketed optimizers and vice versa;
  * ``fuse_buckets=False`` is a clean escape hatch;
  * amp's found_inf flag skips the update branch-free.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.multi_tensor_apply import BucketPlan
from apex_tpu.optimizers import (FusedAdagrad, FusedAdam, FusedLAMB,
                                 FusedNovoGrad, FusedSGD)

OPTS = [
    (FusedAdam, dict(lr=1e-2, weight_decay=0.01)),
    (FusedSGD, dict(lr=0.1, momentum=0.9, weight_decay=1e-4)),
    (FusedAdagrad, dict(lr=1e-2, weight_decay=0.01)),
    (FusedNovoGrad, dict(lr=1e-2, weight_decay=0.01)),
    (FusedLAMB, dict(lr=1e-2, weight_decay=0.01)),
]


def tol(dtype):
    return dict(rtol=2e-2, atol=2e-2) if dtype == jnp.bfloat16 \
        else dict(rtol=2e-5, atol=2e-6)


def _params(dtype, key=0):
    """Several layers of mixed big/small leaves (a realistic pytree the
    packer folds into one bucket per dtype)."""
    ks = jax.random.split(jax.random.key(key), 3)
    return {
        "layer1": {"w": jax.random.normal(
            ks[0], (16, 8), jnp.float32).astype(dtype),
            "b": jnp.zeros((8,), dtype)},
        "layer2": {"w": jax.random.normal(
            ks[1], (8, 4), jnp.float32).astype(dtype),
            "scale": jnp.ones((4,), dtype)},
        "head": jax.random.normal(ks[2], (4, 3), jnp.float32).astype(dtype),
    }


def _grads(params, seed):
    return jax.tree_util.tree_map(
        lambda p: jax.random.normal(jax.random.key(seed), p.shape,
                                    jnp.float32).astype(p.dtype) * 0.1,
        params)


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
@pytest.mark.parametrize("cls,kw", OPTS,
                         ids=[c.__name__ for c, _ in OPTS])
def test_bucketed_matches_per_leaf(cls, kw, dtype):
    params = _params(dtype)
    ref = cls(params, fuse_buckets=False, **kw)
    buck = cls(params, fuse_buckets=True, **kw)
    assert buck.fuse_buckets and not ref.fuse_buckets
    for s in range(3):
        g = _grads(params, 100 + s)
        ref.step(g)
        buck.step(g)
    for a, b in zip(jax.tree_util.tree_leaves(ref.params),
                    jax.tree_util.tree_leaves(buck.params)):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32), **tol(dtype))
    if dtype == jnp.bfloat16:       # masters stepped, both packed+not
        for a, b in zip(jax.tree_util.tree_leaves(ref.masters),
                        jax.tree_util.tree_leaves(buck.masters)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-5, atol=2e-6)


def test_default_is_bucketed_with_escape_hatch():
    p = _params(jnp.float32)
    assert FusedAdam(p, lr=1e-3).fuse_buckets
    assert not FusedAdam(p, lr=1e-3, fuse_buckets=False).fuse_buckets


def test_params_stay_packed_between_steps():
    p = _params(jnp.float32)
    opt = FusedAdam(p, lr=1e-2)
    g = _grads(p, 7)
    opt.step(g)
    # canonical representation is the per-bucket flat buffers
    assert isinstance(opt._param_bufs, list)
    assert sum(b.size for b in opt._param_bufs) \
        == sum(l.size for l in jax.tree_util.tree_leaves(p))
    # the property unpacks lazily and caches until the next step
    v1 = opt.params
    assert opt.params is v1
    opt.step(g)
    assert opt.params is not v1


@pytest.mark.parametrize("cls,kw", OPTS,
                         ids=[c.__name__ for c, _ in OPTS])
def test_state_dict_roundtrip_across_packing(cls, kw):
    """Per-leaf checkpoints load into bucketed optimizers (and back):
    the serialized layout is the per-leaf torch shape either way."""
    params = _params(jnp.float32)
    g = _grads(params, 3)

    old = cls(params, fuse_buckets=False, **kw)
    old.step(g)
    sd = old.state_dict()
    new = cls(old.params, fuse_buckets=True, **kw)
    new.load_state_dict(sd)
    old.step(g)
    new.step(g)
    for a, b in zip(jax.tree_util.tree_leaves(old.params),
                    jax.tree_util.tree_leaves(new.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-7)
    # bucketed state_dict serializes the SAME per-leaf layout
    sd2 = new.state_dict()
    assert (jax.tree_util.tree_structure(sd2["state"])
            == jax.tree_util.tree_structure(sd["state"]))
    back = cls(new.params, fuse_buckets=False, **kw)
    back.load_state_dict(sd2)
    back.step(g)
    old.step(g)
    for a, b in zip(jax.tree_util.tree_leaves(old.params),
                    jax.tree_util.tree_leaves(back.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-7)


def test_novograd_scalar_state_layout_preserved():
    """NovoGrad's per-tensor second moment serializes as per-leaf
    SCALARS (the pre-bucketing layout) even though it lives packed as
    one vector per bucket."""
    params = _params(jnp.float32)
    opt = FusedNovoGrad(params, lr=1e-2)
    opt.step(_grads(params, 1))
    sd = opt.state_dict()
    for leaf in jax.tree_util.tree_leaves(sd["state"]["exp_avg_sq"]):
        assert np.asarray(leaf).shape == ()
    for leaf, p in zip(
            jax.tree_util.tree_leaves(sd["state"]["exp_avg"]),
            jax.tree_util.tree_leaves(params)):
        assert np.asarray(leaf).shape == p.shape


def test_found_inf_skips_update_and_step_clock():
    params = _params(jnp.float32)
    g = _grads(params, 5)
    opt = FusedAdam(params, lr=1e-2)
    p0 = np.asarray(opt.params["head"])
    opt.step(g, found_inf=jnp.int32(1))
    np.testing.assert_array_equal(p0, np.asarray(opt.params["head"]))
    assert int(opt.step_count) == 0
    opt.step(g, found_inf=jnp.int32(0))
    assert int(opt.step_count) == 1
    assert not np.allclose(p0, np.asarray(opt.params["head"]))
    # matches an unconditional step (the skipped call left no trace)
    ref = FusedAdam(params, lr=1e-2)
    ref.step(g)
    np.testing.assert_allclose(np.asarray(ref.params["head"]),
                               np.asarray(opt.params["head"]),
                               rtol=1e-6, atol=1e-7)


def test_found_inf_from_flat_scale():
    """amp interop: flat_scale's on-device overflow flag drives the
    branch-free skip end to end."""
    from apex_tpu.multi_tensor_apply import flatten
    from apex_tpu.ops.multi_tensor import flat_scale

    params = _params(jnp.float32)
    g = _grads(params, 5)
    bad = {**g, "head": g["head"].at[0, 0].set(jnp.inf)}
    opt = FusedAdam(params, lr=1e-2)
    p0 = np.asarray(opt.params["head"])
    for grads in (bad, g):
        flat = flatten([jnp.ravel(l) for l in
                        jax.tree_util.tree_leaves(grads)])
        _, flag = flat_scale(flat, 1.0)
        opt.step(grads, found_inf=flag)
    assert int(opt.step_count) == 1      # only the finite step counted
    assert not np.allclose(p0, np.asarray(opt.params["head"]))


def test_bucketed_offload_state_matches_resident():
    params = _params(jnp.float32)
    g = _grads(params, 9)
    ref = FusedAdam(params, lr=1e-2, weight_decay=0.01)
    off = FusedAdam(params, lr=1e-2, weight_decay=0.01,
                    offload_state=True)
    # bucketed state offloads as WHOLE flat buffers
    for leaf in jax.tree_util.tree_leaves(off.opt_state):
        assert leaf.ndim == 1
        assert leaf.sharding.memory_kind in ("pinned_host",
                                             "unpinned_host")
    for _ in range(2):
        ref.step(g)
        off.step(g)
    for a, b in zip(jax.tree_util.tree_leaves(ref.params),
                    jax.tree_util.tree_leaves(off.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-7)


def test_mixed_dtype_tree_packs_per_dtype_buckets():
    """A tree with f32 AND bf16 leaves packs into one bucket per dtype
    and still matches the per-leaf path."""
    params = {"big": jax.random.normal(jax.random.key(0), (32, 8)),
              "half": jax.random.normal(jax.random.key(1),
                                        (16,)).astype(jnp.bfloat16)}
    # mixed tree => low-precision => masters by default; keep this test
    # about dtype bucketing, not masters
    ref = FusedSGD(params, lr=0.1, momentum=0.9, master_weights=False,
                   fuse_buckets=False)
    buck = FusedSGD(params, lr=0.1, momentum=0.9, master_weights=False,
                    fuse_buckets=True)
    assert len(buck._plan.buckets) == 2
    g = _grads(params, 11)
    for _ in range(2):
        ref.step(g)
        buck.step(g)
    for a, b in zip(jax.tree_util.tree_leaves(ref.params),
                    jax.tree_util.tree_leaves(buck.params)):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32),
                                   rtol=2e-2, atol=2e-2)


class TestBucketPlan:
    def test_declines_non_float_and_empty(self):
        assert BucketPlan.from_tree({}) is None
        assert BucketPlan.from_tree(
            {"w": jnp.ones((4,)), "i": jnp.zeros((2,), jnp.int32)}) is None

    def test_optimizer_falls_back_when_unpackable(self):
        params = {"w": jnp.ones((8,)), "steps": jnp.zeros((1,), jnp.int32)}
        opt = FusedSGD(params, lr=0.1)
        assert not opt.fuse_buckets      # graceful per-leaf fallback

    def test_roundtrip_and_offsets(self):
        tree = {"a": jnp.arange(6.0).reshape(2, 3),
                "b": jnp.arange(4.0) + 10}
        plan = BucketPlan.from_tree(tree)
        bufs = plan.pack(tree)
        assert len(bufs) == 1 and bufs[0].shape == (10,)
        back = plan.unpack(bufs)
        for a, b in zip(jax.tree_util.tree_leaves(tree),
                        jax.tree_util.tree_leaves(back)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_segment_ids_sorted_and_sized(self):
        tree = {"a": jnp.ones((3, 2)), "b": jnp.ones((5,))}
        plan = BucketPlan.from_tree(tree)
        ids = np.asarray(plan.segment_ids(0))
        assert ids.shape == (11,)
        assert (np.diff(ids) >= 0).all()
        assert plan.num_segments(0) == 2


def test_functional_step_layout_detection():
    """functional_step must route by the STATE's actual layout: a
    per-leaf state whose top-level pytree is a list of the right length
    (list-shaped params) is NOT the packed layout (code-review catch)."""
    params = [jnp.ones((4, 4), jnp.float32),
              jnp.ones((3, 3), jnp.bfloat16)]
    g = [jnp.full((4, 4), 0.1), jnp.full((3, 3), 0.1, jnp.bfloat16)]
    opt = FusedAdam(params, lr=1e-2, master_weights=False)
    perleaf_state = opt.init_state(params)
    assert not opt._state_is_packed(perleaf_state)
    assert opt._state_is_packed(opt.opt_state)
    newp, _ = opt.functional_step(params, perleaf_state, g, jnp.int32(1))
    newp2, _ = opt.functional_step(params, opt.opt_state, g, jnp.int32(1))
    np.testing.assert_allclose(np.asarray(newp[0]), np.asarray(newp2[0]),
                               rtol=1e-6, atol=1e-7)


def test_bucketing_microbench_smoke():
    """The per-leaf-vs-bucketed microbench harness runs end to end on
    tiny shapes (CPU: proves the harness, not performance)."""
    from apex_tpu.optimizers.bucketing_bench import \
        bench_optimizer_bucketing
    r = bench_optimizer_bucketing(layers=3, hidden=32, iters=2, reps=1)
    assert r["optim_step_perleaf_ms"] > 0
    assert r["optim_step_bucketed_ms"] > 0
    assert r["optim_bucketing_speedup"] > 0
    assert r["optim_leaves"] == 12
