"""amp frontend + dynamic loss scaler semantics.

Reference test models: tests/L0/run_amp/* (SURVEY.md §4) — opt-level
property resolution, scaler grow/backoff behavior, state_dict round-trip,
and a tiny end-to-end train step with conditional skip.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import amp


def test_opt_level_tables():
    p0 = amp.opt_level_properties("O0")
    assert p0.cast_model_type is None and p0.loss_scale == 1.0
    p2 = amp.opt_level_properties("O2")
    assert p2.cast_model_type == jnp.bfloat16
    assert p2.master_weights is True
    # fp16 selects dynamic scaling; bf16 defaults static
    p2h = amp.opt_level_properties("O2", half_dtype=jnp.float16)
    assert p2h.loss_scale == "dynamic"
    with pytest.raises(ValueError):
        amp.opt_level_properties("O9")


def test_initialize_o2_casts_and_keeps_masters():
    params = {"w": jnp.ones((4, 4)), "b": jnp.zeros((4,)),
              "step": jnp.int32(0)}
    cast, state = amp.initialize(params, opt_level="O2")
    assert cast["w"].dtype == jnp.bfloat16
    assert cast["step"].dtype == jnp.int32  # non-float untouched
    assert state.master_params["w"].dtype == jnp.float32


def test_initialize_o1_noop_params():
    params = {"w": jnp.ones((2,))}
    cast, state = amp.initialize(params, opt_level="O1")
    assert cast["w"].dtype == jnp.float32
    assert state.master_params is None


def test_scaler_growth_and_backoff():
    cfg = amp.LossScaleConfig(init_scale=8.0, growth_interval=3)
    s = amp.LossScaleState.create(8.0)
    # clean steps grow after interval
    for _ in range(3):
        s = amp.update_state(s, jnp.int32(0), cfg)
    assert float(s.loss_scale) == 16.0
    assert int(s.growth_tracker) == 0
    # overflow halves and resets tracker
    s = amp.update_state(s, jnp.int32(1), cfg)
    assert float(s.loss_scale) == 8.0
    assert int(s.growth_tracker) == 0


def test_scaler_min_clamp():
    cfg = amp.LossScaleConfig(init_scale=1.0, min_loss_scale=1.0)
    s = amp.LossScaleState.create(1.0)
    s = amp.update_state(s, jnp.int32(1), cfg)
    assert float(s.loss_scale) == 1.0


def test_scaler_external_skip_does_not_advance_growth_interval():
    """ISSUE 7 regression: a watchdog/quarantine skip is neither a
    clean step nor an overflow — the growth tracker must HOLD, not
    count the non-stepped window toward the growth interval (and the
    scale must not move)."""
    cfg = amp.LossScaleConfig(init_scale=8.0, growth_interval=3)
    s = amp.LossScaleState.create(8.0)
    s = amp.update_state(s, jnp.int32(0), cfg)
    assert int(s.growth_tracker) == 1
    # forced skips: tracker and scale frozen, however many
    for _ in range(5):
        s = amp.update_state(s, jnp.int32(0), cfg, skipped=jnp.int32(1))
    assert int(s.growth_tracker) == 1
    assert float(s.loss_scale) == 8.0
    # resuming clean steps completes the ORIGINAL interval
    s = amp.update_state(s, jnp.int32(0), cfg)
    s = amp.update_state(s, jnp.int32(0), cfg)
    assert float(s.loss_scale) == 16.0
    # skipped=0 behaves exactly like the plain update
    s2 = amp.update_state(s, jnp.int32(0), cfg, skipped=jnp.int32(0))
    assert int(s2.growth_tracker) == 1


def test_scaler_external_skip_traced_under_jit():
    cfg = amp.LossScaleConfig(init_scale=8.0, growth_interval=2)
    step = jax.jit(lambda s, fi, sk: amp.update_state(s, fi, cfg,
                                                      skipped=sk))
    s = amp.LossScaleState.create(8.0)
    s = step(s, jnp.int32(0), jnp.int32(1))       # skipped: hold
    assert int(s.growth_tracker) == 0
    s = step(s, jnp.int32(0), jnp.int32(0))
    s = step(s, jnp.int32(0), jnp.int32(0))       # 2 clean: grow
    assert float(s.loss_scale) == 16.0


def test_re_anchor_resets_to_operating_point():
    cfg = amp.LossScaleConfig(init_scale=2.0 ** 10, growth_interval=4)
    s = amp.LossScaleState.create(2.0 ** 10)
    for _ in range(6):                            # collapse to floor
        s = amp.update_state(s, jnp.int32(1), cfg)
    s = amp.update_state(s, jnp.int32(0), cfg)
    assert float(s.loss_scale) < 2.0 ** 10
    r = amp.re_anchor(s, cfg)
    assert float(r.loss_scale) == 2.0 ** 10
    assert int(r.growth_tracker) == 0 and int(r.found_inf) == 0
    r2 = amp.re_anchor(s, cfg, scale=64.0)        # explicit override
    assert float(r2.loss_scale) == 64.0


def test_amp_state_re_anchor_and_update_scaler_skipped():
    params = {"w": jnp.ones((2,))}
    _, state = amp.initialize(params, opt_level="O2",
                              loss_scale="dynamic")
    state = amp.update_scaler(state, jnp.int32(1))     # backoff
    assert float(state.scaler.loss_scale) == 2.0 ** 15
    held = amp.update_scaler(state, jnp.int32(0),
                             skipped=jnp.int32(1))     # external skip
    assert int(held.scaler.growth_tracker) == 0
    assert float(held.scaler.loss_scale) == 2.0 ** 15
    anchored = state.re_anchor()
    assert float(anchored.scaler.loss_scale) == 2.0 ** 16


def test_state_dict_roundtrip():
    params = {"w": jnp.ones((2,))}
    _, state = amp.initialize(params, opt_level="O2",
                              half_dtype=jnp.float16)
    sd = state.state_dict()
    assert sd["loss_scaler0"]["loss_scale"] == 2.0 ** 16
    state2 = state.load_state_dict(
        {"loss_scaler0": {"loss_scale": 4.0, "unskipped": 7}})
    assert float(state2.scaler.loss_scale) == 4.0
    assert int(state2.scaler.growth_tracker) == 7


def test_scaled_value_and_grad_and_conditional_step():
    params = {"w": jnp.asarray(2.0)}

    def loss_fn(p, x):
        return (p["w"] * x - 1.0) ** 2

    scaler = amp.LossScaleState.create(1024.0)
    loss, grads, found_inf = amp.scaled_value_and_grad(
        loss_fn, scaler, params, 3.0)
    # grads come back UNscaled
    np.testing.assert_allclose(float(grads["w"]), 2 * (2 * 3 - 1) * 3,
                               rtol=1e-6)
    np.testing.assert_allclose(float(loss), (2 * 3 - 1) ** 2, rtol=1e-6)
    assert int(found_inf) == 0

    def step_fn(p, s):
        return {"w": p["w"] - 0.1}, s

    # finite: step applies
    p2, _, s2 = amp.conditional_step(scaler, found_inf, step_fn, params, None)
    np.testing.assert_allclose(float(p2["w"]), 1.9)
    # overflow: step skipped, scale halves
    p3, _, s3 = amp.conditional_step(scaler, jnp.int32(1), step_fn,
                                     params, None)
    np.testing.assert_allclose(float(p3["w"]), 2.0)
    assert float(s3.loss_scale) == 512.0


def test_overflow_detection_in_grads():
    def loss_fn(p, x):
        return jnp.log(p["w"] * x)  # w*x <= 0 -> nan/inf grads

    scaler = amp.LossScaleState.create(2.0)
    params = {"w": jnp.asarray(0.0)}
    _, grads, found_inf = amp.scaled_value_and_grad(loss_fn, scaler,
                                                    params, 1.0)
    assert int(found_inf) == 1


def test_conditional_step_jits():
    """The whole skip-or-step path must trace into one jitted program."""
    def train_step(params, scaler, x):
        def loss_fn(p, x):
            return (p["w"] * x) ** 2
        loss, grads, found_inf = amp.scaled_value_and_grad(
            loss_fn, scaler, params, x)

        def step_fn(p, s):
            return jax.tree_util.tree_map(
                lambda a, g: a - 0.1 * g, p, grads), s

        params, _, scaler = amp.conditional_step(
            scaler, found_inf, step_fn, params, None)
        return params, scaler, loss

    params = {"w": jnp.asarray(1.0)}
    scaler = amp.LossScaleState.create(16.0)
    jitted = jax.jit(train_step, donate_argnums=(0,))
    params, scaler, loss = jitted(params, scaler, 2.0)
    assert np.isfinite(float(loss))


def test_multi_scaler_state_dict_reference_layout():
    """num_losses parity: N AmpStates serialize as loss_scaler0..N-1
    (reference amp.state_dict with num_losses=N) and round-trip."""
    from apex_tpu import amp
    _, s0 = amp.initialize({"w": jnp.ones((2,))}, opt_level="O2",
                           loss_scale="dynamic")
    _, s1 = amp.initialize({"w": jnp.ones((2,))}, opt_level="O2",
                           loss_scale=128.0)
    s0 = amp.update_scaler(s0, jnp.int32(1))    # overflow: scale halves
    sd = amp.state_dict(s0, s1)
    assert set(sd) == {"loss_scaler0", "loss_scaler1"}
    assert sd["loss_scaler0"]["loss_scale"] == 2.0 ** 15
    assert sd["loss_scaler1"]["loss_scale"] == 128.0

    _, f0 = amp.initialize({"w": jnp.ones((2,))}, opt_level="O2",
                           loss_scale="dynamic")
    _, f1 = amp.initialize({"w": jnp.ones((2,))}, opt_level="O2",
                           loss_scale=128.0)
    r0, r1 = amp.load_state_dict(sd, f0, f1)
    assert float(r0.scaler.loss_scale) == 2.0 ** 15
    assert float(r1.scaler.loss_scale) == 128.0
    # single-state form returns a bare AmpState
    r = amp.load_state_dict(amp.state_dict(s1), f1)
    assert float(r.scaler.loss_scale) == 128.0


def test_multi_scaler_load_warns_on_count_mismatch():
    import warnings
    from apex_tpu import amp
    _, s0 = amp.initialize({"w": jnp.ones((2,))}, opt_level="O2",
                           loss_scale=64.0)
    _, s1 = amp.initialize({"w": jnp.ones((2,))}, opt_level="O2",
                           loss_scale=32.0)
    sd = amp.state_dict(s0)                      # one saved scaler
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        r0, r1 = amp.load_state_dict(sd, s0, s1)  # two states passed
    assert any("loss scaler" in str(x.message) for x in w)
    assert float(r0.scaler.loss_scale) == 64.0
