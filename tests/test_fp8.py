"""fp8 training: delayed-scaling bookkeeping, packed state round
trips, overflow latching, watchdog rollback, and the amp.fp8_step
spec (ISSUE 13 acceptance).

The delayed-scaling state transition must be BIT-EXACT across every
layout that computes it: the packed per-bucket pass
(``ops.multi_tensor.flat_amax_scale_update``), its scatter-max
oracle, and the per-leaf tree-walk oracle (``amp.fp8.
update_state_ref``) — and independent of the COMPUTE path (real fp8
dots vs the bf16-compute fallback CPU tier-1 runs).
"""

import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import amp
from apex_tpu.amp import fp8
from apex_tpu.fused_dense import (FusedDense, fp8_matmul,
                                  fused_dense_function)
from apex_tpu.multi_tensor_apply.packer import BucketPlan, cached_plan
from apex_tpu.ops import multi_tensor as mt
from apex_tpu.optimizers import FusedAdam, FusedSGD


def _tree(key=0, bf16=False):
    k = jax.random.key(key)
    ks = jax.random.split(k, 3)
    dt = jnp.bfloat16 if bf16 else jnp.float32
    return {
        "w": jax.random.normal(ks[0], (16, 16), dt) * 3.0,
        "b": jax.random.normal(ks[1], (16,), dt) * 0.01,
        "s": jax.random.normal(ks[2], (4, 4), dt) * 100.0,
    }


def _assert_trees_equal(a, b):
    for x, y in zip(jax.tree_util.tree_leaves(a),
                    jax.tree_util.tree_leaves(b)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


# ---------------------------------------------------------------------
# bookkeeping bit-exactness
# ---------------------------------------------------------------------

@pytest.mark.parametrize("bf16", [False, True], ids=["f32", "bf16"])
def test_amax_scale_update_kernel_vs_ref_bit_exact(bf16):
    tree = _tree(bf16=bf16)
    plan = cached_plan(tree)
    bufs = plan.pack_grads(tree)
    for bi, buf in enumerate(bufs):
        n = plan.num_segments(bi)
        hist = jnp.abs(jax.random.normal(jax.random.key(bi),
                                         (n, 5))).astype(jnp.float32)
        scale = jnp.ones((n,), jnp.float32) * 7.0
        kw = dict(fp8_max=448.0, margin=1.0, backoff_factor=0.5)
        h1, s1, f1 = mt.flat_amax_scale_update(
            buf, plan.segment_ids(bi), n, hist, scale, **kw)
        h2, s2, f2 = mt.flat_amax_scale_update_ref(
            buf, plan.segment_ids(bi), n, hist, scale, **kw)
        np.testing.assert_array_equal(np.asarray(h1), np.asarray(h2))
        np.testing.assert_array_equal(np.asarray(s1), np.asarray(s2))
        assert int(f1) == int(f2) == 0


@pytest.mark.parametrize("bf16", [False, True], ids=["f32", "bf16"])
def test_packed_update_vs_per_leaf_oracle_bit_exact(bf16):
    """Multi-step delayed-scaling trajectory: the packed per-bucket
    pass equals the per-leaf tree-walk oracle bit for bit."""
    policy = fp8.Fp8Policy(amax_history_len=3, interval=2, margin=1.0)
    tree = _tree(bf16=bf16)
    plan = cached_plan(tree)
    st_a = fp8.init_state(plan, policy)
    st_b = fp8.init_state(plan, policy)
    for i in range(5):
        t = jax.tree_util.tree_map(lambda x: x * (1.0 + i), tree)
        bufs = plan.pack_grads(t)
        st_a, fa = fp8.update_state(st_a, bufs, plan, policy)
        st_b, fb = fp8.update_state_ref(st_b, t, plan, policy)
        assert int(fa) == int(fb) == 0
        _assert_trees_equal(st_a.amax_history, st_b.amax_history)
        _assert_trees_equal(st_a.scale, st_b.scale)


def test_interval_cadence_holds_updates():
    policy = fp8.Fp8Policy(amax_history_len=2, interval=3)
    tree = _tree()
    plan = cached_plan(tree)
    bufs = plan.pack_grads(tree)
    st = fp8.init_state(plan, policy)
    st1, _ = fp8.update_state(st, bufs, plan, policy)    # step 0: updates
    st2, _ = fp8.update_state(st1, bufs, plan, policy)   # step 1: holds
    st3, _ = fp8.update_state(st2, bufs, plan, policy)   # step 2: holds
    _assert_trees_equal(st1.scale, st2.scale)
    _assert_trees_equal(st2.amax_history, st3.amax_history)
    assert int(st3.step) == 3
    st4, _ = fp8.update_state(st3, bufs, plan, policy)   # step 3: updates
    assert float(jnp.max(st4.amax_history[0][:, 1])) > 0.0


def test_bookkeeping_identical_across_compute_modes():
    """The bf16-compute oracle contract: a whole fp8 train step under
    compute="bf16" carries EXACTLY the same scale bookkeeping as
    compute="fp8" given the same inputs (on CPU the compute paths
    also agree numerically, so the full state matches bitwise)."""
    states = {}
    for compute in ("fp8", "bf16"):
        policy = fp8.Fp8Policy(amax_history_len=4, compute=compute)
        params = _tree(key=3)
        opt = FusedAdam(params, lr=1e-2)
        opt.enable_fp8(policy)
        pipe = amp.FlatGradPipeline(optimizer=opt, fp8=policy)
        f8 = pipe.fp8_init()
        scaler = amp.LossScaleState.create(2.0 ** 4)
        x = jax.random.normal(jax.random.key(5), (4, 16))

        def loss(p, scales, x):
            h = jnp.tanh(fp8_matmul(x, p["w"], policy=policy,
                                    w_scale=scales["w"]) + p["b"])
            return jnp.mean(h ** 2) + jnp.mean(
                p["s"].astype(jnp.float32) ** 2)

        for _ in range(3):
            scales = opt.fp8_scales()
            _, flat, f8 = pipe.scaled_value_and_grad(
                loss, scaler, opt.params, scales, x, fp8_state=f8)
            opt.step(flat)
        states[compute] = (opt.opt_state["fp8_scale"],
                           opt.opt_state["fp8_amax_history"],
                           f8.scale, f8.amax_history)
    for a, b in zip(states["fp8"], states["bf16"]):
        _assert_trees_equal(a, b)


# ---------------------------------------------------------------------
# fp8_matmul numerics
# ---------------------------------------------------------------------

def test_fp8_matmul_matches_quantize_dequant_oracle():
    x = jax.random.normal(jax.random.key(0), (8, 16), jnp.float32)
    w = jax.random.normal(jax.random.key(1), (16, 4),
                          jnp.float32) * 0.1
    sx, sw = jnp.float32(16.0), jnp.float32(128.0)
    policy = fp8.Fp8Policy()
    y = fp8_matmul(x, w, policy=policy, x_scale=sx, w_scale=sw)
    qx = fp8.quantize(x, sx, "e4m3").astype(jnp.float32)
    qw = fp8.quantize(w, sw, "e4m3").astype(jnp.float32)
    ref = (qx @ qw) / (sx * sw)
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref),
                               rtol=1e-6, atol=1e-6)


def test_fp8_matmul_grad_is_quantized_and_typed():
    x = jax.random.normal(jax.random.key(0), (8, 16), jnp.bfloat16)
    w = jax.random.normal(jax.random.key(1), (16, 4),
                          jnp.bfloat16) * 0.1
    policy = fp8.Fp8Policy()

    def loss(x, w):
        return jnp.sum(fp8_matmul(x, w, policy=policy
                                  ).astype(jnp.float32) ** 2)

    gx, gw = jax.grad(loss, argnums=(0, 1))(x, w)
    assert gx.dtype == x.dtype and gx.shape == x.shape
    assert gw.dtype == w.dtype and gw.shape == w.shape
    assert bool(jnp.all(jnp.isfinite(gx.astype(jnp.float32))))
    # exactly 2 e4m3 + 1 e5m2 quantize converts in fwd+bwd
    jaxpr = jax.make_jaxpr(jax.grad(loss, argnums=(0, 1)))(x, w)
    from apex_tpu.lint.semantic.jaxprs import fp8_convert_counts
    assert fp8_convert_counts(jaxpr) == {"e4m3": 2, "e5m2": 1}


def test_quantize_saturates_and_dynamic_scale_edges():
    big = jnp.float32(1e6) * jnp.ones((4,))
    q = fp8.quantize(big, 1.0, "e4m3")
    assert float(jnp.max(q.astype(jnp.float32))) <= 448.0
    assert float(fp8.dynamic_scale(jnp.zeros((4,)), 448.0)) == 1.0
    assert float(fp8.dynamic_scale(
        jnp.array([jnp.inf], jnp.float32), 448.0)) == 1.0


def test_fused_dense_module_fp8_path():
    policy = fp8.Fp8Policy()
    m = FusedDense(8, 4, param_dtype=jnp.bfloat16, fp8=policy)
    x = jax.random.normal(jax.random.key(0), (2, 8), jnp.bfloat16)
    params = m.init(jax.random.key(1), x)
    y = m.apply(params, x)
    assert y.shape == (2, 4) and y.dtype == jnp.bfloat16
    # the plain module stays the non-fp8 dot
    m0 = FusedDense(8, 4, param_dtype=jnp.bfloat16)
    y0 = m0.apply(params, x)
    jaxpr = jax.make_jaxpr(lambda p, x: m.apply(p, x))(params, x)
    from apex_tpu.lint.semantic.jaxprs import fp8_convert_counts
    assert fp8_convert_counts(jaxpr) == {"e4m3": 2}
    assert y0.shape == y.shape


def test_tensor_parallel_linear_fp8_path():
    from apex_tpu.transformer.tensor_parallel import (
        ColumnParallelLinear, RowParallelLinear)
    policy = fp8.Fp8Policy()
    x = jax.random.normal(jax.random.key(0), (2, 8), jnp.float32)
    col = ColumnParallelLinear(8, 6, fp8=policy)
    p = col.init(jax.random.key(1), x)
    y = col.apply(p, x)
    assert y.shape == (2, 6)
    row = RowParallelLinear(6, 8, fp8=policy)
    p2 = row.init(jax.random.key(2), y)
    assert row.apply(p2, y).shape == (2, 8)


def test_transformer_functional_reexports_fp8_matmul():
    from apex_tpu.transformer import functional
    assert functional.fp8_matmul is fp8_matmul


# ---------------------------------------------------------------------
# overflow: found_inf latch + held step clock + per-tensor backoff
# ---------------------------------------------------------------------

def test_overflow_latches_found_inf_and_holds_step_clock():
    policy = fp8.Fp8Policy(amax_history_len=4)
    params = _tree(key=7)
    opt = FusedAdam(params, lr=1e-2)
    opt.enable_fp8(policy)
    pipe = amp.FlatGradPipeline(optimizer=opt, fp8=policy)
    f8 = pipe.fp8_init()
    scaler = amp.LossScaleState.create(2.0 ** 4)
    grads = jax.tree_util.tree_map(jnp.ones_like, params)
    # one clean step first
    flat = pipe.unscale_and_norm(pipe.pack(grads), scaler)
    flat, f8 = pipe.fp8_update(f8, flat)
    assert int(flat.found_inf) == 0
    opt.step(flat)
    assert int(opt.step_count) == 1
    clean_scale = [np.asarray(s) for s in f8.scale]
    clean_hist = [np.asarray(h) for h in f8.amax_history]
    params_before = jax.tree_util.tree_map(np.asarray, opt.params)
    # poisoned gradients: inf in one leaf
    bad = dict(grads)
    bad["w"] = grads["w"].at[0, 0].set(jnp.inf)
    flat_bad = pipe.unscale_and_norm(pipe.pack(bad), scaler)
    flat_bad, f8_bad = pipe.fp8_update(f8, flat_bad)
    assert int(flat_bad.found_inf) == 1
    opt.step(flat_bad)
    # the step clock held and params did not move
    assert int(opt.step_count) == 1
    _assert_trees_equal(opt.params, params_before)
    # fp8 history held everywhere; only the poisoned tensor's scale
    # backed off (the per-tensor backoff discipline)
    for h, hc in zip(f8_bad.amax_history, clean_hist):
        np.testing.assert_array_equal(np.asarray(h), hc)
    sc = np.concatenate([np.asarray(s) for s in f8_bad.scale])
    cl = np.concatenate(clean_scale)
    assert (sc <= cl).all() and (sc < cl).any()


def test_already_skipped_step_holds_fp8_history():
    """A loss-scale overflow (found_inf set before the fp8 update)
    must keep garbage amax out of the window entirely."""
    policy = fp8.Fp8Policy()
    tree = _tree()
    plan = cached_plan(tree)
    pipe = amp.FlatGradPipeline(plan=plan, fp8=policy)
    f8 = pipe.fp8_init()
    bufs = plan.pack_grads(tree)
    flat = pipe.unscale_and_norm(bufs, inv_scale=jnp.float32(1.0))
    flat = flat._replace(found_inf=jnp.int32(1))   # externally skipped
    flat2, f8b = pipe.fp8_update(f8, flat)
    assert int(flat2.found_inf) == 1
    _assert_trees_equal(f8b.amax_history, f8.amax_history)
    _assert_trees_equal(f8b.scale, f8.scale)


# ---------------------------------------------------------------------
# packed-state round trips
# ---------------------------------------------------------------------

def _fp8_opt(params, policy, **kw):
    opt = FusedAdam(params, lr=1e-2, **kw)
    opt.enable_fp8(policy)
    return opt


def _fp8_slots(opt):
    return {k: [np.asarray(b) for b in v]
            for k, v in opt.opt_state.items() if k.startswith("fp8_")}


def _slots_equal(a, b):
    assert sorted(a) == sorted(b)
    for k in a:
        for x, y in zip(a[k], b[k]):
            np.testing.assert_array_equal(x, y)


def test_state_dict_round_trip_bit_exact():
    policy = fp8.Fp8Policy(amax_history_len=4)
    params = _tree(key=11)
    grads = jax.tree_util.tree_map(jnp.ones_like, params)
    opt = _fp8_opt(params, policy)
    opt.step(grads)
    sd = opt.state_dict()
    opt2 = _fp8_opt(params, policy)
    opt2.load_state_dict(sd)
    opt2.params = opt.params      # state_dict restores state, not params
    _slots_equal(_fp8_slots(opt), _fp8_slots(opt2))
    # continuation is bit-exact
    opt.step(grads)
    opt2.step(grads)
    _slots_equal(_fp8_slots(opt), _fp8_slots(opt2))
    _assert_trees_equal(opt.params, opt2.params)


def test_checkpoint_v2_round_trip_bit_exact(tmp_path):
    from apex_tpu import checkpoint
    policy = fp8.Fp8Policy(amax_history_len=4)
    params = _tree(key=13)
    grads = jax.tree_util.tree_map(jnp.ones_like, params)
    opt = _fp8_opt(params, policy)
    opt.step(grads)
    p = str(tmp_path / "fp8.ckpt")
    checkpoint.save_training_state(p, optimizer=opt, step=1)
    with open(p, "rb") as f:
        assert b"APEX_TPU_CKPT_V2" in f.read(512)   # v2 really taken
    opt2 = _fp8_opt(params, policy)
    checkpoint.load_training_state(p, opt.params, opt2)
    _slots_equal(_fp8_slots(opt), _fp8_slots(opt2))
    opt.step(grads)
    opt2.step(grads)
    _slots_equal(_fp8_slots(opt), _fp8_slots(opt2))
    _assert_trees_equal(opt.params, opt2.params)


def test_rechunk_preserves_fp8_state_values():
    policy = fp8.Fp8Policy(amax_history_len=4)
    params = _tree(key=17)
    grads = jax.tree_util.tree_map(jnp.ones_like, params)
    opt = _fp8_opt(params, policy)
    opt.step(grads)
    scales_before = jax.tree_util.tree_map(np.asarray,
                                           opt.fp8_scales())
    ref = _fp8_opt(params, policy)
    ref.step(grads)
    assert opt.rechunk(600)
    assert len(opt._plan.buckets) > 1
    scales_after = jax.tree_util.tree_map(np.asarray,
                                          opt.fp8_scales())
    _assert_trees_equal(scales_before, scales_after)
    # continuation bit-exact vs the un-rechunked twin
    opt.step(grads)
    ref.step(grads)
    _assert_trees_equal(opt.params, ref.params)
    _assert_trees_equal(opt.fp8_scales(), ref.fp8_scales())


def test_offload_round_trip_matches_resident():
    policy = fp8.Fp8Policy(amax_history_len=4)
    params = _tree(key=19)
    grads = jax.tree_util.tree_map(jnp.ones_like, params)
    a = _fp8_opt(params, policy)
    b = _fp8_opt(params, policy, offload_state=True)
    for _ in range(2):
        a.step(grads)
        b.step(grads)
    _slots_equal(_fp8_slots(a), _fp8_slots(b))
    sd = b.state_dict()
    c = _fp8_opt(params, policy, offload_state=True)
    c.load_state_dict(sd)
    c.params = b.params           # state_dict restores state, not params
    c.step(grads)
    a.step(grads)
    _slots_equal(_fp8_slots(a), _fp8_slots(c))


def test_packer_vector_field_round_trip():
    tree = _tree()
    plan = cached_plan(tree)
    field = jax.tree_util.tree_map(
        lambda l: jnp.arange(6, dtype=jnp.float32)
        * (1.0 + l.size), tree)
    packed = plan.pack_state_field(field)
    assert all(b.ndim == 2 and b.shape[1] == 6 for b in packed)
    back = plan.unpack_state_field(packed)
    _assert_trees_equal(field, back)


def test_per_leaf_optimizer_rejects_enable_fp8():
    opt = FusedSGD(_tree(), lr=1e-2, fuse_buckets=False)
    with pytest.raises(ValueError, match="bucketed"):
        opt.enable_fp8(fp8.Fp8Policy())


# ---------------------------------------------------------------------
# dispatch prefs: tuned policy + int8 routing
# ---------------------------------------------------------------------

def test_tuned_policy_reads_prefs(monkeypatch):
    from apex_tpu.ops import _dispatch
    monkeypatch.setattr(_dispatch, "_FP8",
                        {"amax_history_len": 8, "interval": 4})
    p = fp8.tuned_policy()
    assert p.amax_history_len == 8 and p.interval == 4
    assert fp8.tuned_policy(interval=2).interval == 2   # override wins


def test_int8_matmul_auto_routes_through_prefs(monkeypatch):
    from apex_tpu.ops import _dispatch
    from apex_tpu.quantization import int8_matmul, quantize_int8
    x = jax.random.normal(jax.random.key(0), (4, 8), jnp.bfloat16)
    w = quantize_int8(jax.random.normal(jax.random.key(1),
                                        (8, 4)) * 0.1)
    monkeypatch.setattr(_dispatch, "_QUANT", {"int8_dynamic": True})
    auto = int8_matmul(x, w, dynamic=None)
    dyn = int8_matmul(x, w, dynamic=True)
    wo = int8_matmul(x, w, dynamic=False)
    np.testing.assert_array_equal(np.asarray(auto), np.asarray(dyn))
    monkeypatch.setattr(_dispatch, "_QUANT", {})
    auto2 = int8_matmul(x, w, dynamic=None)
    np.testing.assert_array_equal(np.asarray(auto2), np.asarray(wo))
    # an explicit bool always beats the table
    monkeypatch.setattr(_dispatch, "_QUANT", {"int8_dynamic": True})
    np.testing.assert_array_equal(
        np.asarray(int8_matmul(x, w, dynamic=False)), np.asarray(wo))


def test_prefs_table_normalizes_fp8_and_quant_sections():
    from apex_tpu.ops._dispatch import _normalize_doc
    t = _normalize_doc({
        "fp8": {"amax_history_len": 8, "interval": "bogus"},
        "quantization": {"int8_dynamic": True, "junk": 1}}, None)
    assert t.fp8 == {"amax_history_len": 8}
    assert t.quantization == {"int8_dynamic": True}


# ---------------------------------------------------------------------
# watchdog: fp8 scale collapse -> rollback -> bit-exact replay
# ---------------------------------------------------------------------

def test_fp8_detector_fires_on_pinned_scale_only():
    from apex_tpu.resilience.watchdog import Fp8ScaleCollapseDetector
    det = Fp8ScaleCollapseDetector(floor=1.0, windows=2)
    healthy = [{"step": s, "fp8/scale_min": 64.0} for s in range(4)]
    assert det.observe(healthy) == []
    pinned = [{"step": s, "fp8/scale_min": 0.5} for s in range(4, 8)]
    assert det.observe(pinned) == []            # first floored window
    a = det.observe([{"step": s, "fp8/scale_min": 0.25}
                     for s in range(8, 12)])
    assert len(a) == 1 and a[0].kind == "fp8_scale_collapse"
    assert a[0].severity == "critical"
    # no-information windows don't count either way
    det.reset()
    assert det.observe([{"step": 0, "loss": 1.0}]) == []


def test_default_fp8_detector_ignores_no_signal_init_scale():
    """A tensor with no gradient signal keeps its INIT scale of
    exactly 1.0 forever — the default-suite detector must read that
    as healthy, not as a collapse (its default floor is 2^-8)."""
    from apex_tpu.resilience.watchdog import Fp8ScaleCollapseDetector
    det = Fp8ScaleCollapseDetector()
    for w in range(4):
        assert det.observe(
            [{"step": w * 4 + s, "fp8/scale_min": 1.0}
             for s in range(4)]) == []
    # eight consecutive backoffs from init IS a storm
    det2 = Fp8ScaleCollapseDetector()
    det2.observe([{"step": 0, "fp8/scale_min": 2.0 ** -8}])
    a = det2.observe([{"step": 1, "fp8/scale_min": 2.0 ** -9}])
    assert len(a) == 1 and a[0].kind == "fp8_scale_collapse"


def test_fp8_collapse_in_default_suite_and_actions():
    from apex_tpu.resilience.watchdog import (DEFAULT_ACTIONS,
                                              default_detectors)
    assert DEFAULT_ACTIONS["fp8_scale_collapse"] == "rollback"
    kinds = [getattr(d, "kind", None) for d in default_detectors()]
    assert "fp8_scale_collapse" in kinds


class _Fp8Job:
    """Self-healing fp8 run: eager loop recording fp8/scale_min into
    the telemetry ring; a pinned-scale storm must roll back to LKG
    and replay bit-exactly (the metric stream was poisoned, the
    optimizer path is deterministic — and the fp8 slots ride the v2
    checkpoint through the rollback)."""

    TOTAL, EVERY = 24, 3

    def __init__(self, ckpt_dir, storm_steps=0):
        from apex_tpu import telemetry as telemetry_mod
        from apex_tpu.resilience import CheckpointManager
        from apex_tpu.resilience.retry import RetryPolicy
        from apex_tpu.resilience.watchdog import (
            Fp8ScaleCollapseDetector, Watchdog, WatchdogPolicy)
        params = _tree(key=23)
        self.opt = _fp8_opt(params, fp8.Fp8Policy(amax_history_len=4))
        self.g = jax.tree_util.tree_map(
            lambda p: jnp.ones_like(p) * 1e-2, params)
        self.mgr = CheckpointManager(ckpt_dir, keep=3, every=self.EVERY)
        self.template = jax.tree_util.tree_map(jnp.zeros_like, params)
        self.tel = telemetry_mod.Telemetry(run_dir=None, window=4,
                                           retrace=False)
        self.wd = Watchdog(
            detectors=[Fp8ScaleCollapseDetector(floor=1.0, windows=2)],
            policy=WatchdogPolicy(rollback=RetryPolicy(
                max_retries=2, base_delay_s=0.0)),
            telemetry=self.tel, clean_window=4)
        self.storm_budget = storm_steps

    def step_fn(self, step):
        self.opt.step(self.g)
        scale_min = 64.0
        if step >= 8 and self.storm_budget > 0:
            self.storm_budget -= 1          # APPLICATION-budgeted:
            scale_min = 0.5                 # replays land clean
        self.tel.record({"fp8/scale_min": scale_min}, step)

    def run(self):
        from apex_tpu.resilience import run_elastic
        return run_elastic(self.step_fn, self.mgr, self.opt,
                           total_steps=self.TOTAL,
                           params_like=self.template,
                           watchdog=self.wd, backoff_s=0.0)

    def close(self):
        self.wd.close()
        self.tel.close()
        self.mgr.close()


def test_fp8_scale_collapse_rolls_back_and_replays_bit_exact(tmp_path):
    ref = _Fp8Job(str(tmp_path / "ref"))
    res = ref.run()
    assert res.step == _Fp8Job.TOTAL and res.rollbacks == 0
    ref.close()

    job = _Fp8Job(str(tmp_path / "storm"), storm_steps=8)
    with pytest.warns(UserWarning, match="watchdog rollback"):
        res = job.run()
    assert res.step == _Fp8Job.TOTAL and res.rollbacks == 1
    assert "fp8_scale_collapse" in [a.kind for a in job.wd.timeline]
    rb = [e for e in job.wd.events if e["action"] == "rollback"]
    assert rb and rb[0]["to_step"] is not None
    # bit-exact replay, fp8 slots included
    _assert_trees_equal(job.opt.params, ref.opt.params)
    _slots_equal(_fp8_slots(job.opt), _fp8_slots(ref.opt))
    job.close()


# ---------------------------------------------------------------------
# the spec + bench smoke
# ---------------------------------------------------------------------

def test_fp8_step_spec_passes():
    from apex_tpu.lint.semantic.registry import verify_all
    (res,) = verify_all(["amp.fp8_step"])
    assert res.ok, res.failures
    assert "fp8_quantize_counts" in res.checked
    assert "donated_aliases_min" in res.checked
    assert "no_host_transfer" in res.checked


def test_fp8_bench_smoke():
    from apex_tpu.amp.fp8_bench import (bench_fp8_matmul,
                                        bench_fp8_scale_update)
    r = bench_fp8_matmul(m=32, k=32, n=32, iters=2, reps=2)
    assert r["fp8_matmul_ms"] > 0 and r["bf16_matmul_ms"] > 0
    assert r["fp8_matmul_speedup"] is not None
    r2 = bench_fp8_scale_update(layers=3, hidden=16, iters=2, reps=2)
    assert r2["fp8_scale_fused_ms"] > 0
    assert r2["fp8_scale_update_speedup"] is not None


def test_budget_has_fp8_row():
    import json
    import os
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    with open(os.path.join(here, "tools", "perf_budget.json")) as f:
        budget = json.load(f)
    row = budget["metrics"]["extra.fp8_matmul_speedup"]
    assert row["floor"] == 1.5 and row["direction"] == "higher"
