"""fp16_utils + RNN + reparameterization suites (reference test pattern:
tests/L0/run_fp16util/ — half/master round-trips; RNN cells vs a naive
per-timestep recurrence oracle; weight-norm reconstruction identities)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.RNN import GRU, LSTM, mLSTM
from apex_tpu.fp16_utils import (
    BN_convert_float,
    DynamicLossScaler,
    FP16_Optimizer,
    master_params_to_model_params,
    network_to_half,
    prep_param_lists,
    tree_to_half,
)
from apex_tpu.optimizers import FusedSGD
from apex_tpu.reparameterization import (
    apply_weight_norm,
    remove_weight_norm,
    reparametrize,
)

# ---------------------------------------------------------------------------
# fp16_utils
# ---------------------------------------------------------------------------


def test_network_to_half_keeps_norm_layers_f32():
    params = {"dense": {"kernel": jnp.ones((4, 4))},
              "layernorm_0": {"scale": jnp.ones((4,))},
              "bn": {"bias": jnp.zeros((4,))}}
    half = network_to_half(params)
    assert half["dense"]["kernel"].dtype == jnp.bfloat16
    assert half["layernorm_0"]["scale"].dtype == jnp.float32
    assert half["bn"]["bias"].dtype == jnp.float32
    assert tree_to_half(params)["layernorm_0"]["scale"].dtype == jnp.bfloat16


def test_prep_and_writeback_roundtrip():
    model = {"w": jnp.ones((8,), jnp.bfloat16) * 0.5}
    model, masters = prep_param_lists(model)
    assert masters["w"].dtype == jnp.float32
    masters = {"w": masters["w"] + 0.25}
    model2 = master_params_to_model_params(model, masters)
    assert model2["w"].dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(model2["w"], np.float32), 0.75)


def test_prep_param_lists_flat_master():
    model = {"a": jnp.ones((4,), jnp.bfloat16),
             "b": jnp.zeros((2, 2), jnp.bfloat16)}
    _, (flat, unravel) = prep_param_lists(model, flat_master=True)
    assert flat.dtype == jnp.float32 and flat.shape == (8,)
    back = unravel(flat)
    assert back["b"].shape == (2, 2)


def test_dynamic_loss_scaler_backoff_and_growth():
    s = DynamicLossScaler(init_scale=2.0 ** 8, scale_window=2)
    assert s.has_overflow({"g": jnp.asarray([jnp.inf])})
    s.update_scale(True)
    assert s.loss_scale == 2.0 ** 7
    s.update_scale(False)
    s.update_scale(False)
    assert s.loss_scale == 2.0 ** 8       # grew after window clean steps


def test_fp16_optimizer_skips_on_overflow_and_steps_clean():
    params = {"w": jnp.ones((16,), jnp.bfloat16)}
    opt = FusedSGD(params, lr=0.5)
    fopt = FP16_Optimizer(opt, dynamic_loss_scale=True,
                          dynamic_loss_args={"init_scale": 4.0})
    scale0 = fopt.loss_scale
    bad = {"w": jnp.full((16,), jnp.inf, jnp.float32) * scale0}
    p_after = fopt.step(bad)
    assert fopt.overflow
    assert fopt.loss_scale == scale0 / 2.0
    np.testing.assert_allclose(np.asarray(p_after["w"], np.float32), 1.0)
    good = {"w": jnp.full((16,), 1.0) * fopt.loss_scale}   # d(loss*s)/dw
    p_after = fopt.step(good)
    assert not fopt.overflow
    np.testing.assert_allclose(np.asarray(p_after["w"], np.float32), 0.5)


# ---------------------------------------------------------------------------
# RNN — scan cells vs naive per-step recurrence
# ---------------------------------------------------------------------------

T, B, IN, HID = 6, 3, 8, 16


def _np_lstm(params, x, layer=0):
    wi = np.asarray(params[f"l{layer}_i2h"]["kernel"])
    bi = np.asarray(params[f"l{layer}_i2h"]["bias"])
    wh = np.asarray(params[f"l{layer}_h2h_kernel"])
    bh = np.asarray(params[f"l{layer}_h2h_bias"])
    h = np.zeros((x.shape[1], HID), np.float32)
    c = np.zeros_like(h)
    sig = lambda a: 1.0 / (1.0 + np.exp(-a))  # noqa: E731
    outs = []
    for t in range(x.shape[0]):
        g = x[t] @ wi + bi + h @ wh + bh
        i, f, gg, o = np.split(g, 4, axis=-1)
        c = sig(f) * c + sig(i) * np.tanh(gg)
        h = sig(o) * np.tanh(c)
        outs.append(h)
    return np.stack(outs), h, c


def test_lstm_matches_naive_recurrence():
    m = LSTM(input_size=IN, hidden_size=HID)
    x = jax.random.normal(jax.random.PRNGKey(0), (T, B, IN))
    params = m.init(jax.random.PRNGKey(1), x)["params"]
    out, (h_n, c_n) = m.apply({"params": params}, x)
    want, h, c = _np_lstm(params, np.asarray(x))
    np.testing.assert_allclose(np.asarray(out), want, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(h_n[0]), h, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(c_n[0]), c, rtol=1e-5, atol=1e-5)


def test_gru_shapes_and_determinism():
    m = GRU(input_size=IN, hidden_size=HID, num_layers=2)
    x = jax.random.normal(jax.random.PRNGKey(0), (T, B, IN))
    params = m.init(jax.random.PRNGKey(1), x)["params"]
    out, h_n = m.apply({"params": params}, x)
    assert out.shape == (T, B, HID) and h_n.shape == (2, B, HID)
    out2, _ = m.apply({"params": params}, x)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(out2))


def test_mlstm_runs_and_multiplicative_path_matters():
    m = mLSTM(input_size=IN, hidden_size=HID)
    x = jax.random.normal(jax.random.PRNGKey(0), (T, B, IN))
    params = m.init(jax.random.PRNGKey(1), x)["params"]
    out, _ = m.apply({"params": params}, x)
    assert out.shape == (T, B, HID)
    # zeroing the multiplicative projection changes the output
    z = dict(params)
    z["l0_mx"] = jax.tree_util.tree_map(jnp.zeros_like, params["l0_mx"])
    out_z, _ = m.apply({"params": z}, x)
    assert not np.allclose(np.asarray(out), np.asarray(out_z))


def test_lstm_grad_flows_through_scan():
    m = LSTM(input_size=IN, hidden_size=HID)
    x = jax.random.normal(jax.random.PRNGKey(0), (T, B, IN))
    params = m.init(jax.random.PRNGKey(1), x)["params"]
    g = jax.grad(lambda p: jnp.sum(m.apply({"params": p}, x)[0] ** 2))(
        params)
    assert float(jnp.linalg.norm(g["l0_i2h"]["kernel"])) > 0


# ---------------------------------------------------------------------------
# reparameterization
# ---------------------------------------------------------------------------

def test_weight_norm_roundtrip_identity():
    w = jax.random.normal(jax.random.PRNGKey(0), (8, 4))
    tree = {"dense": {"kernel": w, "bias": jnp.zeros((4,))}}
    wn = apply_weight_norm(tree, dim=-1)
    back = remove_weight_norm(wn)
    np.testing.assert_allclose(np.asarray(back["dense"]["kernel"]),
                               np.asarray(w), rtol=1e-5, atol=1e-6)
    assert back["dense"]["bias"].shape == (4,)


def test_weight_norm_g_scales_magnitude():
    w = jax.random.normal(jax.random.PRNGKey(0), (8, 4))
    wn = apply_weight_norm({"k": {"kernel": w}}, dim=-1)
    wn["k"]["kernel"]["g1"] = wn["k"]["kernel"]["g1"] * 2.0
    w2 = reparametrize(wn)["k"]["kernel"]
    np.testing.assert_allclose(np.asarray(w2), 2.0 * np.asarray(w),
                               rtol=1e-5, atol=1e-5)


def test_weight_norm_differentiable():
    w = jax.random.normal(jax.random.PRNGKey(0), (8, 4))
    wn = apply_weight_norm({"k": {"kernel": w}}, dim=-1)

    def loss(t):
        return jnp.sum(reparametrize(t)["k"]["kernel"] ** 2)
    g = jax.grad(loss)(wn)
    assert float(jnp.linalg.norm(g["k"]["kernel"]["v"])) >= 0
    assert float(jnp.linalg.norm(g["k"]["kernel"]["g1"])) > 0


def test_weight_norm_size1_dim_roundtrip():
    # regression: dim axis of size 1 must still reconstruct exactly
    w = jnp.asarray([[1.0], [2.0], [-3.0]])          # (3, 1), dim=-1
    wn = apply_weight_norm({"k": {"kernel": w}}, dim=-1)
    back = reparametrize(wn)["k"]["kernel"]
    np.testing.assert_allclose(np.asarray(back), np.asarray(w),
                               rtol=1e-6, atol=1e-6)


def test_dynamic_loss_scaler_window_one_grows_first_step():
    """ADVICE r1: with scale_window=1 the FIRST clean step already grows
    the scale (reference condition (iter - last_overflow) % window == 0)."""
    from apex_tpu.fp16_utils import DynamicLossScaler
    s = DynamicLossScaler(init_scale=2.0 ** 8, scale_window=1)
    s.update_scale(overflow=False)
    assert s.loss_scale == 2.0 ** 9
