"""L0 tests for the O1 casting engine (reference test model:
tests/L0/run_amp/test_basic_casts.py + test_promotion.py — does each
listed op run at its listed precision, do mixed inputs promote)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import amp


def _prim_in_dtypes(fn, name, *args):
    jx = jax.make_jaxpr(fn)(*args)
    out = []
    for e in jx.jaxpr.eqns:
        if e.primitive.name == name:
            out += [str(v.aval.dtype) for v in e.invars
                    if hasattr(v.aval, "dtype")]
    return out


def test_O0_is_identity():
    f = lambda x: x @ x
    assert amp.auto_cast(f, compute_dtype=jnp.float32) is f


def test_basic_casts_matmul_half_exp_fp32():
    """FP16_FUNCS analog: dot_general runs bf16; FP32_FUNCS analog:
    exp/log run f32 — on an untouched f32 function."""
    def f(x):
        return jnp.sum(jnp.exp(x @ x * 0.01))

    x = jax.random.normal(jax.random.key(0), (32, 32))
    w = amp.auto_cast(f, compute_dtype=jnp.bfloat16)
    assert set(_prim_in_dtypes(w, "dot_general", x)) == {"bfloat16"}
    assert set(_prim_in_dtypes(w, "exp", x)) == {"float32"}
    np.testing.assert_allclose(float(w(x)), float(f(x)), rtol=2e-2)


def test_promotion_mixed_widens():
    """CASTS analog: bf16 (from a whitelisted op) + f32 operand ->
    the add runs f32, not bf16."""
    def f(x, y):
        h = x @ x          # becomes bf16
        return h + y       # y stays f32 -> promote

    x = jax.random.normal(jax.random.key(0), (16, 16))
    y = jax.random.normal(jax.random.key(1), (16, 16))
    w = amp.auto_cast(f, compute_dtype=jnp.bfloat16)
    assert set(_prim_in_dtypes(w, "add", x, y)) == {"float32"}


def test_nested_jit_and_custom_jvp_are_rewritten():
    """ops inside jitted subfunctions and custom_jvp wrappers (e.g.
    jax.nn.log_softmax) are reached by the rewriter."""
    def f(x):
        return jnp.mean(jax.nn.log_softmax(jax.jit(lambda a: a @ a)(x)))

    x = jax.random.normal(jax.random.key(0), (16, 16))
    w = amp.auto_cast(f, compute_dtype=jnp.bfloat16)
    assert set(_prim_in_dtypes(w, "dot_general", x)) == {"bfloat16"}
    assert set(_prim_in_dtypes(w, "exp", x)) == {"float32"}


def test_opaque_custom_vjp_still_correct():
    """The package's own Pallas ops (custom_vjp, dtype-bound) run
    unmodified at traced precision inside a wrapped function, values
    and grads intact."""
    from apex_tpu.ops.layer_norm import fused_layer_norm

    def f(x, g):
        return jnp.sum(fused_layer_norm(x @ x, g) ** 2)

    x = jax.random.normal(jax.random.key(0), (128, 128))
    g = jnp.ones((128,))
    w = amp.auto_cast(f, compute_dtype=jnp.bfloat16)
    np.testing.assert_allclose(float(w(x, g)), float(f(x, g)), rtol=3e-2)
    gw = jax.grad(w)(x, g)
    gf = jax.grad(f)(x, g)
    assert bool(jnp.all(jnp.isfinite(gw)))
    np.testing.assert_allclose(np.asarray(gw), np.asarray(gf),
                               rtol=1.0, atol=0.15)  # bf16 fwd, loose


def test_opaque_user_custom_vjp_with_gemm_warns():
    """VERDICT r3 #4: a USER custom_vjp whose body holds a plain XLA
    GEMM is skipped by O1 — that skip must be audible, not silent."""
    import warnings as _w
    from apex_tpu.amp import wrap as _wrap

    @jax.custom_vjp
    def user_op(x, w):
        return jnp.tanh(x @ w)

    def fwd(x, w):
        return user_op(x, w), (x, w)

    def bwd(res, ct):
        x, w = res
        dy = ct * (1 - jnp.tanh(x @ w) ** 2)
        return dy @ w.T, x.T @ dy

    user_op.defvjp(fwd, bwd)

    def f(x, w):
        return jnp.sum(user_op(x, w))

    x = jax.random.normal(jax.random.key(0), (16, 16))
    wt = jax.random.normal(jax.random.key(1), (16, 16))
    _wrap._OPAQUE_WARNED.clear()
    with pytest.warns(UserWarning, match="opaque to the casting"):
        amp.auto_cast(f, compute_dtype=jnp.bfloat16)(x, wt)
    # one-time: a second trace of the same primitive stays quiet
    with _w.catch_warnings():
        _w.simplefilter("error")
        amp.auto_cast(f, compute_dtype=jnp.bfloat16)(x, wt)


def test_opaque_own_kernels_do_not_warn():
    """The package's own custom_vjp kernels (pallas bodies, precision
    managed internally) must NOT trigger the opaque-GEMM warning —
    pallas_call interiors are precision-explicit by design."""
    import warnings as _w
    from apex_tpu.amp import wrap as _wrap
    from apex_tpu.ops.attention import flash_attention
    from apex_tpu.ops.layer_norm import fused_layer_norm

    def f(q, k, v, g):
        o = flash_attention(q, k, v)
        return jnp.sum(fused_layer_norm(o[0, :, 0, :], g))

    q = jax.random.normal(jax.random.key(0), (1, 128, 2, 64))
    k = jax.random.normal(jax.random.key(1), (1, 128, 2, 64))
    v = jax.random.normal(jax.random.key(2), (1, 128, 2, 64))
    g = jnp.ones((64,))
    _wrap._OPAQUE_WARNED.clear()   # dedup must not mask a failure here
    with _w.catch_warnings():
        _w.simplefilter("error", UserWarning)
        jax.make_jaxpr(amp.auto_cast(f, compute_dtype=jnp.bfloat16))(
            q, k, v, g)


def test_opaque_bare_pallas_call_does_not_warn():
    """A DIRECT pallas_call (no custom_vjp around it) with a dot in its
    kernel body is a kernel — precision-explicit by design, no warning
    (code-review r4: the nested-skip alone missed this case)."""
    import warnings as _w
    from jax.experimental import pallas as pl
    from apex_tpu.amp import wrap as _wrap

    def kernel(x_ref, w_ref, o_ref):
        o_ref[...] = jnp.dot(x_ref[...], w_ref[...],
                             preferred_element_type=jnp.float32)

    def f(x, w):
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((16, 16), jnp.float32),
            interpret=True)(x, w)

    x = jax.random.normal(jax.random.key(0), (16, 16))
    wt = jax.random.normal(jax.random.key(1), (16, 16))
    _wrap._OPAQUE_WARNED.clear()
    with _w.catch_warnings():
        _w.simplefilter("error", UserWarning)
        jax.make_jaxpr(amp.auto_cast(f, compute_dtype=jnp.bfloat16))(
            x, wt)


def test_opaque_warning_fires_per_distinct_op():
    """Two DIFFERENT user custom_vjp ops share one primitive name AND
    one operand signature; the dedup must still not let the first
    swallow the second's warning (code-review r4: the body fingerprint
    is what tells them apart)."""
    from apex_tpu.amp import wrap as _wrap

    def make_op(act):
        @jax.custom_vjp
        def op(x, w):
            return act(x @ w)

        def fwd(x, w):
            return op(x, w), (x, w)

        def bwd(res, ct):
            x, w = res
            return ct @ w.T, x.T @ ct

        op.defvjp(fwd, bwd)
        return op

    op_a, op_b = make_op(jnp.tanh), make_op(jax.nn.sigmoid)
    xa = jax.random.normal(jax.random.key(0), (8, 8))
    _wrap._OPAQUE_WARNED.clear()
    with pytest.warns(UserWarning, match="opaque to the casting"):
        amp.auto_cast(lambda x: jnp.sum(op_a(x, x)),
                      compute_dtype=jnp.bfloat16)(xa)
    with pytest.warns(UserWarning, match="opaque to the casting"):
        amp.auto_cast(lambda x: jnp.sum(op_b(x, x)),
                      compute_dtype=jnp.bfloat16)(xa)


def test_grad_composes():
    def f(p, x):
        return jnp.mean((x @ p["w"] + p["b"]) ** 2)

    p = {"w": jax.random.normal(jax.random.key(0), (8, 4)),
         "b": jnp.zeros((4,))}
    x = jax.random.normal(jax.random.key(1), (16, 8))
    w = amp.auto_cast(f, compute_dtype=jnp.bfloat16)
    g = jax.jit(jax.grad(w))(p, x)
    g_ref = jax.grad(f)(p, x)
    for a, b in zip(jax.tree_util.tree_leaves(g),
                    jax.tree_util.tree_leaves(g_ref)):
        assert a.dtype == jnp.float32
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-2, atol=5e-2)


def test_cast_inputs_argnums():
    seen = {}

    def f(p, x):
        seen["p"] = p.dtype
        seen["x"] = x.dtype
        return x

    w = amp.cast_inputs(f, jnp.bfloat16, argnums=(1,))
    w(jnp.zeros((2,), jnp.float32), jnp.zeros((2,), jnp.float32))
    assert seen["p"] == jnp.float32
    assert seen["x"] == jnp.bfloat16


def test_pytree_outputs_roundtrip():
    def f(x):
        return {"a": x @ x, "aux": (jnp.sum(x), x + 1)}

    x = jax.random.normal(jax.random.key(0), (8, 8))
    w = amp.auto_cast(f, compute_dtype=jnp.bfloat16)
    out = w(x)
    assert set(out) == {"a", "aux"}
    assert out["a"].shape == (8, 8)
    assert len(out["aux"]) == 2


def _scan_body_prim_dtypes(fn, name, *args):
    """Dtypes of `name` operands INSIDE scan bodies (recursively)."""
    out = []

    def walk(jaxpr):
        for e in jaxpr.eqns:
            if e.primitive.name == name:
                out.extend(str(v.aval.dtype) for v in e.invars
                           if hasattr(v.aval, "dtype"))
            for p in e.params.values():
                if hasattr(p, "jaxpr"):          # ClosedJaxpr
                    walk(p.jaxpr)
                elif isinstance(p, (tuple, list)):
                    for q in p:
                        if hasattr(q, "jaxpr"):
                            walk(q.jaxpr)

    jx = jax.make_jaxpr(fn)(*args)
    for e in jx.jaxpr.eqns:
        if e.primitive.name == "scan":
            walk(e.params["jaxpr"].jaxpr)
        else:
            for p in e.params.values():
                if hasattr(p, "jaxpr"):
                    walk(p.jaxpr)
    return out


def test_scan_based_model_rewritten_with_coherent_carry():
    """lax.scan bodies ARE rewritten (the reference reaches ops inside
    RNN loops via rnn_compat — SURVEY.md §2.1): values and grads stay
    correct, the carry keeps its traced dtype at the loop boundary, and
    the in-body matmul runs at compute dtype."""
    from apex_tpu.RNN import LSTM

    model = LSTM(input_size=16, hidden_size=32, num_layers=1)
    x = jax.random.normal(jax.random.key(0), (12, 2, 16))
    params = model.init(jax.random.key(1), x)

    def f(p, x):
        out, _ = model.apply(p, x)
        return jnp.mean(out ** 2)

    w = amp.auto_cast(f, compute_dtype=jnp.bfloat16)
    np.testing.assert_allclose(float(w(params, x)), float(f(params, x)),
                               rtol=3e-2, atol=1e-3)
    # boundary coherence: the scan eqn's own float operands (carry
    # init, consts, xs) keep their traced f32 dtypes...
    scan_in = _prim_in_dtypes(w, "scan", params, x)
    assert scan_in, "expected a scan eqn in the rewritten jaxpr"
    assert set(d for d in scan_in if "float" in d or "bfloat" in d) \
        == {"float32"}
    # ...while the recurrent h2h matmul INSIDE the body runs bf16
    body_dots = _scan_body_prim_dtypes(w, "dot_general", params, x)
    assert "bfloat16" in body_dots, body_dots
    g = jax.grad(w)(params, x)
    assert all(bool(jnp.all(jnp.isfinite(l)))
               for l in jax.tree_util.tree_leaves(g))


def test_scan_over_layers_gpt_block_bf16_inside():
    """VERDICT r2 #3 done criterion: a lax.scan-over-layers transformer
    block — the dominant big-model idiom — shows bf16 dot_generals
    inside the scan under O1, with f32 carry at the boundary."""
    L, D, H = 4, 32, 64

    def init_layers(key):
        ks = jax.random.split(key, 4)
        s = 1.0 / np.sqrt(D)
        return {
            "wq": jax.random.normal(ks[0], (L, D, D)) * s,
            "wo": jax.random.normal(ks[1], (L, D, D)) * s,
            "w1": jax.random.normal(ks[2], (L, D, H)) * s,
            "w2": jax.random.normal(ks[3], (L, H, D)) * (1.0 / np.sqrt(H)),
        }

    def block(x, lp):
        a = x @ lp["wq"]
        a = jax.nn.softmax(a @ a.T * (1.0 / np.sqrt(D)), axis=-1) @ x
        x = x + a @ lp["wo"]
        h = jax.nn.gelu(x @ lp["w1"])
        return x + h @ lp["w2"]

    def f(p, x):
        def body(carry, lp):
            return block(carry, lp), ()
        out, _ = jax.lax.scan(body, x, p)
        return jnp.mean(out ** 2)

    p = init_layers(jax.random.key(0))
    x = jax.random.normal(jax.random.key(1), (16, D))
    w = amp.auto_cast(f, compute_dtype=jnp.bfloat16)

    body_dots = _scan_body_prim_dtypes(w, "dot_general", p, x)
    assert body_dots and set(body_dots) == {"bfloat16"}, body_dots
    # softmax internals stay f32 inside the loop too
    body_exp = _scan_body_prim_dtypes(w, "exp", p, x)
    assert body_exp and set(body_exp) == {"float32"}, body_exp
    # carry stays f32 at the boundary
    scan_in = _prim_in_dtypes(w, "scan", p, x)
    assert set(d for d in scan_in if "float" in d or "bfloat" in d) \
        == {"float32"}
    np.testing.assert_allclose(float(w(p, x)), float(f(p, x)),
                               rtol=3e-2, atol=1e-3)
    g = jax.grad(w)(p, x)
    g_ref = jax.grad(f)(p, x)
    for a, b in zip(jax.tree_util.tree_leaves(g),
                    jax.tree_util.tree_leaves(g_ref)):
        assert a.dtype == jnp.float32
        a, b = np.asarray(a).ravel(), np.asarray(b).ravel()
        # bf16 through 4 attention layers drifts from the f32 oracle by
        # construction (verified separately: the engine matches a
        # hand-cast mixed-precision scan oracle at cos>0.995 per leaf;
        # deep-layer wq sits near 0.90 vs f32 for ANY bf16 evaluation
        # of this block, scanned or unrolled) — assert direction sanity
        cos = float(a @ b / (np.linalg.norm(a) * np.linalg.norm(b)))
        assert cos > 0.85, cos


def test_while_body_rewritten():
    """while_loop bodies get the same treatment: GEMM in bf16 inside,
    carry dtype preserved, values correct."""
    def f(p, x):
        def cond(c):
            i, _ = c
            return i < 4

        def body(c):
            i, h = c
            return i + 1, jnp.tanh(h @ p)

        _, out = jax.lax.while_loop(cond, body, (0, x))
        return jnp.mean(out ** 2)

    p = jax.random.normal(jax.random.key(0), (16, 16)) * 0.25
    x = jax.random.normal(jax.random.key(1), (8, 16))
    w = amp.auto_cast(f, compute_dtype=jnp.bfloat16)
    out = w(p, x)
    assert out.dtype == jnp.float32
    np.testing.assert_allclose(float(out), float(f(p, x)),
                               rtol=3e-2, atol=1e-3)
    # the rewrite itself: the GEMM inside the while BODY runs bf16
    # (value checks alone would also pass with the loop left opaque)
    jx = jax.make_jaxpr(w)(p, x)
    wh = [e for e in jx.jaxpr.eqns if e.primitive.name == "while"]
    assert wh, "expected a while eqn in the rewritten jaxpr"
    body_dots = [str(v.aval.dtype)
                 for e in wh[0].params["body_jaxpr"].jaxpr.eqns
                 if e.primitive.name == "dot_general" for v in e.invars]
    assert body_dots and set(body_dots) == {"bfloat16"}, body_dots


def test_cond_branches_rewritten_coherently():
    """cond branches are rewritten; asymmetric branches (GEMM vs
    pass-through) still agree on output dtype (cast back to traced)."""
    def f(p, x, t):
        return jnp.sum(jax.lax.cond(t, lambda v: v @ p,
                                    lambda v: v * 2.0, x))

    p = jax.random.normal(jax.random.key(0), (16, 16))
    x = jax.random.normal(jax.random.key(1), (16, 16))
    w = amp.auto_cast(f, compute_dtype=jnp.bfloat16)
    for t in (True, False):
        got, want = float(w(p, x, t)), float(f(p, x, t))
        np.testing.assert_allclose(got, want, rtol=3e-2, atol=1e-3)
    # the rewrite itself: the GEMM branch's dot runs bf16 in the
    # rewritten cond (value checks alone pass with cond left opaque)
    jx = jax.make_jaxpr(w)(p, x, True)
    cd = [e for e in jx.jaxpr.eqns if e.primitive.name == "cond"]
    assert cd, "expected a cond eqn in the rewritten jaxpr"
    br_dots = [str(v.aval.dtype)
               for br in cd[0].params["branches"]
               for e in br.jaxpr.eqns
               if e.primitive.name == "dot_general" for v in e.invars]
    assert br_dots and set(br_dots) == {"bfloat16"}, br_dots


def test_unmodified_flax_cnn_per_op_dtypes_across_levels():
    """VERDICT r1 #4 done criterion: ONE unmodified model under O0/O1/O2
    produces the expected per-op dtypes with no hand-edits."""
    import flax.linen as nn

    class CNN(nn.Module):
        @nn.compact
        def __call__(self, x):
            x = nn.Conv(8, (3, 3), dtype=None)(x)
            x = nn.LayerNorm()(x)            # rsqrt/mean live in FP32_PRIMS
            x = jax.nn.relu(x)
            x = x.reshape(x.shape[0], -1)
            x = nn.Dense(10)(x)
            return jax.nn.log_softmax(x)     # exp/log pinned f32

    model = CNN()
    x = jnp.ones((2, 8, 8, 3), jnp.float32)
    params = model.init(jax.random.key(0), x)
    f = lambda p, xx: model.apply(p, xx)

    # O0: identity — everything stays f32
    _, s0 = amp.initialize(params, opt_level="O0")
    assert s0.wrap_forward(f) is f

    # O1: conv + dot in bf16, exp (softmax) in f32 — unmodified model
    _, s1 = amp.initialize(params, opt_level="O1")
    w1 = s1.wrap_forward(f)
    assert set(_prim_in_dtypes(w1, "conv_general_dilated",
                               params, x)) == {"bfloat16"}
    assert set(_prim_in_dtypes(w1, "dot_general",
                               params, x)) == {"bfloat16"}
    assert set(_prim_in_dtypes(w1, "exp", params, x)) == {"float32"}
    # numerics stay close to f32
    np.testing.assert_allclose(np.asarray(w1(params, x)),
                               np.asarray(f(params, x)),
                               rtol=5e-2, atol=5e-2)

    # O2: the REAL model with its cast (bf16) params and boundary-cast
    # data inputs — whole-model half compute, reference O2 semantics
    params2, s2 = amp.initialize(params, opt_level="O2")
    w2 = s2.wrap_forward(f, cast_argnums=(1,))
    assert set(_prim_in_dtypes(w2, "conv_general_dilated",
                               params2, x)) == {"bfloat16"}
    assert set(_prim_in_dtypes(w2, "dot_general",
                               params2, x)) == {"bfloat16"}
    np.testing.assert_allclose(np.asarray(w2(params2, x)),
                               np.asarray(f(params, x)),
                               rtol=5e-2, atol=5e-2)


def test_scan_with_prng_key_and_int_carry():
    """Non-float scan state (PRNG keys, int counters) must pass
    through the O1 boundary casts untouched."""
    def f(p, x, key):
        def body(carry, w):
            h, k, n = carry
            k, sub = jax.random.split(k)
            h = jnp.tanh(h @ w + jax.random.normal(sub, h.shape) * 0.01)
            return (h, k, n + 1), n
        (h, k, n), ns = jax.lax.scan(body, (x, key, jnp.int32(0)), p)
        return jnp.mean(h ** 2) + 0.0 * jnp.sum(ns)

    p = jax.random.normal(jax.random.key(0), (3, 8, 8)) * 0.3
    x = jax.random.normal(jax.random.key(1), (4, 8))
    key = jax.random.key(2)
    w = amp.auto_cast(f, compute_dtype=jnp.bfloat16)
    np.testing.assert_allclose(float(w(p, x, key)), float(f(p, x, key)),
                               rtol=3e-2, atol=1e-3)
    g = jax.grad(w)(p, x, key)
    assert bool(jnp.all(jnp.isfinite(g)))


# ------------------------------------------------------------------
# Randomized-program property grid (VERDICT r3 #7).  Seeded random
# programs mix listed (GEMM / transcendental / reduction) and unlisted
# primitives with scan/while/cond control flow; for every program the
# rewriter must (a) agree numerically with the unrewritten f32 program
# within compounded-bf16 tolerance and (b) satisfy the dtype invariants
# — every HALF_PRIMS eqn sees bf16 floats, every FP32_PRIMS eqn sees
# f32 — checked by walking the rewritten jaxpr including all control-
# flow sub-jaxprs.  Seeds are fixed, so each case is deterministic.

from apex_tpu.amp import lists as amp_lists  # noqa: E402
from apex_tpu.amp import wrap as amp_wrap    # noqa: E402


def _walk_eqns(jaxpr):
    for eqn in jaxpr.eqns:
        yield eqn
        for sub in amp_wrap._iter_sub_jaxprs(eqn.params):
            yield from _walk_eqns(sub)


def _check_dtype_invariants(jaxpr):
    """Assert the O1 precision routing on every eqn reachable from the
    rewritten jaxpr; returns (#HALF eqns, #FP32 eqns) seen."""
    half_n = fp32_n = 0
    for eqn in _walk_eqns(jaxpr):
        nm = eqn.primitive.name
        fdts = {str(v.aval.dtype) for v in eqn.invars
                if hasattr(v.aval, "dtype")
                and jnp.issubdtype(v.aval.dtype, jnp.floating)}
        if nm in amp_lists.HALF_PRIMS:
            assert fdts <= {"bfloat16"}, (nm, fdts)
            half_n += 1
        elif nm in amp_lists.FP32_PRIMS:
            assert fdts <= {"float32"}, (nm, fdts)
            fp32_n += 1
    return half_n, fp32_n


def _random_program(rng, dim, depth=0):
    """Seeded random f: (B, dim) f32 -> (B, dim) f32.  Every op in the
    pool preserves shape and keeps magnitudes O(1) so bf16 round-off
    stays bounded under composition.  Control-flow ops nest recursively
    (depth-capped) with independently generated bodies.  The returned
    fn carries ``has_while`` (reverse-mode AD cannot cross
    lax.while_loop, in the rewritten and unrewritten program alike)."""
    kinds = ["matmul", "exp", "log", "rsqrt", "center", "cumsum",
             "relu", "affine", "tanh", "scan", "while", "cond"]
    probs = [0.20, 0.09, 0.07, 0.08, 0.08, 0.05,
             0.07, 0.10, 0.06, 0.07, 0.06, 0.07]
    has_while = False

    def make_op(kind):
        nonlocal has_while
        if kind == "matmul":
            w = jnp.asarray(rng.normal(size=(dim, dim)) / np.sqrt(dim),
                            jnp.float32)
            return lambda x, w=w: x @ w
        if kind == "exp":
            return lambda x: jnp.exp(0.2 * x) - 1.0
        if kind == "log":
            return lambda x: jnp.log1p(jnp.abs(x))
        if kind == "rsqrt":
            return lambda x: x * jax.lax.rsqrt(
                jnp.mean(x * x, axis=-1, keepdims=True) + 1.0)
        if kind == "center":
            return lambda x: x - jnp.mean(x, axis=-1, keepdims=True)
        if kind == "cumsum":
            return lambda x: jnp.cumsum(x, axis=-1) * (1.0 / dim)
        if kind == "relu":
            return lambda x: jnp.maximum(x, 0.0) - 0.3
        if kind == "affine":
            b = jnp.asarray(rng.normal(size=(dim,)) * 0.1, jnp.float32)
            return lambda x, b=b: 0.9 * x + b
        if kind == "tanh":
            return jnp.tanh
        if kind == "scan" and depth < 2:
            body = _random_program(rng, dim, depth + 1)
            has_while = has_while or body.has_while

            def op(x, body=body):
                c, _ = jax.lax.scan(lambda c, _: (body(c), None),
                                    x, None, length=2)
                return c
            return op
        if kind == "while" and depth < 2:
            body = _random_program(rng, dim, depth + 1)
            has_while = True

            def op(x, body=body):
                def w_body(state):
                    i, v = state
                    return i + 1, body(v)
                return jax.lax.while_loop(
                    lambda s: s[0] < 2, w_body, (jnp.int32(0), x))[1]
            return op
        if kind == "cond" and depth < 2:
            tb = _random_program(rng, dim, depth + 1)
            fb = _random_program(rng, dim, depth + 1)
            has_while = has_while or tb.has_while or fb.has_while
            # static per-seed predicate: a data-dependent pred near its
            # threshold could take DIFFERENT branches in the rewritten
            # vs reference program under bf16 drift, failing the
            # comparison for reasons unrelated to the rewriter.  Both
            # branches are still traced and rewritten (the dtype
            # invariants see them); traced-pred coherence is pinned by
            # test_cond_branches_rewritten_coherently.
            pred = jnp.asarray(bool(rng.random() < 0.5))

            def op(x, tb=tb, fb=fb, pred=pred):
                return jax.lax.cond(pred, tb, fb, x)
            return op
        return lambda x: x * 0.9  # depth-capped control flow

    ops = [make_op(str(k))
           for k in rng.choice(kinds, size=int(rng.integers(2, 6)),
                               p=probs)]
    if depth == 0:
        # guarantee every top-level program exercises both lists
        ops.insert(int(rng.integers(0, len(ops) + 1)), make_op("matmul"))
        ops.insert(int(rng.integers(0, len(ops) + 2)), make_op("center"))

    def f(x):
        for op in ops:
            x = op(x)
        return x
    f.has_while = has_while
    return f


def _run_fuzz_case(seed):
    rng = np.random.default_rng(seed)
    B, D = 4, 16
    f = _random_program(rng, D)
    x = jnp.asarray(rng.normal(size=(B, D)), jnp.float32)
    w = amp.auto_cast(f, compute_dtype=jnp.bfloat16)

    # (a) numerical agreement with the unrewritten f32 program
    ref = np.asarray(f(x).astype(jnp.float32))
    out = np.asarray(w(x).astype(jnp.float32))
    assert np.isfinite(ref).all() and np.isfinite(out).all()
    np.testing.assert_allclose(out, ref, rtol=0.1, atol=0.05)

    # (b) dtype invariants over the whole rewritten jaxpr
    jx = jax.make_jaxpr(w)(x)
    half_n, fp32_n = _check_dtype_invariants(jx.jaxpr)
    assert half_n >= 1 and fp32_n >= 1, (half_n, fp32_n)

    # (c) the rewrite composes with grad and stays close to the f32
    # gradient (while_loop is not reverse-differentiable in any mode)
    if not f.has_while:
        g32 = np.asarray(jax.grad(lambda t: jnp.sum(f(t)))(x))
        gmx = np.asarray(jax.grad(
            lambda t: jnp.sum(w(t).astype(jnp.float32)))(x))
        assert np.isfinite(gmx).all()
        rel = (np.linalg.norm(gmx - g32)
               / (np.linalg.norm(g32) + 1e-6))
        assert rel < 0.15, rel


@pytest.mark.parametrize("seed", range(8))
def test_fuzz_rewriter_random_programs(seed):
    _run_fuzz_case(seed)


@pytest.mark.slow
@pytest.mark.parametrize("seed", range(8, 48))
def test_fuzz_rewriter_random_programs_full(seed):
    _run_fuzz_case(seed)
