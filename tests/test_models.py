"""Model zoo smoke + correctness tests (BASELINE configs end-to-end)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu import comm
from apex_tpu.models import GPTModel, resnet18
from apex_tpu.models.bert import BertModel


def _megatron_spec_for(path, leaf):
    """Sharding specs by Megatron param-name convention (shared by the
    GPT/BERT tp-parity tests)."""
    name = "/".join(str(p.key) for p in path if hasattr(p, "key"))
    if "/embed/" in f"/{name}/":
        return P(comm.AXIS_MODEL, None)
    if "qkv" in name or "fc1" in name:
        return (P(None, comm.AXIS_MODEL) if leaf.ndim == 2
                else P(comm.AXIS_MODEL))
    if "proj/weight" in name or "fc2/weight" in name:
        return P(comm.AXIS_MODEL, None)
    return P()


def _assert_grads_match(g_tp, g_ref, tag):
    for (pa, a), (_, b) in zip(
            jax.tree_util.tree_flatten_with_path(g_tp)[0],
            jax.tree_util.tree_flatten_with_path(g_ref)[0]):
        name = "/".join(str(p.key) for p in pa if hasattr(p, "key"))
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-3, atol=1e-5,
            err_msg=f"grad mismatch at {name} ({tag})")



def test_resnet18_forward_and_train_step():
    model = resnet18(num_classes=10)
    x = jax.random.normal(jax.random.key(0), (2, 32, 32, 3))
    variables = model.init(jax.random.key(1), x, train=False)
    logits = model.apply(variables, x, train=False)
    assert logits.shape == (2, 10)
    assert logits.dtype == jnp.float32

    def loss_fn(params):
        out, _ = model.apply(
            {"params": params,
             "batch_stats": variables["batch_stats"]},
            x, train=True, mutable=["batch_stats"])
        return jnp.mean(out ** 2)

    g = jax.grad(loss_fn)(variables["params"])
    total = sum(float(jnp.sum(l)) for l in jax.tree_util.tree_leaves(g))
    assert np.isfinite(total)


def test_gpt_single_device_loss_decreases():
    model = GPTModel(vocab_size=64, hidden_size=32, num_heads=4,
                     num_layers=2, max_seq_len=16)
    tokens = jax.random.randint(jax.random.key(0), (4, 16), 0, 64)
    labels = jnp.roll(tokens, -1, axis=1)
    variables = model.init(jax.random.key(1), tokens)

    def loss_fn(v):
        return model.loss(v, tokens, labels)

    l0 = float(loss_fn(variables))
    assert np.isfinite(l0)
    # a couple of SGD steps reduce loss
    v = variables
    for _ in range(10):
        g = jax.grad(loss_fn)(v)
        v = jax.tree_util.tree_map(lambda p, gg: p - 0.5 * gg, v, g)
    l1 = float(loss_fn(v))
    assert l1 < l0, (l0, l1)


@pytest.mark.parametrize("sequence_parallel", [False, True])
def test_gpt_tp_matches_tp1(sequence_parallel):
    """GPT under tp=4 (+SP) == the same GPT with identical weights
    replicated — the Megatron equivalence the reference's transformer
    tests assert."""
    V, H, NH, L, S, B = 64, 32, 4, 2, 16, 2
    tokens = jax.random.randint(jax.random.key(0), (B, S), 0, V)
    labels = jnp.roll(tokens, -1, axis=1)

    def spec_for(path, leaf):
        name = "/".join(str(p.key) for p in path
                        if hasattr(p, "key"))
        if "/embed/" in f"/{name}/":
            return P(comm.AXIS_MODEL, None)
        if "qkv" in name or "fc1" in name:
            return (P(None, comm.AXIS_MODEL) if leaf.ndim == 2
                    else P(comm.AXIS_MODEL))
        if "proj/weight" in name or "fc2/weight" in name:
            return P(comm.AXIS_MODEL, None)
        return P()

    # tree STRUCTURE from a tp=1 trace (no collectives outside shard_map)
    comm.initialize(data=8)
    model1_probe = GPTModel(vocab_size=V, hidden_size=H, num_heads=NH,
                            num_layers=L, max_seq_len=S)
    shape = jax.eval_shape(model1_probe.init, jax.random.key(1), tokens)
    specs = jax.tree_util.tree_map_with_path(spec_for, shape)
    comm.destroy()

    mesh = comm.initialize(data=2, model=4)
    model = GPTModel(vocab_size=V, hidden_size=H, num_heads=NH,
                     num_layers=L, max_seq_len=S,
                     sequence_parallel=sequence_parallel)

    def init_fn(key, tok):
        return model.init(key, tok)

    variables = jax.jit(comm.shard_map(
        init_fn, mesh, in_specs=(P(), P()), out_specs=specs))(
        jax.random.key(1), tokens)

    loss_tp = jax.jit(comm.shard_map(
        lambda v, t, l: model.loss(v, t, l), mesh,
        in_specs=(specs, P(), P()), out_specs=P()))(
        variables, tokens, labels)

    # oracle: same weights, tp=1
    comm.destroy()
    comm.initialize(data=8)  # model axis size 1
    model1 = GPTModel(vocab_size=V, hidden_size=H, num_heads=NH,
                      num_layers=L, max_seq_len=S)
    loss_ref = model1.loss(variables, tokens, labels)
    np.testing.assert_allclose(float(loss_tp), float(loss_ref),
                               rtol=2e-4)


@pytest.mark.parametrize("sequence_parallel", [False, True])
def test_bert_tp_matches_tp1(sequence_parallel):
    """BERT under tp=4 (+SP scatter/gather) == same weights at tp=1."""
    V, H, NH, L, S, B = 64, 32, 4, 2, 16, 2
    tokens = jax.random.randint(jax.random.key(10), (B, S), 0, V)

    def spec_for(path, leaf):
        name = "/".join(str(p.key) for p in path if hasattr(p, "key"))
        if "/embed/" in f"/{name}/":
            return P(comm.AXIS_MODEL, None)
        if "qkv" in name or "fc1" in name:
            return (P(None, comm.AXIS_MODEL) if leaf.ndim == 2
                    else P(comm.AXIS_MODEL))
        if "proj/weight" in name or "fc2/weight" in name:
            return P(comm.AXIS_MODEL, None)
        return P()

    comm.initialize(data=8)
    probe = BertModel(vocab_size=V, hidden_size=H, num_heads=NH,
                      num_layers=L, max_seq_len=S)
    shape = jax.eval_shape(probe.init, jax.random.key(11), tokens)
    specs = jax.tree_util.tree_map_with_path(spec_for, shape)
    comm.destroy()

    mesh = comm.initialize(data=2, model=4)
    model = BertModel(vocab_size=V, hidden_size=H, num_heads=NH,
                      num_layers=L, max_seq_len=S,
                      sequence_parallel=sequence_parallel)
    variables = jax.jit(comm.shard_map(
        lambda k, t: model.init(k, t), mesh,
        in_specs=(P(), P()), out_specs=specs))(jax.random.key(11), tokens)
    out_tp = jax.jit(comm.shard_map(
        lambda v, t: model.apply(v, t), mesh,
        in_specs=(specs, P()), out_specs=P()))(variables, tokens)

    comm.destroy()
    comm.initialize(data=8)
    out_ref = probe.apply(variables, tokens)
    np.testing.assert_allclose(np.asarray(out_tp), np.asarray(out_ref),
                               rtol=2e-4, atol=2e-4)


def test_bert_forward_shapes_and_mask():
    model = BertModel(vocab_size=64, hidden_size=32, num_heads=4,
                      num_layers=2, max_seq_len=16)
    tokens = jax.random.randint(jax.random.key(0), (2, 12), 0, 64)
    amask = jnp.ones((2, 12)).at[:, 8:].set(0)
    variables = model.init(jax.random.key(1), tokens,
                           attention_mask=amask)
    y = model.apply(variables, tokens, attention_mask=amask)
    assert y.shape == (12, 2, 32)
    logits = model.mlm_logits(variables, tokens, attention_mask=amask)
    assert logits.shape == (12, 2, 64)


@pytest.mark.parametrize("strategy", ["ring", "ulysses"])
def test_gpt_layer_context_parallel_matches_full(strategy):
    """A GPT layer with its sequence sharded over the ctx axis (either
    cp strategy, RoPE with global position offsets) == the same layer
    on the full sequence."""
    from apex_tpu.models.gpt import GPTLayer
    H, NH, S, B = 32, 4, 32, 2
    x = jax.random.normal(jax.random.key(0), (S, B, H))

    comm.initialize(data=8)    # ctx axis size 1: plain full-seq oracle
    full = GPTLayer(H, NH, use_rope=True)
    params = full.init(jax.random.key(1), x)
    y_ref = full.apply(params, x)
    comm.destroy()

    mesh = comm.initialize(ctx=4)
    cp_layer = GPTLayer(H, NH, use_rope=True, context_parallel=True,
                        cp_strategy=strategy)
    y_cp = jax.jit(comm.shard_map(
        lambda p, xx: cp_layer.apply(p, xx), mesh,
        in_specs=(P(), P(comm.AXIS_CTX, None, None)),
        out_specs=P(comm.AXIS_CTX, None, None)))(params, x)
    np.testing.assert_allclose(np.asarray(y_cp), np.asarray(y_ref),
                               rtol=2e-4, atol=2e-4)


def test_gpt_layer_rejects_unknown_cp_strategy():
    # the raise happens at trace time, before any collective — no mesh
    # or shard_map needed
    from apex_tpu.models.gpt import GPTLayer
    layer = GPTLayer(32, 4, context_parallel=True, cp_strategy="nope")
    with pytest.raises(ValueError, match="ring.*ulysses|ulysses.*ring"):
        layer.init(jax.random.key(0), jnp.zeros((8, 2, 32)))


@pytest.mark.parametrize("sequence_parallel", [False, True])
def test_gpt_tp_GRADS_match_tp1(sequence_parallel):
    """Every parameter's GRADIENT under tp=4 (+SP) equals the tp=1
    oracle — not just the loss.  Pins the Megatron grad-sync layout:
    SP layernorm/bias param grads psum'd over the model axis (via the
    f/g copy mapping at use), and exactly ONE f-mapping syncing the
    vocab-sharded head's d/dx (the SP exit gather, or copy_to without
    SP).  A loss-only check passes even with all of that missing."""
    V, H, NH, L, S, B = 64, 32, 4, 2, 16, 2
    tokens = jax.random.randint(jax.random.key(0), (B, S), 0, V)
    labels = jnp.roll(tokens, -1, axis=1)

    comm.initialize(data=8)
    probe = GPTModel(vocab_size=V, hidden_size=H, num_heads=NH,
                     num_layers=L, max_seq_len=S)
    shape = jax.eval_shape(probe.init, jax.random.key(1), tokens)
    specs = jax.tree_util.tree_map_with_path(_megatron_spec_for, shape)
    comm.destroy()

    mesh = comm.initialize(data=2, model=4)
    model = GPTModel(vocab_size=V, hidden_size=H, num_heads=NH,
                     num_layers=L, max_seq_len=S,
                     sequence_parallel=sequence_parallel)
    variables = jax.jit(comm.shard_map(
        lambda k, t: model.init(k, t), mesh, in_specs=(P(), P()),
        out_specs=specs))(jax.random.key(1), tokens)
    g_tp = jax.jit(comm.shard_map(
        jax.grad(lambda v, t, l: model.loss(v, t, l)), mesh,
        in_specs=(specs, P(), P()), out_specs=specs))(
        variables, tokens, labels)

    comm.destroy()
    comm.initialize(data=8)
    model1 = GPTModel(vocab_size=V, hidden_size=H, num_heads=NH,
                      num_layers=L, max_seq_len=S)
    g_ref = jax.grad(lambda v, t, l: model1.loss(v, t, l))(
        variables, tokens, labels)

    _assert_grads_match(g_tp, g_ref, f"gpt sp={sequence_parallel}")


@pytest.mark.parametrize("sequence_parallel", [False, True])
def test_bert_tp_GRADS_match_tp1(sequence_parallel):
    """BERT analog of the GPT grad-parity test: every param grad under
    tp=4 (+SP) equals the tp=1 oracle through the MLM head + vocab-
    parallel CE."""
    from apex_tpu.transformer import tensor_parallel as tp_

    V, H, NH, L, S, B = 64, 32, 4, 2, 16, 2
    tokens = jax.random.randint(jax.random.key(10), (B, S), 0, V)
    labels = jax.random.randint(jax.random.key(12), (B, S), 0, V)

    comm.initialize(data=8)
    probe = BertModel(vocab_size=V, hidden_size=H, num_heads=NH,
                      num_layers=L, max_seq_len=S)
    shape = jax.eval_shape(probe.init, jax.random.key(11), tokens)
    specs = jax.tree_util.tree_map_with_path(_megatron_spec_for, shape)
    comm.destroy()

    def mlm_loss(m, v, t, l):
        logits = m.mlm_logits(v, t)                 # (s, b, V/tp)
        return jnp.mean(tp_.vocab_parallel_cross_entropy(
            logits, jnp.transpose(l, (1, 0))))

    mesh = comm.initialize(data=2, model=4)
    model = BertModel(vocab_size=V, hidden_size=H, num_heads=NH,
                      num_layers=L, max_seq_len=S,
                      sequence_parallel=sequence_parallel)
    variables = jax.jit(comm.shard_map(
        lambda k, t: model.init(k, t), mesh,
        in_specs=(P(), P()), out_specs=specs))(jax.random.key(11),
                                               tokens)
    g_tp = jax.jit(comm.shard_map(
        jax.grad(lambda v, t, l: mlm_loss(model, v, t, l)), mesh,
        in_specs=(specs, P(), P()), out_specs=specs))(
        variables, tokens, labels)

    comm.destroy()
    comm.initialize(data=8)
    g_ref = jax.grad(lambda v, t, l: mlm_loss(probe, v, t, l))(
        variables, tokens, labels)

    _assert_grads_match(g_tp, g_ref, f"bert sp={sequence_parallel}")
