"""Model zoo smoke + correctness tests (BASELINE configs end-to-end)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu import comm
from apex_tpu.models import GPTModel, resnet18
from apex_tpu.models.bert import BertModel


def _megatron_spec_for(path, leaf):
    """Sharding specs by Megatron param-name convention (shared by the
    GPT/BERT tp-parity tests)."""
    name = "/".join(str(p.key) for p in path if hasattr(p, "key"))
    if "/embed/" in f"/{name}/":
        return P(comm.AXIS_MODEL, None)
    if "qkv" in name or "fc1" in name:
        return (P(None, comm.AXIS_MODEL) if leaf.ndim == 2
                else P(comm.AXIS_MODEL))
    if "proj/weight" in name or "fc2/weight" in name:
        return P(comm.AXIS_MODEL, None)
    return P()


def _assert_grads_match(g_tp, g_ref, tag):
    for (pa, a), (_, b) in zip(
            jax.tree_util.tree_flatten_with_path(g_tp)[0],
            jax.tree_util.tree_flatten_with_path(g_ref)[0]):
        name = "/".join(str(p.key) for p in pa if hasattr(p, "key"))
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-3, atol=1e-5,
            err_msg=f"grad mismatch at {name} ({tag})")



def test_resnet18_forward_and_train_step():
    model = resnet18(num_classes=10)
    x = jax.random.normal(jax.random.key(0), (2, 32, 32, 3))
    variables = model.init(jax.random.key(1), x, train=False)
    logits = model.apply(variables, x, train=False)
    assert logits.shape == (2, 10)
    assert logits.dtype == jnp.float32

    def loss_fn(params):
        out, _ = model.apply(
            {"params": params,
             "batch_stats": variables["batch_stats"]},
            x, train=True, mutable=["batch_stats"])
        return jnp.mean(out ** 2)

    g = jax.grad(loss_fn)(variables["params"])
    total = sum(float(jnp.sum(l)) for l in jax.tree_util.tree_leaves(g))
    assert np.isfinite(total)


def test_resnet_space_to_depth_stem_equals_7x7():
    """The MXU-efficient stem is the SAME function as the 7x7/s2 conv:
    fold_stem_kernel + space_to_depth must reproduce it to numerical
    equality (the transform is exact in exact arithmetic), and the
    opt-in model must train."""
    from apex_tpu.models.resnet import fold_stem_kernel, space_to_depth

    x = jax.random.normal(jax.random.key(0), (2, 32, 32, 3))
    w7 = jax.random.normal(jax.random.key(1), (7, 7, 3, 16)) * 0.1
    ref = jax.lax.conv_general_dilated(
        x, w7, (2, 2), [(3, 3), (3, 3)],
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    got = jax.lax.conv_general_dilated(
        space_to_depth(x, 2), fold_stem_kernel(w7), (1, 1),
        [(2, 1), (2, 1)], dimension_numbers=("NHWC", "HWIO", "NHWC"))
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)

    model = resnet18(num_classes=10, stem_space_to_depth=True)
    variables = model.init(jax.random.key(2), x, train=False)
    assert "stem_conv" in variables["params"]
    logits = model.apply(variables, x, train=False)
    assert logits.shape == (2, 10)

    def loss_fn(params):
        out, _ = model.apply(
            {"params": params,
             "batch_stats": variables["batch_stats"]},
            x, train=True, mutable=["batch_stats"])
        return jnp.mean(out ** 2)

    g = jax.grad(loss_fn)(variables["params"])
    assert np.isfinite(sum(float(jnp.sum(l))
                           for l in jax.tree_util.tree_leaves(g)))


@pytest.mark.parametrize("use_rope", [False, True])
def test_gpt_packed_batch_matches_per_sequence(use_rope):
    """Packed-batch GPT (segment-masked attention + within-sequence
    positions, apex_tpu.data.pack_sequences form) must produce, for
    every packed sequence, exactly the logits of running that sequence
    alone — the packed-pretraining contract."""
    from apex_tpu.data import pack_sequences

    model = GPTModel(vocab_size=64, hidden_size=32, num_heads=4,
                     num_layers=2, max_seq_len=64, use_rope=use_rope)
    rng = np.random.default_rng(3)
    seqs = [rng.integers(1, 64, size=n) for n in (17, 9, 23, 5)]
    packed = pack_sequences(seqs, max_len=32, pad_id=0)
    tokens = jnp.asarray(packed["tokens"])
    variables = model.init(jax.random.key(0), tokens)

    logits = model.apply(
        variables, tokens,
        segment_ids=jnp.asarray(packed["segment_ids"]),
        positions=jnp.asarray(packed["positions"]))     # (s, b, V)

    for r in range(tokens.shape[0]):
        segs = packed["segment_ids"][r]
        for seg in range(1, int(segs.max()) + 1):
            idx = np.flatnonzero(segs == seg)
            alone = model.apply(
                variables, tokens[r:r + 1, idx])        # (n, 1, V)
            np.testing.assert_allclose(
                np.asarray(logits[idx, r, :], np.float32),
                np.asarray(alone[:, 0, :], np.float32),
                rtol=2e-4, atol=2e-4)

    # one-sided packing is a silent-corruption trap: rejected loudly
    with pytest.raises(ValueError, match="BOTH segment_ids"):
        model.apply(variables, tokens,
                    segment_ids=jnp.asarray(packed["segment_ids"]))
    # packed loss masks padding and forwards the packing args
    labels = jnp.asarray(np.roll(packed["tokens"], -1, axis=1))
    loss_val = model.loss(variables, tokens, labels,
                          segment_ids=jnp.asarray(
                              packed["segment_ids"]),
                          positions=jnp.asarray(packed["positions"]))
    assert np.isfinite(float(loss_val))

    # the keep-mask excludes padding AND each segment's final
    # position: with the shift-by-one labels above, a boundary
    # position's target is the NEXT segment's first token.  Pin the
    # mask two ways: (a) the loss equals the manually masked mean of
    # raw per-token CE; (b) poisoning every excluded label leaves the
    # loss bit-identical.
    seg = packed["segment_ids"].T                        # (s, b)
    nxt = np.concatenate([seg[1:], np.zeros_like(seg[:1])])
    keep = (seg > 0) & (nxt == seg)
    logp = np.asarray(jax.nn.log_softmax(
        np.asarray(logits, np.float32), axis=-1))
    per_tok = -np.take_along_axis(
        logp, np.asarray(labels).T[..., None], axis=-1)[..., 0]
    np.testing.assert_allclose(
        float(loss_val), (per_tok * keep).sum() / keep.sum(),
        rtol=1e-3)
    poisoned = np.asarray(labels).copy()
    poisoned[~keep.T] = 1
    loss_poison = model.loss(variables, tokens, jnp.asarray(poisoned),
                             segment_ids=jnp.asarray(
                                 packed["segment_ids"]),
                             positions=jnp.asarray(packed["positions"]))
    assert float(loss_val) == float(loss_poison)


def test_gpt_packed_rejects_overlong_rows():
    """Learned-position models: the position gather would silently
    CLAMP out-of-range indices; the packed path must fail loudly when
    rows exceed max_seq_len."""
    model = GPTModel(vocab_size=64, hidden_size=32, num_heads=4,
                     num_layers=1, max_seq_len=16)
    tokens = jnp.ones((1, 32), jnp.int32)
    variables = model.init(jax.random.key(0), jnp.ones((1, 8),
                                                       jnp.int32))
    with pytest.raises(ValueError, match="max_seq_len"):
        model.apply(variables, tokens,
                    segment_ids=jnp.ones((1, 32), jnp.int32),
                    positions=jnp.zeros((1, 32), jnp.int32))


def test_gpt_single_device_loss_decreases():
    model = GPTModel(vocab_size=64, hidden_size=32, num_heads=4,
                     num_layers=2, max_seq_len=16)
    tokens = jax.random.randint(jax.random.key(0), (4, 16), 0, 64)
    labels = jnp.roll(tokens, -1, axis=1)
    variables = model.init(jax.random.key(1), tokens)

    def loss_fn(v):
        return model.loss(v, tokens, labels)

    l0 = float(loss_fn(variables))
    assert np.isfinite(l0)
    # a couple of SGD steps reduce loss
    v = variables
    for _ in range(10):
        g = jax.grad(loss_fn)(v)
        v = jax.tree_util.tree_map(lambda p, gg: p - 0.5 * gg, v, g)
    l1 = float(loss_fn(v))
    assert l1 < l0, (l0, l1)


@pytest.mark.parametrize("sequence_parallel", [False, True])
def test_gpt_tp_matches_tp1(sequence_parallel):
    """GPT under tp=4 (+SP) == the same GPT with identical weights
    replicated — the Megatron equivalence the reference's transformer
    tests assert."""
    V, H, NH, L, S, B = 64, 32, 4, 2, 16, 2
    tokens = jax.random.randint(jax.random.key(0), (B, S), 0, V)
    labels = jnp.roll(tokens, -1, axis=1)

    def spec_for(path, leaf):
        name = "/".join(str(p.key) for p in path
                        if hasattr(p, "key"))
        if "/embed/" in f"/{name}/":
            return P(comm.AXIS_MODEL, None)
        if "qkv" in name or "fc1" in name:
            return (P(None, comm.AXIS_MODEL) if leaf.ndim == 2
                    else P(comm.AXIS_MODEL))
        if "proj/weight" in name or "fc2/weight" in name:
            return P(comm.AXIS_MODEL, None)
        return P()

    # tree STRUCTURE from a tp=1 trace (no collectives outside shard_map)
    comm.initialize(data=8)
    model1_probe = GPTModel(vocab_size=V, hidden_size=H, num_heads=NH,
                            num_layers=L, max_seq_len=S)
    shape = jax.eval_shape(model1_probe.init, jax.random.key(1), tokens)
    specs = jax.tree_util.tree_map_with_path(spec_for, shape)
    comm.destroy()

    mesh = comm.initialize(data=2, model=4)
    model = GPTModel(vocab_size=V, hidden_size=H, num_heads=NH,
                     num_layers=L, max_seq_len=S,
                     sequence_parallel=sequence_parallel)

    def init_fn(key, tok):
        return model.init(key, tok)

    variables = jax.jit(comm.shard_map(
        init_fn, mesh, in_specs=(P(), P()), out_specs=specs))(
        jax.random.key(1), tokens)

    loss_tp = jax.jit(comm.shard_map(
        lambda v, t, l: model.loss(v, t, l), mesh,
        in_specs=(specs, P(), P()), out_specs=P()))(
        variables, tokens, labels)

    # oracle: same weights, tp=1
    comm.destroy()
    comm.initialize(data=8)  # model axis size 1
    model1 = GPTModel(vocab_size=V, hidden_size=H, num_heads=NH,
                      num_layers=L, max_seq_len=S)
    loss_ref = model1.loss(variables, tokens, labels)
    np.testing.assert_allclose(float(loss_tp), float(loss_ref),
                               rtol=2e-4)


@pytest.mark.parametrize("sequence_parallel", [False, True])
def test_gpt_packed_tp_matches_tp1(sequence_parallel):
    """Packed batches under tp=4 (+SP) == the tp=1 packed run with the
    same weights: the segment mask and per-sequence positions must
    survive the Megatron sharding (attention sees the gathered full
    sequence under SP, so the full-length (b, s) packing arrays apply
    unchanged)."""
    from apex_tpu.data import pack_sequences

    V, H, NH, L, S = 64, 32, 4, 2, 16
    rng = np.random.default_rng(9)
    packed = pack_sequences(
        [rng.integers(1, V, size=n) for n in (9, 6, 11, 4)],
        max_len=S)
    tokens = jnp.asarray(packed["tokens"])
    segs = jnp.asarray(packed["segment_ids"])
    pos = jnp.asarray(packed["positions"])
    labels = jnp.asarray(
        np.where(packed["segment_ids"] > 0,
                 np.roll(packed["tokens"], -1, axis=1), 0))

    comm.initialize(data=8)
    probe = GPTModel(vocab_size=V, hidden_size=H, num_heads=NH,
                     num_layers=L, max_seq_len=S)
    shape = jax.eval_shape(probe.init, jax.random.key(1), tokens)
    specs = jax.tree_util.tree_map_with_path(_megatron_spec_for, shape)
    comm.destroy()

    mesh = comm.initialize(data=2, model=4)
    model = GPTModel(vocab_size=V, hidden_size=H, num_heads=NH,
                     num_layers=L, max_seq_len=S,
                     sequence_parallel=sequence_parallel)
    variables = jax.jit(comm.shard_map(
        lambda key, tok: model.init(key, tok), mesh,
        in_specs=(P(), P()), out_specs=specs))(
        jax.random.key(1), tokens)
    loss_tp = jax.jit(comm.shard_map(
        lambda v, t, l, s_, p_: model.loss(v, t, l, segment_ids=s_,
                                           positions=p_),
        mesh, in_specs=(specs, P(), P(), P(), P()), out_specs=P()))(
        variables, tokens, labels, segs, pos)

    comm.destroy()
    comm.initialize(data=8)
    model1 = GPTModel(vocab_size=V, hidden_size=H, num_heads=NH,
                      num_layers=L, max_seq_len=S)
    loss_ref = model1.loss(variables, tokens, labels,
                           segment_ids=segs, positions=pos)
    np.testing.assert_allclose(float(loss_tp), float(loss_ref),
                               rtol=2e-4)


@pytest.mark.parametrize("sequence_parallel", [False, True])
def test_bert_tp_matches_tp1(sequence_parallel):
    """BERT under tp=4 (+SP scatter/gather) == same weights at tp=1."""
    V, H, NH, L, S, B = 64, 32, 4, 2, 16, 2
    tokens = jax.random.randint(jax.random.key(10), (B, S), 0, V)

    def spec_for(path, leaf):
        name = "/".join(str(p.key) for p in path if hasattr(p, "key"))
        if "/embed/" in f"/{name}/":
            return P(comm.AXIS_MODEL, None)
        if "qkv" in name or "fc1" in name:
            return (P(None, comm.AXIS_MODEL) if leaf.ndim == 2
                    else P(comm.AXIS_MODEL))
        if "proj/weight" in name or "fc2/weight" in name:
            return P(comm.AXIS_MODEL, None)
        return P()

    comm.initialize(data=8)
    probe = BertModel(vocab_size=V, hidden_size=H, num_heads=NH,
                      num_layers=L, max_seq_len=S)
    shape = jax.eval_shape(probe.init, jax.random.key(11), tokens)
    specs = jax.tree_util.tree_map_with_path(spec_for, shape)
    comm.destroy()

    mesh = comm.initialize(data=2, model=4)
    model = BertModel(vocab_size=V, hidden_size=H, num_heads=NH,
                      num_layers=L, max_seq_len=S,
                      sequence_parallel=sequence_parallel)
    variables = jax.jit(comm.shard_map(
        lambda k, t: model.init(k, t), mesh,
        in_specs=(P(), P()), out_specs=specs))(jax.random.key(11), tokens)
    out_tp = jax.jit(comm.shard_map(
        lambda v, t: model.apply(v, t), mesh,
        in_specs=(specs, P()), out_specs=P()))(variables, tokens)

    comm.destroy()
    comm.initialize(data=8)
    out_ref = probe.apply(variables, tokens)
    np.testing.assert_allclose(np.asarray(out_tp), np.asarray(out_ref),
                               rtol=2e-4, atol=2e-4)


def test_bert_forward_shapes_and_mask():
    model = BertModel(vocab_size=64, hidden_size=32, num_heads=4,
                      num_layers=2, max_seq_len=16)
    tokens = jax.random.randint(jax.random.key(0), (2, 12), 0, 64)
    amask = jnp.ones((2, 12)).at[:, 8:].set(0)
    variables = model.init(jax.random.key(1), tokens,
                           attention_mask=amask)
    y = model.apply(variables, tokens, attention_mask=amask)
    assert y.shape == (12, 2, 32)
    logits = model.mlm_logits(variables, tokens, attention_mask=amask)
    assert logits.shape == (12, 2, 64)


@pytest.mark.parametrize("strategy", ["ring", "ulysses"])
def test_gpt_layer_context_parallel_matches_full(strategy):
    """A GPT layer with its sequence sharded over the ctx axis (either
    cp strategy, RoPE with global position offsets) == the same layer
    on the full sequence."""
    from apex_tpu.models.gpt import GPTLayer
    H, NH, S, B = 32, 4, 32, 2
    x = jax.random.normal(jax.random.key(0), (S, B, H))

    comm.initialize(data=8)    # ctx axis size 1: plain full-seq oracle
    full = GPTLayer(H, NH, use_rope=True)
    params = full.init(jax.random.key(1), x)
    y_ref = full.apply(params, x)
    comm.destroy()

    mesh = comm.initialize(ctx=4)
    cp_layer = GPTLayer(H, NH, use_rope=True, context_parallel=True,
                        cp_strategy=strategy)
    y_cp = jax.jit(comm.shard_map(
        lambda p, xx: cp_layer.apply(p, xx), mesh,
        in_specs=(P(), P(comm.AXIS_CTX, None, None)),
        out_specs=P(comm.AXIS_CTX, None, None)))(params, x)
    np.testing.assert_allclose(np.asarray(y_cp), np.asarray(y_ref),
                               rtol=2e-4, atol=2e-4)


def test_gpt_layer_rejects_unknown_cp_strategy():
    # the raise happens at trace time, before any collective — no mesh
    # or shard_map needed
    from apex_tpu.models.gpt import GPTLayer
    layer = GPTLayer(32, 4, context_parallel=True, cp_strategy="nope")
    with pytest.raises(ValueError, match="ring.*ulysses|ulysses.*ring"):
        layer.init(jax.random.key(0), jnp.zeros((8, 2, 32)))


@pytest.mark.parametrize("sequence_parallel", [False, True])
def test_gpt_tp_GRADS_match_tp1(sequence_parallel):
    """Every parameter's GRADIENT under tp=4 (+SP) equals the tp=1
    oracle — not just the loss.  Pins the Megatron grad-sync layout:
    SP layernorm/bias param grads psum'd over the model axis (via the
    f/g copy mapping at use), and exactly ONE f-mapping syncing the
    vocab-sharded head's d/dx (the SP exit gather, or copy_to without
    SP).  A loss-only check passes even with all of that missing."""
    V, H, NH, L, S, B = 64, 32, 4, 2, 16, 2
    tokens = jax.random.randint(jax.random.key(0), (B, S), 0, V)
    labels = jnp.roll(tokens, -1, axis=1)

    comm.initialize(data=8)
    probe = GPTModel(vocab_size=V, hidden_size=H, num_heads=NH,
                     num_layers=L, max_seq_len=S)
    shape = jax.eval_shape(probe.init, jax.random.key(1), tokens)
    specs = jax.tree_util.tree_map_with_path(_megatron_spec_for, shape)
    comm.destroy()

    mesh = comm.initialize(data=2, model=4)
    model = GPTModel(vocab_size=V, hidden_size=H, num_heads=NH,
                     num_layers=L, max_seq_len=S,
                     sequence_parallel=sequence_parallel)
    variables = jax.jit(comm.shard_map(
        lambda k, t: model.init(k, t), mesh, in_specs=(P(), P()),
        out_specs=specs))(jax.random.key(1), tokens)
    g_tp = jax.jit(comm.shard_map(
        jax.grad(lambda v, t, l: model.loss(v, t, l)), mesh,
        in_specs=(specs, P(), P()), out_specs=specs))(
        variables, tokens, labels)

    comm.destroy()
    comm.initialize(data=8)
    model1 = GPTModel(vocab_size=V, hidden_size=H, num_heads=NH,
                      num_layers=L, max_seq_len=S)
    g_ref = jax.grad(lambda v, t, l: model1.loss(v, t, l))(
        variables, tokens, labels)

    _assert_grads_match(g_tp, g_ref, f"gpt sp={sequence_parallel}")


@pytest.mark.parametrize("sequence_parallel", [False, True])
def test_bert_tp_GRADS_match_tp1(sequence_parallel):
    """BERT analog of the GPT grad-parity test: every param grad under
    tp=4 (+SP) equals the tp=1 oracle through the MLM head + vocab-
    parallel CE."""
    from apex_tpu.transformer import tensor_parallel as tp_

    V, H, NH, L, S, B = 64, 32, 4, 2, 16, 2
    tokens = jax.random.randint(jax.random.key(10), (B, S), 0, V)
    labels = jax.random.randint(jax.random.key(12), (B, S), 0, V)

    comm.initialize(data=8)
    probe = BertModel(vocab_size=V, hidden_size=H, num_heads=NH,
                      num_layers=L, max_seq_len=S)
    shape = jax.eval_shape(probe.init, jax.random.key(11), tokens)
    specs = jax.tree_util.tree_map_with_path(_megatron_spec_for, shape)
    comm.destroy()

    def mlm_loss(m, v, t, l):
        logits = m.mlm_logits(v, t)                 # (s, b, V/tp)
        return jnp.mean(tp_.vocab_parallel_cross_entropy(
            logits, jnp.transpose(l, (1, 0))))

    mesh = comm.initialize(data=2, model=4)
    model = BertModel(vocab_size=V, hidden_size=H, num_heads=NH,
                      num_layers=L, max_seq_len=S,
                      sequence_parallel=sequence_parallel)
    variables = jax.jit(comm.shard_map(
        lambda k, t: model.init(k, t), mesh,
        in_specs=(P(), P()), out_specs=specs))(jax.random.key(11),
                                               tokens)
    g_tp = jax.jit(comm.shard_map(
        jax.grad(lambda v, t, l: mlm_loss(model, v, t, l)), mesh,
        in_specs=(specs, P(), P()), out_specs=specs))(
        variables, tokens, labels)

    comm.destroy()
    comm.initialize(data=8)
    g_ref = jax.grad(lambda v, t, l: mlm_loss(probe, v, t, l))(
        variables, tokens, labels)

    _assert_grads_match(g_tp, g_ref, f"bert sp={sequence_parallel}")


def test_4d_assembly_grads_match_single_device():
    """THE integration guard: the full 4D assembly — vocab-parallel
    embed -> SP scatter -> interleaved-1F1B pipeline (pp=2, V=2 chunks)
    with TP+SP inside the stages -> SP final LN -> exit gather -> tied
    vocab-sharded head -> vocab-parallel CE, grads reduced per the
    documented conventions (psum over pipe for pipe-replicated params,
    pmean over data, f/g mapping on the loss) — produces EXACTLY the
    single-device model's loss and every parameter gradient.  Catches
    the whole partial/scaled-gradient class at once (it found the
    raw-psum loss reduction scaling all grads by pp)."""
    from apex_tpu.models import GPTStage
    from apex_tpu.normalization import fused_layer_norm
    from apex_tpu.transformer import tensor_parallel as tp_
    from apex_tpu.transformer.tensor_parallel.mappings import (
        reduce_from_tensor_model_parallel_region as fg_reduce)
    from apex_tpu.transformer.pipeline_parallel import spmd

    dp, pp, tpsz, VCH = 2, 2, 2, 2
    V, H, NH, S = 64, 32, 4, 16
    MB, M = 2, 2
    B_local = MB * M
    B = dp * B_local
    s_loc = S // tpsz
    A_D, A_P, A_M = comm.AXIS_DATA, comm.AXIS_PIPE, comm.AXIS_MODEL

    embed = tp_.VocabParallelEmbedding(V, H, name="embed")
    stage = GPTStage(H, NH, num_layers=1, sequence_parallel=True)
    tokens = jnp.mod(jnp.arange(B * S, dtype=jnp.int32) * 5,
                     V).reshape(B, S)
    labels = jnp.roll(tokens, -1, axis=1)

    def stage_spec(path, leaf):
        name = "/".join(str(p.key) for p in path if hasattr(p, "key"))
        if "qkv" in name or "fc1" in name:
            inner = (P(None, A_M) if leaf.ndim == 2 else P(A_M))
        elif "proj/weight" in name or "fc2/weight" in name:
            inner = P(A_M, None)
        else:
            inner = P()
        return P(A_P, None, *inner)

    embed_spec = {"params": {"weight": P(A_M, None)}}
    lnf_spec = {"w": P(), "b": P()}
    comm.initialize(data=8)
    probe = jax.eval_shape(
        GPTStage(H, NH, num_layers=1).init, jax.random.key(0),
        jnp.zeros((S, MB, H), jnp.float32))
    stage_specs = jax.tree_util.tree_map_with_path(stage_spec, probe)
    comm.destroy()
    mesh = comm.initialize(data=dp, pipe=pp, model=tpsz)
    pspecs = (embed_spec, stage_specs, lnf_spec)

    def init_fn(key, tok):
        ev = embed.init(key, tok)
        k2 = jax.random.fold_in(jax.random.fold_in(key, 7),
                                jax.lax.axis_index(A_P))
        svs = [stage.init(jax.random.fold_in(k2, c),
                          jnp.zeros((s_loc, MB, H), jnp.float32))
               for c in range(VCH)]
        sv = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *svs)
        sv = jax.tree_util.tree_map(lambda x: x[None], sv)
        return ev, sv, {"w": jnp.ones((H,), jnp.float32),
                        "b": jnp.zeros((H,), jnp.float32)}

    params = jax.jit(comm.shard_map(
        init_fn, mesh, in_specs=(P(), P()), out_specs=pspecs))(
        jax.random.key(0), tokens[:B_local])

    def loss_fn(params, tok, lab):
        ev, sv, lnf = params
        pipe_rank = jax.lax.axis_index(A_P)
        pp_size = jax.lax.axis_size(A_P)
        x = embed.apply(ev, tok)
        x = jnp.transpose(x, (1, 0, 2))
        x = tp_.scatter_to_sequence_parallel_region(x)
        ub = jnp.transpose(x.reshape(x.shape[0], M, MB, H),
                           (1, 0, 2, 3))
        y = spmd.spmd_pipeline_interleaved_1f1b_apply(
            lambda pv, xx: stage.apply(pv, xx),
            jax.tree_util.tree_map(lambda a: a[0], sv), ub)
        y = jnp.transpose(y, (1, 0, 2, 3)).reshape(
            x.shape[0], B_local, H)
        wln = tp_.copy_to_tensor_model_parallel_region(lnf["w"])
        bln = tp_.copy_to_tensor_model_parallel_region(lnf["b"])
        y = fused_layer_norm(y, wln, bln)
        y = tp_.gather_from_sequence_parallel_region(y)
        logits = jnp.dot(y, ev["params"]["weight"].T,
                         preferred_element_type=jnp.float32)
        per_tok = tp_.vocab_parallel_cross_entropy(
            logits, jnp.transpose(lab, (1, 0)))
        return fg_reduce(jnp.where(pipe_rank == pp_size - 1,
                                   jnp.mean(per_tok), 0.0), A_P)

    def grad_step(params, tok, lab):
        loss, g = jax.value_and_grad(loss_fn)(params, tok, lab)
        gev, gsv, glnf = g
        gev = jax.tree_util.tree_map(
            lambda t: jax.lax.psum(t, A_P), gev)
        glnf = jax.tree_util.tree_map(
            lambda t: jax.lax.psum(t, A_P), glnf)
        g = jax.tree_util.tree_map(
            lambda t: jax.lax.pmean(t, A_D), (gev, gsv, glnf))
        return jax.lax.pmean(loss, A_D), g

    loss4d, g4d = jax.jit(comm.shard_map(
        grad_step, mesh, in_specs=(pspecs, P(A_D), P(A_D)),
        out_specs=(P(), pspecs)))(params, tokens, labels)

    comm.destroy()
    comm.initialize(data=8)
    stage1 = GPTStage(H, NH, num_layers=1)
    embed1 = tp_.VocabParallelEmbedding(V, H, name="embed")

    def oracle_loss(params, tok, lab):
        ev, sv, lnf = params
        x = embed1.apply(ev, tok)
        x = jnp.transpose(x, (1, 0, 2))
        for c in range(VCH):                  # global chunk c*pp + s
            for s_ in range(pp):
                chunk = jax.tree_util.tree_map(lambda a: a[s_, c], sv)
                x = stage1.apply(chunk, x)
        y = fused_layer_norm(x, lnf["w"], lnf["b"])
        logits = jnp.dot(y, ev["params"]["weight"].T,
                         preferred_element_type=jnp.float32)
        per_tok = tp_.vocab_parallel_cross_entropy(
            logits, jnp.transpose(lab, (1, 0)))
        return jnp.mean(per_tok)

    loss_ref, g_ref = jax.value_and_grad(oracle_loss)(
        params, tokens, labels)
    np.testing.assert_allclose(float(loss4d), float(loss_ref),
                               rtol=1e-6)
    _assert_grads_match(g4d, g_ref, "4d-assembly")


def test_bert_packed_batch_matches_per_sequence():
    """Packed-batch BERT (bidirectional segment-masked attention +
    within-sequence position lookups) must give, for every packed
    sequence, exactly the encoder output of running it alone."""
    from apex_tpu.data import pack_sequences

    model = BertModel(vocab_size=64, hidden_size=32, num_heads=4,
                      num_layers=2, max_seq_len=32)
    rng = np.random.default_rng(4)
    seqs = [rng.integers(1, 64, size=n) for n in (13, 8, 21, 6)]
    packed = pack_sequences(seqs, max_len=32, pad_id=0)
    tokens = jnp.asarray(packed["tokens"])
    variables = model.init(jax.random.key(0), tokens)

    out = model.apply(variables, tokens,
                      segment_ids=jnp.asarray(packed["segment_ids"]),
                      positions=jnp.asarray(packed["positions"]))

    for r in range(tokens.shape[0]):
        segs = packed["segment_ids"][r]
        for seg in range(1, int(segs.max()) + 1):
            idx = np.flatnonzero(segs == seg)
            alone = model.apply(variables, tokens[r:r + 1, idx])
            np.testing.assert_allclose(
                np.asarray(out[idx, r, :], np.float32),
                np.asarray(alone[:, 0, :], np.float32),
                rtol=2e-4, atol=2e-4)

    with pytest.raises(ValueError, match="BOTH segment_ids"):
        model.apply(variables, tokens,
                    segment_ids=jnp.asarray(packed["segment_ids"]))
    with pytest.raises(ValueError, match="not both"):
        model.apply(variables, tokens,
                    attention_mask=jnp.ones_like(tokens),
                    segment_ids=jnp.asarray(packed["segment_ids"]),
                    positions=jnp.asarray(packed["positions"]))
