"""Interleaved per-bucket collectives + fused flat gradient
accumulation (ISSUE 10).

Two families:

* **Overlap schedule** — the reduce-in-backward seam
  (``FlatGradPipeline(interleave=True)``) is bitwise identical to the
  trailing schedule under an 8-way shard_map, the reduce-scatter +
  all-gather decomposition matches the plain psum, chunked plans
  (``max_bucket_bytes``) round-trip, and the
  ``interleaved_collectives`` dependency-cone checker separates the
  interleaved program from the trailing pathology (so the apexverify
  spec has teeth).

* **Flat accumulation** — ``microbatches=N`` is bit-exact against the
  equivalent single-batch step for all five fused optimizers (exact
  dyadic-rational test data, f32 AND bf16+masters), found_inf latches
  across microbatches, the accumulator zeroes on step commit, donated
  accumulator buffers survive ``state_dict`` snapshots, and the
  accumulation loop's scan body structurally contains one bucket pack
  + one fused add per bucket and ZERO per-leaf unpacking.

Suite ``run_amp`` in tests/run_test.py.
"""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from apex_tpu import amp, comm
from apex_tpu.lint.semantic import jaxprs
from apex_tpu.multi_tensor_apply.packer import BucketPlan
from apex_tpu.ops import multi_tensor as mt
from apex_tpu.optimizers import (FusedAdagrad, FusedAdam, FusedLAMB,
                                 FusedNovoGrad, FusedSGD)

tree_map = jax.tree_util.tree_map
tree_leaves = jax.tree_util.tree_leaves

OPTS = [
    (FusedAdam, {}),
    (FusedSGD, {"momentum": 0.9}),
    (FusedAdagrad, {}),
    (FusedNovoGrad, {}),
    (FusedLAMB, {}),
]


def _exact_params(dtype=jnp.float32, layers=3):
    """Small-integer params: every value a dyadic rational with few
    mantissa bits, so sums/means over power-of-two batch sizes are
    EXACT in f32 (and bf16) — the substrate of the bit-exactness
    claims below."""
    rng = np.random.default_rng(0)
    return {
        f"l{i}": {
            "w": jnp.asarray(rng.integers(-2, 3, (8, 8)), dtype) * 0.5,
            "b": jnp.asarray(rng.integers(-1, 2, (8,)), dtype) * 0.5,
        }
        for i in range(layers)
    }


def _exact_batch(b=8):
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.integers(-2, 3, (b, 8)), jnp.float32)
    y = jnp.asarray(rng.integers(-1, 2, (b, 8)), jnp.float32)
    return x, y


def _quad_loss(p, x, y):
    """Linear tower + quadratic loss: exact arithmetic on the integer
    data above (no transcendental rounds anything)."""
    h = x
    for k in sorted(p):
        h = h @ p[k]["w"].astype(jnp.float32) \
            + p[k]["b"].astype(jnp.float32)
    return jnp.mean((h - y) ** 2)


def _assert_trees_equal(a, b):
    for la, lb in zip(tree_leaves(a), tree_leaves(b)):
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))


# ---------------------------------------------------------------------------
# flat_accumulate kernel
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("gdtype", [jnp.float32, jnp.bfloat16])
def test_flat_accumulate_matches_ref_and_oracle(gdtype):
    rng = np.random.default_rng(2)
    acc = jnp.asarray(rng.standard_normal(1000), jnp.float32)
    g = jnp.asarray(rng.standard_normal(1000), jnp.float32).astype(gdtype)
    out_k, flag_k = mt.flat_accumulate(acc, g, scale=0.5)
    out_r, flag_r = mt.flat_accumulate_ref(acc, g, scale=0.5)
    oracle = acc + g.astype(jnp.float32) * 0.5
    np.testing.assert_array_equal(np.asarray(out_k), np.asarray(out_r))
    np.testing.assert_allclose(np.asarray(out_k), np.asarray(oracle),
                               rtol=1e-6, atol=0)
    assert int(flag_k) == 0 == int(flag_r)
    assert out_k.dtype == jnp.float32


def test_flat_accumulate_flags_nonfinite_result():
    acc = jnp.zeros((8,), jnp.float32)
    g = jnp.zeros((8,), jnp.float32).at[3].set(jnp.inf)
    out, flag = mt.flat_accumulate(acc, g)
    assert int(flag) == 1
    # inf - inf through a later add -> nan: still flagged
    out2, flag2 = mt.flat_accumulate(out, -g)
    assert int(flag2) == 1 and not np.isfinite(np.asarray(out2)[3])


def test_flat_accumulate_rejects_non_f32_accumulator():
    with pytest.raises(ValueError, match="f32"):
        mt.flat_accumulate(jnp.zeros((8,), jnp.bfloat16),
                           jnp.zeros((8,), jnp.bfloat16))
    with pytest.raises(ValueError, match="f32"):
        mt.flat_accumulate_ref(jnp.zeros((8,), jnp.bfloat16),
                               jnp.zeros((8,), jnp.bfloat16))


# ---------------------------------------------------------------------------
# microbatches=N: bit-exact parity vs the single-batch step
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("cls,kw", OPTS,
                         ids=[c.__name__ for c, _ in OPTS])
@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16],
                         ids=["f32", "bf16_masters"])
def test_microbatched_step_bit_exact_vs_single_batch(cls, kw, dtype):
    """The acceptance claim: a microbatches=N flat-accumulated step is
    BIT-EXACT against the equivalent single-large-batch step, for all
    five fused optimizers, f32 and bf16+masters.  Exact dyadic data
    makes every sum/mean exact, so the two summation orders agree to
    the bit; the optimizer then sees bit-identical gradients.  The
    bf16 case uses a single-layer model with magnitudes chosen so
    every cotangent fits bf16's 8 mantissa bits (exact in BOTH
    precisions); f32 runs the deeper tower."""
    if dtype == jnp.bfloat16:
        w0 = jnp.asarray(np.random.default_rng(3).integers(
            -1, 2, (8, 8)), dtype) * 0.5
        mk = lambda: {"head": {"w": w0, "b": jnp.zeros((8,), dtype)}}
        x = jnp.asarray(np.random.default_rng(4).integers(
            -1, 2, (8, 8)), jnp.float32)
        y = jnp.asarray(np.random.default_rng(5).integers(
            0, 2, (8, 8)), jnp.float32)
    else:
        x, y = _exact_batch(8)
        mk = lambda: _exact_params(dtype)
    scaler = amp.LossScaleState.create(2.0 ** 8)   # power of two: exact

    results = {}
    for mode in ("single", "micro"):
        params = mk()
        opt = cls(params, lr=0.25, **kw)
        pipe = amp.FlatGradPipeline(optimizer=opt)
        loss, flat = pipe.scaled_value_and_grad(
            _quad_loss, scaler, params, x, y,
            microbatches=4 if mode == "micro" else 1)
        new_p = opt.step(flat, found_inf=flat.found_inf)
        results[mode] = (loss, flat, new_p)

    l1, f1, p1 = results["single"]
    l2, f2, p2 = results["micro"]
    np.testing.assert_array_equal(np.asarray(l1), np.asarray(l2))
    np.testing.assert_array_equal(np.asarray(f1.grad_norm),
                                  np.asarray(f2.grad_norm))
    # gradient buffers: micro accumulates in f32; the single-batch
    # buffers (model dtype) must match exactly after the same upcast
    for b1, b2 in zip(f1.bufs, f2.bufs):
        np.testing.assert_array_equal(
            np.asarray(b1, np.float32), np.asarray(b2, np.float32))
    _assert_trees_equal(p1, p2)


def test_microbatched_flat_matches_per_leaf_oracle_bit_exact():
    """grads_layout='flat' microbatch accumulation == the per-leaf
    tree oracle, bit for bit (same adds in the same order, packed vs
    unpacked), on ARBITRARY (non-exact) data."""
    params = {f"l{i}": {"w": jax.random.normal(jax.random.key(i),
                                               (8, 8)) * 0.3,
                        "b": jnp.zeros((8,))} for i in range(3)}
    x = jax.random.normal(jax.random.key(9), (8, 8))
    y = jax.random.normal(jax.random.key(10), (8, 8))
    scaler = amp.LossScaleState.create(2.0 ** 10)

    loss_t, grads_t, fi_t = amp.scaled_value_and_grad(
        _quad_loss, scaler, params, x, y, microbatches=4)
    loss_f, flat, fi_f = amp.scaled_value_and_grad(
        _quad_loss, scaler, params, x, y, microbatches=4,
        grads_layout="flat")
    plan = BucketPlan.from_tree(params)
    np.testing.assert_array_equal(np.asarray(loss_t), np.asarray(loss_f))
    assert int(fi_t) == int(fi_f) == 0
    packed_oracle = plan.pack(
        tree_map(lambda g: g.astype(jnp.float32), grads_t))
    for b_o, b_f in zip(packed_oracle, flat.bufs):
        np.testing.assert_array_equal(np.asarray(b_o), np.asarray(b_f))


def test_microbatched_has_aux_and_error_paths():
    params = _exact_params()
    x, y = _exact_batch(8)
    scaler = amp.LossScaleState.create()
    opt = FusedAdam(params, lr=1e-3)
    pipe = amp.FlatGradPipeline(optimizer=opt)

    def loss_aux(p, x, y):
        return _quad_loss(p, x, y), jnp.sum(x)

    (loss, aux), flat = pipe.scaled_value_and_grad(
        loss_aux, scaler, params, x, y, has_aux=True, microbatches=4)
    assert aux.shape == (4,)        # stacked along the microbatch axis
    with pytest.raises(ValueError, match="divide"):
        pipe.scaled_value_and_grad(_quad_loss, scaler, params,
                                   x[:6], y[:6], microbatches=4)
    with pytest.raises(ValueError, match="batch arguments"):
        pipe.scaled_value_and_grad(lambda p: jnp.float32(0.0), scaler,
                                   params, microbatches=4)
    # mismatched leading dims (a non-batch positional arg) must raise
    # clearly, never silently mis-split
    with pytest.raises(ValueError, match="leading"):
        pipe.scaled_value_and_grad(
            lambda p, xx, m: _quad_loss(p, xx, xx * 0) + jnp.sum(m),
            scaler, params, x, jnp.ones((2, 3)), microbatches=4)
    with pytest.raises(ValueError, match="leading"):
        pipe.scaled_value_and_grad(
            lambda p, xx, s: _quad_loss(p, xx, xx * 0) * s,
            scaler, params, x, jnp.float32(2.0), microbatches=4)


# ---------------------------------------------------------------------------
# found_inf latching + branch-free skip across microbatches
# ---------------------------------------------------------------------------

def test_one_bad_microbatch_latches_and_skips_the_whole_step():
    params = _exact_params()
    x, y = _exact_batch(8)
    # poison ONLY microbatch 2 (rows 4..5)
    x_bad = x.at[4, 0].set(jnp.inf)
    scaler = amp.LossScaleState.create(2.0 ** 8)
    opt = FusedAdam(params, lr=0.25)
    pipe = amp.FlatGradPipeline(optimizer=opt)
    p_before = jax.device_get(opt.params)
    step_before = int(opt.step_count)

    loss, flat = pipe.scaled_value_and_grad(
        _quad_loss, scaler, params, x_bad, y, microbatches=4)
    assert int(flat.found_inf) == 1
    # clip coefficient pinned neutral on overflow (never 0 or NaN)
    assert float(flat.clip_coef) == 1.0

    opt.step(flat, found_inf=flat.found_inf)
    _assert_trees_equal(p_before, jax.device_get(opt.params))
    assert int(opt.step_count) == step_before   # clock held too


def test_accumulate_latch_is_sticky_across_later_clean_microbatches():
    params = _exact_params()
    opt = FusedAdam(params, lr=1e-3)
    pipe = amp.FlatGradPipeline(optimizer=opt)
    good = tree_map(jnp.ones_like, params)
    bad = tree_map(lambda p: jnp.full(p.shape, jnp.nan), params)
    acc = pipe.init_accum()
    acc = pipe.accumulate(acc, good)
    assert int(acc.found_inf) == 0
    acc = pipe.accumulate(acc, bad)
    assert int(acc.found_inf) == 1
    acc = pipe.accumulate(acc, good)       # a later clean microbatch
    assert int(acc.found_inf) == 1         # cannot clear the latch
    flat = pipe.finalize(acc, inv_scale=1.0)
    assert int(flat.found_inf) == 1
    assert int(acc.count) == 3


# ---------------------------------------------------------------------------
# accumulator lifecycle: zeroing on commit, donation vs state_dict
# ---------------------------------------------------------------------------

def test_accumulator_zeroing_on_step_commit():
    params = _exact_params()
    x, y = _exact_batch(8)
    scaler = amp.LossScaleState.create(2.0 ** 8)
    opt = FusedAdam(params, lr=0.25)
    pipe = amp.FlatGradPipeline(optimizer=opt)

    def one_window(acc):
        for i in range(4):
            _, g = jax.value_and_grad(
                lambda p: _quad_loss(p, x[2 * i:2 * i + 2],
                                     y[2 * i:2 * i + 2])
                * scaler.loss_scale)(params)
            acc = pipe.accumulate(acc, g)
        return acc

    acc = one_window(pipe.init_accum())
    flat1 = pipe.finalize(acc, scaler)
    acc = pipe.reset_accum(acc)            # step commit zeroes
    assert int(acc.count) == 0 and int(acc.found_inf) == 0
    for b in acc.bufs:
        assert not np.asarray(b).any()
    # the reused (zeroed) accumulator reproduces a fresh one bitwise
    flat2 = pipe.finalize(one_window(acc), scaler)
    for b1, b2 in zip(flat1.bufs, flat2.bufs):
        np.testing.assert_array_equal(np.asarray(b1), np.asarray(b2))


def test_donated_accumulator_survives_state_dict_snapshots():
    """The accumulation step donates its GradAccum (the fused add is
    in place); an optimizer state_dict snapshot taken mid-window must
    stay readable through later donating accumulates AND through the
    committed (donating) optimizer step."""
    params = _exact_params()
    opt = FusedAdam(params, lr=1e-3)
    pipe = amp.FlatGradPipeline(optimizer=opt)
    grads = tree_map(jnp.ones_like, params)

    accum_jit = jax.jit(pipe.accumulate, donate_argnums=(0,))
    acc = accum_jit(opt.grad_accum_init(), grads)
    sd = opt.state_dict()                  # snapshot mid-accumulation
    acc = accum_jit(acc, grads)            # first acc donated away
    flat = pipe.finalize(acc, inv_scale=0.5)
    opt.step(flat, found_inf=flat.found_inf)   # donates opt_state
    # the snapshot is still fully materializable and loadable
    for leaf in tree_leaves(sd["state"]):
        np.asarray(leaf)
    opt2 = FusedAdam(params, lr=1e-3)
    opt2.load_state_dict(sd)
    assert int(opt2.step_count) == 0


# ---------------------------------------------------------------------------
# structural: the accumulation loop never unpacks per leaf
# ---------------------------------------------------------------------------

def test_scan_body_packs_per_bucket_and_never_unpacks():
    """Zero per-leaf work in the accumulation loop, asserted on the
    jaxpr: the scan body holds exactly one bucket-sized concatenate
    per bucket (the pack), one fused accumulate per bucket, and NO
    slice out of a bucket-sized buffer (the unpack signature)."""
    from apex_tpu.ops._dispatch import op_enabled

    params = _exact_params()
    x, y = _exact_batch(8)
    scaler = amp.LossScaleState.create()
    opt = FusedAdam(params, lr=1e-3)
    plan = opt._plan
    nb = len(plan.buckets)
    pipe = amp.FlatGradPipeline(optimizer=opt)

    def micro_step(params, x, y):
        loss, flat = pipe.scaled_value_and_grad(
            _quad_loss, scaler, params, x, y, microbatches=4)
        return loss, flat.bufs

    jaxpr = jax.make_jaxpr(micro_step)(params, x, y)
    scans = [e for e in jaxprs.iter_eqns(jaxpr)
             if e.primitive.name == "scan"]
    assert scans, "microbatches=N must lower to a scan"
    body = scans[0].params["jaxpr"]
    bucket_sizes = {(b.size,) for b in plan.buckets}
    packs = [s for s in jaxprs.concat_out_shapes(body)
             if s in bucket_sizes]
    assert len(packs) == nb
    # no per-leaf unpack: nothing slices a bucket-sized buffer apart
    bad = [e for e in jaxprs.iter_eqns(body)
           if e.primitive.name == "slice"
           and tuple(getattr(e.invars[0].aval, "shape", ()))
           in bucket_sizes]
    assert not bad, [str(e) for e in bad]
    if op_enabled("multi_tensor"):
        counts = jaxprs.primitive_counts(body)
        assert counts.get("pallas_call", 0) == nb   # flat_accumulate
    # and the registered spec pins the donated-accumulator aliasing
    from apex_tpu.lint import semantic
    res = semantic.verify_spec(
        semantic.get_spec("amp.flat_accumulate_step"))
    assert res.ok, res.failures
    assert "donated_aliases_min" in res.checked


# ---------------------------------------------------------------------------
# overlap schedule: interleave seam, decomposition, chunked plans
# ---------------------------------------------------------------------------

def _dp_step(pipe, scaler, mesh):
    def f(p, x, y):
        loss, flat = pipe.scaled_value_and_grad(_quad_loss, scaler,
                                                p, x, y)
        return loss, flat.bufs, flat.grad_norm
    # interleaved vs trailing are two different programs by design —
    # each comparison leg compiles exactly once
    # apexlint: disable-next=APX302
    return jax.jit(comm.shard_map(
        f, mesh, in_specs=(P(), P(comm.AXIS_DATA), P(comm.AXIS_DATA)),
        out_specs=P()))


def test_interleaved_schedule_bitwise_matches_trailing():
    mesh = comm.initialize(data=8)
    try:
        params = _exact_params()
        scaler = amp.LossScaleState.create(2.0 ** 8)
        opt = FusedAdam(params, lr=1e-3, max_bucket_bytes=300)
        assert len(opt._plan.buckets) == 3
        x = jax.random.normal(jax.random.key(5), (16, 8))
        y = jax.random.normal(jax.random.key(6), (16, 8))
        outs = {}
        for name, interleave in (("trail", False), ("seam", True)):
            pipe = amp.FlatGradPipeline(
                optimizer=opt, max_grad_norm=1.0,
                axis_name=comm.AXIS_DATA, interleave=interleave)
            outs[name] = _dp_step(pipe, scaler, mesh)(params, x, y)
        for a, b in zip(outs["trail"][1], outs["seam"][1]):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        np.testing.assert_array_equal(np.asarray(outs["trail"][2]),
                                      np.asarray(outs["seam"][2]))
    finally:
        comm.destroy()


def test_reduce_scatter_decomposition_matches_psum():
    mesh = comm.initialize(data=8)
    try:
        params = _exact_params()
        scaler = amp.LossScaleState.create(2.0 ** 8)
        # deliberately indivisible bucket sizes (72 elems vs 8 ranks
        # pads to 72? 72 % 8 == 0 — use the monolithic 216-elem plan,
        # 216 % 8 == 0 too; chunk at one leaf per bucket to get a
        # 64-elem w and an 8-elem b... all divisible; force padding
        # with a 3-layer + extra 5-elem leaf tree)
        params["odd"] = {"w": jnp.ones((5, 1), jnp.float32),
                         "b": jnp.zeros((3,), jnp.float32)}
        opt = FusedAdam(params, lr=1e-3, max_bucket_bytes=300)
        x = jax.random.normal(jax.random.key(7), (16, 8))
        y = jax.random.normal(jax.random.key(8), (16, 8))

        def loss_fn(p, x, y):
            base = {k: v for k, v in p.items() if k != "odd"}
            return _quad_loss(base, x, y) \
                + jnp.sum(p["odd"]["w"] ** 2) \
                + jnp.sum(p["odd"]["b"] ** 2)

        outs = {}
        for dec in ("psum", "reduce_scatter"):
            pipe = amp.FlatGradPipeline(
                optimizer=opt, axis_name=comm.AXIS_DATA,
                reduce_decompose=dec)

            def f(p, x, y, pipe=pipe):
                loss, flat = pipe.scaled_value_and_grad(
                    loss_fn, scaler, p, x, y)
                return flat.bufs
            # psum vs reduce_scatter are two programs by design
            # apexlint: disable-next=APX302
            outs[dec] = jax.jit(comm.shard_map(
                f, mesh,
                in_specs=(P(), P(comm.AXIS_DATA), P(comm.AXIS_DATA)),
                out_specs=P()))(params, x, y)
        for a, b in zip(outs["psum"], outs["reduce_scatter"]):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-6, atol=1e-7)
    finally:
        comm.destroy()


def test_always_fp32_composes_with_packed_path_without_double_cast():
    from apex_tpu.parallel.distributed import all_reduce_flat_buffers
    mesh = comm.initialize(data=8)
    try:
        bufs = (jnp.ones((256,), jnp.bfloat16),
                jnp.ones((128,), jnp.float32))

        def reduce(bufs):
            return tuple(all_reduce_flat_buffers(
                list(bufs), comm.AXIS_DATA, always_fp32=True))

        fn = comm.shard_map(reduce, mesh, in_specs=(P(),),
                            out_specs=P())
        out = jax.jit(fn)(bufs)
        assert all(b.dtype == jnp.float32 for b in out)
        # exactly ONE convert (bf16 bucket in): the f32 bucket pays
        # zero converts, and nothing casts back after the psum
        jaxpr = jax.make_jaxpr(fn)(bufs)
        converts = [e for e in jaxprs.iter_eqns(jaxpr)
                    if e.primitive.name == "convert_element_type"]
        assert len(converts) == 1, [str(e) for e in converts]
        # average=True over 8 replicated ranks of ones -> exactly 1.0
        np.testing.assert_array_equal(np.asarray(out[1]),
                                      np.ones((128,), np.float32))
    finally:
        comm.destroy()


def test_chunked_plan_roundtrip_and_state_dict():
    params = _exact_params()
    n_elems = sum(int(l.size) for l in tree_leaves(params))
    plan = BucketPlan.from_tree(params, max_bucket_bytes=300)
    assert len(plan.buckets) == 3
    assert sum(b.size for b in plan.buckets) == n_elems
    tree = plan.unpack(plan.pack_work(params))
    _assert_trees_equal(tree, params)
    # a chunked optimizer interloads checkpoints with a monolithic one
    grads = tree_map(jnp.ones_like, params)
    opt_c = FusedAdam(params, lr=0.25, max_bucket_bytes=300)
    opt_m = FusedAdam(params, lr=0.25)
    opt_c.step(grads)
    opt_m.load_state_dict(opt_c.state_dict())
    opt_m.params = opt_c.params
    p_c = opt_c.step(grads)
    p_m = opt_m.step(grads)
    _assert_trees_equal(p_c, p_m)


def test_pipeline_rejects_conflicting_max_bucket_bytes():
    """A supplied plan (optimizer=/plan=) wins over later derivation,
    so a mismatching chunking request must raise — silently keeping
    the optimizer's monolithic plan would degrade interleave=True to
    the trailing schedule it exists to replace."""
    params = _exact_params()
    opt = FusedAdam(params, lr=1e-3)               # monolithic plan
    with pytest.raises(ValueError, match="max_bucket_bytes"):
        amp.FlatGradPipeline(optimizer=opt, max_bucket_bytes=300,
                             interleave=True)
    # matching cap (or none at all) composes fine
    opt_c = FusedAdam(params, lr=1e-3, max_bucket_bytes=300)
    amp.FlatGradPipeline(optimizer=opt_c, max_bucket_bytes=300)
    amp.FlatGradPipeline(optimizer=opt_c)


def test_interleaved_cone_checker_separates_trailing_schedule():
    """The apexverify overlap invariant has teeth: the SAME checker
    that passes the chunked+seam program fails the monolithic trailing
    program."""
    from apex_tpu.lint.semantic.registry import (
        _chk_interleaved_collectives)

    mesh = comm.initialize(data=8)
    try:
        params = _exact_params()
        scaler = amp.LossScaleState.create()
        x = jax.random.normal(jax.random.key(11), (16, 8))
        y = jax.random.normal(jax.random.key(12), (16, 8))

        def jaxpr_of(opt, interleave):
            pipe = amp.FlatGradPipeline(
                optimizer=opt, axis_name=comm.AXIS_DATA,
                interleave=interleave)

            def f(p, x, y):
                loss, flat = pipe.scaled_value_and_grad(
                    _quad_loss, scaler, p, x, y)
                return loss, flat.bufs
            return jax.make_jaxpr(comm.shard_map(
                f, mesh,
                in_specs=(P(), P(comm.AXIS_DATA), P(comm.AXIS_DATA)),
                out_specs=P()))(params, x, y)

        good = jaxpr_of(FusedAdam(params, lr=1e-3,
                                  max_bucket_bytes=300), True)
        bad = jaxpr_of(FusedAdam(params, lr=1e-3), False)
        expect = {"min_collectives": 2}
        assert _chk_interleaved_collectives({"jaxpr": good},
                                            expect) is None
        msg = _chk_interleaved_collectives({"jaxpr": bad}, expect)
        assert msg is not None and "collective" in msg

        # and the dependency cones behind the verdicts are as
        # documented: proper, pairwise-distinct SET subsets
        scopes = jaxprs.collective_compute_cones(good)
        scope = max(scopes, key=lambda s: len(s["collectives"]))
        colls = scope["collectives"]
        assert len(colls) == 3
        assert len({c["cone"] for c in colls}) == 3
        assert min(c["cone_compute"] for c in colls) \
            < scope["total_compute"]
    finally:
        comm.destroy()


def test_registered_overlap_and_accum_specs_pass():
    from apex_tpu.lint import semantic
    res = semantic.verify_spec(
        semantic.get_spec("amp.interleaved_flat_step"))
    assert res.ok, res.failures
    assert {"interleaved_collectives", "donated_aliases_min",
            "psum_count", "no_host_transfer"} <= set(res.checked)
    assert len(semantic.verify_all()) >= 18


# ---------------------------------------------------------------------------
# platform: latency-hiding-scheduler flag wiring (provenance)
# ---------------------------------------------------------------------------

def test_lhs_flags_withheld_unless_tpu_target(monkeypatch):
    from apex_tpu import platform
    monkeypatch.setenv("APEX_TPU_PLATFORM", "cpu")
    prov = platform.enable_latency_hiding_scheduler()
    assert prov["applied"] is False
    assert prov["xla_flags_added"] == []
    assert "not tpu" in prov["reason"]
    assert platform.latency_hiding_provenance() == prov
    # no platform selection at all (the common non-TPU machine):
    # withheld too — "default" must never get TPU-only XLA_FLAGS that
    # a non-TPU backend could reject at init
    monkeypatch.delenv("APEX_TPU_PLATFORM", raising=False)
    monkeypatch.delenv("JAX_PLATFORMS", raising=False)
    prov = platform.enable_latency_hiding_scheduler()
    assert prov["applied"] is False and prov["xla_flags_added"] == []
    assert prov["target"] == "default"


def test_lhs_flags_appended_idempotently_for_tpu_target(monkeypatch):
    import warnings

    from apex_tpu import platform
    monkeypatch.setenv("APEX_TPU_PLATFORM", "tpu")
    monkeypatch.setenv("XLA_FLAGS", "--xla_something_else=1")
    monkeypatch.delenv("LIBTPU_INIT_ARGS", raising=False)
    with warnings.catch_warnings():
        # the backend is already up in this test process: the call
        # must WARN and record applied=False, never half-configure
        warnings.simplefilter("error")
        with pytest.raises(RuntimeWarning, match="backend"):
            platform.enable_latency_hiding_scheduler()
        warnings.simplefilter("ignore")
        prov = platform.enable_latency_hiding_scheduler()
    assert prov["applied"] is False        # backend already initialized
    assert any("latency_hiding" in f for f in prov["xla_flags_added"])
    assert any("async_collective" in f
               for f in prov["libtpu_flags_added"])
    assert "--xla_something_else=1" in os.environ["XLA_FLAGS"]
    # idempotent: a second call adds nothing, records skips
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        prov2 = platform.enable_latency_hiding_scheduler()
    assert prov2["xla_flags_added"] == []
    assert prov2["libtpu_flags_added"] == []
    assert len(prov2["skipped"]) == (
        len(prov["xla_flags_added"]) + len(prov["libtpu_flags_added"]))


# ---------------------------------------------------------------------------
# bench harness smoke (tier-1 keeps the tooling runnable)
# ---------------------------------------------------------------------------

def test_flat_accumulate_microbench_smoke():
    """Harness smoke + the CPU-interpret acceptance floor: at a
    many-leaf single-grid-block shape the fused add beats the per-leaf
    tree-map accumulation >= 1.3x even with Pallas interpreted
    (measured ~4-5x here; the margin absorbs CI timing noise)."""
    from apex_tpu.optimizers.bucketing_bench import bench_flat_accumulate
    r = bench_flat_accumulate(layers=32, hidden=16, iters=3, reps=2)
    assert r["accum_per_leaf_ms"] > 0
    assert r["accum_flat_ms"] > 0
    assert r["accum_leaves"] == 128
    assert r["accum_flat_speedup"] >= 1.3, r


def test_grad_accum_train_bench_smoke():
    from apex_tpu.optimizers.bucketing_bench import bench_grad_accum
    r = bench_grad_accum(layers=2, hidden=16, batch=8,
                         n_micro=(1, 2), iters=2, reps=1)
    for n in (1, 2):
        assert r[f"grad_accum_flat_n{n}_ms"] > 0
        assert r[f"grad_accum_per_leaf_n{n}_ms"] > 0


def test_overlap_schedule_bench_smoke():
    """bench.py's interleaved-vs-trailing observatory leg runs end to
    end off-TPU (capture -> attribute -> overlap_pct both ways); the
    hardware target rides BENCH rounds + the perf_gate budget row."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "bench", os.path.join(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))), "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    r = bench.bench_overlap_schedule(jax, jnp, steps=3, layers=3,
                                     hidden=32)
    assert r["overlap_buckets"] >= 2
    for leg in ("interleaved", "trailing"):
        assert r.get(f"overlap_{leg}_pct") is not None
