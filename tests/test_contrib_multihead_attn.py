"""contrib.multihead_attn + contrib.fmha vs pure-framework oracles
(reference test pattern: apex/contrib/test/multihead_attn/test_* compare
the fast kernels against the torch *_func.py reference paths)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.contrib.fmha import fmha_packed
from apex_tpu.contrib.multihead_attn import (
    EncdecMultiheadAttn,
    SelfMultiheadAttn,
)
from apex_tpu.ops.attention import attention_ref

T, B, E, H = 16, 4, 64, 4


def _oracle_self_attn(params, x, num_heads, causal=False, kpm=None):
    """Stock-JAX MHA using the module's own weights."""
    qkv = x @ params["qkv_proj"]["kernel"]
    q, k, v = jnp.split(qkv, 3, axis=-1)

    def heads(t):
        tt, bb, e = t.shape
        return t.reshape(tt, bb, num_heads, e // num_heads
                         ).transpose(1, 2, 0, 3)
    mask = None
    if kpm is not None:
        mask = jnp.where(kpm[:, None, None, :] != 0, -10000.0, 0.0)
    o = attention_ref(heads(q), heads(k), heads(v), causal=causal,
                      mask=mask)
    o = o.transpose(2, 0, 1, 3).reshape(x.shape)
    return o @ params["out_proj"]["kernel"]


def test_self_attn_matches_oracle():
    m = SelfMultiheadAttn(embed_dim=E, num_heads=H)
    x = jax.random.normal(jax.random.PRNGKey(0), (T, B, E))
    params = m.init(jax.random.PRNGKey(1), x)["params"]
    out, _ = m.apply({"params": params}, x)
    want = _oracle_self_attn(params, x, H)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


def test_self_attn_causal_masks_future():
    m = SelfMultiheadAttn(embed_dim=E, num_heads=H)
    x = jax.random.normal(jax.random.PRNGKey(0), (T, B, E))
    params = m.init(jax.random.PRNGKey(1), x)["params"]
    out, _ = m.apply({"params": params}, x, attn_mask="causal")
    # causal: output at t=0 must be independent of tokens > 0
    x2 = x.at[5:].set(0.0)
    out2, _ = m.apply({"params": params}, x2, attn_mask="causal")
    np.testing.assert_allclose(np.asarray(out[0]), np.asarray(out2[0]),
                               rtol=1e-5, atol=1e-5)
    want = _oracle_self_attn(params, x, H, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


def test_self_attn_key_padding_mask_boolean_and_additive():
    x = jax.random.normal(jax.random.PRNGKey(0), (T, B, E))
    kpm_bool = jnp.zeros((B, T), jnp.int32).at[:, -4:].set(1)
    m = SelfMultiheadAttn(embed_dim=E, num_heads=H)
    params = m.init(jax.random.PRNGKey(1), x)["params"]
    out_b, _ = m.apply({"params": params}, x, key_padding_mask=kpm_bool)
    want = _oracle_self_attn(params, x, H, kpm=kpm_bool)
    np.testing.assert_allclose(np.asarray(out_b), np.asarray(want),
                               rtol=2e-4, atol=2e-4)
    # additive form of the same mask gives the same output
    m_add = SelfMultiheadAttn(embed_dim=E, num_heads=H, mask_additive=True)
    kpm_add = jnp.where(kpm_bool != 0, -10000.0, 0.0)
    out_a, _ = m_add.apply({"params": params}, x, key_padding_mask=kpm_add)
    np.testing.assert_allclose(np.asarray(out_a), np.asarray(out_b),
                               rtol=1e-5, atol=1e-5)


def test_self_attn_norm_add_residual():
    m = SelfMultiheadAttn(embed_dim=E, num_heads=H, include_norm_add=True)
    x = jax.random.normal(jax.random.PRNGKey(0), (T, B, E))
    params = m.init(jax.random.PRNGKey(1), x)["params"]
    out, _ = m.apply({"params": params}, x)
    # zeroing the attention out_proj leaves exactly the residual
    z = jax.tree_util.tree_map(jnp.zeros_like, params)
    z = dict(params)
    z["out_proj"] = jax.tree_util.tree_map(jnp.zeros_like,
                                           params["out_proj"])
    out_z, _ = m.apply({"params": z}, x)
    np.testing.assert_allclose(np.asarray(out_z), np.asarray(x),
                               rtol=1e-5, atol=1e-5)


def test_self_attn_need_weights_shapes_and_rowsum():
    m = SelfMultiheadAttn(embed_dim=E, num_heads=H)
    x = jax.random.normal(jax.random.PRNGKey(0), (T, B, E))
    params = m.init(jax.random.PRNGKey(1), x)["params"]
    _, probs = m.apply({"params": params}, x, need_weights=True)
    assert probs.shape == (B, H, T, T)
    np.testing.assert_allclose(np.asarray(jnp.sum(probs, -1)),
                               np.ones((B, H, T)), rtol=1e-5)


def test_self_attn_separate_qkv_and_bias_grad_flows():
    m = SelfMultiheadAttn(embed_dim=E, num_heads=H, bias=True,
                          separate_qkv_params=True)
    x = jax.random.normal(jax.random.PRNGKey(0), (T, B, E))
    params = m.init(jax.random.PRNGKey(1), x)["params"]
    assert "q_proj" in params and "bias" in params["q_proj"]

    g = jax.grad(lambda p: jnp.sum(m.apply({"params": p}, x)[0] ** 2))(
        params)
    assert float(jnp.linalg.norm(g["q_proj"]["kernel"])) > 0


def test_encdec_attn_cross_shapes_and_oracle():
    tq, tk = 8, 24
    m = EncdecMultiheadAttn(embed_dim=E, num_heads=H)
    q = jax.random.normal(jax.random.PRNGKey(0), (tq, B, E))
    mem = jax.random.normal(jax.random.PRNGKey(1), (tk, B, E))
    params = m.init(jax.random.PRNGKey(2), q, mem)["params"]
    out, _ = m.apply({"params": params}, q, mem)
    assert out.shape == (tq, B, E)

    qp = q @ params["q_proj"]["kernel"]
    kv = mem @ params["kv_proj"]["kernel"]
    k, v = jnp.split(kv, 2, axis=-1)

    def heads(t):
        tt, bb, e = t.shape
        return t.reshape(tt, bb, H, e // H).transpose(1, 2, 0, 3)
    o = attention_ref(heads(qp), heads(k), heads(v))
    want = o.transpose(2, 0, 1, 3).reshape(tq, B, E) \
        @ params["out_proj"]["kernel"]
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


def test_fmha_packed_matches_per_sequence_attention():
    lens = [5, 9, 2]
    total = 32                       # padded packed buffer
    cu = jnp.asarray(np.cumsum([0] + lens), jnp.int32)
    qkv = jax.random.normal(jax.random.PRNGKey(0), (total, 3, H, 16))
    out = fmha_packed(qkv, cu)
    # oracle: run each sequence separately through attention_ref
    for i, ln in enumerate(lens):
        s, e = int(cu[i]), int(cu[i + 1])
        q = qkv[s:e, 0].transpose(1, 0, 2)[None]
        k = qkv[s:e, 1].transpose(1, 0, 2)[None]
        v = qkv[s:e, 2].transpose(1, 0, 2)[None]
        want = attention_ref(q, k, v)[0].transpose(1, 0, 2)
        np.testing.assert_allclose(np.asarray(out[s:e]), np.asarray(want),
                                   rtol=2e-4, atol=2e-4)
    # padding tokens produce zeros
    assert np.all(np.asarray(out[int(cu[-1]):]) == 0.0)


def test_self_attn_fused_dropout_plumbing():
    """Round-4 contrib glue: dropout routes through the FUSED kernel
    (no dense fallback), is stochastic across rng keys, deterministic
    per key, off in eval, and matches the hash-mask oracle built from
    the same key fold."""
    t, b, e, h = 64, 2, 64, 4
    m = SelfMultiheadAttn(embed_dim=e, num_heads=h, dropout=0.4,
                          impl="fast")
    kx, kp = jax.random.split(jax.random.key(0))
    x = jax.random.normal(kx, (t, b, e))
    params = m.init({"params": kp, "dropout": jax.random.key(1)},
                    x, x, x, is_training=True)

    key = jax.random.key(42)
    o1 = m.apply(params, x, x, x, is_training=True,
                 rngs={"dropout": key})[0]
    o2 = m.apply(params, x, x, x, is_training=True,
                 rngs={"dropout": key})[0]
    o3 = m.apply(params, x, x, x, is_training=True,
                 rngs={"dropout": jax.random.key(43)})[0]
    np.testing.assert_array_equal(np.asarray(o1), np.asarray(o2))
    assert float(jnp.max(jnp.abs(o1 - o3))) > 1e-3

    # eval: dropout off, equals the no-dropout oracle
    oe = m.apply(params, x, x, x, is_training=False)[0]
    me = SelfMultiheadAttn(embed_dim=e, num_heads=h, dropout=0.0,
                           impl="fast")
    o0 = me.apply(params, x, x, x, is_training=False)[0]
    np.testing.assert_allclose(np.asarray(oe), np.asarray(o0),
                               rtol=1e-6, atol=1e-6)


def test_fmha_packed_dropout_matches_kernel_semantics():
    """fmha dropout now rides the fused kernel: same key fold + same
    hash mask as flash_attention with the derived seed."""
    from apex_tpu.ops.attention import (dropout_seed_from_key,
                                        flash_attention)

    h, d = 2, 64
    lens = [60, 40, 28]
    total = 160                      # includes padding tail
    cu = jnp.asarray(np.cumsum([0] + lens), jnp.int32)
    qkv = jax.random.normal(jax.random.key(0), (total, 3, h, d))
    rng = jax.random.key(9)

    out = fmha_packed(qkv, cu, p_dropout=0.3, is_training=True,
                      dropout_rng=rng)
    # oracle: the same flash call fmha builds internally
    seg = jnp.searchsorted(cu[1:], jnp.arange(total), side="right")
    valid = jnp.arange(total) < cu[-1]
    q_ids = jnp.where(valid, seg, -1)[None]
    kv_ids = jnp.where(valid, seg, -2)[None]
    tr = lambda x: jnp.transpose(x, (1, 0, 2))[None]
    want = flash_attention(
        tr(qkv[:, 0]), tr(qkv[:, 1]), tr(qkv[:, 2]),
        segment_ids=(q_ids, kv_ids), dropout_rate=0.3,
        dropout_seed=dropout_seed_from_key(rng))
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(jnp.transpose(want[0], (1, 0, 2))),
        rtol=1e-6, atol=1e-6)
    # eval mode: is_training=False zeroes the rate regardless of
    # p_dropout, so repeated calls are identical
    e1 = fmha_packed(qkv, cu, p_dropout=0.3, is_training=False)
    e2 = fmha_packed(qkv, cu, p_dropout=0.3, is_training=False)
    np.testing.assert_array_equal(np.asarray(e1), np.asarray(e2))
