"""Real multi-PROCESS distributed init (VERDICT r4 next-item 5).

The reference's most battle-tested distributed surface is the
`torch.distributed.launch` flow: N OS processes, env-var rendezvous,
init_process_group, collectives (SURVEY.md §2.6).  tests/test_comm.py
pins the env PARSING; this suite exercises the real thing on CPU — it
spawns worker processes that go through `comm.initialize_distributed()`
→ `jax.distributed.initialize()` (gRPC coordinator handshake), build
the global mesh, and run one cross-process psum on the gloo CPU
collectives backend.  Full tier: ~20-40 s of subprocess jax startup on
the 1-core box.
"""

import os
import socket
import subprocess
import sys

import pytest

pytestmark = pytest.mark.slow

_WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "_dist_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _clean_env() -> dict:
    """Strip every rendezvous/platform var the pytest process may hold
    (the conftest's XLA_FLAGS, a developer's WORLD_SIZE) so workers see
    exactly the launcher contract the test sets."""
    env = dict(os.environ)
    for k in ("XLA_FLAGS", "JAX_COORDINATOR_ADDRESS",
              "COORDINATOR_ADDRESS", "WORLD_SIZE", "RANK",
              "NUM_PROCESSES", "PROCESS_ID", "JAX_PLATFORMS",
              "APEX_TPU_PLATFORM", "APEX_TPU_SMOKE"):
        env.pop(k, None)
    return env


@pytest.mark.parametrize("world", [2])
def test_multiprocess_handshake_and_psum(world):
    port = _free_port()
    env = _clean_env()
    procs = [
        subprocess.Popen(
            [sys.executable, _WORKER, str(r), str(world), str(port)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, env=env)
        for r in range(world)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for r, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, (
            f"rank {r} rc={p.returncode}\n{out[-4000:]}")
        assert f"DIST_OK {r}" in out, f"rank {r}:\n{out[-4000:]}"


def test_worker_rejects_bad_rendezvous():
    """A worker pointed at a dead coordinator must FAIL (nonzero exit),
    not silently fall back to single-process — the reference flow's
    failure mode (init_process_group hangs/raises) made misconfigured
    launches visible, and so must ours."""
    port = _free_port()          # bound to nothing: dead address
    env = _clean_env()
    env["APEX_DIST_INIT_TIMEOUT"] = "5"  # cap jax's 300s retry loop
    p = subprocess.Popen(
        [sys.executable, _WORKER, "1", "2", str(port)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env)
    try:
        out, _ = p.communicate(timeout=120)
    except subprocess.TimeoutExpired:
        p.kill()
        out, _ = p.communicate()
        pytest.fail(f"worker hung on dead coordinator:\n{out[-2000:]}")
    assert p.returncode != 0
    assert "DIST_OK" not in out
