"""Real multi-PROCESS distributed init (VERDICT r4 next-item 5).

The reference's most battle-tested distributed surface is the
`torch.distributed.launch` flow: N OS processes, env-var rendezvous,
init_process_group, collectives (SURVEY.md §2.6).  tests/test_comm.py
pins the env PARSING; this suite exercises the real thing on CPU — it
spawns worker processes that go through `comm.initialize_distributed()`
→ `jax.distributed.initialize()` (gRPC coordinator handshake), build
the global mesh, and run one cross-process psum on the gloo CPU
collectives backend.  Full tier: ~20-40 s of subprocess jax startup on
the 1-core box.
"""

import os
import socket
import subprocess
import sys
import time

import pytest

pytestmark = pytest.mark.slow

_WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "_dist_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _clean_env() -> dict:
    """Strip every rendezvous/platform var the pytest process may hold
    (the conftest's XLA_FLAGS, a developer's WORLD_SIZE) so workers see
    exactly the launcher contract the test sets."""
    env = dict(os.environ)
    for k in ("XLA_FLAGS", "JAX_COORDINATOR_ADDRESS",
              "COORDINATOR_ADDRESS", "WORLD_SIZE", "RANK",
              "NUM_PROCESSES", "PROCESS_ID", "JAX_PLATFORMS",
              "APEX_TPU_PLATFORM", "APEX_TPU_SMOKE"):
        env.pop(k, None)
    return env


@pytest.mark.parametrize("world", [2])
def test_multiprocess_handshake_and_psum(world):
    port = _free_port()
    env = _clean_env()
    procs = [
        subprocess.Popen(
            [sys.executable, _WORKER, str(r), str(world), str(port)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, env=env)
        for r in range(world)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for r, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, (
            f"rank {r} rc={p.returncode}\n{out[-4000:]}")
        assert f"DIST_OK {r}" in out, f"rank {r}:\n{out[-4000:]}"


def test_launcher_spawns_world_and_propagates_failure():
    """`python -m apex_tpu.launch` (reference: torch.distributed.launch)
    sets the env contract for N workers, reaps them, and propagates
    the first nonzero exit while tearing the rest down."""
    env = _clean_env()
    p = subprocess.run(
        [sys.executable, "-m", "apex_tpu.launch", "--nproc", "2",
         _WORKER],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env={**env, "PYTHONPATH": os.path.dirname(
            os.path.dirname(_WORKER))},
        timeout=240)
    assert p.returncode == 0, p.stdout[-4000:]
    assert "DIST_OK 0" in p.stdout and "DIST_OK 1" in p.stdout

    # config errors are rejected up front (torchrun semantics): a
    # multi-node shape without a shared coordinator, and a zero-worker
    # launch that would otherwise exit 0 with no training run
    launch_env = {**env, "PYTHONPATH": os.path.dirname(
        os.path.dirname(_WORKER))}
    for argv, needle in (
            (["--nproc", "2", "--nnodes", "2"], "--coordinator"),
            (["--nproc", "0"], "must be >= 1"),
            (["--nproc", "2", "--nnodes", "2", "--node-rank", "2",
              "--coordinator", "127.0.0.1:1"], "node-rank")):
        p = subprocess.run(
            [sys.executable, "-m", "apex_tpu.launch", *argv, _WORKER],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, env=launch_env, timeout=60)
        assert p.returncode == 2, (argv, p.stdout[-500:])
        assert needle in p.stdout, (argv, p.stdout[-500:])

def test_launcher_tears_down_siblings_on_crash(tmp_path):
    """One crashed rank must fail the whole launch promptly — a
    sibling blocked in a collective would otherwise hang forever
    (torchrun semantics)."""
    crash = tmp_path / "crash.py"
    crash.write_text(
        "import os, sys, time\n"
        "if os.environ['RANK'] == '1':\n"
        "    sys.exit(7)\n"
        "time.sleep(120)\n")
    t0 = time.time()
    p = subprocess.run(
        [sys.executable, "-m", "apex_tpu.launch", "--nproc", "2",
         str(crash)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env={**_clean_env(), "PYTHONPATH": os.path.dirname(
            os.path.dirname(_WORKER))},
        timeout=90)
    assert p.returncode == 7, p.stdout[-2000:]
    assert time.time() - t0 < 60    # sibling killed, not waited out


def test_worker_rejects_bad_rendezvous():
    """A worker pointed at a dead coordinator must FAIL (nonzero exit),
    not silently fall back to single-process — the reference flow's
    failure mode (init_process_group hangs/raises) made misconfigured
    launches visible, and so must ours."""
    port = _free_port()          # bound to nothing: dead address
    env = _clean_env()
    env["APEX_DIST_INIT_TIMEOUT"] = "5"  # cap jax's 300s retry loop
    p = subprocess.Popen(
        [sys.executable, _WORKER, "1", "2", str(port)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env)
    try:
        out, _ = p.communicate(timeout=120)
    except subprocess.TimeoutExpired:
        p.kill()
        out, _ = p.communicate()
        pytest.fail(f"worker hung on dead coordinator:\n{out[-2000:]}")
    assert p.returncode != 0
    assert "DIST_OK" not in out
