"""Pipeline schedules vs the unpipelined chain oracle (reference models:
tests/L0/run_transformer/test_pipeline_parallel_fwd_bwd.py): same losses,
same grads, for the host 1F1B schedule AND the SPMD ppermute pipeline."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu import comm
from apex_tpu.transformer import pipeline_parallel as pp

D = 8          # feature width
M = 6          # microbatches
MB = 4         # microbatch size
L = 4          # stages


def stage_apply(params, x):
    return jnp.tanh(x @ params["w"] + params["b"])


def make_stage_params(key, scale=0.5):
    k1, k2 = jax.random.split(key)
    return {"w": jax.random.normal(k1, (D, D)) * scale,
            "b": jax.random.normal(k2, (D,)) * 0.1}


def chain_loss(all_params, x, target):
    h = x
    for p in all_params:
        h = stage_apply(p, h)
    return jnp.mean((h - target) ** 2)


@pytest.fixture
def problem():
    keys = jax.random.split(jax.random.key(0), L)
    params = [make_stage_params(k) for k in keys]
    x = jax.random.normal(jax.random.key(1), (M, MB, D))
    tgt = jax.random.normal(jax.random.key(2), (M, MB, D))
    return params, x, tgt


def fsf_factory(x, tgt):
    """forward_step_func closing over per-microbatch targets."""
    def fsf(mb_index_pair, input_tensor, apply_fn, params):
        mb_x, mb_t = mb_index_pair
        inp = mb_x if input_tensor is None else input_tensor
        out = apply_fn(params, inp)

        def loss_fn(o):
            return jnp.mean((o - mb_t) ** 2)
        return out, loss_fn
    return fsf


def oracle(params, x, tgt):
    """Accumulated-over-microbatches loss/grads of the full chain."""
    losses = [chain_loss(params, x[i], tgt[i]) for i in range(M)]

    def total(ps):
        return sum(chain_loss(ps, x[i], tgt[i]) for i in range(M))
    grads = jax.grad(total)(params)
    return losses, grads


def test_no_pipelining_matches_oracle(problem):
    params, x, tgt = problem
    # single "stage" holding the whole chain
    def apply_all(ps, inp):
        h = inp
        for p in ps:
            h = stage_apply(p, h)
        return h

    batch = [(x[i], tgt[i]) for i in range(M)]
    losses, grads = pp.forward_backward_no_pipelining(
        fsf_factory(x, tgt), batch, [(apply_all, params)])
    want_losses, want_grads = oracle(params, x, tgt)
    np.testing.assert_allclose(np.asarray(losses),
                               np.asarray(want_losses), rtol=1e-5)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-4,
                                                atol=1e-6),
        grads[0], want_grads)


def test_1f1b_matches_oracle(problem):
    params, x, tgt = problem
    batch = [(x[i], tgt[i]) for i in range(M)]
    model = [(stage_apply, p) for p in params]
    losses, grads = pp.forward_backward_pipelining_without_interleaving(
        fsf_factory(x, tgt), batch, model)
    want_losses, want_grads = oracle(params, x, tgt)
    np.testing.assert_allclose(np.asarray(losses),
                               np.asarray(want_losses), rtol=1e-5)
    for s in range(L):
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                a, b, rtol=1e-4, atol=1e-6),
            grads[s], want_grads[s])


def test_1f1b_forward_only(problem):
    params, x, tgt = problem
    batch = [(x[i], tgt[i]) for i in range(M)]
    model = [(stage_apply, p) for p in params]
    losses, grads = pp.forward_backward_pipelining_without_interleaving(
        fsf_factory(x, tgt), batch, model, forward_only=True)
    want_losses, _ = oracle(params, x, tgt)
    assert grads is None
    np.testing.assert_allclose(np.asarray(losses),
                               np.asarray(want_losses), rtol=1e-5)


def test_get_forward_backward_func_dispatch():
    f = pp.get_forward_backward_func(None, 1)
    assert f is pp.forward_backward_no_pipelining
    f = pp.get_forward_backward_func(None, 4)
    assert f is pp.forward_backward_pipelining_without_interleaving
    f = pp.get_forward_backward_func(2, 4)
    assert (getattr(f, "func", None)
            is pp._forward_backward_pipelining_with_interleaving)
    assert f.keywords == {"pipeline_model_parallel_size": 4,
                          "virtual_pipeline_model_parallel_size": 2}


def test_interleaved_1f1b_matches_oracle(problem):
    """P=2 physical stages x V=2 virtual chunks over the same 4-stage
    chain: losses and per-chunk grads must equal the unpipelined oracle
    (reference: ...pipelining_with_interleaving vs single-model runs in
    test_pipeline_parallel_fwd_bwd.py)."""
    params, x, tgt = problem
    batch = [(x[i], tgt[i]) for i in range(M)]
    model = [(stage_apply, p) for p in params]   # v = c*P + s dataflow order
    fwd_bwd = pp.get_forward_backward_func(2, 2)
    losses, grads = fwd_bwd(fsf_factory(x, tgt), batch, model)
    want_losses, want_grads = oracle(params, x, tgt)
    np.testing.assert_allclose(np.asarray(losses),
                               np.asarray(want_losses), rtol=1e-5)
    for s in range(L):
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                a, b, rtol=1e-4, atol=1e-6),
            grads[s], want_grads[s])


def test_interleaved_1f1b_forward_only(problem):
    params, x, tgt = problem
    batch = [(x[i], tgt[i]) for i in range(M)]
    model = [(stage_apply, p) for p in params]
    fwd_bwd = pp.get_forward_backward_func(2, 2)
    losses, grads = fwd_bwd(fsf_factory(x, tgt), batch, model,
                            forward_only=True)
    want_losses, _ = oracle(params, x, tgt)
    assert grads is None
    np.testing.assert_allclose(np.asarray(losses),
                               np.asarray(want_losses), rtol=1e-5)


def test_interleaved_schedule_order_differs(problem):
    """VERDICT r1 #5 'done' criterion: the interleaved execution order is
    actually interleaved — rank 0 returns to chunk 0 for a second
    microbatch group before finishing all of chunk 0's microbatches in a
    row (a non-interleaved chain would never revisit), and its warmup
    follows the (P - r - 1)*2 + (V-1)*P formula."""
    params, x, tgt = problem
    batch = [(x[i], tgt[i]) for i in range(M)]
    model = [(stage_apply, p) for p in params]
    trace = []
    pp._forward_backward_pipelining_with_interleaving(
        fsf_factory(x, tgt), batch, model,
        pipeline_model_parallel_size=2,
        virtual_pipeline_model_parallel_size=2,
        schedule_trace=trace)
    r0_fwd = [(c, mb) for (r, kind, c, mb) in trace
              if r == 0 and kind == "fwd"]
    # reference order: P=2 microbatches on chunk 0, then P on chunk 1,
    # then back to chunk 0 for the next group — interleaving visible as
    # a return to chunk 0
    assert r0_fwd[:4] == [(0, 0), (0, 1), (1, 0), (1, 1)]
    assert r0_fwd[4][0] == 0, "schedule never returned to chunk 0"
    # warmup depth: rank 0 runs (2-0-1)*2 + (2-1)*2 = 4 warmup forwards,
    # then the steady state is fwd-then-bwd, so the first backward is
    # action W+1 = 5
    r0 = [(kind) for (r, kind, c, mb) in trace if r == 0]
    assert r0.index("bwd") == 5
    # and rank 1 fills less pipe: warmup (2-1-1)*2 + 2 = 2 -> bwd at 3
    r1 = [(kind) for (r, kind, c, mb) in trace if r == 1]
    assert r1.index("bwd") == 3


def test_spmd_pipeline_matches_chain(problem):
    """The ppermute scan pipeline == sequential chain, fwd AND grads."""
    params, x, tgt = problem
    mesh = comm.initialize(data=2, pipe=4)
    # stack per-stage params on a leading axis, shard it over "pipe"
    stacked = jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs), *params)

    pspec = jax.tree_util.tree_map(lambda _: P(comm.AXIS_PIPE), params[0])

    def run(stacked_local, xx):
        # stacked_local: (1, D, D) etc — this stage's chunk
        local = jax.tree_util.tree_map(lambda a: a[0], stacked_local)
        return pp.spmd_pipeline(stage_apply, local, xx)

    y = jax.jit(comm.shard_map(
        run, mesh,
        in_specs=(pspec, P()),
        out_specs=P()))(stacked, x)

    h = x
    for p in params:
        h = jax.vmap(stage_apply, in_axes=(None, 0))(p, h)
    np.testing.assert_allclose(np.asarray(y), np.asarray(h),
                               rtol=1e-5, atol=1e-5)


def test_spmd_pipeline_grads_match_chain(problem):
    params, x, tgt = problem
    mesh = comm.initialize(data=2, pipe=4)
    stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *params)
    pspec = jax.tree_util.tree_map(lambda _: P(comm.AXIS_PIPE), params[0])

    def loss(stacked_local, xx, tt):
        local = jax.tree_util.tree_map(lambda a: a[0], stacked_local)
        return pp.spmd_pipeline_loss(
            stage_apply, lambda y, t: jnp.mean((y - t) ** 2),
            local, xx, tt)

    g = jax.jit(comm.shard_map(
        jax.grad(loss), mesh,
        in_specs=(pspec, P(), P()),
        out_specs=pspec))(stacked, x, tgt)

    def chain_mean_loss(ps):
        h = x
        for p in ps:
            h = jax.vmap(stage_apply, in_axes=(None, 0))(p, h)
        return jnp.mean(jax.vmap(
            lambda y, t: jnp.mean((y - t) ** 2))(h, tgt))

    want = jax.grad(chain_mean_loss)(params)
    want_stacked = jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs), *want)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-4,
                                                atol=1e-5),
        g, want_stacked)


def test_spmd_1f1b_matches_chain(problem):
    """The explicit 1F1B scan (O(L) activation window, VERDICT r1 #5)
    produces the same mean loss and stage-local grads as autodiff of
    the chain."""
    params, x, tgt = problem
    mesh = comm.initialize(data=2, pipe=4)
    stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *params)
    pspec = jax.tree_util.tree_map(lambda _: P(comm.AXIS_PIPE), params[0])

    def run(stacked_local, xx, tt):
        local = jax.tree_util.tree_map(lambda a: a[0], stacked_local)
        loss, g = pp.spmd_pipeline_1f1b(
            stage_apply, lambda y, t: jnp.mean((y - t) ** 2),
            local, xx, tt)
        g = jax.tree_util.tree_map(lambda a: a[None], g)
        return loss, g

    loss, g = jax.jit(comm.shard_map(
        run, mesh,
        in_specs=(pspec, P(), P()),
        out_specs=(P(), pspec)))(stacked, x, tgt)

    def chain_mean_loss(ps):
        h = x
        for p in ps:
            h = jax.vmap(stage_apply, in_axes=(None, 0))(p, h)
        return jnp.mean(jax.vmap(
            lambda y, t: jnp.mean((y - t) ** 2))(h, tgt))

    want_loss = chain_mean_loss(params)
    want = jax.grad(chain_mean_loss)(params)
    want_stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *want)
    np.testing.assert_allclose(float(loss), float(want_loss), rtol=1e-5)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-4,
                                                atol=1e-5),
        g, want_stacked)


def test_spmd_1f1b_apply_differentiable_end_to_end(problem):
    """VERDICT r2 #5: the DIFFERENTIABLE 1F1B (custom_vjp drop-in for
    spmd_pipeline) matches chain autodiff for stage grads AND for
    params before (pre-scale) and after (post-head) the pipeline —
    i.e. the input-cotangent path works, which plain
    spmd_pipeline_1f1b cannot provide."""
    params, x, tgt = problem
    mesh = comm.initialize(data=2, pipe=4)
    stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *params)
    pspec = jax.tree_util.tree_map(lambda _: P(comm.AXIS_PIPE), params[0])
    D = x.shape[-1]
    pre = jnp.eye(D) + 0.01 * jnp.arange(D * D).reshape(D, D) / (D * D)
    post = jnp.eye(D) * 0.9

    def loss_1f1b(pre_w, post_w, stacked_local, xx, tt):
        local = jax.tree_util.tree_map(lambda a: a[0], stacked_local)
        ub = xx @ pre_w                       # pre-pipeline op
        y = pp.spmd_pipeline_1f1b_apply(stage_apply, local, ub)
        y = y @ post_w                        # post-pipeline op
        return jnp.mean(jax.vmap(
            lambda yy, t: jnp.mean((yy - t) ** 2))(y, tt))

    def loss_gpipe(pre_w, post_w, stacked_local, xx, tt):
        local = jax.tree_util.tree_map(lambda a: a[0], stacked_local)
        ub = xx @ pre_w
        y = pp.spmd_pipeline(stage_apply, local, ub)
        y = y @ post_w
        return jnp.mean(jax.vmap(
            lambda yy, t: jnp.mean((yy - t) ** 2))(y, tt))

    def run(loss_f):
        return jax.jit(comm.shard_map(
            jax.value_and_grad(loss_f, argnums=(0, 1, 2)), mesh,
            in_specs=(P(), P(), pspec, P(), P()),
            out_specs=(P(), (P(), P(), pspec))))(
            pre, post, stacked, x, tgt)

    l1, g1 = run(loss_1f1b)
    l2, g2 = run(loss_gpipe)
    np.testing.assert_allclose(float(l1), float(l2), rtol=1e-5)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5),
        g1, g2)

    # and the chain oracle (no pipeline at all)
    def chain(pre_w, post_w, ps):
        h = x @ pre_w
        for p in ps:
            h = jax.vmap(stage_apply, in_axes=(None, 0))(p, h)
        h = h @ post_w
        return jnp.mean(jax.vmap(
            lambda yy, t: jnp.mean((yy - t) ** 2))(h, tgt))

    want_l, want_g = jax.value_and_grad(chain, argnums=(0, 1, 2))(
        pre, post, params)
    want_stacked = (want_g[0], want_g[1], jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs), *want_g[2]))
    np.testing.assert_allclose(float(l1), float(want_l), rtol=1e-5)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5),
        g1, want_stacked)


def test_train_pp_grad_reduction_convention(problem):
    """Pin the grad-reduction recipe examples/simple/train_pp.py uses
    (advisor r3 medium): the pipeline OUTPUT is replicated across the
    pipe axis, so post-pipeline (head) grads are already FULL on every
    rank — psum'ing them over pipe scales by pp (a lr*pp error under
    SGD).  Only the PRE-pipeline path is a rank-0 partial and needs the
    psum.  This test runs the example's exact reduction and demands the
    resulting grads equal chain autodiff — with the head psum'ed it
    would see a pp* mismatch and fail."""
    params, x, tgt = problem
    mesh = comm.initialize(data=2, pipe=4)
    stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *params)
    pspec = jax.tree_util.tree_map(lambda _: P(comm.AXIS_PIPE), params[0])
    pre = jnp.eye(D) + 0.02 * jnp.arange(D * D).reshape(D, D) / (D * D)
    post = 0.7 * jnp.eye(D) + 0.01

    def loss_fn(pre_w, post_w, stacked_local, xx, tt):
        local = jax.tree_util.tree_map(lambda a: a[0], stacked_local)
        y = pp.spmd_pipeline_1f1b_apply(stage_apply, local, xx @ pre_w)
        y = y @ post_w
        return jnp.mean(jax.vmap(
            lambda yy, t: jnp.mean((yy - t) ** 2))(y, tt))

    def grad_step(pre_w, post_w, stacked_local, xx, tt):
        g_pre, g_post, g_st = jax.grad(
            loss_fn, argnums=(0, 1, 2))(pre_w, post_w, stacked_local,
                                        xx, tt)
        # the example's reduction: psum ONLY the pre-pipeline partial
        g_pre = jax.lax.psum(g_pre, comm.AXIS_PIPE)
        g = (g_pre, g_post, g_st)
        return jax.tree_util.tree_map(
            lambda t: jax.lax.pmean(t, comm.AXIS_DATA), g)

    got = jax.jit(comm.shard_map(
        grad_step, mesh,
        in_specs=(P(), P(), pspec, P(comm.AXIS_DATA), P(comm.AXIS_DATA)),
        out_specs=(P(), P(), pspec)))(pre, post, stacked, x, tgt)

    dp = 2

    def chain(pre_w, post_w, ps):
        # mean over the dp data shards of the per-shard mean-MSE loss —
        # exactly what the psum(pre)+pmean(data) recipe should produce
        def shard_loss(xx, tt):
            h = xx @ pre_w
            for p in ps:
                h = jax.vmap(stage_apply, in_axes=(None, 0))(p, h)
            h = h @ post_w
            return jnp.mean(jax.vmap(
                lambda yy, t: jnp.mean((yy - t) ** 2))(h, tt))
        xs = x.reshape(dp, M // dp, *x.shape[1:])
        ts = tgt.reshape(dp, -1, *tgt.shape[1:])
        return jnp.mean(jnp.stack(
            [shard_loss(xs[i], ts[i]) for i in range(dp)]))

    want = jax.grad(chain, argnums=(0, 1, 2))(pre, post, params)
    want = (want[0], want[1],
            jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *want[2]))
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5),
        got, want)


def test_spmd_interleaved_matches_chain(problem):
    """SPMD interleaved virtual stages (VERDICT r2 #7): V=2 chunks per
    stage, v=c*P+s placement — outputs AND grads match the sequential
    chain over all P*V chunks, with more microbatches than stages so
    the grouped circular schedule actually engages."""
    params, x, tgt = problem
    mesh = comm.initialize(data=2, pipe=4)
    P_, V = 4, 2
    # build P*V chunks: reuse the 4 stage params twice with a tweak so
    # chunks are all distinct
    chunks = [jax.tree_util.tree_map(lambda a, k=i: a * (1.0 + 0.05 * k),
                                     params[i % P_])
              for i in range(P_ * V)]
    # stage s holds chunks [s, P+s] stacked on a leading V dim
    per_stage = [jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs), chunks[s], chunks[P_ + s])
        for s in range(P_)]
    stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs),
                                     *per_stage)     # (P, V, ...)
    pspec = jax.tree_util.tree_map(lambda _: P(comm.AXIS_PIPE),
                                   params[0])

    def run(stacked_local, xx):
        local = jax.tree_util.tree_map(lambda a: a[0], stacked_local)
        return pp.spmd_pipeline_interleaved(stage_apply, local, xx)

    y = jax.jit(comm.shard_map(
        run, mesh, in_specs=(pspec, P()), out_specs=P()))(stacked, x)

    h = x
    for c in chunks:                      # global chunk order 0..PV-1
        h = jax.vmap(stage_apply, in_axes=(None, 0))(c, h)
    np.testing.assert_allclose(np.asarray(y), np.asarray(h),
                               rtol=1e-5, atol=1e-5)

    # grads through the interleaved pipeline
    def loss_i(stacked_local, xx, tt):
        local = jax.tree_util.tree_map(lambda a: a[0], stacked_local)
        yy = pp.spmd_pipeline_interleaved(stage_apply, local, xx)
        return jnp.mean(jax.vmap(
            lambda a, b: jnp.mean((a - b) ** 2))(yy, tt))

    g = jax.jit(comm.shard_map(
        jax.grad(loss_i), mesh,
        in_specs=(pspec, P(), P()), out_specs=pspec))(stacked, x, tgt)

    def chain_loss(cs):
        hh = x
        for c in cs:
            hh = jax.vmap(stage_apply, in_axes=(None, 0))(c, hh)
        return jnp.mean(jax.vmap(
            lambda a, b: jnp.mean((a - b) ** 2))(hh, tgt))

    want = jax.grad(chain_loss)(chunks)
    want_per_stage = [jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs), want[s], want[P_ + s])
        for s in range(P_)]
    want_stacked = jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs), *want_per_stage)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5),
        g, want_stacked)


class TestInterleaved1F1B:
    """The production schedule: virtual chunks AND the 1F1B window,
    as one SPMD scan driven by static schedule tables."""

    def test_schedule_invariants(self):
        from apex_tpu.transformer.pipeline_parallel.interleaved_1f1b \
            import _greedy_ticks, build_schedule
        for (P_, V, M_) in [(2, 1, 3), (2, 2, 5), (4, 2, 6), (4, 3, 4)]:
            PV = P_ * V
            f, b = _greedy_ticks(P_, V, M_)
            assert len(f) == PV * M_ and len(b) == PV * M_
            for (v, j), t in f.items():
                if v > 0:
                    assert f[(v - 1, j)] + 1 <= t
            for (v, j), t in b.items():
                assert f[(v, j)] <= t
                if v < PV - 1:
                    assert b[(v + 1, j)] + 1 <= t
            from collections import Counter
            assert max(Counter(
                (v % P_, t) for (v, j), t in f.items()).values()) == 1
            assert max(Counter(
                (v % P_, t) for (v, j), t in b.items()).values()) == 1
            # advisor r3: the last virtual stage's first backward seeds
            # from the loss IN the tick of its own forward (the scan
            # body supports it; the scheduler must actually emit it)
            assert b[(PV - 1, 0)] == f[(PV - 1, 0)]
            s = build_schedule(P_, V, M_)
            for nm, cap in (("a_wr_slot", "abuf"), ("f_src_slot", "abuf"),
                            ("x_wr_slot", "xbuf"), ("x_rd_slot", "xbuf"),
                            ("c_wr_slot", "cbuf"), ("c_rd_slot", "cbuf")):
                assert s[nm].max() < s["sizes"][cap]

    def test_activation_window_independent_of_microbatches(self):
        """The 1F1B point: saved-activation slots must NOT grow with
        M (GPipe memory would)."""
        from apex_tpu.transformer.pipeline_parallel.interleaved_1f1b \
            import build_schedule
        a = build_schedule(2, 2, 8)["sizes"]["xbuf"]
        b = build_schedule(2, 2, 64)["sizes"]["xbuf"]
        assert a == b <= 2 * 2 * 2 - 1 + 1

    def test_matches_chain(self, problem):
        """(loss, grads) == chain autodiff over all P*V chunks, with
        M > P so the steady state engages."""
        params, x, tgt = problem
        mesh = comm.initialize(data=2, pipe=4)
        P_, V = 4, 2
        chunks = [jax.tree_util.tree_map(
            lambda a, k=i: a * (1.0 + 0.05 * k), params[i % P_])
            for i in range(P_ * V)]
        per_stage = [jax.tree_util.tree_map(
            lambda *xs: jnp.stack(xs), chunks[s], chunks[P_ + s])
            for s in range(P_)]
        stacked = jax.tree_util.tree_map(
            lambda *xs: jnp.stack(xs), *per_stage)      # (P, V, ...)
        pspec = jax.tree_util.tree_map(lambda _: P(comm.AXIS_PIPE),
                                       params[0])

        def run(stacked_local, xx, tt):
            local = jax.tree_util.tree_map(lambda a: a[0], stacked_local)
            loss, g = pp.spmd_pipeline_interleaved_1f1b(
                stage_apply, lambda y, t: jnp.mean((y - t) ** 2),
                local, xx, tt)
            return loss, jax.tree_util.tree_map(lambda a: a[None], g)

        loss, g = jax.jit(comm.shard_map(
            run, mesh,
            in_specs=(pspec, P(), P()),
            out_specs=(P(), pspec)))(stacked, x, tgt)

        def chain_loss(cs):
            h = x
            for c in cs:
                h = jax.vmap(stage_apply, in_axes=(None, 0))(c, h)
            return jnp.mean(jax.vmap(
                lambda yy, t: jnp.mean((yy - t) ** 2))(h, tgt))

        want_loss = chain_loss(chunks)
        want = jax.grad(chain_loss)(chunks)
        want_per_stage = [jax.tree_util.tree_map(
            lambda *xs: jnp.stack(xs), want[s], want[P_ + s])
            for s in range(P_)]
        want_stacked = jax.tree_util.tree_map(
            lambda *xs: jnp.stack(xs), *want_per_stage)
        np.testing.assert_allclose(float(loss), float(want_loss),
                                   rtol=1e-5)
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5),
            g, want_stacked)

    def test_v1_matches_noninterleaved_1f1b(self, problem):
        """V=1 degenerates to the non-interleaved schedule's results."""
        params, x, tgt = problem
        mesh = comm.initialize(data=2, pipe=4)
        stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs),
                                         *params)
        pspec = jax.tree_util.tree_map(lambda _: P(comm.AXIS_PIPE),
                                       params[0])

        def run_i(stacked_local, xx, tt):
            local = jax.tree_util.tree_map(lambda a: a[0], stacked_local)
            chunked = jax.tree_util.tree_map(lambda a: a[None], local)
            loss, g = pp.spmd_pipeline_interleaved_1f1b(
                stage_apply, lambda y, t: jnp.mean((y - t) ** 2),
                chunked, xx, tt)
            return loss, jax.tree_util.tree_map(lambda a: a[0][None], g)

        def run_n(stacked_local, xx, tt):
            local = jax.tree_util.tree_map(lambda a: a[0], stacked_local)
            loss, g = pp.spmd_pipeline_1f1b(
                stage_apply, lambda y, t: jnp.mean((y - t) ** 2),
                local, xx, tt)
            return loss, jax.tree_util.tree_map(lambda a: a[None], g)

        out_i = jax.jit(comm.shard_map(
            run_i, mesh, in_specs=(pspec, P(), P()),
            out_specs=(P(), pspec)))(stacked, x, tgt)
        out_n = jax.jit(comm.shard_map(
            run_n, mesh, in_specs=(pspec, P(), P()),
            out_specs=(P(), pspec)))(stacked, x, tgt)
        np.testing.assert_allclose(float(out_i[0]), float(out_n[0]),
                                   rtol=1e-6)
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6),
            out_i[1], out_n[1])


def test_interleaved_1f1b_apply_composable(problem):
    """The composable interleaved variant: pre/post-pipeline params AND
    chunked stage params all match chain autodiff (the virtual-chunk
    analog of spmd_pipeline_1f1b_apply)."""
    params, x, tgt = problem
    mesh = comm.initialize(data=2, pipe=4)
    P_, V = 4, 2
    chunks = [jax.tree_util.tree_map(
        lambda a, k=i: a * (1.0 + 0.05 * k), params[i % P_])
        for i in range(P_ * V)]
    per_stage = [jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs), chunks[s], chunks[P_ + s])
        for s in range(P_)]
    stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs),
                                     *per_stage)
    pspec = jax.tree_util.tree_map(lambda _: P(comm.AXIS_PIPE),
                                   params[0])
    D = x.shape[-1]
    pre = jnp.eye(D) + 0.01 * jnp.arange(D * D).reshape(D, D) / (D * D)
    post = jnp.eye(D) * 0.9

    def loss_f(pre_w, post_w, stacked_local, xx, tt):
        local = jax.tree_util.tree_map(lambda a: a[0], stacked_local)
        ub = xx @ pre_w
        y = pp.spmd_pipeline_interleaved_1f1b_apply(stage_apply, local,
                                                    ub)
        y = y @ post_w
        return jnp.mean(jax.vmap(
            lambda yy, t: jnp.mean((yy - t) ** 2))(y, tt))

    l1, g1 = jax.jit(comm.shard_map(
        jax.value_and_grad(loss_f, argnums=(0, 1, 2)), mesh,
        in_specs=(P(), P(), pspec, P(), P()),
        out_specs=(P(), (P(), P(), pspec))))(pre, post, stacked, x, tgt)

    def chain(pre_w, post_w, cs):
        h = x @ pre_w
        for c in cs:
            h = jax.vmap(stage_apply, in_axes=(None, 0))(c, h)
        h = h @ post_w
        return jnp.mean(jax.vmap(
            lambda yy, t: jnp.mean((yy - t) ** 2))(h, tgt))

    want_l, want_g = jax.value_and_grad(chain, argnums=(0, 1, 2))(
        pre, post, chunks)
    want_per_stage = [jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs), want_g[2][s], want_g[2][P_ + s])
        for s in range(P_)]
    want_stacked = (want_g[0], want_g[1], jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs), *want_per_stage))
    np.testing.assert_allclose(float(l1), float(want_l), rtol=1e-5)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5),
        g1, want_stacked)
