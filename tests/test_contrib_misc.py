"""groupbn / focal_loss / index_mul_2d / conv_bias_relu / bottleneck
suites (reference pattern: apex/contrib/test/<feature>/ — fused vs stock
oracle)."""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu import comm
from apex_tpu.contrib.bottleneck import Bottleneck, halo_exchange
from apex_tpu.contrib.conv_bias_relu import (
    ConvBias,
    ConvBiasMaskReLU,
    ConvBiasReLU,
)
from apex_tpu.contrib.cudnn_gbn import GroupBatchNorm2d
from apex_tpu.contrib.focal_loss import focal_loss
from apex_tpu.contrib.groupbn import BatchNorm2d_NHWC
from apex_tpu.contrib.index_mul_2d import index_mul_2d


# ---------------------------------------------------------------------------
# groupbn
# ---------------------------------------------------------------------------

def test_groupbn_matches_flax_batchnorm():
    x = jax.random.normal(jax.random.PRNGKey(0), (4, 6, 6, 16)) * 3 + 1
    m = BatchNorm2d_NHWC(num_features=16)
    v = m.init(jax.random.PRNGKey(1), x, use_running_average=False)
    y, _ = m.apply(v, x, use_running_average=False,
                   mutable=["batch_stats"])
    ref = nn.BatchNorm(use_running_average=False, momentum=0.9)
    vr = ref.init(jax.random.PRNGKey(1), x)
    want, _ = ref.apply(vr, x, mutable=["batch_stats"])
    np.testing.assert_allclose(np.asarray(y), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


def test_groupbn_fused_add_relu():
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 4, 4, 8))
    z = jax.random.normal(jax.random.PRNGKey(1), (2, 4, 4, 8))
    m = BatchNorm2d_NHWC(num_features=8, fuse_relu=True)
    v = m.init(jax.random.PRNGKey(2), x, use_running_average=False)
    y, _ = m.apply(v, x, z, use_running_average=False,
                   mutable=["batch_stats"])
    assert np.all(np.asarray(y) >= 0.0)
    m2 = BatchNorm2d_NHWC(num_features=8)
    y2, _ = m2.apply(v, x, use_running_average=False,
                     mutable=["batch_stats"])
    np.testing.assert_allclose(
        np.asarray(y),
        np.maximum(np.asarray(y2) + np.asarray(z), 0.0),
        rtol=1e-4, atol=1e-4)
    assert GroupBatchNorm2d is BatchNorm2d_NHWC


def test_groupbn_synced_stats_over_mesh(mesh8):
    """bn_group axis: stats must equal the all-batch stats."""
    x = jax.random.normal(jax.random.PRNGKey(0), (8, 4, 4, 8)) * 2 + 3
    m = BatchNorm2d_NHWC(num_features=8, bn_group="data")
    v = m.init(jax.random.PRNGKey(1), x, use_running_average=False)

    def local(xs):
        y, _ = m.apply(v, xs, use_running_average=False,
                       mutable=["batch_stats"])
        return y

    f = comm.shard_map(local, mesh8, in_specs=P("data"),
                       out_specs=P("data"))
    y = f(x)
    y_ref, _ = m.apply(v, x, use_running_average=False,
                       mutable=["batch_stats"])
    np.testing.assert_allclose(np.asarray(y), np.asarray(y_ref),
                               rtol=2e-4, atol=2e-4)


# ---------------------------------------------------------------------------
# focal loss
# ---------------------------------------------------------------------------

def _focal_oracle(x, t, npos, alpha, gamma):
    x = np.asarray(x, np.float64)
    t = np.asarray(t)
    c = x.shape[-1]
    oh = np.zeros(x.shape)
    for i in np.ndindex(t.shape):
        if t[i] >= 0:
            oh[i + (t[i],)] = 1.0
    p = 1.0 / (1.0 + np.exp(-x))
    bce = -(oh * np.log(p) + (1 - oh) * np.log(1 - p))
    pt = p * oh + (1 - p) * (1 - oh)
    at = alpha * oh + (1 - alpha) * (1 - oh)
    l = at * (1 - pt) ** gamma * bce
    l = l * (t != -2)[..., None]
    return l.sum() / max(npos, 1)


def test_focal_loss_matches_oracle():
    x = jax.random.normal(jax.random.PRNGKey(0), (16, 8)) * 2
    t = jnp.asarray([3, -1, 2, 0, -2, 7, 1, -1, 4, 5, -2, 6, 0, 2, 3, 1])
    got = focal_loss(x, t, 9, 8, 0.25, 2.0)
    want = _focal_oracle(x, t, 9, 0.25, 2.0)
    np.testing.assert_allclose(float(got), want, rtol=1e-4)


def test_focal_loss_ignore_index_no_grad():
    x = jax.random.normal(jax.random.PRNGKey(0), (4, 8))
    t = jnp.asarray([1, -2, 2, -2])
    g = jax.grad(lambda xx: focal_loss(xx, t, 2, 8, 0.25, 2.0))(x)
    assert np.all(np.asarray(g)[1] == 0.0)
    assert np.all(np.asarray(g)[3] == 0.0)
    assert np.any(np.asarray(g)[0] != 0.0)


def test_focal_loss_label_smoothing_changes_loss():
    x = jax.random.normal(jax.random.PRNGKey(0), (4, 8))
    t = jnp.asarray([1, 2, 3, 4])
    a = float(focal_loss(x, t, 4, 8, 0.25, 2.0, 0.0))
    b = float(focal_loss(x, t, 4, 8, 0.25, 2.0, 0.1))
    assert a != b


# ---------------------------------------------------------------------------
# index_mul_2d / conv_bias_relu
# ---------------------------------------------------------------------------

def test_index_mul_2d():
    in1 = jax.random.normal(jax.random.PRNGKey(0), (10, 7))
    in2 = jax.random.normal(jax.random.PRNGKey(1), (5, 7))
    idx = jnp.asarray([0, 3, 3, 9, 1])
    out = index_mul_2d(in1, in2, idx)
    want = np.asarray(in1)[np.asarray(idx)] * np.asarray(in2)
    np.testing.assert_allclose(np.asarray(out), want, rtol=1e-6)
    # backward: scatter-add into in1
    g = jax.grad(lambda a: jnp.sum(index_mul_2d(a, in2, idx)))(in1)
    want_g = np.zeros((10, 7))
    for i, j in enumerate(np.asarray(idx)):
        want_g[j] += np.asarray(in2)[i]
    np.testing.assert_allclose(np.asarray(g), want_g, rtol=1e-6)


def test_conv_bias_relu_family():
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 8, 8, 3))
    w = jax.random.normal(jax.random.PRNGKey(1), (3, 3, 3, 16)) * 0.1
    b = jax.random.normal(jax.random.PRNGKey(2), (16,)) * 0.1
    y = ConvBias.apply(x, w, b, padding=1)
    assert y.shape == (2, 8, 8, 16)
    yr = ConvBiasReLU.apply(x, w, b, padding=1)
    np.testing.assert_allclose(np.asarray(yr),
                               np.maximum(np.asarray(y), 0), rtol=1e-6)
    mask = jnp.zeros((2, 8, 8, 16)).at[:, :4].set(1.0)
    ym = ConvBiasMaskReLU.apply(x, w, b, mask, padding=1)
    assert np.all(np.asarray(ym)[:, 4:] == 0.0)
    y2 = ConvBiasReLU.apply(x, w, b, padding=1, stride=2)
    assert y2.shape == (2, 4, 4, 16)


# ---------------------------------------------------------------------------
# bottleneck + halo exchange
# ---------------------------------------------------------------------------

def test_halo_exchange_matches_neighbor_rows(mesh8):
    # 8 ranks over "data"x"model" — use the 4-wide "model" axis
    x = jnp.arange(4 * 8 * 2 * 2, dtype=jnp.float32
                   ).reshape(4, 8, 2, 2)    # (N=4, H=8, W=2, C=2)

    def f(xs):
        return halo_exchange(xs, "model", halo=1, dim=1)

    y = comm.shard_map(f, mesh8, in_specs=P(None, "model"),
                       out_specs=P(None, "model"))(x)
    # each 2-row shard grows to 4 rows; verify middle shard halos
    y = np.asarray(y).reshape(4, 4, 4, 2, 2)   # (N, shard, rows, W, C)
    xs = np.asarray(x).reshape(4, 4, 2, 2, 2)
    np.testing.assert_array_equal(y[:, 1, 0], xs[:, 0, -1])   # prev's last
    np.testing.assert_array_equal(y[:, 1, 1:3], xs[:, 1])     # own rows
    np.testing.assert_array_equal(y[:, 1, 3], xs[:, 2, 0])    # next's first
    assert np.all(y[:, 0, 0] == 0.0)        # top edge zero halo
    assert np.all(y[:, 3, 3] == 0.0)        # bottom edge zero halo


def test_bottleneck_shapes_and_residual():
    m = Bottleneck(in_channels=16, bottleneck_channels=8, out_channels=16)
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 8, 8, 16))
    v = m.init(jax.random.PRNGKey(1), x)
    y = m.apply(v, x)
    assert y.shape == x.shape
    m2 = Bottleneck(in_channels=16, bottleneck_channels=8,
                    out_channels=32, stride=2)
    v2 = m2.init(jax.random.PRNGKey(1), x)
    assert m2.apply(v2, x).shape == (2, 4, 4, 32)


def test_spatial_bottleneck_matches_unsharded(mesh8):
    """The headline: H-sharded bottleneck over the mesh == dense oracle."""
    from apex_tpu.contrib.bottleneck import SpatialBottleneck
    m = Bottleneck(in_channels=8, bottleneck_channels=4, out_channels=8)
    ms = SpatialBottleneck(in_channels=8, bottleneck_channels=4,
                           out_channels=8, spatial_group="model")
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 16, 4, 8))
    v = m.init(jax.random.PRNGKey(1), x)
    want = m.apply(v, x)

    def f(xs):
        return ms.apply(v, xs)

    y = comm.shard_map(f, mesh8, in_specs=P(None, "model"),
                       out_specs=P(None, "model"))(x)
    np.testing.assert_allclose(np.asarray(y), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


def test_spatial_bottleneck_grads_with_group_psum(mesh8):
    """The documented grad convention: param grads psum'd over
    spatial_group equal the unsharded oracle's grads (each rank's
    contribution covers only its H-shard; the reference completes them
    via DDP's world all-reduce)."""
    from apex_tpu.contrib.bottleneck import SpatialBottleneck
    m = Bottleneck(in_channels=8, bottleneck_channels=4, out_channels=8)
    ms = SpatialBottleneck(in_channels=8, bottleneck_channels=4,
                           out_channels=8, spatial_group="model")
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 16, 4, 8))
    v = m.init(jax.random.PRNGKey(1), x)

    def loss_sharded(v, xs):
        return jnp.sum(ms.apply(v, xs).astype(jnp.float32) ** 2)

    def step(v, xs):
        g = jax.grad(loss_sharded)(v, xs)
        return jax.tree_util.tree_map(
            lambda t: jax.lax.psum(t, "model"), g)

    g = jax.jit(comm.shard_map(
        step, mesh8, in_specs=(P(), P(None, "model")),
        out_specs=P()))(v, x)
    g_ref = jax.grad(
        lambda v: jnp.sum(m.apply(v, x).astype(jnp.float32) ** 2))(v)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-3, atol=2e-4),
        g, g_ref)
