"""Pallas multi-tensor kernels vs jnp oracles.

Mirrors the reference's dominant test pattern (SURVEY.md §4): fused kernel
vs stock oracle, allclose under per-dtype tolerances, over a small
shape x dtype grid.  Kernels run in interpreter mode on CPU.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.ops import multi_tensor as mt
from apex_tpu.multi_tensor_apply import (flatten, unflatten,
                                         multi_tensor_applier)

SIZES = [1, 100, 128, 1024, 5000]
DTYPES = [jnp.float32, jnp.bfloat16]


def tol(dtype):
    return dict(rtol=2e-2, atol=2e-2) if dtype == jnp.bfloat16 \
        else dict(rtol=1e-6, atol=1e-6)


@pytest.mark.parametrize("n", SIZES)
@pytest.mark.parametrize("dtype", DTYPES)
def test_flat_scale(n, dtype):
    x = jax.random.normal(jax.random.key(0), (n,), jnp.float32).astype(dtype)
    out, flag = mt.flat_scale(x, 2.5)
    ref, rflag = mt.flat_scale_ref(x, 2.5)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32), **tol(dtype))
    assert int(flag) == int(rflag) == 0


def test_flat_scale_detects_inf():
    x = jnp.array([1.0, jnp.inf, 3.0], jnp.float32)
    _, flag = mt.flat_scale(x, 1.0)
    assert int(flag) == 1
    x = jnp.array([1.0, jnp.nan], jnp.float32)
    _, flag = mt.flat_scale(x, 1.0)
    assert int(flag) == 1


@pytest.mark.parametrize("n", SIZES)
def test_flat_axpby(n):
    k1, k2 = jax.random.split(jax.random.key(1))
    x = jax.random.normal(k1, (n,))
    y = jax.random.normal(k2, (n,))
    out, flag = mt.flat_axpby(0.5, x, -1.5, y)
    ref, _ = mt.flat_axpby_ref(0.5, x, -1.5, y)
    np.testing.assert_allclose(out, ref, rtol=1e-6)
    assert int(flag) == 0


@pytest.mark.parametrize("n", SIZES)
def test_flat_l2norm(n):
    x = jax.random.normal(jax.random.key(2), (n,))
    got = mt.flat_l2norm(x)
    want = mt.flat_l2norm_ref(x)
    np.testing.assert_allclose(got, want, rtol=1e-5)


@pytest.mark.parametrize("adam_w", [True, False])
@pytest.mark.parametrize("dtype", DTYPES)
def test_flat_adam_matches_ref(adam_w, dtype):
    n = 3000
    keys = jax.random.split(jax.random.key(3), 4)
    p = jax.random.normal(keys[0], (n,), jnp.float32).astype(dtype)
    g = jax.random.normal(keys[1], (n,), jnp.float32).astype(dtype)
    m = jnp.zeros((n,), jnp.float32)
    v = jnp.zeros((n,), jnp.float32)
    kw = dict(lr=1e-3, beta1=0.9, beta2=0.999, eps=1e-8,
              weight_decay=0.01, step=1, adam_w_mode=adam_w)
    po, mo, vo = mt.flat_adam(p, g, m, v, **kw)
    pr, mr, vr = mt.flat_adam_ref(p, g, m, v, **kw)
    np.testing.assert_allclose(np.asarray(po, np.float32),
                               np.asarray(pr, np.float32), **tol(dtype))
    np.testing.assert_allclose(mo, mr, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(vo, vr, rtol=1e-5, atol=1e-6)


def test_flat_adam_matches_torch_adamw():
    torch = pytest.importorskip("torch")
    n = 512
    rng = np.random.RandomState(0)
    p0 = rng.randn(n).astype(np.float32)
    g0 = rng.randn(n).astype(np.float32)

    tp = torch.nn.Parameter(torch.tensor(p0))
    opt = torch.optim.AdamW([tp], lr=1e-3, betas=(0.9, 0.999), eps=1e-8,
                            weight_decay=0.01)
    tp.grad = torch.tensor(g0)
    opt.step()

    p = jnp.asarray(p0)
    g = jnp.asarray(g0)
    m = jnp.zeros((n,), jnp.float32)
    v = jnp.zeros((n,), jnp.float32)
    po, _, _ = mt.flat_adam(p, g, m, v, lr=1e-3, beta1=0.9, beta2=0.999,
                            eps=1e-8, weight_decay=0.01, step=1,
                            adam_w_mode=True)
    np.testing.assert_allclose(np.asarray(po), tp.detach().numpy(),
                               rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("momentum,nesterov", [(0.0, False), (0.9, False),
                                               (0.9, True)])
def test_flat_sgd_matches_torch(momentum, nesterov):
    torch = pytest.importorskip("torch")
    n = 257
    rng = np.random.RandomState(1)
    p0 = rng.randn(n).astype(np.float32)

    tp = torch.nn.Parameter(torch.tensor(p0))
    opt = torch.optim.SGD([tp], lr=0.1, momentum=momentum,
                          nesterov=nesterov, weight_decay=1e-4)
    p = jnp.asarray(p0)
    buf = jnp.zeros((n,), jnp.float32)
    for step in range(3):
        g0 = rng.randn(n).astype(np.float32)
        tp.grad = torch.tensor(g0)
        opt.step()
        p, buf = mt.flat_sgd(p, jnp.asarray(g0), buf, lr=0.1,
                             momentum=momentum, nesterov=nesterov,
                             weight_decay=1e-4, first_run=(step == 0))
    np.testing.assert_allclose(np.asarray(p), tp.detach().numpy(),
                               rtol=1e-5, atol=1e-6)


def test_flatten_unflatten_roundtrip():
    ts = [jnp.arange(6.0).reshape(2, 3), jnp.ones((4,)), jnp.zeros((1, 1))]
    flat = flatten(ts)
    assert flat.shape == (11,)
    back = unflatten(flat, ts)
    for a, b in zip(ts, back):
        np.testing.assert_array_equal(a, b)


def test_multi_tensor_applier_scale():
    ts = [jnp.full((5,), 2.0), jnp.full((3, 3), -1.0)]
    outs, flag = multi_tensor_applier(mt.flat_scale, None, [ts], 3.0)
    np.testing.assert_allclose(outs[0], jnp.full((5,), 6.0))
    np.testing.assert_allclose(outs[1], jnp.full((3, 3), -3.0))
    assert int(flag) == 0


class TestDispatchPrefs:
    """Measure-aware dispatch (VERDICT r2 #2): the preference table and
    env overrides gate each kernel family onto Pallas or the XLA path."""

    def test_default_prefers_pallas(self, monkeypatch):
        from apex_tpu.ops import _dispatch
        monkeypatch.setattr(_dispatch, "_PREFS", {})
        monkeypatch.delenv("APEX_TPU_PREFER_XLA", raising=False)
        monkeypatch.delenv("APEX_TPU_PREFER_PALLAS", raising=False)
        assert _dispatch.op_enabled("layer_norm")
        assert _dispatch.op_enabled("never-measured-op")

    def test_measured_loss_flips_to_xla(self, monkeypatch):
        from apex_tpu.ops import _dispatch
        monkeypatch.setattr(_dispatch, "_PREFS", {"softmax": False,
                                                  "attention": True})
        assert not _dispatch.op_enabled("softmax")
        assert _dispatch.op_enabled("attention")

    def test_env_overrides_beat_table(self, monkeypatch):
        from apex_tpu.ops import _dispatch
        monkeypatch.setattr(_dispatch, "_PREFS", {"softmax": False})
        monkeypatch.setenv("APEX_TPU_PREFER_PALLAS", "softmax")
        assert _dispatch.op_enabled("softmax")
        monkeypatch.setenv("APEX_TPU_PREFER_XLA", "layer_norm, xentropy")
        assert not _dispatch.op_enabled("layer_norm")
        assert not _dispatch.op_enabled("xentropy")

    def test_disabled_pallas_wins_over_everything(self, monkeypatch):
        from apex_tpu.ops import _dispatch
        monkeypatch.setenv("APEX_TPU_DISABLE_PALLAS", "1")
        monkeypatch.setenv("APEX_TPU_PREFER_PALLAS", "softmax")
        assert not _dispatch.op_enabled("softmax")

    def test_xla_pref_routes_layer_norm_to_oracle(self, monkeypatch):
        """The gate actually changes the computed path: with layer_norm
        preferred to XLA, fused_layer_norm still computes correctly
        (through the reference path) and no pallas_call appears."""
        import jax
        import jax.numpy as jnp
        import numpy as np
        from apex_tpu.ops import _dispatch, layer_norm as ln

        x = jax.random.normal(jax.random.key(0), (16, 256))
        w = jnp.ones((256,)); b = jnp.zeros((256,))
        want = ln.layer_norm_ref(x, w, b)
        monkeypatch.setattr(_dispatch, "_PREFS", {"layer_norm": False})
        got = ln.fused_layer_norm(x, w, b)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-6, atol=1e-6)
        jx = jax.make_jaxpr(lambda t: ln.fused_layer_norm(t, w, b))(x)
        prims = {e.primitive.name for e in jx.jaxpr.eqns}
        assert "pallas_call" not in prims, prims

    def test_prefs_written_from_rows(self, tmp_path):
        import importlib, json as _json, os as _os
        tools = _os.path.abspath(_os.path.join(
            _os.path.dirname(__file__), "..", "tools"))
        import sys as _sys
        _sys.path.insert(0, tools)
        try:
            kb = importlib.import_module("kernel_bench")
        finally:
            _sys.path.remove(tools)
        rows = [
            {"kernel": "fused_layer_norm", "speedup": 1.4, "backend": "tpu"},
            {"kernel": "fused_layer_norm_grad", "speedup": 0.8,
             "backend": "tpu"},
            {"kernel": "flash_attention", "speedup": 2.0, "backend": "tpu"},
            {"kernel": "int8_matmul_weight_only", "speedup": 1.9,
             "backend": "tpu"},               # not a dispatch family
            {"kernel": "flat_adam", "speedup": None, "backend": "tpu"},
        ]
        p = tmp_path / "prefs.json"
        prefs = kb.write_prefs(rows, str(p))
        data = _json.loads(p.read_text())
        # one slow shape disables the family; missing speedups ignored
        assert prefs == {"layer_norm": False, "attention": True}
        assert data["prefer_pallas"] == prefs
        assert data["methodology"] == "amortized"


@pytest.mark.parametrize("dtype", DTYPES)
def test_flat_adagrad_matches_ref(dtype):
    n = 2000
    keys = jax.random.split(jax.random.key(4), 2)
    p = jax.random.normal(keys[0], (n,), jnp.float32).astype(dtype)
    g = jax.random.normal(keys[1], (n,), jnp.float32).astype(dtype)
    h = jnp.abs(jax.random.normal(jax.random.key(5), (n,))) * 0.1
    kw = dict(lr=1e-2, eps=1e-10, weight_decay=0.01)
    po, ho = mt.flat_adagrad(p, g, h, **kw)
    pr, hr = mt.flat_adagrad_ref(p, g, h, **kw)
    np.testing.assert_allclose(np.asarray(po, np.float32),
                               np.asarray(pr, np.float32), **tol(dtype))
    np.testing.assert_allclose(ho, hr, rtol=1e-5, atol=1e-6)


def _segmented_buffers(n_leaves=4, key=6):
    sizes = [257, 128, 1000, 5]
    n = sum(sizes)
    seg = jnp.asarray(np.repeat(np.arange(n_leaves, dtype=np.int32),
                                sizes))
    ks = jax.random.split(jax.random.key(key), 4)
    p = jax.random.normal(ks[0], (n,))
    g = jax.random.normal(ks[1], (n,))
    m = jax.random.normal(ks[2], (n,)) * 0.1
    v = jnp.abs(jax.random.normal(ks[3], (n,))) * 0.1
    return p, g, m, v, seg, n_leaves


@pytest.mark.parametrize("use_nvlamb", [False, True])
def test_flat_lamb_matches_ref(use_nvlamb):
    p, g, m, v, seg, ns = _segmented_buffers()
    kw = dict(lr=1e-3, beta1=0.9, beta2=0.999, eps=1e-6,
              weight_decay=0.01, step=3, clip_coeff=0.7,
              use_nvlamb=use_nvlamb)
    po, mo, vo = mt.flat_lamb(p, g, m, v, seg, ns, **kw)
    pr, mr, vr = mt.flat_lamb_ref(p, g, m, v, seg, ns, **kw)
    np.testing.assert_allclose(po, pr, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(mo, mr, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(vo, vr, rtol=1e-5, atol=1e-6)


def test_flat_lamb_trust_ratio_is_per_segment():
    """The segmented kernel must reproduce the per-leaf trust ratios —
    not one bucket-global ratio."""
    from apex_tpu.optimizers import _functional as F
    p, g, m, v, seg, ns = _segmented_buffers()
    sizes = [257, 128, 1000, 5]
    kw = dict(lr=1e-3, beta1=0.9, beta2=0.999, eps=1e-6,
              weight_decay=0.01, step=3)
    po, _, _ = mt.flat_lamb(p, g, m, v, seg, ns, **kw)
    o = 0
    for sz in sizes:
        sl = slice(o, o + sz)
        pe, _, _ = F.lamb_step(p[sl], g[sl], m[sl], v[sl], **kw)
        np.testing.assert_allclose(po[sl], pe, rtol=1e-5, atol=1e-6)
        o += sz


@pytest.mark.parametrize("first_run", [True, False])
def test_flat_novograd_matches_per_leaf(first_run):
    from apex_tpu.optimizers import _functional as F
    p, g, m, _, seg, ns = _segmented_buffers(key=8)
    sizes = [257, 128, 1000, 5]
    vseg = jnp.abs(jax.random.normal(jax.random.key(9), (ns,))) * 0.2
    kw = dict(lr=1e-3, beta1=0.95, beta2=0.98, eps=1e-8,
              weight_decay=0.01, first_run=first_run)
    po, mo, vo = mt.flat_novograd(p, g, m, vseg, seg, **kw)
    pr, mr, vr = mt.flat_novograd_ref(p, g, m, vseg, seg, **kw)
    np.testing.assert_allclose(po, pr, rtol=1e-5, atol=1e-6)
    o = 0
    for i, sz in enumerate(sizes):
        sl = slice(o, o + sz)
        pe, me, ve = F.novograd_step(p[sl], g[sl], m[sl], vseg[i], **kw)
        np.testing.assert_allclose(po[sl], pe, rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(vo[i], ve, rtol=1e-5, atol=1e-6)
        o += sz


def test_flat_sgd_traced_first_run():
    """first_run may be a traced bool (step == 1 inside a jitted
    optimizer step) on both the kernel and the ref path."""
    n = 300
    p = jax.random.normal(jax.random.key(0), (n,))
    g = jax.random.normal(jax.random.key(1), (n,))
    buf = jax.random.normal(jax.random.key(2), (n,))
    kw = dict(lr=0.1, momentum=0.9, weight_decay=1e-4)

    @jax.jit
    def step(p, g, buf, count):
        return mt.flat_sgd(p, g, buf, first_run=count == 1, **kw)

    for count, want_first in ((1, True), (2, False)):
        po, bo = step(p, g, buf, jnp.int32(count))
        pr, br = mt.flat_sgd_ref(p, g, buf, first_run=want_first, **kw)
        np.testing.assert_allclose(po, pr, rtol=1e-6, atol=1e-7)
        np.testing.assert_allclose(bo, br, rtol=1e-6, atol=1e-7)


class TestMultiTensorApplierMixedDtype:
    """The reference dispatches per dtype group; extras (overflow flags,
    norms) combine across groups — flags by max, norms by rss."""

    def test_mixed_dtype_scale_groups_and_flags(self):
        ts = [jnp.full((5,), 2.0, jnp.float32),
              jnp.full((3, 3), -1.0, jnp.bfloat16),
              jnp.full((7,), 4.0, jnp.float32)]
        outs, flag = multi_tensor_applier(mt.flat_scale, None, [ts], 3.0)
        assert [o.dtype for o in outs] == [t.dtype for t in ts]
        np.testing.assert_allclose(np.asarray(outs[0]), 6.0)
        np.testing.assert_allclose(np.asarray(outs[1], np.float32), -3.0)
        np.testing.assert_allclose(np.asarray(outs[2]), 12.0)
        assert int(flag) == 0

    def test_mixed_dtype_flag_combines_by_max(self):
        ts = [jnp.ones((4,), jnp.float32),
              jnp.array([1.0, jnp.inf], jnp.bfloat16)]
        _, flag = multi_tensor_applier(mt.flat_scale, None, [ts], 1.0)
        assert int(flag) == 1

    def test_mixed_dtype_norm_combines_by_rss(self):
        ts = [jnp.full((4,), 3.0, jnp.float32),
              jnp.full((4,), 4.0, jnp.bfloat16)]
        (norm,) = multi_tensor_applier(mt.flat_l2norm, None, [ts])
        want = np.sqrt(sum(float(jnp.sum(t.astype(jnp.float32) ** 2))
                           for t in ts))
        np.testing.assert_allclose(float(norm), want, rtol=1e-3)
