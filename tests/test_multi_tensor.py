"""Pallas multi-tensor kernels vs jnp oracles.

Mirrors the reference's dominant test pattern (SURVEY.md §4): fused kernel
vs stock oracle, allclose under per-dtype tolerances, over a small
shape x dtype grid.  Kernels run in interpreter mode on CPU.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.ops import multi_tensor as mt
from apex_tpu.multi_tensor_apply import (flatten, unflatten,
                                         multi_tensor_applier)

SIZES = [1, 100, 128, 1024, 5000]
DTYPES = [jnp.float32, jnp.bfloat16]


def tol(dtype):
    return dict(rtol=2e-2, atol=2e-2) if dtype == jnp.bfloat16 \
        else dict(rtol=1e-6, atol=1e-6)


@pytest.mark.parametrize("n", SIZES)
@pytest.mark.parametrize("dtype", DTYPES)
def test_flat_scale(n, dtype):
    x = jax.random.normal(jax.random.key(0), (n,), jnp.float32).astype(dtype)
    out, flag = mt.flat_scale(x, 2.5)
    ref, rflag = mt.flat_scale_ref(x, 2.5)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32), **tol(dtype))
    assert int(flag) == int(rflag) == 0


def test_flat_scale_detects_inf():
    x = jnp.array([1.0, jnp.inf, 3.0], jnp.float32)
    _, flag = mt.flat_scale(x, 1.0)
    assert int(flag) == 1
    x = jnp.array([1.0, jnp.nan], jnp.float32)
    _, flag = mt.flat_scale(x, 1.0)
    assert int(flag) == 1


@pytest.mark.parametrize("n", SIZES)
def test_flat_axpby(n):
    k1, k2 = jax.random.split(jax.random.key(1))
    x = jax.random.normal(k1, (n,))
    y = jax.random.normal(k2, (n,))
    out, flag = mt.flat_axpby(0.5, x, -1.5, y)
    ref, _ = mt.flat_axpby_ref(0.5, x, -1.5, y)
    np.testing.assert_allclose(out, ref, rtol=1e-6)
    assert int(flag) == 0


@pytest.mark.parametrize("n", SIZES)
def test_flat_l2norm(n):
    x = jax.random.normal(jax.random.key(2), (n,))
    got = mt.flat_l2norm(x)
    want = mt.flat_l2norm_ref(x)
    np.testing.assert_allclose(got, want, rtol=1e-5)


@pytest.mark.parametrize("adam_w", [True, False])
@pytest.mark.parametrize("dtype", DTYPES)
def test_flat_adam_matches_ref(adam_w, dtype):
    n = 3000
    keys = jax.random.split(jax.random.key(3), 4)
    p = jax.random.normal(keys[0], (n,), jnp.float32).astype(dtype)
    g = jax.random.normal(keys[1], (n,), jnp.float32).astype(dtype)
    m = jnp.zeros((n,), jnp.float32)
    v = jnp.zeros((n,), jnp.float32)
    kw = dict(lr=1e-3, beta1=0.9, beta2=0.999, eps=1e-8,
              weight_decay=0.01, step=1, adam_w_mode=adam_w)
    po, mo, vo = mt.flat_adam(p, g, m, v, **kw)
    pr, mr, vr = mt.flat_adam_ref(p, g, m, v, **kw)
    np.testing.assert_allclose(np.asarray(po, np.float32),
                               np.asarray(pr, np.float32), **tol(dtype))
    np.testing.assert_allclose(mo, mr, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(vo, vr, rtol=1e-5, atol=1e-6)


def test_flat_adam_matches_torch_adamw():
    torch = pytest.importorskip("torch")
    n = 512
    rng = np.random.RandomState(0)
    p0 = rng.randn(n).astype(np.float32)
    g0 = rng.randn(n).astype(np.float32)

    tp = torch.nn.Parameter(torch.tensor(p0))
    opt = torch.optim.AdamW([tp], lr=1e-3, betas=(0.9, 0.999), eps=1e-8,
                            weight_decay=0.01)
    tp.grad = torch.tensor(g0)
    opt.step()

    p = jnp.asarray(p0)
    g = jnp.asarray(g0)
    m = jnp.zeros((n,), jnp.float32)
    v = jnp.zeros((n,), jnp.float32)
    po, _, _ = mt.flat_adam(p, g, m, v, lr=1e-3, beta1=0.9, beta2=0.999,
                            eps=1e-8, weight_decay=0.01, step=1,
                            adam_w_mode=True)
    np.testing.assert_allclose(np.asarray(po), tp.detach().numpy(),
                               rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("momentum,nesterov", [(0.0, False), (0.9, False),
                                               (0.9, True)])
def test_flat_sgd_matches_torch(momentum, nesterov):
    torch = pytest.importorskip("torch")
    n = 257
    rng = np.random.RandomState(1)
    p0 = rng.randn(n).astype(np.float32)

    tp = torch.nn.Parameter(torch.tensor(p0))
    opt = torch.optim.SGD([tp], lr=0.1, momentum=momentum,
                          nesterov=nesterov, weight_decay=1e-4)
    p = jnp.asarray(p0)
    buf = jnp.zeros((n,), jnp.float32)
    for step in range(3):
        g0 = rng.randn(n).astype(np.float32)
        tp.grad = torch.tensor(g0)
        opt.step()
        p, buf = mt.flat_sgd(p, jnp.asarray(g0), buf, lr=0.1,
                             momentum=momentum, nesterov=nesterov,
                             weight_decay=1e-4, first_run=(step == 0))
    np.testing.assert_allclose(np.asarray(p), tp.detach().numpy(),
                               rtol=1e-5, atol=1e-6)


def test_flatten_unflatten_roundtrip():
    ts = [jnp.arange(6.0).reshape(2, 3), jnp.ones((4,)), jnp.zeros((1, 1))]
    flat = flatten(ts)
    assert flat.shape == (11,)
    back = unflatten(flat, ts)
    for a, b in zip(ts, back):
        np.testing.assert_array_equal(a, b)


def test_multi_tensor_applier_scale():
    ts = [jnp.full((5,), 2.0), jnp.full((3, 3), -1.0)]
    outs, flag = multi_tensor_applier(mt.flat_scale, None, [ts], 3.0)
    np.testing.assert_allclose(outs[0], jnp.full((5,), 6.0))
    np.testing.assert_allclose(outs[1], jnp.full((3, 3), -3.0))
    assert int(flag) == 0
