"""contrib.transducer vs naive DP oracle (reference test pattern:
apex/contrib/test/transducer/test_transducer_joint.py /
test_transducer_loss.py — kernel vs reference python impl)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.contrib.transducer import TransducerJoint, TransducerLoss
from apex_tpu.ops.transducer import (
    transducer_joint,
    transducer_loss,
    transducer_loss_ref,
)

B, T, U, V, H = 3, 10, 6, 8, 16   # U = max_y + 1


def _loss_data(seed=0):
    k1, k2 = jax.random.split(jax.random.PRNGKey(seed))
    x = jax.random.normal(k1, (B, T, U, V), jnp.float32)
    label = jax.random.randint(k2, (B, U - 1), 1, V)
    f_len = jnp.asarray([T, T - 3, T - 1])
    y_len = jnp.asarray([U - 1, U - 2, U - 3])
    return x, label, f_len, y_len


def test_joint_broadcast_add_and_relu():
    f = jax.random.normal(jax.random.PRNGKey(0), (B, T, H))
    g = jax.random.normal(jax.random.PRNGKey(1), (B, U, H))
    h = transducer_joint(f, g)
    assert h.shape == (B, T, U, H)
    want = np.asarray(f)[:, :, None, :] + np.asarray(g)[:, None, :, :]
    np.testing.assert_allclose(np.asarray(h), want, rtol=1e-6)
    h_relu = transducer_joint(f, g, relu=True)
    np.testing.assert_allclose(np.asarray(h_relu), np.maximum(want, 0),
                               rtol=1e-6)


def test_joint_masks_padded_cells():
    f = jnp.ones((B, T, H))
    g = jnp.ones((B, U, H))
    f_len = jnp.asarray([T, 4, T])
    g_len = jnp.asarray([U, U, 2])
    h = TransducerJoint(pack_output=True)(f, g, f_len, g_len)
    assert np.all(np.asarray(h[1, 4:]) == 0.0)
    assert np.all(np.asarray(h[2, :, 2:]) == 0.0)
    assert np.all(np.asarray(h[0]) == 2.0)


def test_loss_matches_dp_oracle():
    x, label, f_len, y_len = _loss_data()
    got = transducer_loss(x, label, f_len, y_len)
    want = transducer_loss_ref(x, label, f_len, y_len)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-4)


def test_loss_nonzero_blank_idx():
    x, label, f_len, y_len = _loss_data(seed=3)
    label = jnp.where(label == 2, 3, label)    # keep blank=2 out of labels
    got = transducer_loss(x, label, f_len, y_len, blank_idx=2)
    want = transducer_loss_ref(x, label, f_len, y_len, blank_idx=2)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-4)


def test_loss_grad_is_finite_and_correct_vs_numerical():
    x, label, f_len, y_len = _loss_data(seed=1)
    g = jax.grad(lambda xx: jnp.sum(
        transducer_loss(xx, label, f_len, y_len)))(x)
    assert np.all(np.isfinite(np.asarray(g)))
    # numerical check in f64 (f32 finite differences are below noise)
    with jax.enable_x64(True):
        x64 = x.astype(jnp.float64)
        loss_fn = lambda xx: jnp.sum(  # noqa: E731
            transducer_loss(xx, label, f_len, y_len))
        g64 = jax.grad(loss_fn)(x64)
        rng = np.random.RandomState(0)
        for _ in range(6):
            idx = tuple(rng.randint(0, s) for s in x.shape)
            eps = 1e-6
            num = (float(loss_fn(x64.at[idx].add(eps)))
                   - float(loss_fn(x64.at[idx].add(-eps)))) / (2 * eps)
            np.testing.assert_allclose(float(g64[idx]), num, rtol=1e-4,
                                       atol=1e-7)
        # and the f32 analytic grad tracks the f64 one
        np.testing.assert_allclose(np.asarray(g), np.asarray(g64),
                                   rtol=1e-3, atol=1e-4)


def test_loss_grad_zero_outside_valid_region():
    x, label, f_len, y_len = _loss_data(seed=2)
    g = jax.grad(lambda xx: jnp.sum(
        transducer_loss(xx, label, f_len, y_len)))(x)
    # example 1 has f_len = T-3: frames beyond it must not matter
    assert np.all(np.asarray(g)[1, int(f_len[1]):] == 0.0)


def test_loss_facade_jits():
    x, label, f_len, y_len = _loss_data()
    loss = jax.jit(TransducerLoss())(x, label, f_len, y_len)
    assert loss.shape == (B,)
    assert np.all(np.isfinite(np.asarray(loss)))
