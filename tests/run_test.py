"""Reference-shaped test driver (reference: tests/L0/run_test.py, which
selects suites like run_amp / run_optimizers / run_fused_layer_norm /
run_transformer — SURVEY.md §4).

This repo's suites are plain pytest; this driver maps the reference's
suite names onto them so the reference's invocation habit
(`python tests/run_test.py --include run_amp`) keeps working.

    python tests/run_test.py                      # fast tier (default)
    python tests/run_test.py --tier full          # everything (nightly)
    python tests/run_test.py --include run_amp run_optimizers

Tiers (VERDICT r2 #9): the default FAST tier excludes tests marked
``slow`` (integration-weight suites, listed centrally in
tests/conftest.py) and round-trips in ~5 minutes on the 1-core CI box;
the FULL tier runs everything and is the nightly/pre-merge bar.
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys

SUITES = {
    "run_amp": ["tests/test_amp.py", "tests/test_amp_wrap.py",
                "tests/test_amp_flat_pipeline.py",
                "tests/test_grad_accum.py",
                "tests/test_fp8.py",
                "tests/test_L1_trajectory.py",
                "tests/test_torch_amp.py"],
    "run_optimizers": ["tests/test_multi_tensor.py",
                       "tests/test_optimizers.py",
                       "tests/test_bucketed_optimizers.py",
                       "tests/test_distributed_optimizers.py"],
    "run_fused_layer_norm": ["tests/test_fused_layer_norm.py"],
    "run_fused_softmax": ["tests/test_fused_softmax_rope.py"],
    "run_mlp": ["tests/test_fused_dense.py"],
    "run_transformer": ["tests/test_tensor_parallel.py",
                        "tests/test_pipeline_parallel.py",
                        "tests/test_comm.py", "tests/test_moe.py",
                        "tests/test_microbatches.py"],
    "run_fp16util": ["tests/test_fp16_rnn_reparam.py"],
    "run_attention": ["tests/test_attention.py",
                      "tests/test_contrib_multihead_attn.py"],
    "run_contrib": ["tests/test_contrib_xentropy_clipgrad.py",
                    "tests/test_contrib_transducer.py",
                    "tests/test_contrib_misc.py",
                    "tests/test_sparsity_pyprof.py"],
    "run_distributed": ["tests/test_parallel.py",
                        "tests/test_wgrad.py",
                        "tests/test_distributed_launch.py"],
    "run_checkpoint": ["tests/test_native_checkpoint.py",
                       "tests/test_resilience.py",
                       "tests/test_fleet.py",
                       "tests/test_fleet_grow.py",
                       # incident-id correlation + the merged fleet
                       # timeline (telemetry timeline CLI)
                       "tests/test_incident_timeline.py"],
    "run_models": ["tests/test_models.py"],
    "run_examples": ["tests/test_examples_smoke.py"],
    "run_data": ["tests/test_data.py"],
    "run_offload": ["tests/test_offload.py"],
    "run_quantization": ["tests/test_quantization.py"],
    # harness/tooling logic (platform select, amortized timer, the
    # kernel-bench distillers that write dispatch defaults, and the
    # autotuner + per-topology dispatch tables + perf_gate auto mode)
    "run_harness": ["tests/test_platform.py", "tests/test_benchlib.py",
                    "tests/test_kernel_bench_logic.py",
                    "tests/test_autotune.py"],
    "run_lint": ["tests/test_lint.py"],
    # apexverify: jaxpr-level invariant specs over the public jitted
    # entry points + the findings-baseline diff gate (tools/check.sh)
    "run_lint_semantic": ["tests/test_lint_semantic.py"],
    # apexrace: thread-root/shared-state/lock-domain analysis over the
    # whole package + the races it surfaced (regression tests)
    "run_lint_concurrency": ["tests/test_lint_concurrency.py"],
    # apexcost: donation-aware liveness cost cards + the committed
    # ledger diff gate + the ddp telemetry cross-check
    "run_lint_cost": ["tests/test_lint_cost.py"],
    # the serving path: paged KV arena, AOT prefill/decode programs,
    # the continuous-batching engine and its chaos matrix (hung
    # decode, shed, drain, replica failover)
    "run_serving": ["tests/test_serving.py",
                    # request-level lifecycle traces + SLO histograms
                    # (gap-free under chaos, cross-host failover lanes)
                    "tests/test_reqtrace.py"],
    # run-time training telemetry (metric ring, emitters, spans,
    # retrace counter) + the pyprof nvtx/prof satellites + the live
    # /metrics exporter
    "run_telemetry": ["tests/test_telemetry.py",
                      "tests/test_export.py"],
    # the performance observatory: trace parsing, attribution/overlap,
    # cost-model MFU, report CLI, and the perf regression gate
    "run_profiler": ["tests/test_profiler.py"],
    # AOT Mosaic lowering for the TPU platform — runs in CPU CI
    "run_tpu_lowering": ["tests/test_tpu_lowering.py"],
    # TPU-only: needs APEX_TPU_SMOKE=1 and a real chip (else skips)
    "run_tpu_smoke": ["tests/test_tpu_smoke.py"],
}


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--include", nargs="+", default=None,
                   help=f"suites: {sorted(SUITES)}")
    p.add_argument("--exclude", nargs="*", default=[])
    p.add_argument("--tier", choices=("fast", "full"), default="fast",
                   help="fast (default): skip @slow tests; "
                        "full: run everything (nightly bar)")
    args, passthrough = p.parse_known_args()

    names = args.include if args.include else sorted(SUITES)
    unknown = [n for n in names + args.exclude if n not in SUITES]
    if unknown:
        p.error(f"unknown suites {unknown}; available: {sorted(SUITES)}")
    files: list = []
    for n in names:
        if n not in args.exclude:
            files += SUITES[n]
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    tier = ["-m", "not slow"] if args.tier == "fast" else []
    cmd = [sys.executable, "-m", "pytest", "-q", *tier, *files,
           *passthrough]
    print(" ".join(cmd))
    sys.exit(subprocess.call(cmd, cwd=root))


if __name__ == "__main__":
    main()
