"""TPU smoke suite: every Pallas kernel under a REAL Mosaic compile.

VERDICT.md round 1, Weak #2: all 199 CPU tests run the kernels with
``interpret=True``; nothing proved the lane/tiling/VMEM assumptions on
hardware.  This suite runs each kernel non-interpreted on the device
against its jnp reference, across the bench-relevant shapes.

Run with:  APEX_TPU_SMOKE=1 python -m pytest tests/test_tpu_smoke.py -v
(skipped entirely when the backend is not a real TPU; the default
``pytest tests/`` run forces CPU in conftest and skips these).

Reference test model: tests/L0 oracle pattern (SURVEY.md §4) — fused
kernel vs stock implementation, allclose under per-dtype tolerances.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

def _on_tpu() -> bool:
    if os.environ.get("APEX_TPU_SMOKE") != "1":
        return False
    try:
        # the tunnel serves one client at a time: init can fail with
        # UNAVAILABLE if another process holds it — skip, don't error
        return jax.default_backend() == "tpu"
    except Exception:
        return False


pytestmark = pytest.mark.skipif(
    not _on_tpu(),
    reason="requires APEX_TPU_SMOKE=1 and a free, real TPU backend")


def _tol(dtype):
    return dict(rtol=2e-2, atol=2e-2) if dtype == jnp.bfloat16 else \
        dict(rtol=2e-5, atol=2e-5)


def _close(a, b, dtype=None, **kw):
    dtype = dtype or a.dtype
    tol = {**_tol(dtype), **kw}
    np.testing.assert_allclose(np.asarray(a, np.float32),
                               np.asarray(b, np.float32), **tol)


# ---------------------------------------------------------------------------
# attention
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("dtype", [jnp.bfloat16, jnp.float32])
@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("shape", [(2, 4, 512, 64), (1, 2, 2048, 128)])
def test_flash_attention_fwd(shape, causal, dtype):
    from apex_tpu.ops.attention import flash_attention, attention_ref
    b, h, s, d = shape
    ks = jax.random.split(jax.random.key(0), 3)
    q = jax.random.normal(ks[0], (b, h, s, d), dtype)
    k = jax.random.normal(ks[1], (b, h, s, d), dtype)
    v = jax.random.normal(ks[2], (b, h, s, d), dtype)
    o = jax.jit(flash_attention, static_argnums=(3,))(q, k, v, causal)
    o_ref = attention_ref(q, k, v, causal=causal)
    _close(o, o_ref, dtype)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_attention_long_seq(causal):
    """sk >= 8k must stay in the kernel (VERDICT Weak #3)."""
    from apex_tpu.ops.attention import flash_attention, attention_ref
    b, h, s, d = 1, 1, 8192, 128
    ks = jax.random.split(jax.random.key(1), 3)
    q = jax.random.normal(ks[0], (b, h, s, d), jnp.bfloat16)
    k = jax.random.normal(ks[1], (b, h, s, d), jnp.bfloat16)
    v = jax.random.normal(ks[2], (b, h, s, d), jnp.bfloat16)
    o = jax.jit(flash_attention, static_argnums=(3,))(q, k, v, causal)
    _close(o, attention_ref(q, k, v, causal=causal), jnp.bfloat16)


def test_flash_attention_long_seq_grads():
    """Pallas backward kernels at multi-block length (dq over KV grid,
    dk/dv over Q grid)."""
    from apex_tpu.ops.attention import flash_attention, attention_ref
    b, h, s, d = 1, 2, 4096, 64
    ks = jax.random.split(jax.random.key(5), 3)
    q = jax.random.normal(ks[0], (b, h, s, d), jnp.bfloat16)
    k = jax.random.normal(ks[1], (b, h, s, d), jnp.bfloat16)
    v = jax.random.normal(ks[2], (b, h, s, d), jnp.bfloat16)
    g = jax.jit(jax.grad(
        lambda q, k, v: jnp.sum(flash_attention(q, k, v, True)
                                .astype(jnp.float32) ** 2) / s,
        argnums=(0, 1, 2)))(q, k, v)
    g_ref = jax.grad(
        lambda q, k, v: jnp.sum(attention_ref(q, k, v, causal=True)
                                .astype(jnp.float32) ** 2) / s,
        argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g, g_ref):
        _close(a, b_, jnp.bfloat16, rtol=5e-2, atol=5e-2)


def test_flash_attention_segment_ids_tpu():
    """Segment masking (fmha path) under real Mosaic."""
    from apex_tpu.ops.attention import flash_attention, attention_ref
    b, h, s, d = 1, 2, 512, 64
    ks = jax.random.split(jax.random.key(6), 3)
    q = jax.random.normal(ks[0], (b, h, s, d), jnp.bfloat16)
    k = jax.random.normal(ks[1], (b, h, s, d), jnp.bfloat16)
    v = jax.random.normal(ks[2], (b, h, s, d), jnp.bfloat16)
    seg = (jnp.arange(s)[None] // 128).astype(jnp.int32)
    o = jax.jit(lambda *a: flash_attention(
        *a, segment_ids=(seg, seg)))(q, k, v)
    same = seg[:, None, :, None] == seg[:, None, None, :]
    o_ref = attention_ref(q, k, v, mask=jnp.where(same, 0.0, -1e30))
    _close(o, o_ref, jnp.bfloat16)


def test_flash_attention_grads():
    from apex_tpu.ops.attention import flash_attention, attention_ref
    b, h, s, d = 2, 2, 256, 64
    ks = jax.random.split(jax.random.key(2), 3)
    q = jax.random.normal(ks[0], (b, h, s, d), jnp.float32)
    k = jax.random.normal(ks[1], (b, h, s, d), jnp.float32)
    v = jax.random.normal(ks[2], (b, h, s, d), jnp.float32)

    def loss(f):
        return lambda q, k, v: jnp.sum(f(q, k, v, True) ** 2)

    g = jax.jit(jax.grad(loss(flash_attention), argnums=(0, 1, 2)))(q, k, v)
    g_ref = jax.grad(
        lambda q, k, v: jnp.sum(
            attention_ref(q, k, v, causal=True) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g, g_ref):
        _close(a, b_, jnp.float32, rtol=1e-3, atol=1e-3)


def test_flash_attention_gqa_grads():
    """Grouped-query attention under real Mosaic: the kernel reads the
    small K/V directly; fwd and all grads vs the repeat-kv oracle."""
    from apex_tpu.ops.attention import flash_attention, attention_ref
    b, h, hk, s, d = 1, 4, 2, 256, 64
    ks = jax.random.split(jax.random.key(9), 3)
    q = jax.random.normal(ks[0], (b, h, s, d), jnp.float32)
    k = jax.random.normal(ks[1], (b, hk, s, d), jnp.float32)
    v = jax.random.normal(ks[2], (b, hk, s, d), jnp.float32)

    o = jax.jit(lambda *a: flash_attention(*a, causal=True))(q, k, v)
    _close(o, attention_ref(q, k, v, causal=True), jnp.float32)

    def loss(f):
        return lambda q, k, v: jnp.sum(f(q, k, v, True) ** 2)

    g = jax.jit(jax.grad(loss(flash_attention),
                         argnums=(0, 1, 2)))(q, k, v)
    g_ref = jax.grad(loss(attention_ref), argnums=(0, 1, 2))(q, k, v)
    assert g[1].shape == (b, hk, s, d)
    for a, b_ in zip(g, g_ref):
        _close(a, b_, jnp.float32, rtol=1e-3, atol=1e-3)


def test_flash_attention_dropout_grads():
    """Fused hash-mask dropout under real Mosaic: kernel vs the jnp
    oracle sharing the same mask — fwd and all grads elementwise."""
    from apex_tpu.ops.attention import flash_attention, attention_ref
    b, h, s, d = 1, 2, 256, 64
    ks = jax.random.split(jax.random.key(21), 3)
    q = jax.random.normal(ks[0], (b, h, s, d), jnp.float32)
    k = jax.random.normal(ks[1], (b, h, s, d), jnp.float32)
    v = jax.random.normal(ks[2], (b, h, s, d), jnp.float32)
    seed = jnp.int32(99)
    kw = dict(causal=True, dropout_rate=0.2, dropout_seed=seed)

    o = jax.jit(lambda *a: flash_attention(*a, **kw))(q, k, v)
    _close(o, attention_ref(q, k, v, **kw), jnp.float32)

    def loss(f):
        return lambda q, k, v: jnp.sum(f(q, k, v, **kw) ** 2)

    g = jax.jit(jax.grad(loss(flash_attention),
                         argnums=(0, 1, 2)))(q, k, v)
    g_ref = jax.grad(loss(attention_ref), argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g, g_ref):
        _close(a, b_, jnp.float32, rtol=1e-3, atol=1e-3)


def test_flash_attention_gqa_dropout_segments_grads():
    """The triple composition (grouped kv heads + fused dropout +
    packed-segment masking) non-interpreted on the chip — each feature
    changes the kernel's index maps, so their interaction is its own
    Mosaic surface.  Fwd + all grads vs the oracle."""
    from apex_tpu.ops.attention import attention_ref, flash_attention
    b, h, hk, s, d = 1, 4, 2, 256, 64
    ks = jax.random.split(jax.random.key(23), 3)
    q = jax.random.normal(ks[0], (b, h, s, d), jnp.float32)
    k = jax.random.normal(ks[1], (b, hk, s, d), jnp.float32)
    v = jax.random.normal(ks[2], (b, hk, s, d), jnp.float32)
    ids = jnp.asarray(np.repeat([1, 2], [120, 136])[None, :],
                      jnp.int32)
    kw = dict(causal=True, dropout_rate=0.25,
              dropout_seed=jnp.int32(77))
    same = ids[:, None, :, None] == ids[:, None, None, :]
    mask = jnp.where(same, 0.0, -1e30)

    o = jax.jit(lambda *a: flash_attention(
        *a, segment_ids=(ids, ids), **kw))(q, k, v)
    _close(o, attention_ref(q, k, v, mask=mask, **kw), jnp.float32)

    g = jax.jit(jax.grad(
        lambda q, k, v: jnp.sum(flash_attention(
            q, k, v, segment_ids=(ids, ids), **kw) ** 2),
        argnums=(0, 1, 2)))(q, k, v)
    g_ref = jax.grad(
        lambda q, k, v: jnp.sum(attention_ref(
            q, k, v, mask=mask, **kw) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    assert g[1].shape == (b, hk, s, d)
    for a, b_ in zip(g, g_ref):
        _close(a, b_, jnp.float32, rtol=1e-3, atol=1e-3)


# ---------------------------------------------------------------------------
# layer norm / rms norm
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("dtype", [jnp.bfloat16, jnp.float32])
@pytest.mark.parametrize("h", [1024, 4096])
@pytest.mark.parametrize("rms", [False, True])
def test_norm_fwd_bwd(h, rms, dtype):
    from apex_tpu.ops import layer_norm as ln
    rows = 512
    x = jax.random.normal(jax.random.key(0), (rows, h), dtype)
    w = jax.random.normal(jax.random.key(1), (h,), dtype) * 0.1 + 1.0
    b = jax.random.normal(jax.random.key(2), (h,), dtype) * 0.1

    if rms:
        fused = lambda x, w: ln.fused_rms_norm(x, w)
        ref = lambda x, w: ln.rms_norm_ref(x, w)
        args = (x, w)
    else:
        fused = lambda x, w, b: ln.fused_layer_norm(x, w, b)
        ref = lambda x, w, b: ln.layer_norm_ref(x, w, b)
        args = (x, w, b)

    y = jax.jit(fused)(*args)
    _close(y, ref(*args), dtype)

    g = jax.jit(jax.grad(lambda *a: jnp.sum(fused(*a) ** 2),
                         argnums=tuple(range(len(args)))))(*args)
    g_ref = jax.grad(lambda *a: jnp.sum(ref(*a) ** 2),
                     argnums=tuple(range(len(args))))(*args)
    for a, b_ in zip(g, g_ref):
        _close(a, b_, dtype, rtol=5e-2 if dtype == jnp.bfloat16 else 1e-4,
               atol=5e-2 if dtype == jnp.bfloat16 else 1e-4)


# ---------------------------------------------------------------------------
# fused softmax family
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("dtype", [jnp.bfloat16, jnp.float32])
def test_scaled_masked_softmax(dtype):
    from apex_tpu.ops import softmax as sm
    b, h, sq, sk = 2, 4, 256, 256
    x = jax.random.normal(jax.random.key(0), (b, h, sq, sk), dtype)
    mask = jax.random.bernoulli(jax.random.key(1), 0.2, (b, 1, sq, sk))
    # scale is a nondiff/static arg — jitting it traced is a TypeError
    y = jax.jit(sm.scaled_masked_softmax,
                static_argnums=(2,))(x, mask, 0.83)
    _close(y, sm.scaled_masked_softmax_ref(x, mask, 0.83), dtype)


@pytest.mark.parametrize("dtype", [jnp.bfloat16, jnp.float32])
def test_scaled_upper_triang_masked_softmax(dtype):
    from apex_tpu.ops import softmax as sm
    a, sq = 8, 512
    x = jax.random.normal(jax.random.key(0), (a, sq, sq), dtype)
    y = jax.jit(sm.scaled_upper_triang_masked_softmax,
                static_argnums=(1,))(x, 0.5)
    _close(y, sm.scaled_upper_triang_masked_softmax_ref(x, 0.5), dtype)
    g = jax.jit(jax.grad(
        lambda x: jnp.sum(
            sm.scaled_upper_triang_masked_softmax(x, 0.5) ** 2)))(x)
    g_ref = jax.grad(
        lambda x: jnp.sum(
            sm.scaled_upper_triang_masked_softmax_ref(x, 0.5) ** 2))(x)
    _close(g, g_ref, dtype, rtol=5e-2 if dtype == jnp.bfloat16 else 1e-4,
           atol=5e-2 if dtype == jnp.bfloat16 else 1e-4)


# ---------------------------------------------------------------------------
# multi-tensor substrate (flat buffer kernels)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("n", [1 << 16, (1 << 20) + 123])
def test_flat_scale_axpby_l2norm(n):
    from apex_tpu.ops import multi_tensor as mt
    x = jax.random.normal(jax.random.key(0), (n,), jnp.float32)
    y = jax.random.normal(jax.random.key(1), (n,), jnp.float32)
    s = jnp.float32(0.37)
    o, flag = jax.jit(mt.flat_scale)(x, s)
    o_ref, flag_ref = mt.flat_scale_ref(x, s)
    _close(o, o_ref, jnp.float32)
    assert int(flag) == int(flag_ref) == 0
    o, flag = jax.jit(mt.flat_axpby)(0.5, x, -0.25, y)
    o_ref, _ = mt.flat_axpby_ref(0.5, x, -0.25, y)
    _close(o, o_ref, jnp.float32)
    nrm = jax.jit(mt.flat_l2norm)(x)
    _close(nrm, mt.flat_l2norm_ref(x), jnp.float32, rtol=1e-4, atol=1e-4)


def test_flat_scale_inf_flag():
    from apex_tpu.ops import multi_tensor as mt
    x = jnp.array([1.0, jnp.inf, 3.0] + [0.0] * 1021, jnp.float32)
    _, flag = jax.jit(mt.flat_scale)(x, jnp.float32(1.0))
    assert int(flag) == 1


def test_flat_adam_sgd():
    from apex_tpu.ops import multi_tensor as mt
    n = 1 << 18
    ks = jax.random.split(jax.random.key(0), 4)
    p = jax.random.normal(ks[0], (n,), jnp.float32)
    g = jax.random.normal(ks[1], (n,), jnp.float32) * 0.1
    m = jax.random.normal(ks[2], (n,), jnp.float32) * 0.01
    v = jnp.abs(jax.random.normal(ks[3], (n,), jnp.float32)) * 0.01
    kw = dict(lr=1e-3, beta1=0.9, beta2=0.999, eps=1e-8,
              weight_decay=0.01, step=7, adam_w_mode=True)
    out = jax.jit(lambda *a: mt.flat_adam(*a, **kw))(p, g, m, v)
    ref = mt.flat_adam_ref(p, g, m, v, **kw)
    for a, b_ in zip(out, ref):
        _close(a, b_, jnp.float32, rtol=1e-5, atol=1e-6)
    kw = dict(lr=0.1, momentum=0.9, dampening=0.0, weight_decay=1e-4,
              nesterov=False, first_run=False)
    out = jax.jit(lambda *a: mt.flat_sgd(*a, **kw))(p, g, m)
    ref = mt.flat_sgd_ref(p, g, m, **kw)
    for a, b_ in zip(out, ref):
        _close(a, b_, jnp.float32, rtol=1e-5, atol=1e-6)


# ---------------------------------------------------------------------------
# welford / xentropy
# ---------------------------------------------------------------------------

def test_welford():
    from apex_tpu.ops import welford as wf
    x = jax.random.normal(jax.random.key(0), (4096, 256), jnp.float32) * 3
    cnt, mean, m2 = jax.jit(wf.welford_mean_var)(x)
    cnt_r, mean_r, m2_r = wf.welford_mean_var_ref(x)
    _close(mean, mean_r, jnp.float32, rtol=1e-4, atol=1e-4)
    _close(m2, m2_r, jnp.float32, rtol=1e-3, atol=1e-3)


@pytest.mark.parametrize("dtype", [jnp.bfloat16, jnp.float32])
@pytest.mark.parametrize("smoothing", [0.0, 0.1])
def test_xentropy(dtype, smoothing):
    from apex_tpu.ops import xentropy as xe
    rows, c = 1024, 32768  # BERT-vocab scale
    logits = jax.random.normal(jax.random.key(0), (rows, c), dtype)
    labels = jax.random.randint(jax.random.key(1), (rows,), 0, c)
    loss = jax.jit(lambda l, t: xe.softmax_cross_entropy(
        l, t, smoothing=smoothing))(logits, labels)
    loss_ref = xe.softmax_cross_entropy_ref(logits, labels,
                                            smoothing=smoothing)
    _close(loss, loss_ref, dtype)
    g = jax.jit(jax.grad(lambda l: jnp.sum(
        xe.softmax_cross_entropy(l, labels, smoothing=smoothing))))(logits)
    g_ref = jax.grad(lambda l: jnp.sum(
        xe.softmax_cross_entropy_ref(l, labels,
                                     smoothing=smoothing)))(logits)
    _close(g, g_ref, dtype, rtol=5e-2 if dtype == jnp.bfloat16 else 1e-4,
           atol=5e-2 if dtype == jnp.bfloat16 else 1e-4)


# ---------------------------------------------------------------------------
# rope / transducer / wgrad (jnp+scan paths — compile-on-TPU sanity)
# ---------------------------------------------------------------------------

def test_rope():
    from apex_tpu.ops import rope as rp
    s, b, h, d = 256, 2, 4, 64
    t = jax.random.normal(jax.random.key(0), (s, b, h, d), jnp.bfloat16)
    freqs = jax.random.normal(jax.random.key(1), (s, 1, 1, d), jnp.float32)
    y = jax.jit(rp.fused_apply_rotary_pos_emb)(t, freqs)
    _close(y, rp.rope_ref(t, freqs), jnp.bfloat16)


def test_transducer_loss():
    from apex_tpu.ops import transducer as td
    b, t, u, v = 2, 16, 8, 32
    x = jax.nn.log_softmax(
        jax.random.normal(jax.random.key(0), (b, t, u + 1, v)), axis=-1)
    label = jax.random.randint(jax.random.key(1), (b, u), 1, v)
    f_len = jnp.array([t, t - 3])
    y_len = jnp.array([u, u - 2])
    loss = jax.jit(td.transducer_loss)(x, label, f_len, y_len)
    loss_ref = td.transducer_loss_ref(x, label, f_len, y_len)
    _close(loss, loss_ref, jnp.float32, rtol=1e-4, atol=1e-4)


def test_wgrad_accum():
    from apex_tpu.ops import wgrad as wg
    x = jax.random.normal(jax.random.key(0), (512, 1024), jnp.bfloat16)
    dy = jax.random.normal(jax.random.key(1), (512, 2048), jnp.bfloat16)
    main = jnp.zeros((2048, 1024), jnp.float32)
    out = jax.jit(wg.wgrad_gemm_accum_fp32)(x, dy, main)
    ref = wg.wgrad_gemm_accum_ref(x, dy, main)
    _close(out, ref, jnp.float32, rtol=1e-3, atol=1e-3)


# ---------------------------------------------------------------------------
# round-2 additions: int8 MXU matmuls, host-offload paths
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("dynamic", [False, True])
def test_int8_matmul(dynamic):
    from apex_tpu.quantization import int8_matmul, quantize_int8
    x = jax.random.normal(jax.random.key(0), (128, 512), jnp.bfloat16)
    w = jax.random.normal(jax.random.key(1), (512, 256)) * 0.1
    y = jax.jit(lambda x: int8_matmul(x, quantize_int8(w),
                                      dynamic=dynamic))(x)
    y_ref = x.astype(jnp.float32) @ w
    _close(y, y_ref, jnp.bfloat16, rtol=0.08, atol=0.15)


def test_offloaded_optimizer_fused_step():
    """offload_state on REAL hardware: state in pinned host memory,
    one-program step, numerics equal to the resident optimizer."""
    from apex_tpu.optimizers import FusedAdam
    params = {"w": jax.random.normal(jax.random.key(0), (1 << 16,))}
    g = {"w": jax.random.normal(jax.random.key(1), (1 << 16,)) * 0.01}
    ref = FusedAdam(params, lr=1e-3)
    off = FusedAdam(params, lr=1e-3, offload_state=True)
    assert off._fused_offload          # on TPU the fused path is built
    for _ in range(3):
        ref.step(g)
        off.step(g)
    _close(off.params["w"], ref.params["w"], jnp.float32,
           rtol=1e-6, atol=1e-6)
    for leaf in jax.tree_util.tree_leaves(off.opt_state):
        assert leaf.sharding.memory_kind == "pinned_host"


def test_activation_offload_grads():
    from apex_tpu.offload import checkpoint_name, offload_checkpoint
    w1 = jax.random.normal(jax.random.key(0), (256, 1024),
                           jnp.bfloat16) * 0.05
    w2 = jax.random.normal(jax.random.key(1), (1024, 256),
                           jnp.bfloat16) * 0.05
    x = jax.random.normal(jax.random.key(2), (512, 256), jnp.bfloat16)

    def block(w1, w2, x):
        h = checkpoint_name(jax.nn.gelu(
            jnp.dot(x, w1, preferred_element_type=jnp.float32)
            .astype(jnp.bfloat16)), "ffn_hidden")
        return jnp.dot(h, w2, preferred_element_type=jnp.float32)

    def loss(f):
        return lambda w1, w2, x: jnp.sum(f(w1, w2, x) ** 2)

    off = offload_checkpoint(block, offload_names=("ffn_hidden",))
    g_off = jax.jit(jax.grad(loss(off), argnums=(0, 1)))(w1, w2, x)
    g_ref = jax.jit(jax.grad(loss(block), argnums=(0, 1)))(w1, w2, x)
    # The terminal forces --xla_allow_excess_precision=true, under
    # which the UNrematerialized program may keep the f32 gelu output
    # where it only feeds a dot, while the offloaded program rounds h
    # through bf16 at the host boundary (round-4 window: every diff
    # was <= 1 bf16 ulp of the row scale; the fixed atol=0.02 flagged
    # near-zero elements).  Compare up to one bf16 rounding of each
    # ROW's dominant term — global-max scaling would grant large-row
    # slack to small rows and hide a real offload bug there.
    for a, b in zip(g_off, g_ref):
        a32 = np.asarray(a, np.float32)
        b32 = np.asarray(b, np.float32)
        row = np.max(np.abs(b32), axis=-1, keepdims=True)
        tol = 2.0 ** -7 * row + 0.02 * np.abs(b32) + 1e-6
        bad = np.abs(a32 - b32) > tol
        assert not bad.any(), (
            f"{bad.sum()} elements beyond row-scaled bf16 tolerance; "
            f"max diff {np.max(np.abs(a32 - b32)):.4g}")
