"""Fused/flash attention + ring attention vs the XLA oracle (reference
models: apex/contrib/test/multihead_attn + fmha suites)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu import comm
from apex_tpu.ops import attention as attn


def qkv(key, b=2, h=2, s=64, d=128, dtype=jnp.float32):
    ks = jax.random.split(key, 3)
    mk = lambda k: jax.random.normal(k, (b, h, s, d), jnp.float32
                                     ).astype(dtype)
    return mk(ks[0]), mk(ks[1]), mk(ks[2])


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_flash_attention_matches_ref(causal, dtype):
    q, k, v = qkv(jax.random.key(0), dtype=dtype)
    o = attn.flash_attention(q, k, v, causal)
    want = attn.attention_ref(q, k, v, causal=causal)
    tol = dict(rtol=2e-2, atol=2e-2) if dtype == jnp.bfloat16 else \
        dict(rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(o, np.float32),
                               np.asarray(want, np.float32), **tol)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_attention_grads_match_ref(causal):
    q, k, v = qkv(jax.random.key(1), s=32)

    def f(q, k, v):
        return jnp.sum(attn.flash_attention(q, k, v, causal) ** 2)

    def fr(q, k, v):
        return jnp.sum(attn.attention_ref(q, k, v, causal=causal) ** 2)

    g = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(fr, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=5e-5)


@pytest.mark.parametrize("d", [64, 80])
@pytest.mark.parametrize("causal", [False, True])
def test_flash_attention_unaligned_head_dim(causal, d):
    """Real head dims (64, 80) take the lane-padded kernel path; values
    and grads must still match the oracle."""
    q, k, v = qkv(jax.random.key(7), s=32, d=d)
    o = attn.flash_attention(q, k, v, causal)
    want = attn.attention_ref(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(o), np.asarray(want),
                               rtol=1e-5, atol=1e-5)
    g = jax.grad(lambda *a: jnp.sum(
        attn.flash_attention(*a, causal) ** 2), argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(lambda *a: jnp.sum(
        attn.attention_ref(*a, causal=causal) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=5e-5)


def test_flash_attention_cross_lengths():
    """Encoder-decoder shape: Sq != Sk."""
    kq, kk = jax.random.split(jax.random.key(2))
    q = jax.random.normal(kq, (2, 2, 24, 128))
    k = jax.random.normal(kk, (2, 2, 56, 128))
    v = jax.random.normal(jax.random.key(3), (2, 2, 56, 128))
    o = attn.flash_attention(q, k, v, False)
    want = attn.attention_ref(q, k, v)
    np.testing.assert_allclose(o, want, rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_full(causal):
    """Sequence sharded over the ctx axis == unsharded attention."""
    mesh = comm.initialize(data=2, ctx=4)
    b, h, s, d = 2, 2, 32, 16   # s sharded 4-way
    q = jax.random.normal(jax.random.key(4), (b, h, s, d))
    k = jax.random.normal(jax.random.key(5), (b, h, s, d))
    v = jax.random.normal(jax.random.key(6), (b, h, s, d))

    def f(q, k, v):
        return attn.ring_attention(q, k, v, causal=causal)

    o = jax.jit(comm.shard_map(
        f, mesh,
        in_specs=(P(None, None, comm.AXIS_CTX, None),) * 3,
        out_specs=P(None, None, comm.AXIS_CTX, None)))(q, k, v)
    want = attn.attention_ref(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(o), np.asarray(want),
                               rtol=1e-4, atol=1e-4)


def test_ring_attention_grads_match_full():
    mesh = comm.initialize(data=2, ctx=4)
    b, h, s, d = 1, 2, 16, 8
    q = jax.random.normal(jax.random.key(7), (b, h, s, d))
    k = jax.random.normal(jax.random.key(8), (b, h, s, d))
    v = jax.random.normal(jax.random.key(9), (b, h, s, d))

    def f(q, k, v):
        return jnp.sum(attn.ring_attention(q, k, v, causal=True) ** 2)

    g = jax.jit(comm.shard_map(
        jax.grad(f, argnums=(0, 1, 2)), mesh,
        in_specs=(P(None, None, comm.AXIS_CTX, None),) * 3,
        out_specs=(P(None, None, comm.AXIS_CTX, None),) * 3))(q, k, v)

    def fr(q, k, v):
        return jnp.sum(attn.attention_ref(q, k, v, causal=True) ** 2)

    gr = jax.grad(fr, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_attention_segment_ids(causal):
    """Segment masking (the fmha contract): cross-segment pairs masked,
    tokens with unmatched ids produce zero rows."""
    b, h, s, d = 2, 2, 64, 128
    q, k, v = qkv(jax.random.key(3), b=b, h=h, s=s, d=d)
    seg = jnp.concatenate([jnp.zeros((b, 24), jnp.int32),
                           jnp.ones((b, 24), jnp.int32),
                           jnp.full((b, 16), 2, jnp.int32)], axis=1)
    q_ids = jnp.where(jnp.arange(s)[None] < 56, seg, -1)
    kv_ids = jnp.where(jnp.arange(s)[None] < 56, seg, -2)
    o = attn.flash_attention(q, k, v, causal,
                             segment_ids=(q_ids, kv_ids))
    same = q_ids[:, None, :, None] == kv_ids[:, None, None, :]
    mask = jnp.where(same, 0.0, -1e30)
    want = attn.attention_ref(q, k, v, causal=causal, mask=mask)
    # fully-masked q rows: kernel gives exact zeros
    want = jnp.where((jnp.arange(s) < 56)[None, None, :, None], want, 0.0)
    np.testing.assert_allclose(np.asarray(o), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_flash_attention_segment_ids_grads():
    b, h, s, d = 1, 2, 64, 64
    q, k, v = qkv(jax.random.key(4), b=b, h=h, s=s, d=d)
    seg = (jnp.arange(s)[None] >= 32).astype(jnp.int32)
    ids = (seg, seg)
    same = seg[:, None, :, None] == seg[:, None, None, :]
    mask = jnp.where(same, 0.0, -1e30)

    g = jax.grad(lambda *a: jnp.sum(
        attn.flash_attention(*a, segment_ids=ids) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(lambda *a: jnp.sum(
        attn.attention_ref(*a, mask=mask) ** 2), argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=1e-4, atol=5e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_attention_multiblock_tiling(causal):
    """Sequences spanning multiple 128-blocks and a non-divisible
    length (footprint of the K-tiled online-softmax rework)."""
    q, k, v = qkv(jax.random.key(5), b=1, h=1, s=320, d=64)
    o = attn.flash_attention(q, k, v, causal)
    want = attn.attention_ref(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(o), np.asarray(want),
                               rtol=1e-5, atol=1e-5)
    g = jax.grad(lambda *a: jnp.sum(
        attn.flash_attention(*a, causal) ** 2), argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(lambda *a: jnp.sum(
        attn.attention_ref(*a, causal=causal) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=1e-4, atol=5e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_single_kv_fast_path_matches_generic_kernel(causal,
                                                    monkeypatch):
    """The nk==1 scratch-free forward (round 5) vs the generic online
    kernel on the SAME inputs — kernel-to-kernel, tighter than the
    oracle-tolerance grids: forcing a 128 cap makes the same s=256
    shape tile as two KV blocks through the generic body."""
    from apex_tpu.ops import _dispatch

    # pin the geometry sources: a dev-shell cap export or a measured
    # table entry would silently tile BOTH legs multi-block and the
    # comparison would cover nothing
    monkeypatch.delenv("APEX_TPU_ATTN_BLOCK_CAP", raising=False)
    monkeypatch.setattr(_dispatch, "_ATTN_CAPS", {})
    q, k, v = qkv(jax.random.key(9), b=1, h=2, s=256, d=64)

    def fwd_and_grads():
        o = attn.flash_attention(q, k, v, causal)
        g = jax.grad(lambda *a: jnp.sum(
            attn.flash_attention(*a, causal) ** 2),
            argnums=(0, 1, 2))(q, k, v)
        return (o,) + g

    assert attn._geom(q, k)[7] == 256       # bk covers skp: nk == 1
    fast = fwd_and_grads()          # default cap 512 -> nk == 1
    monkeypatch.setenv("APEX_TPU_ATTN_BLOCK_CAP", "128")
    assert attn._geom(q, k)[7] == 128       # forced: nk == 2
    generic = fwd_and_grads()
    for a, b_ in zip(fast, generic):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_kernel_matches_ring_ref(causal):
    """The flash-kernel ring == the jnp blockwise ring (fwd + grads),
    on multi-128-block per-shard lengths."""
    b, h, s, d = 1, 2, 8 * 256, 64     # 256 tokens per ctx shard
    ks = jax.random.split(jax.random.key(9), 3)
    q = jax.random.normal(ks[0], (b, h, s, d))
    k = jax.random.normal(ks[1], (b, h, s, d))
    v = jax.random.normal(ks[2], (b, h, s, d))
    mesh = comm.initialize(data=1, ctx=8, model=1)
    spec = P(None, None, "ctx")

    def mk(f):
        def loss(q, k, v):
            return jnp.sum(f(q, k, v, causal=causal)
                           .astype(jnp.float32) ** 2) / s
        return jax.jit(comm.shard_map(
            lambda q, k, v: (loss(q, k, v),
                             jax.grad(loss, argnums=(0, 1, 2))(q, k, v)),
            mesh, in_specs=(spec,) * 3, out_specs=(P(), (spec,) * 3)))

    l1, g1 = mk(attn.ring_attention)(q, k, v)
    l2, g2 = mk(attn.ring_attention_ref)(q, k, v)
    np.testing.assert_allclose(float(l1), float(l2), rtol=1e-5)
    for a, b_ in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_attention_matches_full(causal):
    """all_to_all sequence parallelism == unsharded attention."""
    mesh = comm.initialize(data=2, ctx=4)
    b, h, s, d = 2, 4, 32, 16   # h and s both divisible by ctx=4
    q = jax.random.normal(jax.random.key(20), (b, h, s, d))
    k = jax.random.normal(jax.random.key(21), (b, h, s, d))
    v = jax.random.normal(jax.random.key(22), (b, h, s, d))

    def f(q, k, v):
        return attn.ulysses_attention(q, k, v, causal=causal)

    o = jax.jit(comm.shard_map(
        f, mesh,
        in_specs=(P(None, None, comm.AXIS_CTX, None),) * 3,
        out_specs=P(None, None, comm.AXIS_CTX, None)))(q, k, v)
    want = attn.attention_ref(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(o), np.asarray(want),
                               rtol=1e-4, atol=1e-4)


def test_ulysses_attention_grads_match_full():
    mesh = comm.initialize(data=2, ctx=4)
    b, h, s, d = 1, 4, 16, 8
    q = jax.random.normal(jax.random.key(23), (b, h, s, d))
    k = jax.random.normal(jax.random.key(24), (b, h, s, d))
    v = jax.random.normal(jax.random.key(25), (b, h, s, d))

    def f(q, k, v):
        # per-shard local loss: the shard losses sum to the global one,
        # so the transposed all_to_alls accumulate exactly the full
        # gradient (same pattern as the ring-attention grads test)
        return jnp.sum(attn.ulysses_attention(q, k, v, causal=True) ** 2)

    g = jax.jit(comm.shard_map(
        jax.grad(f, argnums=(0, 1, 2)), mesh,
        in_specs=(P(None, None, comm.AXIS_CTX, None),) * 3,
        out_specs=(P(None, None, comm.AXIS_CTX, None),) * 3))(q, k, v)

    def fr(q, k, v):
        return jnp.sum(attn.attention_ref(q, k, v, causal=True) ** 2)

    gr = jax.grad(fr, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=1e-4, atol=1e-4)


def test_ulysses_attention_rejects_indivisible_heads():
    mesh = comm.initialize(ctx=4)
    q = jax.random.normal(jax.random.key(26), (1, 3, 16, 8))  # h=3

    def f(q):
        return attn.ulysses_attention(q, q, q)

    with pytest.raises(ValueError, match="divisible"):
        jax.jit(comm.shard_map(
            f, mesh, in_specs=(P(None, None, comm.AXIS_CTX, None),),
            out_specs=P(None, None, comm.AXIS_CTX, None)))(q)


def test_attn_block_cap_env_knob(monkeypatch):
    """APEX_TPU_ATTN_BLOCK_CAP (swept by kernel_bench --sweep-attn on
    hardware) overrides the default geometry; bad values fail loudly;
    the kernel stays correct at a non-default cap."""
    from apex_tpu.ops import attention as A

    monkeypatch.delenv("APEX_TPU_ATTN_BLOCK_CAP", raising=False)
    q = jnp.zeros((1, 1, 512, 64), jnp.float32)
    k = jnp.zeros((1, 1, 512, 64), jnp.float32)
    assert A._geom(q, k)[6] == 512            # default cap at dp=128
    # a cap above the padded length clamps to one block, not 128
    monkeypatch.setenv("APEX_TPU_ATTN_BLOCK_CAP", "1024")
    assert A._geom(q, k)[6] == 512
    monkeypatch.delenv("APEX_TPU_ATTN_BLOCK_CAP")
    monkeypatch.setenv("APEX_TPU_ATTN_BLOCK_CAP", "256")
    assert A._geom(q, k)[6] == 256
    monkeypatch.setenv("APEX_TPU_ATTN_BLOCK_CAP", "100")
    with pytest.raises(ValueError, match="multiple of 128"):
        A._geom(q, k)
    # correctness at a GENUINELY overridden geometry: s=512 with
    # cap=128 tiles 4x4 blocks where the default cap (512) would run a
    # single block — a silently ignored env var would not change tiling
    monkeypatch.setenv("APEX_TPU_ATTN_BLOCK_CAP", "128")
    ks = jax.random.split(jax.random.key(0), 3)
    q, k, v = (jax.random.normal(kk, (1, 2, 512, 64)) for kk in ks)
    assert A._geom(q, k)[6] == 128            # bq actually overridden
    got = A.flash_attention(q, k, v, causal=True)
    want = A.attention_ref(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("hk,causal", [(2, False), (2, True),
                                       (1, True), (4, False)])
def test_gqa_flash_matches_repeated_kv_oracle(hk, causal):
    """Grouped-query / multi-query attention (beyond-reference): the
    kernel reads the small K/V directly (no repeat materialization);
    output and all grads must match the repeat-kv oracle, with dk/dv
    summed over each kv head's q group."""
    from apex_tpu.ops import attention as A

    b, h, s, d = 2, 4, 256, 64
    ks = jax.random.split(jax.random.key(3), 3)
    q = jax.random.normal(ks[0], (b, h, s, d))
    k = jax.random.normal(ks[1], (b, hk, s, d))
    v = jax.random.normal(ks[2], (b, hk, s, d))

    got = A.flash_attention(q, k, v, causal=causal)
    want = A.attention_ref(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)

    def loss(f):
        return lambda q, k, v: jnp.sum(
            f(q, k, v, causal=causal).astype(jnp.float32) ** 2)

    gq, gk, gv = jax.grad(loss(A.flash_attention),
                          argnums=(0, 1, 2))(q, k, v)
    oq, ok, ov = jax.grad(loss(A.attention_ref),
                          argnums=(0, 1, 2))(q, k, v)
    assert gk.shape == (b, hk, s, d) and gv.shape == (b, hk, s, d)
    for g, o in ((gq, oq), (gk, ok), (gv, ov)):
        np.testing.assert_allclose(np.asarray(g), np.asarray(o),
                                   rtol=2e-4, atol=2e-4)


def test_gqa_dropout_segments_compose():
    """The three attention extensions TOGETHER — grouped-query heads,
    fused probability dropout, and packed-segment masking — against
    the oracle (which repeats kv, applies the same hash mask, and
    masks cross-segment): fwd and all grads.  Pairwise combinations
    have their own tests; this pins the triple."""
    from apex_tpu.ops import attention as A

    b, h, hk, s, d = 1, 4, 2, 128, 64
    ks = jax.random.split(jax.random.key(11), 3)
    q = jax.random.normal(ks[0], (b, h, s, d))
    k = jax.random.normal(ks[1], (b, hk, s, d))
    v = jax.random.normal(ks[2], (b, hk, s, d))
    ids = jnp.asarray(np.repeat([1, 2], [60, 68])[None, :], jnp.int32)
    seed = jnp.int32(77)
    kw = dict(causal=True, dropout_rate=0.25, dropout_seed=seed)

    def ref(q, k, v):
        same = ids[:, None, :, None] == ids[:, None, None, :]
        return A.attention_ref(q, k, v,
                               mask=jnp.where(same, 0.0, A._NEG), **kw)

    got = A.flash_attention(q, k, v, segment_ids=(ids, ids), **kw)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref(q, k, v)),
                               rtol=2e-5, atol=2e-5)

    gs = jax.grad(lambda q, k, v: jnp.sum(A.flash_attention(
        q, k, v, segment_ids=(ids, ids), **kw
    ).astype(jnp.float32) ** 2), argnums=(0, 1, 2))(q, k, v)
    os_ = jax.grad(lambda q, k, v: jnp.sum(
        ref(q, k, v).astype(jnp.float32) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    assert gs[1].shape == (b, hk, s, d)
    for g, o in zip(gs, os_):
        np.testing.assert_allclose(np.asarray(g), np.asarray(o),
                                   rtol=2e-4, atol=2e-4)


def test_gqa_with_segment_ids_and_padding():
    """GQA composes with packed-batch masking and non-128-multiple
    sequence lengths (padded geometry)."""
    from apex_tpu.ops import attention as A

    b, h, hk, s, d = 1, 4, 2, 200, 64
    ks = jax.random.split(jax.random.key(5), 3)
    q = jax.random.normal(ks[0], (b, h, s, d))
    k = jax.random.normal(ks[1], (b, hk, s, d))
    v = jax.random.normal(ks[2], (b, hk, s, d))
    ids = jnp.asarray(
        np.repeat([0, 1, 2], [80, 70, 50])[None, :], jnp.int32)

    got = A.flash_attention(q, k, v, segment_ids=(ids, ids))
    same = ids[:, None, :, None] == ids[:, None, None, :]
    want = A.attention_ref(q, k, v, mask=jnp.where(same, 0.0, A._NEG))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)

    # grads: the dkv seg BlockSpecs batch-index by KV-head grid rows
    # (i // hk, not i // h) — only wrong when hk < h AND segments are
    # set, so pin exactly that combination
    gq, gk, gv = jax.grad(
        lambda q, k, v: jnp.sum(A.flash_attention(
            q, k, v, segment_ids=(ids, ids)).astype(jnp.float32) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    oq, ok, ov = jax.grad(
        lambda q, k, v: jnp.sum(A.attention_ref(
            q, k, v, mask=jnp.where(same, 0.0, A._NEG)
        ).astype(jnp.float32) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    assert gk.shape == (b, hk, s, d)
    for g, o in ((gq, oq), (gk, ok), (gv, ov)):
        np.testing.assert_allclose(np.asarray(g), np.asarray(o),
                                   rtol=2e-4, atol=2e-4)


def test_gqa_rejects_indivisible_heads():
    from apex_tpu.ops import attention as A

    q = jnp.zeros((1, 4, 128, 64))
    kv = jnp.zeros((1, 3, 128, 64))
    with pytest.raises(ValueError, match="multiple of kv heads"):
        A.flash_attention(q, kv, kv)
    # the ring's blockwise math is head-aligned with q: GQA shapes must
    # refuse loudly up front, not break in backward
    kv2 = jnp.zeros((1, 2, 128, 64))
    with pytest.raises(ValueError, match="equal q/kv head counts"):
        A.ring_attention(q, kv2, kv2)


@pytest.mark.parametrize("causal,hk", [(False, 4), (True, 4), (True, 2)])
def test_fused_dropout_matches_oracle(causal, hk):
    """Fused hash-mask dropout: the kernel and the jnp oracle share
    _keep_mask, so outputs and ALL grads must agree elementwise (the
    backward kernels reconstruct the identical mask from coordinates;
    GQA composes — the dkv kernel re-derives the flat q row)."""
    from apex_tpu.ops import attention as A

    b, h, s, d = 2, 4, 256, 64
    ks = jax.random.split(jax.random.key(11), 3)
    q = jax.random.normal(ks[0], (b, h, s, d))
    k = jax.random.normal(ks[1], (b, hk, s, d))
    v = jax.random.normal(ks[2], (b, hk, s, d))
    seed = jnp.int32(77)

    kw = dict(causal=causal, dropout_rate=0.25, dropout_seed=seed)
    got = A.flash_attention(q, k, v, **kw)
    want = A.attention_ref(q, k, v, **kw)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)

    def loss(f):
        return lambda *a: jnp.sum(f(*a, **kw).astype(jnp.float32) ** 2)

    g = jax.grad(loss(A.flash_attention), argnums=(0, 1, 2))(q, k, v)
    o = jax.grad(loss(A.attention_ref), argnums=(0, 1, 2))(q, k, v)
    assert g[1].shape == (b, hk, s, d)
    for a_, b_ in zip(g, o):
        np.testing.assert_allclose(np.asarray(a_), np.asarray(b_),
                                   rtol=2e-4, atol=2e-4)


def test_fused_dropout_mask_properties():
    """Keep rate ~= 1-rate; same seed -> identical mask; different
    seed -> different mask; rate 0 -> identity with no seed needed."""
    from apex_tpu.ops import attention as A

    keep = A.dropout_keep_ref(jnp.int32(5), 2, 4, 128, 128, 0.3)
    frac = float(jnp.mean(keep.astype(jnp.float32)))
    assert abs(frac - 0.7) < 0.01, frac
    keep2 = A.dropout_keep_ref(jnp.int32(5), 2, 4, 128, 128, 0.3)
    assert bool(jnp.all(keep == keep2))
    keep3 = A.dropout_keep_ref(jnp.int32(6), 2, 4, 128, 128, 0.3)
    assert float(jnp.mean((keep != keep3).astype(jnp.float32))) > 0.1

    ks = jax.random.split(jax.random.key(0), 3)
    q, k, v = (jax.random.normal(kk, (1, 2, 128, 64)) for kk in ks)
    o0 = A.flash_attention(q, k, v, dropout_rate=0.0)
    o_plain = A.flash_attention(q, k, v)
    np.testing.assert_array_equal(np.asarray(o0), np.asarray(o_plain))

    with pytest.raises(ValueError, match="requires dropout_seed"):
        A.flash_attention(q, k, v, dropout_rate=0.1)
    with pytest.raises(ValueError, match="must be in"):
        A.flash_attention(q, k, v, dropout_rate=1.0,
                          dropout_seed=jnp.int32(0))


def test_fused_dropout_with_segment_ids():
    """Dropout composes with packed-batch masking: cross-segment pairs
    stay zero regardless of the dropout mask."""
    from apex_tpu.ops import attention as A

    b, h, s, d = 1, 2, 256, 64
    ks = jax.random.split(jax.random.key(13), 3)
    q, k, v = (jax.random.normal(kk, (b, h, s, d)) for kk in ks)
    ids = (jnp.arange(s)[None] // 64).astype(jnp.int32)
    seed = jnp.int32(3)

    got = A.flash_attention(q, k, v, segment_ids=(ids, ids),
                            dropout_rate=0.2, dropout_seed=seed)
    same = ids[:, None, :, None] == ids[:, None, None, :]
    want = A.attention_ref(q, k, v, mask=jnp.where(same, 0.0, A._NEG),
                           dropout_rate=0.2, dropout_seed=seed)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_fused_dropout_dispatch_stable(monkeypatch):
    """The escape-hatch XLA path drops the SAME elements as the kernel
    (both hash the same coordinates), so flipping the dispatch gate
    never changes training behavior."""
    from apex_tpu.ops import _dispatch
    from apex_tpu.ops import attention as A

    ks = jax.random.split(jax.random.key(17), 3)
    q, k, v = (jax.random.normal(kk, (1, 2, 128, 64)) for kk in ks)
    kw = dict(causal=True, dropout_rate=0.3,
              dropout_seed=jnp.int32(123))
    o_kernel = A.flash_attention(q, k, v, **kw)
    monkeypatch.setattr(_dispatch, "_PREFS",
                        {"attention_f32": False, "attention": False})
    o_xla = A.flash_attention(q, k, v, **kw)
    np.testing.assert_allclose(np.asarray(o_kernel), np.asarray(o_xla),
                               rtol=2e-5, atol=2e-5)


def test_dense_fallback_memory_gate(monkeypatch):
    """A measured prefer-XLA preference must not route LONG sequences
    to the dense fallback: the (B, H, Sq, Sk) f32 score tensor grows
    quadratically (48G HBM at s=8192 in the round-4 window) while the
    flash kernel is O(S).  Past the element budget the preference is
    ignored; under it the measured choice stands."""
    from apex_tpu.ops import _dispatch
    from apex_tpu.ops import attention as A

    routed = []
    monkeypatch.setattr(
        A, "_flash",
        lambda q, *a, **k: (routed.append("flash"), q * 0)[1])
    monkeypatch.setattr(
        A, "attention_ref",
        lambda q, *a, **k: (routed.append("dense"), q * 0)[1])
    monkeypatch.setattr(_dispatch, "_PREFS",
                        {"attention": False, "attention_f32": False})

    small = jnp.zeros((1, 2, 128, 64), jnp.bfloat16)
    A.flash_attention(small, small, small, causal=True)
    assert routed == ["dense"]          # measured preference honored

    big = jnp.zeros((1, 1, 16384, 64), jnp.bfloat16)
    routed.clear()
    A.flash_attention(big, big, big, causal=True)
    assert routed == ["flash"]          # 16384^2 >= budget: gate wins

    # budget is operator-tunable; shrinking it flips the small shape
    monkeypatch.setenv("APEX_TPU_ATTN_DENSE_MAX_SCORES", "1024")
    routed.clear()
    A.flash_attention(small, small, small, causal=True)
    assert routed == ["flash"]
    monkeypatch.delenv("APEX_TPU_ATTN_DENSE_MAX_SCORES")

    # operator overrides are NOT subject to the gate: the global escape
    # hatch and an explicit PREFER_XLA must reach the dense path even at
    # shapes the gate would veto (jvp-over-custom_vjp, miscompile
    # workarounds — the operator knows why they asked)
    monkeypatch.setenv("APEX_TPU_DISABLE_PALLAS", "1")
    routed.clear()
    A.flash_attention(big, big, big, causal=True)
    assert routed == ["dense"]
    monkeypatch.delenv("APEX_TPU_DISABLE_PALLAS")
    monkeypatch.setenv("APEX_TPU_PREFER_XLA", "attention")
    routed.clear()
    A.flash_attention(big, big, big, causal=True)
    assert routed == ["dense"]


def test_attn_block_cap_measured_table(monkeypatch):
    """The sweep-written attn_block_cap table in dispatch_prefs.json
    sets the default geometry per padded head dim; the env knob still
    wins over it, and unmeasured head dims keep the static default."""
    from apex_tpu.ops import _dispatch
    from apex_tpu.ops import attention as A

    monkeypatch.delenv("APEX_TPU_ATTN_BLOCK_CAP", raising=False)
    monkeypatch.setattr(_dispatch, "_ATTN_CAPS", {"128": 256})
    q = jnp.zeros((1, 1, 1024, 64), jnp.float32)   # dp=128
    k = jnp.zeros((1, 1, 1024, 64), jnp.float32)
    assert A._geom(q, k)[6] == 256                 # measured wins
    monkeypatch.setenv("APEX_TPU_ATTN_BLOCK_CAP", "128")
    assert A._geom(q, k)[6] == 128                 # env beats measured
    monkeypatch.delenv("APEX_TPU_ATTN_BLOCK_CAP")
    q = jnp.zeros((1, 1, 1024, 256), jnp.float32)  # dp=256: unmeasured
    k = jnp.zeros((1, 1, 1024, 256), jnp.float32)
    assert A._geom(q, k)[6] == 256                 # static default
    # a hand-edited cap above the sweep grid's ceiling for this head
    # dim is clamped to VMEM-feasible geometry, not compiled blindly
    monkeypatch.setattr(_dispatch, "_ATTN_CAPS", {"256": 1024})
    assert A._geom(q, k)[6] == 512                 # ceiling at dp=256


def test_dispatch_prefs_attn_caps_parse(tmp_path, monkeypatch):
    """_load_prefs returns the measured cap table and never propagates
    a malformed file (the documented import-safety contract)."""
    import json as _json

    from apex_tpu.ops import _dispatch

    p = tmp_path / "prefs.json"
    p.write_text(_json.dumps({
        "prefer_pallas": {"attention": True},
        "methodology": "amortized",
        "attn_block_cap": {"128": 256, "256": "512", "64": "auto",
                           "bad": 100, "worse": -128}}))
    monkeypatch.setattr(_dispatch, "_PREFS_PATH", str(p))
    prefs, caps = _dispatch._load_prefs()
    assert prefs == {"attention": True}
    # 100 is not a 128-multiple, -128 is negative, "auto" is not an
    # int: each dropped per-entry WITHOUT discarding prefer_pallas
    assert caps == {"128": 256, "256": 512}

    # a table without the amortized-methodology stamp is provisional
    # (pre-amortization runs timed the relay RTT, not the kernels —
    # routing AND cap winners alike were drawn from noise): the whole
    # table is inert until a re-measure stamps it
    p.write_text(_json.dumps({
        "prefer_pallas": {"attention": False},
        "attn_block_cap": {"128": 256}}))
    assert _dispatch._load_prefs() == ({}, {})

    p.write_text("{truncated")
    assert _dispatch._load_prefs() == ({}, {})


def test_f32_attention_is_its_own_dispatch_family(monkeypatch):
    """A hardware measurement that routes f32 flash to the XLA path
    (Precision.HIGHEST multi-pass dots may lose there) must NOT take
    the bf16 kernel down with it — and vice versa."""
    from apex_tpu.ops import _dispatch, attention as A

    monkeypatch.setattr(_dispatch, "_PREFS", {"attention_f32": False})
    ks = jax.random.split(jax.random.key(0), 3)
    qf, kf, vf = (jax.random.normal(kk, (1, 2, 256, 64)) for kk in ks)
    qb, kb, vb = (t.astype(jnp.bfloat16) for t in (qf, kf, vf))

    def prims(jx):
        out = set()
        def walk(j):
            for e in j.eqns:
                out.add(e.primitive.name)
                for p in e.params.values():
                    if hasattr(p, "jaxpr"):
                        walk(p.jaxpr)
        walk(jx.jaxpr)
        return out

    # recursive walk is load-bearing: a pallas_call only ever appears
    # nested inside the kernel's custom_vjp_call, never at top level
    jx32 = jax.make_jaxpr(
        lambda q, k, v: A.flash_attention(q, k, v, causal=True))(
        qf, kf, vf)
    assert "pallas_call" not in prims(jx32)

    jx16 = jax.make_jaxpr(
        lambda q, k, v: A.flash_attention(q, k, v, causal=True))(
        qb, kb, vb)
    assert "pallas_call" in prims(jx16)
    # f32 output stays correct through the rerouted path
    got = A.flash_attention(qf, kf, vf, causal=True)
    want = A.attention_ref(qf, kf, vf, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-6, atol=1e-6)
