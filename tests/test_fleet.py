"""apex_tpu.resilience.fleet — multi-host failure domains: liveness
beacons, deadline-armed step boundaries, survivor agreement, and the
shrink-to-healthy-mesh recovery driven through run_elastic (the third
leg of the failure-domain triad)."""

import errno
import json
import os
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.optimizers import FusedAdam
from apex_tpu.resilience import (CheckpointManager, FleetMonitor,
                                 FleetRecoveryFailed,
                                 StepDeadlineExceeded, Watchdog,
                                 run_elastic)
from apex_tpu.resilience import fleet as fleet_mod
from apex_tpu.resilience.faults import FaultInjector, FaultSpec
from apex_tpu.resilience.fleet import (DeadlineCalibrator,
                                       DeadlineRunner, FileChannel,
                                       LocalChannel, SimulatedPeers)


# ---------------------------------------------------------------------
# Channels
# ---------------------------------------------------------------------

@pytest.mark.parametrize("make", [
    lambda tmp: LocalChannel(),
    lambda tmp: FileChannel(str(tmp / "fleet")),
], ids=["local", "file"])
def test_channel_roundtrip_newest_wins_and_prefix(tmp_path, make):
    ch = make(tmp_path)
    ch.put("beacon/0", {"step": 1})
    ch.put("beacon/0", {"step": 2})          # overwrite: newest wins
    ch.put("beacon/1", {"step": 7})
    ch.put("verdict/1/0", {"survivors": [0]})
    got = ch.get_all("beacon/")
    assert got == {"beacon/0": {"step": 2}, "beacon/1": {"step": 7}}
    assert set(ch.get_all("verdict/1/")) == {"verdict/1/0"}


class _FakeKVClient:
    """jax.distributed KV-client shape: key_value_set (optionally
    rejecting allow_overwrite like old clients) + key_value_dir_get."""

    def __init__(self, allow_overwrite_supported):
        self._ok = allow_overwrite_supported
        self.data = {}

    def key_value_set(self, key, value, allow_overwrite=None):
        if allow_overwrite is not None and not self._ok:
            raise TypeError("allow_overwrite not supported")
        self.data[key] = value

    def key_value_dir_get(self, prefix):
        return [(k, v) for k, v in sorted(self.data.items())
                if k.startswith(prefix)]


@pytest.mark.parametrize("overwrite", [True, False],
                         ids=["overwrite", "seq-fallback"])
def test_kv_channel_keeps_per_host_keys(overwrite):
    """Host ids are digit key tails ('beacon/0', 'verdict/3/1') and
    must NOT be mistaken for the write-once fallback's 8-digit
    sequence suffix — that collapse made every peer look silent on
    the production transport."""
    from apex_tpu.resilience.fleet import KVChannel
    ch = KVChannel(client=_FakeKVClient(overwrite))
    for h in (0, 1, 2):
        ch.put(f"beacon/{h}", {"host": h, "step": 1})
        ch.put(f"beacon/{h}", {"host": h, "step": 2})   # newest wins
    got = ch.get_all("beacon/")
    assert set(got) == {"beacon/0", "beacon/1", "beacon/2"}
    assert all(rec["step"] == 2 for rec in got.values())
    ch.put("verdict/3/1", {"host": 1, "survivors": [0, 1]})
    assert set(ch.get_all("verdict/3/")) == {"verdict/3/1"}


def test_file_channel_skips_torn_writes(tmp_path):
    ch = FileChannel(str(tmp_path))
    ch.put("beacon/0", {"step": 3})
    # a crashed writer leaves garbage bytes under a beacon name
    with open(os.path.join(str(tmp_path), "beacon__1.json"), "w") as f:
        f.write('{"step": ')
    got = ch.get_all("beacon/")
    assert got == {"beacon/0": {"step": 3}}  # torn file skipped


# ---------------------------------------------------------------------
# FleetMonitor classification
# ---------------------------------------------------------------------

def _lag_monitor(ch, host=0, n_hosts=3, slow=2, dead=4, **kw):
    """A step-lag-only monitor (deterministic: no wall clock)."""
    return FleetMonitor(channel=ch, host=host, n_hosts=n_hosts,
                        slow_after_steps=slow, dead_after_steps=dead,
                        slow_after_s=None, dead_after_s=None,
                        agreement_timeout_s=0.2, **kw)


def test_monitor_validation():
    ch = LocalChannel()
    with pytest.raises(ValueError, match="criterion"):
        FleetMonitor(channel=ch, host=0, n_hosts=2,
                     slow_after_s=None, dead_after_s=None)
    with pytest.raises(ValueError):
        FleetMonitor(channel=ch, host=0, n_hosts=2,
                     slow_after_s=5.0, dead_after_s=1.0)
    with pytest.raises(ValueError):
        FleetMonitor(channel=ch, host=0, n_hosts=2,
                     slow_after_s=None, dead_after_s=None,
                     slow_after_steps=8, dead_after_steps=4)
    with pytest.raises(ValueError, match="both"):
        FleetMonitor(channel=ch, host=0, n_hosts=2,
                     slow_after_s=1.0, dead_after_s=None)


def test_step_lag_classification_slow_then_dead_sticky():
    ch = LocalChannel()
    mon = _lag_monitor(ch, slow=2, dead=4)
    sim = SimulatedPeers(ch, hosts=[1, 2]).attach(mon)
    for s in range(1, 4):
        assert mon.beat(s) == []
    sim.kill(2)                               # beacons freeze at step 3
    events = []
    for s in range(4, 12):
        events += mon.beat(s)
    kinds = [(e.kind, e.host) for e in events]
    # one slow warning, then one dead event, then silence (sticky)
    assert kinds == [("host_slow", 2), ("host_dead", 2)]
    assert mon.dead_hosts() == [2]
    assert mon.live_hosts() == [0, 1]
    assert mon.status(1) == fleet_mod.HOST_LIVE


def test_slow_episode_rearms_on_recovery():
    """A slow peer warns once per EPISODE: recovery re-arms, a second
    episode warns again — the slow-network contract (warn only,
    never evict)."""
    ch = LocalChannel()
    mon = _lag_monitor(ch, n_hosts=2, slow=2, dead=50)
    sim = SimulatedPeers(ch, hosts=[1]).attach(mon)
    with FaultInjector([
            FaultSpec("slow_network", at_step=3, target=1, n_steps=3,
                      lag_steps=3),
            FaultSpec("slow_network", at_step=10, target=1, n_steps=3,
                      lag_steps=3)]):
        events = []
        for s in range(1, 16):
            events += mon.beat(s)
    assert [(e.kind, e.host) for e in events] == \
        [("host_slow", 1), ("host_slow", 1)]
    assert mon.dead_hosts() == []             # slow never kills


def test_wall_clock_classification_with_fake_clock():
    clk = [1000.0]
    ch = LocalChannel()
    mon = FleetMonitor(channel=ch, host=0, n_hosts=2,
                       slow_after_s=1.0, dead_after_s=3.0,
                       clock=lambda: clk[0])
    ch.put("beacon/1", {"host": 1, "step": 1, "wall_time": clk[0],
                        "incarnation": 7})
    assert mon.poll(1) == []
    clk[0] += 2.0                             # age 2s: slow
    evs = mon.poll(2)
    assert [e.kind for e in evs] == ["host_slow"]
    clk[0] += 2.0                             # age 4s: dead
    evs = mon.poll(3)
    assert [e.kind for e in evs] == ["host_dead"]
    assert evs[0].gap_s >= 3.0 and evs[0].peer_step == 1


def test_missing_beacon_ages_from_monitor_start():
    """A peer that NEVER beacons must still be declared dead (startup
    grace = the dead deadline from monitor start), not live forever."""
    clk = [0.0]
    ch = LocalChannel()
    mon = FleetMonitor(channel=ch, host=0, n_hosts=2,
                       slow_after_s=1.0, dead_after_s=2.0,
                       clock=lambda: clk[0])
    assert mon.poll(1) == []                  # inside the grace
    clk[0] += 3.0
    assert [e.kind for e in mon.poll(2)] == ["host_dead"]


def test_fleet_counters_emitted():
    from apex_tpu.telemetry import hostmetrics
    got = {}
    sink = lambda name, v: got.__setitem__(name, v)
    hostmetrics.add_sink(sink)
    try:
        ch = LocalChannel()
        mon = _lag_monitor(ch, n_hosts=3)
        SimulatedPeers(ch, hosts=[1, 2]).attach(mon)
        mon.beat(1)
    finally:
        hostmetrics.remove_sink(sink)
    assert got["fleet/hosts_live"] == 3
    assert got["fleet/hosts_dead"] == 0
    assert got["fleet/hosts_slow"] == 0
    assert "fleet/beacon_gap_ms" in got
    assert "fleet/beacon_lag_steps" in got


def test_beacon_channel_failure_degrades_not_crashes(tmp_path):
    """A transient channel failure must never kill training: publish
    warns (once) and classification treats the channel as silent."""
    import shutil
    ch = FileChannel(str(tmp_path / "fleet"))
    mon = _lag_monitor(ch, n_hosts=2)
    shutil.rmtree(str(tmp_path / "fleet"))    # channel gone
    with pytest.warns(UserWarning, match="beacon publish failed"):
        assert mon.beat(1) == []              # degrades, no raise
    mon.beat(2)                               # warned once, no flood


def test_host_failure_record_shape():
    f = fleet_mod.HostFailure(kind="host_dead", host=2, step=9,
                              peer_step=4, gap_s=1.5, lag_steps=5)
    rec = f.record()
    assert rec["kind"] == "fleet" and rec["event"] == "host_dead"
    assert rec["host"] == 2 and rec["step"] == 9
    json.dumps(rec)                           # JSONL-able


# ---------------------------------------------------------------------
# Agreement
# ---------------------------------------------------------------------

def test_two_real_monitors_agree_and_drop_silent_third():
    """Two live hosts (each a real monitor on the shared channel) and
    one silent host: both survivors compute the SAME agreed set with
    the silent host dropped — by response timeout, not by an allgather
    a dead host would hang."""
    ch = LocalChannel()
    m0 = _lag_monitor(ch, host=0, n_hosts=3)
    m1 = _lag_monitor(ch, host=1, n_hosts=3)
    # each answers the other's round when polled (no threads needed:
    # publishing a verdict is non-blocking, reading is idempotent)
    m0.add_spin_hook(lambda epoch: ch.put(
        f"verdict/{epoch}/1", {"host": 1, "epoch": epoch, "step": 5,
                               "survivors": [0, 1, 2]}))
    e0, s0 = m0.agree_survivors(5, timeout_s=0.05)
    m1.add_spin_hook(lambda epoch: None)
    e1, s1 = m1.agree_survivors(5, timeout_s=0.05)
    assert s0 == [0, 1]                       # 2 never responded
    # m1 reads the SAME published verdicts for epoch 1 (m0's proposal
    # [0,1,2] and the injected host-1 verdict), so it lands on the
    # same set
    assert (e1, s1) == (e0, [0, 1])
    assert m0.hosts == [0, 1] and m0.epoch == e0


def test_agreement_fast_path_when_all_respond():
    ch = LocalChannel()
    mon = _lag_monitor(ch, host=0, n_hosts=3)
    SimulatedPeers(ch, hosts=[1, 2]).attach(mon)
    t0 = time.monotonic()
    epoch, survivors = mon.agree_survivors(3, timeout_s=5.0)
    assert survivors == [0, 1, 2] and epoch == 1
    assert time.monotonic() - t0 < 2.0        # no timeout wait burned


def test_agreement_excluding_self_evicts_instead_of_split_brain():
    """When a responder's proposal rules THIS host dead, the agreed
    set excludes it — the host must self-evict (typed raise), never
    rebuild a divergent mesh the real survivors don't share."""
    ch = LocalChannel()
    mon = _lag_monitor(ch, host=0, n_hosts=3)
    # host 1 answers but its live view is {1, 2} — it ruled us dead
    mon.add_spin_hook(lambda epoch: ch.put(
        f"verdict/{epoch}/1", {"host": 1, "epoch": epoch, "step": 4,
                               "survivors": [1, 2]}))
    with pytest.raises(FleetRecoveryFailed, match="excluded"):
        mon.agree_survivors(4, timeout_s=0.05)


def test_agreement_intersects_divergent_proposals():
    """A responder that itself saw another host dead shrinks the
    agreed set: intersection of proposals, restricted to responders."""
    ch = LocalChannel()
    mon = _lag_monitor(ch, host=0, n_hosts=3)
    # host 1 responds but claims host 2 is dead; host 2 responds too
    mon.add_spin_hook(lambda epoch: (
        ch.put(f"verdict/{epoch}/1",
               {"host": 1, "epoch": epoch, "step": 4,
                "survivors": [0, 1]}),
        ch.put(f"verdict/{epoch}/2",
               {"host": 2, "epoch": epoch, "step": 4,
                "survivors": [0, 1, 2]})))
    _, survivors = mon.agree_survivors(4, timeout_s=0.05)
    assert survivors == [0, 1]


# ---------------------------------------------------------------------
# Deadline machinery
# ---------------------------------------------------------------------

def test_deadline_runner_result_exception_and_timeout():
    with DeadlineRunner() as r:
        assert r.run(lambda: 41 + 1, 5.0) == 42
        with pytest.raises(ZeroDivisionError):
            r.run(lambda: 1 // 0, 5.0)
        gen = r.generation
        with pytest.raises(StepDeadlineExceeded) as ei:
            r.run(lambda: time.sleep(5.0), 0.1, step=7, phase="save")
        assert ei.value.step == 7 and ei.value.phase == "save"
        assert ei.value.deadline_s == 0.1
        assert r.generation == gen + 1        # abandoned: gen bumped
        # a fresh worker serves the next call; the abandoned one's
        # late result cannot leak into it
        assert r.run(lambda: "fresh", 5.0) == "fresh"


def test_deadline_runner_close_idempotent():
    r = DeadlineRunner()
    r.run(lambda: None, 1.0)
    r.close()
    r.close()
    assert r.run(lambda: 1, 1.0) == 1         # usable again
    r.close()


def test_deadline_calibrator_tracks_baseline():
    c = DeadlineCalibrator(factor=5.0, min_s=0.5, max_s=10.0,
                           default_s=99.0, min_history=3)
    assert c.deadline_s() == 99.0             # no history yet
    for _ in range(4):
        c.note(0.2)
    assert c.deadline_s() == pytest.approx(1.0)   # 5 x median
    for _ in range(64):
        c.note(10.0)
    assert c.deadline_s() == 10.0             # clamped at max_s
    with pytest.raises(ValueError):
        DeadlineCalibrator(factor=1.0)


def test_deadline_calibrator_seeds_from_watchdog_baseline():
    """run_elastic(step_deadline='auto') calibrates from the step-time
    baseline the watchdog already tracks: before the calibrator's own
    history accrues, the watchdog's straggler-detector samples set the
    deadline instead of the blind default."""
    from apex_tpu.resilience.watchdog import StepTimeDetector

    wd = Watchdog(detectors=[StepTimeDetector(min_history=4)],
                  clean_window=4)
    t = [0.0]
    wd._clock = lambda: t[0]
    for i in range(8):                        # 0.2s/step baseline
        t[0] += 0.2
        wd.check(i)
    assert len(wd.recent_step_times()) >= 4
    c = DeadlineCalibrator(factor=5.0, min_s=0.1, max_s=60.0,
                           default_s=99.0, min_history=3,
                           history_source=wd.recent_step_times)
    assert c.deadline_s() == pytest.approx(1.0)   # 5 x 0.2, not 99
    c.note(2.0)
    c.note(2.0)
    c.note(2.0)                               # own history takes over
    assert c.deadline_s() == pytest.approx(10.0)
    # a watchdog without a StepTimeDetector reports an empty baseline
    assert Watchdog(detectors=[], clean_window=4) \
        .recent_step_times() == []


# ---------------------------------------------------------------------
# run_elastic integration: the fleet chaos matrix.
# peer_death / peer_hang / slow_network x {mid-step, mid-save,
# pre-restore} under faked multi-host — each must end in the
# documented action, and recovery must replay bit-exact vs an
# uninterrupted run on the same (shrunk) mesh.
# ---------------------------------------------------------------------

_TOTAL, _EVERY = 12, 3


def _mixed_tree():
    return {
        "w1": jnp.linspace(-1.0, 1.0, 256).astype(jnp.bfloat16
                                                  ).reshape(16, 16),
        "b1": jnp.linspace(0.0, 1.0, 16).astype(jnp.float32),
    }


def _grads_for(tree):
    return jax.tree_util.tree_map(
        lambda p: (p.astype(jnp.float32) * 1e-2 + 1e-3).astype(p.dtype),
        tree)


def _assert_tree_equal(a, b):
    la, lb = jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def _mirror_peer(mgr):
    """Fake the manager's 2-host lockstep agreement (peer mirrors this
    host) so the restore after a shrink drives the full collective
    code path too."""
    def allgather(arr):
        arr = np.asarray(arr)
        return np.stack([arr, arr])
    mgr._allgather = allgather
    mgr._process_count = lambda: 2


class _FleetJob:
    """One faked-multi-host 'process lifetime': optimizer + manager
    (mirror-peer lockstep) + FleetMonitor over simulated peers."""

    def __init__(self, ckpt_dir, n_hosts=3, slow=2, dead=4,
                 total=_TOTAL):
        tree = _mixed_tree()
        self.opt = FusedAdam(tree, lr=1e-2)
        self.g = _grads_for(tree)
        self.total = total
        self.mgr = CheckpointManager(ckpt_dir, keep=3, every=_EVERY)
        _mirror_peer(self.mgr)
        self.template = jax.tree_util.tree_map(jnp.zeros_like, tree)
        self.channel = LocalChannel()
        self.mon = _lag_monitor(self.channel, n_hosts=n_hosts,
                                slow=slow, dead=dead)
        self.sim = SimulatedPeers(self.channel,
                                  hosts=list(range(1, n_hosts)))
        self.sim.attach(self.mon)
        self.shrinks = []

    def step_fn(self, step):
        self.opt.step(self.g)

    def run(self, **kw):
        kw.setdefault("backoff_s", 0.0)
        return run_elastic(
            self.step_fn, self.mgr, self.opt, total_steps=self.total,
            params_like=self.template, fleet=self.mon,
            on_shrink=lambda survivors, epoch:
                self.shrinks.append((epoch, tuple(survivors))), **kw)

    def close(self):
        self.mon.close()
        self.mgr.close()


@pytest.fixture(scope="module")
def _fleet_reference(tmp_path_factory):
    """The uninterrupted faked-fleet run every recovered run must
    match bit-exactly (the 'uninterrupted shrunk run': the step math
    is mesh-size-independent here, so one reference serves)."""
    job = _FleetJob(str(tmp_path_factory.mktemp("fleet_ref")))
    res = job.run()
    assert res.step == _TOTAL and res.mesh_shrinks == 0
    job.close()
    return job


# phase -> the step the fault lands on: mid-step (off-cadence),
# mid-save (on the save cadence), pre-restore (dead before this
# incarnation's first step — the job below seeds checkpoints first)
_PHASES = {"mid-step": 5, "mid-save": _EVERY * 2, "pre-restore": 1}


@pytest.mark.parametrize("phase", sorted(_PHASES))
def test_peer_death_shrinks_and_replays_bit_exact(tmp_path, phase,
                                                  _fleet_reference):
    """Acceptance: kill one faked host -> survivors agree on the death
    within the step-lag deadline, re-initialize the shrunk mesh
    (on_shrink), restore via the manager and replay bit-exact vs an
    uninterrupted run."""
    if phase == "pre-restore":
        seed = _FleetJob(str(tmp_path), total=_EVERY * 2)
        assert seed.run().step == _EVERY * 2
        seed.close()
    with FaultInjector([FaultSpec("peer_death",
                                  at_step=_PHASES[phase],
                                  target=2)]) as inj:
        job = _FleetJob(str(tmp_path))
        with pytest.warns(UserWarning, match="shrinking to healthy"):
            res = job.run()
        assert inj.fired
    assert res.step == _TOTAL and res.mesh_shrinks == 1
    assert job.shrinks and job.shrinks[0][1] == (0, 1)
    assert job.mon.hosts == [0, 1]            # monitor shrank too
    kinds = [f.kind for f in job.mon.timeline]
    assert "host_dead" in kinds
    shrink_events = [e for e in job.mon.events
                     if e.get("event") == "shrink"]
    assert shrink_events and shrink_events[0]["dead"] == [2]
    _assert_tree_equal(job.opt.params, _fleet_reference.opt.params)
    job.close()


# the hang must land AFTER the calibrator has a baseline (the first
# steps include jit compilation, covered by the generous default
# deadline): pre-restore resumes at 7 and hangs on step 9, two clean
# resumed steps into the new incarnation
_HANG_PHASES = {"mid-step": 5, "mid-save": _EVERY * 2,
                "pre-restore": 9}


def _test_calibrator(max_s=2.0):
    """Generous default (first steps compile), tight once calibrated —
    the auto-calibration shape at test-friendly scales."""
    return DeadlineCalibrator(factor=20.0, min_s=0.5, max_s=max_s,
                              default_s=30.0, min_history=2)


@pytest.mark.parametrize("phase", sorted(_HANG_PHASES))
def test_peer_hang_converts_to_deadline_and_recovers(tmp_path, phase,
                                                     _fleet_reference):
    """Acceptance: a hung peer converts the would-be infinite block
    into a typed StepDeadlineExceeded WITHIN the (calibrated)
    deadline, then the same agreement -> shrink -> restore ->
    bit-exact replay."""
    if phase == "pre-restore":
        seed = _FleetJob(str(tmp_path), total=_EVERY * 2)
        assert seed.run().step == _EVERY * 2
        seed.close()
    hang_s = 30.0
    with FaultInjector([FaultSpec("peer_hang",
                                  at_step=_HANG_PHASES[phase],
                                  target=2, delay_s=hang_s)]) as inj:
        job = _FleetJob(str(tmp_path))
        t0 = time.monotonic()
        with pytest.warns(UserWarning, match="deadline"):
            res = job.run(step_deadline=_test_calibrator())
        wall = time.monotonic() - t0
        assert inj.fired
    # converted within the deadline, nowhere near the hang duration
    assert wall < hang_s / 2
    assert res.step == _TOTAL and res.mesh_shrinks == 1
    assert any(e.get("event") == "deadline_exceeded"
               for e in job.mon.events)
    assert job.shrinks and job.shrinks[0][1] == (0, 1)
    _assert_tree_equal(job.opt.params, _fleet_reference.opt.params)
    job.close()


@pytest.mark.parametrize("phase", sorted(_PHASES))
def test_slow_network_warns_only(tmp_path, phase, _fleet_reference):
    """A slow peer is an infrastructure warning: no agreement, no
    shrink, no state action — the run completes bit-exact."""
    with FaultInjector([FaultSpec("slow_network",
                                  at_step=_PHASES[phase], target=1,
                                  n_steps=3, lag_steps=3)]) as inj:
        job = _FleetJob(str(tmp_path), slow=2, dead=50)
        with pytest.warns(UserWarning, match="is slow"):
            res = job.run()
        assert inj.fired
    assert res.step == _TOTAL and res.mesh_shrinks == 0
    assert res.restarts == 0
    assert [f.kind for f in job.mon.timeline] == ["host_slow"]
    assert not job.shrinks
    _assert_tree_equal(job.opt.params, _fleet_reference.opt.params)
    job.close()


def test_shrink_restore_reshards_onto_shrunk_mesh(tmp_path,
                                                  _fleet_reference):
    """The shrink restore rides the existing ``sharding=`` reshard
    flow: ``shrink_sharding`` (evaluated AFTER the mesh re-init)
    lands the restored state on the shrunk device set, and the replay
    still matches bit-exact."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec

    ndev = min(4, len(jax.devices()))
    if ndev < 2:
        pytest.skip("needs >= 2 devices")
    evaluated = []

    def shrink_sharding():
        # built lazily — the real flow constructs it over the mesh
        # on_shrink just re-initialized
        s = NamedSharding(Mesh(np.array(jax.devices()[:ndev]), ("x",)),
                          PartitionSpec())
        evaluated.append(s)
        return s

    with FaultInjector([FaultSpec("peer_death", at_step=5,
                                  target=2)]) as inj:
        job = _FleetJob(str(tmp_path))
        with pytest.warns(UserWarning, match="shrinking to healthy"):
            res = job.run(shrink_sharding=shrink_sharding)
        assert inj.fired
    assert res.mesh_shrinks == 1 and len(evaluated) == 1
    # the restored-and-replayed state lives on the shrunk device set
    for buf in job.opt._param_bufs:
        assert len(buf.sharding.device_set) == ndev
    _assert_tree_equal(job.opt.params, _fleet_reference.opt.params)
    job.close()


def test_shrink_recovery_rewinds_telemetry_and_resets_watchdog(
        tmp_path):
    """Replay parity with the watchdog rollback path: a shrink
    recovery must rewind the telemetry session (the flush watermark
    would otherwise silently drop the replayed steps' records) and
    reset watchdog detector state (stale history from the abandoned
    timeline must not re-trigger on replayed step numbers)."""
    from apex_tpu import telemetry as telemetry_mod
    from apex_tpu.resilience.watchdog import Detector

    class _ResetSpy(Detector):
        name = "spy"
        resets = 0

        def observe(self, records):
            return []

        def reset(self):
            self.resets += 1

    tel = telemetry_mod.Telemetry(run_dir=None, window=4,
                                  retrace=False)
    spy = _ResetSpy()
    wd = Watchdog(detectors=[spy], telemetry=tel, clean_window=2)
    job = _FleetJob(str(tmp_path))
    job.mon.telemetry = tel                   # the session the fleet
    #                                           events would ride
    rewinds = []
    orig_rewind = tel.rewind
    tel.rewind = lambda s: (rewinds.append(s), orig_rewind(s))[1]
    spy.resets = 0
    with FaultInjector([FaultSpec("peer_death", at_step=5,
                                  target=2)]):
        import warnings as _w
        with _w.catch_warnings():
            _w.simplefilter("ignore")
            res = job.run(watchdog=wd)
    assert res.mesh_shrinks == 1
    shrink = next(e for e in job.mon.events
                  if e.get("event") == "shrink")
    assert rewinds == [shrink["to_step"]]     # rewound to the restore
    assert spy.resets >= 1                    # detectors cleared
    wd.close()
    tel.close()
    job.close()


def test_shrink_budget_exhaustion_raises_typed(tmp_path):
    """Shrink recovery rides the shared RetryPolicy budget: with zero
    retries, a peer death raises FleetRecoveryFailed instead of
    looping."""
    with FaultInjector([FaultSpec("peer_death", at_step=2, target=2)]):
        job = _FleetJob(str(tmp_path))
        with pytest.raises(FleetRecoveryFailed):
            with pytest.warns(UserWarning):
                job.run(max_restarts=0)
    job.close()


def test_shrink_without_any_checkpoint_raises_typed(tmp_path):
    """A death before the first save has nothing to restore the
    survivors from: typed failure, not a silent fresh restart."""
    with FaultInjector([FaultSpec("peer_death", at_step=1, target=2)]):
        job = _FleetJob(str(tmp_path))
        job.mgr.every = 10_000                # no cadence save ever
        with pytest.raises(FleetRecoveryFailed):
            with pytest.warns(UserWarning):
                job.run()
    job.close()


def test_step_deadline_without_fleet_propagates(tmp_path):
    """A deadline conversion with no fleet monitor has nobody to
    agree a shrink with — the typed error propagates to the external
    scheduler."""
    job = _FleetJob(str(tmp_path))

    def hung_step(step):
        if step == 4:
            time.sleep(30.0)
        job.step_fn(step)

    t0 = time.monotonic()
    with pytest.raises(StepDeadlineExceeded) as ei:
        run_elastic(hung_step, job.mgr, job.opt, total_steps=_TOTAL,
                    params_like=job.template,
                    step_deadline=_test_calibrator(), backoff_s=0.0)
    assert ei.value.phase == "step" and ei.value.step == 4
    assert time.monotonic() - t0 < 15.0
    job.close()


def test_hung_save_converts_to_deadline(tmp_path):
    """The cadence save is deadline-armed too: a save blocked joining
    a hung in-flight write (slow NFS) converts instead of blocking
    forever."""
    job = _FleetJob(str(tmp_path))
    with FaultInjector([FaultSpec("slow_disk", at_save=0,
                                  delay_s=3.0)]):
        with pytest.raises(StepDeadlineExceeded) as ei:
            run_elastic(job.step_fn, job.mgr, job.opt,
                        total_steps=_TOTAL, params_like=job.template,
                        step_deadline=_test_calibrator(max_s=0.5),
                        backoff_s=0.0)
    assert ei.value.phase == "save"
    job.close()


def test_step_deadline_auto_calibrates_and_completes(tmp_path):
    """step_deadline='auto' must never false-positive on a healthy
    run: the calibrated deadline tracks the trailing baseline."""
    job = _FleetJob(str(tmp_path))
    res = job.run(step_deadline="auto")
    assert res.step == _TOTAL and res.mesh_shrinks == 0
    job.close()


# ---------------------------------------------------------------------
# Satellite: non-retryable errnos (ENOSPC) abort instead of burning
# the retry budget.
# ---------------------------------------------------------------------

def test_disk_full_aborts_without_burning_retry_budget(tmp_path):
    """An ENOSPC save failure goes straight to the abort path: no
    backoff sleeps, no restore-and-replay loop."""
    job = _FleetJob(str(tmp_path))
    slept = []
    with FaultInjector([FaultSpec("disk_full", at_save=0)]) as inj:
        with pytest.raises(OSError) as ei:
            with pytest.warns(UserWarning, match="non-retryable"):
                run_elastic(job.step_fn, job.mgr, job.opt,
                            total_steps=_TOTAL,
                            params_like=job.template,
                            sleep=slept.append)
        assert inj.fired
    assert ei.value.errno == errno.ENOSPC
    assert slept == []                        # zero retries attempted
    job.close()


def test_disk_full_writes_postmortem_with_watchdog(tmp_path):
    """With a watchdog attached, the non-retryable abort leaves the
    post-mortem bundle on disk before propagating."""
    job = _FleetJob(str(tmp_path / "ckpt"))
    pm_dir = str(tmp_path / "pm")
    wd = Watchdog(detectors=[], clean_window=4, postmortem_dir=pm_dir)
    with FaultInjector([FaultSpec("disk_full", at_save=0)]):
        with pytest.raises(OSError):
            with pytest.warns(UserWarning, match="non-retryable"):
                run_elastic(job.step_fn, job.mgr, job.opt,
                            total_steps=_TOTAL,
                            params_like=job.template, watchdog=wd)
    bundles = [d for d in os.listdir(pm_dir)
               if d.startswith("postmortem-")]
    assert bundles, "no post-mortem bundle written"
    job.close()


def test_transient_oserror_still_retries(tmp_path):
    """The classification must not over-reach: a garden-variety
    transient OSError keeps the existing bounded retry behavior."""
    job = _FleetJob(str(tmp_path))
    job.opt.step(job.g)
    job.mgr.save(3, optimizer=job.opt)
    job.mgr.wait()
    failed = []

    def flaky(step):
        if step == 5 and not failed:
            failed.append(step)
            raise OSError(errno.EIO, "transient")
        job.step_fn(step)

    with pytest.warns(UserWarning, match="restoring newest"):
        res = run_elastic(flaky, job.mgr, job.opt, total_steps=_TOTAL,
                          params_like=job.template, backoff_s=0.0)
    assert res.restarts == 1 and res.step == _TOTAL
    job.close()


# ---------------------------------------------------------------------
# Satellite: dead-host stale-.tmp GC.
# ---------------------------------------------------------------------

def test_gc_dead_host_tmp_scoped_to_dead_hosts_only(tmp_path):
    """The agreed lowest-rank survivor clears a DEAD peer's orphaned
    .tmp files — never a live peer's (their .tmp may be an in-flight
    write) and never published checkpoints."""
    mgr = CheckpointManager(str(tmp_path), keep=2, every=5,
                            all_hosts=True)
    dead_tmp = tmp_path / "step-5.p2.ckpt.tmp"
    live_tmp = tmp_path / "step-5.p1.ckpt.tmp"
    published = tmp_path / "step-5.p2.ckpt"
    for p in (dead_tmp, live_tmp, published):
        p.write_bytes(b"x")
    # a non-lowest-rank survivor must not sweep
    assert mgr.gc_dead_host_tmp([2], [0, 1], rank=1) == 0
    assert dead_tmp.exists()
    # the lowest-rank survivor sweeps exactly the dead host's .tmp
    assert mgr.gc_dead_host_tmp([2], [0, 1], rank=0) == 1
    assert not dead_tmp.exists()
    assert live_tmp.exists() and published.exists()
    mgr.close()


def test_gc_dead_host_tmp_single_writer_form(tmp_path):
    """With all_hosts=False only host 0 writes the plain .ckpt.tmp
    shape — swept only when host 0 itself is among the dead, by the
    new lowest-rank survivor."""
    mgr = CheckpointManager(str(tmp_path), keep=2, every=5)
    orphan = tmp_path / "step-7.ckpt.tmp"
    orphan.write_bytes(b"x")                  # after init (own-suffix GC)
    # host 0 alive: nobody touches its tmp
    assert mgr.gc_dead_host_tmp([2], [0, 1], rank=0) == 0
    assert orphan.exists()
    # host 0 dead: survivor 1 sweeps it
    assert mgr.gc_dead_host_tmp([0], [1, 2], rank=1) == 1
    assert not orphan.exists()
    mgr.close()


# ---------------------------------------------------------------------
# Telemetry: fleet events ride the session flush; summarize renders
# the fleet timeline in text and --json.
# ---------------------------------------------------------------------

def test_fleet_events_land_in_session_jsonl_and_summarize(tmp_path):
    from apex_tpu import telemetry as telemetry_mod
    from apex_tpu.telemetry.cli import summarize

    run_dir = str(tmp_path / "run")
    tel = telemetry_mod.Telemetry(run_dir, window=4, retrace=False)
    ch = LocalChannel()
    mon = _lag_monitor(ch, slow=2, dead=4, telemetry=tel)
    sim = SimulatedPeers(ch, hosts=[1, 2]).attach(mon)
    for s in range(1, 4):
        tel.record({"loss": 1.0}, s)
        mon.beat(s)
    sim.kill(2)
    for s in range(4, 12):
        tel.record({"loss": 1.0}, s)
        mon.beat(s)
    epoch, survivors = mon.agree_survivors(11, timeout_s=0.2)
    mon.note_shrink(11, epoch, survivors, [2], restored_step=9)
    mon.close()
    tel.close()

    recs = [json.loads(l) for l in
            open(os.path.join(run_dir, "telemetry.jsonl"))]
    fleet_recs = [r for r in recs if r.get("kind") == "fleet"]
    assert {r["event"] for r in fleet_recs} == \
        {"host_slow", "host_dead", "shrink"}
    counters = {r["name"] for r in recs if r.get("kind") == "counter"}
    assert {"fleet/hosts_live", "fleet/hosts_dead",
            "fleet/beacon_lag_steps", "fleet/mesh_shrinks"} <= counters

    import io
    out = io.StringIO()
    assert summarize(run_dir, out=out) == 0
    text = out.getvalue()
    assert "fleet timeline:" in text
    assert "host_dead" in text and "shrink" in text
    assert "survivors=[0, 1]" in text

    out = io.StringIO()
    assert summarize(run_dir, as_json=True, out=out) == 0
    doc = json.loads(out.getvalue())
    assert [e["event"] for e in doc["fleet"]].count("host_dead") == 1
    assert any(e["event"] == "shrink" for e in doc["fleet"])


def test_summarize_without_fleet_records_has_no_timeline(tmp_path):
    from apex_tpu.telemetry.cli import summarize
    import io
    p = tmp_path / "telemetry.jsonl"
    p.write_text('{"kind": "step", "step": 1, "loss": 1.0}\n')
    out = io.StringIO()
    assert summarize(str(tmp_path), out=out) == 0
    assert "fleet timeline:" not in out.getvalue()


# ---------------------------------------------------------------------
# Bench smoke (tier-1: proves the harness, not performance) + result
# surface.
# ---------------------------------------------------------------------

def test_fleet_overhead_bench_smoke():
    from apex_tpu.telemetry.bench import bench_fleet_overhead
    r = bench_fleet_overhead(layers=2, hidden=16, window=8, n_hosts=3,
                             iters=2, reps=1)
    assert r["fleet_on_ms"] > 0 and r["fleet_off_ms"] > 0
    assert r["fleet_beat_ms"] >= 0
    assert r["fleet_hosts"] == 3


def test_elastic_result_mesh_shrinks_defaults_zero():
    from apex_tpu.resilience import ElasticResult
    res = ElasticResult(step=1, preempted=False, restarts=0,
                        restored_from=None)
    assert res.mesh_shrinks == 0 and res.rollbacks == 0
