"""Live telemetry export: the MetricsServer scrape surface
(telemetry/export.py) — /metrics Prometheus text, /healthz, the
observer/sink/emitter intake paths, the zero-added-sync contract's
runtime smoke, and the exporter-overhead bench harness."""

import json
import urllib.request

import jax.numpy as jnp
import pytest

from apex_tpu import telemetry
from apex_tpu.telemetry import hostmetrics
from apex_tpu.telemetry.export import (MetricsServer, metric_name,
                                       render_prometheus)


def _get(url):
    with urllib.request.urlopen(url, timeout=5) as r:
        return r.status, r.read().decode("utf-8")


def _gauges(body):
    out = {}
    for line in body.splitlines():
        if line.startswith("#") or not line.strip():
            continue
        name, val = line.rsplit(" ", 1)
        out[name] = float(val)
    return out


def test_metric_name_sanitization():
    assert metric_name("loss") == "apex_tpu_loss"
    assert metric_name("amp/grad_norm") == "apex_tpu_amp_grad_norm"
    assert metric_name("fleet/hosts-dead") == "apex_tpu_fleet_hosts_dead"


def test_render_prometheus_deterministic():
    body = render_prometheus({"b": 2.0, "a": 1.0},
                             {("c", (("k", "v"),)): 3.0})
    assert body.splitlines() == [
        "# TYPE a gauge", "a 1", "# TYPE b gauge", "b 2",
        "# TYPE c gauge", 'c{k="v"} 3']


def test_serves_metrics_and_healthz_and_404():
    with telemetry.Telemetry(run_dir=None, window=4,
                             retrace=False) as tel, \
            MetricsServer(telemetry=tel, port=0) as srv:
        for s in range(1, 4):
            tel.record({"loss": jnp.float32(2.0 - 0.5 * s)}, s)
        tel.flush()
        status, body = _get(f"{srv.url}/metrics")
        assert status == 200
        g = _gauges(body)
        # newest step's value wins (gauges), and the watermark gauge
        # says how fresh the scrape is
        assert g["apex_tpu_loss"] == 0.5
        assert g["apex_tpu_exported_step"] == 3
        assert g["apex_tpu_up"] == 1
        status, body = _get(f"{srv.url}/healthz")
        assert status == 200
        h = json.loads(body)
        assert h["status"] == "ok" and h["exported_step"] == 3
        with pytest.raises(urllib.error.HTTPError):
            _get(f"{srv.url}/nope")


def test_hostmetrics_sink_flips_live_without_a_flush():
    """The liveness gauges must flip the instant the producer emits
    (beat cadence), NOT a window later: fleet/hosts_dead rides the
    hostmetrics sink straight into the snapshot, and the monotone
    _total lets a scraper that missed the flip still see it."""
    with telemetry.Telemetry(run_dir=None, window=64,
                             retrace=False) as tel, \
            MetricsServer(telemetry=tel, port=0) as srv:
        hostmetrics.emit("fleet/hosts_dead", 0)
        g = _gauges(_get(f"{srv.url}/metrics")[1])
        assert g["apex_tpu_fleet_hosts_dead"] == 0
        assert g["apex_tpu_fleet_hosts_dead_total"] == 0
        hostmetrics.emit("fleet/hosts_dead", 1)   # no flush happened
        g = _gauges(_get(f"{srv.url}/metrics")[1])
        assert g["apex_tpu_fleet_hosts_dead"] == 1
        assert g["apex_tpu_fleet_hosts_dead_total"] == 1
        hostmetrics.emit("fleet/hosts_dead", 0)   # shrink recovered
        g = _gauges(_get(f"{srv.url}/metrics")[1])
        assert g["apex_tpu_fleet_hosts_dead"] == 0
        assert g["apex_tpu_fleet_hosts_dead_total"] == 1  # monotone


def test_event_records_count_by_kind_and_incident_gauge():
    """The emitter fan-out hands the exporter the EVENT records; it
    counts them by kind and keeps the open-incident flag keyed by the
    correlation id (1 while open, 0 once the chain closes)."""
    with telemetry.Telemetry(run_dir=None, window=4,
                             retrace=False) as tel, \
            MetricsServer(telemetry=tel, port=0) as srv:
        srv.emit([
            {"kind": "anomaly", "anomaly": "nan_streak", "step": 5,
             "incident_id": "inc-001-nan_streak-e0"},
            {"kind": "watchdog", "action": "rollback", "step": 5,
             "incident_id": "inc-001-nan_streak-e0"},
            {"kind": "fleet", "event": "shrink", "step": 7},
            {"kind": "fleet", "event": "autoscale", "action": "grow",
             "step": 9},
        ])
        g = _gauges(_get(f"{srv.url}/metrics")[1])
        assert g["apex_tpu_anomaly_nan_streak_events_total"] == 1
        assert g["apex_tpu_watchdog_rollback_events_total"] == 1
        assert g["apex_tpu_fleet_shrink_events_total"] == 1
        assert g["apex_tpu_autoscale_grow_events_total"] == 1
        body = _get(f"{srv.url}/metrics")[1]
        assert ('apex_tpu_incident_open'
                '{incident_id="inc-001-nan_streak-e0"} 1') in body
        srv.emit([{"kind": "watchdog", "action": "replay_complete",
                   "step": 9,
                   "incident_id": "inc-001-nan_streak-e0"}])
        body = _get(f"{srv.url}/metrics")[1]
        assert ('apex_tpu_incident_open'
                '{incident_id="inc-001-nan_streak-e0"} 0') in body


def test_close_is_idempotent_and_detaches():
    tel = telemetry.Telemetry(run_dir=None, window=4, retrace=False)
    srv = MetricsServer(telemetry=tel, port=0)
    url = srv.url
    tel.record({"loss": jnp.float32(1.0)}, 1)
    tel.flush()
    assert _get(f"{url}/metrics")[0] == 200
    srv.close()
    srv.close()                              # idempotent
    # detached: a later flush must not touch the dead server
    tel.record({"loss": jnp.float32(2.0)}, 2)
    tel.flush()
    with pytest.raises(OSError):
        _get(f"{url}/metrics")
    tel.close()                              # emitter close: no raise


def test_large_integer_gauges_render_exact():
    """{:g} would truncate exported_step past 999999 (long pretrains
    cross 1e6 steps routinely) — integral samples must print exact."""
    from apex_tpu.telemetry.export import render_prometheus
    body = render_prometheus({"apex_tpu_exported_step": 1234567.0,
                              "apex_tpu_loss": 0.123456789012}, {})
    assert "apex_tpu_exported_step 1234567" in body
    assert "1.23457e" not in body
    assert "apex_tpu_loss 0.123456789" in body


def test_closed_incident_labels_are_pruned_bounded():
    """Label cardinality stays bounded: the newest closed incident is
    kept (a scraper must see the 1 -> 0 flip) but older closed ids are
    pruned — a week of incidents must not grow a label series each."""
    with telemetry.Telemetry(run_dir=None, window=4,
                             retrace=False) as tel, \
            MetricsServer(telemetry=tel, port=0) as srv:
        for n in range(1, 4):
            iid = f"inc-{n:03d}-host_dead-h2.{n}-e0"
            srv.emit([{"kind": "fleet", "event": "host_dead",
                       "step": n, "incident_id": iid}])
            srv.emit([{"kind": "fleet", "event": "replay_complete",
                       "step": n, "incident_id": iid}])
        body = _get(f"{srv.url}/metrics")[1]
        open_lines = [l for l in body.splitlines()
                      if l.startswith("apex_tpu_incident_open{")]
        assert open_lines == [
            'apex_tpu_incident_open'
            '{incident_id="inc-003-host_dead-h2.3-e0"} 0']


def test_two_servers_on_one_session_both_close_with_it():
    """Telemetry.close() iterates a snapshot: an emitter whose close
    detaches it (the server) must not make the one registered after
    it skip its own close."""
    tel = telemetry.Telemetry(run_dir=None, window=4, retrace=False)
    a = MetricsServer(telemetry=tel, port=0)
    b = MetricsServer(telemetry=tel, port=0)
    url_a, url_b = a.url, b.url
    tel.close()
    for url in (url_a, url_b):
        with pytest.raises(OSError):
            _get(f"{url}/metrics")
    assert a._closed and b._closed


def test_session_close_also_closes_attached_server():
    tel = telemetry.Telemetry(run_dir=None, window=4, retrace=False)
    srv = MetricsServer(telemetry=tel, port=0)
    url = srv.url
    tel.close()                 # emitter fan-out closes the server
    with pytest.raises(OSError):
        _get(f"{url}/metrics")


def test_exported_instrumented_step_adds_no_device_sync():
    """The runtime twin of the telemetry.exported_step apexverify
    spec: an instrumented step with the exporter attached traces to
    the SAME jaxpr as without it — the scrape surface reads flushed
    host data only."""
    import jax

    def step(x):
        telemetry.emit_metric("loss", x.sum())
        return x * 2.0

    tel = telemetry.Telemetry(run_dir=None, window=4, retrace=False)
    x = jnp.ones((4,))
    bare = jax.make_jaxpr(tel.instrument(step))(tel.buf,
                                                jnp.int32(0), x)
    srv = MetricsServer(telemetry=tel, port=0)
    exported = jax.make_jaxpr(tel.instrument(step))(tel.buf,
                                                    jnp.int32(0), x)
    assert str(bare) == str(exported)
    srv.close()
    tel.close()


def test_exported_step_spec_registered():
    from apex_tpu.lint import semantic
    names = [s.name for s in semantic.all_specs()]
    assert "telemetry.exported_step" in names


def test_exporter_overhead_bench_smoke():
    from apex_tpu.telemetry.bench import bench_exporter_overhead
    r = bench_exporter_overhead(layers=2, hidden=16, window=8,
                                iters=2, reps=1)
    assert r["exporter_on_ms"] > 0 and r["exporter_off_ms"] > 0
    assert r["export_publish_ms"] >= 0
    assert r["exporter_window"] == 8


def test_controller_signal_source_feeds_queue_window():
    """FleetController(signal_source=): an external load signal (a
    serving admission queue, anything outside the ring schema) rides
    the same hysteresis window as the queue metric — the PR-12
    follow-up."""
    from apex_tpu.resilience.fleet import FleetController
    box = {"depth": 100.0}
    ctrl = FleetController(queue_high=10.0, queue_low=1.0,
                           signal_source=lambda: box["depth"],
                           window=4, patience=2, cooldown_steps=0)
    try:
        d1 = ctrl.decide(1, n_hosts=2, candidates=1)
        assert d1.action == "stay" and d1.reason == "patience"
        d2 = ctrl.decide(2, n_hosts=2, candidates=1)
        assert d2.action == "grow" and d2.reason == "queue_depth"
        assert d2.signal == 100.0
        # the source may return None (no sample) and may even raise —
        # a broken gauge must never kill the supervisor loop
        box["depth"] = None
        ctrl.decide(3, n_hosts=2, candidates=1)

        def boom():
            raise RuntimeError("gauge down")
        ctrl.signal_source = boom
        ctrl.decide(4, n_hosts=2, candidates=1)
    finally:
        ctrl.close()


def test_controller_still_requires_some_queue_carrier():
    from apex_tpu.resilience.fleet import FleetController
    with pytest.raises(ValueError, match="signal_source"):
        FleetController(queue_high=10.0)
