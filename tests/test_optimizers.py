"""Fused-optimizer facade contracts (reference model:
tests/L0/run_optimizers — here the ctor-level masters contract;
numeric step parity lives in test_multi_tensor.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest


class TestMastersContract:
    """masters= ctor contract (apex O2: masters come from the ORIGINAL
    f32 init, not from re-upcasting rounded half params)."""

    def _params(self, dtype):
        return {"w": jnp.ones((8, 8), dtype), "b": jnp.zeros((8,), dtype)}

    def test_external_masters_used_verbatim(self):
        from apex_tpu.optimizers import FusedSGD
        p32 = self._params(jnp.float32)
        # perturb below bf16 resolution: must survive into the masters
        p32 = jax.tree_util.tree_map(lambda x: x + 1e-4, p32)
        pbf = jax.tree_util.tree_map(
            lambda x: x.astype(jnp.bfloat16), p32)
        opt = FusedSGD(pbf, lr=0.1, masters=p32)
        np.testing.assert_array_equal(np.asarray(opt.masters["w"]),
                                      np.asarray(p32["w"]))

    def test_masters_with_master_weights_false_raises(self):
        from apex_tpu.optimizers import FusedSGD
        pbf = self._params(jnp.bfloat16)
        with pytest.raises(ValueError, match="contradictory"):
            FusedSGD(pbf, lr=0.1, master_weights=False,
                     masters=self._params(jnp.float32))

    def test_masters_for_f32_params_raises(self):
        from apex_tpu.optimizers import FusedSGD
        with pytest.raises(ValueError, match="low-precision"):
            FusedSGD(self._params(jnp.float32), lr=0.1,
                     masters=self._params(jnp.float32))

    def test_masters_structure_mismatch_raises(self):
        from apex_tpu.optimizers import FusedSGD
        pbf = self._params(jnp.bfloat16)
        with pytest.raises(ValueError, match="structure"):
            FusedSGD(pbf, lr=0.1, masters={"w": jnp.ones((8, 8))})


def test_offload_state_matches_resident_adam():
    """offload_state=True (opt state in pinned host memory) must step
    identically to the resident optimizer; off-TPU the eager fallback
    round-trips the state per step."""
    from apex_tpu.optimizers import FusedAdam
    params = {"w": jax.random.normal(jax.random.PRNGKey(0), (256,)),
              "b": jnp.zeros((16,))}
    g = {"w": jax.random.normal(jax.random.PRNGKey(1), (256,)) * 0.1,
         "b": jnp.full((16,), 0.01)}
    ref = FusedAdam(params, lr=1e-2, weight_decay=0.01)
    off = FusedAdam(params, lr=1e-2, weight_decay=0.01,
                    offload_state=True)
    # pinned_host where the backend exposes it; older-jax CPU backends
    # name their only (host) space unpinned_host
    for leaf in jax.tree_util.tree_leaves(off.opt_state):
        assert leaf.sharding.memory_kind in ("pinned_host",
                                             "unpinned_host")
    for _ in range(3):
        ref.step(g)
        off.step(g)
    for a, b in zip(jax.tree_util.tree_leaves(ref.params),
                    jax.tree_util.tree_leaves(off.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-6)
    # state stays host-resident after stepping
    for leaf in jax.tree_util.tree_leaves(off.opt_state):
        assert leaf.sharding.memory_kind in ("pinned_host",
                                             "unpinned_host")


def test_offload_fused_step_lowers_for_tpu():
    """The TPU fused-offload path (in-jit host->device pull +
    out_shardings push-back) must lower for the tpu platform — AOT,
    no device needed (same tier as tests/test_tpu_lowering.py)."""
    import apex_tpu.optimizers._base as base
    from apex_tpu.optimizers import FusedAdam
    params = {"w": jnp.zeros((128,))}
    opt = FusedAdam(params, lr=1e-3, offload_state=True)
    assert not opt._fused_offload          # built on CPU: eager mode
    # build the fused jit the TPU branch would have built (bucketed
    # path: params travel as the packed per-bucket buffers)
    fused = jax.jit(
        opt._full_step_offload,
        out_shardings=(None, None,
                       jax.tree_util.tree_map(base._host_sharding,
                                              opt.opt_state)))
    g = {"w": jnp.ones((128,))}
    hypers = {"lr": jnp.float32(1e-3)}
    fused.trace(opt._param_bufs, None, opt.opt_state, g, jnp.int32(1),
                jnp.float32(1.0), hypers, None).lower(
        lowering_platforms=("tpu",))


def test_offload_state_rehomed_on_restore():
    """load_state_dict must land the restored state back in pinned host
    memory immediately (code-review r2 finding)."""
    from apex_tpu.optimizers import FusedAdam
    params = {"w": jnp.ones((64,))}
    opt = FusedAdam(params, lr=1e-3, offload_state=True)
    opt.step({"w": jnp.full((64,), 0.1)})
    sd = opt.state_dict()
    # device-resident copy of the state, as a checkpoint restore gives
    sd["state"] = jax.tree_util.tree_map(
        lambda x: jax.device_put(
            np.asarray(x), jax.devices()[0]), sd["state"])
    opt2 = FusedAdam(params, lr=1e-3, offload_state=True)
    opt2.load_state_dict(sd)
    for leaf in jax.tree_util.tree_leaves(opt2.opt_state):
        assert leaf.sharding.memory_kind in ("pinned_host",
                                             "unpinned_host")


def test_state_dict_snapshot_survives_donating_step():
    """step() donates opt_state to the compiled update; a state_dict
    taken BEFORE that step must stay readable (it must not alias the
    soon-to-be-deleted buffers), and a restored checkpoint dict must
    likewise survive the restoring optimizer's next step."""
    from apex_tpu.optimizers import FusedAdam
    params = {"w": jnp.ones((8,))}
    g = {"w": jnp.full((8,), 0.5)}
    opt = FusedAdam(params, lr=1e-2)
    opt.step(g)
    sd = opt.state_dict()
    opt.step(g)                       # donates the live opt_state
    for leaf in jax.tree_util.tree_leaves(sd["state"]):
        np.asarray(leaf)              # snapshot buffers still alive

    opt2 = FusedAdam(params, lr=1e-2)
    opt2.load_state_dict(sd)
    opt2.step(g)
    for leaf in jax.tree_util.tree_leaves(sd["state"]):
        np.asarray(leaf)              # checkpoint dict still alive
