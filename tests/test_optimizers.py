"""Fused-optimizer facade contracts (reference model:
tests/L0/run_optimizers — here the ctor-level masters contract;
numeric step parity lives in test_multi_tensor.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest


class TestMastersContract:
    """masters= ctor contract (apex O2: masters come from the ORIGINAL
    f32 init, not from re-upcasting rounded half params)."""

    def _params(self, dtype):
        return {"w": jnp.ones((8, 8), dtype), "b": jnp.zeros((8,), dtype)}

    def test_external_masters_used_verbatim(self):
        from apex_tpu.optimizers import FusedSGD
        p32 = self._params(jnp.float32)
        # perturb below bf16 resolution: must survive into the masters
        p32 = jax.tree_util.tree_map(lambda x: x + 1e-4, p32)
        pbf = jax.tree_util.tree_map(
            lambda x: x.astype(jnp.bfloat16), p32)
        opt = FusedSGD(pbf, lr=0.1, masters=p32)
        np.testing.assert_array_equal(np.asarray(opt.masters["w"]),
                                      np.asarray(p32["w"]))

    def test_masters_with_master_weights_false_raises(self):
        from apex_tpu.optimizers import FusedSGD
        pbf = self._params(jnp.bfloat16)
        with pytest.raises(ValueError, match="contradictory"):
            FusedSGD(pbf, lr=0.1, master_weights=False,
                     masters=self._params(jnp.float32))

    def test_masters_for_f32_params_raises(self):
        from apex_tpu.optimizers import FusedSGD
        with pytest.raises(ValueError, match="low-precision"):
            FusedSGD(self._params(jnp.float32), lr=0.1,
                     masters=self._params(jnp.float32))

    def test_masters_structure_mismatch_raises(self):
        from apex_tpu.optimizers import FusedSGD
        pbf = self._params(jnp.bfloat16)
        with pytest.raises(ValueError, match="structure"):
            FusedSGD(pbf, lr=0.1, masters={"w": jnp.ones((8, 8))})
