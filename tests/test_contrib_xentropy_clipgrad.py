"""contrib.xentropy + contrib.clip_grad vs stock-JAX oracles (reference
test pattern: apex/contrib/test/xentropy/test_label_smoothing.py — fused
kernel vs pure-framework oracle under per-dtype tolerances)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.contrib.clip_grad import clip_grad_norm_
from apex_tpu.contrib.xentropy import SoftmaxCrossEntropyLoss
from apex_tpu.ops.xentropy import (
    softmax_cross_entropy,
    softmax_cross_entropy_ref,
)


def _data(n, c, dtype, seed=0):
    k1, k2 = jax.random.split(jax.random.PRNGKey(seed))
    logits = jax.random.normal(k1, (n, c), jnp.float32).astype(dtype) * 2.0
    labels = jax.random.randint(k2, (n,), 0, c)
    return logits, labels


@pytest.mark.parametrize("dtype,tol", [(jnp.float32, 1e-5),
                                       (jnp.bfloat16, 2e-2)])
@pytest.mark.parametrize("smoothing", [0.0, 0.1])
@pytest.mark.parametrize("c", [128, 1000])   # 1000: non-lane-aligned fallback
def test_xentropy_forward(dtype, tol, smoothing, c):
    logits, labels = _data(64, c, dtype)
    got = softmax_cross_entropy(logits, labels, smoothing)
    want = softmax_cross_entropy_ref(logits, labels, smoothing)
    np.testing.assert_allclose(np.asarray(got, np.float32),
                               np.asarray(want, np.float32),
                               rtol=tol, atol=tol)


@pytest.mark.parametrize("smoothing", [0.0, 0.1])
def test_xentropy_grad_matches_autodiff_oracle(smoothing):
    logits, labels = _data(32, 256, jnp.float32, seed=1)

    got = jax.grad(lambda x: jnp.mean(
        softmax_cross_entropy(x, labels, smoothing)))(logits)
    want = jax.grad(lambda x: jnp.mean(
        softmax_cross_entropy_ref(x, labels, smoothing)))(logits)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_xentropy_padding_idx_zeroes_loss_and_grad():
    logits, labels = _data(16, 128, jnp.float32, seed=2)
    labels = labels.at[::4].set(0)   # padding_idx = 0
    losses = SoftmaxCrossEntropyLoss.apply(logits, labels, 0.1, 0)
    assert np.all(np.asarray(losses)[::4] == 0.0)
    g = jax.grad(lambda x: jnp.sum(
        SoftmaxCrossEntropyLoss.apply(x, labels, 0.1, 0)))(logits)
    assert np.all(np.asarray(g)[::4] == 0.0)
    assert np.any(np.asarray(g)[1::4] != 0.0)


def test_xentropy_half_to_float_dtype():
    logits, labels = _data(8, 128, jnp.bfloat16)
    assert softmax_cross_entropy(logits, labels, 0.0, True).dtype == \
        jnp.float32
    assert softmax_cross_entropy(logits, labels, 0.0, False).dtype == \
        jnp.bfloat16


def test_clip_grad_norm_clips_and_reports():
    grads = {"w": jnp.full((64, 64), 1.0), "b": jnp.full((64,), -2.0)}
    flat = jnp.concatenate([g.ravel() for g in
                            jax.tree_util.tree_leaves(grads)])
    expect_norm = float(jnp.linalg.norm(flat))
    clipped, total = clip_grad_norm_(grads, max_norm=1.0)
    assert abs(float(total) - expect_norm) < 1e-3
    cflat = jnp.concatenate([g.ravel() for g in
                             jax.tree_util.tree_leaves(clipped)])
    assert abs(float(jnp.linalg.norm(cflat)) - 1.0) < 1e-3
    # direction preserved
    np.testing.assert_allclose(np.asarray(cflat) * expect_norm,
                               np.asarray(flat), rtol=1e-3)


def test_clip_grad_norm_noop_below_threshold():
    grads = [jnp.ones((8, 8)) * 1e-3]
    clipped, total = clip_grad_norm_(grads, max_norm=10.0)
    np.testing.assert_allclose(np.asarray(clipped[0]),
                               np.asarray(grads[0]), rtol=1e-5)


def test_clip_grad_norm_inf_norm():
    grads = [jnp.asarray([1.0, -5.0, 3.0])]
    clipped, total = clip_grad_norm_(grads, max_norm=1.0,
                                     norm_type=float("inf"))
    assert abs(float(total) - 5.0) < 1e-5
    assert abs(float(jnp.max(jnp.abs(clipped[0]))) - 1.0) < 1e-3
