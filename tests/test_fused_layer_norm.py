"""Fused LayerNorm/RMSNorm vs jnp and torch oracles (reference model:
tests/L0/run_fused_layer_norm/test_fused_layer_norm.py — fused kernel
vs torch.nn.LayerNorm across a dtype x affine x shape grid)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import normalization
from apex_tpu.ops import layer_norm as ln


def _tol(dtype):
    return dict(rtol=2e-2, atol=2e-2) if dtype == jnp.bfloat16 else \
        dict(rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
@pytest.mark.parametrize("h", [128, 1024, 80])   # 80: non-128-multiple path
@pytest.mark.parametrize("rms", [False, True])
def test_fused_norm_matches_jnp_ref(h, rms, dtype):
    rows = 64
    x = (jax.random.normal(jax.random.key(0), (rows, h)) * 3 + 1
         ).astype(dtype)
    w = (jax.random.normal(jax.random.key(1), (h,)) * 0.1 + 1.0
         ).astype(jnp.float32)
    b = (jax.random.normal(jax.random.key(2), (h,)) * 0.1
         ).astype(jnp.float32)
    if rms:
        y = ln.fused_rms_norm(x, w)
        want = ln.rms_norm_ref(x, w)
    else:
        y = ln.fused_layer_norm(x, w, b)
        want = ln.layer_norm_ref(x, w, b)
    np.testing.assert_allclose(np.asarray(y, np.float32),
                               np.asarray(want, np.float32), **_tol(dtype))


@pytest.mark.parametrize("h", [128, 1024])
@pytest.mark.parametrize("rms", [False, True])
def test_fused_norm_grads_match_ref(h, rms):
    rows = 32
    x = jax.random.normal(jax.random.key(3), (rows, h)) * 2
    w = jax.random.normal(jax.random.key(4), (h,)) * 0.1 + 1.0
    b = jax.random.normal(jax.random.key(5), (h,)) * 0.1

    if rms:
        fused = lambda x, w: jnp.sum(ln.fused_rms_norm(x, w) ** 2)
        ref = lambda x, w: jnp.sum(ln.rms_norm_ref(x, w) ** 2)
        args = (x, w)
    else:
        fused = lambda x, w, b: jnp.sum(ln.fused_layer_norm(x, w, b) ** 2)
        ref = lambda x, w, b: jnp.sum(ln.layer_norm_ref(x, w, b) ** 2)
        args = (x, w, b)
    g = jax.grad(fused, argnums=tuple(range(len(args))))(*args)
    g_ref = jax.grad(ref, argnums=tuple(range(len(args))))(*args)
    for a, b_ in zip(g, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("h", [64, 256])
def test_layer_norm_matches_torch_oracle(h):
    """The reference's own oracle: torch.nn.LayerNorm, same weights."""
    torch = pytest.importorskip("torch")
    rows = 16
    rng = np.random.default_rng(0)
    x = rng.normal(size=(rows, h)).astype(np.float32) * 2 + 0.5
    w = rng.normal(size=h).astype(np.float32) * 0.2 + 1.0
    b = rng.normal(size=h).astype(np.float32) * 0.1

    m = torch.nn.LayerNorm(h, eps=1e-5)
    with torch.no_grad():
        m.weight.copy_(torch.from_numpy(w))
        m.bias.copy_(torch.from_numpy(b))
    want = m(torch.from_numpy(x)).detach().numpy()

    y = ln.fused_layer_norm(jnp.asarray(x), jnp.asarray(w),
                            jnp.asarray(b))
    np.testing.assert_allclose(np.asarray(y), want, rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("cls,rms", [
    (normalization.FusedLayerNorm, False),
    (normalization.FusedRMSNorm, True),
    (normalization.MixedFusedLayerNorm, False),
    (normalization.MixedFusedRMSNorm, True),
])
def test_module_classes(cls, rms):
    h = 256
    m = cls(h)
    x = jax.random.normal(jax.random.key(6), (8, h))
    params = m.init(jax.random.key(7), x)
    y = m.apply(params, x)
    want = ln.rms_norm_ref(x) if rms else ln.layer_norm_ref(x)
    np.testing.assert_allclose(np.asarray(y), np.asarray(want),
                               rtol=1e-5, atol=1e-5)
    # affine params exist and are trainable
    leaves = jax.tree_util.tree_leaves(params)
    assert len(leaves) >= 1
    g = jax.grad(lambda p: jnp.sum(m.apply(p, x) ** 2))(params)
    assert all(bool(jnp.all(jnp.isfinite(l)))
               for l in jax.tree_util.tree_leaves(g))


def test_no_affine_paths():
    h = 128
    x = jax.random.normal(jax.random.key(8), (8, h))
    np.testing.assert_allclose(
        np.asarray(ln.fused_layer_norm(x)),
        np.asarray(ln.layer_norm_ref(x)), rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(
        np.asarray(ln.fused_rms_norm(x)),
        np.asarray(ln.rms_norm_ref(x)), rtol=1e-5, atol=1e-5)
