"""Test harness: simulate an 8-chip topology on CPU host devices.

The reference cannot simulate multi-GPU (SURVEY.md §4: distributed tests
skip without >=2 real GPUs).  JAX can: force 8 host-platform devices and
run every DP/TP/PP/SP suite on a real Mesh in one process.  Pallas kernels
run in interpreter mode off-TPU (apex_tpu.ops._dispatch).

Environment note: sitecustomize registers the axon TPU PJRT plugin in
every Python process and overrides platform selection, so env vars set
here are too late — we must flip the already-imported jax config to CPU
BEFORE the first backend use (otherwise the first jax.devices() call
blocks trying to claim the TPU tunnel).
"""

import os

import jax

# import-time env reads are THE POINT here: the backend must be chosen
# before the first jax.devices() call (module docstring), so they
# cannot move into a function called later.
if os.environ.get("APEX_TPU_SMOKE") == "1":   # apexlint: disable=APX601
    # TPU smoke mode (tests/test_tpu_smoke.py): keep the real backend and
    # persist compiled executables so re-runs skip the slow first compile.
    jax.config.update(
        "jax_compilation_cache_dir",
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), ".jax_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
else:
    jax.config.update("jax_platforms", "cpu")
    _flags = os.environ.get("XLA_FLAGS", "")   # apexlint: disable=APX601
    if "--xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + " --xla_force_host_platform_device_count=8").strip()

import pytest  # noqa: E402

# ---- speed tiers (VERDICT r2 #9) -----------------------------------
# The box CI runs on has ONE core (no xdist win), so the fast tier is
# a marker filter: `-m "not slow"` (the tests/run_test.py default)
# finishes in ~5 min; the nightly full tier runs everything.  Slow
# tests are listed HERE, centrally, so the list can be regenerated
# from `pytest --durations=60` without touching every file; the
# threshold for membership is ≥ ~5s of single-test wall time.
SLOW_MODULES = {
    "test_L1_trajectory.py",      # reference L1 tier: whole-training
    "test_examples_smoke.py",     # reference L6 tier: runs examples
    "test_distributed_launch.py",  # spawns multi-process jax workers
}
SLOW_TESTS = {
    "test_grad_accum.py::test_overlap_schedule_bench_smoke",
    "test_models.py::test_gpt_single_device_loss_decreases",
    "test_models.py::test_resnet18_forward_and_train_step",
    "test_models.py::test_gpt_tp_matches_tp1",
    "test_models.py::test_gpt_packed_tp_matches_tp1",
    "test_models.py::test_gpt_packed_batch_matches_per_sequence",
    "test_models.py::test_bert_packed_batch_matches_per_sequence",
    "test_models.py::test_gpt_tp_GRADS_match_tp1",
    "test_models.py::test_bert_tp_GRADS_match_tp1",
    "test_models.py::test_4d_assembly_grads_match_single_device",
    "test_models.py::test_bert_tp_matches_tp1",
    "test_models.py::test_gpt_layer_context_parallel_matches_full",
    "test_models.py::test_bert_forward_shapes_and_mask",
    "test_contrib_transducer.py::"
    "test_loss_grad_is_finite_and_correct_vs_numerical",
    "test_offload.py::test_gpt_layer_tags_compose_with_offload",
    "test_parallel.py::test_ddp_syncbn_resnet_config5_matches_full_batch",
    "test_contrib_misc.py::test_spatial_bottleneck_matches_unsharded",
    "test_contrib_misc.py::test_spatial_bottleneck_grads_with_group_psum",
    "test_contrib_misc.py::test_bottleneck_shapes_and_residual",
    "test_attention.py::test_ring_attention_grads_match_full",
    "test_attention.py::test_ring_kernel_matches_ring_ref",
    "test_attention.py::test_flash_attention_multiblock_tiling",
    "test_attention.py::test_single_kv_fast_path_matches_generic_kernel",
    "test_attention.py::test_flash_attention_segment_ids_grads",
    "test_attention.py::test_ulysses_attention_grads_match_full",
    "test_moe.py::test_expert_parallel_grads_finite_and_match",
    "test_moe.py::test_single_rank_matches_oracle",
    "test_amp_wrap.py::test_scan_over_layers_gpt_block_bf16_inside",
    "test_tensor_parallel.py::test_tp_mlp_forward_and_grads_match_dense",
    "test_tensor_parallel.py::test_sequence_parallel_mlp_matches_dense",
    "test_fused_softmax_rope.py::test_causal_softmax_matches_ref_and_grads",
    "test_contrib_multihead_attn.py::"
    "test_fmha_packed_matches_per_sequence_attention",
    "test_kernel_bench_logic.py::test_tiny_cpu",  # packed-varlen bench
    # three CLI subprocesses, each paying the jax import; the tier-1
    # lint gate is test_package_self_check, which stays fast-tier
    "test_lint.py::test_cli_exit_codes_and_json",
    # runs the full toy example (60 amp steps) in-process
    "test_telemetry.py::test_train_toy_telemetry_end_to_end",
}


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: integration-weight test excluded from the fast tier "
        "(tests/run_test.py default); the full tier runs everything")


def pytest_collection_modifyitems(config, items):
    """Smoke mode pins the real TPU backend for the whole process, so
    only the smoke file may run — deselect everything else rather than
    letting CPU-intended mesh suites loose on the single-client TPU.
    Otherwise: centrally apply the `slow` marker."""
    if os.environ.get("APEX_TPU_SMOKE") == "1":
        keep = [it for it in items if "test_tpu_smoke" in str(it.fspath)]
        drop = [it for it in items
                if "test_tpu_smoke" not in str(it.fspath)]
        if drop:
            config.hook.pytest_deselected(items=drop)
            items[:] = keep
        return
    for it in items:
        fname = os.path.basename(str(it.fspath))
        base = getattr(it, "originalname", None) or it.name
        if fname in SLOW_MODULES or f"{fname}::{base}" in SLOW_TESTS:
            it.add_marker(pytest.mark.slow)


@pytest.fixture(autouse=True)
def _reset_mesh():
    """Each test sees a fresh (uninitialized) global mesh."""
    from apex_tpu import comm
    comm.destroy()
    yield
    comm.destroy()


@pytest.fixture(autouse=True)
def _neutral_dispatch(monkeypatch):
    """Pin kernel dispatch to its design default (prefer Pallas) for
    every test: a measured dispatch_prefs.json or an exported
    APEX_TPU_PREFER_* in the developer's shell must never silently
    reroute kernel-correctness tests onto the reference path (they
    would then assert ref-vs-ref and a real kernel bug would pass CI).
    Dispatch-mechanism tests override _PREFS/env explicitly."""
    from apex_tpu.ops import _dispatch
    monkeypatch.setattr(_dispatch, "_PREFS", {})
    monkeypatch.setattr(_dispatch, "_ATTN_CAPS", {})
    monkeypatch.setattr(_dispatch, "_PIPELINE", {})
    monkeypatch.setattr(_dispatch, "_FP8", {})
    monkeypatch.setattr(_dispatch, "_QUANT", {})
    monkeypatch.setattr(_dispatch, "_SERVING", {})
    monkeypatch.setattr(_dispatch, "_INSTALLED", None)
    monkeypatch.delenv("APEX_TPU_PREFER_PALLAS", raising=False)
    monkeypatch.delenv("APEX_TPU_PREFER_XLA", raising=False)


@pytest.fixture
def mesh8():
    from apex_tpu import comm
    return comm.initialize(data=2, pipe=1, ctx=1, model=4)
