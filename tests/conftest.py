"""Test harness: simulate an 8-chip topology on CPU host devices.

The reference cannot simulate multi-GPU (SURVEY.md §4: distributed tests
skip without >=2 real GPUs).  JAX can: force 8 host-platform devices and
run every DP/TP/PP/SP suite on a real Mesh in one process.  Pallas kernels
run in interpreter mode off-TPU (apex_tpu.ops._dispatch).

Environment note: sitecustomize registers the axon TPU PJRT plugin in
every Python process and overrides platform selection, so env vars set
here are too late — we must flip the already-imported jax config to CPU
BEFORE the first backend use (otherwise the first jax.devices() call
blocks trying to claim the TPU tunnel).
"""

import os

import jax

jax.config.update("jax_platforms", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _reset_mesh():
    """Each test sees a fresh (uninitialized) global mesh."""
    from apex_tpu import comm
    comm.destroy()
    yield
    comm.destroy()


@pytest.fixture
def mesh8():
    from apex_tpu import comm
    return comm.initialize(data=2, pipe=1, ctx=1, model=4)
