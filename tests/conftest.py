"""Test harness: simulate an 8-chip topology on CPU host devices.

The reference cannot simulate multi-GPU (SURVEY.md §4: distributed tests
skip without >=2 real GPUs).  JAX can: force 8 host-platform devices and
run every DP/TP/PP/SP suite on a real Mesh in one process.  Pallas kernels
run in interpreter mode off-TPU (apex_tpu.ops._dispatch).

Environment note: sitecustomize registers the axon TPU PJRT plugin in
every Python process and overrides platform selection, so env vars set
here are too late — we must flip the already-imported jax config to CPU
BEFORE the first backend use (otherwise the first jax.devices() call
blocks trying to claim the TPU tunnel).
"""

import os

import jax

if os.environ.get("APEX_TPU_SMOKE") == "1":
    # TPU smoke mode (tests/test_tpu_smoke.py): keep the real backend and
    # persist compiled executables so re-runs skip the slow first compile.
    jax.config.update(
        "jax_compilation_cache_dir",
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), ".jax_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
else:
    jax.config.update("jax_platforms", "cpu")
    _flags = os.environ.get("XLA_FLAGS", "")
    if "--xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + " --xla_force_host_platform_device_count=8").strip()

import pytest  # noqa: E402


def pytest_collection_modifyitems(config, items):
    """Smoke mode pins the real TPU backend for the whole process, so
    only the smoke file may run — deselect everything else rather than
    letting CPU-intended mesh suites loose on the single-client TPU."""
    if os.environ.get("APEX_TPU_SMOKE") != "1":
        return
    keep = [it for it in items if "test_tpu_smoke" in str(it.fspath)]
    drop = [it for it in items if "test_tpu_smoke" not in str(it.fspath)]
    if drop:
        config.hook.pytest_deselected(items=drop)
        items[:] = keep


@pytest.fixture(autouse=True)
def _reset_mesh():
    """Each test sees a fresh (uninitialized) global mesh."""
    from apex_tpu import comm
    comm.destroy()
    yield
    comm.destroy()


@pytest.fixture
def mesh8():
    from apex_tpu import comm
    return comm.initialize(data=2, pipe=1, ctx=1, model=4)
