"""apexlint: fixture matrix, suppression semantics, CLI contract, and
the tier-1 self-check keeping apex_tpu/ itself lint-clean.

Fixtures in tests/lint_fixtures/ are linted as text, never imported —
the bad ones contain deliberate hazards that would not survive a real
trace.
"""

import json
import os
import subprocess
import sys

import pytest

from apex_tpu.lint import all_rules, lint_paths, lint_source, rule_catalog

HERE = os.path.dirname(os.path.abspath(__file__))
FIXTURES = os.path.join(HERE, "lint_fixtures")
REPO = os.path.dirname(HERE)

# fixture file -> exactly the rule ids it must (and may) trigger;
# equality keeps each fixture family-pure so one rule's drift can't
# hide behind another's findings
BAD_FIXTURES = {
    "bad_host_sync.py": {"APX101"},
    "bad_telemetry_sync.py": {"APX102"},
    "bad_dtype.py": {"APX201", "APX202", "APX203"},
    "bad_retrace.py": {"APX301", "APX302", "APX303"},
    "bad_donation.py": {"APX401"},
    "bad_pallas.py": {"APX501", "APX502"},
    "bad_import_env.py": {"APX601"},
}
GOOD_FIXTURES = [
    "good_host_sync.py", "good_telemetry_sync.py", "good_dtype.py",
    "good_retrace.py", "good_donation.py", "good_pallas.py",
    "good_import_env.py",
]


def _lint_fixture(name):
    return lint_paths([os.path.join(FIXTURES, name)])


@pytest.mark.parametrize("name,expected", sorted(BAD_FIXTURES.items()))
def test_bad_fixture_flags_its_family(name, expected):
    findings = _lint_fixture(name)
    assert {f.rule_id for f in findings} == expected
    # each finding carries a usable location and message
    for f in findings:
        assert f.line > 0 and f.message and f.path.endswith(name)


@pytest.mark.parametrize("name", GOOD_FIXTURES)
def test_good_fixture_is_clean(name):
    findings = _lint_fixture(name)
    assert findings == [], [f.format() for f in findings]


def test_every_rule_family_has_fixture_coverage():
    """The acceptance contract: every rule family (6 static + the
    APX102 runtime-telemetry twin) has a positive (bad fixture) and a
    negative (good twin)."""
    covered = set().union(*BAD_FIXTURES.values())
    families = {rid[:4] for rid, _, _ in rule_catalog()}
    assert {rid[:4] for rid in covered} == families
    assert len(BAD_FIXTURES) >= 7 == len(GOOD_FIXTURES)
    ids = [r.id for r in all_rules()]
    assert len(ids) == len(set(ids))


# ---- suppression semantics ------------------------------------------------

_BAD_LINE = "import os\nX = os.environ.get('A')\n"


def test_suppress_same_line():
    src = "import os\nX = os.environ.get('A')  # apexlint: disable=APX601\n"
    assert lint_source(src, "f.py", all_rules()) == []


def test_suppress_next_line():
    src = ("import os\n# apexlint: disable-next=APX601\n"
           "X = os.environ.get('A')\n")
    assert lint_source(src, "f.py", all_rules()) == []


def test_suppress_all_and_wrong_rule():
    base = _BAD_LINE
    assert lint_source(base, "f.py", all_rules()) != []
    hit = base.replace("\n", "  # apexlint: disable=all\n", 2)
    assert lint_source(hit, "f.py", all_rules()) == []
    miss = base.replace("\n", "  # apexlint: disable=APX101\n", 2)
    assert lint_source(miss, "f.py", all_rules()) != []


def test_skip_file():
    src = "# apexlint: skip-file\n" + _BAD_LINE
    assert lint_source(src, "f.py", all_rules()) == []


def test_syntax_error_reports_apx000():
    findings = lint_source("def broken(:\n", "f.py", all_rules())
    assert [f.rule_id for f in findings] == ["APX000"]


# ---- CLI contract ---------------------------------------------------------

def _run_cli(*args):
    return subprocess.run(
        [sys.executable, "-m", "apex_tpu.lint", *args],
        capture_output=True, text=True, cwd=REPO, timeout=300)


def test_package_self_check():
    """Tier-1 gate: the shipped tree must stay apexlint-clean."""
    proc = _run_cli("apex_tpu/")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "clean" in proc.stdout


def test_cli_exit_codes_and_json():
    bad = os.path.join("tests", "lint_fixtures", "bad_import_env.py")
    proc = _run_cli("--json", bad)
    assert proc.returncode == 1
    payload = json.loads(proc.stdout)
    assert payload["files_checked"] == 1
    assert payload["finding_count"] == len(payload["findings"]) > 0
    assert {f["rule_id"] for f in payload["findings"]} == {"APX601"}
    assert _run_cli("no/such/path.py").returncode == 2
    assert _run_cli("--select", "APX999", "apex_tpu/").returncode == 2
    assert _run_cli("--list-rules").returncode == 0
    # tools/lint.py defaults to apex_tpu/ even when an option value is
    # the only non-dash token (`--select APX101` is not a path)
    proc = subprocess.run(
        [sys.executable, "tools/lint.py", "--select", "APX601"],
        capture_output=True, text=True, cwd=REPO, timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "clean" in proc.stdout


def test_select_and_ignore_filters():
    path = os.path.join(FIXTURES, "bad_dtype.py")
    only = lint_paths([path], select={"APX201"})
    assert {f.rule_id for f in only} == {"APX201"}
    rest = lint_paths([path], ignore={"APX201"})
    assert "APX201" not in {f.rule_id for f in rest} and rest


def test_in_process_self_check_matches_cli():
    """Same invariant as test_package_self_check without the subprocess
    (runs in the fast tier): apex_tpu/ has zero findings."""
    findings = lint_paths([os.path.join(REPO, "apex_tpu")])
    assert findings == [], [f.format() for f in findings]
