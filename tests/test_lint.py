"""apexlint: fixture matrix, suppression semantics, CLI contract, and
the tier-1 self-check keeping apex_tpu/ itself lint-clean.

Fixtures in tests/lint_fixtures/ are linted as text, never imported —
the bad ones contain deliberate hazards that would not survive a real
trace.
"""

import json
import os
import subprocess
import sys

import pytest

from apex_tpu.lint import all_rules, lint_paths, lint_source, rule_catalog

HERE = os.path.dirname(os.path.abspath(__file__))
FIXTURES = os.path.join(HERE, "lint_fixtures")
REPO = os.path.dirname(HERE)

# fixture file -> exactly the rule ids it must (and may) trigger;
# equality keeps each fixture family-pure so one rule's drift can't
# hide behind another's findings
BAD_FIXTURES = {
    "bad_host_sync.py": {"APX101"},
    "bad_telemetry_sync.py": {"APX102"},
    "bad_accum_unpack.py": {"APX103"},
    "bad_dtype.py": {"APX201", "APX202", "APX203"},
    "bad_fp8_scale.py": {"APX204"},
    "bad_retrace.py": {"APX301", "APX302", "APX303"},
    "bad_donation.py": {"APX401"},
    "bad_use_after_donate.py": {"APX402"},
    "bad_pallas.py": {"APX501", "APX502"},
    "bad_import_env.py": {"APX601"},
    "bad_collectives.py": {"APX701", "APX702", "APX703"},
    "bad_trace_state.py": {"APX801"},
}
GOOD_FIXTURES = [
    "good_host_sync.py", "good_telemetry_sync.py",
    "good_accum_unpack.py", "good_dtype.py",
    "good_fp8_scale.py",
    "good_retrace.py", "good_donation.py", "good_use_after_donate.py",
    "good_pallas.py", "good_import_env.py", "good_collectives.py",
    "good_trace_state.py",
]


def _lint_fixture(name):
    return lint_paths([os.path.join(FIXTURES, name)])


@pytest.mark.parametrize("name,expected", sorted(BAD_FIXTURES.items()))
def test_bad_fixture_flags_its_family(name, expected):
    findings = _lint_fixture(name)
    assert {f.rule_id for f in findings} == expected
    # each finding carries a usable location and message
    for f in findings:
        assert f.line > 0 and f.message and f.path.endswith(name)


@pytest.mark.parametrize("name", GOOD_FIXTURES)
def test_good_fixture_is_clean(name):
    findings = _lint_fixture(name)
    assert findings == [], [f.format() for f in findings]


def test_every_rule_family_has_fixture_coverage():
    """The acceptance contract: every rule family has a positive (bad
    fixture) and a negative (good twin)."""
    covered = set().union(*BAD_FIXTURES.values())
    families = {rid[:4] for rid, _, _ in rule_catalog()}
    assert {rid[:4] for rid in covered} == families
    assert len(BAD_FIXTURES) >= 12 == len(GOOD_FIXTURES)
    ids = [r.id for r in all_rules()]
    assert len(ids) == len(set(ids))


def test_fixture_matrix_completeness_auto_discovered():
    """Meta-test (no hand-kept list): EVERY registered rule id must be
    triggered by at least one bad_* fixture, and every bad_* fixture
    must have a good_* twin that lints clean — so a future rule cannot
    ship untested and a fixture cannot silently lose its negative."""
    bad = sorted(n for n in os.listdir(FIXTURES) if n.startswith("bad_"))
    good = {n for n in os.listdir(FIXTURES) if n.startswith("good_")}
    triggered = set()
    for name in bad:
        triggered |= {f.rule_id for f in _lint_fixture(name)}
        twin = "good_" + name[len("bad_"):]
        assert twin in good, f"{name} lacks its clean twin {twin}"
        assert _lint_fixture(twin) == [], twin
    missing = {r.id for r in all_rules()} - triggered
    assert not missing, (
        f"registered rule id(s) with no bad_* fixture coverage: "
        f"{sorted(missing)} — add a fixture pair before shipping the "
        "rule (docs/lint.md 'Extending')")


# ---- suppression semantics ------------------------------------------------

_BAD_LINE = "import os\nX = os.environ.get('A')\n"


def test_suppress_same_line():
    src = "import os\nX = os.environ.get('A')  # apexlint: disable=APX601\n"
    assert lint_source(src, "f.py", all_rules()) == []


def test_suppress_next_line():
    src = ("import os\n# apexlint: disable-next=APX601\n"
           "X = os.environ.get('A')\n")
    assert lint_source(src, "f.py", all_rules()) == []


def test_suppress_all_and_wrong_rule():
    base = _BAD_LINE
    assert lint_source(base, "f.py", all_rules()) != []
    hit = base.replace("\n", "  # apexlint: disable=all\n", 2)
    assert lint_source(hit, "f.py", all_rules()) == []
    miss = base.replace("\n", "  # apexlint: disable=APX101\n", 2)
    assert lint_source(miss, "f.py", all_rules()) != []


def test_skip_file():
    src = "# apexlint: skip-file\n" + _BAD_LINE
    assert lint_source(src, "f.py", all_rules()) == []


def test_syntax_error_reports_apx000():
    findings = lint_source("def broken(:\n", "f.py", all_rules())
    assert [f.rule_id for f in findings] == ["APX000"]


# ---- CLI contract ---------------------------------------------------------

def _run_cli(*args):
    return subprocess.run(
        [sys.executable, "-m", "apex_tpu.lint", *args],
        capture_output=True, text=True, cwd=REPO, timeout=300)


def test_package_self_check():
    """Tier-1 gate: the shipped tree must stay apexlint-clean."""
    proc = _run_cli("apex_tpu/")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "clean" in proc.stdout


def test_cli_exit_codes_and_json():
    bad = os.path.join("tests", "lint_fixtures", "bad_import_env.py")
    proc = _run_cli("--json", bad)
    assert proc.returncode == 1
    payload = json.loads(proc.stdout)
    assert payload["files_checked"] == 1
    assert payload["finding_count"] == len(payload["findings"]) > 0
    assert {f["rule_id"] for f in payload["findings"]} == {"APX601"}
    assert _run_cli("no/such/path.py").returncode == 2
    assert _run_cli("--select", "APX999", "apex_tpu/").returncode == 2
    assert _run_cli("--list-rules").returncode == 0
    # tools/lint.py defaults to apex_tpu/ even when an option value is
    # the only non-dash token (`--select APX101` is not a path)
    proc = subprocess.run(
        [sys.executable, "tools/lint.py", "--select", "APX601"],
        capture_output=True, text=True, cwd=REPO, timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "clean" in proc.stdout


def test_select_and_ignore_filters():
    path = os.path.join(FIXTURES, "bad_dtype.py")
    only = lint_paths([path], select={"APX201"})
    assert {f.rule_id for f in only} == {"APX201"}
    rest = lint_paths([path], ignore={"APX201"})
    assert "APX201" not in {f.rule_id for f in rest} and rest


def test_in_process_self_check_matches_cli():
    """Same invariant as test_package_self_check without the subprocess
    (runs in the fast tier): apex_tpu/ has zero findings."""
    findings = lint_paths([os.path.join(REPO, "apex_tpu")])
    assert findings == [], [f.format() for f in findings]


def test_repo_wide_self_check_relaxed_profile():
    """Satellite gate: tests/, examples/ and tools/ lint clean under
    the relaxed profile (APX101/102 exempt inside test bodies; the
    deliberately-hazardous lint_fixtures tree is pruned from
    directory walks by collect_files)."""
    paths = [os.path.join(REPO, d) for d in ("tests", "examples",
                                             "tools")]
    findings = lint_paths(paths, relax_test_bodies=True)
    assert findings == [], [f.format() for f in findings]


# ---- path hygiene ---------------------------------------------------------

_HAZARD = "import os\nX = os.environ.get('A')\n"


def test_duplicate_spellings_lint_once(tmp_path):
    mod = tmp_path / "m.py"
    mod.write_text(_HAZARD)
    dotted = os.path.join(str(tmp_path), ".", "m.py")
    link = tmp_path / "alias"
    link.symlink_to(tmp_path)
    via_link = str(link / "m.py")
    findings = lint_paths([str(mod), str(mod), dotted, via_link,
                           str(tmp_path)])
    assert len(findings) == 1, [f.format() for f in findings]
    # the reported spelling is normalized (no /./ segments)
    assert "/./" not in findings[0].path


def test_collect_files_deterministic_and_deduped(tmp_path):
    from apex_tpu.lint.engine import collect_files
    for name in ("b.py", "a.py"):
        (tmp_path / name).write_text("x = 1\n")
    files = collect_files([str(tmp_path / "b.py"), str(tmp_path),
                           str(tmp_path / "a.py")])
    assert files == sorted(files)
    assert len(files) == len(set(files)) == 2


def test_json_reporter_order_is_deterministic():
    """JSON output is sorted by (path, line, col, rule) no matter the
    order findings were produced in."""
    from apex_tpu.lint.findings import Finding
    from apex_tpu.lint.reporters import render_json
    scrambled = [
        Finding("b.py", 9, 1, "APX601", "x", "m2"),
        Finding("a.py", 5, 2, "APX301", "x", "m1"),
        Finding("a.py", 5, 1, "APX101", "x", "m0"),
    ]
    payload = json.loads(render_json(scrambled, 2))
    got = [(f["path"], f["line"], f["col"], f["rule_id"])
           for f in payload["findings"]]
    assert got == sorted(got)
    assert payload["baselined_count"] == 0


# ---- interprocedural tier -------------------------------------------------

def test_interprocedural_host_sync_through_helper(tmp_path):
    """A host sync hidden behind a helper in ANOTHER module: invisible
    to per-file linting, caught by the project pass."""
    (tmp_path / "helpers.py").write_text(
        "def fetch(x):\n    return float(x)\n")
    (tmp_path / "train.py").write_text(
        "import jax\nimport helpers\n\n\n@jax.jit\n"
        "def run(x):\n    return helpers.fetch(x)\n")
    findings = lint_paths([str(tmp_path)])
    hits = [f for f in findings if f.rule_id == "APX101"]
    assert hits and hits[0].path.endswith("helpers.py"), \
        [f.format() for f in findings]
    # the helper alone (no jit root in sight) stays clean
    assert lint_paths([str(tmp_path / "helpers.py")]) == []


def test_interprocedural_donation_of_imported_step(tmp_path):
    """`jax.jit(imported_step)` without donation: the step def lives in
    another module, the missing donate_argnums is still caught."""
    (tmp_path / "steps.py").write_text(
        "def train_step(params, opt_state, grads):\n"
        "    return params, opt_state\n")
    (tmp_path / "wire.py").write_text(
        "import jax\nfrom steps import train_step\n\n"
        "jstep = jax.jit(train_step)\n")
    findings = lint_paths([str(tmp_path)])
    hits = [f for f in findings if f.rule_id == "APX401"]
    assert hits and hits[0].path.endswith("wire.py"), \
        [f.format() for f in findings]
    # donating spelling is clean
    (tmp_path / "wire.py").write_text(
        "import jax\nfrom steps import train_step\n\n"
        "jstep = jax.jit(train_step, donate_argnums=(0, 1))\n")
    assert [f for f in lint_paths([str(tmp_path)])
            if f.rule_id == "APX401"] == []


def test_use_after_donate_spares_disjoint_branches():
    """Regression for the false positive the rule's first draft fired
    on optimizers/_base.step(): the donating call and the 'later
    read' live in mutually exclusive if/else arms, so no execution
    order ever reads the donated buffer."""
    src = (
        "import jax\n\n\n"
        "def advance(state, x):\n"
        "    return state + x\n\n\n"
        "step = jax.jit(advance, donate_argnums=(0,))\n\n\n"
        "def run(flag, state, x):\n"
        "    if flag:\n"
        "        out = step(state, x)\n"
        "    else:\n"
        "        out = state * 2\n"
        "    return out\n")
    findings = lint_source(src, "f.py", all_rules())
    assert [f for f in findings if f.rule_id == "APX402"] == [], \
        [f.format() for f in findings]
    # ...while a genuine straight-line reuse still fires
    bad = src.replace("    return out\n",
                      "    return out + state\n")
    hits = [f for f in lint_source(bad, "f.py", all_rules())
            if f.rule_id == "APX402"]
    assert len(hits) == 1, hits


def test_use_after_donate_spares_shadowing_scopes():
    """A same-named parameter/local in a NESTED def (or, for a
    module-level donation, in any later function) is a fresh variable
    — its reads must not count as uses of the donated buffer."""
    src = (
        "import jax\n\n"
        "step = jax.jit(lambda s, x: (s, x), donate_argnums=(0,))\n\n\n"
        "def train(state, x):\n"
        "    out = step(state, x)\n\n"
        "    def helper(state):\n"
        "        return state + 1\n\n"
        "    return helper(out[0])\n")
    findings = lint_source(src, "f.py", all_rules())
    assert [f for f in findings if f.rule_id == "APX402"] == [], \
        [f.format() for f in findings]
    # module-level donation, same-named local in another function
    src2 = (
        "import jax\n\n"
        "step = jax.jit(lambda s: s, donate_argnums=(0,))\n"
        "state = [1.0]\n"
        "new = step(state)\n\n\n"
        "def other():\n"
        "    state = 2\n"
        "    return state\n")
    findings2 = lint_source(src2, "f.py", all_rules())
    assert [f for f in findings2 if f.rule_id == "APX402"] == [], \
        [f.format() for f in findings2]


def test_use_after_donate_try_arms():
    """`else`/`finally` run after a SUCCESSFUL donating body — reads
    there see a deleted buffer and must fire; an except handler runs
    only when the body raised, so its reads stay exempt."""
    tmpl = (
        "import jax\n\n"
        "step = jax.jit(lambda s, x: s + x, donate_argnums=(0,))\n\n\n"
        "def run(state, x):\n"
        "    try:\n"
        "        out = step(state, x)\n"
        "    {arm}\n"
        "        {read}\n"
        "    return out\n")
    for arm, expect in (("else:", 1), ("finally:", 1),
                        ("except ValueError:", 0)):
        src = tmpl.format(arm=arm, read="out = state")
        if arm == "else:":
            src = src.replace("    else:",
                              "    except ValueError:\n"
                              "        out = None\n    else:")
        hits = [f for f in lint_source(src, "f.py", all_rules())
                if f.rule_id == "APX402"]
        assert len(hits) == expect, (arm, [f.format() for f in hits])


def test_use_after_donate_loop_back_edge():
    """Donating inside a loop without rebinding passes a deleted
    buffer on iteration 2 — must fire; the carry idiom and a fresh
    per-iteration binding stay clean."""
    bad = (
        "import jax\n\n"
        "step = jax.jit(lambda s, x: s + x, donate_argnums=(0,))\n\n\n"
        "def run(state, xs):\n"
        "    outs = []\n"
        "    for x in xs:\n"
        "        outs.append(step(state, x))\n"
        "    return outs\n")
    hits = [f for f in lint_source(bad, "f.py", all_rules())
            if f.rule_id == "APX402"]
    assert len(hits) == 1 and hits[0].line == 9, hits
    carry = (
        "import jax\n\n"
        "step = jax.jit(lambda s, x: (s + x, x), donate_argnums=(0,))\n\n\n"
        "def run(state, xs):\n"
        "    for x in xs:\n"
        "        state, aux = step(state, x)\n"
        "    return state\n")
    assert [f for f in lint_source(carry, "f.py", all_rules())
            if f.rule_id == "APX402"] == []
    fresh = (
        "import jax\n\n"
        "step = jax.jit(lambda s, x: s + x, donate_argnums=(0,))\n\n\n"
        "def run(xs):\n"
        "    outs = []\n"
        "    for x in xs:\n"
        "        state = [1.0]\n"
        "        outs.append(step(state, x))\n"
        "    return outs\n")
    assert [f for f in lint_source(fresh, "f.py", all_rules())
            if f.rule_id == "APX402"] == []


def test_use_after_donate_partial_factory_is_not_a_donating_call():
    """`functools.partial(jax.jit, donate_argnums=...)` bound to a
    name is a FACTORY — its call arguments are functions to wrap, not
    donated buffers.  Only the decorator spelling of partial donates."""
    src = (
        "import functools\n"
        "import jax\n\n\n"
        "def train_step(s, x):\n"
        "    return s + x\n\n\n"
        "jit_donate = functools.partial(jax.jit, donate_argnums=(0,))\n"
        "step = jit_donate(train_step)\n"
        "eval_step = jit_donate(train_step)\n")
    findings = lint_source(src, "f.py", all_rules())
    assert [f for f in findings if f.rule_id == "APX402"] == [], \
        [f.format() for f in findings]
    # the decorator form of the same partial still registers donation
    src_dec = (
        "import functools\n"
        "import jax\n\n\n"
        "@functools.partial(jax.jit, donate_argnums=(0,))\n"
        "def step(s, x):\n"
        "    return s + x\n\n\n"
        "def run(state, x):\n"
        "    out = step(state, x)\n"
        "    return state + out\n")
    hits = [f for f in lint_source(src_dec, "f.py", all_rules())
            if f.rule_id == "APX402"]
    assert len(hits) == 1, hits


def test_dead_collective_loop_carry_is_live():
    """The ring idiom — `acc += recv; recv = ppermute(...)` inside a
    loop — consumes the collective's result on the NEXT iteration;
    a read earlier in the same loop body keeps it live (no APX703)."""
    src = (
        "import jax\n"
        "from jax.experimental.shard_map import shard_map\n\n\n"
        "def ring(acc, x, w, recv, n):\n"
        "    for step in range(n):\n"
        "        acc = acc + x @ w + recv\n"
        "        recv = jax.lax.ppermute(x, 'i', perm=[(0, 1)])\n"
        "    return acc\n\n\n"
        "f = shard_map(ring, None, in_specs=None, out_specs=None)\n")
    findings = lint_source(src, "f.py", all_rules())
    assert [f for f in findings if f.rule_id == "APX703"] == [], \
        [f.format() for f in findings]
    # a result never read anywhere — even across iterations — still fires
    dead = src.replace("acc = acc + x @ w + recv", "acc = acc + x @ w")
    hits = [f for f in lint_source(dead, "f.py", all_rules())
            if f.rule_id == "APX703"]
    assert len(hits) == 1, hits


def test_unbound_axis_detected_despite_tiling_axis_kwarg():
    """`all_gather(x, 'name', axis=0)` carries the axis NAME
    positionally and the integer tiling dimension in `axis=` — the int
    kwarg must not mask the name from APX701/702."""
    src = (
        "import jax\n\n\n"
        "def f(x):\n"
        "    return jax.lax.all_gather(x, 'typo_axis', axis=0)\n")
    hits = [f for f in lint_source(src, "f.py", all_rules())
            if f.rule_id in ("APX701", "APX702")]
    assert hits, "axis=0 kwarg masked the unbound positional axis name"


def test_callgraph_same_stem_files_resolve_deterministically(tmp_path):
    """Two non-package files with the same stem must not cross-resolve
    to whichever was linted last: ambiguous module names drop out of
    cross-module resolution, so findings are argument-order
    independent."""
    (tmp_path / "a").mkdir(); (tmp_path / "b").mkdir()
    pa = tmp_path / "a" / "utils.py"
    pb = tmp_path / "b" / "utils.py"
    pm = tmp_path / "main.py"
    pa.write_text("def helper_step_fn(x):\n    return float(x)\n")
    pb.write_text("def helper_step_fn(x):\n    return x\n")
    pm.write_text("import jax\nfrom utils import helper_step_fn\n"
                  "step = jax.jit(helper_step_fn)\n")
    fwd = [(f.path, f.line, f.rule_id)
           for f in lint_paths([str(pa), str(pb), str(pm)])]
    rev = [(f.path, f.line, f.rule_id)
           for f in lint_paths([str(pb), str(pa), str(pm)])]
    assert fwd == rev


def test_relaxed_profile_exempts_test_bodies_only(tmp_path):
    """APX101 inside a test_* body is exempt under the relaxed
    profile; the same hazard in a module-level helper of the same
    test file still gates — and without the profile both gate."""
    src = (
        "import jax\n\n\n"
        "def hot_helper_step_fn(x):\n"
        "    return float(x)\n\n\n"
        "def test_sync():\n"
        "    def train_step(x):\n"
        "        return float(x)\n"
        "    assert train_step(1.0)\n")
    f = tmp_path / "test_mod.py"
    f.write_text(src)
    strict = lint_paths([str(f)])
    relaxed = lint_paths([str(f)], relax_test_bodies=True)
    assert {x.line for x in strict if x.rule_id == "APX101"} == {5, 10}
    assert {x.line for x in relaxed if x.rule_id == "APX101"} == {5}
    # the exemption keys on test_* exactly — a tester_*/testbed_*
    # helper in a test file still gates
    h = tmp_path / "test_helper.py"
    h.write_text("import jax\n\n\n"
                 "def tester_step_fn(x):\n"
                 "    return float(x)\n")
    assert {x.line for x in lint_paths([str(h)], relax_test_bodies=True)
            if x.rule_id == "APX101"} == {5}
    # non-test files are untouched by the profile
    g = tmp_path / "mod.py"
    g.write_text(src)
    assert len(lint_paths([str(g)], relax_test_bodies=True)) == \
        len(strict)
