"""Native apex_C host runtime + checkpoint/resume (reference pattern:
flatten/unflatten round-trips; examples/imagenet checkpoint bundle)."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import _native, checkpoint
from apex_tpu.optimizers import FusedAdam


def test_native_library_builds():
    # the toolchain is part of this image; the build must succeed here
    assert _native.available(), "g++ build of libapex_c.so failed"


def test_host_flatten_unflatten_roundtrip():
    arrays = [np.random.randn(17, 5).astype(np.float32),
              np.random.randn(3).astype(np.float64),
              np.arange(10, dtype=np.int32),
              np.random.randn(2, 2, 2).astype(np.float16)]
    flat = _native.host_flatten(arrays)
    assert flat.nbytes == sum(a.nbytes for a in arrays)
    back = _native.host_unflatten(flat, arrays)
    for a, b in zip(arrays, back):
        np.testing.assert_array_equal(a, b)
        assert b.dtype == a.dtype


def test_host_flatten_matches_numpy_fallback():
    arrays = [np.random.randn(100).astype(np.float32) for _ in range(7)]
    flat = _native.host_flatten(arrays)
    want = np.concatenate([a.view(np.uint8) for a in arrays])
    np.testing.assert_array_equal(flat, want)


def test_host_l2norm():
    x = np.random.randn(100000).astype(np.float32)
    got = _native.host_l2norm(x)
    np.testing.assert_allclose(got, np.linalg.norm(x.astype(np.float64)),
                               rtol=1e-6)


def test_checkpoint_roundtrip(tmp_path):
    tree = {"a": jnp.arange(12.0).reshape(3, 4),
            "b": {"c": jnp.ones((5,), jnp.bfloat16),
                  "d": jnp.asarray([1, 2, 3])}}
    p = str(tmp_path / "ckpt.apex")
    checkpoint.save_checkpoint(p, tree, {"note": "hi"})
    back, meta = checkpoint.load_checkpoint(p, tree)
    assert meta["note"] == "hi"
    for k in ("a",):
        np.testing.assert_array_equal(np.asarray(back[k]),
                                      np.asarray(tree[k]))
    assert back["b"]["c"].dtype == jnp.bfloat16


def test_checkpoint_detects_corruption(tmp_path):
    tree = {"a": jnp.ones((64,))}
    p = str(tmp_path / "ckpt.apex")
    checkpoint.save_checkpoint(p, tree)
    raw = bytearray(open(p, "rb").read())
    raw[-16:-12] = b"\xff\xff\xff\xff"    # clobber one float (NaN)
    open(p, "wb").write(bytes(raw))
    with pytest.raises(ValueError, match="crc|checksum"):
        checkpoint.load_checkpoint(p, tree)


def test_checkpoint_wrong_template_rejected(tmp_path):
    tree = {"a": jnp.ones((4,)), "b": jnp.ones((4,))}
    p = str(tmp_path / "ckpt.apex")
    checkpoint.save_checkpoint(p, tree)
    with pytest.raises(ValueError, match="leaves"):
        checkpoint.load_checkpoint(p, {"a": jnp.ones((4,))})


def test_training_state_resume_continues_identically(tmp_path):
    """The reference L0 checkpointing test pattern: save mid-training,
    restore into a fresh optimizer, training continues bit-identically."""
    params = {"w": jax.random.normal(jax.random.PRNGKey(0), (16, 4))}
    grads = [{"w": jax.random.normal(jax.random.PRNGKey(i), (16, 4)) * .1}
             for i in range(6)]
    opt = FusedAdam(params, lr=1e-2)
    for g in grads[:3]:
        opt.step(g)
    p = str(tmp_path / "train.apex")
    checkpoint.save_training_state(p, opt.params, opt,
                                   amp_state={"loss_scale": 1024.0},
                                   step=3)
    # continue the original
    for g in grads[3:]:
        ref = opt.step(g)
    # restore into a FRESH optimizer and replay
    opt2 = FusedAdam(params, lr=1e-2)
    rp, amp_state, step = checkpoint.load_training_state(p, params, opt2)
    assert step == 3 and amp_state["loss_scale"] == 1024.0
    for g in grads[3:]:
        got = opt2.step(g)
    np.testing.assert_array_equal(np.asarray(got["w"]),
                                  np.asarray(ref["w"]))


def test_truncated_checkpoint_rejected(tmp_path):
    """ADVICE r1 medium: a truncated payload must raise BEFORE the
    native memcpy reads out of bounds."""
    import os
    from apex_tpu import checkpoint as ckpt
    tree = {"w": jnp.arange(64, dtype=jnp.float32).reshape(8, 8),
            "step": jnp.int32(7)}
    p = str(tmp_path / "c.ckpt")
    ckpt.save_checkpoint(p, tree)
    data = open(p, "rb").read()
    open(p, "wb").write(data[:-16])          # chop the tail
    with pytest.raises(ValueError, match="truncated|bytes"):
        ckpt.load_checkpoint(p, tree)


def test_integer_leaf_corruption_detected(tmp_path):
    """ADVICE r1: integer leaves are covered by the whole-payload crc."""
    from apex_tpu import checkpoint as ckpt
    tree = {"w": jnp.ones((4,), jnp.float32),
            "step": jnp.arange(16, dtype=jnp.int32)}
    p = str(tmp_path / "c.ckpt")
    ckpt.save_checkpoint(p, tree)
    data = bytearray(open(p, "rb").read())
    data[-2] ^= 0xFF                         # flip a byte in an int leaf
    open(p, "wb").write(bytes(data))
    with pytest.raises(ValueError, match="crc|checksum"):
        ckpt.load_checkpoint(p, tree)


def test_async_checkpointer_round_trip(tmp_path):
    from apex_tpu import checkpoint as ckpt
    tree = {"w": jnp.arange(32, dtype=jnp.float32),
            "b": jnp.ones((4,), jnp.bfloat16)}
    p = str(tmp_path / "a.ckpt")
    with ckpt.AsyncCheckpointer() as ac:
        ac.save(p, tree, metadata={"step": 3})
        ac.wait_until_finished()
        got, meta = ckpt.load_checkpoint(p, tree)
    assert meta["step"] == 3
    np.testing.assert_array_equal(np.asarray(got["w"]),
                                  np.asarray(tree["w"]))


def test_async_checkpointer_training_state_consistent(tmp_path):
    """The snapshot must be of the step at save() time, even if the
    optimizer keeps stepping while the worker writes."""
    from apex_tpu import checkpoint as ckpt
    from apex_tpu.optimizers import FusedSGD
    params = {"w": jnp.ones((128,), jnp.float32)}
    opt = FusedSGD(params, lr=0.1, momentum=0.9)
    g = {"w": jnp.full((128,), 0.01, jnp.float32)}
    opt.step(g)
    p = str(tmp_path / "t.ckpt")
    with ckpt.AsyncCheckpointer() as ac:
        ac.save_training_state(p, opt.params, opt, step=1)
        w_at_save = np.asarray(opt.params["w"]).copy()
        for _ in range(5):          # keep training while it writes
            opt.step(g)
        ac.wait_until_finished()
    params2 = {"w": jnp.zeros((128,), jnp.float32)}
    opt2 = FusedSGD(params2, lr=0.1, momentum=0.9)
    restored, _, step = ckpt.load_training_state(p, params2, opt2)
    assert step == 1
    np.testing.assert_array_equal(np.asarray(restored["w"]), w_at_save)


def test_async_checkpointer_propagates_worker_errors(tmp_path):
    from apex_tpu import checkpoint as ckpt
    ac = ckpt.AsyncCheckpointer()
    ac.save(str(tmp_path / "no" / "such" / "dir" / "x.ckpt"),
            {"w": jnp.ones((2,))})
    with pytest.raises(FileNotFoundError):
        ac.wait_until_finished()
    ac.close()


def test_async_checkpointer_survives_buffer_donation(tmp_path):
    """The caller's next jitted step may donate (delete) the saved
    buffers; the default device-side leaf copy must keep the snapshot
    alive (code-review r2 finding)."""
    from apex_tpu import checkpoint as ckpt
    w = jnp.arange(1 << 16, dtype=jnp.float32)
    p = str(tmp_path / "d.ckpt")
    with ckpt.AsyncCheckpointer() as ac:
        ac.save(p, {"w": w}, metadata={"step": 4})
        w.delete()                 # simulate donation of the original
        ac.wait_until_finished()
    got, meta = ckpt.load_checkpoint(
        p, {"w": jnp.zeros((1 << 16,), jnp.float32)})
    assert meta["step"] == 4
    assert float(got["w"][-1]) == float((1 << 16) - 1)


def test_async_checkpointer_empty_metadata_not_torn(tmp_path):
    """metadata={} must still be snapshotted (falsy-dict regression)."""
    from apex_tpu import checkpoint as ckpt
    md = {}
    p = str(tmp_path / "m.ckpt")
    with ckpt.AsyncCheckpointer() as ac:
        ac.save(p, {"w": jnp.ones((1 << 20,))}, metadata=md)
        md["late"] = True          # caller mutates after submit
        ac.wait_until_finished()
    _, meta = ckpt.load_checkpoint(p, {"w": jnp.ones((1 << 20,))})
    assert meta == {}
