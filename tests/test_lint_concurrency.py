"""apexrace: fixture matrix, root discovery over the repo's own
registration seams, suppression + CLI + baseline contract, and
regression tests for the real races the tier surfaced in the shipped
tree (serving engine late-binding, retrace counter lock, fleet beat
lock, elastic save-thunk generation guard).

Fixtures in tests/lint_fixtures/concurrency/ are linted as text, never
imported — the bad ones contain deliberate hazards.
"""

import json
import os
import shutil
import subprocess
import sys
import threading
import time

import pytest

from apex_tpu.lint import engine
from apex_tpu.lint.concurrency import (DEFAULT_BASELINE, all_rules,
                                       build_model,
                                       lint_concurrency_source,
                                       rule_catalog, rule_ids,
                                       run_concurrency)
from apex_tpu.lint.concurrency import roots as roots_mod

HERE = os.path.dirname(os.path.abspath(__file__))
FIXTURES = os.path.join(HERE, "lint_fixtures", "concurrency")
REPO = os.path.dirname(HERE)

# fixture file -> exactly the rule ids it must (and may) trigger —
# equality keeps each fixture family-pure (test_lint.py's contract)
BAD_FIXTURES = {
    "bad_apx1001.py": {"APX1001"},
    "bad_apx1002.py": {"APX1002"},
    "bad_apx1003.py": {"APX1003"},
    "bad_apx1004.py": {"APX1004"},
    "bad_apx1005.py": {"APX1005"},
}
GOOD_FIXTURES = [
    "good_apx1001.py", "good_apx1002.py", "good_apx1003.py",
    "good_apx1004.py", "good_apx1005.py",
]


def _lint_fixture(name):
    path = os.path.join(FIXTURES, name)
    with open(path, encoding="utf-8") as fh:
        return lint_concurrency_source(fh.read(), path)


# ---------------------------------------------------------------------------
# fixture matrix
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("name,expected", sorted(BAD_FIXTURES.items()))
def test_bad_fixture_flags_its_family(name, expected):
    findings = _lint_fixture(name)
    assert {f.rule_id for f in findings} == expected
    for f in findings:
        assert f.line > 0 and f.message and f.path.endswith(name)


@pytest.mark.parametrize("name", GOOD_FIXTURES)
def test_good_fixture_is_clean(name):
    findings = _lint_fixture(name)
    assert findings == [], [f.format() for f in findings]


def test_fixture_matrix_completeness_auto_discovered():
    """Meta-test (no hand-kept list): EVERY registered APX1xxx rule id
    must fire from at least one bad_* fixture, and every bad_* fixture
    must have a good_* twin that lints clean."""
    bad = sorted(n for n in os.listdir(FIXTURES) if n.startswith("bad_"))
    good = {n for n in os.listdir(FIXTURES) if n.startswith("good_")}
    triggered = set()
    for name in bad:
        triggered |= {f.rule_id for f in _lint_fixture(name)}
        twin = "good_" + name[len("bad_"):]
        assert twin in good, f"{name} lacks its clean twin {twin}"
        assert _lint_fixture(twin) == [], twin
    missing = rule_ids() - triggered
    assert not missing, (
        f"registered rule id(s) with no bad_* fixture coverage: "
        f"{sorted(missing)} — add a fixture pair before shipping the "
        "rule (docs/lint.md 'Extending')")


def test_rule_catalog_shape():
    ids = sorted(r.id for r in all_rules())
    assert ids == ["APX1001", "APX1002", "APX1003", "APX1004",
                   "APX1005"]
    for rid, name, desc in rule_catalog():
        assert rid.startswith("APX1") and name and desc


# ---------------------------------------------------------------------------
# root discovery over the repo's own seams
# ---------------------------------------------------------------------------

def _roots_of(*relpaths):
    parsed = []
    for rel in relpaths:
        with open(os.path.join(REPO, rel), encoding="utf-8") as fh:
            one = engine._parse_file(fh.read(), rel)
        assert one is not None and not hasattr(one, "rule_id"), rel
        parsed.append(one[0])
    return roots_mod.discover(build_model(parsed))


def test_root_finder_sees_preemption_guard_signal_handler():
    kinds = {(r.kind, r.label)
             for r in _roots_of("apex_tpu/resilience/preemption.py")}
    assert ("signal", "self._on_signal") in kinds


def test_root_finder_sees_metrics_server_seams():
    """export.py alone carries four seams: the threaded http server,
    its handler class, the hostmetrics sink, and the
    Telemetry.add_observer registration."""
    rs = _roots_of("apex_tpu/telemetry/export.py")
    pairs = {(r.kind, r.label) for r in rs}
    assert ("http", "_Handler.do_GET") in pairs
    assert ("thread", "self._httpd.serve_forever") in pairs
    assert ("sink", "self._on_counter") in pairs
    assert ("observer", "self._on_flush") in pairs   # add_observer seam


def test_root_finder_sees_deadline_runner_thunks():
    rs = _roots_of("apex_tpu/resilience/elastic.py",
                   "apex_tpu/resilience/fleet.py")
    runner_labels = {r.label for r in rs if r.kind == "runner"}
    assert {"thunk", "save_thunk"} <= runner_labels
    # the runner's persistent worker loop is itself a thread root
    assert any(r.kind == "thread" and r.label == "loop" for r in rs)


def test_root_finder_sees_engine_deadline_and_executor():
    rs = _roots_of("apex_tpu/serving/engine.py")
    assert any(r.kind == "runner" and r.label == "thunk" for r in rs)
    assert any(r.kind == "executor" for r in rs)


def test_root_preemptive_partition():
    """Observer/emitter/atexit callbacks run on the flush (main)
    thread — they widen reachability but are not preemptive; every
    true concurrency source is."""
    mk = lambda kind: roots_mod.Root(kind=kind, target=None,
                                     label="x", path="p.py", line=1)
    for kind in sorted(roots_mod.PREEMPTIVE_KINDS):
        assert mk(kind).preemptive, kind
    for kind in ("observer", "emitter", "atexit"):
        assert not mk(kind).preemptive, kind


# ---------------------------------------------------------------------------
# suppression semantics (shared with the AST tier's pragma parser)
# ---------------------------------------------------------------------------

def _bad_src(name="bad_apx1001.py"):
    with open(os.path.join(FIXTURES, name), encoding="utf-8") as fh:
        return fh.read()


def test_suppress_same_line():
    src = _bad_src().replace(
        "self.total += 1",
        "self.total += 1   # apexlint: disable=APX1001")
    assert lint_concurrency_source(src, "t.py") == []


def test_suppress_next_line():
    src = _bad_src().replace(
        "            self.total += 1",
        "            # apexlint: disable-next=APX1001\n"
        "            self.total += 1")
    assert lint_concurrency_source(src, "t.py") == []


def test_wrong_rule_id_does_not_suppress():
    src = _bad_src().replace(
        "self.total += 1",
        "self.total += 1   # apexlint: disable=APX1002")
    assert [f.rule_id for f in
            lint_concurrency_source(src, "t.py")] == ["APX1001"]


# ---------------------------------------------------------------------------
# CLI + baseline contract
# ---------------------------------------------------------------------------

def _cli(args, cwd=REPO):
    return subprocess.run(
        [sys.executable, "-m", "apex_tpu.lint"] + args,
        capture_output=True, text=True, cwd=cwd, timeout=120)


@pytest.fixture
def bad_tree(tmp_path):
    """A lintable copy of the bad APX1001 fixture, outside any
    lint_fixtures/ dir (collect_files prunes those)."""
    work = tmp_path / "pkg"
    work.mkdir()
    shutil.copy(os.path.join(FIXTURES, "bad_apx1001.py"),
                work / "mod.py")
    return work


def test_cli_concurrency_finds_and_filters(bad_tree):
    proc = _cli(["--concurrency", str(bad_tree)])
    assert proc.returncode == 1
    assert "APX1001" in proc.stdout

    assert _cli(["--concurrency", "--ignore", "APX1001",
                 str(bad_tree)]).returncode == 0
    assert _cli(["--concurrency", "--select", "APX1002",
                 str(bad_tree)]).returncode == 0
    sel = _cli(["--concurrency", "--select", "APX1001", str(bad_tree)])
    assert sel.returncode == 1 and "APX1001" in sel.stdout
    # unknown APX1xxx-looking id is a usage error, not silence
    assert _cli(["--concurrency", "--select", "APX1099",
                 str(bad_tree)]).returncode == 2


def test_cli_concurrency_json(bad_tree):
    proc = _cli(["--concurrency", "--json", str(bad_tree)])
    assert proc.returncode == 1
    payload = json.loads(proc.stdout)
    assert any(f["rule_id"] == "APX1001" for f in payload["findings"])


def test_cli_write_baseline_guards(bad_tree, tmp_path):
    """--write-baseline must name exactly one target: explicit file
    always wins; a bare run or an ambiguous two-tier run exits 2
    rather than guessing which SHIPPED baseline to overwrite."""
    bl = tmp_path / "bl.json"
    proc = _cli(["--concurrency", "--write-baseline",
                 "--baseline", str(bl), str(bad_tree)])
    assert proc.returncode == 0 and bl.exists()
    keys = json.load(open(bl))["findings"]
    assert any(k["rule_id"] == "APX1001" for k in keys)

    # no tier, no file: refuse
    assert _cli(["--write-baseline", str(bad_tree)]).returncode == 2
    # both tiers, no file: ambiguous, refuse
    assert _cli(["--semantic", "--concurrency", "--write-baseline",
                 str(bad_tree)]).returncode == 2

    # the written baseline makes the same run exit 0, rendered
    # [baselined] — found, reported, never gating
    proc = _cli(["--concurrency", "--baseline", str(bl),
                 str(bad_tree)])
    assert proc.returncode == 0
    assert "[baselined]" in proc.stdout


def test_shipped_tree_concurrency_gate_and_budget():
    """The acceptance criterion + the tier's share of the tools/
    check.sh wall-clock budget: `--concurrency apex_tpu/` exits 0 on
    the shipped tree, renders every baselined finding `[baselined]`,
    and rounds in well under the 60 s full-gate budget on one CPU
    core."""
    t0 = time.monotonic()
    proc = _cli(["--concurrency", "apex_tpu/"])
    elapsed = time.monotonic() - t0
    assert proc.returncode == 0, proc.stdout + proc.stderr
    shipped = json.load(open(DEFAULT_BASELINE))["findings"]
    assert shipped, "shipped concurrency baseline unexpectedly empty"
    assert proc.stdout.count("[baselined]") == len(shipped)
    assert elapsed < 60.0, f"concurrency gate took {elapsed:.1f}s"


def test_run_concurrency_prunes_fixture_dirs():
    """Walking tests/ (the relaxed-profile gate's shape) never
    descends into the deliberately-hazardous lint_fixtures tree, so
    the bad_apx* fixtures cannot leak findings into a real run."""
    files = engine.collect_files([HERE])
    assert files and not [p for p in files if "lint_fixtures" in p]
    findings, _ = run_concurrency([HERE])
    assert not [f for f in findings if "lint_fixtures" in f.path]


# ---------------------------------------------------------------------------
# regressions: the real races apexrace surfaced in the shipped tree
# ---------------------------------------------------------------------------

def test_retrace_counter_concurrent_bumps_lose_nothing():
    """APX1001 fix (telemetry/retrace.py): the monitoring listener and
    wrapped-function bumps fire on arbitrary threads while the flush
    thread reads — every counter touch now takes the lock.  Without
    it, `Counter[label] += 1` is a read-modify-write that drops
    increments under thread switches."""
    from apex_tpu.telemetry import RetraceCounter

    c = RetraceCounter()
    wrapped = c.wrap(lambda: None, name="hot")
    n_threads, per_thread = 4, 20_000
    # parties: the bumpers, the reader, and main releasing the race
    barrier = threading.Barrier(n_threads + 2)
    done = threading.Event()
    snapshots = []

    def bumper():
        barrier.wait()
        for _ in range(per_thread):
            wrapped()

    def reader():
        barrier.wait()
        while not done.is_set():
            snapshots.append(c.records())

    threads = [threading.Thread(target=bumper)
               for _ in range(n_threads)]
    rd = threading.Thread(target=reader)
    old = sys.getswitchinterval()
    sys.setswitchinterval(1e-5)       # force frequent thread switches
    try:
        for t in threads:
            t.start()
        rd.start()
        barrier.wait()
        for t in threads:
            t.join()
    finally:
        done.set()
        rd.join()
        sys.setswitchinterval(old)
    assert c.counts["hot"] == n_threads * per_thread
    assert snapshots                  # the reader really raced the bumps


def test_fleet_controller_beat_intake_is_synchronized():
    """APX1001 fix (resilience/fleet.py): the `fleet/hosts_slow`
    hostmetrics sink fires on monitor/worker threads while decide()
    reads on the supervisor thread — both sides now hold _beat_lock.
    Writers and a decide() reader race behind a barrier; the last
    write must be visible and nothing may throw."""
    from apex_tpu.resilience import fleet as fleet_mod
    from apex_tpu.telemetry import hostmetrics

    ctrl = fleet_mod.FleetController(step_time_high_s=1e9,
                                     cooldown_steps=0)
    n_threads, per_thread = 4, 2_000
    # parties: the writers, the decider, and main releasing the race
    barrier = threading.Barrier(n_threads + 2)
    errors = []

    def writer(v):
        barrier.wait()
        for _ in range(per_thread):
            hostmetrics.emit("fleet/hosts_slow", v)

    def decider():
        barrier.wait()
        try:
            for step in range(per_thread):
                ctrl.decide(step, n_hosts=4)
        except BaseException as e:    # noqa: BLE001 — reported below
            errors.append(e)

    try:
        threads = [threading.Thread(target=writer, args=(float(i),))
                   for i in range(n_threads)]
        threads.append(threading.Thread(target=decider))
        for t in threads:
            t.start()
        barrier.wait()
        for t in threads:
            t.join()
        assert not errors, errors
        # quiesced: one more beat through the public path is visible
        hostmetrics.emit("fleet/hosts_slow", 2.0)
        with ctrl._beat_lock:
            assert ctrl._hosts_slow == 2.0
    finally:
        ctrl.close()


def test_engine_deadline_thunks_bind_state_before_submission():
    """APX1001 fix (serving/engine.py): the deadline-runner thunks
    must capture programs/params/state BEFORE submission — a thunk
    reading `self.*` late can race replica-failover recovery swapping
    those attributes and execute half-old, half-new state.  Pin the
    closure shape: no lambda under the admission paths (_admit_one /
    _admit_batch) or _decode closes over self."""
    import types

    from apex_tpu.serving.engine import Engine

    def lambdas_of(code):
        out = []
        for k in code.co_consts:
            if isinstance(k, types.CodeType):
                if k.co_name == "<lambda>":
                    out.append(k)
                out.extend(lambdas_of(k))
        return out

    for meth, want in (("_admit_one", {"prefill", "params", "st"}),
                       ("_admit_batch", {"prog", "params", "st"}),
                       ("_decode", {"decode", "params", "st"})):
        lams = lambdas_of(getattr(Engine, meth).__code__)
        assert lams, f"{meth} lost its deadline thunk"
        for lam in lams:
            free = set(lam.co_freevars)
            assert "self" not in free, (
                f"{meth} deadline thunk captures self again: {free}")
        assert any(want <= set(lam.co_freevars) for lam in lams), (
            f"{meth} thunk no longer pre-binds {want}")


def test_elastic_save_thunk_rechecks_generation(tmp_path, monkeypatch):
    """APX1001 fix (resilience/elastic.py): a save thunk executed by a
    worker the deadline machinery already abandoned must skip
    manager.maybe_save — the recovery path owns the manager's
    rotation/pin state now.  Simulate exactly that interleaving by
    bumping runner.generation between the closure's capture and its
    execution; the guard must return False without saving."""
    import jax.numpy as jnp

    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.resilience import run_elastic
    from apex_tpu.resilience import fleet as fleet_mod
    from apex_tpu.resilience.manager import CheckpointManager

    stale_saves = []
    real_run = fleet_mod.DeadlineRunner.run

    def hijack(self, fn, deadline_s, step=-1, phase="step"):
        if phase == "save":
            self.generation += 1      # "abandoned after capture"
            stale_saves.append(fn())  # the stale worker runs it anyway
            return False
        return real_run(self, fn, deadline_s, step=step, phase=phase)

    monkeypatch.setattr(fleet_mod.DeadlineRunner, "run", hijack)

    tree = {"w": jnp.ones((8,), jnp.float32)}
    opt = FusedAdam(tree, lr=1e-2)
    g = {"w": jnp.full((8,), 0.01, jnp.float32)}
    mgr = CheckpointManager(str(tmp_path), keep=2, every=2)
    real_saves = []
    orig_maybe_save = mgr.maybe_save
    monkeypatch.setattr(
        mgr, "maybe_save",
        lambda *a, **k: real_saves.append(a) or orig_maybe_save(*a, **k))
    try:
        res = run_elastic(lambda step: opt.step(g), mgr, opt,
                          total_steps=4, step_deadline=30.0,
                          backoff_s=0.0)
    finally:
        mgr.close()
    assert res.step == 4 and not res.preempted
    assert stale_saves and all(v is False for v in stale_saves), (
        "stale save thunk ran manager.maybe_save instead of "
        f"skipping: {stale_saves}")
    assert real_saves == [], (
        "abandoned-generation save thunk still reached the manager")
