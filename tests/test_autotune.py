"""Autotuner + per-topology dispatch tables.

Covers the three layers ISSUE 11 added: (a) the per-topology
dispatch-table selection in ops/_dispatch.py (wrong-topology tables
ignored loudly, missing tables fall back, malformed entries drop
per-entry, the cached-with-invalidation accessor and install_prefs),
(b) the stdlib schema validator + budget restamp logic in
tools/autotune.py, and the perf_gate auto-gating mode, and (c) the
acceptance flow: ``tools/autotune.py --cpu-smoke`` end to end —
sweep -> schema-valid table -> installed table changes a dispatch
decision (via the new accessor) -> perf_budget row restamped with
sweep provenance."""

import importlib.util
import json
import os
import warnings

import pytest

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_path(name, path):
    spec = importlib.util.spec_from_file_location(name, path)
    m = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(m)
    return m


def _load_tool(name):
    return _load_path(name, os.path.join(_ROOT, "tools", f"{name}.py"))


at = _load_tool("autotune")
pg = _load_tool("perf_gate")


def _topo_block(key="cpu-8", kind="cpu", n=8):
    return {"key": key, "device_kind": kind, "device_count": n,
            "process_count": 1}


def _good_table(key="cpu-8"):
    return {
        "schema": at.SCHEMA_VERSION,
        "methodology": "amortized",
        "source": "tools/autotune.py",
        "topology": _topo_block(key),
        "noise_floor_pct": 3.5,
        "prefer_pallas": {"multi_tensor": False, "welford": True},
        "attn_block_cap": {"128": 512},
        "pipeline": {"max_bucket_bytes": 1 << 25,
                     "reduce_decompose": "reduce_scatter"},
        "serving": {"page_size": 8, "decode_window": 8,
                    "kv_dtype": "int8", "prefix_share": True,
                    "spec_k": 4, "weight_dtype": "int8",
                    "prefill_batch": 4},
    }


# ---------------------------------------------------------------------------
# schema validation (the check.sh gate)
# ---------------------------------------------------------------------------

class TestValidateTable:
    def test_schema_version_in_sync_with_dispatch(self):
        from apex_tpu.ops import _dispatch
        assert at.SCHEMA_VERSION == _dispatch.SCHEMA_VERSION

    def test_good_per_topology_table_passes(self):
        assert at.validate_table(
            _good_table(), per_topology=True,
            path="x/dispatch_prefs.cpu-8.json") == []

    def test_shipped_tables_validate(self):
        assert at.validate_paths() == []

    def test_spec_k_zero_is_valid(self):
        # spec_k is the one serving integer where 0 is a VALID value
        # (speculation off) — it must not ride the positive-int check
        doc = _good_table()
        doc["serving"]["spec_k"] = 0
        assert at.validate_table(
            doc, per_topology=True,
            path="x/dispatch_prefs.cpu-8.json") == []

    @pytest.mark.parametrize("mutate,needle", [
        (lambda d: d.pop("methodology"), "methodology"),
        (lambda d: d.pop("topology"), "topology block"),
        (lambda d: d.pop("noise_floor_pct"), "noise_floor_pct"),
        (lambda d: d.update(schema=1), "schema=2"),
        (lambda d: d["prefer_pallas"].update(softmax="yes"),
         "JSON boolean"),
        (lambda d: d["attn_block_cap"].update({"128": 100}),
         "multiple of 128"),
        (lambda d: d["pipeline"].update(reduce_decompose="allreduce"),
         "reduce_decompose"),
        (lambda d: d["pipeline"].update(max_bucket_bytes=-4),
         "max_bucket_bytes"),
        (lambda d: d["topology"].pop("key"), "string 'key'"),
        (lambda d: d["serving"].update(page_size=0),
         "serving.page_size"),
        (lambda d: d["serving"].update(kv_dtype="fp4"),
         "serving.kv_dtype"),
        (lambda d: d["serving"].update(prefix_share="yes"),
         "serving.prefix_share"),
        (lambda d: d["serving"].update(spec_k=-1),
         "serving.spec_k"),
        (lambda d: d["serving"].update(weight_dtype="fp4"),
         "serving.weight_dtype"),
        (lambda d: d["serving"].update(prefill_batch=0),
         "serving.prefill_batch"),
    ])
    def test_each_violation_fails_fast(self, mutate, needle):
        doc = _good_table()
        mutate(doc)
        errs = at.validate_table(doc, per_topology=True,
                                 path="x/dispatch_prefs.cpu-8.json")
        assert errs and any(needle in e for e in errs), (needle, errs)

    def test_filename_must_match_topology_key(self):
        errs = at.validate_table(_good_table("tpu_v4-8"),
                                 per_topology=True,
                                 path="x/dispatch_prefs.cpu-8.json")
        assert any("filename must match" in e for e in errs)

    def test_default_table_needs_no_topology(self):
        # the shipped topology-agnostic default stays valid...
        assert at.validate_table(
            {"methodology": "amortized",
             "prefer_pallas": {"welford": True}},
            per_topology=False) == []
        # ...but the methodology stamp is still mandatory everywhere
        errs = at.validate_table({"prefer_pallas": {}},
                                 per_topology=False)
        assert any("methodology" in e for e in errs)

    def test_validate_paths_flags_unreadable_and_bad(self, tmp_path):
        good = tmp_path / "dispatch_prefs.cpu-8.json"
        good.write_text(json.dumps(_good_table()))
        bad = tmp_path / "dispatch_prefs.json"
        bad.write_text("{truncated")
        errs = at.validate_paths([str(good), str(bad)])
        assert len(errs) == 1 and "unreadable" in errs[0]


# ---------------------------------------------------------------------------
# budget restamp
# ---------------------------------------------------------------------------

class TestRestampBudget:
    BUDGET = {
        "stamped_at": "2026-07-31T03:41:18Z",
        "metrics": {
            "extra.grad_accum_n8_speedup": {
                "floor": 1.0, "direction": "higher", "noise_pct": 10.0},
            "extra.resnet50_step_ms": {
                "ceiling": 60.71, "direction": "lower",
                "noise_pct": 5.0},
        }}

    def test_floor_moves_with_provenance(self):
        b = json.loads(json.dumps(self.BUDGET))
        rows = at.restamp_budget(
            b, {"extra.grad_accum_n8_speedup": 1.84},
            topology="tpu_v5e-8", backend="tpu", noise_floor_pct=3.0,
            mode="full", when="2026-08-04T00:00:00Z")
        assert rows == ["extra.grad_accum_n8_speedup"]
        spec = b["metrics"]["extra.grad_accum_n8_speedup"]
        assert spec["floor"] == 1.84
        assert spec["restamped"]["by"] == "tools/autotune.py"
        assert spec["restamped"]["topology"] == "tpu_v5e-8"
        # a hardware restamp moves the gate's auto-mode anchor
        assert b["stamped_at"] == "2026-08-04T00:00:00Z"

    def test_lower_is_better_moves_ceiling(self):
        b = json.loads(json.dumps(self.BUDGET))
        at.restamp_budget(
            b, {"extra.resnet50_step_ms": 55.2}, topology="t",
            backend="tpu", noise_floor_pct=3.0, mode="full",
            when="2026-08-04T00:00:00Z")
        assert b["metrics"]["extra.resnet50_step_ms"]["ceiling"] == 55.2

    def test_cpu_smoke_never_moves_the_stamp_date(self):
        # row provenance lands (the plumbing proof) but the gate's
        # auto-mode anchor only moves on hardware
        b = json.loads(json.dumps(self.BUDGET))
        rows = at.restamp_budget(
            b, {"extra.grad_accum_n8_speedup": 0.4}, topology="cpu-8",
            backend="cpu", noise_floor_pct=12.0, mode="cpu-smoke",
            when="2026-08-04T00:00:00Z")
        assert rows == ["extra.grad_accum_n8_speedup"]
        assert b["stamped_at"] == "2026-07-31T03:41:18Z"
        assert b["metrics"]["extra.grad_accum_n8_speedup"][
            "restamped"]["mode"] == "cpu-smoke"

    def test_unknown_metrics_ignored(self):
        b = json.loads(json.dumps(self.BUDGET))
        assert at.restamp_budget(
            b, {"extra.never_heard_of_it": 9.9}, topology="t",
            backend="tpu", noise_floor_pct=3.0, mode="full",
            when="w") == []


# ---------------------------------------------------------------------------
# perf_gate auto-gating mode
# ---------------------------------------------------------------------------

class TestPerfGateAutoMode:
    BUDGET = {"stamped_at": "2026-07-31T03:41:18Z", "metrics": {}}

    @staticmethod
    def _round(backend="tpu", when="2026-08-01T00:00:00Z",
               cached=False, value=100.0):
        p = {"backend": backend, "value": value}
        if cached:
            p["extra"] = {"cached_measured_at": when}
        else:
            p["measured_at"] = when
        return p

    def test_newer_live_round_gates(self):
        gating, reason = pg.choose_mode(
            self.BUDGET, [(4, self._round(when="2026-07-31T03:41:18Z")),
                          (6, self._round(when="2026-08-04T01:00:00Z"))])
        assert gating and "postdates" in reason

    def test_round_covered_by_stamp_reports_only(self):
        gating, reason = pg.choose_mode(
            self.BUDGET,
            [(5, self._round(when="2026-07-31T03:41:18Z",
                             cached=True))])
        assert not gating and "does not postdate" in reason

    def test_cpu_newest_round_reports_only(self):
        gating, reason = pg.choose_mode(
            self.BUDGET, [(4, self._round(when="2026-08-04T01:00:00Z")),
                          (6, self._round(backend="cpu-fallback"))])
        assert not gating and "not a hardware round" in reason

    def test_missing_timestamps_report_only(self):
        p = {"backend": "tpu", "value": 10.0}
        gating, reason = pg.choose_mode(self.BUDGET, [(4, p)])
        assert not gating and "cannot compare" in reason
        gating, _ = pg.choose_mode({"metrics": {}}, [(4, self._round())])
        assert not gating

    def test_no_rounds_report_only(self):
        gating, reason = pg.choose_mode(self.BUDGET, [])
        assert not gating

    def test_repo_state_is_report_only_today(self):
        """The committed r04/r05 cached rounds re-serve the window the
        budget was stamped from — flipping to gating on them would
        block exactly the PRs that will re-measure them."""
        with open(os.path.join(_ROOT, "tools",
                               "perf_budget.json")) as f:
            budget = json.load(f)
        gating, _ = pg.choose_mode(budget, pg.load_rounds(_ROOT))
        assert not gating

    def test_cli_exit_codes(self, tmp_path):
        budget = tmp_path / "b.json"
        budget.write_text(json.dumps({
            "stamped_at": "2026-07-01T00:00:00Z",
            "metrics": {"value": {"floor": 200.0,
                                  "direction": "higher",
                                  "noise_pct": 5.0}}}))
        art = tmp_path / "BENCH_r01.json"
        art.write_text(json.dumps({"parsed": {
            "backend": "tpu", "value": 100.0,
            "measured_at": "2026-08-01T00:00:00Z"}}))
        # auto mode gates (round postdates stamp) and the breach fails
        assert pg.main(["--budget", str(budget), "--root",
                        str(tmp_path)]) == 1
        # forced report-only always exits 0
        assert pg.main(["--budget", str(budget), "--root",
                        str(tmp_path), "--report"]) == 0
        # an older round does not gate even on a breach
        art.write_text(json.dumps({"parsed": {
            "backend": "tpu", "value": 100.0,
            "measured_at": "2026-06-01T00:00:00Z"}}))
        assert pg.main(["--budget", str(budget), "--root",
                        str(tmp_path)]) == 0
        # --gate forces it back on
        assert pg.main(["--budget", str(budget), "--root",
                        str(tmp_path), "--gate"]) == 1


# ---------------------------------------------------------------------------
# per-topology table selection (ops/_dispatch.py)
# ---------------------------------------------------------------------------

@pytest.fixture
def live_dispatch(monkeypatch, tmp_path):
    """Undo conftest's neutralization so the file-backed accessor is
    live, rooted at an empty tmp dir (no shipped table in play)."""
    from apex_tpu.ops import _dispatch
    monkeypatch.setattr(_dispatch, "_PREFS", None)
    monkeypatch.setattr(_dispatch, "_ATTN_CAPS", None)
    monkeypatch.setattr(_dispatch, "_PIPELINE", None)
    monkeypatch.setattr(_dispatch, "_INSTALLED", None)
    monkeypatch.setattr(_dispatch, "_CACHE", None)
    monkeypatch.setattr(_dispatch, "_PREFS_PATH",
                        str(tmp_path / "dispatch_prefs.json"))
    return _dispatch, tmp_path


def _write(path, doc):
    path.write_text(json.dumps(doc))


class TestTopologySelection:
    def test_matching_topology_table_wins_over_default(
            self, live_dispatch):
        _dispatch, root = live_dispatch
        key = _dispatch.topology_key()
        _write(root / "dispatch_prefs.json",
               {"methodology": "amortized",
                "prefer_pallas": {"welford": True}})
        _write(root / f"dispatch_prefs.{key}.json", _good_table(key))
        assert not _dispatch.op_enabled("multi_tensor")
        assert _dispatch.op_enabled("welford")
        assert _dispatch.attn_block_cap(128) == 512
        assert _dispatch.pipeline_pref("reduce_decompose") \
            == "reduce_scatter"
        assert _dispatch.dispatch_tables().topology == key

    def test_wrong_topology_table_ignored_with_warning(
            self, live_dispatch):
        _dispatch, root = live_dispatch
        key = _dispatch.topology_key()
        _write(root / "dispatch_prefs.json",
               {"methodology": "amortized",
                "prefer_pallas": {"multi_tensor": True}})
        # the file is NAMED for this topology but stamped for another
        # (a copied-over table): ignored, loudly, default table steers
        _write(root / f"dispatch_prefs.{key}.json",
               _good_table("tpu_v4-8"))
        with pytest.warns(RuntimeWarning, match="topology"):
            assert _dispatch.op_enabled("multi_tensor")
        assert _dispatch.dispatch_tables().topology is None

    def test_missing_topology_table_falls_back_to_default(
            self, live_dispatch):
        _dispatch, root = live_dispatch
        _write(root / "dispatch_prefs.json",
               {"methodology": "amortized",
                "prefer_pallas": {"softmax": False},
                "attn_block_cap": {"128": 256}})
        with warnings.catch_warnings():
            warnings.simplefilter("error")
            assert not _dispatch.op_enabled("softmax")
            assert _dispatch.attn_block_cap(128) == 256

    def test_default_table_with_foreign_topology_ignored(
            self, live_dispatch):
        """kernel_bench --write-prefs stamps topology into the default
        table now: a table benched on one fleet must not silently
        steer another (absent block = legacy/portable, still steers)."""
        _dispatch, root = live_dispatch
        doc = {"methodology": "amortized",
               "prefer_pallas": {"welford": False},
               "topology": _topo_block("tpu_v4-8", "TPU v4", 8)}
        _write(root / "dispatch_prefs.json", doc)
        with pytest.warns(RuntimeWarning, match="topology"):
            assert _dispatch.op_enabled("welford")

    def test_malformed_entries_drop_per_entry(self, live_dispatch):
        _dispatch, root = live_dispatch
        key = _dispatch.topology_key()
        doc = _good_table(key)
        doc["attn_block_cap"] = {"128": 256, "256": "auto", "64": -128}
        doc["pipeline"] = {"max_bucket_bytes": "lots",
                           "reduce_decompose": "reduce_scatter",
                           "unknown_knob": 7}
        doc["serving"] = {"page_size": 16, "decode_window": "wide",
                          "kv_dtype": "fp4", "prefix_share": "yes"}
        _write(root / f"dispatch_prefs.{key}.json", doc)
        t = _dispatch.dispatch_tables()
        assert t.attn_block_cap == {"128": 256}
        # bad max_bucket_bytes dropped, good reduce_decompose kept
        assert t.pipeline == {"reduce_decompose": "reduce_scatter"}
        assert _dispatch.pipeline_pref("max_bucket_bytes") is None
        # serving: good page_size kept; out-of-domain kv_dtype,
        # non-bool prefix_share, and non-int window all dropped
        assert t.serving == {"page_size": 16}
        assert _dispatch.serving_pref("kv_dtype", "f32") == "f32"
        assert _dispatch.serving_pref("prefix_share", False) is False
        # the routing table survived its siblings' bad entries
        assert not _dispatch.op_enabled("multi_tensor")

    def test_stale_methodology_per_topology_table_warns(
            self, live_dispatch):
        _dispatch, root = live_dispatch
        key = _dispatch.topology_key()
        doc = _good_table(key)
        doc["methodology"] = "dispatch-per-iteration"
        _write(root / f"dispatch_prefs.{key}.json", doc)
        with pytest.warns(RuntimeWarning, match="IGNORED"):
            assert _dispatch.op_enabled("multi_tensor")

    def test_no_tables_at_all_is_design_default(self, live_dispatch):
        _dispatch, _ = live_dispatch
        with warnings.catch_warnings():
            warnings.simplefilter("error")
            assert _dispatch.op_enabled("anything")
            assert _dispatch.attn_block_cap(128) is None
            assert _dispatch.pipeline_pref("reduce_decompose",
                                           "psum") == "psum"


class TestCachedAccessor:
    def test_rewritten_file_invalidates_via_mtime(self, live_dispatch):
        _dispatch, root = live_dispatch
        key = _dispatch.topology_key()
        p = root / f"dispatch_prefs.{key}.json"
        _write(p, _good_table(key))
        assert not _dispatch.op_enabled("multi_tensor")
        doc = _good_table(key)
        doc["prefer_pallas"]["multi_tensor"] = True
        _write(p, doc)
        os.utime(p, (os.path.getmtime(p) + 2,) * 2)
        assert _dispatch.op_enabled("multi_tensor")

    def test_explicit_invalidate(self, live_dispatch):
        _dispatch, root = live_dispatch
        key = _dispatch.topology_key()
        p = root / f"dispatch_prefs.{key}.json"
        _write(p, _good_table(key))
        assert not _dispatch.op_enabled("multi_tensor")
        p.unlink()
        _dispatch.invalidate_prefs_cache()
        assert _dispatch.op_enabled("multi_tensor")

    def test_install_prefs_steers_without_reload(self, live_dispatch):
        _dispatch, _ = live_dispatch
        key = _dispatch.topology_key()
        assert _dispatch.op_enabled("multi_tensor")   # design default
        t = _dispatch.install_prefs(_good_table(key))
        assert t.source == "<installed>"
        assert not _dispatch.op_enabled("multi_tensor")
        assert _dispatch.attn_block_cap(128) == 512
        assert _dispatch.pipeline_pref("max_bucket_bytes") == 1 << 25
        # prefs_disabled classification works through the accessor
        assert _dispatch.prefs_disabled("multi_tensor")
        _dispatch.install_prefs(None)
        assert _dispatch.op_enabled("multi_tensor")

    def test_install_rejects_stale_or_foreign_tables(
            self, live_dispatch):
        _dispatch, _ = live_dispatch
        doc = _good_table(_dispatch.topology_key())
        doc["methodology"] = "dispatch-per-iteration"
        with pytest.raises(ValueError, match="IGNORED"):
            _dispatch.install_prefs(doc)
        with pytest.raises(ValueError, match="topology"):
            _dispatch.install_prefs(_good_table("tpu_v4-8"))
        # ...unless the caller explicitly opts out of the check
        t = _dispatch.install_prefs(_good_table("tpu_v4-8"),
                                    check_topology=False)
        assert not _dispatch.op_enabled("multi_tensor")
        assert t.topology == "tpu_v4-8"
        _dispatch.install_prefs(None)

    def test_topology_block_shape(self):
        from apex_tpu.ops import _dispatch
        b = _dispatch.topology_block()
        assert b["key"] == _dispatch.topology_key()
        assert b["device_count"] >= 1 and b["device_kind"]
        assert at.validate_table(
            {**_good_table(), "topology": b}, per_topology=True) == []


class TestAutoKnobConsumers:
    def test_flat_pipeline_auto_resolves_from_table(self,
                                                    live_dispatch):
        import jax.numpy as jnp

        from apex_tpu import amp
        _dispatch, _ = live_dispatch
        _dispatch.install_prefs(_good_table(_dispatch.topology_key()))
        try:
            params = {"w": jnp.ones((64,), jnp.float32)}
            pipe = amp.FlatGradPipeline(params=params,
                                        reduce_decompose="auto",
                                        max_bucket_bytes="auto")
            assert pipe.reduce_decompose == "reduce_scatter"
            assert pipe.max_bucket_bytes == 1 << 25
        finally:
            _dispatch.install_prefs(None)

    def test_flat_pipeline_auto_defers_to_supplied_plan(
            self, live_dispatch):
        import jax.numpy as jnp

        from apex_tpu import amp
        from apex_tpu.multi_tensor_apply.packer import BucketPlan
        _dispatch, _ = live_dispatch
        _dispatch.install_prefs(_good_table(_dispatch.topology_key()))
        try:
            params = {"w": jnp.ones((64,), jnp.float32)}
            plan = BucketPlan.from_tree(params)
            # "auto" + an explicit plan: the plan owns its chunking —
            # no conflict error, no silent re-chunk
            pipe = amp.FlatGradPipeline(plan=plan,
                                        max_bucket_bytes="auto")
            assert pipe.max_bucket_bytes == getattr(
                plan, "max_bucket_bytes", None)
        finally:
            _dispatch.install_prefs(None)

    def test_ddp_auto_resolves_from_table(self, live_dispatch):
        from apex_tpu.parallel import DistributedDataParallel
        _dispatch, _ = live_dispatch
        _dispatch.install_prefs(_good_table(_dispatch.topology_key()))
        try:
            ddp = DistributedDataParallel(lambda p, x: x,
                                          reduce_decompose="auto")
            assert ddp.reduce_decompose == "reduce_scatter"
        finally:
            _dispatch.install_prefs(None)
        ddp = DistributedDataParallel(lambda p, x: x,
                                      reduce_decompose="auto")
        assert ddp.reduce_decompose == "psum"


# ---------------------------------------------------------------------------
# acceptance: the full --cpu-smoke pipeline in tier-1
# ---------------------------------------------------------------------------

def test_cpu_smoke_end_to_end(tmp_path, monkeypatch):
    """sweep -> schema-valid per-topology table -> installed table
    demonstrably changes >= 1 dispatch decision (via the accessor) ->
    perf_budget row restamped with sweep provenance.  Runs the REAL
    tools/autotune.py main in-process (tiny fixed candidate lists,
    interpret mode)."""
    from apex_tpu.ops import _dispatch

    # undo conftest's neutralization: the demonstration must flow
    # through the live accessor
    monkeypatch.setattr(_dispatch, "_PREFS", None)
    monkeypatch.setattr(_dispatch, "_ATTN_CAPS", None)
    monkeypatch.setattr(_dispatch, "_PIPELINE", None)
    monkeypatch.setattr(_dispatch, "_INSTALLED", None)
    monkeypatch.setattr(_dispatch, "_CACHE", None)
    monkeypatch.setenv("APEX_TPU_PALLAS_INTERPRET", "1")
    out = tmp_path / "autotune"

    assert at.main(["--cpu-smoke", "--out", str(out)]) == 0

    key = _dispatch.topology_key()
    table_path = out / f"dispatch_prefs.{key}.json"
    assert table_path.exists()
    # schema-valid per the SAME validator check.sh runs
    assert at.validate_paths([str(table_path)]) == []
    doc = json.loads(table_path.read_text())
    assert doc["schema"] == _dispatch.SCHEMA_VERSION
    assert doc["methodology"] == "amortized"
    assert doc["topology"]["key"] == key
    assert doc["noise_floor_pct"] >= 0
    assert doc["sweep"]["records"]           # provenance retained

    summary = json.loads((out / "autotune_summary.json").read_text())
    # the sweep demonstrated (through install_prefs + the accessor)
    # that installing the table changes at least one dispatch decision
    assert summary["decision_changes"], summary
    # ...and the demonstration is reproducible here, via the accessor
    before = {c["decision"]: c["before"]
              for c in summary["decision_changes"]}
    _dispatch.install_prefs(doc)
    try:
        for c in summary["decision_changes"]:
            name = c["decision"]
            if name.startswith("op_enabled:"):
                got = _dispatch.op_enabled(name.split(":", 1)[1])
            elif name.startswith("attn_block_cap:"):
                got = _dispatch.attn_block_cap(name.split(":", 1)[1])
            elif name == "pipeline:max_bucket_bytes":
                got = _dispatch.pipeline_pref("max_bucket_bytes")
            else:
                got = _dispatch.pipeline_pref("reduce_decompose",
                                              "psum")
            assert got == c["after"] and got != before[name], c
    finally:
        _dispatch.install_prefs(None)

    # the budget COPY (never the repo file) gained sweep provenance
    assert summary["budget_rows_restamped"]
    budget = json.loads((out / "perf_budget.json").read_text())
    for row in summary["budget_rows_restamped"]:
        stamp = budget["metrics"][row]["restamped"]
        assert stamp["by"] == "tools/autotune.py"
        assert stamp["mode"] == "cpu-smoke"
        assert stamp["topology"] == key
    # a cpu restamp must not move the gate's auto-mode anchor
    with open(os.path.join(_ROOT, "tools", "perf_budget.json")) as f:
        assert budget["stamped_at"] == json.load(f)["stamped_at"]

    # device-timeline cross-check ran for any flipped routing family
    # the smoke config nominates for checking
    records = json.loads(
        (out / "autotune_summary.json").read_text())["sweep_records"]
    routing = [r for r in records if r.get("space") == "routing"]
    assert routing
    for r in routing:
        flip = r.get("decision", {}).get("prefer_pallas", {})
        if r["family"] == "multi_tensor" and flip \
                and not all(flip.values()):
            assert "device_check" in r, r
