"""apex_tpu.telemetry: the zero-host-sync contract, end to end.

Covers the ring (write/flush round trip under jit, donation), the
structural no-per-step-host-transfer guarantee (jaxpr walk of an
instrumented flat-AMP train step), JSONL schema stability, span
nesting/exception safety, the retrace counter (monitoring hook + the
forced-retrace wrapper), rank-0-only emission under a faked
multi-process config, and the pyprof satellite fixes (thread-local
nvtx stack, prof --json + newest-by-mtime)."""

import json
import os
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import amp, telemetry
from apex_tpu.optimizers import FusedAdam
from apex_tpu.telemetry import _tape
from apex_tpu.telemetry.cli import main as telemetry_cli, summarize
from apex_tpu.telemetry.ring import MetricRing
from apex_tpu.telemetry.session import JSONL_NAME

tree_map = jax.tree_util.tree_map


# ---------------------------------------------------------------------------
# MetricRing
# ---------------------------------------------------------------------------

def test_ring_record_flush_round_trip_under_jit():
    ring = MetricRing(("loss", "grad_norm"), window=4)
    buf = ring.init()
    rec = jax.jit(ring.record)
    for i in range(6):          # wraps: steps 2..5 survive, 0..1 evicted
        buf = rec(buf, {"loss": jnp.float32(i * 0.5),
                        "grad_norm": jnp.float32(i)}, i)
    out = ring.decode(jax.device_get(buf))
    assert [r["step"] for r in out] == [2, 3, 4, 5]
    assert [r["loss"] for r in out] == [1.0, 1.5, 2.0, 2.5]
    assert [r["grad_norm"] for r in out] == [2.0, 3.0, 4.0, 5.0]
    # decode is incremental: after_step skips already-flushed rows
    assert [r["step"] for r in ring.decode(jax.device_get(buf),
                                           after_step=4)] == [5]


def test_ring_partial_writes_compose_and_unknown_names_ignored():
    ring = MetricRing(("a", "b"), window=2)
    buf = ring.init()
    buf = ring.record(buf, {"a": 1.0, "other": 9.0}, 0)
    buf = ring.record(buf, {"b": 2.0}, 0)     # same step, second producer
    (r,) = ring.decode(jax.device_get(buf))
    assert r == {"step": 0, "a": 1.0, "b": 2.0}


def test_ring_nan_metric_decodes_to_none_with_stable_schema():
    ring = MetricRing(("a", "b"), window=2)
    buf = ring.record(ring.init(), {"a": jnp.float32(jnp.nan)}, 3)
    (r,) = ring.decode(jax.device_get(buf))
    assert set(r) == {"step", "a", "b"}       # full key set always
    assert r["a"] is None and r["b"] is None


def test_ring_wrap_clears_evicted_rows_and_midstep_flush_is_safe(
        tmp_path):
    """Two producers per step + a wrapping ring: no stale metric may
    survive row eviction, and the window flush must never cut off a
    step that is still accumulating (both were real bugs)."""
    ring = MetricRing(("loss", "gn"), window=3)
    buf = ring.init()
    for s in range(5):
        buf = ring.record(buf, {"loss": float(s)}, s)
        if s != 1:                        # step 1's producer-2 missing
            buf = ring.record(buf, {"gn": 10.0 * s}, s)
    out = ring.decode(jax.device_get(buf))
    assert [r["step"] for r in out] == [2, 3, 4]
    assert [r["gn"] for r in out] == [20.0, 30.0, 40.0]
    # step 1's gn=10.0 must not reappear on the row step 4 reclaimed
    assert all(r["loss"] == float(r["step"]) for r in out)

    # session: auto-flush fires mid-step without losing producer 2
    d = str(tmp_path / "run")
    with telemetry.Telemetry(d, metrics=("loss", "gn"), window=3,
                             retrace=False) as tel:
        for s in range(5):
            tel.record({"loss": float(s)}, s)
            tel.record({"gn": 10.0 * s}, s)
    lines = [json.loads(l) for l in
             open(os.path.join(d, JSONL_NAME)) if l.strip()]
    steps = {l["step"]: l for l in lines
             if l.get("kind", "step") == "step"}
    assert sorted(steps) == [0, 1, 2, 3, 4]
    for s, r in steps.items():
        assert r["loss"] == float(s), r
        assert r["gn"] == 10.0 * s, r


def test_ring_step_exact_beyond_f32_integers():
    """Step ids stay exact past 2^24 (lo/hi split cells): neighboring
    huge steps must not merge into one row."""
    ring = MetricRing(("a",), window=4)
    buf = ring.init()
    s0 = (1 << 24)                     # 16_777_216: f32 folds s0+1 into s0
    for i in range(3):
        buf = ring.record(buf, {"a": float(i)}, s0 + i)
    out = ring.decode(jax.device_get(buf))
    assert [r["step"] for r in out] == [s0, s0 + 1, s0 + 2]
    assert [r["a"] for r in out] == [0.0, 1.0, 2.0]


def test_tape_stack_is_thread_local():
    """A background thread's producer emissions must not land on the
    main thread's step tape (same hazard class as the nvtx stack)."""
    _tape.push()
    done = threading.Event()

    def background():
        _tape.emit("bg_metric", 1.0)          # no tape in THIS thread
        _tape.push()
        _tape.emit("bg_own", 2.0)
        assert float(_tape.pop().values["bg_own"]) == 2.0
        done.set()

    t = threading.Thread(target=background)
    t.start()
    t.join()
    assert done.is_set()
    tape = _tape.pop()
    assert "bg_metric" not in tape.values
    assert "bg_own" not in tape.values


def test_ring_rejects_bad_config():
    with pytest.raises(ValueError, match="window"):
        MetricRing(("a",), window=0)
    with pytest.raises(ValueError, match="reserved"):
        MetricRing(("step", "a"))
    with pytest.raises(ValueError, match="at least one"):
        MetricRing(())


def test_session_commit_donates_ring_buffer():
    tel = telemetry.Telemetry(run_dir=None, metrics=("loss",), window=8,
                              retrace=False)
    b0 = tel.buf
    tel.record({"loss": jnp.float32(1.0)}, 0)
    assert b0.is_deleted()      # donated: never two live ring copies
    tel.close()


# ---------------------------------------------------------------------------
# structural guarantee: telemetry adds ZERO per-step host transfers —
# now owned by the shared apexverify spec `telemetry.instrumented_step`
# (apex_tpu/lint/semantic/specs.py traces the same instrumented
# flat-AMP step this test used to build by hand)
# ---------------------------------------------------------------------------

def test_instrumented_step_jaxpr_has_no_host_callbacks():
    """A telemetry-on flat-AMP train step contains no callback/transfer
    primitives — the ring writes are plain dynamic_update_slices; the
    only device_get in the subsystem is the window flush, which lives
    OUTSIDE the step program entirely.  Asserted by the registered
    invariant spec (the same walker the `--semantic` CI gate runs)."""
    from apex_tpu.lint import semantic

    res = semantic.verify_spec(
        semantic.get_spec("telemetry.instrumented_step"))
    assert res.ok, res.failures
    # assertion strength preserved: the spec checked both the zero-
    # transfer invariant and the presence of the ring write (the
    # VALUES are asserted by
    # test_instrument_records_producer_metrics_end_to_end)
    assert {"no_host_transfer", "dus_min"} <= set(res.checked)


def test_instrument_records_producer_metrics_end_to_end():
    params = {"w": jnp.ones((8, 8)) * 0.1, "b": jnp.zeros((8,))}
    x = jax.random.normal(jax.random.key(1), (4, 8))
    scaler = amp.LossScaleState.create()
    opt = FusedAdam(params, lr=1e-3)
    pipe = amp.FlatGradPipeline(optimizer=opt, max_grad_norm=1.0)
    tel = telemetry.Telemetry(run_dir=None, window=4, retrace=False)

    def loss_fn(p, x):
        return jnp.mean((x @ p["w"] + p["b"]) ** 2)

    def train_step(work_bufs, opt_state, scaler, x, step):
        ptree = opt._plan.unpack_model(work_bufs)
        loss, flat = pipe.scaled_value_and_grad(loss_fn, scaler, ptree, x)
        new_bufs, _, new_state = opt._full_step_flat(
            work_bufs, None, opt_state, flat.bufs, step, 1.0,
            {}, flat.found_inf)
        return loss, new_bufs, new_state

    step_fn = jax.jit(tel.instrument(train_step), donate_argnums=(0,))
    bufs, state = opt._param_bufs, opt.opt_state
    for i in range(3):
        tbuf, (loss, bufs, state) = step_fn(
            tel.buf, i, bufs, state, scaler, x, jnp.int32(i + 1))
        tel.update(tbuf, i)
    recs = tel.flush()
    assert [r["step"] for r in recs] == [0, 1, 2]
    for r in recs:
        assert r["loss"] is not None
        assert r["amp/grad_norm"] is not None and r["amp/grad_norm"] > 0
        assert r["amp/clip_coef"] is not None
        assert r["amp/found_inf"] == 0.0
        assert r["amp/loss_scale"] == float(scaler.loss_scale)
        assert r["optim/skipped"] == 0.0
    tel.close()


def test_functional_step_applies_found_inf_skip_and_emits():
    """The public embed-in-your-jit entry point honors the overflow
    flag (docs wiring table: optim/skipped) — both with an explicit
    found_inf and with a FlatGrads bundle."""
    params = {"w": jnp.ones((8, 8)) * 0.5, "b": jnp.zeros((8,))}
    opt = FusedAdam(params, lr=1e-2)
    grads = tree_map(lambda p: p * 1e-2 + 1e-3, params)
    bundle = amp.FlatGradPipeline(optimizer=opt).unscale_and_norm(
        opt._plan.pack_grads(grads))

    _tape.push()
    new_p, new_s = opt.functional_step(params, opt.opt_state, grads,
                                       jnp.int32(1),
                                       found_inf=jnp.int32(1))
    t = _tape.pop()
    assert float(t.values["optim/skipped"]) == 1.0
    for a, b in zip(jax.tree_util.tree_leaves(new_p),
                    jax.tree_util.tree_leaves(params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # FlatGrads bundle: found_inf/clip ride along; finite -> steps
    new_p2, _ = opt.functional_step(params, opt.opt_state, bundle,
                                    jnp.int32(1))
    assert not np.allclose(np.asarray(new_p2["w"]),
                           np.asarray(params["w"]))
    # per-leaf state rejects the bundle loudly (step() parity)
    opt_pl = FusedAdam(params, lr=1e-2, fuse_buckets=False)
    with pytest.raises(ValueError, match="FlatGrads"):
        opt_pl.functional_step(params, opt_pl.opt_state, bundle,
                               jnp.int32(1))


def test_tape_reduce_combines():
    _tape.push()
    _tape.emit("m", 3.0, reduce="max")
    _tape.emit("m", 5.0, reduce="max")
    _tape.emit("s", 1.0, reduce="sum")
    _tape.emit("s", 2.0, reduce="sum")
    _tape.emit("n", 3.0, reduce="rss")
    _tape.emit("n", 4.0, reduce="rss")
    t = _tape.pop()
    assert float(t.values["m"]) == 5.0
    assert float(t.values["s"]) == 3.0
    assert float(t.values["n"]) == pytest.approx(5.0)
    # no active tape: emit is a no-op, never an error
    _tape.emit("m", 1.0)


def test_eager_tape_drops_foreign_tracers():
    """A tape opened eagerly must not capture tracers from a nested jit
    (they would escape that trace); concrete values still land."""
    _tape.push()

    @jax.jit
    def inner(x):
        _tape.emit("inner_metric", x)
        return x + 1

    inner(jnp.float32(1.0))
    _tape.emit("outer_metric", jnp.float32(2.0))
    t = _tape.pop()
    assert "inner_metric" not in t.values
    assert float(t.values["outer_metric"]) == 2.0


def test_traced_tape_drops_nested_jit_tracers():
    """An instrumented step calling a separately-jitted helper that
    emits must not capture the helper's tracers (they belong to the
    inner trace) — the metric is absent, never an escape crash."""
    ring = MetricRing(("own", "foreign"), window=2)

    @jax.jit
    def helper(x):
        _tape.emit("foreign", x * 2)
        return x * 2

    def step(x):
        _tape.emit("own", x + 1)
        return helper(x)

    def wrapped(buf, step_i, x):
        tape = _tape.push()
        try:
            out = step(x)
        finally:
            _tape.pop()
        return ring.record(buf, tape.values, step_i), out

    buf, _ = jax.jit(wrapped)(ring.init(), 0, jnp.float32(3.0))
    (rec,) = ring.decode(jax.device_get(buf))
    assert rec["own"] == 4.0
    assert rec["foreign"] is None


def test_flush_cadence_counts_records_not_step_numbers(tmp_path):
    """Recording every k-th step (metrics cadence != step cadence) must
    still flush before the ring wraps — nothing is silently lost."""
    d = str(tmp_path / "sparse")
    with telemetry.Telemetry(d, metrics=("loss",), window=4,
                             retrace=False) as tel:
        for step in range(0, 100, 10):        # 10 records, window 4
            tel.record({"loss": float(step)}, step)
    lines = [json.loads(l) for l in
             open(os.path.join(d, JSONL_NAME)) if l.strip()]
    steps = [l["step"] for l in lines
             if l.get("kind", "step") == "step" and "step" in l]
    assert steps == list(range(0, 100, 10))   # all 10 survived


# ---------------------------------------------------------------------------
# emitters / JSONL schema / rank gating
# ---------------------------------------------------------------------------

def test_jsonl_schema_stability(tmp_path):
    d = str(tmp_path / "run")
    with telemetry.Telemetry(d, metrics=("loss", "amp/grad_norm"),
                             window=4, retrace=False) as tel:
        for i in range(5):
            tel.record({"loss": float(i)} if i % 2 == 0
                       else {"loss": float(i),
                             "amp/grad_norm": 0.5}, i)
    lines = [json.loads(l) for l in
             open(os.path.join(d, JSONL_NAME)) if l.strip()]
    assert lines[0]["kind"] == "schema"
    assert lines[0]["metrics"] == ["loss", "amp/grad_norm"]
    steps = [l for l in lines if l.get("kind", "step") == "step"
             or ("step" in l and "kind" not in l)]
    # every record carries the full schema key set, missing -> null
    for r in steps:
        assert set(r) == {"step", "loss", "amp/grad_norm"}
    assert steps[0]["amp/grad_norm"] is None      # even steps omit it
    assert steps[1]["amp/grad_norm"] == 0.5
    # CSV twin exists with matching header
    with open(os.path.join(d, "scalars.csv")) as f:
        assert f.readline().strip() == "step,loss,amp/grad_norm"


def test_console_logger_rate_limited(capsys):
    import io
    out = io.StringIO()
    lg = telemetry.StepLogger(interval_s=3600.0, stream=out,
                              metrics=("loss",))
    lg.emit([{"step": 0, "loss": 1.0}])
    lg.emit([{"step": 1, "loss": 2.0}])       # inside the interval
    assert out.getvalue().count("telemetry:") == 1
    lg2 = telemetry.StepLogger(interval_s=0.0, stream=out,
                               metrics=("loss",))
    lg2.emit([{"step": 2, "loss": 3.0}])
    lg2.emit([{"step": 3, "loss": 4.0}])
    assert out.getvalue().count("telemetry:") == 3


def test_rank0_only_emission_under_faked_multiprocess(tmp_path,
                                                     monkeypatch):
    d = str(tmp_path / "rank1")
    monkeypatch.setattr(jax, "process_index", lambda: 1)
    tel = telemetry.Telemetry(d, metrics=("loss",), window=2,
                              retrace=False)
    tel.record({"loss": 1.0}, 0)
    tel.record({"loss": 2.0}, 1)              # window boundary
    assert tel.flush() == []                  # non-writer: no fetch
    tel.close()
    assert not os.path.exists(os.path.join(d, JSONL_NAME))
    # rank 0 writes (rank0_only respected, not inverted)
    monkeypatch.setattr(jax, "process_index", lambda: 0)
    d0 = str(tmp_path / "rank0")
    with telemetry.Telemetry(d0, metrics=("loss",), window=2,
                             retrace=False) as tel0:
        tel0.record({"loss": 1.0}, 0)
    assert os.path.exists(os.path.join(d0, JSONL_NAME))


# ---------------------------------------------------------------------------
# spans
# ---------------------------------------------------------------------------

def test_span_nesting_and_exception_unwind():
    tel = telemetry.Telemetry(run_dir=None, metrics=("loss",),
                              retrace=False)
    with telemetry.span("outer"):
        with telemetry.span("inner"):
            time.sleep(0.01)
    with pytest.raises(RuntimeError):
        with telemetry.span("raises"):
            raise RuntimeError("boom")
    recs = {r["name"]: r for r in tel.spans.records()}
    assert recs["inner"]["count"] == 1
    assert recs["outer"]["total_ms"] >= recs["inner"]["total_ms"] >= 10.0
    assert recs["raises"]["count"] == 1       # recorded despite the raise
    tel.close()
    # after close the sink is gone: spans no longer accumulate
    with telemetry.span("after"):
        pass
    assert "after" not in {r["name"] for r in tel.spans.records()}


def test_checkpoint_manager_reports_spans(tmp_path):
    from apex_tpu.resilience import CheckpointManager
    tel = telemetry.Telemetry(run_dir=None, metrics=("loss",),
                              retrace=False)
    params = {"w": jnp.ones((4,))}
    with CheckpointManager(str(tmp_path), keep=2, every=1) as mgr:
        mgr.maybe_save(0, params)
        mgr.wait()
        assert mgr.restore_latest(params) is not None
    names = {r["name"] for r in tel.spans.records()}
    assert {"checkpoint/save", "checkpoint/restore"} <= names
    tel.close()


def test_checkpoint_counters_flow_to_jsonl_and_summarize(tmp_path,
                                                         capsys):
    """ckpt/save_ms, ckpt/bytes_written, ckpt/blocked_ms and
    ckpt/restore_step ride the session flush as counter records and
    render in the summarize counter table (ISSUE 6 satellite)."""
    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.resilience import CheckpointManager

    d = str(tmp_path / "run")
    ckdir = str(tmp_path / "ckpts")
    with telemetry.Telemetry(d, window=4) as tel:
        params = {"w": jnp.ones((32,))}
        opt = FusedAdam(params, lr=0.1)
        g = {"w": jnp.full((32,), 0.01)}
        with CheckpointManager(ckdir, keep=2, every=1) as mgr:
            for step in range(1, 4):
                opt.step(g)
                tel.record({"loss": 1.0 / step}, step)
                mgr.maybe_save(step, optimizer=opt)
            mgr.wait()
            assert mgr.restore_latest({"w": jnp.zeros((32,))},
                                      opt) is not None
        recs = {r["name"]: r for r in tel.counters.records()}
        assert recs["ckpt/save_ms"]["count"] == 3
        assert recs["ckpt/bytes_written"]["total"] > 0
        assert recs["ckpt/restore_step"]["last"] == 3.0
    # counter records landed in the jsonl...
    with open(os.path.join(d, "telemetry.jsonl")) as f:
        kinds = [json.loads(l).get("kind") for l in f if l.strip()]
    assert "counter" in kinds
    # ...and summarize renders them next to the span tables
    assert telemetry_cli(["summarize", d]) == 0
    out = capsys.readouterr().out
    assert "counters (cumulative):" in out
    assert "ckpt/save_ms" in out and "ckpt/bytes_written" in out
    assert telemetry_cli(["summarize", d, "--json"]) == 0
    payload = json.loads(capsys.readouterr().out)
    assert any(c["name"] == "ckpt/save_ms" for c in payload["counters"])


def test_counter_sink_removed_after_close():
    from apex_tpu.telemetry import hostmetrics
    tel = telemetry.Telemetry(run_dir=None, metrics=("loss",),
                              retrace=False)
    hostmetrics.emit("ckpt/save_ms", 1.0)
    assert tel.counters.records()
    tel.close()
    hostmetrics.emit("ckpt/save_ms", 99.0)
    assert tel.counters.records()[0]["count"] == 1   # no longer sunk


# ---------------------------------------------------------------------------
# retrace counter
# ---------------------------------------------------------------------------

def test_retrace_counter_fires_on_forced_retrace():
    c = telemetry.RetraceCounter()

    def f(x):
        return x * 2

    wrapped = jax.jit(c.wrap(f, name="f"))
    wrapped(jnp.zeros((4,)))
    wrapped(jnp.zeros((4,)))                  # cache hit: no retrace
    assert c.counts["f"] == 1
    wrapped(jnp.zeros((8,)))                  # forced retrace: new shape
    assert c.counts["f"] == 2
    assert c.retraces() == {"f": 1}
    recs = c.records(step=7)
    assert {"kind": "retrace", "name": "f", "traces": 2, "retraces": 1,
            "step": 7} in recs


def test_retrace_counter_monitoring_hook_counts_compiles():
    c = telemetry.RetraceCounter()
    if not c.install():
        pytest.skip("jax.monitoring unavailable")
    try:
        # apexlint: disable-next=APX302
        jax.jit(lambda x: x + 1)(jnp.zeros((3,)))
        # apexlint: disable-next=APX302
        jax.jit(lambda x: x + 2)(jnp.zeros((5,)))
        assert c.traces() >= 2
        assert c.compile_secs > 0
        assert any(r["name"] == "<process>" for r in c.records())
    finally:
        c.uninstall()
    before = c.traces()
    jax.jit(lambda x: x + 3)(jnp.zeros((7,)))  # apexlint: disable=APX302
    assert c.traces() == before               # uninstalled: no counting


# ---------------------------------------------------------------------------
# lockwatch (the RetraceCounter pattern for locks)
# ---------------------------------------------------------------------------

def test_watched_lock_counters_ride_flush_and_summarize(tmp_path,
                                                        capsys):
    d = str(tmp_path / "run")
    with telemetry.Telemetry(d, window=2, retrace=False) as tel:
        lk = telemetry.WatchedLock("export")
        for step in (1, 2):
            with lk:
                pass
            tel.record({"loss": 1.0 / step}, step)
        recs = {r["name"]: r for r in tel.counters.records()}
        assert recs["lock/export/held_ms"]["count"] == 2
        assert recs["lock/export/wait_ms"]["count"] == 2
        assert recs["lock/export/held_ms"]["total"] >= 0.0
    # the lock/* counters render next to ckpt/* in summarize
    assert telemetry_cli(["summarize", d]) == 0
    out = capsys.readouterr().out
    assert "counters (cumulative):" in out
    assert "lock/export/held_ms" in out and "lock/export/wait_ms" in out


def test_watched_lock_rlock_reentrancy_one_pair_per_cycle():
    tel = telemetry.Telemetry(run_dir=None, metrics=("loss",),
                              retrace=False)
    rl = telemetry.WatchedLock("nested", lock=threading.RLock())
    with rl:
        with rl:                      # inner acquire: no wait, no emit
            assert rl.locked()
    pairs = {r["name"]: r["count"] for r in tel.counters.records()}
    assert pairs == {"lock/nested/wait_ms": 1,
                     "lock/nested/held_ms": 1}
    tel.close()


def test_watched_lock_off_path_and_mid_hold_sink_registration():
    """With no sink the wrapper emits nothing; a sink registered
    MID-hold must not be charged a bogus held time for a cycle whose
    acquire ran untimed (the sentinel guard)."""
    # the premise is "telemetry off": a sink leaked by an earlier test
    # anywhere in the suite would turn the first acquire into a timed
    # cycle and break it, so assert the suite-hygiene contract here
    from apex_tpu.telemetry import hostmetrics
    assert not hostmetrics.active(), \
        "hostmetrics sink leaked by an earlier test"
    lk = telemetry.WatchedLock("race")
    lk.acquire()                      # telemetry off: untimed cycle
    tel = telemetry.Telemetry(run_dir=None, metrics=("loss",),
                              retrace=False)
    lk.release()
    assert tel.counters.records() == []
    with lk:                          # fully-observed cycle: one pair
        pass
    pairs = {r["name"]: r["count"] for r in tel.counters.records()}
    assert pairs == {"lock/race/wait_ms": 1, "lock/race/held_ms": 1}
    tel.close()


def test_watched_lock_actually_excludes():
    """The proxy is a real lock: racing increments through it lose
    nothing (barrier start, exact final count)."""
    lk = telemetry.WatchedLock("mutex")
    n_threads, per_thread = 4, 5_000
    state = {"n": 0}
    barrier = threading.Barrier(n_threads + 1)

    def worker():
        barrier.wait()
        for _ in range(per_thread):
            with lk:
                state["n"] += 1

    threads = [threading.Thread(target=worker)
               for _ in range(n_threads)]
    for t in threads:
        t.start()
    barrier.wait()
    for t in threads:
        t.join()
    assert state["n"] == n_threads * per_thread
    assert not lk.locked()


# ---------------------------------------------------------------------------
# CLI summarize
# ---------------------------------------------------------------------------

def test_summarize_renders_step_spans_retraces(tmp_path, capsys):
    d = str(tmp_path / "run")
    with telemetry.Telemetry(d, window=4) as tel:
        with telemetry.span("eval"):
            pass
        for i in range(6):
            tel.record({"loss": 1.0 / (i + 1),
                        "amp/grad_norm": 0.1 * i,
                        "amp/loss_scale": 65536.0,
                        "amp/found_inf": 1.0 if i == 2 else 0.0}, i)
    assert telemetry_cli(["summarize", d]) == 0
    out = capsys.readouterr().out
    assert "grad_norm" in out and "loss_scale" in out
    assert "overflow steps: 1" in out
    assert "eval" in out                      # span table
    assert "compilation:" in out              # retrace table
    # --json is machine-parseable with the same content
    assert telemetry_cli(["summarize", d, "--json"]) == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["overflow_steps"] == 1
    assert len(payload["steps"]) == 6
    assert any(s["name"] == "eval" for s in payload["spans"])


def test_summarize_exit_codes(tmp_path, capsys):
    assert summarize(str(tmp_path / "nope")) == 1
    empty = tmp_path / "telemetry.jsonl"
    empty.write_text('{"kind": "schema", "version": 1, "metrics": []}\n')
    assert summarize(str(tmp_path)) == 1      # schema but zero steps
    capsys.readouterr()


# ---------------------------------------------------------------------------
# bench harness smoke (tier-1 keeps the tooling runnable)
# ---------------------------------------------------------------------------

def test_telemetry_overhead_bench_smoke():
    from apex_tpu.telemetry.bench import bench_telemetry_overhead
    r = bench_telemetry_overhead(layers=3, hidden=32, window=8,
                                 iters=2, reps=1)
    assert r["telemetry_off_ms"] > 0
    assert r["telemetry_on_ms"] > 0
    assert "telemetry_overhead_pct" in r
    assert r["telemetry_flush_ms"] >= 0


def test_lockwatch_overhead_bench_smoke():
    from apex_tpu.telemetry.bench import bench_lockwatch_overhead
    r = bench_lockwatch_overhead(window=8, n_metrics=4, iters=5,
                                 reps=2)
    assert r["lockwatch_off_ms"] > 0
    assert r["lockwatch_on_ms"] > 0
    assert "lockwatch_overhead_pct" in r
    assert r["lockwatch_acquire_ns"] >= 0


# ---------------------------------------------------------------------------
# pyprof satellites: thread-local nvtx, prof --json + newest-by-mtime
# ---------------------------------------------------------------------------

def test_nvtx_stack_is_thread_local():
    from apex_tpu.pyprof import nvtx
    errors = []

    def worker(tag):
        try:
            for _ in range(50):
                d1 = nvtx.range_push(f"{tag}/a")
                d2 = nvtx.range_push(f"{tag}/b")
                assert d2 == d1 + 1           # no cross-thread depth
                assert nvtx.range_pop() == d1
                assert nvtx.range_pop() == d1 - 1
        except BaseException as e:            # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert errors == []


def test_nvtx_exception_unwind_balances_stack():
    from apex_tpu.pyprof import nvtx
    nvtx.range_push("outer")
    try:
        nvtx.range_push("inner")
        raise RuntimeError("body raised")
    except RuntimeError:
        # best-effort unwind from the except branch never raises and
        # always balances, whatever state named_scope was left in
        assert nvtx.range_pop() == 1
        assert nvtx.range_pop() == 0
    assert nvtx.range_pop() == 0              # extra pop still harmless
    # the stack is usable again afterwards
    assert nvtx.range_push("again") == 1
    assert nvtx.range_pop() == 0


def _write_trace(outdir, name, ops, mtime=None):
    import gzip
    d = outdir / "plugins" / "profile" / name
    d.mkdir(parents=True, exist_ok=True)
    events = [
        {"ph": "M", "pid": 3, "name": "process_name",
         "args": {"name": "/device:TPU:0"}},
        {"ph": "M", "pid": 3, "tid": 7, "name": "thread_name",
         "args": {"name": "XLA Ops"}},
    ] + [{"ph": "X", "pid": 3, "tid": 7, "name": op, "dur": dur}
         for op, dur in ops]
    p = d / "vm.trace.json.gz"
    with gzip.open(p, "wt") as f:
        json.dump({"traceEvents": events}, f)
    if mtime is not None:
        os.utime(p, (mtime, mtime))


def test_prof_picks_newest_trace_by_mtime(tmp_path):
    from apex_tpu.pyprof import prof
    now = time.time()
    # lexicographically LATER dir holds the OLDER capture
    _write_trace(tmp_path, "z_old_run", [("stale.1", 1000)],
                 mtime=now - 1000)
    _write_trace(tmp_path, "a_new_run", [("fresh.2", 2000)], mtime=now)
    rows = prof.summarize_device_ops(str(tmp_path))
    assert [r[0] for r in rows] == ["fresh.2"]


def test_prof_json_output_and_empty_exit_code(tmp_path, capsys):
    from apex_tpu.pyprof import prof
    _write_trace(tmp_path, "run", [("fusion.9", 3000), ("conv", 1000)])
    assert prof.main([str(tmp_path), "--json"]) == 0
    rows = json.loads(capsys.readouterr().out)
    assert rows == [{"op": "fusion.9", "where": "device",
                     "total_ms": 3.0, "pct": 75.0},
                    {"op": "conv", "where": "device",
                     "total_ms": 1.0, "pct": 25.0}]
    # empty-trace path: exit 1, and --json stays parseable
    empty = tmp_path / "empty"
    empty.mkdir()
    assert prof.main([str(empty)]) == 1
    capsys.readouterr()
    assert prof.main([str(empty), "--json"]) == 1
    assert json.loads(capsys.readouterr().out) == []


# ---------------------------------------------------------------------------
# end-to-end: examples/simple with telemetry on -> summarize (slow tier)
# ---------------------------------------------------------------------------

def test_train_toy_telemetry_end_to_end(tmp_path, capsys):
    import runpy
    import sys
    d = str(tmp_path / "toyrun")
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = os.path.join(root, "examples", "simple", "train_toy.py")
    old = sys.argv
    sys.argv = [path, "--telemetry-dir", d]
    try:
        runpy.run_path(path, run_name="__main__")
    finally:
        sys.argv = old
    out = capsys.readouterr().out
    assert "OK: loss" in out
    assert telemetry_cli(["summarize", d]) == 0
    table = capsys.readouterr().out
    assert "grad_norm" in table and "loss_scale" in table
    assert "final_eval" in table


# ---------------------------------------------------------------------------
# ISSUE 7: flush observers, rewind, anomaly timeline (watchdog surface)
# ---------------------------------------------------------------------------

def test_flush_observer_sees_records_and_injects_events(tmp_path):
    d = str(tmp_path / "run")
    seen, tel = [], telemetry.Telemetry(d, metrics=("loss",), window=4,
                                        retrace=False)

    def obs(records):
        seen.extend(r["step"] for r in records)
        if records:
            return [{"kind": "anomaly", "anomaly": "test_kind",
                     "severity": "warn", "step": records[-1]["step"],
                     "first_step": records[0]["step"],
                     "detector": "t", "evidence": {}}]

    tel.add_observer(obs)
    for i in range(6):
        tel.record({"loss": float(i)}, i)
    tel.close()
    assert seen == [0, 1, 2, 3, 4, 5]         # every step reached it
    lines = [json.loads(l) for l in
             open(os.path.join(d, JSONL_NAME))]
    assert any(r.get("kind") == "anomaly" and
               r.get("anomaly") == "test_kind" for r in lines)


def test_flush_observer_runs_on_nonwriter_rank(tmp_path, monkeypatch):
    """Multi-host watchdogs must all reach the same verdict: with an
    observer attached, a rank0_only session still fetches and decodes
    its LOCAL ring on non-zero ranks — emitters stay silent."""
    d = str(tmp_path / "rank1")
    monkeypatch.setattr(jax, "process_index", lambda: 1)
    tel = telemetry.Telemetry(d, metrics=("loss",), window=2,
                              retrace=False)
    seen = []
    tel.add_observer(lambda records:
                     seen.extend(r["step"] for r in records))
    tel.record({"loss": 1.0}, 0)
    tel.record({"loss": 2.0}, 1)
    assert tel.flush() == []                  # contract: returns []
    tel.close()
    assert seen == [0, 1]                     # ...but the observer saw
    assert not os.path.exists(os.path.join(d, JSONL_NAME))


def test_remove_observer_and_no_observer_skips_fetch(monkeypatch):
    tel = telemetry.Telemetry(run_dir=None, metrics=("loss",),
                              window=4, retrace=False)
    calls = []
    obs = lambda records: calls.append(len(records))
    tel.add_observer(obs)
    tel.remove_observer(obs)
    tel.remove_observer(obs)                  # idempotent
    tel.record({"loss": 1.0}, 0)
    tel.flush()
    tel.close()
    assert calls == []


def test_rewind_replays_steps_and_summarize_keeps_newest(tmp_path,
                                                         capsys):
    """After a rollback, replayed step numbers must re-record and
    re-emit; the raw JSONL keeps both passes, the summarize surface
    renders the REPLAYED (newest) values."""
    d = str(tmp_path / "run")
    with telemetry.Telemetry(d, metrics=("loss",), window=4,
                             retrace=False) as tel:
        for i in range(1, 7):
            tel.record({"loss": 100.0 + i}, i)    # the "bad" pass
        tel.rewind(2)                             # rollback to step 2
        for i in range(3, 7):
            tel.record({"loss": float(i)}, i)     # the replay
    lines = [json.loads(l) for l in open(os.path.join(d, JSONL_NAME))]
    steps = [r for r in lines if r.get("kind", "step") == "step"
             and "step" in r]
    # both passes of step 4 are on the record
    assert sorted(r["loss"] for r in steps
                  if r["step"] == 4) == [4.0, 104.0]
    assert telemetry_cli(["summarize", d, "--json"]) == 0
    payload = json.loads(capsys.readouterr().out)
    by_step = {r["step"]: r for r in payload["steps"]}
    assert by_step[4]["loss"] == 4.0              # replay wins
    assert by_step[1]["loss"] == 101.0            # pre-rollback kept


def test_summarize_renders_anomaly_timeline(tmp_path, capsys):
    d = tmp_path
    recs = [
        {"kind": "schema", "version": 1, "metrics": ["loss"]},
        {"kind": "step", "step": 1, "loss": 1.0},
        {"kind": "step", "step": 2, "loss": 999.0},
        {"kind": "anomaly", "anomaly": "loss_spike",
         "severity": "warn", "step": 2, "first_step": 2,
         "detector": "loss_spike", "evidence": {"zscore": 12.5}},
        {"kind": "watchdog", "action": "rollback", "step": 3,
         "to_step": 1, "anomaly": "loss_spike", "rollbacks": 1},
        {"kind": "step", "step": 3, "loss": 1.1},
    ]
    (d / "telemetry.jsonl").write_text(
        "\n".join(json.dumps(r) for r in recs) + "\n")
    assert telemetry_cli(["summarize", str(d)]) == 0
    out = capsys.readouterr().out
    assert "anomaly timeline:" in out
    assert "loss_spike" in out and "zscore=12.5" in out
    assert "rollback" in out and "to_step=1" in out
    assert telemetry_cli(["summarize", str(d), "--json"]) == 0
    payload = json.loads(capsys.readouterr().out)
    kinds = [r["kind"] for r in payload["anomalies"]]
    assert kinds == ["anomaly", "watchdog"]
