"""apex_tpu.serving: the decode engine + its chaos matrix.

The serving robustness contract under test (docs/serving.md):

- every request the engine ever sees ends in exactly ONE typed
  verdict — nothing is dropped silently, under ANY fault kind;
- a hung decode evicts only its suspects; the surviving batch
  continues from its KV pages BIT-EXACTLY (same tokens as an
  uninterrupted run);
- drain returns every request (in-flight finish, queued come back
  ``drained``); a replica death re-admits its queue on survivors
  under ONE shared incident id;
- admission sheds under watermark hysteresis with typed reasons;
- the AOT programs stay free of host traffic with the KV arena
  donated (the serving.decode_step / serving.prefill_step specs).
"""

import collections

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import serving
from apex_tpu.resilience import fleet as fleet_mod
from apex_tpu.resilience.faults import FaultInjector, FaultSpec
from apex_tpu.resilience.preemption import PreemptionGuard
from apex_tpu.serving import admission as adm

CFG = serving.DecoderConfig(vocab_size=64, hidden=16, n_layers=2,
                            n_heads=2, n_kv_heads=2, ffn=32,
                            max_seq=32, eos_token=1)
PARAMS = serving.init_params(jax.random.key(0), CFG)

TERMINAL = {adm.COMPLETED, adm.SHED, adm.EVICTED, adm.DRAINED,
            adm.FAILED}


def make_engine(multi_replica=False, **kw):
    """One tiny engine (2 slots, 4-token pages, window 4); with
    ``multi_replica`` a faked 2-replica fleet on a LocalChannel."""
    kw.setdefault("page_size", 4)
    kw.setdefault("n_pages", 16)
    kw.setdefault("max_slots", 2)
    kw.setdefault("pages_per_slot", 4)
    kw.setdefault("window", 4)
    kw.setdefault("prefill_buckets", [4, 8])
    replica = None
    cleanup = []
    if multi_replica:
        channel = fleet_mod.LocalChannel()
        mon = fleet_mod.FleetMonitor(
            channel=channel, host=0, n_hosts=2,
            slow_after_steps=2, dead_after_steps=4,
            slow_after_s=None, dead_after_s=None,
            agreement_timeout_s=0.2)
        sim = fleet_mod.SimulatedPeers(channel, hosts=[1]).attach(mon)
        replica = serving.ReplicaSet(mon).attach_simulation(sim)
        replica._channel_for_test = channel
        cleanup.append(mon.close)
    eng = serving.Engine(PARAMS, CFG, replica=replica, **kw)
    eng._cleanup_for_test = cleanup
    return eng


def close_engine(eng):
    eng.close()
    for fn in getattr(eng, "_cleanup_for_test", []):
        fn()


def run_with_faults(eng, reqs, faults=(), stagger=False,
                    min_windows=0):
    inj = FaultInjector(list(faults)).install() if faults else None
    try:
        if stagger:
            eng.submit(serving.Request(**reqs[0]))
            eng.step_window()
            for r in reqs[1:]:
                eng.submit(serving.Request(**r))
        else:
            for r in reqs:
                eng.submit(serving.Request(**r))
        return eng.serve(min_windows=min_windows)
    finally:
        if inj is not None:
            inj.uninstall()


def assert_all_verdicted(results, submitted_ids):
    """The zero-dropped-without-a-verdict contract."""
    assert set(results) >= set(submitted_ids), \
        sorted(set(submitted_ids) - set(results))
    for r in results.values():
        assert r.verdict in TERMINAL, (r.id, r.verdict)


# ---------------------------------------------------------------------------
# arena + admission units
# ---------------------------------------------------------------------------

def test_arena_accounting_acquire_release():
    spec = serving.ArenaSpec(n_layers=2, n_kv_heads=2, head_dim=8,
                             page_size=4, n_pages=8, max_slots=2,
                             pages_per_slot=4)
    a = serving.KVArena(spec)
    assert a.free_pages == 8 and a.free_slots == 2
    assert a.pages_needed(9) == 3
    assert a.fits_ever(16) and not a.fits_ever(17)
    slot, pages = a.acquire(9)
    assert len(pages) == 3 and a.free_pages == 5
    row = np.asarray(a.slot_row(slot))
    assert list(row[:3]) == pages
    assert all(row[3:] == spec.trash_page)
    a.release(slot)
    assert a.free_pages == 8 and a.free_slots == 2
    assert np.all(np.asarray(a.slot_row(slot)) == spec.trash_page)


def test_arena_rejects_unplaceable_geometry():
    with pytest.raises(ValueError, match="never be placed"):
        serving.ArenaSpec(n_layers=1, n_kv_heads=1, head_dim=4,
                          page_size=4, n_pages=2, max_slots=1,
                          pages_per_slot=4).validate()


def test_admission_watermark_hysteresis():
    c = adm.AdmissionController(max_queue=10, queue_high=6,
                                queue_low=2)
    # below the high watermark: queue
    v = c.decide(8, fits_ever=True, fits_now=False, queue_depth=5)
    assert v.action == "queue"
    # at the high watermark the latch closes: typed backpressure
    v = c.decide(8, fits_ever=True, fits_now=False, queue_depth=6)
    assert v == ("shed", adm.REASON_BACKPRESSURE)
    # still above LOW: the latch stays closed (no per-request flap)
    v = c.decide(8, fits_ever=True, fits_now=False, queue_depth=4)
    assert v == ("shed", adm.REASON_BACKPRESSURE)
    # at/below low: re-opens
    v = c.decide(8, fits_ever=True, fits_now=False, queue_depth=2)
    assert v.action == "queue"
    assert c.shed_count == 2


def test_admission_typed_reasons():
    c = adm.AdmissionController(max_queue=2)
    assert c.decide(99, fits_ever=False, fits_now=False,
                    queue_depth=0) == ("shed", adm.REASON_OOM)
    assert c.decide(4, fits_ever=True, fits_now=False,
                    queue_depth=2) == ("shed", adm.REASON_QUEUE_FULL)
    assert c.decide(4, fits_ever=True, fits_now=False, queue_depth=0,
                    draining=True) == ("shed", adm.REASON_DRAINING)
    assert c.decide(4, fits_ever=True, fits_now=True,
                    queue_depth=0).action == "admit"


# ---------------------------------------------------------------------------
# engine basics
# ---------------------------------------------------------------------------

def test_engine_serves_and_is_batch_composition_independent():
    reqs = [dict(id="a", prompt=[5, 6, 7], max_new_tokens=6),
            dict(id="b", prompt=[9, 10], max_new_tokens=5)]
    eng = make_engine()
    both = run_with_faults(eng, reqs)
    close_engine(eng)
    assert both["a"].verdict == adm.COMPLETED
    assert both["b"].verdict == adm.COMPLETED
    assert len(both["a"].tokens) == 6 and len(both["b"].tokens) == 5
    eng = make_engine()
    solo = run_with_faults(eng, reqs[:1])
    close_engine(eng)
    # per-slot computations are independent of batch composition —
    # the invariant eviction/re-admission bit-exactness rests on
    assert solo["a"].tokens == both["a"].tokens


def test_engine_matches_full_recompute_oracle():
    """Greedy decode through the paged engine equals greedy decode by
    full prefill recompute at every step (same params, same math up
    to the cached-KV identity)."""
    prompt, n_new = [5, 6, 7], 5
    eng = make_engine()
    res = run_with_faults(eng, [dict(id="a", prompt=prompt,
                                     max_new_tokens=n_new)])
    close_engine(eng)
    # ONE fixed-shape jitted oracle (padded to a bucket): lengths
    # vary, shapes don't — no per-step retrace
    bucket = 16

    @jax.jit
    def oracle_next(toks, length):
        logits, _, _ = serving.prefill_forward(PARAMS, CFG, toks,
                                               length)
        return jnp.argmax(logits[0])

    seq = list(prompt)
    out = []
    for _ in range(n_new):
        toks = np.zeros((1, bucket), np.int32)
        toks[0, :len(seq)] = seq
        nxt = int(oracle_next(jnp.asarray(toks),
                              jnp.asarray([len(seq)], jnp.int32)))
        out.append(nxt)
        seq.append(nxt)
        if nxt == CFG.eos_token:
            break
    assert res["a"].tokens == out


def test_engine_continuous_batching_more_requests_than_slots():
    reqs = [dict(id=f"r{i}", prompt=[3 + i], max_new_tokens=4)
            for i in range(6)]
    eng = make_engine()     # 2 slots, 6 requests
    res = run_with_faults(eng, reqs)
    close_engine(eng)
    assert_all_verdicted(res, [r["id"] for r in reqs])
    assert all(r.verdict == adm.COMPLETED for r in res.values())


def test_engine_geometry_defaults_from_dispatch_prefs(monkeypatch):
    from apex_tpu.ops import _dispatch
    monkeypatch.setattr(_dispatch, "_SERVING",
                        {"page_size": 4, "decode_window": 4})
    # geometry deliberately matches the storm test's engine, so the
    # steered build hits the compiled-program cache
    eng = serving.Engine(PARAMS, CFG, n_pages=16, max_slots=1,
                         pages_per_slot=4, prefill_buckets=[4, 8])
    assert eng.arena.spec.page_size == 4
    assert eng.window == 4
    close_engine(eng)


def test_duplicate_request_id_rejected():
    eng = make_engine()
    eng.submit(serving.Request(id="x", prompt=[3], max_new_tokens=2))
    with pytest.raises(ValueError, match="duplicate"):
        eng.submit(serving.Request(id="x", prompt=[4],
                                   max_new_tokens=2))
    eng.serve()
    close_engine(eng)


# ---------------------------------------------------------------------------
# the chaos matrix: every serving fault kind x {single, multi-replica}
# ends in its documented typed verdict, zero dropped without a verdict
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("multi", [False, True],
                         ids=["single-replica", "multi-replica"])
def test_chaos_hung_decode_evicts_suspect_survivors_bit_exact(multi):
    reqs = [dict(id="healthy", prompt=[5, 6, 7], max_new_tokens=10),
            dict(id="suspect", prompt=[9, 10], max_new_tokens=10)]
    eng = make_engine(multi_replica=multi)
    base = run_with_faults(eng, reqs, stagger=True)
    close_engine(eng)
    # windows: 1 = healthy admitted; 2 = suspect admitted AND the
    # decode dispatch wedges (0.5s stall vs 0.15s deadline)
    eng = make_engine(multi_replica=multi, decode_deadline_s=0.15)
    res = run_with_faults(
        eng, reqs, stagger=True,
        faults=[FaultSpec("hung_decode", at_step=2, delay_s=0.5)])
    assert_all_verdicted(res, ["healthy", "suspect"])
    # only the offender evicted, typed
    assert res["suspect"].verdict == adm.EVICTED
    assert res["suspect"].reason == adm.REASON_HUNG_DECODE
    assert res["suspect"].incident_id is not None
    # the healthy request was NOT evicted and continued from its KV
    # pages bit-exactly — same tokens as the uninterrupted run
    assert res["healthy"].verdict == adm.COMPLETED
    assert res["healthy"].tokens == base["healthy"].tokens
    # the incident opened and closed (recovery, not a wedged flag)
    assert eng.incidents.history and eng.incidents.current is None
    assert "hung_decode" in eng.incidents.history[0]
    close_engine(eng)


@pytest.mark.parametrize("multi", [False, True],
                         ids=["single-replica", "multi-replica"])
def test_chaos_slow_request_evicts_only_target(multi):
    reqs = [dict(id="slow", prompt=[4, 5], max_new_tokens=12),
            dict(id="ok", prompt=[6], max_new_tokens=12)]
    eng = make_engine(multi_replica=multi)
    res = run_with_faults(
        eng, reqs,
        faults=[FaultSpec("slow_request", at_step=2, target=0)])
    close_engine(eng)
    assert_all_verdicted(res, ["slow", "ok"])
    assert res["slow"].verdict == adm.EVICTED
    assert res["slow"].reason == adm.REASON_DEADLINE
    assert res["ok"].verdict == adm.COMPLETED


@pytest.mark.parametrize("multi", [False, True],
                         ids=["single-replica", "multi-replica"])
def test_chaos_queue_storm_sheds_typed_under_hysteresis(multi):
    eng = make_engine(multi_replica=multi, max_slots=1, max_queue=4,
                      queue_high=3, queue_low=1)
    res = run_with_faults(
        eng, [dict(id="x", prompt=[4], max_new_tokens=4)],
        faults=[FaultSpec("queue_storm", at_step=1, n_steps=1)])
    close_engine(eng)
    assert_all_verdicted(res, list(res))
    verdicts = collections.Counter(
        (r.verdict, r.reason) for r in res.values())
    # the storm's 8 synthetic requests all got verdicts: some shed
    # with the typed backpressure reason, the rest queued+completed
    assert len(res) == 9
    shed = verdicts[(adm.SHED, adm.REASON_BACKPRESSURE)] \
        + verdicts[(adm.SHED, adm.REASON_QUEUE_FULL)]
    assert shed >= 4
    assert shed + sum(1 for r in res.values()
                      if r.verdict == adm.COMPLETED) == 9


@pytest.mark.parametrize("multi", [False, True],
                         ids=["single-replica", "multi-replica"])
def test_chaos_oom_admission_typed_shed(multi):
    eng = make_engine(multi_replica=multi)
    res = run_with_faults(
        eng, [dict(id="x", prompt=[4], max_new_tokens=4)],
        faults=[FaultSpec("oom_admission", at_step=1)])
    close_engine(eng)
    assert_all_verdicted(res, list(res))
    ooms = [r for r in res.values() if r.reason == adm.REASON_OOM]
    assert len(ooms) == 1 and ooms[0].verdict == adm.SHED
    assert res["x"].verdict == adm.COMPLETED


@pytest.mark.parametrize("multi", [False, True],
                         ids=["single-replica", "multi-replica"])
def test_chaos_drain_on_sigterm_returns_every_request(multi):
    """Preemption notice -> stop admitting, finish in-flight, queued
    come back ``drained`` — nothing vanishes."""
    eng = make_engine(multi_replica=multi, max_slots=1,
                      guard=PreemptionGuard(preempt_at_step=2))
    reqs = [dict(id=f"r{i}", prompt=[3 + i], max_new_tokens=6)
            for i in range(4)]
    res = run_with_faults(eng, reqs)
    events = list(eng._event_records) + eng._on_flush([])
    close_engine(eng)
    assert_all_verdicted(res, [r["id"] for r in reqs])
    by_verdict = collections.Counter(r.verdict for r in res.values())
    assert by_verdict[adm.COMPLETED] >= 1      # in-flight finished
    assert by_verdict[adm.DRAINED] >= 1        # queued returned
    assert by_verdict[adm.COMPLETED] + by_verdict[adm.DRAINED] == 4
    # the flush now also carries reqtrace/hist records — filter on the
    # event key
    names = [e.get("event") for e in events]
    assert "drain_begin" in names and "drain_complete" in names


def test_chaos_replica_death_readmits_under_one_incident_id():
    eng = make_engine(multi_replica=True)
    # the peer replica's published queue ledger, then its death
    eng.replica._channel_for_test.put(
        "serving_queue/1",
        {"host": 1, "requests": [
            {"id": "peer-a", "prompt": [7, 8], "max_new_tokens": 4},
            {"id": "peer-b", "prompt": [9], "max_new_tokens": 3}]})
    res = run_with_faults(
        eng, [dict(id="mine", prompt=[5], max_new_tokens=8)],
        faults=[FaultSpec("replica_death", at_step=2, target=1)],
        min_windows=12)
    mon = eng.replica.monitor
    close_engine(eng)
    assert_all_verdicted(res, ["mine", "peer-a", "peer-b"])
    assert res["mine"].verdict == adm.COMPLETED
    # the dead replica's queue re-admitted and completed, every
    # verdict stamped with the SAME incident id — minted from
    # replicated facts (host 1, incarnation 1, epoch 0)
    iids = {res[r].incident_id for r in ("peer-a", "peer-b")}
    assert len(iids) == 1
    (iid,) = iids
    assert iid == "inc-001-host_dead-h1.1-e0"
    assert res["peer-a"].readmitted_from == 1
    assert res["peer-b"].verdict == adm.COMPLETED
    # the chain closed once every re-admitted request resolved
    assert mon.incidents.current is None
    assert mon.incidents.history == [iid]


def test_chaos_hung_decode_after_dispatch_rebuilds_arena():
    """The POST-dispatch hang (review finding): the donated arena was
    consumed by the abandoned call, so recovery must rebuild a fresh
    arena and re-place survivors from prompt + emitted tokens —
    request-level recovery, never reuse of poisoned buffers."""
    from apex_tpu.serving.engine import DecodeDeadlineExceeded
    reqs = [dict(id="healthy", prompt=[5, 6, 7], max_new_tokens=10),
            dict(id="suspect", prompt=[9, 10], max_new_tokens=10)]
    eng = make_engine()
    base = run_with_faults(eng, reqs)
    close_engine(eng)
    eng = make_engine()
    eng.submit(serving.Request(**reqs[0]))
    eng.submit(serving.Request(**reqs[1]))
    eng.step_window()       # both admitted, one window decoded
    old_arena = eng.arena
    suspect_slot = next(s for s, a in eng._active.items()
                        if a.req.id == "suspect")
    eng._admitted_this_window = [suspect_slot]
    eng._handle_hung_decode(DecodeDeadlineExceeded(
        "post-dispatch hang", window=2, phase="decode",
        deadline_s=0.1, dispatched=True))
    assert eng.arena is not old_arena       # rebuilt, not reused
    res = eng.serve()
    events = eng._on_flush([])
    close_engine(eng)
    assert_all_verdicted(res, ["healthy", "suspect"])
    assert res["suspect"].verdict == adm.EVICTED
    assert res["suspect"].reason == adm.REASON_HUNG_DECODE
    # the survivor completed with the SAME tokens as an uninterrupted
    # run (the replayed prefix recomputes to the same greedy path)
    assert res["healthy"].verdict == adm.COMPLETED
    assert res["healthy"].tokens == base["healthy"].tokens
    assert any(e.get("event") == "arena_rebuilt" for e in events)
    assert eng.incidents.current is None    # recovered, then closed


def test_chaos_hung_decode_on_last_request_still_closes_incident():
    """Review finding: when the hang evicts the ONLY in-flight request
    there is no later successful window to resolve the incident — it
    must close at recovery time, so a later unrelated incident cannot
    silently join the stale id."""
    eng = make_engine(decode_deadline_s=0.15)
    res = run_with_faults(
        eng, [dict(id="only", prompt=[5, 6], max_new_tokens=8)],
        faults=[FaultSpec("hung_decode", at_step=1, delay_s=0.5)])
    assert res["only"].verdict == adm.EVICTED
    assert eng.incidents.history and eng.incidents.current is None
    close_engine(eng)


def test_submit_prompt_beyond_prefill_buckets_sheds_oom():
    """Review finding: a prompt no compiled bucket covers must shed
    with the typed oom reason at submit — not crash the serve loop at
    admission time."""
    eng = make_engine(prefill_buckets=[4])    # slot capacity is 16
    verdict = eng.submit(serving.Request(
        id="long", prompt=[2] * 6, max_new_tokens=4))
    assert verdict == "shed"
    assert eng.results["long"].verdict == adm.SHED
    assert eng.results["long"].reason == adm.REASON_OOM
    # a covered prompt still serves normally
    eng.submit(serving.Request(id="ok", prompt=[3, 4],
                               max_new_tokens=4))
    res = eng.serve()
    assert res["ok"].verdict == adm.COMPLETED
    close_engine(eng)


def test_chaos_replica_death_nonclaimant_survivor_stays_quiet():
    """Review finding: in a 3+ replica fleet only the lowest-rank
    survivor owns the failover chain — a non-claimant survivor must
    not emit replica_failover or stamp incident_resolved (which would
    close the merged timeline's incident while the claimant is still
    re-admitting); it closes its LOCAL log quietly."""
    channel = fleet_mod.LocalChannel()
    mon = fleet_mod.FleetMonitor(
        channel=channel, host=1, n_hosts=3,
        slow_after_steps=2, dead_after_steps=4,
        slow_after_s=None, dead_after_s=None,
        agreement_timeout_s=0.2)
    sim = fleet_mod.SimulatedPeers(channel, hosts=[0, 2]).attach(mon)
    replica = serving.ReplicaSet(mon).attach_simulation(sim)
    eng = serving.Engine(PARAMS, CFG, page_size=4, n_pages=16,
                         max_slots=2, pages_per_slot=4, window=4,
                         prefill_buckets=[4, 8], replica=replica)
    channel.put("serving_queue/2", {"host": 2, "requests": [
        {"id": "peer-x", "prompt": [7], "max_new_tokens": 3}]})
    res = run_with_faults(
        eng, [dict(id="mine", prompt=[5], max_new_tokens=6)],
        faults=[FaultSpec("replica_death", at_step=2, target=2)],
        min_windows=12)
    events = list(eng._event_records) + eng._on_flush([])
    eng.close()
    mon.close()
    assert res["mine"].verdict == adm.COMPLETED
    # host 0 (alive, lowest-rank) owns the claim — host 1 re-admits
    # nothing and stays silent about the chain it plays no part in
    assert not eng.replica.is_claimant()
    assert "peer-x" not in res
    names = [e.get("event") for e in events]
    assert "replica_failover" not in names
    assert "incident_resolved" not in names
    # the local log closed quietly: later local events do not ride
    # the dead peer's incident id
    assert mon.incidents.current is None


def test_hung_decode_during_failover_chain_keeps_incident_open():
    """Review finding: a hang during an unresolved failover chain
    rides the SAME incident id (open is idempotent) but must not
    steal its closure semantics — the incident stays open until every
    re-admitted request has a verdict, then closes exactly once."""
    from apex_tpu.serving.engine import DecodeDeadlineExceeded
    eng = make_engine(multi_replica=True)
    eng.replica._channel_for_test.put(
        "serving_queue/1",
        {"host": 1, "requests": [
            {"id": "peer-a", "prompt": [7, 8], "max_new_tokens": 6},
            {"id": "peer-b", "prompt": [9], "max_new_tokens": 6}]})
    eng.replica.kill_peer(1)
    eng.submit(serving.Request(id="mine", prompt=[5],
                               max_new_tokens=6))
    for _ in range(30):                    # beat until the claim lands
        eng.step_window()
        if eng._readmitted_pending:
            break
    assert eng._readmitted_pending and eng.incidents.current
    iid = eng.incidents.current
    # mid-chain hang: the failover incident must survive it
    eng._handle_hung_decode(DecodeDeadlineExceeded(
        "mid-chain wedge", window=99, deadline_s=0.1,
        dispatched=False))
    assert eng.incidents.current == iid
    assert eng._incident_cause == "replica_death"
    res = eng.serve(min_windows=4)
    mon = eng.replica.monitor
    close_engine(eng)
    assert_all_verdicted(res, ["mine", "peer-a", "peer-b"])
    # ONE incident, closed only after the chain fully resolved, and
    # every re-admitted verdict stamped with it
    assert mon.incidents.current is None
    assert mon.incidents.history == [iid]
    for rid in ("peer-a", "peer-b"):
        assert res[rid].incident_id == iid


def test_drain_closes_open_hung_incident():
    """Review finding: a drain that empties the engine while a
    hung-decode incident is still open (its queued survivors got
    drained, so no successful window ever proved recovery) must close
    the incident — serve() may not end with it eternally open."""
    eng = make_engine(max_slots=1, decode_deadline_s=0.15,
                      guard=PreemptionGuard(preempt_at_step=3))
    reqs = [dict(id=f"r{i}", prompt=[3 + i], max_new_tokens=6)
            for i in range(4)]
    res = run_with_faults(
        eng, reqs,
        faults=[FaultSpec("hung_decode", at_step=2, delay_s=0.5)])
    assert_all_verdicted(res, [r["id"] for r in reqs])
    assert eng.incidents.history          # the hang minted one
    assert eng.incidents.current is None  # ...and drain closed it
    close_engine(eng)


def test_prefill_failure_types_verdict_and_frees_slot():
    """Review finding: a NON-deadline prefill failure (device OOM,
    runtime error) must not drop the already-popped request without a
    verdict nor leak its acquired slot/pages — the decode path's
    generic handler, mirrored."""
    import copy
    eng = make_engine()
    free_pages, free_slots = eng.arena.free_pages, eng.arena.free_slots
    # clone the program set before sabotaging it: cached_programs
    # memoizes, and the shared copy must stay healthy
    eng.programs = copy.copy(eng.programs)

    def boom(*a, **k):
        raise RuntimeError("synthetic prefill device failure")

    eng.programs.prefill = {bk: boom for bk in eng.programs.prefill}
    eng.submit(serving.Request(id="doomed", prompt=[3, 4],
                               max_new_tokens=4))
    with pytest.raises(RuntimeError, match="synthetic prefill"):
        eng.serve()
    assert eng.results["doomed"].verdict == adm.FAILED
    assert eng.results["doomed"].reason == "prefill_error"
    assert eng.arena.free_pages == free_pages
    assert eng.arena.free_slots == free_slots
    close_engine(eng)


def test_results_ledger_bounded_by_results_cap():
    """Review finding: a long-lived server must not retain every
    request's full token list forever — oldest terminal verdicts fall
    off past results_cap (and their ids become reusable)."""
    eng = make_engine(results_cap=4)
    for i in range(10):
        eng.submit(serving.Request(id=f"r{i}", prompt=[3],
                                   max_new_tokens=2))
    res = eng.serve()
    assert len(eng.results) <= 4
    assert len(res) <= 4
    # the newest verdict survives; the oldest were pruned
    assert "r9" in eng.results and "r0" not in eng.results
    # a pruned id is reusable without tripping the duplicate check
    eng.submit(serving.Request(id="r0", prompt=[3], max_new_tokens=2))
    assert eng.serve()["r0"].verdict == adm.COMPLETED
    close_engine(eng)


def test_chaos_every_serving_fault_kind_is_registered():
    assert set(FaultInjector.SERVING_KINDS) <= set(FaultInjector.KINDS)
    assert set(FaultInjector.SERVING_KINDS) <= \
        set(FaultInjector.STEP_KINDS)
    assert set(FaultInjector.SERVING_KINDS) == {
        "hung_decode", "slow_request", "replica_death",
        "queue_storm", "oom_admission"}
    # each kind documented in the fault-table docstring
    import apex_tpu.resilience.faults as faults_mod
    for kind in FaultInjector.SERVING_KINDS:
        assert kind in faults_mod.__doc__


# ---------------------------------------------------------------------------
# autoscaler wiring (ROADMAP item 5 follow-up): the engine's queue
# depth drives the PR-12 FleetController through signal_source
# ---------------------------------------------------------------------------

def test_fleet_controller_grows_on_queue_storm():
    eng = make_engine(max_slots=1, max_queue=32)
    ctrl = fleet_mod.FleetController(
        signal_source=eng.queue_depth, queue_high=4.0, queue_low=1.0,
        patience=2, cooldown_steps=0)
    # quiet queue: stay
    assert ctrl.decide(1, n_hosts=1, candidates=1).action == "stay"
    # storm the queue past the watermark
    for i in range(8):
        eng.submit(serving.Request(id=f"s{i}", prompt=[3],
                                   max_new_tokens=2))
    assert ctrl.decide(2, n_hosts=1, candidates=1).action == "stay"
    d = ctrl.decide(3, n_hosts=1, candidates=1)   # patience met
    assert d.action == "grow" and d.reason == "queue_depth"
    # drain the queue; the shrink side eventually fires too
    eng.serve()
    for step in range(4, 10):
        d = ctrl.decide(step, n_hosts=2, candidates=0)
    assert d.action == "shrink"
    ctrl.close()
    close_engine(eng)


# ---------------------------------------------------------------------------
# observability: /metrics gauges + event records
# ---------------------------------------------------------------------------

def test_serving_counters_reach_metrics_server():
    from apex_tpu.telemetry.export import MetricsServer
    srv = MetricsServer(port=0)
    try:
        eng = make_engine()
        run_with_faults(eng, [dict(id="a", prompt=[5, 6],
                                   max_new_tokens=4)])
        close_engine(eng)
        body = srv.render()
    finally:
        srv.close()
    for gauge in ("apex_tpu_serving_queue_depth",
                  "apex_tpu_serving_completed_total",
                  "apex_tpu_serving_tokens_total",
                  "apex_tpu_serving_p50_token_ms",
                  "apex_tpu_serving_p99_token_ms"):
        assert gauge in body, gauge


def test_serving_events_ride_session_flush_and_timeline(tmp_path):
    from apex_tpu import telemetry
    from apex_tpu.telemetry import timeline as tl
    run_dir = str(tmp_path / "run")
    tel = telemetry.Telemetry(run_dir, window=4, retrace=False)
    eng = make_engine(telemetry=tel, decode_deadline_s=0.15)
    run_with_faults(
        eng,
        [dict(id="healthy", prompt=[5, 6, 7], max_new_tokens=8),
         dict(id="suspect", prompt=[9, 10], max_new_tokens=8)],
        stagger=True,
        faults=[FaultSpec("hung_decode", at_step=2, delay_s=0.5)])
    close_engine(eng)
    tel.close()
    doc = tl.build([run_dir])
    assert doc is not None and len(doc["incidents"]) == 1
    inc = doc["incidents"][0]
    assert "hung_decode" in inc["incident_id"]
    assert inc["closed"]
    labels = [e["kind"] + ":" + e.get("event", "?")
              for e in inc["events"]]
    assert "serving:hung_decode" in labels
    assert "serving:request_evicted" in labels
    assert "serving:incident_resolved" in labels


def test_metrics_server_counts_serving_events():
    from apex_tpu.telemetry.export import MetricsServer
    srv = MetricsServer(port=0)
    try:
        srv.emit([{"kind": "serving", "event": "hung_decode",
                   "incident_id": "inc-001-hung_decode-e0"},
                  {"kind": "serving", "event": "incident_resolved",
                   "incident_id": "inc-001-hung_decode-e0"}])
        body = srv.render()
    finally:
        srv.close()
    assert "apex_tpu_serving_hung_decode_events_total 1" in body
    assert ('apex_tpu_incident_open{incident_id='
            '"inc-001-hung_decode-e0"} 0') in body


# ---------------------------------------------------------------------------
# apexverify specs + bench smoke
# ---------------------------------------------------------------------------

def test_serving_specs_registered_and_green():
    from apex_tpu.lint.semantic import registry
    for name in ("serving.decode_step", "serving.prefill_step",
                 "serving.decode_step_quantized",
                 "serving.sample_step",
                 "serving.spec_decode_step",
                 "serving.decode_step_w8",
                 "serving.spec_decode_step_quantized",
                 "serving.prefill_batched",
                 "serving.traced_decode_step"):
        result = registry.verify_spec(registry.get_spec(name))
        assert result.ok, (name, result.failures)
        assert result.checked


def test_spec_count_is_31():
    from apex_tpu.lint import semantic
    assert len(semantic.all_specs()) == 31


def test_bench_smoke():
    from apex_tpu.serving.bench import bench_decode_step, bench_serving
    r = bench_decode_step(n_layers=1, hidden=16, n_heads=2,
                          max_slots=2, page_size=4, pages_per_slot=2,
                          window=2, iters=2, reps=2)
    assert r["decode_step_paged_ms"] > 0
    assert r["decode_step_tokens_per_sec"] > 0
    s = bench_serving(n_requests=2, n_layers=1, hidden=16, n_heads=2,
                      max_slots=2, page_size=4, pages_per_slot=2,
                      window=2, max_new_tokens=3)
    assert s["decode_tokens_per_sec"] > 0
    assert s["serving_completed"] == 2
    assert s["serving_p99_ms"] >= s["serving_p50_ms"] >= 0


def test_bench_kv_quant_gather_smoke():
    """The kernel_bench ``kv_quant_gather`` row's harness, tiny: the
    bytes ratio is structural — (head_dim+4)/(2*head_dim) — and must
    sit under the ``extra.kv_bytes_per_token`` ceiling (0.55) at the
    production head_dim the bench defaults pin."""
    from apex_tpu.serving.bench import bench_kv_quant_gather
    r = bench_kv_quant_gather(n_layers=1, hidden=256, n_heads=4,
                              max_slots=2, page_size=4,
                              pages_per_slot=2, iters=2, reps=2)
    assert r["kv_quant_gather_int8_ms"] > 0
    assert r["kv_quant_gather_bf16_ms"] > 0
    assert r["kv_gather_head_dim"] == 64
    assert r["kv_bytes_per_token_ratio"] <= 0.55


def test_bench_prefix_admission_smoke():
    """The kernel_bench ``prefix_admission`` row's harness, tiny: the
    savings factor is counted from the engine's prefill/extend program
    counters — at 4-way sharing it must clear the budget floor (2.0)
    with every request completed."""
    from apex_tpu.serving.bench import bench_prefix_admission
    r = bench_prefix_admission(n_requests=4, n_layers=1, hidden=16,
                               n_heads=2, page_size=4,
                               pages_per_slot=8, prompt_len=6,
                               window=4, max_new_tokens=3)
    assert r["prefix_completed"] == 4
    assert r["prefix_n_prefills"] == 1
    assert r["prefix_n_extends"] == 3
    assert r["prefix_prefill_savings"] >= 2.0


# ---------------------------------------------------------------------------
# int8 quantized KV arena (ISSUE 17 tentpole axis a)
# ---------------------------------------------------------------------------

def test_arena_int8_halves_kv_bytes():
    spec = serving.ArenaSpec(n_layers=2, n_kv_heads=2, head_dim=64,
                             page_size=4, n_pages=8, max_slots=2,
                             pages_per_slot=4)
    f32 = serving.KVArena(spec)
    i8 = serving.KVArena(spec, dtype="int8")
    assert not f32.quantized and i8.quantized
    # int8 pages carry values + one f32 scale per vector:
    # (head_dim + 4) / (4 * head_dim) vs f32, well under half
    assert i8.bytes_per_token() / f32.bytes_per_token() \
        == pytest.approx((64 + 4) / (4 * 64))
    # the budget-row ratio is taken against bf16 (2 bytes/value)
    assert (64 + 4) / (2 * 64) <= 0.55
    # float arenas keep stub scale planes so ONE program signature
    # serves every mode
    assert f32.k_scale.shape == (1, 1, 1, 1)
    assert i8.k_scale.shape == i8.k.shape[:-1]


def test_int8_engine_matches_f32_dequant_oracle():
    """The quantization acceptance bar: the int8 engine's greedy
    stream equals a hand-rolled oracle that keeps the cache in f32 but
    round-trips EVERY written vector through quantize/dequantize at a
    fixed quant state — storage changes, math does not."""
    from apex_tpu.quantization import dequantize_kv, quantize_kv_int8
    from apex_tpu.serving.model import decode_forward, prefill_forward

    prompt, n_new = [5, 6, 7], 6
    eng = make_engine(kv_dtype="int8")
    res = run_with_faults(eng, [dict(id="a", prompt=prompt,
                                     max_new_tokens=n_new)])
    close_engine(eng)
    assert res["a"].verdict == adm.COMPLETED

    plen, ctx, bucket = len(prompt), 16, 4
    toks = np.zeros((1, bucket), np.int32)
    toks[0, :plen] = prompt
    logits, k, v = jax.jit(
        lambda t, n: prefill_forward(PARAMS, CFG, t, n))(
            jnp.asarray(toks), jnp.asarray([plen], jnp.int32))

    def roundtrip(x):
        q, s = quantize_kv_int8(x)
        return dequantize_kv(q, s)

    shape = (CFG.n_layers, 1, ctx, CFG.n_kv_heads, CFG.head_dim)
    kc = jnp.zeros(shape).at[:, :, :bucket].set(roundtrip(k))
    vc = jnp.zeros(shape).at[:, :, :bucket].set(roundtrip(v))
    seq = list(prompt) + [int(jnp.argmax(logits[0]))]
    out = [seq[-1]]
    step = jax.jit(lambda t, p, kk, vv, vis: decode_forward(
        PARAMS, CFG, t, p, kk, vv, vis))
    while len(out) < n_new and out[-1] != CFG.eos_token:
        pos = len(seq) - 1
        vis = (jnp.arange(ctx) <= pos)[None, :]
        logits, k_new, v_new = step(
            jnp.asarray([seq[-1]], jnp.int32),
            jnp.asarray([pos], jnp.int32), kc, vc, vis)
        kc = kc.at[:, 0, pos].set(roundtrip(k_new)[:, 0])
        vc = vc.at[:, 0, pos].set(roundtrip(v_new)[:, 0])
        nxt = int(jnp.argmax(logits[0]))
        out.append(nxt)
        seq.append(nxt)
    assert res["a"].tokens == out


def test_int8_engine_batch_composition_independent():
    reqs = [dict(id="a", prompt=[5, 6, 7], max_new_tokens=6),
            dict(id="b", prompt=[9, 10], max_new_tokens=5)]
    eng = make_engine(kv_dtype="int8")
    both = run_with_faults(eng, reqs)
    close_engine(eng)
    eng = make_engine(kv_dtype="int8")
    solo = run_with_faults(eng, reqs[:1])
    close_engine(eng)
    assert solo["a"].tokens == both["a"].tokens
    assert both["a"].verdict == both["b"].verdict == adm.COMPLETED


def test_engine_kv_dtype_defaults_from_dispatch_prefs(monkeypatch):
    from apex_tpu.ops import _dispatch
    # one knob per engine build, so each reuses a program set another
    # test compiles anyway (int8 greedy / f32 shared) instead of
    # paying for the unique int8+share combination
    monkeypatch.setattr(_dispatch, "_SERVING", {"kv_dtype": "int8"})
    eng = make_engine()
    assert eng.arena.quantized and eng._trie is None
    close_engine(eng)
    monkeypatch.setattr(_dispatch, "_SERVING", {"prefix_share": True})
    eng = make_engine()
    assert not eng.arena.quantized
    assert eng.prefix_share and eng._trie is not None
    close_engine(eng)
    # an explicit constructor argument beats the table
    monkeypatch.setattr(_dispatch, "_SERVING",
                        {"kv_dtype": "int8", "prefix_share": True})
    eng = make_engine(kv_dtype="f32", prefix_share=False)
    assert not eng.arena.quantized and eng._trie is None
    close_engine(eng)


@pytest.mark.parametrize("kv_dtype", ["f32", "int8"])
def test_chaos_hung_decode_int8_survivors_bit_exact(kv_dtype):
    """The chaos matrix re-run under int8: a PRE-dispatch hang evicts
    only its suspects and the survivors' pages are untouched — at a
    fixed quant state the surviving stream stays bit-exact in BOTH
    storage dtypes."""
    reqs = [dict(id="healthy", prompt=[5, 6, 7], max_new_tokens=10),
            dict(id="suspect", prompt=[9, 10], max_new_tokens=10)]
    eng = make_engine(kv_dtype=kv_dtype)
    base = run_with_faults(eng, reqs, stagger=True)
    close_engine(eng)
    eng = make_engine(kv_dtype=kv_dtype, decode_deadline_s=0.15)
    res = run_with_faults(
        eng, reqs, stagger=True,
        faults=[FaultSpec("hung_decode", at_step=2, delay_s=0.5)])
    assert_all_verdicted(res, ["healthy", "suspect"])
    assert res["suspect"].verdict == adm.EVICTED
    assert res["healthy"].verdict == adm.COMPLETED
    assert res["healthy"].tokens == base["healthy"].tokens
    assert eng.incidents.history and eng.incidents.current is None
    close_engine(eng)


# ---------------------------------------------------------------------------
# refcounted prefix sharing + COW (tentpole axis b)
# ---------------------------------------------------------------------------

def test_arena_shared_release_decrefs_never_frees():
    spec = serving.ArenaSpec(n_layers=1, n_kv_heads=1, head_dim=4,
                             page_size=4, n_pages=8, max_slots=3,
                             pages_per_slot=4)
    a = serving.KVArena(spec)
    owner, pages = a.acquire(12)            # 3 pages
    sharer, own = a.acquire_shared(12, pages[:2])
    assert len(own) == 1
    assert [a.page_ref(p) for p in pages[:2]] == [2, 2]
    a.check_accounting()
    # releasing the OWNER decrefs the aliased pages but frees only
    # its exclusive tail — the sharer's view stays live
    freed = a.release(owner)
    assert set(freed) == {pages[2]}
    assert [a.page_ref(p) for p in pages[:2]] == [1, 1]
    a.check_accounting()
    # the last reference going away frees them
    freed = a.release(sharer)
    assert set(freed) == set(pages[:2]) | set(own)
    assert a.free_pages == spec.n_pages
    a.check_accounting()


def test_arena_cow_detaches_shared_page():
    spec = serving.ArenaSpec(n_layers=1, n_kv_heads=1, head_dim=4,
                             page_size=4, n_pages=8, max_slots=2,
                             pages_per_slot=4)
    a = serving.KVArena(spec)
    owner, pages = a.acquire(8)
    sharer, own = a.acquire_shared(8, pages)
    assert own == []
    old, new = a.cow(sharer, 1)
    assert old == pages[1] and new not in pages
    assert a.page_ref(old) == 1 and a.page_ref(new) == 1
    assert list(np.asarray(a.slot_row(sharer))[:2]) == [pages[0], new]
    a.check_accounting()
    # COW of an exclusively-owned page is a caller bug
    with pytest.raises(RuntimeError, match="exclusively-owned"):
        a.cow(owner, 1)


def test_prefix_trie_register_match_prune():
    t = serving.PrefixTrie(page_size=4)
    t.register([5, 6, 7, 9, 10, 11], [0, 1])
    # exact full-prompt hit: full pages + the COW-able tail
    assert t.match([5, 6, 7, 9, 10, 11]) == ([0], 1)
    # longer prompt sharing the covered prefix: full pages only
    assert t.match([5, 6, 7, 9, 10, 11, 12, 13, 14]) == ([0], None)
    # diverging inside the first page: no hit
    assert t.match([5, 6, 8, 9]) == ([], None)
    t.prune([1])
    assert t.match([5, 6, 7, 9, 10, 11]) == ([0], None)
    t.clear()
    assert t.match([5, 6, 7, 9, 10, 11]) == ([], None)
    assert len(t) == 0


def test_prefix_single_prefill_and_stream_exactness():
    """The acceptance bar made literal: N requests sharing one prompt
    prefill it exactly ONCE (prefill-call counting), alias its pages,
    and every stream equals the unshared engine's stream."""
    prompt, n_new = [5, 6, 7], 6
    eng = make_engine()
    base = run_with_faults(eng, [dict(id="a", prompt=prompt,
                                      max_new_tokens=n_new)])
    close_engine(eng)
    eng = make_engine(max_slots=3, n_pages=24, prefix_share=True)
    reqs = [dict(id=f"s{i}", prompt=prompt, max_new_tokens=n_new)
            for i in range(3)]
    res = run_with_faults(eng, reqs)
    assert eng._n_prefills == 1
    assert eng._n_extends == 2
    assert eng._prefix_hits == 2
    assert eng._cow_copies == 2
    eng.arena.check_accounting()
    close_engine(eng)
    for i in range(3):
        assert res[f"s{i}"].verdict == adm.COMPLETED
        assert res[f"s{i}"].tokens == base["a"].tokens


def test_prefix_cow_on_divergence_isolates_writers():
    """Two sharers of one prompt each get a PRIVATE copy of the fork
    page before their first divergent write — their generated pages
    never alias, and the shared full pages are never written."""
    prompt = [5, 6, 7, 9, 10, 11]           # spans page 0 + tail page 1
    eng = make_engine(max_slots=2, n_pages=16, prefix_share=True)
    eng.submit(serving.Request(id="a", prompt=prompt,
                               max_new_tokens=10))
    eng.step_window()
    eng.submit(serving.Request(id="b", prompt=prompt,
                               max_new_tokens=10))
    eng.step_window()
    rows = {a.req.id: list(np.asarray(eng.arena.slot_row(s))[:3])
            for s, a in eng._active.items()}
    # page 0 (the fully-covered prefix) aliased by both...
    assert rows["a"][0] == rows["b"][0]
    assert eng.arena.page_ref(rows["a"][0]) == 2
    # ...the fork page COW-detached: same content, different page
    assert rows["a"][1] != rows["b"][1]
    assert eng._cow_copies == 1
    eng.arena.check_accounting()
    res = eng.serve()
    close_engine(eng)
    assert res["a"].tokens == res["b"].tokens
    assert res["a"].verdict == res["b"].verdict == adm.COMPLETED


@pytest.mark.parametrize("kv_dtype", ["f32", "int8"])
def test_chaos_hung_decode_evicted_sharer_decrefs_never_frees(kv_dtype):
    """Chaos x sharing x quantization: evicting a sharer decrefs the
    aliased pages, never frees them — the surviving registrar keeps
    decoding from its own pages bit-exactly, in both storage dtypes."""
    prompt = [5, 6, 7, 9, 10, 11]
    reqs = [dict(id="healthy", prompt=prompt, max_new_tokens=10),
            dict(id="suspect", prompt=prompt, max_new_tokens=10)]
    eng = make_engine(kv_dtype=kv_dtype, prefix_share=True)
    base = run_with_faults(eng, reqs, stagger=True)
    close_engine(eng)
    eng = make_engine(kv_dtype=kv_dtype, prefix_share=True,
                      decode_deadline_s=0.15)
    res = run_with_faults(
        eng, reqs, stagger=True,
        faults=[FaultSpec("hung_decode", at_step=2, delay_s=0.5)])
    assert_all_verdicted(res, ["healthy", "suspect"])
    assert res["suspect"].verdict == adm.EVICTED
    assert res["healthy"].verdict == adm.COMPLETED
    assert res["healthy"].tokens == base["healthy"].tokens
    eng.arena.check_accounting()
    close_engine(eng)


def test_arena_fuzz_admit_evict_cow_accounting():
    """Satellite 6: drive the arena through a random walk of plain
    admits, shared admits, COW detaches and releases — the page-
    conservation invariant must hold after EVERY operation."""
    import random
    rng = random.Random(170817)
    spec = serving.ArenaSpec(n_layers=1, n_kv_heads=1, head_dim=4,
                             page_size=4, n_pages=24, max_slots=6,
                             pages_per_slot=4)
    a = serving.KVArena(spec)
    occupied = []
    for _ in range(600):
        op = rng.choice(["acquire", "shared", "cow", "release"])
        if op == "acquire":
            tokens = rng.randint(1, spec.slot_tokens)
            if a.fits_now(tokens):
                slot, _ = a.acquire(tokens)
                occupied.append(slot)
        elif op == "shared" and occupied:
            donor = rng.choice(occupied)
            row = a._slot_pages[donor]
            k = rng.randint(1, len(row))
            extra = rng.randint(0, spec.pages_per_slot - k)
            tokens = (k + extra) * spec.page_size
            if a.fits_now(tokens, n_shared=k):
                slot, _ = a.acquire_shared(tokens, row[:k])
                occupied.append(slot)
        elif op == "cow" and occupied and a.free_pages:
            slot = rng.choice(occupied)
            row = a._slot_pages[slot]
            shared_idx = [i for i, p in enumerate(row)
                          if a.page_ref(p) > 1]
            if shared_idx:
                a.cow(slot, rng.choice(shared_idx))
        elif op == "release" and occupied:
            slot = occupied.pop(rng.randrange(len(occupied)))
            a.release(slot)
        a.check_accounting()
    for slot in occupied:
        a.release(slot)
    a.check_accounting()
    assert a.free_pages == spec.n_pages
    assert a.free_slots == spec.max_slots


# ---------------------------------------------------------------------------
# device-side sampling (tentpole axis c)
# ---------------------------------------------------------------------------

def _sample_args(logits, seed=0, temperature=1.0, top_k=0, top_p=1.0):
    b = logits.shape[0]
    rng = jnp.stack([jax.random.PRNGKey(seed + i) for i in range(b)])
    return (logits, rng, jnp.zeros((b,), jnp.int32),
            jnp.full((b,), temperature, jnp.float32),
            jnp.full((b,), top_k, jnp.int32),
            jnp.full((b,), top_p, jnp.float32))


def test_sample_tokens_greedy_and_filters():
    logits = jnp.asarray([[0.0, 3.0, 1.0, 2.0],
                          [5.0, 0.0, 4.0, 1.0]])
    # temperature <= 0: exact greedy
    out = serving.sample_tokens(*_sample_args(logits, temperature=0.0))
    assert list(np.asarray(out)) == [1, 0]
    # top_k=1 collapses any temperature to greedy
    out = serving.sample_tokens(*_sample_args(logits, temperature=5.0,
                                              top_k=1))
    assert list(np.asarray(out)) == [1, 0]
    # a vanishing nucleus keeps only the argmax token
    out = serving.sample_tokens(*_sample_args(logits, temperature=5.0,
                                              top_p=1e-9))
    assert list(np.asarray(out)) == [1, 0]
    # top_k=2 can only ever emit the two largest logits
    draws = set()
    args = _sample_args(logits, temperature=10.0, top_k=2)
    for pos in range(32):
        out = serving.sample_tokens(
            args[0], args[1], jnp.full((2,), pos, jnp.int32),
            *args[3:])
        draws.add((int(out[0]), int(out[1])))
    assert {d[0] for d in draws} <= {1, 3}
    assert {d[1] for d in draws} <= {0, 2}
    assert len(draws) > 1                   # it actually samples


def test_sample_tokens_depends_only_on_seed_and_position():
    logits = jnp.asarray(np.random.default_rng(0).normal(
        size=(1, 32)).astype(np.float32))
    a1 = serving.sample_tokens(*_sample_args(logits, seed=7,
                                             temperature=0.9))
    a2 = serving.sample_tokens(*_sample_args(logits, seed=7,
                                             temperature=0.9))
    assert int(a1[0]) == int(a2[0])
    # the same request drawn in a DIFFERENT batch composition sees the
    # same (seed, position) key -> the same token
    wide = jnp.concatenate([logits, logits * 0.0])
    args = _sample_args(wide, seed=7, temperature=0.9)
    rng = jnp.stack([jax.random.PRNGKey(7), jax.random.PRNGKey(99)])
    out = serving.sample_tokens(wide, rng, args[2], args[3], args[4],
                                args[5])
    assert int(out[0]) == int(a1[0])


def test_seeded_sampling_reproducible_across_batch_composition():
    """Engine-level acceptance: a seeded sampled stream is bit-exact
    regardless of what else is in the batch — the draw key folds in
    only (request seed, absolute position)."""
    samp = dict(temperature=0.8, top_k=3, top_p=0.95, seed=17)
    reqs = [dict(id="a", prompt=[5, 6, 7], max_new_tokens=6, **samp),
            dict(id="b", prompt=[9, 10], max_new_tokens=5)]
    eng = make_engine()
    both = run_with_faults(eng, reqs)
    close_engine(eng)
    eng = make_engine()
    solo = run_with_faults(eng, reqs[:1])
    close_engine(eng)
    assert both["a"].verdict == adm.COMPLETED
    assert solo["a"].tokens == both["a"].tokens
    # the greedy neighbour is untouched by its sampling neighbour
    eng = make_engine()
    greedy = run_with_faults(eng, [reqs[1]])
    close_engine(eng)
    assert greedy["b"].tokens == both["b"].tokens


def test_sampled_request_rides_ledger_and_replay():
    """Sampling params survive the results ledger round-trip (the
    arena-rebuild replay path re-prefills with them, keeping seeded
    streams reproducible across recovery)."""
    r = serving.Request(id="x", prompt=[3, 4], max_new_tokens=4,
                        temperature=0.7, top_k=5, top_p=0.9, seed=11)
    back = serving.Request.from_ledger(r.ledger_record())
    assert (back.temperature, back.top_k, back.top_p, back.seed) \
        == (0.7, 5, 0.9, 11)
    greedy = serving.Request.from_ledger(serving.Request(
        id="y", prompt=[3], max_new_tokens=2).ledger_record())
    assert greedy.temperature == 0.0 and greedy.seed == 0


# ---------------------------------------------------------------------------
# sharing observability: prefix gauges on /metrics
# ---------------------------------------------------------------------------

def test_prefix_gauges_reach_metrics_server():
    from apex_tpu.telemetry.export import MetricsServer
    srv = MetricsServer(port=0)
    try:
        eng = make_engine(max_slots=3, n_pages=24, prefix_share=True)
        prompt = [5, 6, 7, 9, 10]
        run_with_faults(eng, [
            dict(id=f"s{i}", prompt=prompt, max_new_tokens=4)
            for i in range(3)])
        saved = eng._kv_bytes_saved
        close_engine(eng)
        body = srv.render()
    finally:
        srv.close()
    assert saved > 0
    assert "apex_tpu_serving_prefix_hits" in body
    assert "apex_tpu_serving_kv_bytes_saved" in body
    assert "apex_tpu_serving_cow_copies" in body


# ---------------------------------------------------------------------------
# speculative decoding + int8 weights + batched prefill (ISSUE 18)
# ---------------------------------------------------------------------------

_SPEC_BASE_RUN: dict = {}    # plain-greedy baseline, shared across K


@pytest.mark.parametrize("spec_k", [2, 4, 8])
def test_spec_decode_bit_exact_vs_plain_greedy(spec_k):
    """The tentpole acceptance bar: greedy speculative decode is
    BIT-EXACT against plain greedy for every K — accept/rollback
    commits exactly the longest agreeing prefix, and the verify pass
    scores each position with the same numerics as a plain step."""
    reqs = [dict(id="a", prompt=[5, 6, 5, 6, 5], max_new_tokens=10),
            dict(id="b", prompt=[9, 10], max_new_tokens=8)]
    if not _SPEC_BASE_RUN:
        eng = make_engine()
        _SPEC_BASE_RUN.update(run_with_faults(eng, reqs))
        close_engine(eng)
    base = _SPEC_BASE_RUN
    eng = make_engine(spec_k=spec_k)
    res = run_with_faults(eng, reqs)
    drafted, accepted = eng._spec_drafted, eng._spec_accepted
    close_engine(eng)
    for rid in ("a", "b"):
        assert res[rid].verdict == adm.COMPLETED
        assert res[rid].tokens == base[rid].tokens, (spec_k, rid)
    assert drafted > 0
    assert 0 <= accepted <= drafted


def test_spec_decode_batch_composition_independent():
    """A speculating slot's stream does not depend on its batch
    neighbours: drafts, verify, and rollback are all per-slot."""
    reqs = [dict(id="a", prompt=[5, 6, 5, 6], max_new_tokens=8),
            dict(id="b", prompt=[9, 10, 11], max_new_tokens=6)]
    eng = make_engine(spec_k=4)
    both = run_with_faults(eng, reqs)
    close_engine(eng)
    eng = make_engine(spec_k=4)
    solo = run_with_faults(eng, reqs[:1])
    close_engine(eng)
    assert solo["a"].tokens == both["a"].tokens
    assert both["a"].verdict == both["b"].verdict == adm.COMPLETED


def test_spec_decode_sampled_stream_bit_exact():
    """Acceptance under temperature/top-p: the sampling PRNG key folds
    in (seed, absolute position), and speculation advances the fold by
    the ACCEPTED count only — so a sampled stream is bit-exact against
    the plain engine for any K."""
    samp = dict(temperature=0.8, top_k=5, top_p=0.9, seed=17)
    reqs = [dict(id="a", prompt=[5, 6, 5, 6, 5, 6],
                 max_new_tokens=8, **samp),
            dict(id="b", prompt=[9, 10], max_new_tokens=6)]
    eng = make_engine()
    base = run_with_faults(eng, reqs)
    close_engine(eng)
    eng = make_engine(spec_k=4)
    res = run_with_faults(eng, reqs)
    drafted = eng._spec_drafted
    close_engine(eng)
    assert res["a"].tokens == base["a"].tokens
    assert res["b"].tokens == base["b"].tokens
    assert drafted > 0


@pytest.mark.parametrize("spec_k", [4])
def test_chaos_hung_decode_spec_survivors_bit_exact(spec_k):
    """The chaos matrix with speculation enabled: a PRE-dispatch hang
    evicts only its suspects, the arena rebuild replays survivors with
    their history rings re-seeded, and the surviving stream stays
    bit-exact — mid-stream eviction does not disturb speculation."""
    reqs = [dict(id="healthy", prompt=[5, 6, 7], max_new_tokens=10),
            dict(id="suspect", prompt=[9, 10], max_new_tokens=10)]
    eng = make_engine(spec_k=spec_k)
    base = run_with_faults(eng, reqs, stagger=True)
    close_engine(eng)
    eng = make_engine(spec_k=spec_k, decode_deadline_s=0.15)
    res = run_with_faults(
        eng, reqs, stagger=True,
        faults=[FaultSpec("hung_decode", at_step=2, delay_s=0.5)])
    assert_all_verdicted(res, ["healthy", "suspect"])
    assert res["suspect"].verdict == adm.EVICTED
    assert res["healthy"].verdict == adm.COMPLETED
    assert res["healthy"].tokens == base["healthy"].tokens
    assert eng.incidents.history and eng.incidents.current is None
    close_engine(eng)


def test_int8_weight_engine_matches_dequant_oracle():
    """The weight-quantization acceptance bar: the int8-weight
    engine's greedy stream equals a plain f32 engine fed the
    DEQUANTIZED weights — the weight-only int8 path computes
    ``x @ dequant(w)`` with the same f32 dot, so storage changes,
    math does not."""
    from apex_tpu.quantization import dequantize, quantize_int8
    from apex_tpu.serving.model import _QUANT_WEIGHTS

    reqs = [dict(id="a", prompt=[5, 6, 7], max_new_tokens=8),
            dict(id="b", prompt=[9, 10], max_new_tokens=6)]
    eng = make_engine(weight_dtype="int8")
    res = run_with_faults(eng, reqs)
    close_engine(eng)

    deq = dict(PARAMS)
    deq["layers"] = [
        {k: (dequantize(quantize_int8(v, axis=0), jnp.float32)
             if k in _QUANT_WEIGHTS else v)
         for k, v in lp.items()}
        for lp in PARAMS["layers"]]
    # fresh params identity -> its own AOT set; one bucket keeps it
    # as small as the prompts allow (padding never changes numerics)
    oracle_eng = serving.Engine(deq, CFG, page_size=4, n_pages=16,
                                max_slots=2, pages_per_slot=4,
                                window=4, prefill_buckets=[4])
    oracle = run_with_faults(oracle_eng, reqs)
    oracle_eng.close()
    for rid in ("a", "b"):
        assert res[rid].verdict == adm.COMPLETED
        assert res[rid].tokens == oracle[rid].tokens


def test_batched_prefill_matches_serial_admission():
    """Batched multi-request prefill drains same-bucket FIFO groups
    through ONE program call each with streams identical to serial
    admission — the program-invocation counters are the proof (the
    B=4 speedup floor itself grades through bench_batched_prefill's
    budget row).  Groups are strictly bucket-homogeneous (the
    bucket-8 prompt breaks its group into singleton calls) and seeded
    sampled requests ride the batched path bit-exactly."""
    reqs = [dict(id="r0", prompt=[2, 3, 4], max_new_tokens=5),
            dict(id="r1", prompt=[5, 3, 4], max_new_tokens=5),
            dict(id="s", prompt=[5, 6, 5], max_new_tokens=5,
                 temperature=0.8, top_k=5, top_p=0.9, seed=17),
            dict(id="long", prompt=[3, 4, 5, 6, 7], max_new_tokens=4)]
    eng = make_engine()               # serial baseline, fully cached
    base = run_with_faults(eng, reqs)
    serial_calls = eng._n_prefill_calls
    close_engine(eng)
    assert serial_calls == 4
    eng = make_engine(prefill_batch=2)
    res = run_with_faults(eng, reqs)
    assert eng._n_prefills == 4
    # [r0 r1] batch (bucket 4); then [s] alone — long (bucket 8)
    # breaks its group — then [long]
    assert eng._n_prefill_calls == 3
    close_engine(eng)
    for rid in ("r0", "r1", "s", "long"):
        assert res[rid].verdict == adm.COMPLETED
        assert res[rid].tokens == base[rid].tokens


def test_engine_spec_knobs_default_from_dispatch_prefs(monkeypatch):
    from apex_tpu.ops import _dispatch
    # one knob per engine build so each reuses a program set another
    # test compiles anyway (the kv_dtype defaults-test discipline)
    monkeypatch.setattr(_dispatch, "_SERVING", {"spec_k": 2})
    eng = make_engine()
    assert eng.spec_k == 2 and eng.weight_dtype == "f32"
    close_engine(eng)
    monkeypatch.setattr(_dispatch, "_SERVING",
                        {"weight_dtype": "int8"})
    eng = make_engine()
    assert eng.weight_dtype == "int8" and eng.spec_k == 0
    close_engine(eng)
    monkeypatch.setattr(_dispatch, "_SERVING", {"prefill_batch": 2})
    eng = make_engine()
    assert eng.prefill_batch == 2
    close_engine(eng)
    # an explicit constructor argument beats the table
    monkeypatch.setattr(_dispatch, "_SERVING",
                        {"spec_k": 4, "weight_dtype": "int8",
                         "prefill_batch": 2})
    eng = make_engine(spec_k=0, weight_dtype="f32", prefill_batch=1)
    assert eng.spec_k == 0
    assert eng.weight_dtype == "f32"
    assert eng.prefill_batch == 1
    close_engine(eng)


@pytest.mark.slow
def test_bench_spec_decode_smoke():
    """The spec_verify_step kernel_bench row's harness: the repetitive
    -suffix fixture must clear the extra.spec_accept_rate floor (0.5)
    bit-exactly — the accept rate is counted from the engine's
    serving/spec_* counters, so wall-clock noise cannot fake it.
    Slow-marked: tier-1 already drives BOTH serving benches end-to-end
    through the autotune cpu-smoke's sweep_serving_compute."""
    from apex_tpu.serving.bench import bench_spec_decode
    r = bench_spec_decode(n_requests=2, n_layers=1, hidden=32,
                          n_heads=2, window=4, spec_k=4,
                          max_new_tokens=10)
    assert r["spec_k"] == 4
    assert r["spec_drafted"] > 0
    assert r["spec_bit_exact"] == 1
    assert r["spec_verify_step_ms"] > 0


@pytest.mark.slow
def test_bench_batched_prefill_smoke():
    """The batched-prefill bench: speedup is requests / program
    invocations, so B=4 same-bucket admission must grade >= the
    budget floor (1.5) with zero noise.  Slow-marked: tier-1 already
    drives both serving benches through the autotune cpu-smoke."""
    from apex_tpu.serving.bench import bench_batched_prefill
    r = bench_batched_prefill(n_requests=2, n_layers=1, hidden=32,
                              n_heads=2, prefill_batch=2,
                              max_new_tokens=3)
    assert r["batched_prefill_speedup"] >= 1.5
    assert r["batched_prefill_bit_exact"] == 1
    assert r["batched_prefill_ms"] > 0
