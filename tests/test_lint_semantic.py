"""apexverify (apex_tpu.lint.semantic): spec registry, invariant
checkers, jaxpr walkers, baseline diff semantics, the CLI contract,
and the tier-1 acceptance gate — every registered entry-point spec
passes, inside the wall-clock budget that keeps the gate cheap.

Suite `run_lint_semantic` in tests/run_test.py.
"""

import json
import os
import subprocess
import sys
import time

import jax
import jax.numpy as jnp
import pytest

from apex_tpu.lint import semantic
from apex_tpu.lint.semantic import baseline as bl
from apex_tpu.lint.semantic import jaxprs, registry
from apex_tpu.lint.findings import Finding

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)

# ---------------------------------------------------------------------------
# the acceptance gate
# ---------------------------------------------------------------------------

def test_every_registered_spec_passes():
    """THE tier-1 semantic gate: every public-entry-point invariant
    spec verifies clean (zero transfer primitives, donation aliased,
    expected kernel counts, no f64, no orphan collectives)."""
    results = semantic.verify_all()
    failures = {r.name: r.failures for r in results if not r.ok}
    assert not failures, failures
    assert len(results) >= 14   # 5 optimizers x 2 paths + 4 pipelines
    # every spec actually checked something substantive
    for r in results:
        assert r.checked, r.name


def test_registry_covers_the_public_entry_points():
    names = set(semantic.spec_names())
    for opt in ("FusedAdam", "FusedSGD", "FusedAdagrad",
                "FusedNovoGrad", "FusedLAMB"):
        assert f"optim.{opt}.bucketed" in names
        assert f"optim.{opt}.per_leaf" in names
    assert {"amp.flat_pipeline_step", "amp.scaled_value_and_grad",
            "telemetry.instrumented_step",
            "ddp.all_reduce_flat_buffers"} <= names


def test_spec_anchors_are_real_files():
    for spec in semantic.all_specs():
        assert os.path.exists(os.path.join(REPO, spec.anchor)), \
            (spec.name, spec.anchor)
        assert spec.description


def test_full_gate_wall_clock_budget():
    """tools/check.sh stays cheap: the ENTIRE lint+verify pass (AST
    tier over apex_tpu/ + all semantic specs, one fresh process with
    its jax import) rounds in < 60 s on one CPU core."""
    t0 = time.monotonic()
    proc = subprocess.run(
        [sys.executable, "-m", "apex_tpu.lint", "--semantic",
         "apex_tpu/"],
        capture_output=True, text=True, cwd=REPO, timeout=120)
    elapsed = time.monotonic() - t0
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "semantic specs" in proc.stdout
    assert elapsed < 60.0, f"lint+verify gate took {elapsed:.1f}s"


# ---------------------------------------------------------------------------
# registry / checker mechanics (temporary specs, cleaned up per test)
# ---------------------------------------------------------------------------

@pytest.fixture
def scratch_spec():
    created = []

    def make(name, builder, anchor="apex_tpu/lint/semantic/specs.py"):
        semantic.register_spec(name, anchor=anchor)(builder)
        created.append(name)
        return registry.get_spec(name)

    yield make
    for name in created:
        registry._REGISTRY.pop(name, None)


def test_violated_invariant_reports_failure(scratch_spec):
    spec = scratch_spec("tmp.too_many_pallas", lambda: {
        "fn": lambda x: x + 1.0, "args": (jnp.ones((4,)),),
        "expect": {"pallas_calls": 99},
    })
    res = semantic.verify_spec(spec)
    assert not res.ok and "pallas" in res.failures[0]
    findings = semantic.results_to_findings([res])
    assert [f.rule_id for f in findings] == ["APX901"]
    assert findings[0].severity == "error"
    assert "tmp.too_many_pallas" in findings[0].message


def test_build_error_reports_apx902(scratch_spec):
    def broken():
        raise RuntimeError("entry point gone")
    spec = scratch_spec("tmp.broken", broken)
    res = semantic.verify_spec(spec)
    assert not res.ok
    findings = semantic.results_to_findings([res])
    assert [f.rule_id for f in findings] == ["APX902"]


def test_unknown_invariant_key_fails_loudly(scratch_spec):
    spec = scratch_spec("tmp.typo", lambda: {
        "fn": lambda x: x, "args": (jnp.ones(3),),
        "expect": {"no_host_transfers": True},   # typo'd key
    })
    res = semantic.verify_spec(spec)
    assert not res.ok and "unknown invariant" in res.failures[0]


def test_empty_expect_fails(scratch_spec):
    spec = scratch_spec("tmp.empty", lambda: {
        "fn": lambda x: x, "args": (jnp.ones(3),), "expect": {}})
    res = semantic.verify_spec(spec)
    assert not res.ok and "declares no invariants" in res.failures[0]


def test_donation_invariant_positive_and_negative(scratch_spec):
    def step(state, x):
        return state + x, x * 2.0
    args = (jnp.ones((16,)), jnp.ones((16,)))
    ok = scratch_spec("tmp.donated", lambda: {
        "fn": step, "args": args,
        "jit_kwargs": {"donate_argnums": (0,)},
        "expect": {"donated_aliases": 1}})
    assert semantic.verify_spec(ok).ok
    missing = scratch_spec("tmp.undonated", lambda: {
        "fn": step, "args": args, "jit_kwargs": {},
        "expect": {"donated_aliases_min": 1}})
    res = semantic.verify_spec(missing)
    assert not res.ok and "donation not honored" in res.failures[0]


# ---------------------------------------------------------------------------
# jaxpr walkers
# ---------------------------------------------------------------------------

def test_host_transfer_detection_on_callback():
    def noisy(x):
        jax.debug.callback(lambda v: None, x)
        return x * 2

    jaxpr = jax.make_jaxpr(noisy)(jnp.ones(4))
    bad = jaxprs.host_transfer_prims(jaxpr)
    assert bad and any("callback" in p for p in bad)
    assert jaxprs.host_transfer_prims(
        jax.make_jaxpr(lambda x: x * 2)(jnp.ones(4))) == []


def test_concat_shapes_and_counts_recurse_into_subjaxprs():
    def f(a, b):
        def body(_, c):
            return jnp.concatenate([c, c])[: c.shape[0]]
        return jax.lax.fori_loop(0, 3, body, jnp.concatenate([a, b]))

    jaxpr = jax.make_jaxpr(f)(jnp.ones(4), jnp.ones(4))
    shapes = jaxprs.concat_out_shapes(jaxpr)
    assert (8,) in shapes and (16,) in shapes   # outer + loop body
    assert jaxprs.primitive_counts(jaxpr)["concatenate"] == 2


def test_orphan_collective_detection():
    import numpy as np
    from jax.sharding import Mesh, PartitionSpec as P
    from apex_tpu import comm

    mesh = Mesh(np.array(jax.devices()[:1]), ("data",))

    def dead(x):
        # deliberately dead: this test PROVES the walker catches it
        jax.lax.psum(jnp.ones(()), "data")   # apexlint: disable=APX703
        return x * 2

    def live(x):
        return jax.lax.psum(x, "data")

    dead_j = jax.make_jaxpr(comm.shard_map(
        dead, mesh, in_specs=P(), out_specs=P()))(jnp.ones(8))
    live_j = jax.make_jaxpr(comm.shard_map(
        live, mesh, in_specs=P(), out_specs=P()))(jnp.ones(8))
    assert "psum" in jaxprs.orphan_collectives(dead_j)
    assert jaxprs.orphan_collectives(live_j) == []
    assert jaxprs.collective_axis_names(live_j) == {"data"}


def test_axis_is_bound_probe_leaves_no_collective():
    """Regression for the real finding apexverify surfaced: the old
    `axis_index` probe left a dead collective in every program that
    called comm.axis_is_bound (the ring-attention partitioner-bug
    shape); the statically-folded psum(1) probe leaves NOTHING."""
    import numpy as np
    from jax.sharding import Mesh, PartitionSpec as P
    from apex_tpu import comm

    mesh = Mesh(np.array(jax.devices()[:1]), ("data",))

    def probing(x):
        assert comm.axis_is_bound("data")
        assert not comm.axis_is_bound("nope")
        return x * 2

    jaxpr = jax.make_jaxpr(comm.shard_map(
        probing, mesh, in_specs=P(), out_specs=P()))(jnp.ones(8))
    assert jaxprs.orphan_collectives(jaxpr) == []
    assert jaxprs.collective_axis_names(jaxpr) == set()


def test_donated_alias_count_reads_lowered_text():
    lowered = jax.jit(lambda a, b: (a + b, b),
                      donate_argnums=(0,)).lower(jnp.ones(4),
                                                 jnp.ones(4))
    assert jaxprs.donated_alias_count(lowered.as_text()) == 1


# ---------------------------------------------------------------------------
# baseline semantics
# ---------------------------------------------------------------------------

def _finding(path="a.py", rule="APX901", msg="m", line=1):
    return Finding(path=path, line=line, col=1, rule_id=rule,
                   rule_name="x", message=msg)


def test_baseline_roundtrip_and_split(tmp_path):
    f1 = _finding(msg="one")
    f2 = _finding(msg="two", line=9)
    path = str(tmp_path / "baseline.json")
    bl.save(path, [f1])
    base = bl.load(path)
    new, old, stale = bl.split([f1, f2], base)
    assert [f.message for f in new] == ["two"]
    assert [f.message for f in old] == ["one"]
    assert stale == set()
    # line drift does NOT un-baseline a finding (keys ignore line/col)
    moved = _finding(msg="one", line=55)
    new2, old2, _ = bl.split([moved], base)
    assert new2 == [] and old2 == [moved]
    # fixed finding -> stale entry reported, nothing gates
    new3, old3, stale3 = bl.split([], base)
    assert new3 == [] and old3 == [] and len(stale3) == 1


def test_shipped_baseline_is_empty():
    """Head is clean: the shipped baseline carries zero accepted
    findings, so CI gates on everything."""
    assert bl.load(bl.DEFAULT_BASELINE) == set()


# ---------------------------------------------------------------------------
# CLI contract (subprocesses pay the jax import: slow tier)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_cli_semantic_baseline_flow(tmp_path):
    def run(*args):
        return subprocess.run(
            [sys.executable, "-m", "apex_tpu.lint", *args],
            capture_output=True, text=True, cwd=REPO, timeout=300)

    # --list-specs names every optimizer spec
    proc = run("--list-specs")
    assert proc.returncode == 0
    assert "optim.FusedAdam.bucketed" in proc.stdout

    # a hazard gates normally, is silenced by a written baseline,
    # and gates again when a NEW finding appears
    mod = tmp_path / "m.py"
    mod.write_text("import os\nX = os.environ.get('A')\n")
    base = str(tmp_path / "base.json")
    assert run(str(mod)).returncode == 1
    proc = run("--baseline", base, "--write-baseline", str(mod))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    proc = run("--baseline", base, str(mod))
    assert proc.returncode == 0
    assert "1 baselined finding" in proc.stdout
    mod.write_text("import os\nimport jax\nX = os.environ.get('A')\n"
                   "\n\n@jax.jit\ndef f(x):\n    if x:\n"
                   "        return x\n    return x + 1\n")
    proc = run("--baseline", base, str(mod))
    assert proc.returncode == 1
    payload = run("--json", "--baseline", base, str(mod))
    data = json.loads(payload.stdout)
    assert data["finding_count"] == 1 and data["baselined_count"] == 1
    assert data["findings"][0]["rule_id"] == "APX301"

    # --write-baseline without --baseline/--semantic must refuse (it
    # would otherwise overwrite the SHIPPED package baseline)
    proc = run("--write-baseline", str(mod))
    assert proc.returncode == 2
    assert "--baseline" in proc.stderr

    # baselined findings stay VISIBLE (tagged), per the documented
    # "reported but never gate" contract — text and JSON
    mod.write_text("import os\nX = os.environ.get('A')\n")
    assert run("--baseline", base, "--write-baseline",
               str(mod)).returncode == 0
    proc = run("--baseline", base, str(mod))
    assert proc.returncode == 0
    assert "[baselined]" in proc.stdout and "APX601" in proc.stdout
    data = json.loads(run("--json", "--baseline", base,
                          str(mod)).stdout)
    assert data["baselined_count"] == 1
    assert data["baselined"][0]["rule_id"] == "APX601"

    # --ignore/--select cover the semantic tier's ids too
    proc = run("--ignore", "APX902", str(mod))
    assert proc.returncode == 1          # APX601 still gates
    proc = run("--ignore", "APX601,APX902", str(mod))
    assert proc.returncode == 0, proc.stdout + proc.stderr
