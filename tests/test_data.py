"""apex_tpu.data (device prefetcher) — reference: the data_prefetcher
class in the reference's examples/imagenet/main_amp.py."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from apex_tpu.data import DevicePrefetcher, prefetch_to_device


def _batches(n, b=4):
    for i in range(n):
        yield {"x": np.full((b, 8), i, np.float32),
               "y": np.full((b,), i, np.int32)}


def test_prefetcher_yields_all_batches_in_order_on_device():
    out = list(DevicePrefetcher(_batches(5), depth=2))
    assert len(out) == 5
    for i, b in enumerate(out):
        assert isinstance(b["x"], jax.Array)
        assert float(b["x"][0, 0]) == i
        assert int(b["y"][0]) == i


def test_prefetcher_reference_next_idiom():
    pf = DevicePrefetcher(_batches(2))
    seen = 0
    batch = pf.next()
    while batch is not None:
        seen += 1
        batch = pf.next()
    assert seen == 2
    # the apex data_prefetcher keeps returning None after exhaustion —
    # extra probes must not deadlock
    assert pf.next() is None
    assert pf.next() is None


def test_prefetcher_early_exit_close_releases_feeder():
    pf = DevicePrefetcher(_batches(100), depth=2)
    first = next(iter(pf))
    assert float(first["x"][0, 0]) == 0
    pf.close()                      # abandon mid-stream
    assert not pf._thread.is_alive()
    assert pf.next() is None        # closed prefetcher is exhausted


def test_prefetcher_context_manager():
    with DevicePrefetcher(_batches(50), depth=2) as pf:
        for i, _ in zip(range(3), pf):
            pass
    assert not pf._thread.is_alive()


def test_prefetcher_with_sharding_lands_on_mesh():
    from apex_tpu import comm
    comm.initialize(data=jax.device_count())
    sh = comm.sharding("data")
    n = jax.device_count()
    it = ({"x": np.ones((2 * n, 4), np.float32)} for _ in range(3))
    for b in prefetch_to_device(it, depth=2, sharding=sh):
        assert b["x"].sharding == sh
        assert float(jnp.sum(b["x"])) == 2 * n * 4
    comm.destroy()


def test_prefetcher_propagates_source_errors():
    def bad():
        yield {"x": np.zeros((2,), np.float32)}
        raise RuntimeError("loader died")

    pf = DevicePrefetcher(bad(), depth=1)
    assert pf.next() is not None
    with pytest.raises(RuntimeError, match="loader died"):
        pf.__next__()


def test_prefetcher_rejects_bad_depth():
    with pytest.raises(ValueError):
        DevicePrefetcher(_batches(1), depth=0)


def test_close_wakes_blocked_consumer():
    """A consumer blocked in __next__ when close() runs must observe
    shutdown, not hang forever (advisor r2)."""
    import threading, queue as _q

    def slow():
        yield {"x": np.zeros((2,), np.float32)}
        import time
        time.sleep(30)          # feeder never produces a second batch
        yield {"x": np.zeros((2,), np.float32)}

    pf = DevicePrefetcher(slow(), depth=1)
    assert pf.next() is not None
    got = _q.Queue()

    def consume():
        try:
            pf.__next__()
            got.put("item")
        except StopIteration:
            got.put("stop")

    t = threading.Thread(target=consume, daemon=True)
    t.start()
    import time
    time.sleep(0.2)             # let the consumer block in q.get()
    pf.close()
    assert got.get(timeout=5.0) == "stop"
    t.join(timeout=5.0)
    assert not t.is_alive()


class TestPackSequences:
    """pack_sequences + the flash kernel's segment-id routing must
    together equal per-sequence attention computed separately — the
    packed-varlen contract (reference: contrib/fmha)."""

    def test_packing_invariants(self):
        from apex_tpu.data import pack_sequences

        rng = np.random.default_rng(0)
        lens = [7, 3, 9, 1, 5, 5, 2, 8]
        seqs = [rng.integers(1, 100, size=n) for n in lens]
        out = pack_sequences(seqs, max_len=16, pad_id=0)
        toks, segs, pos = (out["tokens"], out["segment_ids"],
                           out["positions"])
        assert toks.shape == segs.shape == pos.shape
        assert toks.shape[1] == 16
        # every token survives, grouped contiguously, positions 0..n-1
        seen = []
        for r in range(toks.shape[0]):
            for seg in range(1, int(segs[r].max()) + 1):
                m = segs[r] == seg
                assert m.sum() > 0
                idx = np.flatnonzero(m)
                assert (np.diff(idx) == 1).all()          # contiguous
                np.testing.assert_array_equal(
                    pos[r, idx], np.arange(len(idx)))
                seen.append(toks[r, idx].tolist())
        assert sorted(map(tuple, seen)) == sorted(
            tuple(s.tolist()) for s in seqs)
        # padding is segment 0, pad_id, position 0
        padm = segs == 0
        assert (toks[padm] == 0).all() and (pos[padm] == 0).all()

    def test_packing_invariants_randomized(self):
        """Random corpora: every token survives exactly once, rows
        never overflow, segment/position/pad invariants hold."""
        from apex_tpu.data import pack_sequences

        rng = np.random.default_rng(7)
        for trial in range(20):
            max_len = int(rng.integers(8, 64))
            n_seqs = int(rng.integers(1, 24))
            lens = rng.integers(1, max_len + 1, size=n_seqs)
            seqs = [rng.integers(1, 1000, size=n) for n in lens]
            out = pack_sequences(seqs, max_len=max_len, pad_id=0)
            toks, segs, pos = (out["tokens"], out["segment_ids"],
                               out["positions"])
            # rows never overflow; bins actually used
            assert (segs > 0).sum() == sum(lens)
            assert toks.shape[1] == max_len
            recovered = []
            for r in range(toks.shape[0]):
                row_segs = segs[r]
                assert row_segs.max() >= 1      # no all-padding rows
                for seg in range(1, int(row_segs.max()) + 1):
                    idx = np.flatnonzero(row_segs == seg)
                    assert len(idx)             # ids are contiguous 1..K
                    assert (np.diff(idx) == 1).all()
                    np.testing.assert_array_equal(
                        pos[r, idx], np.arange(len(idx)))
                    recovered.append(tuple(toks[r, idx]))
            assert sorted(recovered) == sorted(
                tuple(s.tolist()) for s in seqs), f"trial {trial}"
            # attention form consistent with the base ids
            np.testing.assert_array_equal(
                out["q_segment_ids"] < 0, segs == 0)
            np.testing.assert_array_equal(
                out["kv_segment_ids"] < 0, segs == 0)

    def test_too_long_or_empty_raises(self):
        from apex_tpu.data import pack_sequences
        with pytest.raises(ValueError, match="longer than"):
            pack_sequences([list(range(20))], max_len=16)
        with pytest.raises(ValueError, match="empty"):
            pack_sequences([[1, 2], []], max_len=16)

    def test_packed_attention_matches_per_sequence(self):
        from apex_tpu.data import pack_sequences
        from apex_tpu.ops.attention import flash_attention

        rng = np.random.default_rng(1)
        lens = [48, 31, 17, 64, 9]
        d, L = 32, 128
        # per-sequence q/k/v derived deterministically from token ids so
        # the packed and unpacked paths see identical values
        seqs = [rng.integers(1, 50, size=n) for n in lens]
        packed = pack_sequences(seqs, max_len=L, pad_id=0)
        qids = jnp.asarray(packed["q_segment_ids"])
        kvids = jnp.asarray(packed["kv_segment_ids"])
        B = qids.shape[0]

        def feats(tok_row):  # (L,) -> (1, 1, L, d)
            base = jnp.asarray(tok_row, jnp.float32)[:, None]
            ang = base * (jnp.arange(d, dtype=jnp.float32)[None] + 1.0)
            return (jnp.stack([jnp.sin(ang), jnp.cos(ang)],
                              -1).reshape(len(tok_row), 2 * d)
                    [:, :d][None, None] * 0.3)

        q = jnp.concatenate([feats(packed["tokens"][r]) for r in
                             range(B)], axis=0)
        o_packed = flash_attention(q, q, q, causal=False,
                                   segment_ids=(qids, kvids))
        for r in range(B):
            for seg in range(1, int(np.max(packed["segment_ids"][r]))
                             + 1):
                idx = np.flatnonzero(packed["segment_ids"][r] == seg)
                qs = q[r:r + 1, :, idx, :]
                o_ref = flash_attention(qs, qs, qs, causal=False)
                np.testing.assert_allclose(
                    np.asarray(o_packed[r:r + 1, :, idx, :],
                               np.float32),
                    np.asarray(o_ref, np.float32), rtol=2e-5,
                    atol=2e-5)
        # disjoint pad ids per side (-1 vs -2, the contrib.fmha
        # convention): pad rows are fully masked and output EXACT
        # zeros — no downstream masking needed
        padm = np.asarray(packed["segment_ids"]) == 0
        assert (np.asarray(o_packed, np.float32)
                [np.broadcast_to(padm[:, None, :, None],
                                 o_packed.shape)] == 0).all()


class TestPackDataset:
    """Streaming packer: fixed batch shapes, every token exactly once,
    padding only in the final batch."""

    def test_stream_invariants(self):
        from apex_tpu.data import pack_dataset

        rng = np.random.default_rng(11)
        lens = rng.integers(1, 33, size=137)
        seqs = [rng.integers(1, 1000, size=n) for n in lens]
        batches = list(pack_dataset(iter(seqs), max_len=32,
                                    rows_per_batch=4,
                                    buffer_batches=3))
        assert batches, "no batches emitted"
        recovered = []
        for i, b in enumerate(batches):
            assert b["tokens"].shape == (4, 32)
            assert set(b) == {"tokens", "segment_ids", "positions",
                              "q_segment_ids", "kv_segment_ids"}
            all_pad_rows = (b["segment_ids"] == 0).all(axis=1)
            if all_pad_rows.any():
                # padding rows only in the FINAL batch, only at the end
                assert i == len(batches) - 1
            for r in range(4):
                segs = b["segment_ids"][r]
                for seg in range(1, int(segs.max(initial=0)) + 1):
                    recovered.append(
                        tuple(b["tokens"][r][segs == seg]))
        assert sorted(recovered) == sorted(
            tuple(s.tolist()) for s in seqs)

    def test_small_stream_single_padded_batch(self):
        from apex_tpu.data import pack_dataset

        batches = list(pack_dataset([[1, 2, 3]], max_len=8,
                                    rows_per_batch=4))
        assert len(batches) == 1
        b = batches[0]
        assert b["tokens"].shape == (4, 8)
        assert (b["segment_ids"][1:] == 0).all()
        assert (b["q_segment_ids"][1:] == -1).all()
        assert (b["kv_segment_ids"][1:] == -2).all()
