"""apex_tpu.data (device prefetcher) — reference: the data_prefetcher
class in the reference's examples/imagenet/main_amp.py."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from apex_tpu.data import DevicePrefetcher, prefetch_to_device


def _batches(n, b=4):
    for i in range(n):
        yield {"x": np.full((b, 8), i, np.float32),
               "y": np.full((b,), i, np.int32)}


def test_prefetcher_yields_all_batches_in_order_on_device():
    out = list(DevicePrefetcher(_batches(5), depth=2))
    assert len(out) == 5
    for i, b in enumerate(out):
        assert isinstance(b["x"], jax.Array)
        assert float(b["x"][0, 0]) == i
        assert int(b["y"][0]) == i


def test_prefetcher_reference_next_idiom():
    pf = DevicePrefetcher(_batches(2))
    seen = 0
    batch = pf.next()
    while batch is not None:
        seen += 1
        batch = pf.next()
    assert seen == 2
    # the apex data_prefetcher keeps returning None after exhaustion —
    # extra probes must not deadlock
    assert pf.next() is None
    assert pf.next() is None


def test_prefetcher_early_exit_close_releases_feeder():
    pf = DevicePrefetcher(_batches(100), depth=2)
    first = next(iter(pf))
    assert float(first["x"][0, 0]) == 0
    pf.close()                      # abandon mid-stream
    assert not pf._thread.is_alive()
    assert pf.next() is None        # closed prefetcher is exhausted


def test_prefetcher_context_manager():
    with DevicePrefetcher(_batches(50), depth=2) as pf:
        for i, _ in zip(range(3), pf):
            pass
    assert not pf._thread.is_alive()


def test_prefetcher_with_sharding_lands_on_mesh():
    from apex_tpu import comm
    comm.initialize(data=jax.device_count())
    sh = comm.sharding("data")
    n = jax.device_count()
    it = ({"x": np.ones((2 * n, 4), np.float32)} for _ in range(3))
    for b in prefetch_to_device(it, depth=2, sharding=sh):
        assert b["x"].sharding == sh
        assert float(jnp.sum(b["x"])) == 2 * n * 4
    comm.destroy()


def test_prefetcher_propagates_source_errors():
    def bad():
        yield {"x": np.zeros((2,), np.float32)}
        raise RuntimeError("loader died")

    pf = DevicePrefetcher(bad(), depth=1)
    assert pf.next() is not None
    with pytest.raises(RuntimeError, match="loader died"):
        pf.__next__()


def test_prefetcher_rejects_bad_depth():
    with pytest.raises(ValueError):
        DevicePrefetcher(_batches(1), depth=0)


def test_close_wakes_blocked_consumer():
    """A consumer blocked in __next__ when close() runs must observe
    shutdown, not hang forever (advisor r2)."""
    import threading, queue as _q

    def slow():
        yield {"x": np.zeros((2,), np.float32)}
        import time
        time.sleep(30)          # feeder never produces a second batch
        yield {"x": np.zeros((2,), np.float32)}

    pf = DevicePrefetcher(slow(), depth=1)
    assert pf.next() is not None
    got = _q.Queue()

    def consume():
        try:
            pf.__next__()
            got.put("item")
        except StopIteration:
            got.put("stop")

    t = threading.Thread(target=consume, daemon=True)
    t.start()
    import time
    time.sleep(0.2)             # let the consumer block in q.get()
    pf.close()
    assert got.get(timeout=5.0) == "stop"
    t.join(timeout=5.0)
    assert not t.is_alive()
