"""contrib.sparsity (ASP) + pyprof shim + transformer.testing harness
(reference pattern: apex/contrib/test/sparsity/ — mask density and
training-with-masks invariants)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import apex_tpu.pyprof as pyprof
from apex_tpu.contrib.sparsity import ASP, create_mask
from apex_tpu.contrib.sparsity.sparse_masklib import mn_1d_mask
from apex_tpu.optimizers import FusedSGD
from apex_tpu.pyprof import nvtx


@pytest.fixture(autouse=True)
def _reset_asp():
    ASP._masks = None
    yield
    ASP._masks = None


def test_mask_density_and_topk():
    w = jax.random.normal(jax.random.PRNGKey(0), (16, 64))
    m = create_mask(w, "m4n2_1d")
    assert float(jnp.mean(m)) == 0.5
    # each group of 4 keeps exactly its 2 largest |w|
    wg = np.asarray(w).reshape(16, 16, 4)
    mg = np.asarray(m).reshape(16, 16, 4)
    for i in range(16):
        for g in range(16):
            kept = np.sort(np.abs(wg[i, g][mg[i, g] > 0]))
            dropped = np.abs(wg[i, g][mg[i, g] == 0])
            assert kept.shape == (2,) and dropped.shape == (2,)
            assert kept.min() >= dropped.max() - 1e-7


def test_mask_ties_keep_exact_count():
    w = jnp.ones((2, 8))
    m = mn_1d_mask(w, 4, 2)
    assert int(jnp.sum(m)) == 8          # exactly 2 per group despite ties


def test_create_mask_rejects_bad_shapes_and_patterns():
    with pytest.raises(ValueError, match="divisible"):
        create_mask(jnp.ones((3, 6)), "m4n2_1d")
    with pytest.raises(ValueError, match="unknown pattern"):
        create_mask(jnp.ones((4, 8)), "m16n3_1d")


def test_asp_prune_and_training_preserves_sparsity():
    params = {"dense": {"kernel": jax.random.normal(
        jax.random.PRNGKey(0), (32, 16))},
        "bias": jnp.ones((16,))}
    opt = FusedSGD(params, lr=0.1)
    masked = ASP.prune_trained_model(params, opt)
    assert float(jnp.mean(masked["dense"]["kernel"] != 0)) <= 0.5
    np.testing.assert_allclose(np.asarray(masked["bias"]), 1.0)  # skipped
    # steps keep the pruned pattern
    for i in range(3):
        g = jax.tree_util.tree_map(
            lambda x: jnp.ones_like(x), params)
        p = opt.step(g)
    zeros = np.asarray(ASP.masks()["dense"]["kernel"]) == 0
    assert np.all(np.asarray(p["dense"]["kernel"])[zeros] == 0.0)
    assert np.all(np.asarray(p["bias"]) != 1.0)   # unmasked leaf trained


def test_asp_restore_disables():
    params = {"k": jnp.ones((4, 8))}
    ASP.init_model_for_pruning(params)
    ASP.compute_sparse_masks(params)
    assert ASP.is_sparsity_enabled()
    ASP.restore_pruned_weights(params)
    assert not ASP.is_sparsity_enabled()


def test_nvtx_push_pop_and_annotate():
    pyprof.init()
    assert pyprof.enabled()
    depth = nvtx.range_push("outer")
    assert depth == 1
    with nvtx.range("inner"):
        pass
    assert nvtx.range_pop() == 0
    assert nvtx.range_pop() == 0        # extra pop is harmless

    @nvtx.annotate("f")
    def f(x):
        return x * 2
    assert float(f(jnp.float32(3))) == 6.0


def test_pyprof_prof_parses_trace_dir(tmp_path, capsys):
    """The prof half (reference: apex/pyprof/prof parsers) lives in the
    package and renders the top-device-ops table from a written trace
    dir; tools/profile_step.summarize_device_ops is an alias of it."""
    import gzip
    import json

    from apex_tpu.pyprof import prof

    d = tmp_path / "plugins" / "profile" / "2026_01_01"
    d.mkdir(parents=True)
    events = [
        {"ph": "M", "pid": 3, "name": "process_name",
         "args": {"name": "/device:TPU:0"}},
        {"ph": "M", "pid": 3, "tid": 7, "name": "thread_name",
         "args": {"name": "XLA Ops"}},
        {"ph": "X", "pid": 3, "tid": 7, "name": "fusion.9",
         "dur": 3000},
        {"ph": "X", "pid": 3, "tid": 7, "name": "conv", "dur": 1000},
    ]
    with gzip.open(d / "vm.trace.json.gz", "wt") as f:
        json.dump({"traceEvents": events}, f)

    rows = prof.summarize_device_ops(str(tmp_path))
    assert rows == [["fusion.9", 3.0, 75.0], ["conv", 1.0, 25.0]]

    assert prof.main([str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "fusion.9" in out and "75.0%" in out
    assert prof.main([str(tmp_path / "nothing")]) == 1


def test_testing_commons_builds_mesh():
    from apex_tpu.transformer.testing import commons, global_vars
    mesh = commons.initialize_distributed(tensor_model_parallel_size=2,
                                          pipeline_model_parallel_size=2)
    assert mesh.shape["model"] == 2 and mesh.shape["pipe"] == 2
    from apex_tpu.transformer import parallel_state
    assert parallel_state.get_tensor_model_parallel_world_size() == 2
    commons.destroy_distributed()
    args = global_vars.set_global_variables(hidden_size=128)
    assert global_vars.get_args().hidden_size == 128
    global_vars.destroy_global_vars()
    with pytest.raises(RuntimeError):
        global_vars.get_args()


class TestPermutationSearch:
    """reference: apex/contrib/sparsity/permutation_search_kernels —
    permuting input channels must never reduce, and usually increases,
    the 2:4-retained magnitude."""

    def _w(self, r=32, c=64, seed=0):
        rng = np.random.default_rng(seed)
        # heavy-tailed columns so grouping matters
        scale = rng.lognormal(0.0, 1.5, size=c)
        return rng.normal(size=(r, c)) * scale

    def test_valid_permutation(self):
        from apex_tpu.contrib import sparsity as sp
        w = self._w()
        perm = sp.search_for_good_permutation(w, max_sweeps=3)
        assert sorted(perm.tolist()) == list(range(w.shape[-1]))

    def test_retained_magnitude_improves(self):
        from apex_tpu.contrib import sparsity as sp
        w = self._w()
        base = sp.sum_after_2_to_4(w)
        perm = sp.search_for_good_permutation(w, max_sweeps=5)
        permuted = sp.apply_permutation(w, perm)
        assert sp.sum_after_2_to_4(permuted) >= base
        # heavy-tailed columns: the search should find real gains
        assert sp.sum_after_2_to_4(permuted) > base * 1.0001

    def test_greedy_beats_or_equals_init(self):
        from apex_tpu.contrib import sparsity as sp
        w = self._w(seed=3)
        init = sp.magnitude_init_permutation(w)
        refined = sp.search_for_good_permutation(w, max_sweeps=5)
        assert (sp.sum_after_2_to_4(sp.apply_permutation(w, refined))
                >= sp.sum_after_2_to_4(sp.apply_permutation(w, init)))

    def test_invert_roundtrip(self):
        from apex_tpu.contrib import sparsity as sp
        w = self._w(r=4, c=16, seed=1)
        perm = sp.search_for_good_permutation(w, max_sweeps=2)
        inv = sp.invert_permutation(perm)
        np.testing.assert_array_equal(
            sp.apply_permutation(sp.apply_permutation(w, perm), inv), w)

    def test_mask_on_permuted_is_2to4(self):
        from apex_tpu.contrib import sparsity as sp
        from apex_tpu.contrib.sparsity import create_mask
        w = jnp.asarray(self._w())
        perm = sp.search_for_good_permutation(np.asarray(w))
        mask = create_mask(jnp.asarray(sp.apply_permutation(
            np.asarray(w), perm)))
        m = np.asarray(mask).reshape(w.shape[0], -1, 4)
        np.testing.assert_array_equal(m.sum(-1), 2)
