"""apex_tpu.offload — activation offload under remat (beyond-reference)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.offload import checkpoint_name, offload_checkpoint


def _block(w1, w2, x):
    h = checkpoint_name(jax.nn.gelu(x @ w1), "ffn_hidden")
    return checkpoint_name(h @ w2, "out")


def test_offload_checkpoint_matches_plain_grads():
    w1 = jax.random.normal(jax.random.key(0), (64, 256)) * 0.1
    w2 = jax.random.normal(jax.random.key(1), (256, 64)) * 0.1
    x = jax.random.normal(jax.random.key(2), (8, 64))

    def loss(f):
        return lambda w1, w2, x: jnp.sum(f(w1, w2, x) ** 2)

    g_plain = jax.jit(jax.grad(loss(_block), argnums=(0, 1)))(w1, w2, x)
    off = offload_checkpoint(_block, offload_names=("ffn_hidden",))
    g_off = jax.jit(jax.grad(loss(off), argnums=(0, 1)))(w1, w2, x)
    for a, b in zip(g_plain, g_off):
        # f32 tolerance: remat recomputes the forward, so XLA may fuse
        # and reassociate the matmul reductions differently from the
        # saved-activation program — a few-ulp f32 delta, not a bug
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


def test_offload_checkpoint_lowers_for_tpu():
    """The offload remat policy must lower for the TPU platform (AOT,
    no device — same tier as tests/test_tpu_lowering.py)."""
    w1 = jnp.zeros((64, 256))
    w2 = jnp.zeros((256, 64))
    x = jnp.zeros((8, 64))
    off = offload_checkpoint(_block, offload_names=("ffn_hidden",),
                             save_names=("out",))
    jax.jit(jax.grad(
        lambda w1, w2, x: jnp.sum(off(w1, w2, x) ** 2),
        argnums=(0, 1))).trace(w1, w2, x).lower(
        lowering_platforms=("tpu",))


def test_gpt_layer_tags_compose_with_offload():
    """GPTLayer pre-tags attn_out/ffn_hidden; offload_checkpoint over
    the unmodified layer must produce the same grads as plain apply."""
    from apex_tpu import comm
    from apex_tpu.models.gpt import GPTLayer
    comm.initialize(data=8)
    layer = GPTLayer(32, 4)
    x = jax.random.normal(jax.random.key(0), (16, 2, 32))
    params = layer.init(jax.random.key(1), x)

    def loss(apply):
        return lambda p, xx: jnp.sum(apply(p, xx) ** 2)

    g_plain = jax.jit(jax.grad(loss(layer.apply)))(params, x)
    off = offload_checkpoint(layer.apply,
                             offload_names=("attn_out", "ffn_hidden"))
    g_off = jax.jit(jax.grad(loss(off)))(params, x)
    for a, b in zip(jax.tree_util.tree_leaves(g_plain),
                    jax.tree_util.tree_leaves(g_off)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-5)
    comm.destroy()
