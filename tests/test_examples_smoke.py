"""Examples tier under CI: run the fast examples in-process (reference
model: examples are the reference's L6 layer; keeping them green is part
of the public contract — SURVEY.md §1)."""

import runpy
import sys

import pytest


def _run(path, argv):
    old = sys.argv
    sys.argv = [path] + argv
    try:
        runpy.run_path(path, run_name="__main__")
    finally:
        sys.argv = old


def test_train_multiproc_via_launcher():
    """The reference's torch.distributed.launch example flow, end to
    end: launcher -> N processes -> initialize_distributed handshake ->
    cross-process grad all-reduce -> converging loss on every rank."""
    import os
    import subprocess

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    for k in ("XLA_FLAGS", "JAX_PLATFORMS", "JAX_COORDINATOR_ADDRESS",
              "COORDINATOR_ADDRESS", "WORLD_SIZE", "RANK",
              "NUM_PROCESSES", "PROCESS_ID", "APEX_TPU_SMOKE"):
        env.pop(k, None)
    env["PYTHONPATH"] = root
    p = subprocess.run(
        [sys.executable, "-m", "apex_tpu.launch", "--nproc", "2",
         os.path.join("examples", "simple", "distributed",
                      "train_multiproc.py")],
        capture_output=True, text=True, env=env, cwd=root, timeout=300)
    assert p.returncode == 0, p.stdout[-3000:] + p.stderr[-2000:]
    assert "[rank 0] OK" in p.stdout and "[rank 1] OK" in p.stdout


def test_train_toy_runs_and_converges(capsys):
    _run("examples/simple/train_toy.py", [])
    assert "OK: loss" in capsys.readouterr().out


def test_train_toy_preempt_and_resume(tmp_path, capsys):
    """Kill-and-resume the toy run — the acceptance flow a
    preemptible-fleet user copies: a preemption notice produces one
    final durable checkpoint and a clean exit; rerunning with the same
    --checkpoint-dir resumes from that step and finishes; and the
    checkpoint telemetry (ckpt/* counters, checkpoint/* spans) renders
    on the summarize surface."""
    ckpt = str(tmp_path / "ckpt")
    tel = str(tmp_path / "telemetry")
    _run("examples/simple/train_toy.py",
         ["--steps", "24", "--save-every", "6",
          "--checkpoint-dir", ckpt, "--preempt-at-step", "10"])
    out = capsys.readouterr().out
    assert "preempted: final checkpoint durable at step 10" in out
    assert "OK" not in out                  # partial run: no bar
    _run("examples/simple/train_toy.py",
         ["--steps", "24", "--save-every", "6",
          "--checkpoint-dir", ckpt, "--telemetry-dir", tel])
    out = capsys.readouterr().out
    assert "resumed at step 10" in out and "OK: resumed" in out
    from apex_tpu.telemetry.cli import main as telemetry_cli
    assert telemetry_cli(["summarize", tel]) == 0
    out = capsys.readouterr().out
    assert "ckpt/save_ms" in out and "checkpoint/save" in out


def test_train_toy_watchdog_self_heals_nan_fault(tmp_path, capsys):
    """The self-healing acceptance flow: an injected NaN fault storms
    past the scaler's backoff, the watchdog detects the streak at a
    window flush, rolls back to the last-known-good checkpoint,
    replays to completion — and the anomaly timeline (detection +
    rollback action) renders on the summarize surface."""
    import warnings as _warnings

    ckpt = str(tmp_path / "ckpt")
    tel = str(tmp_path / "telemetry")
    with _warnings.catch_warnings():
        _warnings.simplefilter("ignore")      # the rollback warns: fine
        _run("examples/simple/train_toy.py",
             ["--steps", "48", "--save-every", "6",
              "--checkpoint-dir", ckpt, "--telemetry-dir", tel,
              "--watchdog", "--inject-nan-at", "20"])
    out = capsys.readouterr().out
    assert "run self-healed" in out
    assert "OK:" in out                       # replay converged
    from apex_tpu.telemetry.cli import main as telemetry_cli
    assert telemetry_cli(["summarize", tel]) == 0
    out = capsys.readouterr().out
    assert "anomaly timeline:" in out
    assert "nan_streak" in out and "rollback" in out


def test_train_toy_fleet_kill_one_host_shrinks_and_recovers(tmp_path,
                                                            capsys):
    """The multi-host failure-domain acceptance flow: one faked host
    of the toy's 3-host fleet stops beaconing mid-run, the survivors
    agree on the death within the step-lag deadline, shrink, restore
    the last checkpoint and replay to completion — and the whole
    sequence (beacon gap -> host_dead -> shrink -> resume) renders as
    the fleet timeline on the summarize surface."""
    import warnings as _warnings

    ckpt = str(tmp_path / "ckpt")
    tel = str(tmp_path / "telemetry")
    with _warnings.catch_warnings():
        _warnings.simplefilter("ignore")      # the recovery warns: fine
        _run("examples/simple/train_toy.py",
             ["--steps", "48", "--save-every", "6",
              "--checkpoint-dir", ckpt, "--telemetry-dir", tel,
              "--fleet", "--kill-host-at", "20"])
    out = capsys.readouterr().out
    assert "fleet: 3 hosts (2 simulated peers)" in out
    assert "shrank to healthy mesh" in out
    assert "OK:" in out                       # replay converged
    from apex_tpu.telemetry.cli import main as telemetry_cli
    assert telemetry_cli(["summarize", tel]) == 0
    out = capsys.readouterr().out
    assert "fleet timeline:" in out
    assert "host_dead" in out and "shrink" in out
    assert "fleet/hosts_dead" in out          # counters table rows


def test_train_toy_live_metrics_scrape_and_incident_timeline(
        tmp_path, capsys):
    """The live-observability acceptance flow: train with
    --serve-metrics while a background scraper polls /metrics.  The
    fleet death + the injected NaN storm must FLIP the exported
    gauges mid-run (fleet_hosts_dead / watchdog rollback totals go
    0 -> >=1, monotone so the scraper cannot miss them), and
    afterwards the whole beacon-gap -> agreement -> shrink -> replay
    chain must share ONE incident_id — rendered by ``telemetry
    timeline`` as a single closed incident."""
    import json as _json
    import socket
    import threading
    import urllib.request
    import warnings as _warnings

    ckpt = str(tmp_path / "ckpt")
    tel = str(tmp_path / "telemetry")
    with socket.socket() as s:                # pick a free port
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    samples, stop = [], threading.Event()

    def scrape():
        url = f"http://127.0.0.1:{port}/metrics"
        while not stop.is_set():
            try:
                with urllib.request.urlopen(url, timeout=1) as r:
                    body = r.read().decode()
                g = {}
                for line in body.splitlines():
                    if not line.startswith("#") and " " in line:
                        n, v = line.rsplit(" ", 1)
                        g[n] = float(v)
                samples.append(g)
            except OSError:
                pass                          # server not up/gone yet
            stop.wait(0.005)

    t = threading.Thread(target=scrape, daemon=True)
    t.start()
    try:
        with _warnings.catch_warnings():
            _warnings.simplefilter("ignore")  # the recoveries warn
            _run("examples/simple/train_toy.py",
                 ["--steps", "64", "--save-every", "6",
                  "--checkpoint-dir", ckpt, "--telemetry-dir", tel,
                  "--fleet", "--kill-host-at", "40",
                  "--watchdog", "--inject-nan-at", "18",
                  "--serve-metrics", str(port)])
    finally:
        stop.set()
        t.join(timeout=5)
    out = capsys.readouterr().out
    assert f"serving live metrics at http://127.0.0.1:{port}" in out
    assert "shrank to healthy mesh" in out
    assert "run self-healed" in out
    assert len(samples) > 2                   # genuinely scraped live
    # the gauges FLIPPED mid-run: an early scrape predates both
    # incidents, a later one carries them (totals are monotone)
    dead = [g.get("apex_tpu_fleet_hosts_dead_total", 0.0)
            for g in samples]
    assert dead[0] == 0.0 and max(dead) >= 1.0
    last = samples[-1]
    assert last.get("apex_tpu_fleet_mesh_shrinks_total", 0) >= 1
    assert last.get("apex_tpu_watchdog_rollback_events_total", 0) >= 1
    assert last.get("apex_tpu_anomaly_nan_streak_events_total", 0) >= 1
    assert last.get("apex_tpu_exported_step", -1) > 0
    # the shrink chain shares ONE incident_id end to end
    recs = []
    with open(tmp_path / "telemetry" / "telemetry.jsonl",
              encoding="utf-8") as f:
        for line in f:
            recs.append(_json.loads(line))
    by_ev = {}
    for r in recs:
        if r.get("kind") == "fleet" and "incident_id" in r:
            by_ev.setdefault(r["event"], set()).add(r["incident_id"])
    assert by_ev["host_dead"] == by_ev["shrink"] \
        == by_ev["replay_complete"]
    assert len(by_ev["shrink"]) == 1
    from apex_tpu.telemetry.cli import main as telemetry_cli
    assert telemetry_cli(["timeline", tel, "--json"]) == 0
    doc = _json.loads(capsys.readouterr().out)
    shrink_incs = [i for i in doc["incidents"]
                   if any(e.get("event") == "shrink"
                          for e in i["events"])]
    assert len(shrink_incs) == 1
    inc = shrink_incs[0]
    assert inc["closed"] and inc["opened_by"] == "fleet:host_dead"
    evs = [e.get("event") or e.get("action") for e in inc["events"]]
    assert "host_dead" in evs and "shrink" in evs \
        and "replay_complete" in evs


def test_train_toy_revive_host_admits_and_grows(tmp_path, capsys):
    """The elastic scale-UP acceptance flow, end to end: kill ->
    shrink -> return -> admit -> grow.  The killed peer comes back
    under a fresh incarnation, the members admit it at a step
    boundary, the mesh grows back to full strength and the checkpoint
    reshards onto it — with the whole timeline (host_dead -> shrink ->
    host_return -> grow) visible in ``telemetry summarize``."""
    import warnings as _warnings

    ckpt = str(tmp_path / "ckpt")
    tel = str(tmp_path / "telemetry")
    with _warnings.catch_warnings():
        _warnings.simplefilter("ignore")      # the recoveries warn: fine
        _run("examples/simple/train_toy.py",
             ["--steps", "60", "--save-every", "6",
              "--checkpoint-dir", ckpt, "--telemetry-dir", tel,
              "--fleet", "--kill-host-at", "16",
              "--revive-host-at", "34"])
    out = capsys.readouterr().out
    assert "shrank to healthy mesh" in out
    assert "grew back to full mesh" in out
    assert "OK:" in out                       # replay converged
    from apex_tpu.telemetry.cli import main as telemetry_cli
    assert telemetry_cli(["summarize", tel]) == 0
    out = capsys.readouterr().out
    assert "fleet timeline:" in out
    assert "host_dead" in out and "shrink" in out
    assert "host_return" in out and "grow" in out
    assert "fleet/mesh_grows" in out          # counters table row


def test_serve_gpt_chaos_scrape_and_incident_timeline(tmp_path,
                                                      capsys):
    """The serving acceptance flow: the engine demo decodes with
    --port while a background scraper polls /metrics, and
    --inject-hung-decode-at drives detect -> evict -> re-admit.  A
    mid-run scrape must carry the ``serving_*`` gauges, and the whole
    failover chain (hung_decode -> eviction -> resolution) must share
    ONE incident id rendered by ``telemetry timeline --json`` as a
    single closed incident."""
    import json as _json
    import socket
    import threading
    import urllib.request

    tel = str(tmp_path / "telemetry")
    with socket.socket() as s:                # pick a free port
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    samples, stop = [], threading.Event()

    def scrape():
        url = f"http://127.0.0.1:{port}/metrics"
        while not stop.is_set():
            try:
                with urllib.request.urlopen(url, timeout=1) as r:
                    body = r.read().decode()
                g = {}
                for line in body.splitlines():
                    if not line.startswith("#") and " " in line \
                            and "{" not in line:
                        n, v = line.rsplit(" ", 1)
                        g[n] = float(v)
                samples.append(g)
            except OSError:
                pass                          # server not up/gone yet
            stop.wait(0.005)

    t = threading.Thread(target=scrape, daemon=True)
    t.start()
    try:
        _run("examples/gpt/serve.py",
             ["--requests", "5", "--max-new-tokens", "10",
              "--telemetry-dir", tel, "--port", str(port),
              "--inject-hung-decode-at", "3"])
    finally:
        stop.set()
        t.join(timeout=5)
    out = capsys.readouterr().out
    assert f"serving live metrics at http://127.0.0.1:{port}" in out
    assert "re-admitting evicted request" in out
    assert "incident chain: inc-001-hung_decode-e0 [closed]" in out
    assert "OK:" in out
    assert len(samples) > 2                   # genuinely scraped live
    # a MID-RUN scrape carries the serving gauges
    mid = [g for g in samples
           if "apex_tpu_serving_queue_depth" in g]
    assert mid, "no scrape saw serving gauges"
    last = samples[-1]
    assert last.get("apex_tpu_serving_completed_total", 0) >= 4
    assert last.get("apex_tpu_serving_evictions_total", 0) >= 1
    assert last.get(
        "apex_tpu_serving_hung_decode_events_total", 0) >= 1
    assert "apex_tpu_serving_p99_token_ms" in last
    # the failover chain shares ONE incident id end to end
    from apex_tpu.telemetry.cli import main as telemetry_cli
    assert telemetry_cli(["timeline", tel, "--json"]) == 0
    doc = _json.loads(capsys.readouterr().out)
    assert len(doc["incidents"]) == 1
    inc = doc["incidents"][0]
    assert inc["incident_id"] == "inc-001-hung_decode-e0"
    assert inc["closed"]
    assert inc["opened_by"] == "serving:hung_decode"
    evs = [e.get("event") for e in inc["events"]]
    assert "hung_decode" in evs and "request_evicted" in evs \
        and "incident_resolved" in evs


def test_serve_gpt_shared_prefix_int8_gauges_live_and_summarize(
        tmp_path, capsys):
    """The serving memory frontier demo: --shared-system-prompt +
    --kv-dtype int8 + --sample decodes with --port while a background
    scraper polls /metrics.  A MID-RUN scrape must carry the prefix-
    sharing gauges (``apex_tpu_serving_prefix_hits`` /
    ``_kv_bytes_saved``), and ``telemetry summarize`` renders the same
    counters afterwards — the step-less serving run has a summarize
    surface too."""
    import socket
    import threading
    import urllib.request

    tel = str(tmp_path / "telemetry")
    with socket.socket() as s:                # pick a free port
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    samples, stop = [], threading.Event()

    def scrape():
        url = f"http://127.0.0.1:{port}/metrics"
        while not stop.is_set():
            try:
                with urllib.request.urlopen(url, timeout=1) as r:
                    body = r.read().decode()
                g = {}
                for line in body.splitlines():
                    if not line.startswith("#") and " " in line \
                            and "{" not in line:
                        n, v = line.rsplit(" ", 1)
                        g[n] = float(v)
                samples.append(g)
            except OSError:
                pass                          # server not up/gone yet
            stop.wait(0.005)

    t = threading.Thread(target=scrape, daemon=True)
    t.start()
    try:
        _run("examples/gpt/serve.py",
             ["--requests", "5", "--max-new-tokens", "10",
              "--kv-dtype", "int8", "--sample", "0.8:0.95",
              "--shared-system-prompt",
              "--telemetry-dir", tel, "--port", str(port)])
    finally:
        stop.set()
        t.join(timeout=5)
    out = capsys.readouterr().out
    assert "'quantized': True" in out and "'dtype': 'int8'" in out
    assert "prefix sharing:" in out
    assert "OK:" in out
    assert len(samples) > 2                   # genuinely scraped live
    # a MID-RUN scrape carries the prefix-sharing gauges
    mid = [g for g in samples
           if "apex_tpu_serving_prefix_hits" in g]
    assert mid, "no scrape saw the prefix gauges"
    assert any(g.get("apex_tpu_serving_kv_bytes_saved", 0) > 0
               for g in samples)
    last = samples[-1]
    assert last.get("apex_tpu_serving_prefix_hits", 0) >= 1
    # ...and the counters land on the summarize surface afterwards
    from apex_tpu.telemetry.cli import main as telemetry_cli
    assert telemetry_cli(["summarize", tel]) == 0
    summary = capsys.readouterr().out
    assert "serving/prefix_hits" in summary
    assert "serving/kv_bytes_saved" in summary


def test_serve_gpt_speculative_int8_weights_gauges_live(
        tmp_path, capsys):
    """The compute frontier demo: --speculate + --weight-dtype int8 +
    --prefill-batch decodes with --port while a background scraper
    polls /metrics.  A MID-RUN scrape must carry the speculation
    counters (``apex_tpu_serving_spec_accepted`` / ``_drafted``), and
    the final stdout summary reports the accept rate and the batched
    prefill program-call count."""
    import socket
    import threading
    import urllib.request

    tel = str(tmp_path / "telemetry")
    with socket.socket() as s:                # pick a free port
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    samples, stop = [], threading.Event()

    def scrape():
        url = f"http://127.0.0.1:{port}/metrics"
        while not stop.is_set():
            try:
                with urllib.request.urlopen(url, timeout=1) as r:
                    body = r.read().decode()
                g = {}
                for line in body.splitlines():
                    if not line.startswith("#") and " " in line \
                            and "{" not in line:
                        n, v = line.rsplit(" ", 1)
                        g[n] = float(v)
                samples.append(g)
            except OSError:
                pass                          # server not up/gone yet
            stop.wait(0.005)

    t = threading.Thread(target=scrape, daemon=True)
    t.start()
    try:
        _run("examples/gpt/serve.py",
             ["--requests", "4", "--max-new-tokens", "8",
              "--speculate", "2", "--weight-dtype", "int8",
              "--prefill-batch", "2",
              "--telemetry-dir", tel, "--port", str(port)])
    finally:
        stop.set()
        t.join(timeout=5)
    out = capsys.readouterr().out
    assert "speculation: K=2" in out
    assert "batched prefill:" in out
    assert "OK:" in out
    assert len(samples) > 2                   # genuinely scraped live
    # a MID-RUN scrape carries the speculation counters
    mid = [g for g in samples
           if "apex_tpu_serving_spec_accepted" in g]
    assert mid, "no scrape saw the speculation counters"
    last = samples[-1]
    assert last.get("apex_tpu_serving_spec_drafted", 0) > 0
    assert last.get("apex_tpu_serving_spec_accepted", 0) >= 0


def test_serve_gpt_trace_dir_slo_histograms_live(tmp_path, capsys):
    """The observability acceptance flow: --trace-dir records request
    lifecycle traces while --port serves live metrics.  A MID-RUN
    scrape must carry the Prometheus SLO histograms
    (``apex_tpu_serving_ttft_ms_bucket``), the dumped reqtrace.jsonl
    must be gap-free for every request, and ``telemetry summarize``
    renders the per-run SLO table off the same dir."""
    import json as _json
    import os
    import socket
    import threading
    import urllib.request

    trace = str(tmp_path / "trace")
    with socket.socket() as s:                # pick a free port
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    bodies, stop = [], threading.Event()

    def scrape():
        url = f"http://127.0.0.1:{port}/metrics"
        while not stop.is_set():
            try:
                with urllib.request.urlopen(url, timeout=1) as r:
                    bodies.append(r.read().decode())
            except OSError:
                pass                          # server not up/gone yet
            stop.wait(0.005)

    t = threading.Thread(target=scrape, daemon=True)
    t.start()
    try:
        _run("examples/gpt/serve.py",
             ["--requests", "4", "--max-new-tokens", "8",
              "--trace-dir", trace, "--port", str(port)])
    finally:
        stop.set()
        t.join(timeout=5)
    out = capsys.readouterr().out
    assert "request traces written to" in out
    assert "SLO summary" in out
    assert "OK:" in out
    assert len(bodies) > 2                    # genuinely scraped live
    # a MID-RUN scrape carries the Prometheus SLO histograms — the
    # third metric class next to gauges and counters
    mid = [b for b in bodies
           if "apex_tpu_serving_ttft_ms_bucket" in b]
    assert mid, "no scrape saw the SLO histograms"
    last = mid[-1]
    assert "# TYPE apex_tpu_serving_ttft_ms histogram" in last
    assert 'apex_tpu_serving_ttft_ms_bucket{le="+Inf"}' in last
    assert "apex_tpu_serving_ttft_ms_sum" in last
    assert "apex_tpu_serving_ttft_ms_count" in last
    # the dumped trace file is gap-free for every request
    from apex_tpu.telemetry import trace_gaps
    with open(os.path.join(trace, "reqtrace.jsonl")) as f:
        recs = [_json.loads(line) for line in f]
    assert len(recs) == 4
    for rec in recs:
        assert rec["verdict"] == "completed"
        assert trace_gaps(rec) == [], rec
    # ...and the SLO table renders off the same dir
    from apex_tpu.telemetry.cli import main as telemetry_cli
    assert telemetry_cli(["summarize", trace]) == 0
    summary = capsys.readouterr().out
    assert "serving SLO:" in summary
    assert "ttft_ms" in summary


def test_imagenet_preempt_and_resume(tmp_path, capsys):
    """The imagenet example's save path rides the same resilience
    manager: --checkpoint-dir rotates bucket-native checkpoints and a
    preemption notice leaves a resumable final one."""
    ckpt = str(tmp_path / "ckpt")
    common = ["--cpu", "--batch-size", "2", "--image-size", "32",
              "--arch", "resnet18", "--save-every", "3",
              "--checkpoint-dir", ckpt]
    _run("examples/imagenet/main_amp.py",
         common + ["--steps", "6", "--preempt-at-step", "4"])
    out = capsys.readouterr().out
    assert "preempted: final checkpoint durable at step 4" in out
    _run("examples/imagenet/main_amp.py", common + ["--steps", "6"])
    out = capsys.readouterr().out
    # --steps is the TOTAL: the resumed run finishes at 6, not 4+6
    assert "resumed at step 4" in out and "(step 6)" in out


def test_imagenet_tiny_cpu(capsys):
    _run("examples/imagenet/main_amp.py",
         ["--cpu", "--steps", "2", "--batch-size", "2",
          "--image-size", "32", "--arch", "resnet18"])
    assert "throughput" in capsys.readouterr().out


def test_imagenet_grad_accum_flat(capsys):
    # microbatches= adoption: the flat-accumulation path (ISSUE 10)
    # drives the same loop — 2 microbatches per step, fused adds, the
    # latched found_inf feeding the branch-free skip
    _run("examples/imagenet/main_amp.py",
         ["--cpu", "--steps", "2", "--batch-size", "4",
          "--image-size", "32", "--arch", "resnet18",
          "--grad-accum", "2"])
    out = capsys.readouterr().out
    assert "throughput" in out and "grad-accum 2 (flat)" in out


def test_imagenet_space_to_depth_stem(capsys):
    # the MXU-efficient stem bench.py enables on hardware, reachable
    # from the reference-shaped CLI too
    _run("examples/imagenet/main_amp.py",
         ["--cpu", "--steps", "2", "--batch-size", "2",
          "--image-size", "32", "--arch", "resnet18",
          "--stem-space-to-depth"])
    assert "throughput" in capsys.readouterr().out


def test_dcgan_two_scalers(capsys):
    _run("examples/dcgan/main_amp.py",
         ["--cpu", "--steps", "2", "--batch-size", "4"])
    out = capsys.readouterr().out
    assert "loss_scaler0" in out and "loss_scaler1" in out


@pytest.mark.slow
def test_bert_pretrain_mlm_tiny(capsys):
    _run("examples/bert/pretrain_mlm.py",
         ["--cpu", "--steps", "2"])
    assert "step time" in capsys.readouterr().out


@pytest.mark.slow
def test_bert_pretrain_mlm_packed(capsys):
    _run("examples/bert/pretrain_mlm.py",
         ["--cpu", "--steps", "2", "--packed"])
    out = capsys.readouterr().out
    assert "packed" in out and "step time" in out


@pytest.mark.slow
def test_gpt_block_tiny(capsys):
    _run("examples/gpt/train_block.py",
         ["--cpu", "--steps", "2", "--layers", "1", "--hidden", "64",
          "--heads", "4", "--seq-len", "64", "--batch-size", "2"])
    assert "step time" in capsys.readouterr().out


def test_train_tp_converges(capsys):
    _run("examples/simple/train_tp.py", [])
    assert "OK: loss" in capsys.readouterr().out


def test_train_ddp_converges(capsys):
    _run("examples/simple/distributed/train_ddp.py", [])
    assert "OK: loss" in capsys.readouterr().out


def test_train_pp_1f1b_converges(capsys):
    _run("examples/simple/train_pp.py", [])
    assert "OK: loss" in capsys.readouterr().out


def test_train_pp_interleaved_converges(capsys):
    _run("examples/simple/train_pp.py", ["--virtual", "2"])
    out = capsys.readouterr().out
    assert "OK: loss" in out and "interleaved-1F1B V=2" in out


def test_train_4d_gpt_converges_with_grad_accum(capsys):
    # microbatches= adoption on the per-leaf path (3-axis-sharded
    # state: the packer declines by design, the scan oracle runs)
    _run("examples/gpt/train_4d.py", ["--steps", "8", "--accum", "2"])
    assert "OK:" in capsys.readouterr().out


def test_train_4d_gpt_converges(capsys):
    _run("examples/gpt/train_4d.py", ["--steps", "8"])
    out = capsys.readouterr().out
    assert "OK: loss" in out and "pp=2x2chunks" in out
