"""FusedDense / FusedDenseGeluDense / MLP vs plain-XLA oracles
(reference model: apex tests/L0/run_mlp/test_mlp.py pattern — fused module
vs an nn.Sequential oracle, plus init sanity)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.fused_dense import (FusedDense, FusedDenseGeluDense,
                                  fused_dense_function)
from apex_tpu.mlp import MLP, mlp_function


def test_fused_dense_matches_linear():
    key = jax.random.key(0)
    x = jax.random.normal(key, (4, 7, 32))
    m = FusedDense(32, 48)
    v = m.init(jax.random.key(1), x)
    y = m.apply(v, x)
    w = v["params"]["weight"]
    b = v["params"]["bias"]
    want = x @ w.T + b
    np.testing.assert_allclose(np.asarray(y), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_fused_dense_init_variance_is_fan_in():
    """Weight layout is torch-style (out, in): fan-in must be the LAST
    axis or a wide layer initializes ~sqrt(out/in) too large."""
    m = FusedDense(1024, 4)
    v = m.init(jax.random.key(0), jnp.zeros((2, 1024)))
    std = float(jnp.std(v["params"]["weight"]))
    assert abs(std - (1.0 / 1024) ** 0.5) < 0.01, std
    x = jax.random.normal(jax.random.key(1), (512, 1024))
    y = m.apply(v, x)
    assert float(jnp.std(y)) < 2.0   # ~1.0 for lecun, ~14 when broken


def test_fused_dense_gelu_dense_matches_oracle():
    x = jax.random.normal(jax.random.key(2), (8, 16))
    m = FusedDenseGeluDense(16, 64, 24)
    v = m.init(jax.random.key(3), x)
    p = v["params"]
    h = x @ p["weight1"].T + p["bias1"]
    want = jax.nn.gelu(h, approximate=True) @ p["weight2"].T + p["bias2"]
    y = m.apply(v, x)
    np.testing.assert_allclose(np.asarray(y), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_fused_dense_function_bf16_accumulates_f32():
    x = jax.random.normal(jax.random.key(4), (16, 256)).astype(jnp.bfloat16)
    w = jax.random.normal(jax.random.key(5), (32, 256)).astype(jnp.bfloat16)
    y = fused_dense_function(x, w)
    want = (np.asarray(x, np.float32) @ np.asarray(w, np.float32).T)
    np.testing.assert_allclose(np.asarray(y, np.float32), want,
                               rtol=3e-2, atol=3e-1)
    assert y.dtype == jnp.bfloat16


@pytest.mark.parametrize("bias", [True, False])
@pytest.mark.parametrize("activation", ["relu", "sigmoid", "none"])
def test_mlp_matches_functional(bias, activation):
    sizes = [16, 32, 8]
    m = MLP(sizes, bias=bias, activation=activation)
    x = jax.random.normal(jax.random.key(6), (5, 16))
    v = m.init(jax.random.key(7), x)
    y = m.apply(v, x)
    params = []
    for i in range(len(sizes) - 1):
        lp = v["params"][f"layer_{i}"]
        params.append((lp["kernel"], lp["bias"]) if bias else lp["kernel"])
    want = mlp_function(params, x, bias=bias, activation=activation)
    np.testing.assert_allclose(np.asarray(y), np.asarray(want),
                               rtol=1e-5, atol=1e-5)
