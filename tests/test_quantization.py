"""apex_tpu.quantization — int8 inference tier (beyond reference)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.quantization import (QTensor, QuantDense, dequantize,
                                   int8_matmul, quantize_int8,
                                   quantize_model)


def test_quantize_roundtrip_error_bound():
    w = jax.random.normal(jax.random.key(0), (128, 64)) * 0.3
    t = quantize_int8(w, axis=0)
    assert t.q.dtype == jnp.int8 and t.scale.shape == (1, 64)
    err = np.abs(np.asarray(dequantize(t, jnp.float32)) - np.asarray(w))
    # symmetric int8: per-channel max error <= scale/2
    assert (err <= np.asarray(t.scale) / 2 + 1e-7).all()


def test_weight_only_matmul_close_to_f32():
    k = jax.random.key(1)
    x = jax.random.normal(k, (8, 256), jnp.bfloat16)
    w = jax.random.normal(jax.random.key(2), (256, 64)) * 0.1
    y_ref = np.asarray(x.astype(jnp.float32) @ w)
    y = int8_matmul(x, quantize_int8(w), dynamic=False)
    assert y.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(y, np.float32), y_ref,
                               rtol=0.05, atol=0.12)


def test_dynamic_int8_matmul_close_to_f32():
    x = jax.random.normal(jax.random.key(3), (8, 256), jnp.bfloat16)
    w = jax.random.normal(jax.random.key(4), (256, 64)) * 0.1
    y_ref = np.asarray(x.astype(jnp.float32) @ w)
    y = int8_matmul(x, quantize_int8(w), dynamic=True)
    np.testing.assert_allclose(np.asarray(y, np.float32), y_ref,
                               rtol=0.08, atol=0.15)


def test_quantize_model_default_predicate():
    params = {"dense": {"kernel": jnp.ones((32, 16)),
                        "bias": jnp.zeros((16,))},
              "ln": {"scale": jnp.ones((32,))}}
    q = quantize_model(params)
    assert isinstance(q["dense"]["kernel"], QTensor)
    assert q["dense"]["bias"].shape == (16,)       # 1D untouched
    assert q["ln"]["scale"].shape == (32,)
    # still a pytree: jit/tree_map work
    n = len(jax.tree_util.tree_leaves(q))
    assert n == 4   # q + scale + bias + ln.scale


@pytest.mark.parametrize("dynamic", [False, True])
def test_quant_dense_matches_fused_dense(dynamic):
    from apex_tpu.fused_dense import fused_dense_function
    w = jax.random.normal(jax.random.key(5), (64, 256)) * 0.05  # (Out, In)
    b = jax.random.normal(jax.random.key(6), (64,)) * 0.1
    x = jax.random.normal(jax.random.key(7), (4, 256), jnp.bfloat16)
    y_ref = np.asarray(fused_dense_function(x, w, b), np.float32)
    qd = QuantDense.from_weights(w, b, dynamic=dynamic)
    y = qd(x)
    np.testing.assert_allclose(np.asarray(y, np.float32), y_ref,
                               rtol=0.1, atol=0.15)


@pytest.mark.parametrize("dynamic", [False, True])
def test_int8_matmul_lowers_for_tpu(dynamic):
    """Both modes must lower for the TPU platform (AOT, no device)."""
    x = jnp.zeros((128, 512), jnp.bfloat16)
    w = quantize_int8(jnp.zeros((512, 256)))
    jax.jit(lambda x, q, s: int8_matmul(
        x, QTensor(q=q, scale=s), dynamic=dynamic)).trace(
        x, w.q, w.scale).lower(lowering_platforms=("tpu",))


@pytest.mark.parametrize("dynamic", [False, True])
def test_int8_matmul_rank1_contract(dynamic):
    """1-D input keeps rank 1 in BOTH modes (code-review r2 finding)."""
    x = jax.random.normal(jax.random.key(8), (256,), jnp.bfloat16)
    w = quantize_int8(jax.random.normal(jax.random.key(9),
                                        (256, 64)) * 0.1)
    y = int8_matmul(x, w, dynamic=dynamic)
    assert y.shape == (64,)
