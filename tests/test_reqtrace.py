"""Request-level tracing + SLO histograms (docs/observability.md).

The observability contract under test:

- fixed log-scale histogram quantiles are within one bucket width of
  the exact order statistic, and merges are associative/commutative
  (N replicas fold in any order);
- every request the engine verdicts has a GAP-FREE lifecycle trace
  (enqueue -> admit -> decode windows -> verdict), under chaos too;
- a failover re-admission's trace rides the replica queue ledger:
  the merged timeline renders ONE request lane spanning both hosts
  under the failover's incident id;
- the live ``/metrics`` endpoint renders the histograms in the
  Prometheus exposition format, and ``telemetry summarize`` renders
  the per-run SLO table;
- tracing is free: the traced engine emits a bit-exact token stream
  at ~1.0x the untraced wall time (kernel_bench ``reqtrace_overhead``).
"""

import json
import os
import random
import time

import jax
import pytest

from apex_tpu import serving
from apex_tpu.resilience import fleet as fleet_mod
from apex_tpu.resilience.faults import FaultInjector, FaultSpec
from apex_tpu.serving import admission as adm
from apex_tpu.telemetry.hist import (DEFAULT_BOUNDS_MS, HistogramSet,
                                     LatencyHistogram, merge_records,
                                     prometheus_histogram_lines)
from apex_tpu.telemetry.reqtrace import RequestTracer, trace_gaps

CFG = serving.DecoderConfig(vocab_size=64, hidden=16, n_layers=2,
                            n_heads=2, n_kv_heads=2, ffn=32,
                            max_seq=32, eos_token=1)
PARAMS = serving.init_params(jax.random.key(0), CFG)


def make_engine(multi_replica=False, **kw):
    """Same tiny geometry as test_serving (shared compile cache)."""
    kw.setdefault("page_size", 4)
    kw.setdefault("n_pages", 16)
    kw.setdefault("max_slots", 2)
    kw.setdefault("pages_per_slot", 4)
    kw.setdefault("window", 4)
    kw.setdefault("prefill_buckets", [4, 8])
    replica = None
    cleanup = []
    if multi_replica:
        channel = fleet_mod.LocalChannel()
        mon = fleet_mod.FleetMonitor(
            channel=channel, host=0, n_hosts=2,
            slow_after_steps=2, dead_after_steps=4,
            slow_after_s=None, dead_after_s=None,
            agreement_timeout_s=0.2)
        sim = fleet_mod.SimulatedPeers(channel, hosts=[1]).attach(mon)
        replica = serving.ReplicaSet(mon).attach_simulation(sim)
        replica._channel_for_test = channel
        cleanup.append(mon.close)
    eng = serving.Engine(PARAMS, CFG, replica=replica, **kw)
    eng._cleanup_for_test = cleanup
    return eng


def close_engine(eng):
    eng.close()
    for fn in getattr(eng, "_cleanup_for_test", []):
        fn()


def _write_run(dirpath, host, records):
    os.makedirs(dirpath, exist_ok=True)
    with open(os.path.join(dirpath, "telemetry.jsonl"), "w") as f:
        f.write(json.dumps({"kind": "schema", "version": 2,
                            "host": host}) + "\n")
        for r in records:
            f.write(json.dumps(r) + "\n")


# ---------------------------------------------------------------------------
# histograms: bounded-error quantiles, associative merges, exposition
# ---------------------------------------------------------------------------

def test_hist_quantile_within_one_bucket_width():
    rng = random.Random(0)
    vals = [rng.lognormvariate(3.0, 1.5) for _ in range(500)]
    h = LatencyHistogram()
    for v in vals:
        h.observe(v)
    ordered = sorted(vals)
    for q in (0.5, 0.9, 0.99):
        exact = ordered[max(0, int(q * len(vals)) - 1)]
        est = h.quantile(q)
        width = h.bucket_width(exact)
        assert abs(est - exact) <= width + 1e-9, (q, est, exact, width)


def test_hist_quantile_edge_cases():
    h = LatencyHistogram()
    assert h.quantile(0.5) == 0.0          # empty: never fabricates
    h.observe(1e9)                         # past the scheme's range
    assert h.quantile(0.99) == DEFAULT_BOUNDS_MS[-1]   # clamps, floor


def test_hist_merge_associative_and_commutative():
    rng = random.Random(1)
    parts = []
    for _ in range(3):
        h = LatencyHistogram()
        for _ in range(50):
            h.observe(rng.uniform(0.1, 5000.0))
        parts.append(h.to_record("serving/e2e_ms"))

    def fold(order):
        return merge_records([parts[i] for i in order])

    a, b = fold([0, 1, 2]), fold([2, 0, 1])
    assert a.counts == b.counts
    assert a.count == b.count
    assert abs(a.sum - b.sum) < 1e-6
    # merging a fold-of-two with the third == folding all three
    ab = merge_records(parts[:2]).merge(
        LatencyHistogram.from_record(parts[2]))
    assert ab.counts == a.counts
    with pytest.raises(ValueError):
        LatencyHistogram(bounds=(1.0, 2.0)).merge(LatencyHistogram())


def test_hist_record_roundtrip():
    h = LatencyHistogram()
    for v in (0.3, 7.0, 120.0, 120.0):
        h.observe(v)
    rec = h.to_record("serving/ttft_ms", step=3)
    assert rec["kind"] == "hist" and rec["step"] == 3
    back = LatencyHistogram.from_record(rec)
    assert back.counts == h.counts and back.count == 4
    assert abs(back.sum - h.sum) < 1e-6


def test_hist_prometheus_exposition_well_formed():
    h = LatencyHistogram()
    for v in (0.2, 3.0, 50.0):
        h.observe(v)
    lines = prometheus_histogram_lines(
        "apex_tpu_serving_ttft_ms", h.to_record("serving/ttft_ms"))
    assert lines[0] == "# TYPE apex_tpu_serving_ttft_ms histogram"
    buckets = [ln for ln in lines if "_bucket{le=" in ln]
    cums = [int(ln.rsplit(" ", 1)[1]) for ln in buckets]
    assert cums == sorted(cums)            # CUMULATIVE, monotone
    assert buckets[-1].startswith(
        'apex_tpu_serving_ttft_ms_bucket{le="+Inf"}')
    assert cums[-1] == 3
    assert any(ln.startswith("apex_tpu_serving_ttft_ms_sum ")
               for ln in lines)
    assert "apex_tpu_serving_ttft_ms_count 3" in lines


def test_histogram_set_auto_names_and_nonempty_records():
    hs = HistogramSet()
    hs.observe("serving/ttft_ms", 12.0)
    hs.observe("custom/lat_ms", 1.0)       # unknown name auto-creates
    recs = hs.records(step=7)
    names = {r["name"] for r in recs}
    assert names == {"serving/ttft_ms", "custom/lat_ms"}  # empty skip
    assert all(r["step"] == 7 for r in recs)


# ---------------------------------------------------------------------------
# tracer: lifecycle assembly, gap detection, drain-open partials
# ---------------------------------------------------------------------------

def test_tracer_lifecycle_gap_free_and_latencies():
    tr = RequestTracer(host=0)
    tr.enqueue("r1", t=100.0)
    tr.admit("r1", window=1, slot=0, mode="prefill",
             queue_ms=500.0, t=100.5)
    tr.decode_window("r1", 1, 2, t=100.6)
    tr.decode_window("r1", 2, 2, drafted=2, accepted=1, t=100.7)
    rec = tr.verdict("r1", "completed", window=2, n_tokens=5, t=100.8)
    assert trace_gaps(rec) == []
    assert rec["ttft_ms"] == pytest.approx(500.0)
    assert rec["e2e_ms"] == pytest.approx(800.0)
    assert rec["queue_ms"] == pytest.approx(500.0)
    assert rec["host"] == 0 and rec["tokens"] == 5
    spec_ev = [e for e in rec["events"] if e.get("drafted")]
    assert spec_ev and spec_ev[0]["accepted"] == 1
    # the latencies landed in the streaming SLO histograms
    assert tr.slo.hist("serving/ttft_ms").count == 1
    assert tr.slo.hist("serving/e2e_ms").count == 1
    assert tr.slo.hist("serving/queue_ms").count == 1
    assert tr.hist_records(step=2)
    assert tr.open_ids() == []


def test_trace_gaps_detects_broken_lifecycles():
    tr = RequestTracer()
    # verdict with no open trace: a record still comes back, gapped
    rec = tr.verdict("ghost", "completed", n_tokens=3)
    gaps = trace_gaps(rec)
    assert "missing enqueue" in gaps
    assert "completed without admit" in gaps
    assert "tokens without admit" in gaps
    assert trace_gaps({"id": "x", "verdict": "nope", "events": [
        {"phase": "enqueue", "t": 1.0, "step": 0},
        {"phase": "verdict", "t": 2.0, "step": 0}]}) \
        == ["unknown verdict 'nope'"]
    assert "non-monotone timestamps" in trace_gaps(
        {"id": "x", "verdict": "completed", "events": [
            {"phase": "enqueue", "t": 5.0, "step": 0},
            {"phase": "admit", "t": 1.0, "step": 0},
            {"phase": "verdict", "t": 6.0, "step": 0}]})
    assert "decode windows not increasing" in trace_gaps(
        {"id": "x", "verdict": "completed", "events": [
            {"phase": "enqueue", "t": 1.0, "step": 0},
            {"phase": "admit", "t": 2.0, "step": 1},
            {"phase": "decode_window", "t": 3.0, "step": 2},
            {"phase": "decode_window", "t": 4.0, "step": 2},
            {"phase": "verdict", "t": 5.0, "step": 2}]})
    assert "verdict not last" in trace_gaps(
        {"id": "x", "verdict": "shed", "events": [
            {"phase": "enqueue", "t": 1.0, "step": 0},
            {"phase": "verdict", "t": 2.0, "step": 0},
            {"phase": "admit", "t": 3.0, "step": 0}]})


def test_tracer_drain_open_emits_partials():
    tr = RequestTracer(host=1)
    tr.enqueue("a", t=10.0)
    tr.enqueue("b", t=11.0)
    tr.admit("a", window=0, slot=0, mode="prefill",
             queue_ms=1.0, t=10.1)
    parts = tr.drain_open(window=3)
    assert [p["id"] for p in parts] == ["a", "b"]
    for p in parts:
        assert p["open"] is True and p["host"] == 1
        assert "verdict" not in p
        assert p["events"][0]["phase"] == "enqueue"
    assert tr.open_ids() == []
    # partials carry NO latency observations (no verdict happened)
    assert tr.slo.hist("serving/e2e_ms").count == 0


# ---------------------------------------------------------------------------
# timeline: request lanes, skew correction, cross-host failover
# ---------------------------------------------------------------------------

def test_request_lanes_cross_host_synthetic():
    from apex_tpu.telemetry import timeline as tl
    dead = RequestTracer(host=1)
    dead.enqueue("req", t=50.0)
    (partial,) = dead.drain_open(window=2)
    claim = RequestTracer(host=0)
    claim.enqueue("req", t=50.0, readmitted_from=1)
    claim.admit("req", window=5, slot=0, mode="prefill",
                queue_ms=2000.0, t=52.0)
    claim.decode_window("req", 5, 3, t=52.1)
    term = claim.verdict("req", "completed", window=6, n_tokens=3,
                         incident_id="inc-001-host_dead-h1.1-e0",
                         t=52.2)
    (lane,) = tl.request_lanes([partial, term])
    assert lane["hosts"] == [0, 1]          # ONE lane, both hosts
    assert lane["verdict"] == "completed"
    assert lane["verdict_host"] == 0
    assert lane["incident_id"] == "inc-001-host_dead-h1.1-e0"
    assert lane["readmitted_from"] == 1
    assert lane["t_start"] == pytest.approx(50.0)
    assert lane["t_end"] == pytest.approx(52.2)


def test_merge_run_dirs_corrects_nested_trace_stamps(tmp_path):
    from apex_tpu.telemetry import timeline as tl
    clock0 = [{"kind": "clock", "step": 0, "wall_time": 100.0},
              {"kind": "clock", "step": 10, "wall_time": 110.0}]
    clock1 = [{"kind": "clock", "step": 0, "wall_time": 105.0},
              {"kind": "clock", "step": 10, "wall_time": 115.0}]
    rec1 = {"kind": "reqtrace", "id": "r", "step": 4, "t": 107.0,
            "verdict": "completed", "tokens": 1, "host": 1,
            "enqueue_t": 106.0, "events": [
                {"phase": "enqueue", "t": 106.0, "step": 3},
                {"phase": "admit", "t": 106.5, "step": 4},
                {"phase": "verdict", "t": 107.0, "step": 4}]}
    _write_run(str(tmp_path / "h0"), 0, clock0)
    _write_run(str(tmp_path / "h1"), 1, clock1 + [rec1])
    merged = tl.merge_run_dirs([str(tmp_path / "h0"),
                                str(tmp_path / "h1")])
    assert merged["offsets"]["1"] == pytest.approx(5.0)
    (out,) = [r for r in merged["records"]
              if r.get("kind") == "reqtrace"]
    # host 1's clock runs 5s fast: every stamp — top-level, enqueue,
    # and each NESTED lifecycle event — lands on the reference clock
    assert out["t"] == pytest.approx(102.0)
    assert out["enqueue_t"] == pytest.approx(101.0)
    assert [e["t"] for e in out["events"]] == \
        pytest.approx([101.0, 101.5, 102.0])
    # the source record was not mutated by the correction
    assert rec1["events"][0]["t"] == pytest.approx(106.0)


# ---------------------------------------------------------------------------
# the engine end-to-end: chaos traces, failover lane, /metrics, bench
# ---------------------------------------------------------------------------

def test_chaos_hung_decode_traces_gap_free():
    eng = make_engine(decode_deadline_s=0.15)
    inj = FaultInjector(
        [FaultSpec("hung_decode", at_step=2, delay_s=0.5)]).install()
    try:
        eng.submit(serving.Request(id="healthy", prompt=[5, 6, 7],
                                   max_new_tokens=10))
        eng.step_window()
        eng.submit(serving.Request(id="suspect", prompt=[9, 10],
                                   max_new_tokens=10))
        res = eng.serve()
    finally:
        inj.uninstall()
    traces = {r["id"]: r for r in eng.tracer.records}
    close_engine(eng)
    # EVERY verdicted request has a gap-free trace — chaos included
    assert set(traces) == set(res)
    for rid, r in res.items():
        rec = traces[rid]
        assert rec["verdict"] == r.verdict
        assert trace_gaps(rec) == [], (rid, trace_gaps(rec))
    assert res["suspect"].verdict == adm.EVICTED
    assert traces["suspect"]["reason"] == adm.REASON_HUNG_DECODE
    assert traces["suspect"]["incident_id"] is not None
    # decode windows were recorded off the window read-back
    assert any(e["phase"] == "decode_window"
               for e in traces["healthy"]["events"])
    assert traces["healthy"]["ttft_ms"] >= 0
    assert eng.tracer.hist_records()


def test_failover_lane_spans_hosts_end_to_end(tmp_path):
    """The cross-host request lane, for real: the dead replica's
    queue ledger carries the ORIGINAL enqueue stamp, the claimant
    re-admits and completes, and the merged two-dir timeline renders
    one lane spanning both hosts under the failover incident id."""
    from apex_tpu.telemetry import timeline as tl
    t_orig = round(time.time() - 5.0, 6)
    eng = make_engine(multi_replica=True)
    eng.replica._channel_for_test.put(
        "serving_queue/1",
        {"host": 1, "requests": [
            {"id": "peer-a", "prompt": [7, 8], "max_new_tokens": 4,
             "enqueued_t": t_orig}]})
    inj = FaultInjector(
        [FaultSpec("replica_death", at_step=2, target=1)]).install()
    try:
        eng.submit(serving.Request(id="mine", prompt=[5],
                                   max_new_tokens=8))
        res = eng.serve(min_windows=12)
    finally:
        inj.uninstall()
    claimant_recs = list(eng.tracer.records)
    close_engine(eng)
    assert res["peer-a"].verdict == adm.COMPLETED
    term = {r["id"]: r for r in claimant_recs}["peer-a"]
    # the ledger stamp survived re-admission: the claimant's trace
    # starts at the DEAD host's submit time
    assert term["enqueue_t"] == pytest.approx(t_orig, abs=1e-3)
    assert term["readmitted_from"] == 1
    assert term["incident_id"] == "inc-001-host_dead-h1.1-e0"
    assert trace_gaps(term) == []

    # the dead host's shard: its engine died with the trace open
    dead = RequestTracer(host=1)
    dead.enqueue("peer-a", t=t_orig)
    dead_parts = dead.drain_open(window=1)
    _write_run(str(tmp_path / "h1"), 1, dead_parts)
    _write_run(str(tmp_path / "h0"), 0, claimant_recs)
    doc = tl.build([str(tmp_path / "h0"), str(tmp_path / "h1")])
    (lane,) = [ln for ln in doc["requests"] if ln["id"] == "peer-a"]
    assert lane["hosts"] == [0, 1]
    assert lane["verdict"] == "completed"
    assert lane["verdict_host"] == 0
    assert lane["incident_id"] == "inc-001-host_dead-h1.1-e0"
    # ...and the chrome trace opens the async lane on the dead host's
    # pid and closes it on the claimant's
    events = tl.chrome_trace(doc)["traceEvents"]
    req = [e for e in events if e.get("cat") == "request"
           and e.get("id") == "peer-a"]
    phases = {e["ph"]: e for e in req}
    assert set(phases) == {"b", "n", "e"}
    assert phases["b"]["pid"] == 1 and phases["e"]["pid"] == 0


def test_metrics_server_renders_histograms_and_trace_counters():
    from apex_tpu.telemetry.export import MetricsServer
    h = LatencyHistogram()
    for v in (1.0, 8.0, 300.0):
        h.observe(v)
    tr = RequestTracer(host=0)
    tr.enqueue("q", t=1.0)
    tr.admit("q", window=0, slot=0, mode="prefill",
             queue_ms=0.5, t=1.01)
    rec = tr.verdict("q", "completed", n_tokens=2, t=1.05)
    srv = MetricsServer(port=0)
    try:
        srv.emit([h.to_record("serving/ttft_ms", step=1), rec])
        # a NEWER cumulative snapshot replaces, never double-counts
        h.observe(9000.0)
        srv.emit([h.to_record("serving/ttft_ms", step=2)])
        body = srv.render()
    finally:
        srv.close()
    assert "# TYPE apex_tpu_serving_ttft_ms histogram" in body
    assert 'apex_tpu_serving_ttft_ms_bucket{le="+Inf"} 4' in body
    assert "apex_tpu_serving_ttft_ms_count 4" in body
    assert "apex_tpu_reqtrace_completed_events_total 1" in body


def test_summarize_renders_slo_table(tmp_path, capsys):
    from apex_tpu.telemetry.cli import main as telemetry_cli
    tr = RequestTracer(host=0)
    for i, t0 in enumerate((100.0, 100.2)):
        rid = f"r{i}"
        tr.enqueue(rid, t=t0)
        tr.admit(rid, window=0, slot=i, mode="prefill",
                 queue_ms=40.0, t=t0 + 0.04)
        tr.decode_window(rid, 1, 3, t=t0 + 0.1)
        tr.verdict(rid, "completed", window=1, n_tokens=3,
                   t=t0 + 0.2)
    run = str(tmp_path / "run")
    _write_run(run, 0, list(tr.records) + tr.hist_records(step=1))
    assert telemetry_cli(["summarize", run]) == 0
    out = capsys.readouterr().out
    assert "serving SLO: 2 request(s), 6 token(s)" in out
    assert "completed" in out
    assert "ttft_ms" in out and "p99_ms" in out
    # --json carries the same section structurally
    assert telemetry_cli(["summarize", run, "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["serving"]["requests"] == 2
    assert doc["serving"]["verdicts"] == {"completed": 2}
    assert doc["serving"]["latency_ms"]["serving/ttft_ms"]["count"] == 2


def test_bench_reqtrace_overhead_smoke():
    """The kernel_bench ``reqtrace_overhead`` row's harness, tiny:
    tracing must not perturb the token stream (bit-exact oracle); the
    ratio itself is wall-clock noise on CPU, so only sanity-check it."""
    from apex_tpu.serving.bench import bench_reqtrace_overhead
    r = bench_reqtrace_overhead(n_requests=2, n_layers=1, hidden=16,
                                n_heads=2, page_size=4,
                                pages_per_slot=2, window=2,
                                max_new_tokens=3)
    assert r["reqtrace_on_ms"] > 0 and r["reqtrace_off_ms"] > 0
    assert r["reqtrace_traces"] == 2
    assert r["reqtrace_bit_exact"] == 1
