"""Pure-logic tests for the hardware kernel-bench distillers: the
pieces that turn measured timings into committed dispatch defaults
(dispatch_prefs.json) must be right BEFORE a scarce tunnel window runs
them (the sweep executes unattended inside tools/run_tpu_validation.sh)."""

import importlib.util
import json
import os


def _load_path(name, path):
    spec = importlib.util.spec_from_file_location(name, path)
    m = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(m)
    return m


_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_tool(name):
    return _load_path(name, os.path.join(_ROOT, "tools", f"{name}.py"))


kb = _load_tool("kernel_bench")
osv = _load_tool("one_session_validation")
ps = _load_tool("profile_step")


def _load_bench():
    return _load_path("bench_mod", os.path.join(_ROOT, "bench.py"))


class TestSelectAttnCaps:
    def test_lowest_mean_relative_time_wins(self):
        caps = kb.select_attn_caps({
            (128, 128): [1.5, 1.2],
            (128, 256): [1.0, 1.1],
            (128, 512): [1.3, 1.0],
        })
        assert caps == {"128": 256}

    def test_partial_sample_cannot_win(self):
        # cap 1024 was only feasible on the long-sequence shape and won
        # there, but must not become the tier default on one sample
        caps = kb.select_attn_caps({
            (128, 256): [1.0, 1.0],
            (128, 512): [1.1, 1.2],
            (128, 1024): [0.8],
        })
        assert caps == {"128": 256}

    def test_per_dp_winners_are_independent(self):
        caps = kb.select_attn_caps({
            (128, 256): [1.0],
            (128, 512): [1.4],
            (256, 128): [1.0],
            (256, 512): [1.6],
        })
        assert caps == {"128": 256, "256": 128}

    def test_empty(self):
        assert kb.select_attn_caps({}) == {}


class TestWritePrefs:
    def test_merge_preserves_attn_caps(self, tmp_path):
        p = tmp_path / "prefs.json"
        p.write_text(json.dumps({"methodology": "amortized",
                                 "attn_block_cap": {"128": 256}}))
        rows = [
            {"kernel": "fused_layer_norm", "speedup": 1.3, "backend": "tpu"},
            {"kernel": "fused_layer_norm_grad", "speedup": 1.1,
             "backend": "tpu"},
            {"kernel": "flash_attention", "speedup": 0.9, "backend": "tpu"},
        ]
        prefs = kb.write_prefs(rows, str(p))
        doc = json.loads(p.read_text())
        assert doc["attn_block_cap"] == {"128": 256}
        assert doc["prefer_pallas"] == prefs == {
            "layer_norm": True, "attention": False}
        assert doc["backend"] == "tpu"
        # the stamp that lets _load_prefs trust this table's routing
        assert doc["methodology"] == "amortized"

    def test_any_slower_shape_flips_family_to_xla(self, tmp_path):
        p = tmp_path / "prefs.json"
        rows = [
            {"kernel": "flash_attention", "speedup": 1.5, "backend": "tpu"},
            {"kernel": "flash_attention_grad", "speedup": 0.95,
             "backend": "tpu"},
        ]
        assert kb.write_prefs(rows, str(p)) == {"attention": False}

    def test_stale_era_tables_not_laundered(self, tmp_path):
        # read-modify-write + a whole-file methodology stamp must not
        # re-bless the OTHER table's dispatch-per-iteration data: a
        # prefs-only run drops the old caps, a sweep-only merge (via
        # _load_trusted_doc) drops the old routing
        p = tmp_path / "prefs.json"
        p.write_text(json.dumps({
            "methodology": "dispatch-per-iteration",
            "prefer_pallas": {"attention": False},
            "attn_block_cap": {"128": 256}}))
        kb.write_prefs([{"kernel": "welford_mean_var", "speedup": 1.2,
                         "backend": "tpu"}], str(p))
        doc = json.loads(p.read_text())
        assert doc["methodology"] == "amortized"
        assert "attn_block_cap" not in doc       # stale caps dropped
        assert doc["prefer_pallas"] == {"welford": True}

        p.write_text(json.dumps({
            "methodology": "dispatch-per-iteration",
            "prefer_pallas": {"attention": False},
            "attn_block_cap": {"128": 256}}))
        doc = kb._load_trusted_doc(str(p))
        assert "prefer_pallas" not in doc
        assert "attn_block_cap" not in doc

        # an amortized-era doc survives the merge intact
        p.write_text(json.dumps({
            "methodology": "amortized",
            "attn_block_cap": {"128": 512}}))
        kb.write_prefs([{"kernel": "welford_mean_var", "speedup": 1.2,
                         "backend": "tpu"}], str(p))
        assert json.loads(p.read_text())["attn_block_cap"] == {
            "128": 512}

    def test_topology_and_noise_metadata(self, tmp_path):
        """--write-prefs records WHERE (topology block) and HOW
        REPEATABLY (noise floor) the table was measured, making
        hand-run bench output schema-compatible with autotune's
        per-topology tables — and topology-checked at load."""
        at = _load_tool("autotune")
        p = tmp_path / "prefs.json"
        topo = {"key": "tpu_v5e-8", "device_kind": "TPU v5e",
                "device_count": 8, "process_count": 2}
        rows = [{"kernel": "welford_mean_var", "speedup": 1.2,
                 "backend": "tpu"}]
        kb.write_prefs(rows, str(p), topology=topo,
                       noise_floor_pct=3.456)
        doc = json.loads(p.read_text())
        assert doc["topology"] == topo
        assert doc["schema"] == 2
        assert doc["noise_floor_pct"] == 3.46
        # the written table passes the check.sh schema validator
        assert at.validate_table(doc, per_topology=False) == []
        # legacy call shape (no metadata) stays valid and stamp-free
        kb.write_prefs(rows, str(p.with_name("p2.json")))
        doc2 = json.loads(p.with_name("p2.json").read_text())
        assert "topology" not in doc2 and "noise_floor_pct" not in doc2

    def test_stale_era_doc_strips_topology_metadata(self, tmp_path):
        """_load_trusted_doc must not launder a stale-era table's
        topology/noise stamps into the fresh doc (they describe the
        discarded measurements, not the new ones)."""
        p = tmp_path / "prefs.json"
        p.write_text(json.dumps({
            "methodology": "dispatch-per-iteration",
            "topology": {"key": "tpu_v4-8"}, "schema": 2,
            "noise_floor_pct": 1.0,
            "pipeline": {"reduce_decompose": "reduce_scatter"}}))
        doc = kb._load_trusted_doc(str(p))
        for k in ("topology", "schema", "noise_floor_pct", "pipeline"):
            assert k not in doc, k

    def test_corrupt_existing_file_does_not_abort(self, tmp_path):
        p = tmp_path / "prefs.json"
        p.write_text("{truncated")
        rows = [{"kernel": "welford_mean_var", "speedup": 2.0,
                 "backend": "tpu"}]
        assert kb.write_prefs(rows, str(p)) == {"welford": True}
        assert json.loads(p.read_text())["prefer_pallas"] == {
            "welford": True}

    def test_discarded_stale_table_warns(self, tmp_path, monkeypatch):
        """A prefs table dropped for lacking the amortized stamp must
        say so: silence here hid a stale-benchmark misconfiguration
        (the operator believes measured routing is active when the
        design default is)."""
        import pytest
        from apex_tpu.ops import _dispatch
        p = tmp_path / "prefs.json"
        p.write_text(json.dumps({
            "methodology": "dispatch-per-iteration",
            "prefer_pallas": {"softmax": False}}))
        monkeypatch.setattr(_dispatch, "_PREFS_PATH", str(p))
        with pytest.warns(RuntimeWarning, match="IGNORED"):
            assert _dispatch._load_prefs() == ({}, {})

    def test_absent_or_trusted_table_stays_silent(self, tmp_path,
                                                  monkeypatch):
        """Only the DISCARD warns: a missing file and an amortized
        table are both healthy states."""
        import warnings
        from apex_tpu.ops import _dispatch
        with warnings.catch_warnings():
            warnings.simplefilter("error")
            monkeypatch.setattr(_dispatch, "_PREFS_PATH",
                                str(tmp_path / "absent.json"))
            assert _dispatch._load_prefs() == ({}, {})
            good = tmp_path / "good.json"
            good.write_text(json.dumps({
                "methodology": "amortized",
                "prefer_pallas": {"softmax": False}}))
            monkeypatch.setattr(_dispatch, "_PREFS_PATH", str(good))
            assert _dispatch._load_prefs() == ({"softmax": False}, {})


class TestRelayDeathWatchdogParser:
    """The validator's mid-session relay-death detector keys off the
    same ss -tln listener parse as tunnel_watch.sh; a parse bug either
    hard-exits a healthy session (false death) or leaves the next
    window blocked behind a wedged client (missed death)."""

    HEADER = "State  Recv-Q Send-Q Local Address:Port  Peer Address:Port\n"

    def test_relay_ports_count_as_alive(self):
        txt = (self.HEADER
               + "LISTEN 0 64 127.0.0.1:8117 0.0.0.0:*\n"
               + "LISTEN 0 128 0.0.0.0:2024 0.0.0.0:*\n")
        assert osv._has_nonbaseline_listener(txt)

    def test_baseline_only_means_dead(self):
        txt = (self.HEADER
               + "LISTEN 0 128 0.0.0.0:2024 0.0.0.0:*\n"
               + "LISTEN 0 1024 127.0.0.1:48271 0.0.0.0:*\n")
        assert not osv._has_nonbaseline_listener(txt)

    def test_empty_and_header_only_mean_dead(self):
        assert not osv._has_nonbaseline_listener("")
        assert not osv._has_nonbaseline_listener(self.HEADER)

    def test_port_suffix_collision_not_excluded(self):
        # 127.0.0.1:12024 must NOT match the :2024 baseline anchor
        txt = self.HEADER + "LISTEN 0 64 127.0.0.1:12024 0.0.0.0:*\n"
        assert osv._has_nonbaseline_listener(txt)

    def test_port_set_for_armtime_snapshot(self):
        # the watchdog keys death to the ports seen at arm time; the
        # parser must return the SET, and known infra listeners (sshd
        # :22) must be excluded up front — inside the arm set they
        # would block the death verdict for the whole session
        txt = (self.HEADER
               + "LISTEN 0 64 127.0.0.1:8117 0.0.0.0:*\n"
               + "LISTEN 0 64 127.0.0.1:9001 0.0.0.0:*\n"
               + "LISTEN 0 64 0.0.0.0:22 0.0.0.0:*\n"
               + "LISTEN 0 128 0.0.0.0:2024 0.0.0.0:*\n")
        assert osv._nonbaseline_ports(txt) == {8117, 9001}
        # arm-time {8117, 9001} vs current {9001}: one relay port
        # still up -> intersection nonempty -> alive (conservative);
        # current {9999} (all arm-time ports gone, new relay's port
        # up) -> dead, freeing the watcher to fire at the new relay
        armed = osv._nonbaseline_ports(txt)
        assert armed & osv._nonbaseline_ports(
            self.HEADER + "LISTEN 0 64 127.0.0.1:9001 0.0.0.0:*\n")
        assert not (armed & osv._nonbaseline_ports(
            self.HEADER + "LISTEN 0 64 127.0.0.1:9999 0.0.0.0:*\n"))


class TestTraceOpSummarizer:
    """profile_step.summarize_device_ops distills the profiler's
    Chrome trace into the top-device-ops table; it must aggregate ONLY
    the device XLA-Ops thread (the round-4 capture had 998909 host
    python events vs 434 device ops — counting hosts would bury the
    signal it exists to surface)."""

    def _write_trace(self, tmp_path, events):
        import gzip
        d = tmp_path / "plugins" / "profile" / "2026_01_01"
        d.mkdir(parents=True)
        with gzip.open(d / "vm.trace.json.gz", "wt") as f:
            json.dump({"traceEvents": events}, f)
        return str(tmp_path)

    def test_aggregates_device_ops_only(self, tmp_path):
        events = [
            {"ph": "M", "pid": 3, "name": "process_name",
             "args": {"name": "/device:TPU:0"}},
            {"ph": "M", "pid": 3, "tid": 7, "name": "thread_name",
             "args": {"name": "XLA Ops"}},
            {"ph": "M", "pid": 9, "name": "process_name",
             "args": {"name": "/host:CPU"}},
            {"ph": "M", "pid": 9, "tid": 1, "name": "thread_name",
             "args": {"name": "python"}},
            # device ops: fusion.1 twice (3ms), conv once (1ms)
            {"ph": "X", "pid": 3, "tid": 7, "name": "fusion.1",
             "dur": 2000},
            {"ph": "X", "pid": 3, "tid": 7, "name": "fusion.1",
             "dur": 1000},
            {"ph": "X", "pid": 3, "tid": 7, "name": "conv", "dur": 1000},
            # host noise that must NOT count
            {"ph": "X", "pid": 9, "tid": 1, "name": "python_call",
             "dur": 999999},
            # device process, non-op thread must not count either
            {"ph": "X", "pid": 3, "tid": 8, "name": "Steps",
             "dur": 888888},
        ]
        rows = ps.summarize_device_ops(self._write_trace(tmp_path,
                                                         events))
        assert rows == [["fusion.1", 3.0, 75.0], ["conv", 1.0, 25.0]]

    def test_empty_or_missing_trace(self, tmp_path):
        assert ps.summarize_device_ops(str(tmp_path)) == []
        rows = ps.summarize_device_ops(self._write_trace(
            tmp_path, [{"ph": "M", "pid": 3, "name": "process_name",
                        "args": {"name": "/device:TPU:0"}}]))
        assert rows == []


def test_run_test_suite_map_covers_every_test_file():
    """The reference-shaped suite driver (tests/run_test.py) maps suite
    names onto pytest files; a new test module left out of the map is
    silently skipped by `--include`-style invocations."""
    import glob

    rt = _load_path("run_test_mod",
                    os.path.join(_ROOT, "tests", "run_test.py"))
    mapped = {f for fs in rt.SUITES.values() for f in fs}
    have = {"tests/" + os.path.basename(p)
            for p in glob.glob(os.path.join(_ROOT, "tests",
                                            "test_*.py"))}
    assert have <= mapped, f"unmapped test files: {sorted(have - mapped)}"
    # and no dangling entries: a renamed module must not leave a map
    # entry pytest would abort on
    assert mapped <= have, f"stale suite entries: {sorted(mapped - have)}"


class TestBertPackedVarlenBench:
    """The packed-vs-dense varlen extra must run end to end on a tiny
    model before it spends window time: both legs train, the real-token
    accounting is consistent, and packed fits more real tokens into
    the same device batch."""

    def test_tiny_cpu(self):
        import jax
        import jax.numpy as jnp

        bench = _load_bench()

        from apex_tpu.models.bert import BertModel
        tiny = BertModel(vocab_size=128, hidden_size=32, num_heads=4,
                         num_layers=1, max_seq_len=64,
                         dtype=jnp.float32)
        out = bench.bench_bert_packed_varlen(
            jax, jnp, model=tiny, rows=2, seq=64, steps=2, chunk=2)
        for k in ("bert_varlen_packed_step_ms",
                  "bert_varlen_dense_step_ms",
                  "bert_varlen_packed_real_tokens_per_sec",
                  "bert_varlen_dense_real_tokens_per_sec",
                  "bert_varlen_packed_speedup"):
            assert k in out and out[k] > 0, (k, out)


def test_bench_final_line_carries_measured_at():
    """The child's final bench line must stamp its capture time:
    perf_gate's auto-gating compares it against the budget's
    stamped_at, so a live hardware round without it could NEVER arm
    the gate (it would fall into the 'cannot compare' report-only
    branch forever)."""
    import re

    bench = _load_bench()
    pg = _load_tool("perf_gate")
    out = bench._stamp_measured_at({"backend": "tpu", "value": 1.0})
    assert re.fullmatch(r"\d{4}-\d{2}-\d{2}T\d{2}:\d{2}:\d{2}Z",
                        out["measured_at"])
    # ...and perf_gate reads exactly this field
    assert pg.round_when(out) == out["measured_at"]
    # an existing stamp (a re-emitted cached line) is preserved
    assert bench._stamp_measured_at(
        {"measured_at": "2026-07-31T03:41:18Z"})["measured_at"] \
        == "2026-07-31T03:41:18Z"


class TestCachedTpuResult:
    """bench.py's report-time fallback ladder serves the recorded
    hardware window when the tunnel is down; a bug here either loses a
    real measurement or re-labels a CPU line as hardware."""

    def test_contract(self, tmp_path):
        bench = _load_bench()

        p = tmp_path / "bench_tpu.json"
        # clean TPU line with embedded capture time and a long error
        p.write_text(json.dumps({
            "metric": "m", "value": 2108.2, "backend": "tpu",
            "measured_at": "2026-07-31T03:41:18Z",
            "errors": ["x" * 500], "extra": {}}))
        c = bench._cached_tpu_result(str(p))
        assert c["backend"] == "tpu-cached"
        assert c["extra"]["cached_measured_at"] == "2026-07-31T03:41:18Z"
        assert "measured_at" not in c            # moved into extra
        # stubbed AND marked as the capture session's, not this run's
        assert c["errors"][0] == "captured: " + "x" * 150

        # non-TPU or zero-valued lines never qualify
        p.write_text(json.dumps({"metric": "m", "value": 1.5,
                                 "backend": "cpu-fallback"}))
        assert bench._cached_tpu_result(str(p)) is None
        p.write_text(json.dumps({"metric": "m", "value": 0,
                                 "backend": "tpu"}))
        assert bench._cached_tpu_result(str(p)) is None
        # missing / unparseable files resolve to None, never raise
        assert bench._cached_tpu_result(str(tmp_path / "no.json")) is None
        p.write_text("{not json")
        assert bench._cached_tpu_result(str(p)) is None
