"""Pure-logic tests for the hardware kernel-bench distillers: the
pieces that turn measured timings into committed dispatch defaults
(dispatch_prefs.json) must be right BEFORE a scarce tunnel window runs
them (the sweep executes unattended inside tools/run_tpu_validation.sh)."""

import importlib.util
import json
import os

_spec = importlib.util.spec_from_file_location(
    "kernel_bench",
    os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                 "tools", "kernel_bench.py"))
kb = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(kb)

_ospec = importlib.util.spec_from_file_location(
    "one_session_validation",
    os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                 "tools", "one_session_validation.py"))
osv = importlib.util.module_from_spec(_ospec)
_ospec.loader.exec_module(osv)


class TestSelectAttnCaps:
    def test_lowest_mean_relative_time_wins(self):
        caps = kb.select_attn_caps({
            (128, 128): [1.5, 1.2],
            (128, 256): [1.0, 1.1],
            (128, 512): [1.3, 1.0],
        })
        assert caps == {"128": 256}

    def test_partial_sample_cannot_win(self):
        # cap 1024 was only feasible on the long-sequence shape and won
        # there, but must not become the tier default on one sample
        caps = kb.select_attn_caps({
            (128, 256): [1.0, 1.0],
            (128, 512): [1.1, 1.2],
            (128, 1024): [0.8],
        })
        assert caps == {"128": 256}

    def test_per_dp_winners_are_independent(self):
        caps = kb.select_attn_caps({
            (128, 256): [1.0],
            (128, 512): [1.4],
            (256, 128): [1.0],
            (256, 512): [1.6],
        })
        assert caps == {"128": 256, "256": 128}

    def test_empty(self):
        assert kb.select_attn_caps({}) == {}


class TestWritePrefs:
    def test_merge_preserves_attn_caps(self, tmp_path):
        p = tmp_path / "prefs.json"
        p.write_text(json.dumps({"attn_block_cap": {"128": 256}}))
        rows = [
            {"kernel": "fused_layer_norm", "speedup": 1.3, "backend": "tpu"},
            {"kernel": "fused_layer_norm_grad", "speedup": 1.1,
             "backend": "tpu"},
            {"kernel": "flash_attention", "speedup": 0.9, "backend": "tpu"},
        ]
        prefs = kb.write_prefs(rows, str(p))
        doc = json.loads(p.read_text())
        assert doc["attn_block_cap"] == {"128": 256}
        assert doc["prefer_pallas"] == prefs == {
            "layer_norm": True, "attention": False}
        assert doc["backend"] == "tpu"

    def test_any_slower_shape_flips_family_to_xla(self, tmp_path):
        p = tmp_path / "prefs.json"
        rows = [
            {"kernel": "flash_attention", "speedup": 1.5, "backend": "tpu"},
            {"kernel": "flash_attention_grad", "speedup": 0.95,
             "backend": "tpu"},
        ]
        assert kb.write_prefs(rows, str(p)) == {"attention": False}

    def test_corrupt_existing_file_does_not_abort(self, tmp_path):
        p = tmp_path / "prefs.json"
        p.write_text("{truncated")
        rows = [{"kernel": "welford_mean_var", "speedup": 2.0,
                 "backend": "tpu"}]
        assert kb.write_prefs(rows, str(p)) == {"welford": True}
        assert json.loads(p.read_text())["prefer_pallas"] == {
            "welford": True}


class TestRelayDeathWatchdogParser:
    """The validator's mid-session relay-death detector keys off the
    same ss -tln listener parse as tunnel_watch.sh; a parse bug either
    hard-exits a healthy session (false death) or leaves the next
    window blocked behind a wedged client (missed death)."""

    HEADER = "State  Recv-Q Send-Q Local Address:Port  Peer Address:Port\n"

    def test_relay_ports_count_as_alive(self):
        txt = (self.HEADER
               + "LISTEN 0 64 127.0.0.1:8117 0.0.0.0:*\n"
               + "LISTEN 0 128 0.0.0.0:2024 0.0.0.0:*\n")
        assert osv._has_nonbaseline_listener(txt)

    def test_baseline_only_means_dead(self):
        txt = (self.HEADER
               + "LISTEN 0 128 0.0.0.0:2024 0.0.0.0:*\n"
               + "LISTEN 0 1024 127.0.0.1:48271 0.0.0.0:*\n")
        assert not osv._has_nonbaseline_listener(txt)

    def test_empty_and_header_only_mean_dead(self):
        assert not osv._has_nonbaseline_listener("")
        assert not osv._has_nonbaseline_listener(self.HEADER)

    def test_port_suffix_collision_not_excluded(self):
        # 127.0.0.1:12024 must NOT match the :2024 baseline anchor
        txt = self.HEADER + "LISTEN 0 64 127.0.0.1:12024 0.0.0.0:*\n"
        assert osv._has_nonbaseline_listener(txt)
