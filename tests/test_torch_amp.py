"""torch-facing amp shim (apex_tpu.torch_compat.amp) vs plain torch.

The reference's public contract is torch-facing (`import apex;
amp.initialize(...)`, SURVEY.md §0) and its pure-Python install runs
amp with no extensions at all — BASELINE config 1.  These tests mirror
the reference L0 run_amp pattern: train small torch models on CPU
through the shim, oracle = the same model trained in plain fp32.
"""

import numpy as np
import pytest

torch = pytest.importorskip("torch")

import torch.nn as nn  # noqa: E402

from apex_tpu.torch_compat import amp  # noqa: E402


@pytest.fixture(autouse=True)
def _clean_amp():
    yield
    amp.deinitialize()


def _tiny_model(seed=0, bn=False):
    torch.manual_seed(seed)
    layers = [nn.Conv2d(3, 8, 3, padding=1)]
    if bn:
        layers.append(nn.BatchNorm2d(8))
    layers += [nn.ReLU(), nn.Flatten(), nn.Linear(8 * 8 * 8, 10)]
    return nn.Sequential(*layers)


def _batch(seed=1):
    g = torch.Generator().manual_seed(seed)
    x = torch.randn(4, 3, 8, 8, generator=g)
    y = torch.randint(0, 10, (4,), generator=g)
    return x, y


def _train(model, optimizer, steps=5, use_amp=True):
    losses = []
    crit = nn.CrossEntropyLoss()
    for _ in range(steps):
        x, y = _batch()
        optimizer.zero_grad()
        loss = crit(model(x).float(), y)
        if use_amp:
            with amp.scale_loss(loss, optimizer) as scaled:
                scaled.backward()
        else:
            loss.backward()
        optimizer.step()
        losses.append(float(loss.detach()))
    return losses


def test_o0_matches_plain_fp32_exactly():
    """O0 is a no-op: identical trajectory to untouched torch."""
    m_ref = _tiny_model()
    o_ref = torch.optim.SGD(m_ref.parameters(), lr=0.1)
    ref = _train(m_ref, o_ref, use_amp=False)

    m = _tiny_model()
    o = torch.optim.SGD(m.parameters(), lr=0.1)
    m, o = amp.initialize(m, o, opt_level="O0")
    got = _train(m, o)
    np.testing.assert_allclose(got, ref, rtol=0, atol=0)


@pytest.mark.parametrize("opt_level", ["O1", "O2", "O3"])
def test_levels_train_close_to_fp32(opt_level):
    """Mixed-precision trajectories track the fp32 oracle (reference
    L1 tier semantics: training-dynamics equivalence, not exact
    numerics)."""
    m_ref = _tiny_model(bn=(opt_level == "O2"))
    o_ref = torch.optim.SGD(m_ref.parameters(), lr=0.05)
    ref = _train(m_ref, o_ref, use_amp=False)

    m = _tiny_model(bn=(opt_level == "O2"))
    o = torch.optim.SGD(m.parameters(), lr=0.05)
    m, o = amp.initialize(m, o, opt_level=opt_level)
    got = _train(m, o)
    assert got[-1] < got[0]                      # it learns
    np.testing.assert_allclose(got, ref, rtol=0.15, atol=0.15)


def test_o2_model_is_half_bn_is_fp32():
    m = _tiny_model(bn=True)
    o = torch.optim.SGD(m.parameters(), lr=0.1)
    m, o = amp.initialize(m, o, opt_level="O2")
    assert m[0].weight.dtype == torch.bfloat16    # conv cast
    assert m[1].weight.dtype == torch.float32     # BN kept fp32
    assert m[1].running_mean.dtype == torch.float32
    # masters: the optimizer steps fp32 copies of the half params
    masters = list(amp.master_params(o))
    assert all(p.dtype == torch.float32 for p in masters)
    # fp32 inputs are cast at forward; output comes back half
    out = m(torch.randn(2, 3, 8, 8))
    assert out.dtype == torch.bfloat16


def test_o2_master_weights_stay_synced():
    m = _tiny_model()
    o = torch.optim.SGD(m.parameters(), lr=0.1)
    m, o = amp.initialize(m, o, opt_level="O2")
    _train(m, o, steps=3)
    for master, model_p in o._amp_masters:
        np.testing.assert_allclose(
            model_p.detach().float().numpy(),
            master.detach().to(model_p.dtype).float().numpy())


def test_dynamic_scaler_backs_off_on_inf_then_recovers():
    m = _tiny_model()
    o = torch.optim.SGD(m.parameters(), lr=0.1)
    m, o = amp.initialize(m, o, opt_level="O2")
    x, y = _batch()
    crit = nn.CrossEntropyLoss()
    scaler = amp._amp_state.loss_scalers[0]
    s0 = scaler.loss_scale()

    o.zero_grad()
    loss = crit(m(x).float(), y)
    with amp.scale_loss(loss, o) as scaled:
        scaled.backward()
        # poison a MODEL grad (where backward deposits) before the
        # context exit runs the unscale/overflow pass
        next(iter(m.parameters())).grad[0] = float("inf")
    o.step()
    assert scaler.loss_scale() == s0 / 2         # backoff

    o.zero_grad()
    loss = crit(m(x).float(), y)
    with amp.scale_loss(loss, o) as scaled:
        scaled.backward()
    o.step()
    assert scaler.loss_scale() == s0 / 2         # clean: no growth yet
    assert scaler._unskipped == 1


def test_skipped_step_leaves_params_untouched():
    m = _tiny_model()
    o = torch.optim.SGD(m.parameters(), lr=0.1)
    m, o = amp.initialize(m, o, opt_level="O2")
    x, y = _batch()
    crit = nn.CrossEntropyLoss()
    o.zero_grad()
    loss = crit(m(x).float(), y)
    with amp.scale_loss(loss, o) as scaled:
        scaled.backward()
        next(iter(m.parameters())).grad[0] = float("nan")
    snap = [p.detach().clone() for p in amp.master_params(o)]
    model_snap = [p.detach().clone() for p in m.parameters()]
    o.step()
    for p, s in zip(amp.master_params(o), snap):
        assert torch.equal(p.detach(), s)
    for p, s in zip(m.parameters(), model_snap):
        assert torch.equal(p.detach(), s)


def test_scaler_grows_after_window():
    m = _tiny_model()
    o = torch.optim.SGD(m.parameters(), lr=0.01)
    m, o = amp.initialize(m, o, opt_level="O1")
    scaler = amp._amp_state.loss_scalers[0]
    scaler._window = 3                           # shrink for the test
    s0 = scaler.loss_scale()
    _train(m, o, steps=3)
    assert scaler.loss_scale() == s0 * 2


def test_num_losses_gives_independent_scalers():
    """Reference: initialize(..., num_losses=N) + scale_loss(loss_id=i)
    — an overflow on one loss must not back off the other's scale."""
    m = _tiny_model()
    o = torch.optim.SGD(m.parameters(), lr=0.1)
    m, o = amp.initialize(m, o, opt_level="O2", num_losses=2)
    assert len(amp._amp_state.loss_scalers) == 2
    x, y = _batch()
    crit = nn.CrossEntropyLoss()
    s0 = amp._amp_state.loss_scalers[0].loss_scale()

    o.zero_grad()
    loss = crit(m(x).float(), y)
    with amp.scale_loss(loss, o, loss_id=0) as scaled:
        scaled.backward()
        next(iter(m.parameters())).grad[0] = float("inf")
    assert amp._amp_state.loss_scalers[0].loss_scale() == s0 / 2
    assert amp._amp_state.loss_scalers[1].loss_scale() == s0


def test_state_dict_roundtrip():
    m = _tiny_model()
    o = torch.optim.SGD(m.parameters(), lr=0.1)
    m, o = amp.initialize(m, o, opt_level="O2")
    amp._amp_state.loss_scalers[0]._scale = 1024.0
    amp._amp_state.loss_scalers[0]._unskipped = 7
    sd = amp.state_dict()

    amp.deinitialize()
    m2 = _tiny_model()
    o2 = torch.optim.SGD(m2.parameters(), lr=0.1)
    amp.initialize(m2, o2, opt_level="O2")
    amp.load_state_dict(sd)
    assert amp._amp_state.loss_scalers[0].loss_scale() == 1024.0
    assert amp._amp_state.loss_scalers[0]._unskipped == 7


def test_o1_patches_and_deinitialize_restores():
    import torch.nn.functional as F
    orig_linear = F.linear
    m = _tiny_model()
    o = torch.optim.SGD(m.parameters(), lr=0.1)
    amp.initialize(m, o, opt_level="O1")
    assert hasattr(F.linear, "_amp_original")
    # GEMM runs half under the patch (model params stay fp32)
    out = m(torch.randn(2, 3, 8, 8))
    assert out.dtype == torch.bfloat16
    assert m[0].weight.dtype == torch.float32
    # fp32-list ops come back fp32 even on half inputs
    sm = F.softmax(torch.randn(4, 4, dtype=torch.bfloat16), dim=-1)
    assert sm.dtype == torch.float32
    amp.deinitialize()
    assert F.linear is orig_linear


def test_double_initialize_is_a_fresh_init():
    """A second initialize on the same model/optimizer must undo the
    first (a naive second _process_optimizer pass would orphan the
    masters and silently stop training)."""
    m = _tiny_model()
    o = torch.optim.SGD(m.parameters(), lr=0.1)
    m, o = amp.initialize(m, o, opt_level="O2")
    with pytest.warns(UserWarning, match="twice"):
        m, o = amp.initialize(m, o, opt_level="O2")
    assert len(o._amp_masters) > 0               # masters re-wired
    losses = _train(m, o, steps=3)
    assert losses[-1] < losses[0]                # still learns
    for master, model_p in o._amp_masters:
        np.testing.assert_allclose(
            model_p.detach().float().numpy(),
            master.detach().to(model_p.dtype).float().numpy())


def test_reference_kwargs_accepted():
    """apex example code ported verbatim uses verbosity / enabled /
    min_loss_scale / max_loss_scale / cast_model_outputs — they must
    work, not TypeError."""
    m = _tiny_model()
    o = torch.optim.SGD(m.parameters(), lr=0.1)
    m, o = amp.initialize(m, o, opt_level="O2", verbosity=0,
                          min_loss_scale=128.0, max_loss_scale=2.0 ** 18,
                          cast_model_outputs=torch.float32)
    out = m(torch.randn(2, 3, 8, 8))
    assert out.dtype == torch.float32           # cast_model_outputs
    s = amp._amp_state.loss_scalers[0]
    assert (s._min, s._max) == (128.0, 2.0 ** 18)
    s._scale = 128.0
    s.update_scale(overflow=True)
    assert s.loss_scale() == 128.0              # floor holds
    s._scale, s._unskipped, s._window = 2.0 ** 18, 0, 1
    s.update_scale(overflow=False)
    assert s.loss_scale() == 2.0 ** 18          # ceiling holds

    amp.deinitialize()
    m2 = _tiny_model()
    o2 = torch.optim.SGD(m2.parameters(), lr=0.1)
    w0 = next(iter(m2.parameters())).detach().clone()
    m2, o2 = amp.initialize(m2, o2, opt_level="O2", enabled=False)
    assert next(iter(m2.parameters())).dtype == torch.float32  # untouched
    crit = nn.CrossEntropyLoss()
    x, y = _batch()
    o2.zero_grad()
    loss = crit(m2(x), y)
    with amp.scale_loss(loss, o2) as scaled:
        assert scaled is loss                   # pure passthrough
        scaled.backward()
    o2.step()
    assert not torch.equal(next(iter(m2.parameters())).detach(), w0)


def test_o1_out_kwarg_fails_loudly():
    """out= under O1 is unsupportable either way (cast it and the
    caller's buffer is never written; don't and torch rejects the
    dtype mix) — the shim must fail with a clear error, like the
    reference's ban, never corrupt silently."""
    m = _tiny_model()
    o = torch.optim.SGD(m.parameters(), lr=0.1)
    amp.initialize(m, o, opt_level="O1")
    a = torch.randn(4, 4)
    buf = torch.empty(4, 4)
    with pytest.raises(NotImplementedError, match="out="):
        torch.mm(a, a, out=buf)


def test_bad_opt_level_and_unknown_option():
    m = _tiny_model()
    o = torch.optim.SGD(m.parameters(), lr=0.1)
    with pytest.raises(ValueError, match="opt_level"):
        amp.initialize(m, o, opt_level="O4")
    with pytest.raises(TypeError, match="unknown"):
        amp.initialize(m, o, opt_level="O1", not_an_option=1)


def test_gradient_accumulation_with_delay_unscale():
    """The reference pattern: N-1 backwards under
    delay_unscale=True accumulate SCALED grads untouched; the final
    scale_loss unscales the sum once.  Without the flag each exit
    would divide the accumulated sum again."""
    m = _tiny_model()
    o = torch.optim.SGD(m.parameters(), lr=0.1)
    m, o = amp.initialize(m, o, opt_level="O2")
    crit = nn.CrossEntropyLoss()
    x1, y1 = _batch(1)
    x2, y2 = _batch(2)

    o.zero_grad()
    with amp.scale_loss(crit(m(x1).float(), y1), o,
                        delay_unscale=True) as s:
        s.backward()
    with amp.scale_loss(crit(m(x2).float(), y2), o) as s:
        s.backward()

    # oracle: fp32 model, two plain accumulated backwards
    m_ref = _tiny_model()
    loss = (crit(m_ref(x1), y1) + crit(m_ref(x2), y2))
    loss.backward()
    g_amp = next(iter(m.parameters())).grad.float()
    g_ref = next(iter(m_ref.parameters())).grad
    np.testing.assert_allclose(np.asarray(g_amp), np.asarray(g_ref),
                               rtol=0.08, atol=0.02)


def test_cast_tree_handles_namedtuple_and_defaultdict():
    import collections
    import typing

    class Batch(typing.NamedTuple):
        x: torch.Tensor
        n: int

    b = Batch(torch.randn(2, 4), 3)
    out = amp._cast_tree(b, torch.bfloat16)
    assert isinstance(out, Batch)
    assert out.x.dtype == torch.bfloat16 and out.n == 3

    d = collections.defaultdict(list, {"x": torch.randn(2, 4)})
    out = amp._cast_tree(d, torch.bfloat16)
    assert isinstance(out, collections.defaultdict)
    assert out.default_factory is list
    assert out["x"].dtype == torch.bfloat16


def test_deinitialize_restores_usable_fp32_model():
    """After deinitialize a cast model must be plain fp32 and callable
    on fp32 inputs, carrying the TRAINED values (from the masters)."""
    m = _tiny_model()
    o = torch.optim.SGD(m.parameters(), lr=0.1)
    m, o = amp.initialize(m, o, opt_level="O2")
    _train(m, o, steps=2)
    trained = [mast.detach().clone() for mast, _ in o._amp_masters]
    amp.deinitialize()
    assert all(p.dtype == torch.float32 for p in m.parameters())
    m(torch.randn(2, 3, 8, 8))                   # usable on fp32 input
    for p, want in zip((p for p in m.parameters()
                        if p.requires_grad), trained):
        np.testing.assert_allclose(p.detach().numpy(), want.numpy())


def test_deinitialize_keeps_trained_bn_fp32():
    """fp32-exempt tensors (BN params + running stats) train IN PLACE
    under O2 — deinitialize must not roll them back to the pre-cast
    snapshot."""
    m = _tiny_model(bn=True)
    o = torch.optim.SGD(m.parameters(), lr=0.1)
    m, o = amp.initialize(m, o, opt_level="O2")
    _train(m, o, steps=3)
    rm = m[1].running_mean.detach().clone()
    w = m[1].weight.detach().clone()
    assert not torch.equal(rm, torch.zeros_like(rm))   # stats trained
    amp.deinitialize()
    assert torch.equal(m[1].running_mean, rm)
    assert torch.equal(m[1].weight, w)
    assert all(p.dtype == torch.float32 for p in m.parameters())


def test_o2_masters_copy_pre_cast_fp32():
    """Masters must come from the ORIGINAL fp32 values, not from
    re-upcasting the rounded bf16 params (the JAX amp path's rule)."""
    m = _tiny_model()
    orig = next(iter(m.parameters())).detach().clone()
    o = torch.optim.SGD(m.parameters(), lr=0.1)
    m, o = amp.initialize(m, o, opt_level="O2")
    master = o._amp_masters[0][0]
    assert torch.equal(master.detach(), orig)    # exact, no bf16 trip


def test_unprepared_optimizer_fails_loudly():
    m = _tiny_model()
    o = torch.optim.SGD(m.parameters(), lr=0.1)
    amp.initialize(m, opt_level="O1")           # optimizer-less form
    loss = m(torch.randn(2, 3, 8, 8)).float().sum()
    with pytest.raises(RuntimeError, match="not prepared"):
        with amp.scale_loss(loss, o):
            pass


def test_o2_dict_inputs_are_cast():
    """Dict batches (the HF/collate pattern) must be cast at forward
    like positional tensors (reference: the amp applier walks
    mappings)."""

    class DictNet(nn.Module):
        def __init__(self):
            super().__init__()
            self.lin = nn.Linear(8, 4)

        def forward(self, batch):
            return self.lin(batch["x"])

    m = DictNet()
    o = torch.optim.SGD(m.parameters(), lr=0.1)
    m, o = amp.initialize(m, o, opt_level="O2")
    out = m({"x": torch.randn(2, 8)})           # fp32 in a dict
    assert out.dtype == torch.bfloat16
