"""Expert-parallel MoE vs its dense oracle (no reference equivalent —
a TPU-native extension, like ring attention; SURVEY.md §2.5 marks EP
out of apex's scope)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu import comm
from apex_tpu.transformer import moe

T, H, F, E = 64, 16, 32, 8


def _inputs(seed=0):
    ks = jax.random.split(jax.random.key(seed), 4)
    x = jax.random.normal(ks[0], (T, H))
    router = jax.random.normal(ks[1], (H, E)) * 0.5
    w1 = jax.random.normal(ks[2], (E, H, F)) * 0.1
    w2 = jax.random.normal(ks[3], (E, F, H)) * 0.1
    return x, router, w1, w2


def test_top2_gating_capacity_and_renorm():
    logits = jax.random.normal(jax.random.key(1), (T, E))
    cap = moe._capacity(T, E, 1.25)
    dispatch, combine, aux = moe.top2_gating(logits, cap)
    assert dispatch.shape == (T, E, cap)
    # each capacity slot holds at most one token
    assert int(jnp.max(jnp.sum(dispatch, axis=0))) <= 1
    # kept tokens' gates renormalize to 1; dropped rows are all-zero
    tok_w = jnp.sum(combine, axis=(1, 2))
    full = jnp.isclose(tok_w, 1.0, atol=1e-6)
    empty = jnp.isclose(tok_w, 0.0, atol=1e-6)
    partial = ~(full | empty)
    # a token keeping only one of its two choices has weight < 1
    assert bool(jnp.all(tok_w <= 1.0 + 1e-6))
    # partial rows must carry exactly one surviving choice's gate:
    # strictly between 0 and 1
    pw = np.asarray(tok_w)[np.asarray(partial)]
    assert ((pw > 0.0) & (pw < 1.0)).all()
    # at generous capacity most tokens keep both choices
    assert int(jnp.sum(full)) > 0
    assert float(aux) > 0.0


def test_single_rank_matches_oracle():
    x, router, w1, w2 = _inputs()
    m = moe.ExpertParallelMLP(H, F, E, capacity_factor=2.0, axis=None)
    params = {"router": router, "w1": w1, "w2": w2}
    out, aux = m.apply({"params": params}, x)
    cap = moe._capacity(T, E, 2.0)
    want, want_aux = moe.moe_ref(x, router, w1, w2, cap)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(float(aux), float(want_aux), rtol=1e-5)


def test_expert_parallel_matches_oracle():
    """Experts sharded 8 ways; ONE all_to_all each way; output equals
    the dense oracle on every rank."""
    x, router, w1, w2 = _inputs(seed=2)
    mesh = comm.initialize(data=1, model=8)
    m = moe.ExpertParallelMLP(H, F, E, capacity_factor=2.0)

    def run(router, w1_local, w2_local, x):
        params = {"router": router, "w1": w1_local, "w2": w2_local}
        return m.apply({"params": params}, x)

    out, aux = jax.jit(comm.shard_map(
        run, mesh,
        in_specs=(P(), P(comm.AXIS_MODEL), P(comm.AXIS_MODEL), P()),
        out_specs=(P(), P())))(router, w1, w2, x)

    cap = moe._capacity(T, E, 2.0)
    want, want_aux = moe.moe_ref(x, router, w1, w2, cap)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(float(aux), float(want_aux), rtol=1e-5)


def test_expert_parallel_grads_finite_and_match():
    """Tokens sharded over the expert axis (each rank routes its own
    shard); SPMD autodiff through the two all_to_alls yields
    d(sum of all ranks' losses)/d local experts — compared against the
    per-shard oracle sum."""
    x, router, w1, w2 = _inputs(seed=3)       # (T, H): 8 shards of T/8
    mesh = comm.initialize(data=1, model=8)
    m = moe.ExpertParallelMLP(H, F, E, capacity_factor=2.0)
    t_r = T // 8
    cap = moe._capacity(t_r, E, 2.0)

    def loss_sharded(router, w1_local, w2_local, x_local):
        params = {"router": router, "w1": w1_local, "w2": w2_local}
        out, aux = m.apply({"params": params}, x_local)
        return jnp.sum(out.astype(jnp.float32) ** 2) + 0.01 * aux

    g = jax.jit(comm.shard_map(
        jax.grad(loss_sharded, argnums=(0, 1, 2)), mesh,
        in_specs=(P(), P(comm.AXIS_MODEL), P(comm.AXIS_MODEL),
                  P(comm.AXIS_MODEL)),
        out_specs=(P(), P(comm.AXIS_MODEL), P(comm.AXIS_MODEL))))(
        router, w1, w2, x)

    def loss_ref(router_, w1_, w2_):
        total = 0.0
        for r in range(8):
            xr = x[r * t_r:(r + 1) * t_r]
            out, aux = moe.moe_ref(xr, router_, w1_, w2_, cap)
            total = total + jnp.sum(out.astype(jnp.float32) ** 2) \
                + 0.01 * aux
        return total

    # the REPLICATED router's grad must equal the oracle too: each
    # rank only sees its token shard, so this pins the f/g psum on the
    # router param (a loss/expert-grads-only check missed its absence)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(router, w1, w2)
    for a, b in zip(g, g_ref):
        assert bool(jnp.all(jnp.isfinite(a)))
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-3, atol=1e-4)


def test_capacity_drops_are_deterministic():
    """Tight capacity: some tokens drop, output rows for dropped tokens
    are exactly zero (residual path semantics)."""
    x, router, w1, w2 = _inputs(seed=4)
    m = moe.ExpertParallelMLP(H, F, E, capacity_factor=0.5, axis=None)
    params = {"router": router, "w1": w1, "w2": w2}
    out, _ = m.apply({"params": params}, x)
    out2, _ = m.apply({"params": params}, x)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(out2))
    cap = moe._capacity(T, E, 0.5)
    _, combine, _ = moe.top2_gating(
        x.astype(jnp.float32) @ router, cap)
    dropped = np.asarray(jnp.sum(combine, axis=(1, 2)) == 0.0)
    assert dropped.any(), "expected some dropped tokens at cf=0.5"
    np.testing.assert_allclose(np.asarray(out)[dropped], 0.0, atol=1e-6)


def test_router_jitter_perturbs_routing():
    x, router, w1, w2 = _inputs(seed=5)
    m = moe.ExpertParallelMLP(H, F, E, capacity_factor=2.0, axis=None,
                              router_jitter_eps=0.3)
    params = {"router": router, "w1": w1, "w2": w2}
    o1, _ = m.apply({"params": params}, x,
                    rngs={"router": jax.random.key(0)})
    o2, _ = m.apply({"params": params}, x,
                    rngs={"router": jax.random.key(1)})
    # different jitter draws change routing for some tokens
    assert not np.allclose(np.asarray(o1), np.asarray(o2))
    # same draw is deterministic
    o3, _ = m.apply({"params": params}, x,
                    rngs={"router": jax.random.key(0)})
    np.testing.assert_array_equal(np.asarray(o1), np.asarray(o3))


def test_router_jitter_eval_deterministic_no_rng():
    """deterministic=True disables jitter: no rng needed, same output
    as an eps=0 module (the repo's dropout convention)."""
    x, router, w1, w2 = _inputs(seed=6)
    params = {"router": router, "w1": w1, "w2": w2}
    m = moe.ExpertParallelMLP(H, F, E, capacity_factor=2.0, axis=None,
                              router_jitter_eps=0.3)
    out, _ = m.apply({"params": params}, x, deterministic=True)
    m0 = moe.ExpertParallelMLP(H, F, E, capacity_factor=2.0, axis=None)
    want, _ = m0.apply({"params": params}, x)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(want))
