"""Flat end-to-end AMP gradient pipeline (amp/flat_pipeline.py).

Equivalence against the per-leaf amp oracle (unscale_grads +
check_finite + clip_grad_norm + per-leaf optimizer step), overflow
handling, clip-coefficient parity, packed-grads step() parity for all
five fused optimizers, bucket-granular all-reduce, and the structural
op-count guarantee: ONE gradient pack per bucket, ZERO per-leaf
unscale/clip ops in the hot step's jaxpr.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu import amp, comm
from apex_tpu.contrib.clip_grad import clip_grad_norm
from apex_tpu.multi_tensor_apply.packer import BucketPlan
from apex_tpu.ops import multi_tensor as mt
from apex_tpu.optimizers import (FusedAdagrad, FusedAdam, FusedLAMB,
                                 FusedNovoGrad, FusedSGD)

tree_leaves = jax.tree_util.tree_leaves
tree_map = jax.tree_util.tree_map


def _params(dtype=jnp.float32, layers=3, hidden=24):
    keys = jax.random.split(jax.random.key(0), layers)
    return {
        f"l{i}": {
            "w": (jax.random.normal(keys[i], (hidden, hidden)) * 0.3
                  ).astype(dtype),
            "b": jnp.zeros((hidden,), dtype),
            "s": jnp.ones((hidden,), dtype),
        }
        for i in range(layers)
    }


def _grads_like(params, scale=1.0, seed=7):
    keys = jax.random.split(jax.random.key(seed),
                            len(tree_leaves(params)))
    flat, treedef = jax.tree_util.tree_flatten(params)
    return jax.tree_util.tree_unflatten(treedef, [
        (jax.random.normal(k, l.shape) * scale).astype(l.dtype)
        for k, l in zip(keys, flat)])


def _assert_trees_close(a, b, **kw):
    for x, y in zip(tree_leaves(a), tree_leaves(b)):
        np.testing.assert_allclose(np.asarray(x, np.float32),
                                   np.asarray(y, np.float32), **kw)


# ---------------------------------------------------------------------------
# fused kernel vs per-leaf amp oracle
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_flat_unscale_norm_matches_perleaf_amp(dtype):
    """pack + flat_unscale_norm == check_finite + unscale_grads +
    global norm, for f32 and bf16 gradient trees."""
    params = _params(dtype)
    grads = _grads_like(params, scale=512.0)   # "loss-scaled" magnitudes
    state = amp.LossScaleState.create(2.0 ** 9)

    # per-leaf oracle
    fi_ref = amp.check_finite(grads)
    g_ref = amp.unscale_grads(grads, state)
    norm_ref = jnp.sqrt(sum(
        jnp.sum(l.astype(jnp.float32) ** 2) for l in tree_leaves(g_ref)))

    plan = BucketPlan.from_tree(grads)
    pipe = amp.FlatGradPipeline(plan=plan)
    flat = pipe.unscale_and_norm(pipe.pack(grads), state)

    assert int(flat.found_inf) == int(fi_ref) == 0
    # kernel norm accumulates pre-rounding f32; per-leaf norm reads the
    # rounded unscaled tree — bf16 tolerance covers the rounding delta
    tol = 1e-5 if dtype == jnp.float32 else 2e-2
    np.testing.assert_allclose(float(flat.grad_norm), float(norm_ref),
                               rtol=tol)
    _assert_trees_close(pipe.grads_tree(flat), g_ref,
                        rtol=tol, atol=1e-6)
    # kernel vs its own _ref oracle, exact same contract
    for buf in pipe.pack(grads):
        o_k, n_k, f_k = mt.flat_unscale_norm(buf, 1.0 / state.loss_scale)
        o_r, n_r, f_r = mt.flat_unscale_norm_ref(buf,
                                                 1.0 / state.loss_scale)
        np.testing.assert_allclose(np.asarray(o_k, np.float32),
                                   np.asarray(o_r, np.float32), rtol=1e-6)
        np.testing.assert_allclose(float(n_k), float(n_r), rtol=1e-5)
        assert int(f_k) == int(f_r)


@pytest.mark.parametrize("bad", [jnp.inf, -jnp.inf, jnp.nan])
def test_nonfinite_injection_drives_found_inf_and_skip(bad):
    params = _params()
    grads = _grads_like(params)
    grads["l1"]["w"] = grads["l1"]["w"].at[2, 3].set(bad)
    state = amp.LossScaleState.create(2.0 ** 4)

    opt = FusedAdam(params, lr=1e-2)
    pipe = amp.FlatGradPipeline(optimizer=opt, max_grad_norm=1.0)
    flat = pipe.unscale_and_norm(pipe.pack(grads), state)
    assert int(flat.found_inf) == 1
    # NaN-safe clip coefficient: stays 1.0, never NaN
    assert float(flat.clip_coef) == 1.0

    before = opt.params
    new_params = pipe.step(flat)        # branch-free skip
    _assert_trees_close(new_params, before, rtol=0, atol=0)
    assert int(opt.step_count) == 0     # skipped step keeps the clock

    # clean grads on the same optimizer DO step
    flat2 = pipe.unscale_and_norm(pipe.pack(_grads_like(params)), state)
    assert int(flat2.found_inf) == 0
    stepped = pipe.step(flat2)
    assert any(
        not np.allclose(np.asarray(a), np.asarray(b))
        for a, b in zip(tree_leaves(stepped), tree_leaves(before)))
    assert int(opt.step_count) == 1


def test_clip_coef_matches_clip_grad_norm():
    params = _params()
    grads = _grads_like(params, scale=3.0)   # norm safely above max_norm
    state = amp.LossScaleState.create(1.0)   # isolate the clip math

    max_norm = 1.5
    pipe = amp.FlatGradPipeline(params=params, max_grad_norm=max_norm)
    flat = pipe.unscale_and_norm(pipe.pack(grads), state)

    clipped_ref, norm_ref = clip_grad_norm(grads, max_norm)
    np.testing.assert_allclose(float(flat.grad_norm), float(norm_ref),
                               rtol=1e-6)
    # same formula: max_norm / (norm + eps)
    np.testing.assert_allclose(
        float(flat.clip_coef),
        float(jnp.minimum(max_norm / (norm_ref + 1e-6), 1.0)), rtol=1e-6)
    # applying clip_coef to the flat buffers == the clipped tree
    _assert_trees_close(
        pipe.grads_tree(flat._replace(
            bufs=[b * flat.clip_coef for b in flat.bufs])),
        clipped_ref, rtol=1e-5, atol=1e-7)
    # below max_norm: no clipping
    pipe2 = amp.FlatGradPipeline(params=params, max_grad_norm=1e6)
    assert float(pipe2.unscale_and_norm(
        pipe2.pack(grads), state).clip_coef) == 1.0


def test_clip_grad_norm_packed_delegation():
    grads = _params()   # any float tree works as "grads"
    plan = BucketPlan.from_tree(grads)
    bufs = plan.pack_grads(grads)
    c_tree, n_tree = clip_grad_norm(grads, 0.7)
    c_bufs, n_bufs = clip_grad_norm(bufs, 0.7)
    assert isinstance(c_bufs, list) and len(c_bufs) == len(bufs)
    np.testing.assert_allclose(float(n_tree), float(n_bufs), rtol=1e-6)
    _assert_trees_close(plan.unpack_grads(c_bufs), c_tree, rtol=1e-6)


# ---------------------------------------------------------------------------
# packed-grads step() parity, all five optimizers
# ---------------------------------------------------------------------------

_OPTIMIZERS = [
    (FusedAdam, dict(lr=1e-2)),
    (FusedSGD, dict(lr=1e-2, momentum=0.9)),
    (FusedAdagrad, dict(lr=1e-2)),
    (FusedNovoGrad, dict(lr=1e-2)),
    (FusedLAMB, dict(lr=1e-2, max_grad_norm=0.0)),
]


@pytest.mark.parametrize("cls,kw", _OPTIMIZERS,
                         ids=[c.__name__ for c, _ in _OPTIMIZERS])
@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_packed_step_matches_unpacked(cls, kw, dtype):
    """step(packed buffers) == step(pytree), f32 and bf16+masters,
    including a traced clip_coef folded into the kernels."""
    params = _params(dtype)
    opt_tree = cls(params, **kw)
    opt_pack = cls(params, **kw)
    assert opt_pack.fuse_buckets
    clip = jnp.float32(0.75)
    for s in range(2):   # two steps: momentum/first_run paths both run
        grads = _grads_like(params, seed=10 + s)
        p_tree = opt_tree.step(grads, clip_coef=clip)
        bufs = opt_pack._plan.pack_grads(grads)
        p_pack = opt_pack.step(bufs, clip_coef=clip)
        _assert_trees_close(p_tree, p_pack, rtol=1e-6, atol=1e-7)
        if opt_tree.masters is not None:
            _assert_trees_close(opt_tree.masters, opt_pack.masters,
                                rtol=1e-6, atol=1e-7)


def test_step_accepts_flat_grads_bundle():
    """step(FlatGrads) pulls bufs + found_inf + clip_coef from the
    bundle; equivalent to passing them explicitly."""
    params = _params()
    grads = _grads_like(params)
    state = amp.LossScaleState.create(2.0 ** 3)
    opt_a = FusedAdam(params, lr=1e-2)
    opt_b = FusedAdam(params, lr=1e-2)
    pipe = amp.FlatGradPipeline(optimizer=opt_a, max_grad_norm=0.5)
    flat = pipe.unscale_and_norm(pipe.pack(grads), state)
    p_a = opt_a.step(flat)
    p_b = opt_b.step(flat.bufs, found_inf=flat.found_inf,
                     clip_coef=flat.clip_coef)
    _assert_trees_close(p_a, p_b, rtol=0, atol=0)


def test_clip_coef_fold_equals_prescaled_grads():
    """clip_coef folding == multiplying the gradients by clip_coef."""
    params = _params()
    grads = _grads_like(params)
    for cls, kw in _OPTIMIZERS:
        o1, o2 = cls(params, **kw), cls(params, **kw)
        p1 = o1.step(tree_map(lambda g: g * 0.5, grads))
        p2 = o2.step(grads, clip_coef=jnp.float32(0.5))
        _assert_trees_close(p1, p2, rtol=1e-5, atol=1e-7)


# ---------------------------------------------------------------------------
# full AMP train step: flat pipeline vs per-leaf oracle
# ---------------------------------------------------------------------------

def _loss_fn(p, x):
    h = x
    for name in sorted(p):
        h = jnp.tanh(h @ p[name]["w"].astype(jnp.float32)
                     + p[name]["b"].astype(jnp.float32))
        h = h * p[name]["s"].astype(jnp.float32)
    return jnp.sum(h ** 2) * 0.1


@pytest.mark.parametrize("opt_level", ["O1", "O2"])
def test_full_amp_step_flat_matches_perleaf(opt_level):
    """scaled_value_and_grad -> pack -> fused unscale/norm -> packed
    clipped step == the per-leaf chain, for pure-f32 (O1) and
    bf16+masters (O2)."""
    params0 = _params(jnp.float32)
    x = jax.random.normal(jax.random.key(1), (4, 24))
    params, amp_state = amp.initialize(params0, opt_level=opt_level)
    state = amp_state.scaler
    masters = amp_state.master_params
    max_norm = 0.5

    opt_ref = FusedAdam(params, lr=1e-2, masters=masters,
                        fuse_buckets=False)
    opt_flat = FusedAdam(params, lr=1e-2, masters=masters,
                         fuse_buckets=True)
    assert opt_flat.fuse_buckets

    # per-leaf oracle chain
    loss_ref, grads, fi = amp.scaled_value_and_grad(
        _loss_fn, state, params, x)
    clipped, _ = clip_grad_norm(grads, max_norm)
    p_ref = opt_ref.step(clipped, found_inf=fi)

    # flat pipeline chain
    pipe = amp_state.flat_pipeline(optimizer=opt_flat,
                                   max_grad_norm=max_norm)
    loss_flat, flat = pipe.scaled_value_and_grad(_loss_fn, state,
                                                 params, x)
    p_flat = pipe.step(flat)

    np.testing.assert_allclose(float(loss_ref), float(loss_flat),
                               rtol=1e-6)
    tol = dict(rtol=1e-5, atol=1e-6) if opt_level == "O1" \
        else dict(rtol=2e-2, atol=2e-4)   # bf16 params; norm rounding
    _assert_trees_close(p_ref, p_flat, **tol)
    if opt_ref.masters is not None:
        # f32 masters carry the true update; tighter than the bf16 params
        _assert_trees_close(opt_ref.masters, opt_flat.masters,
                            rtol=5e-4, atol=1e-6)


def test_scaler_entry_grads_layout_flat():
    """amp.scaled_value_and_grad(grads_layout='flat') returns a
    FlatGrads bundle equal to the tree layout's grads."""
    params = _params()
    x = jax.random.normal(jax.random.key(2), (4, 24))
    state = amp.LossScaleState.create(2.0 ** 8)
    loss_t, grads, fi_t = amp.scaled_value_and_grad(
        _loss_fn, state, params, x)
    # plan=None: a cached plan is derived from the gradient tree
    loss_f, flat, fi_f = amp.scaled_value_and_grad(
        _loss_fn, state, params, x, grads_layout="flat")
    assert isinstance(flat, amp.FlatGrads)
    assert int(fi_t) == int(fi_f) == 0
    np.testing.assert_allclose(float(loss_t), float(loss_f), rtol=1e-6)
    plan = BucketPlan.from_tree(grads)
    _assert_trees_close(plan.unpack_grads(flat.bufs), grads,
                        rtol=1e-5, atol=1e-7)
    with pytest.raises(ValueError):
        amp.scaled_value_and_grad(_loss_fn, state, params, x,
                                  grads_layout="banana")


# ---------------------------------------------------------------------------
# bucket-granular data-parallel all-reduce
# ---------------------------------------------------------------------------

def test_bucketed_allreduce_matches_perleaf():
    from apex_tpu.parallel import (Reducer, all_reduce_gradients)
    mesh = comm.initialize(data=8)
    params = _params()
    plan = BucketPlan.from_tree(params)
    gx = jax.random.normal(jax.random.key(3),
                           (8,) + (24, 24))   # per-shard w grads

    def per_leaf(gs):
        tree = _grads_like(params)
        tree["l0"]["w"] = gs[0]
        return all_reduce_gradients(tree, comm.AXIS_DATA)

    def bucketed(gs):
        tree = _grads_like(params)
        tree["l0"]["w"] = gs[0]
        return Reducer(axis_name=comm.AXIS_DATA, plan=plan).reduce(tree)

    def bucketed_packed(gs):
        tree = _grads_like(params)
        tree["l0"]["w"] = gs[0]
        bufs = Reducer(axis_name=comm.AXIS_DATA, plan=plan).reduce(
            plan.pack_grads(tree))
        return plan.unpack_grads(bufs)

    sm = lambda f: jax.jit(comm.shard_map(
        f, mesh, in_specs=P(comm.AXIS_DATA), out_specs=P()))
    r_leaf = sm(per_leaf)(gx)
    r_bucket = sm(bucketed)(gx)
    r_packed = sm(bucketed_packed)(gx)
    _assert_trees_close(r_leaf, r_bucket, rtol=1e-6, atol=1e-7)
    _assert_trees_close(r_leaf, r_packed, rtol=1e-6, atol=1e-7)
    comm.destroy()


# ---------------------------------------------------------------------------
# structural guarantee: ONE pack, zero per-leaf amp ops — now owned by
# the shared apexverify spec (apex_tpu/lint/semantic), which this test
# drives; the per-leaf contrast (not a library invariant) stays local
# but uses the same shared walker, so neither side can silently weaken.
# ---------------------------------------------------------------------------

def test_op_count_one_pack_zero_perleaf_amp_ops():
    """The jitted flat AMP train step contains exactly ONE gradient
    pack per bucket, 2 pallas_calls per bucket and ZERO per-leaf
    unscale/clip/finite-check ops — asserted by the registered
    `amp.flat_pipeline_step` invariant spec; the per-leaf oracle step
    contains one finite check per leaf (local contrast)."""
    from apex_tpu.lint import semantic
    from apex_tpu.ops._dispatch import op_enabled

    res = semantic.verify_spec(semantic.get_spec("amp.flat_pipeline_step"))
    assert res.ok, res.failures
    # the spec really checked the invariants this test used to own
    checked = set(res.checked)
    assert {"bucket_concats", "no_host_transfer",
            "is_finite_max", "no_f64"} <= checked, checked
    if op_enabled("multi_tensor"):
        # exactly 2 pallas_calls per bucket (unscale_norm + adam):
        # clip folds into the optimizer kernel's grad scaling
        assert "pallas_calls" in checked, checked

    # contrast: the per-leaf oracle walks every leaf
    params = _params()
    x = jax.random.normal(jax.random.key(4), (4, 24))
    state = amp.LossScaleState.create()
    n_leaves = len(tree_leaves(params))
    opt_pl = FusedAdam(params, lr=1e-3, fuse_buckets=False)

    def per_leaf_step(ptree, opt_state, scaler, x, step):
        loss, grads, fi = amp.scaled_value_and_grad(_loss_fn, scaler,
                                                    ptree, x)
        clipped, _ = clip_grad_norm(grads, 1.0)
        new_p, new_state = opt_pl.functional_step(
            ptree, opt_state, clipped, step)
        return loss, new_p, new_state

    jaxpr_pl = jax.make_jaxpr(per_leaf_step)(
        params, opt_pl.opt_state, state, x, jnp.int32(1))
    counts_pl = semantic.jaxprs.primitive_counts(jaxpr_pl)
    assert counts_pl.get("is_finite", 0) >= n_leaves

    # the bucketed step's finite checks stay strictly below per-leaf:
    # the spec pinned them at <= n_buckets (0 with kernels enabled),
    # and every tiny spec tree has more leaves than buckets
    opt_b = FusedAdam(params, lr=1e-3)
    assert len(opt_b._plan.buckets) < n_leaves


# ---------------------------------------------------------------------------
# bench harness smoke (tier-1 keeps the tooling runnable, like
# bucketing_bench)
# ---------------------------------------------------------------------------

def test_amp_pipeline_microbench_smoke():
    from apex_tpu.optimizers.bucketing_bench import bench_amp_pipeline
    r = bench_amp_pipeline(layers=3, hidden=32, iters=2, reps=1)
    assert r["amp_step_per_leaf_ms"] > 0
    assert r["amp_step_flat_ms"] > 0
    assert r["amp_pipeline_speedup"] > 0
    assert r["amp_leaves"] == 12
