"""Causal incident correlation + the fleet-wide timeline: incident-id
minting (telemetry/incident.py), its threading through the watchdog
and fleet event records, the multi-run-dir merge front-end with
beacon-clock skew correction (telemetry/timeline.py), the
``telemetry timeline`` CLI (text / --json / --chrome-trace), and the
v1-schema regression contract (old run dirs keep rendering)."""

import io
import json
import os
import time

import jax.numpy as jnp
import pytest

from apex_tpu import telemetry
from apex_tpu.resilience import fleet as fleet_mod
from apex_tpu.resilience.watchdog import (NanStreakDetector, Watchdog)
from apex_tpu.telemetry import timeline as timeline_mod
from apex_tpu.telemetry.cli import summarize
from apex_tpu.telemetry.cli import timeline as timeline_cli
from apex_tpu.telemetry.incident import IncidentLog, mint

_FIXDIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "timeline_fixtures")


# ---------------------------------------------------------------------
# IncidentLog
# ---------------------------------------------------------------------

def test_mint_is_a_pure_function_of_replicated_facts():
    assert mint("host_dead", 1, host=2, incarnation=7, epoch=3) == \
        "inc-001-host_dead-h2.7-e3"
    # subject-less incidents (replicated watchdog verdicts, deadlines)
    assert mint("nan_streak", 12) == "inc-012-nan_streak-e0"


def test_incident_log_open_is_idempotent_until_closed():
    log = IncidentLog()
    a = log.open("host_dead", host=2, incarnation=1)
    # the second subsystem to notice JOINS the chain, never forks it
    assert log.open("nan_streak") == a
    assert log.close("inc-999-bogus-e0") is False   # stale: no-op
    assert log.current == a
    assert log.close(a) is True and log.current is None
    b = log.open("deadline", epoch=2)
    assert b != a and b == "inc-002-deadline-e2"
    assert log.history == [a, b]


def test_incident_log_tag_threads_only_while_open():
    log = IncidentLog()
    rec = log.tag({"kind": "fleet", "event": "host_slow"})
    assert "incident_id" not in rec
    iid = log.open("host_dead", host=1, incarnation=1)
    assert log.tag({"kind": "fleet"})["incident_id"] == iid


# ---------------------------------------------------------------------
# Watchdog threading
# ---------------------------------------------------------------------

def _overflow_window(lo, hi, bad=()):
    return [{"step": s, "amp/found_inf": 1.0 if s in bad else 0.0}
            for s in range(lo, hi)]


def test_watchdog_anomaly_opens_incident_and_threads_records():
    wd = Watchdog(detectors=[NanStreakDetector(streak=2)],
                  clean_window=4)
    found = wd.observe(_overflow_window(1, 4, bad=(1, 2)))
    assert len(found) == 1 and found[0].kind == "nan_streak"
    iid = found[0].incident_id
    assert iid is not None and iid.startswith("inc-001-nan_streak")
    assert wd.incidents.current == iid
    assert found[0].record()["incident_id"] == iid
    # rollback + replay: the action events carry the id out, and the
    # replay catching up closes the chain with one replay_complete
    wd.note_rollback(0, 3, found[0])
    wd.note_replay_complete(4)
    actions = [(e["action"], e.get("incident_id")) for e in wd.events]
    assert actions == [("rollback", iid), ("replay_complete", iid)]
    assert wd.incidents.current is None
    wd.close()


def test_watchdog_quarantine_incident_resolves_after_clean_window():
    from apex_tpu.resilience.watchdog import WatchdogPolicy
    wd = Watchdog(detectors=[NanStreakDetector(streak=2)],
                  policy=WatchdogPolicy(
                      actions={"nan_streak": "quarantine"}),
                  clean_window=3)
    found = wd.observe(_overflow_window(1, 3, bad=(1, 2)))
    iid = found[0].incident_id
    assert wd.incidents.current == iid
    # the verdict must be TAKEN before a clean window may resolve the
    # incident (run_elastic's check() at the next step boundary) — an
    # un-adjudicated anomaly holds the incident open
    wd.observe(_overflow_window(3, 10))
    assert wd.incidents.current == iid        # still pending a verdict
    assert wd.check(10).action == "quarantine"
    wd.note_quarantine(10, found[0])
    wd.observe(_overflow_window(10, 16))      # clean window ages out
    assert wd.incidents.current is None
    resolved = [e for e in wd.events
                if e["action"] == "incident_resolved"]
    assert len(resolved) == 1 and resolved[0]["incident_id"] == iid
    assert wd.events[0]["action"] == "quarantine" \
        and wd.events[0]["incident_id"] == iid
    wd.close()


# ---------------------------------------------------------------------
# Fleet threading: determinism across hosts
# ---------------------------------------------------------------------

def _lag_monitor(ch, host, n_hosts, tel=None):
    return fleet_mod.FleetMonitor(
        channel=ch, host=host, n_hosts=n_hosts,
        slow_after_steps=2, dead_after_steps=4,
        slow_after_s=None, dead_after_s=None,
        agreement_timeout_s=0.1, telemetry=tel)


def _drive_fleet_pair(d0, d1):
    """Two REAL monitors (own sessions, own run dirs) on one channel;
    host 2 beacons twice then goes silent -> both monitors detect the
    death, agree, shrink, and complete the replay."""
    ch = fleet_mod.LocalChannel()
    tel0 = telemetry.Telemetry(d0, window=4, retrace=False, host=0)
    tel1 = telemetry.Telemetry(d1, window=4, retrace=False, host=1)
    m0 = _lag_monitor(ch, 0, 3, tel0)
    m1 = _lag_monitor(ch, 1, 3, tel1)
    # m0's agreement round needs host 1's verdict published while m0
    # polls (single thread): the spin hook publishes m1's live view
    m0.add_spin_hook(lambda epoch: ch.put(
        f"verdict/{epoch}/1", {"host": 1, "epoch": epoch,
                               "survivors": [0, 1]}))
    for step in range(1, 9):
        if step <= 2:
            ch.put("beacon/2", {"host": 2, "step": step,
                                "wall_time": time.time(),
                                "incarnation": 1})
        for host, (tel, mon) in enumerate(((tel0, m0), (tel1, m1))):
            tel.record({"loss": jnp.float32(1.0 / step)}, step)
            dead = [f for f in mon.beat(step)
                    if f.kind == "host_dead"]
            if dead:
                epoch, survivors = mon.agree_survivors(
                    step, timeout_s=0.2)
                mon.note_shrink(step, epoch, survivors, [2],
                                step - 1)
                mon.note_replay_complete(step)
    for tel, mon in ((tel0, m0), (tel1, m1)):
        mon.close()
        tel.close()
    return m0, m1


def test_surviving_hosts_mint_the_same_incident_id(tmp_path):
    """THE correlation contract: every survivor stamps the SAME id
    for the same peer death without any extra coordination — the id
    is a pure function of replicated facts (dead peer's identity,
    epoch, incident ordinal)."""
    m0, m1 = _drive_fleet_pair(str(tmp_path / "h0"),
                               str(tmp_path / "h1"))
    assert m0.incidents.history == m1.incidents.history
    assert len(m0.incidents.history) == 1
    iid = m0.incidents.history[0]
    assert iid.startswith("inc-001-host_dead-h2.1-e")
    assert m0.incidents.current is None     # replay closed it
    for mon in (m0, m1):
        chain = [(e["event"], e.get("incident_id"))
                 for e in mon.events]
        assert chain == [("shrink", iid), ("replay_complete", iid)]


def test_fleet_chain_renders_as_single_incident_across_run_dirs(
        tmp_path, capsys):
    """The acceptance flow: kill one host of a faked fleet -> the
    beacon-gap/agreement/shrink/replay chain shares ONE incident_id
    across the surviving hosts' run dirs, and ``telemetry timeline``
    renders it as a single ordered incident — text, --json, and a
    valid Chrome trace."""
    d0, d1 = str(tmp_path / "h0"), str(tmp_path / "h1")
    _drive_fleet_pair(d0, d1)
    # text
    buf = io.StringIO()
    assert timeline_cli([d0, d1], out=buf) == 0
    text = buf.getvalue()
    assert text.count("incident inc-001-host_dead-h2.1-e") == 1
    assert "[closed]" in text and "hosts [0, 1]" in text
    for label in ("fleet:host_dead", "fleet:shrink",
                  "fleet:replay_complete"):
        assert label in text
    # --json: one incident carrying the whole chain from BOTH hosts
    buf = io.StringIO()
    assert timeline_cli([d0, d1], as_json=True, out=buf) == 0
    doc = json.loads(buf.getvalue())
    assert len(doc["incidents"]) == 1
    inc = doc["incidents"][0]
    assert inc["hosts"] == [0, 1] and inc["closed"]
    assert inc["opened_by"] == "fleet:host_dead"
    kinds = [(r.get("event"), r["host"]) for r in inc["events"]]
    for ev in ("host_dead", "shrink", "replay_complete"):
        assert (ev, 0) in kinds and (ev, 1) in kinds
    # events are ordered: the dead-detections precede the shrinks
    # precede the replay-completes
    order = [r.get("event") for r in inc["events"]]
    assert order.index("host_dead") < order.index("shrink") \
        < order.index("replay_complete")
    # --chrome-trace: a valid trace document Perfetto can load
    trace_path = str(tmp_path / "trace.json")
    buf = io.StringIO()
    assert timeline_cli([d0, d1], chrome_trace_path=trace_path,
                        out=buf) == 0
    with open(trace_path, encoding="utf-8") as f:
        trace = json.load(f)
    evs = trace["traceEvents"]
    assert isinstance(evs, list) and evs
    for e in evs:
        assert "ph" in e and "pid" in e and "name" in e
        if e["ph"] != "M":
            assert isinstance(e["ts"], (int, float))
    spans = [e for e in evs if e["ph"] == "X"]
    assert {e["pid"] for e in spans} == {0, 1}   # one span per host
    assert all(e["name"].startswith("inc-001-host_dead")
               for e in spans)


# ---------------------------------------------------------------------
# Merge front-end: dedupe, skew, fixtures
# ---------------------------------------------------------------------

def _write_jsonl(path, records):
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w", encoding="utf-8") as f:
        for r in records:
            f.write(json.dumps(r, sort_keys=True) + "\n")


def test_merge_dedupes_newest_per_host_and_step(tmp_path):
    """The dedupe rule: a replay re-records the steps it replays —
    the NEWEST record per (host, step) survives, while the same step
    on ANOTHER host is a different row entirely."""
    d0, d1 = str(tmp_path / "h0"), str(tmp_path / "h1")
    _write_jsonl(os.path.join(d0, "telemetry.jsonl"), [
        {"kind": "schema", "version": 2, "metrics": ["loss"],
         "host": 0},
        {"step": 5, "loss": 9.0},       # pre-rollback value
        {"step": 6, "loss": 8.0},
        {"step": 5, "loss": 1.0},       # the replay's re-record
    ])
    _write_jsonl(os.path.join(d1, "telemetry.jsonl"), [
        {"kind": "schema", "version": 2, "metrics": ["loss"],
         "host": 1},
        {"step": 5, "loss": 2.0},
    ])
    merged = timeline_mod.merge_run_dirs([d0, d1])
    steps = {(r["host"], r["step"]): r["loss"]
             for r in merged["steps"]}
    assert steps == {(0, 5): 1.0, (0, 6): 8.0, (1, 5): 2.0}
    # the multi-dir summarize front-end applies the same rule
    buf = io.StringIO()
    assert summarize([d0, d1], as_json=True, out=buf) == 0
    doc = json.loads(buf.getvalue())
    assert doc["hosts"] == [0, 1]
    got = {(r["host"], r["step"]): r["loss"] for r in doc["steps"]}
    assert got == steps


def test_offsets_estimated_from_step_aligned_clock_records():
    """The checked-in two-host fixture has host 1's wall clock 120 s
    ahead: the step-aligned clock records expose exactly that offset,
    and the corrected stamps interleave the two hosts' events."""
    merged = timeline_mod.merge_run_dirs(
        [os.path.join(_FIXDIR, "host0"),
         os.path.join(_FIXDIR, "host1")])
    assert merged["offsets"] == {"0": 0.0, "1": 120.0}
    dead = [r for r in merged["records"]
            if r.get("event") == "host_dead"]
    # corrected: host 1's 1127.1 stamp reads as 1007.1 — within a
    # fraction of a second of host 0's 1007.0, not 120 s later
    ts = {r["host"]: r["t"] for r in dead}
    assert abs(ts[1] - ts[0]) < 1.0


def test_checked_in_fixture_renders_one_closed_incident(capsys):
    """tools/check.sh smoke's contract, pinned as a test: the fixture
    renders one closed incident spanning both hosts, --json parses,
    and the chrome trace is valid."""
    dirs = [os.path.join(_FIXDIR, "host0"),
            os.path.join(_FIXDIR, "host1")]
    buf = io.StringIO()
    assert timeline_cli(dirs, as_json=True, out=buf) == 0
    doc = json.loads(buf.getvalue())
    assert len(doc["incidents"]) == 1
    inc = doc["incidents"][0]
    assert inc["incident_id"] == "inc-001-host_dead-h2.1-e0"
    assert inc["hosts"] == [0, 1] and inc["closed"]
    buf = io.StringIO()
    assert timeline_cli(dirs, chrome_trace_path="-", out=buf) == 0
    trace = json.loads(buf.getvalue())
    assert len(trace["traceEvents"]) > 0


def test_timeline_missing_dirs_exit_1(tmp_path, capsys):
    buf = io.StringIO()
    assert timeline_cli([str(tmp_path / "nope")], out=buf) == 1
    assert "no telemetry.jsonl" in buf.getvalue()


# ---------------------------------------------------------------------
# v1 schema regression: old run dirs keep rendering
# ---------------------------------------------------------------------

_V1_RECORDS = [
    {"kind": "schema", "version": 1, "metrics": ["loss"]},
    {"step": 1, "loss": 2.0},
    {"step": 2, "loss": 1.5},
    # v1 fleet event: no incident_id, no t, no host anywhere
    {"kind": "fleet", "event": "host_dead", "host": 2, "step": 2,
     "peer_step": 1, "gap_s": 4.0, "lag_steps": 1},
    {"kind": "fleet", "event": "shrink", "step": 2, "epoch": 1,
     "survivors": [0, 1], "dead": [2], "reason": "failure",
     "to_step": 1},
    {"kind": "counter", "name": "fleet/mesh_shrinks", "count": 1,
     "total": 1.0, "max": 1.0, "last": 1.0, "step": 2},
]


def test_v1_run_dir_still_summarizes(tmp_path):
    d = str(tmp_path / "v1run")
    _write_jsonl(os.path.join(d, "telemetry.jsonl"), _V1_RECORDS)
    buf = io.StringIO()
    assert summarize(d, out=buf) == 0
    out = buf.getvalue()
    assert "host_dead" in out and "shrink" in out
    buf = io.StringIO()
    assert summarize(d, as_json=True, out=buf) == 0
    json.loads(buf.getvalue())


def test_v1_run_dirs_still_merge_into_a_timeline(tmp_path):
    """A v1 dir has no host header, no clock records and no incident
    ids: the merge assigns fallback hosts, skips skew correction and
    lists the events ungrouped — it must never crash or drop them."""
    d0, d1 = str(tmp_path / "a"), str(tmp_path / "b")
    _write_jsonl(os.path.join(d0, "telemetry.jsonl"), _V1_RECORDS)
    _write_jsonl(os.path.join(d1, "telemetry.jsonl"), _V1_RECORDS)
    buf = io.StringIO()
    assert timeline_cli([d0, d1], as_json=True, out=buf) == 0
    doc = json.loads(buf.getvalue())
    assert doc["hosts"] == [0, 1]           # fallback enumeration
    assert doc["incidents"] == []
    labels = {(_r["host"], _r.get("event"))
              for _r in doc["ungrouped"]}
    assert (0, "host_dead") in labels and (1, "shrink") in labels
    # text + chrome trace stay renderable without any wall stamps
    buf = io.StringIO()
    assert timeline_cli([d0, d1], out=buf) == 0
    assert "events outside any incident" in buf.getvalue()
    buf = io.StringIO()
    assert timeline_cli([d0, d1], chrome_trace_path="-",
                        out=buf) == 0
    json.loads(buf.getvalue())


def test_mixed_v1_and_v2_dirs_merge(tmp_path):
    """A fleet mid-upgrade: one host still writes v1, another v2 —
    the merge keeps the v2 host's claimed id and gives the v1 dir a
    free one."""
    d0, d1 = str(tmp_path / "old"), str(tmp_path / "new")
    _write_jsonl(os.path.join(d0, "telemetry.jsonl"), _V1_RECORDS)
    _write_jsonl(os.path.join(d1, "telemetry.jsonl"), [
        {"kind": "schema", "version": 2, "metrics": ["loss"],
         "host": 0},
        {"step": 1, "loss": 2.0},
        {"kind": "fleet", "event": "host_dead", "host": 2, "step": 2,
         "peer_step": 1, "gap_s": 4.0, "lag_steps": 1, "t": 1002.0,
         "incident_id": "inc-001-host_dead-h2.1-e0"},
    ])
    merged = timeline_mod.merge_run_dirs([d0, d1])
    assert merged["hosts"] == [0, 1]
    hosts_with_incident = {r["host"] for r in merged["records"]
                           if r.get("incident_id")}
    assert hosts_with_incident == {0}       # the v2 dir claimed host 0
