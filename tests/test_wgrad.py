"""ops.wgrad (fused weight-grad accumulation) vs oracle."""

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.ops.wgrad import wgrad_gemm_accum_fp32, wgrad_gemm_accum_ref


def test_wgrad_accumulates_f32():
    x = jax.random.normal(jax.random.PRNGKey(0), (4, 8, 32),
                          jnp.bfloat16)
    dy = jax.random.normal(jax.random.PRNGKey(1), (4, 8, 16),
                           jnp.bfloat16)
    acc = jnp.ones((16, 32), jnp.float32)  # (Out, In): reference layout
    got = wgrad_gemm_accum_fp32(x, dy, acc)
    want = wgrad_gemm_accum_ref(x, dy, acc)
    assert got.dtype == jnp.float32
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-2, atol=2e-2)


def test_wgrad_microbatch_accumulation_matches_full_batch():
    """The reference's raison d'etre: sum of microbatch wgrads == full
    batch wgrad, accumulated in f32."""
    x = jax.random.normal(jax.random.PRNGKey(0), (16, 32))
    dy = jax.random.normal(jax.random.PRNGKey(1), (16, 8))
    full = wgrad_gemm_accum_fp32(x, dy, jnp.zeros((8, 32)))
    acc = jnp.zeros((8, 32))
    step = jax.jit(wgrad_gemm_accum_fp32, donate_argnums=(2,))
    for i in range(4):
        acc = step(x[i * 4:(i + 1) * 4], dy[i * 4:(i + 1) * 4], acc)
    np.testing.assert_allclose(np.asarray(acc), np.asarray(full),
                               rtol=1e-5, atol=1e-5)
