"""apex_tpu.parallel tests (reference models: tests/distributed/
synced_batchnorm — SyncBN vs single-process BN oracle; DDP grad
equivalence; LARC math).  Multi-chip is simulated on the 8-device CPU
mesh, which the reference could not do (SURVEY.md §4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu import comm
from apex_tpu.ops import welford
from apex_tpu.parallel import (DistributedDataParallel, LARC,
                               SyncBatchNorm, all_reduce_gradients,
                               sync_batch_norm_stats)
from apex_tpu.optimizers import FusedSGD


@pytest.mark.parametrize("n,c", [(32, 128), (100, 256), (7, 128)])
def test_welford_kernel_vs_ref(n, c):
    x = jax.random.normal(jax.random.key(0), (n, c))
    mean, var, cnt = welford.welford_mean_var(x)
    mref, vref, cref = welford.welford_mean_var_ref(x)
    np.testing.assert_allclose(mean, mref, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(var, vref, rtol=1e-4, atol=1e-5)
    assert float(cnt) == float(cref) == n


def test_welford_combine():
    x = jax.random.normal(jax.random.key(1), (64, 4))
    a, b = x[:20], x[20:]
    na, ma, m2a = 20.0, jnp.mean(a, 0), jnp.sum((a - jnp.mean(a, 0))**2, 0)
    nb, mb, m2b = 44.0, jnp.mean(b, 0), jnp.sum((b - jnp.mean(b, 0))**2, 0)
    n, m, m2 = welford.welford_combine(na, ma, m2a, nb, mb, m2b)
    np.testing.assert_allclose(m, jnp.mean(x, 0), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(m2 / n, jnp.var(x, 0), rtol=1e-4, atol=1e-6)


def test_sync_stats_match_full_batch():
    """Stats synced over a sharded batch == full-batch stats (the
    reference's synced_batchnorm/two_gpu_unit_test oracle)."""
    mesh = comm.initialize(data=8)
    x = jax.random.normal(jax.random.key(2), (64, 16))

    def f(xs):
        mean, var, n = sync_batch_norm_stats(xs, comm.AXIS_DATA)
        return mean, var

    mean, var = jax.jit(comm.shard_map(
        f, mesh, in_specs=P(comm.AXIS_DATA), out_specs=P()))(x)
    np.testing.assert_allclose(mean, jnp.mean(x, 0), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(var, jnp.var(x, 0), rtol=1e-4, atol=1e-6)


def test_syncbn_module_matches_full_batch_bn():
    mesh = comm.initialize(data=8)
    c = 8
    bn = SyncBatchNorm(num_features=c)
    x = jax.random.normal(jax.random.key(3), (32, c)) * 2.0 + 1.0
    variables = bn.init(jax.random.key(0), x, use_running_average=False)

    def f(v, xs):
        y, updates = bn.apply(v, xs, use_running_average=False,
                              mutable=["batch_stats"])
        return y, updates

    y, updates = jax.jit(comm.shard_map(
        f, mesh, in_specs=(P(), P(comm.AXIS_DATA)),
        out_specs=(P(comm.AXIS_DATA), P())))(variables, x)

    # oracle: full-batch normalization
    mu, var = jnp.mean(x, 0), jnp.var(x, 0)
    want = (x - mu) / jnp.sqrt(var + bn.eps)
    np.testing.assert_allclose(np.asarray(y), np.asarray(want),
                               rtol=1e-4, atol=1e-4)
    # running stats got the (unbiased-var) momentum update
    rm = updates["batch_stats"]["running_mean"]
    np.testing.assert_allclose(rm, 0.1 * mu, rtol=1e-4, atol=1e-5)


def test_ddp_reduce_matches_full_batch_grads():
    """Per-shard grads + DDP reduction == full-batch grads (the
    reference's DDP contract)."""
    mesh = comm.initialize(data=8)
    w = jnp.ones((16,))
    x = jax.random.normal(jax.random.key(4), (64, 16))
    y = jax.random.normal(jax.random.key(5), (64,))

    def loss_fn(w, x, y):
        return jnp.mean((x @ w - y) ** 2)

    full_grad = jax.grad(loss_fn)(w, x, y)
    ddp = DistributedDataParallel(None)

    def step(w, xs, ys):
        g = jax.grad(loss_fn)(w, xs, ys)
        return ddp.reduce_gradients(g)

    g = jax.jit(comm.shard_map(
        step, mesh, in_specs=(P(), P(comm.AXIS_DATA), P(comm.AXIS_DATA)),
        out_specs=P()))(w, x, y)
    np.testing.assert_allclose(g, full_grad, rtol=1e-5, atol=1e-6)


def test_ddp_outside_shard_map_is_identity():
    ddp = DistributedDataParallel(None)
    g = {"w": jnp.ones((4,))}
    out = ddp.reduce_gradients(g)
    np.testing.assert_array_equal(out["w"], g["w"])


def test_larc_clips_effective_lr():
    p = {"w": jnp.full((8,), 10.0)}   # large params -> adaptive >> lr
    g = {"w": jnp.full((8,), 0.01)}
    opt = FusedSGD(p, lr=0.1)
    larc = LARC(opt, trust_coefficient=0.02, clip=True)
    new = larc.step(g)
    # clipped: effective lr == lr, so update == lr * g
    np.testing.assert_allclose(np.asarray(new["w"]),
                               10.0 - 0.1 * 0.01, rtol=1e-5)


def test_larc_adaptive_when_unclipped():
    p = {"w": jnp.full((4,), 1.0)}
    g = {"w": jnp.full((4,), 100.0)}  # huge grads -> adaptive < lr
    opt = FusedSGD(p, lr=0.1)
    larc = LARC(opt, trust_coefficient=0.02, clip=True)
    new = larc.step(g)
    p_norm, g_norm = 2.0, 200.0
    adaptive = 0.02 * p_norm / g_norm      # 2e-4, /lr=2e-3 < 1 -> unclipped
    want = 1.0 - 0.1 * (adaptive / 0.1) * 100.0
    np.testing.assert_allclose(np.asarray(new["w"]), want, rtol=1e-4)


def test_convert_syncbn_from_flax_batchnorm():
    import flax.linen as nn
    import jax.numpy as jnp
    from apex_tpu.parallel import convert_syncbn_model
    sbn = convert_syncbn_model(nn.BatchNorm(use_running_average=False))
    x = jax.random.normal(jax.random.key(11), (16, 8)) + 3.0
    v = sbn.init(jax.random.key(0), x, use_running_average=False)
    y, _ = sbn.apply(v, x, use_running_average=False,
                     mutable=["batch_stats"])
    np.testing.assert_allclose(np.asarray(jnp.mean(y, 0)), 0.0, atol=1e-5)


def test_syncbn_large_mean_stability():
    """Chan-combined stats survive mean >> std (sum/sumsq would not)."""
    from apex_tpu.parallel import sync_batch_norm_stats
    x = 300.0 + 0.05 * jax.random.normal(jax.random.key(12), (4096, 4))
    mean, var, n = sync_batch_norm_stats(x, None)
    np.testing.assert_allclose(np.asarray(var),
                               np.asarray(jnp.var(x, 0)), rtol=1e-2)
    assert float(var.min()) > 1e-4


def test_ddp_syncbn_resnet_config5_matches_full_batch():
    """BASELINE config 5 at CI scale: DDP + SyncBatchNorm on a
    Bottleneck ResNet (resnet101's block family, tiny depth) over a
    dp=8 mesh.  The whole point of SyncBN under DDP: per-shard grads
    after the DDP reduction equal the single-device FULL-batch grads,
    because the BN stats are synced over the data axis."""
    import functools
    from apex_tpu.models import ResNet
    from apex_tpu.models.resnet import Bottleneck as RBottleneck

    model = ResNet(
        block_cls=RBottleneck, stage_sizes=[1, 1], num_classes=4,
        width=8,
        norm_cls=functools.partial(SyncBatchNorm, channel_last=True,
                                   process_group=comm.AXIS_DATA))
    x = jax.random.normal(jax.random.key(0), (16, 32, 32, 3))
    y = jax.random.randint(jax.random.key(1), (16,), 0, 4)
    variables = model.init(jax.random.key(2), x, train=False)
    params, stats = variables["params"], variables["batch_stats"]

    def loss_fn(p, st, xs, ys):
        logits, upd = model.apply({"params": p, "batch_stats": st},
                                  xs, train=True,
                                  mutable=["batch_stats"])
        onehot = jax.nn.one_hot(ys, 4)
        loss = -jnp.mean(jnp.sum(
            jax.nn.log_softmax(logits) * onehot, axis=-1))
        return loss, upd["batch_stats"]

    # oracle: single device, full batch (no axis bound -> local stats
    # ARE full-batch stats)
    comm.destroy()
    (want_loss, want_stats), want_g = jax.value_and_grad(
        loss_fn, has_aux=True)(params, stats, x, y)

    # dp=8: batch sharded, SyncBN syncs stats, DDP reduces grads
    mesh = comm.initialize(data=8)
    ddp = DistributedDataParallel(None)

    def step(p, st, xs, ys):
        (loss, new_st), g = jax.value_and_grad(
            loss_fn, has_aux=True)(p, st, xs, ys)
        return (jax.lax.pmean(loss, comm.AXIS_DATA),
                jax.tree_util.tree_map(
                    lambda s: jax.lax.pmean(s, comm.AXIS_DATA), new_st),
                ddp.reduce_gradients(g))

    loss, new_stats, g = jax.jit(comm.shard_map(
        step, mesh,
        in_specs=(P(), P(), P(comm.AXIS_DATA), P(comm.AXIS_DATA)),
        out_specs=(P(), P(), P())))(params, stats, x, y)

    np.testing.assert_allclose(float(loss), float(want_loss),
                               rtol=1e-5, atol=1e-6)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5),
        g, want_g)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5),
        new_stats, want_stats)
