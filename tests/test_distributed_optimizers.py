"""contrib.optimizers (ZeRO-sharded Adam/LAMB) vs the single-device
fused optimizers (reference pattern: distributed optimizer vs its
non-distributed oracle, apex/contrib/test/optimizers/)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.contrib.optimizers import (
    DistributedFusedAdam,
    DistributedFusedLAMB,
)
from apex_tpu.optimizers import FusedAdam, FusedLAMB


def _tree(seed=0):
    k = jax.random.PRNGKey(seed)
    k1, k2, k3 = jax.random.split(k, 3)
    return {
        "w": jax.random.normal(k1, (33, 17)),     # odd sizes force padding
        "b": jax.random.normal(k2, (7,)),
        "e": jax.random.normal(k3, (5, 3)),
    }


def _grads(seed=1):
    return jax.tree_util.tree_map(
        lambda x: x * 0.1 + 0.01, _tree(seed))


def test_requires_mesh():
    with pytest.raises(RuntimeError, match="mesh"):
        DistributedFusedAdam(_tree())


def test_distributed_adam_matches_fused_adam(mesh8):
    params = _tree()
    dopt = DistributedFusedAdam(params, lr=1e-2, weight_decay=0.01)
    ropt = FusedAdam(params, lr=1e-2, weight_decay=0.01)
    p_d, p_r = params, params
    for i in range(5):
        g = _grads(seed=10 + i)
        p_d = dopt.step(g)
        p_r = ropt.step(g)
    for k in params:
        np.testing.assert_allclose(np.asarray(p_d[k]), np.asarray(p_r[k]),
                                   rtol=1e-5, atol=1e-6)


def test_distributed_adam_state_is_sharded(mesh8):
    dopt = DistributedFusedAdam(_tree(), lr=1e-2)
    spec = dopt.state[0].sharding.spec
    assert spec == P("data")
    # shard buffer length divisible by axis size
    assert dopt.state[0].shape[0] % mesh8.shape["data"] == 0


def test_distributed_lamb_matches_fused_lamb_single_tensor(mesh8):
    # one-leaf tree: flat-buffer trust ratio == per-tensor trust ratio
    params = {"w": jax.random.normal(jax.random.PRNGKey(0), (64, 8))}
    dopt = DistributedFusedLAMB(params, lr=1e-2, weight_decay=0.01,
                                max_grad_norm=0.0)
    ropt = FusedLAMB(params, lr=1e-2, weight_decay=0.01,
                     max_grad_norm=0.0)
    p_d = p_r = params
    for i in range(3):
        g = {"w": jax.random.normal(jax.random.PRNGKey(5 + i),
                                    (64, 8)) * 0.1}
        p_d = dopt.step(g)
        p_r = ropt.step(g)
    np.testing.assert_allclose(np.asarray(p_d["w"]), np.asarray(p_r["w"]),
                               rtol=2e-5, atol=1e-6)


def test_distributed_lamb_clips_global_norm(mesh8):
    params = {"w": jnp.ones((16,))}
    dopt = DistributedFusedLAMB(params, lr=1e-3, max_grad_norm=1.0,
                                weight_decay=0.0)
    big = {"w": jnp.full((16,), 100.0)}
    small = {"w": jnp.full((16,), 100.0) / float(jnp.linalg.norm(
        jnp.full((16,), 100.0)))}
    p1 = dopt.step(big)
    dopt2 = DistributedFusedLAMB(params, lr=1e-3, max_grad_norm=1.0,
                                 weight_decay=0.0)
    p2 = dopt2.step(small)
    np.testing.assert_allclose(np.asarray(p1["w"]), np.asarray(p2["w"]),
                               rtol=1e-5)


def test_distributed_adam_grad_scale_and_state_dict(mesh8):
    params = _tree()
    a = DistributedFusedAdam(params, lr=1e-2)
    b = DistributedFusedAdam(params, lr=1e-2)
    g = _grads()
    pa = a.step(jax.tree_util.tree_map(lambda x: x * 8.0, g),
                grad_scale=8.0)
    pb = b.step(g)
    for k in params:
        np.testing.assert_allclose(np.asarray(pa[k]), np.asarray(pb[k]),
                                   rtol=1e-5, atol=1e-6)
    sd = a.state_dict()
    c = DistributedFusedAdam(params, lr=1e-2)
    c.load_state_dict(sd)
    pc = c.step(g)
    pa2 = a.step(g)
    np.testing.assert_allclose(np.asarray(pc["w"]), np.asarray(pa2["w"]),
                               rtol=1e-5, atol=1e-6)
