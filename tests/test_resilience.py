"""apex_tpu.resilience — crash-safe checkpoint rotation + resume
(SURVEY.md §5: the TPU recovery story the reference lacks)."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.resilience import CheckpointManager


def _train(mgr, steps, start=0):
    from apex_tpu.optimizers import FusedSGD
    params = {"w": jnp.ones((64,))}
    opt = FusedSGD(params, lr=0.1)
    g = {"w": jnp.full((64,), 0.01)}
    restored = mgr.restore_latest({"w": jnp.zeros((64,))}, opt)
    s0 = 0
    if restored is not None:
        _, _, s0 = restored
    for step in range(s0 + 1, steps + 1):
        opt.step(g)
        mgr.maybe_save(step, opt.params, opt)
    mgr.wait()
    return opt, s0


def test_rotation_keeps_newest_k(tmp_path):
    with CheckpointManager(str(tmp_path), keep=2, every=5) as mgr:
        _train(mgr, 30)
        assert mgr.steps_on_disk() == [25, 30]


def test_resume_continues_from_latest(tmp_path):
    with CheckpointManager(str(tmp_path), keep=3, every=5) as mgr:
        opt1, s0 = _train(mgr, 20)
        assert s0 == 0
    with CheckpointManager(str(tmp_path), keep=3, every=5) as mgr:
        opt2, s0 = _train(mgr, 20)   # "crash" and restart at 20
        assert s0 == 20              # no extra steps run
    np.testing.assert_array_equal(np.asarray(opt1.params["w"]),
                                  np.asarray(opt2.params["w"]))


def test_corrupt_newest_falls_back_to_previous(tmp_path):
    with CheckpointManager(str(tmp_path), keep=3, every=5) as mgr:
        _train(mgr, 15)
        steps = mgr.steps_on_disk()
        assert steps == [5, 10, 15]
        # truncate the newest (mid-write crash artifact)
        p = os.path.join(str(tmp_path), "step-15.ckpt")
        data = open(p, "rb").read()
        open(p, "wb").write(data[:len(data) // 2])
        from apex_tpu.optimizers import FusedSGD
        opt = FusedSGD({"w": jnp.zeros((64,))}, lr=0.1)
        restored = mgr.restore_latest({"w": jnp.zeros((64,))}, opt)
        assert restored is not None
        _, _, step = restored
        assert step == 10            # newest VALID


def test_empty_dir_returns_none(tmp_path):
    with CheckpointManager(str(tmp_path / "fresh"), every=5) as mgr:
        assert mgr.restore_latest({"w": jnp.zeros((4,))}) is None


def test_bad_config_rejected(tmp_path):
    with pytest.raises(ValueError):
        CheckpointManager(str(tmp_path), keep=0)
    with pytest.raises(ValueError):
        CheckpointManager(str(tmp_path), every=0)


def test_template_mismatch_raises_not_skips(tmp_path):
    """A wrong restore template is a caller bug (code-review r2): it
    must raise, not silently restart from step 0."""
    from apex_tpu.checkpoint import TemplateMismatchError
    with CheckpointManager(str(tmp_path), keep=3, every=5) as mgr:
        _train(mgr, 10)
        with pytest.raises(TemplateMismatchError):
            mgr.restore_latest({"w": jnp.zeros((8,))})   # wrong shape


def test_gc_never_drops_below_keep_durable(tmp_path):
    """While a save is in flight, the durable window stays intact
    (keep=1 regression: a failed in-flight write must not leave zero)."""
    with CheckpointManager(str(tmp_path), keep=1, every=5) as mgr:
        from apex_tpu.optimizers import FusedSGD
        opt = FusedSGD({"w": jnp.ones((64,))}, lr=0.1)
        g = {"w": jnp.full((64,), 0.01)}
        for _ in range(5):
            opt.step(g)
        mgr.maybe_save(5, opt.params, opt)
        mgr.wait()                            # step-5 durable
        assert mgr.steps_on_disk() == [5]
        for _ in range(5):
            opt.step(g)
        mgr.maybe_save(10, opt.params, opt)   # step-10 in flight
        # the one durable checkpoint must still exist right after the
        # new save was scheduled and _gc ran
        assert 5 in mgr.steps_on_disk()
        mgr.wait()
        assert mgr.steps_on_disk() == [10]    # trimmed to keep


def test_orphaned_tmp_cleared_on_init(tmp_path):
    """A crash mid-write leaves step-N.ckpt.tmp behind; a new manager
    in the same directory must clear it (advisor r2)."""
    orphan = tmp_path / "step-5.ckpt.tmp"
    orphan.write_bytes(b"garbage from a dead process")
    with CheckpointManager(str(tmp_path), keep=3, every=5):
        assert not orphan.exists()


def test_corrupt_skip_emits_warning(tmp_path):
    """Skipping a corrupt checkpoint at restore must be observable
    (advisor r2): silence here means an unexplained restart-from-
    scratch."""
    with CheckpointManager(str(tmp_path), keep=3, every=5) as mgr:
        _train(mgr, 10)
        mgr.wait()
        newest = max(mgr.steps_on_disk())
        p = tmp_path / f"step-{newest}.ckpt"
        p.write_bytes(p.read_bytes()[:20])    # truncate = crash artifact
        from apex_tpu.optimizers import FusedSGD
        opt = FusedSGD({"w": jnp.zeros((64,))}, lr=0.1)
        with pytest.warns(UserWarning, match="skipping .*step-%d" % newest):
            out = mgr.restore_latest({"w": jnp.zeros((64,))}, opt)
        assert out is not None                # fell back to older step


# ---------------------------------------------------------------------
# Multi-host resume agreement (VERDICT r3 #5): simulate a 2-host
# cluster by faking the manager's collective hooks — each test drives
# one host's restore with a pre-recorded view of its peer's allgather
# contributions.
# ---------------------------------------------------------------------

def _fake_peer(mgr, peer_steps, peer_ok=1, rank=1):
    """Make mgr see a 2-process cluster whose other host holds
    ``peer_steps`` on disk and reports ``peer_ok`` for every load."""
    cap = max(mgr._SYNC_CAP, mgr.keep + 2)

    def allgather(arr):
        arr = np.asarray(arr)
        if arr.shape == (1,):                      # per-step ok flag
            peer = np.asarray([peer_ok], np.int64)
        else:                                      # step-set vector
            peer = np.full((cap,), -1, np.int64)
            tail = sorted(peer_steps)[-cap:]
            peer[:len(tail)] = tail
        pair = (peer, arr) if rank == 1 else (arr, peer)
        return np.stack(pair)

    mgr._allgather = allgather
    mgr._process_count = lambda: 2


def test_multihost_nonwriter_resumes_from_host0_step(tmp_path):
    """Shared filesystem, all_hosts=False: the NON-writer host's
    restore lands on host 0's newest step via the agreement protocol
    (previously it just read the same files by luck; now it is a
    contract)."""
    with CheckpointManager(str(tmp_path), keep=3, every=5) as mgr0:
        _train(mgr0, 15)
        host0_steps = mgr0.steps_on_disk()
        assert host0_steps == [5, 10, 15]

    # host 1: same (shared) directory, not the writer
    from apex_tpu.optimizers import FusedSGD
    mgr1 = CheckpointManager(str(tmp_path), keep=3, every=5)
    _fake_peer(mgr1, host0_steps, rank=1)
    opt = FusedSGD({"w": jnp.zeros((64,))}, lr=0.1)
    out = mgr1.restore_latest({"w": jnp.zeros((64,))}, opt)
    assert out is not None
    assert out[2] == 15                    # host 0's newest step
    mgr1.close()


def test_multihost_partial_publish_agrees_on_common_step(tmp_path):
    """Per-host disks, all_hosts=True: the peer missed the newest
    publish (crash between hosts' writes) — both sides must fall back
    to the newest COMMON step, not their own newest."""
    d0 = tmp_path / "h0"
    with CheckpointManager(str(d0), keep=3, every=5,
                           all_hosts=True) as mgr0:
        _train(mgr0, 15)
        assert mgr0.steps_on_disk() == [5, 10, 15]

    from apex_tpu.optimizers import FusedSGD
    mgr = CheckpointManager(str(d0), keep=3, every=5, all_hosts=True)
    _fake_peer(mgr, [5, 10], rank=0)       # peer never published 15
    opt = FusedSGD({"w": jnp.zeros((64,))}, lr=0.1)
    out = mgr.restore_latest({"w": jnp.zeros((64,))}, opt)
    assert out is not None
    assert out[2] == 10                    # newest step EVERY host has
    mgr.close()


def test_multihost_no_common_steps_starts_fresh_with_warning(tmp_path):
    """Per-host disks, all_hosts=False: host 0 has checkpoints, the
    peer has none — the cluster must start fresh TOGETHER (host 0
    warns), never host-0-resumes-while-peers-restart."""
    with CheckpointManager(str(tmp_path), keep=3, every=5) as mgr0:
        _train(mgr0, 10)

    from apex_tpu.optimizers import FusedSGD
    mgr = CheckpointManager(str(tmp_path), keep=3, every=5)
    _fake_peer(mgr, [], rank=0)            # peer disk is empty
    opt = FusedSGD({"w": jnp.zeros((64,))}, lr=0.1)
    with pytest.warns(UserWarning, match="cluster shares none"):
        out = mgr.restore_latest({"w": jnp.zeros((64,))}, opt)
    assert out is None
    mgr.close()


def test_multihost_peer_reject_rolls_back_optimizer(tmp_path):
    """A step that loads locally but fails on a peer is discarded; if
    the whole walk ends fresh, the optimizer must be back to its
    pre-restore state (the discarded load had mutated it)."""
    with CheckpointManager(str(tmp_path), keep=2, every=5) as mgr0:
        _train(mgr0, 10)

    from apex_tpu.optimizers import FusedSGD
    mgr = CheckpointManager(str(tmp_path), keep=2, every=5)
    # peer holds the same steps but every load fails over there
    _fake_peer(mgr, mgr.steps_on_disk(), peer_ok=0, rank=0)
    opt = FusedSGD({"w": jnp.zeros((64,))}, lr=0.1)
    before = np.asarray(opt.params["w"]).copy()
    with pytest.warns(UserWarning, match="failed on another host"):
        out = mgr.restore_latest({"w": jnp.zeros((64,))}, opt)
    assert out is None
    np.testing.assert_array_equal(np.asarray(opt.params["w"]), before)
    mgr.close()


def test_multihost_template_mismatch_aborts_cluster_in_lockstep(tmp_path):
    """A template mismatch on ANY host must abort the restore on EVERY
    host (code-review r4): a lone raiser would strand its peers inside
    the next collective."""
    from apex_tpu.checkpoint import TemplateMismatchError
    from apex_tpu.optimizers import FusedSGD

    with CheckpointManager(str(tmp_path), keep=2, every=5) as mgr0:
        _train(mgr0, 10)

    # this host loads fine; the PEER reports a template mismatch (2)
    mgr = CheckpointManager(str(tmp_path), keep=2, every=5)
    _fake_peer(mgr, mgr.steps_on_disk(), peer_ok=2, rank=0)
    opt = FusedSGD({"w": jnp.zeros((64,))}, lr=0.1)
    with pytest.raises(TemplateMismatchError, match="another host"):
        mgr.restore_latest({"w": jnp.zeros((64,))}, opt)
    mgr.close()


def test_multihost_stranded_checkpoints_warn_from_any_host(tmp_path):
    """The fresh-start warning must fire even when host 0's own disk is
    empty and only a PEER holds checkpoints (code-review r4)."""
    from apex_tpu.optimizers import FusedSGD

    empty = tmp_path / "empty"
    mgr = CheckpointManager(str(empty), keep=2, every=5)
    assert mgr.steps_on_disk() == []
    _fake_peer(mgr, [5, 10], rank=0)       # peer has files, we don't
    opt = FusedSGD({"w": jnp.zeros((64,))}, lr=0.1)
    with pytest.warns(UserWarning, match="cluster shares none"):
        out = mgr.restore_latest({"w": jnp.zeros((64,))}, opt)
    assert out is None
    mgr.close()


def test_multihost_fatal_abort_rolls_back_local_optimizer(tmp_path):
    """When a peer's template mismatch aborts the restore, a host whose
    OWN load succeeded must hand back a pristine optimizer with the
    raise (code-review r4): callers catching the abort to fall back to
    fresh training must not inherit a half-restored optimizer."""
    from apex_tpu.checkpoint import TemplateMismatchError
    from apex_tpu.optimizers import FusedSGD

    with CheckpointManager(str(tmp_path), keep=2, every=5) as mgr0:
        _train(mgr0, 10)

    mgr = CheckpointManager(str(tmp_path), keep=2, every=5)
    _fake_peer(mgr, mgr.steps_on_disk(), peer_ok=2, rank=0)
    opt = FusedSGD({"w": jnp.zeros((64,))}, lr=0.1)
    before = np.asarray(opt.params["w"]).copy()
    with pytest.raises(TemplateMismatchError):
        mgr.restore_latest({"w": jnp.zeros((64,))}, opt)
    np.testing.assert_array_equal(np.asarray(opt.params["w"]), before)
    mgr.close()
