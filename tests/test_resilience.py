"""apex_tpu.resilience — crash-safe checkpoint rotation + resume
(SURVEY.md §5: the TPU recovery story the reference lacks)."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.resilience import CheckpointManager


def _train(mgr, steps, start=0):
    from apex_tpu.optimizers import FusedSGD
    params = {"w": jnp.ones((64,))}
    opt = FusedSGD(params, lr=0.1)
    g = {"w": jnp.full((64,), 0.01)}
    restored = mgr.restore_latest({"w": jnp.zeros((64,))}, opt)
    s0 = 0
    if restored is not None:
        _, _, s0 = restored
    for step in range(s0 + 1, steps + 1):
        opt.step(g)
        mgr.maybe_save(step, opt.params, opt)
    mgr.wait()
    return opt, s0


def test_rotation_keeps_newest_k(tmp_path):
    with CheckpointManager(str(tmp_path), keep=2, every=5) as mgr:
        _train(mgr, 30)
        assert mgr.steps_on_disk() == [25, 30]


def test_resume_continues_from_latest(tmp_path):
    with CheckpointManager(str(tmp_path), keep=3, every=5) as mgr:
        opt1, s0 = _train(mgr, 20)
        assert s0 == 0
    with CheckpointManager(str(tmp_path), keep=3, every=5) as mgr:
        opt2, s0 = _train(mgr, 20)   # "crash" and restart at 20
        assert s0 == 20              # no extra steps run
    np.testing.assert_array_equal(np.asarray(opt1.params["w"]),
                                  np.asarray(opt2.params["w"]))


def test_corrupt_newest_falls_back_to_previous(tmp_path):
    with CheckpointManager(str(tmp_path), keep=3, every=5) as mgr:
        _train(mgr, 15)
        steps = mgr.steps_on_disk()
        assert steps == [5, 10, 15]
        # truncate the newest (mid-write crash artifact)
        p = os.path.join(str(tmp_path), "step-15.ckpt")
        data = open(p, "rb").read()
        open(p, "wb").write(data[:len(data) // 2])
        from apex_tpu.optimizers import FusedSGD
        opt = FusedSGD({"w": jnp.zeros((64,))}, lr=0.1)
        restored = mgr.restore_latest({"w": jnp.zeros((64,))}, opt)
        assert restored is not None
        _, _, step = restored
        assert step == 10            # newest VALID


def test_empty_dir_returns_none(tmp_path):
    with CheckpointManager(str(tmp_path / "fresh"), every=5) as mgr:
        assert mgr.restore_latest({"w": jnp.zeros((4,))}) is None


def test_bad_config_rejected(tmp_path):
    with pytest.raises(ValueError):
        CheckpointManager(str(tmp_path), keep=0)
    with pytest.raises(ValueError):
        CheckpointManager(str(tmp_path), every=0)


def test_template_mismatch_raises_not_skips(tmp_path):
    """A wrong restore template is a caller bug (code-review r2): it
    must raise, not silently restart from step 0."""
    from apex_tpu.checkpoint import TemplateMismatchError
    with CheckpointManager(str(tmp_path), keep=3, every=5) as mgr:
        _train(mgr, 10)
        with pytest.raises(TemplateMismatchError):
            mgr.restore_latest({"w": jnp.zeros((8,))})   # wrong shape


def test_gc_never_drops_below_keep_durable(tmp_path):
    """While a save is in flight, the durable window stays intact
    (keep=1 regression: a failed in-flight write must not leave zero)."""
    with CheckpointManager(str(tmp_path), keep=1, every=5) as mgr:
        from apex_tpu.optimizers import FusedSGD
        opt = FusedSGD({"w": jnp.ones((64,))}, lr=0.1)
        g = {"w": jnp.full((64,), 0.01)}
        for _ in range(5):
            opt.step(g)
        mgr.maybe_save(5, opt.params, opt)
        mgr.wait()                            # step-5 durable
        assert mgr.steps_on_disk() == [5]
        for _ in range(5):
            opt.step(g)
        mgr.maybe_save(10, opt.params, opt)   # step-10 in flight
        # the one durable checkpoint must still exist right after the
        # new save was scheduled and _gc ran
        assert 5 in mgr.steps_on_disk()
        mgr.wait()
        assert mgr.steps_on_disk() == [10]    # trimmed to keep


def test_orphaned_tmp_cleared_on_init(tmp_path):
    """A crash mid-write leaves step-N.ckpt.tmp behind; a new manager
    in the same directory must clear it (advisor r2)."""
    orphan = tmp_path / "step-5.ckpt.tmp"
    orphan.write_bytes(b"garbage from a dead process")
    with CheckpointManager(str(tmp_path), keep=3, every=5):
        assert not orphan.exists()


def test_corrupt_skip_emits_warning(tmp_path):
    """Skipping a corrupt checkpoint at restore must be observable
    (advisor r2): silence here means an unexplained restart-from-
    scratch."""
    with CheckpointManager(str(tmp_path), keep=3, every=5) as mgr:
        _train(mgr, 10)
        mgr.wait()
        newest = max(mgr.steps_on_disk())
        p = tmp_path / f"step-{newest}.ckpt"
        p.write_bytes(p.read_bytes()[:20])    # truncate = crash artifact
        from apex_tpu.optimizers import FusedSGD
        opt = FusedSGD({"w": jnp.zeros((64,))}, lr=0.1)
        with pytest.warns(UserWarning, match="skipping .*step-%d" % newest):
            out = mgr.restore_latest({"w": jnp.zeros((64,))}, opt)
        assert out is not None                # fell back to older step


# ---------------------------------------------------------------------
# Multi-host resume agreement (VERDICT r3 #5): simulate a 2-host
# cluster by faking the manager's collective hooks — each test drives
# one host's restore with a pre-recorded view of its peer's allgather
# contributions.
# ---------------------------------------------------------------------

def _fake_peer(mgr, peer_steps, peer_ok=1, rank=1):
    """Make mgr see a 2-process cluster whose other host holds
    ``peer_steps`` on disk and reports ``peer_ok`` for every load."""
    cap = max(mgr._SYNC_CAP, mgr.keep + 2)

    def allgather(arr):
        arr = np.asarray(arr)
        if arr.shape == (1,):                      # per-step ok flag
            peer = np.asarray([peer_ok], np.int64)
        else:                                      # step-set vector
            peer = np.full((cap,), -1, np.int64)
            tail = sorted(peer_steps)[-cap:]
            peer[:len(tail)] = tail
        pair = (peer, arr) if rank == 1 else (arr, peer)
        return np.stack(pair)

    mgr._allgather = allgather
    mgr._process_count = lambda: 2


def test_multihost_nonwriter_resumes_from_host0_step(tmp_path):
    """Shared filesystem, all_hosts=False: the NON-writer host's
    restore lands on host 0's newest step via the agreement protocol
    (previously it just read the same files by luck; now it is a
    contract)."""
    with CheckpointManager(str(tmp_path), keep=3, every=5) as mgr0:
        _train(mgr0, 15)
        host0_steps = mgr0.steps_on_disk()
        assert host0_steps == [5, 10, 15]

    # host 1: same (shared) directory, not the writer
    from apex_tpu.optimizers import FusedSGD
    mgr1 = CheckpointManager(str(tmp_path), keep=3, every=5)
    _fake_peer(mgr1, host0_steps, rank=1)
    opt = FusedSGD({"w": jnp.zeros((64,))}, lr=0.1)
    out = mgr1.restore_latest({"w": jnp.zeros((64,))}, opt)
    assert out is not None
    assert out[2] == 15                    # host 0's newest step
    mgr1.close()


def test_multihost_partial_publish_agrees_on_common_step(tmp_path):
    """Per-host disks, all_hosts=True: the peer missed the newest
    publish (crash between hosts' writes) — both sides must fall back
    to the newest COMMON step, not their own newest."""
    d0 = tmp_path / "h0"
    with CheckpointManager(str(d0), keep=3, every=5,
                           all_hosts=True) as mgr0:
        _train(mgr0, 15)
        assert mgr0.steps_on_disk() == [5, 10, 15]

    from apex_tpu.optimizers import FusedSGD
    mgr = CheckpointManager(str(d0), keep=3, every=5, all_hosts=True)
    _fake_peer(mgr, [5, 10], rank=0)       # peer never published 15
    opt = FusedSGD({"w": jnp.zeros((64,))}, lr=0.1)
    out = mgr.restore_latest({"w": jnp.zeros((64,))}, opt)
    assert out is not None
    assert out[2] == 10                    # newest step EVERY host has
    mgr.close()


def test_multihost_no_common_steps_starts_fresh_with_warning(tmp_path):
    """Per-host disks, all_hosts=False: host 0 has checkpoints, the
    peer has none — the cluster must start fresh TOGETHER (host 0
    warns), never host-0-resumes-while-peers-restart."""
    with CheckpointManager(str(tmp_path), keep=3, every=5) as mgr0:
        _train(mgr0, 10)

    from apex_tpu.optimizers import FusedSGD
    mgr = CheckpointManager(str(tmp_path), keep=3, every=5)
    _fake_peer(mgr, [], rank=0)            # peer disk is empty
    opt = FusedSGD({"w": jnp.zeros((64,))}, lr=0.1)
    with pytest.warns(UserWarning, match="cluster shares none"):
        out = mgr.restore_latest({"w": jnp.zeros((64,))}, opt)
    assert out is None
    mgr.close()


def test_multihost_peer_reject_rolls_back_optimizer(tmp_path):
    """A step that loads locally but fails on a peer is discarded; if
    the whole walk ends fresh, the optimizer must be back to its
    pre-restore state (the discarded load had mutated it)."""
    with CheckpointManager(str(tmp_path), keep=2, every=5) as mgr0:
        _train(mgr0, 10)

    from apex_tpu.optimizers import FusedSGD
    mgr = CheckpointManager(str(tmp_path), keep=2, every=5)
    # peer holds the same steps but every load fails over there
    _fake_peer(mgr, mgr.steps_on_disk(), peer_ok=0, rank=0)
    opt = FusedSGD({"w": jnp.zeros((64,))}, lr=0.1)
    before = np.asarray(opt.params["w"]).copy()
    with pytest.warns(UserWarning, match="failed on another host"):
        out = mgr.restore_latest({"w": jnp.zeros((64,))}, opt)
    assert out is None
    np.testing.assert_array_equal(np.asarray(opt.params["w"]), before)
    mgr.close()


def test_multihost_template_mismatch_aborts_cluster_in_lockstep(tmp_path):
    """A template mismatch on ANY host must abort the restore on EVERY
    host (code-review r4): a lone raiser would strand its peers inside
    the next collective."""
    from apex_tpu.checkpoint import TemplateMismatchError
    from apex_tpu.optimizers import FusedSGD

    with CheckpointManager(str(tmp_path), keep=2, every=5) as mgr0:
        _train(mgr0, 10)

    # this host loads fine; the PEER reports a template mismatch (2)
    mgr = CheckpointManager(str(tmp_path), keep=2, every=5)
    _fake_peer(mgr, mgr.steps_on_disk(), peer_ok=2, rank=0)
    opt = FusedSGD({"w": jnp.zeros((64,))}, lr=0.1)
    with pytest.raises(TemplateMismatchError, match="another host"):
        mgr.restore_latest({"w": jnp.zeros((64,))}, opt)
    mgr.close()


def test_multihost_stranded_checkpoints_warn_from_any_host(tmp_path):
    """The fresh-start warning must fire even when host 0's own disk is
    empty and only a PEER holds checkpoints (code-review r4)."""
    from apex_tpu.optimizers import FusedSGD

    empty = tmp_path / "empty"
    mgr = CheckpointManager(str(empty), keep=2, every=5)
    assert mgr.steps_on_disk() == []
    _fake_peer(mgr, [5, 10], rank=0)       # peer has files, we don't
    opt = FusedSGD({"w": jnp.zeros((64,))}, lr=0.1)
    with pytest.warns(UserWarning, match="cluster shares none"):
        out = mgr.restore_latest({"w": jnp.zeros((64,))}, opt)
    assert out is None
    mgr.close()


def test_multihost_fatal_abort_rolls_back_local_optimizer(tmp_path):
    """When a peer's template mismatch aborts the restore, a host whose
    OWN load succeeded must hand back a pristine optimizer with the
    raise (code-review r4): callers catching the abort to fall back to
    fresh training must not inherit a half-restored optimizer."""
    from apex_tpu.checkpoint import TemplateMismatchError
    from apex_tpu.optimizers import FusedSGD

    with CheckpointManager(str(tmp_path), keep=2, every=5) as mgr0:
        _train(mgr0, 10)

    mgr = CheckpointManager(str(tmp_path), keep=2, every=5)
    _fake_peer(mgr, mgr.steps_on_disk(), peer_ok=2, rank=0)
    opt = FusedSGD({"w": jnp.zeros((64,))}, lr=0.1)
    before = np.asarray(opt.params["w"]).copy()
    with pytest.raises(TemplateMismatchError):
        mgr.restore_latest({"w": jnp.zeros((64,))}, opt)
    np.testing.assert_array_equal(np.asarray(opt.params["w"]), before)
    mgr.close()


# =====================================================================
# ISSUE 6: bucket-native v2 checkpoints, preemption-safe restart, and
# the fault-injection chaos matrix.
# =====================================================================

import signal
import threading
import time

from apex_tpu import checkpoint as ckpt_mod
from apex_tpu.amp import LossScaler
from apex_tpu.optimizers import FusedAdam
from apex_tpu.resilience import ElasticResult, PreemptionGuard, run_elastic
from apex_tpu.resilience.faults import (FaultInjector, FaultSpec,
                                        InjectedCrash)


def _mixed_tree():
    """Small mixed-dtype tree: bf16 matmul weights + f32 vectors — two
    dtype buckets, auto-created f32 masters (the amp-O2 state mix)."""
    return {
        "w1": jnp.linspace(-1.0, 1.0, 256).astype(jnp.bfloat16
                                                  ).reshape(16, 16),
        "b1": jnp.linspace(0.0, 1.0, 16).astype(jnp.float32),
        "w2": jnp.linspace(0.5, -0.5, 64).astype(jnp.bfloat16
                                                 ).reshape(8, 8),
        "s": jnp.ones((3,), jnp.float32),
    }


def _grads_for(tree):
    return jax.tree_util.tree_map(
        lambda p: (p.astype(jnp.float32) * 1e-2 + 1e-3).astype(p.dtype),
        tree)


def _assert_tree_equal(a, b):
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def _opt_states_equal(o1, o2):
    s1, s2 = o1.state_dict(), o2.state_dict()
    assert int(s1["step"]) == int(s2["step"])
    _assert_tree_equal(s1["state"], s2["state"])
    _assert_tree_equal(o1.params, o2.params)
    if s1.get("masters") is not None or s2.get("masters") is not None:
        _assert_tree_equal(s1["masters"], s2["masters"])


# ---------------------------------------------------------------------
# Format v2: bucket-native packed checkpoints
# ---------------------------------------------------------------------

def test_v2_roundtrip_packed_fast_path(tmp_path):
    """v2 save from a bucketed optimizer restores onto an identically
    planned optimizer via direct buffer adoption — and the file really
    is the v2 format."""
    tree = _mixed_tree()
    opt = FusedAdam(tree, lr=1e-2)
    g = _grads_for(tree)
    for _ in range(3):
        opt.step(g)
    p = str(tmp_path / "v2.ckpt")
    ckpt_mod.save_training_state(p, optimizer=opt, step=3,
                                 amp_state={"loss_scale": 8.0})
    header = ckpt_mod.read_checkpoint_header(p)
    assert header["magic"] == "APEX_TPU_CKPT_V2"
    assert header["plan"]["paths"]          # leaf identities recorded

    opt2 = FusedAdam(_mixed_tree(), lr=1e-2)
    params, amp_sd, step = ckpt_mod.load_training_state(
        p, jax.tree_util.tree_map(jnp.zeros_like, tree), opt2)
    assert step == 3 and amp_sd == {"loss_scale": 8.0}
    _opt_states_equal(opt, opt2)
    _assert_tree_equal(params, opt.params)


def test_v2_save_does_zero_per_leaf_unpack(tmp_path, monkeypatch):
    """Structural acceptance: the bucket-native save is exactly one
    device copy + one d2h per packed buffer — plan.unpack* is never
    called and no per-leaf traffic happens."""
    from apex_tpu.multi_tensor_apply.packer import BucketPlan
    from apex_tpu.optimizers import _base as base_mod

    tree = _mixed_tree()
    opt = FusedAdam(tree, lr=1e-2)
    opt.step(_grads_for(tree))

    def _boom(*a, **k):
        raise AssertionError("per-leaf unpack on the v2 save path")
    monkeypatch.setattr(BucketPlan, "unpack", _boom)
    monkeypatch.setattr(BucketPlan, "unpack_model", _boom)
    monkeypatch.setattr(BucketPlan, "unpack_state_field", _boom,
                        raising=False)

    copies, transfers = [], []
    real_copy, real_d2h = base_mod._device_copy, ckpt_mod._d2h
    monkeypatch.setattr(base_mod, "_device_copy",
                        lambda b: copies.append(1) or real_copy(b))
    monkeypatch.setattr(ckpt_mod, "_d2h",
                        lambda b: transfers.append(1) or real_d2h(b))

    p = str(tmp_path / "v2.ckpt")
    ckpt_mod.save_training_state(p, optimizer=opt, step=1)

    n_bufs = (len(opt._param_bufs)
              + (len(opt._master_bufs) if opt._master_bufs else 0)
              + sum(len(v) for v in opt.opt_state.values()))
    assert len(copies) == n_bufs        # ONE device copy per buffer
    assert len(transfers) == n_bufs     # ONE d2h per buffer
    assert ckpt_mod.read_checkpoint_header(p)["magic"] == \
        "APEX_TPU_CKPT_V2"


def test_v1_file_loads_into_bucketed_and_v2_into_perleaf(tmp_path):
    """Format interop both ways: v1 -> bucketed optimizer, and v2 ->
    fuse_buckets=False optimizer (the per-leaf fallback flow)."""
    tree = _mixed_tree()
    opt = FusedAdam(tree, lr=1e-2)
    g = _grads_for(tree)
    for _ in range(2):
        opt.step(g)

    p1 = str(tmp_path / "v1.ckpt")
    ckpt_mod.save_training_state(p1, optimizer=opt, step=2, format="v1")
    assert ckpt_mod.read_checkpoint_header(p1)["magic"] == \
        "APEX_TPU_CKPT_V1"
    opt_b = FusedAdam(_mixed_tree(), lr=1e-2)
    ckpt_mod.load_training_state(
        p1, jax.tree_util.tree_map(jnp.zeros_like, tree), opt_b)
    _opt_states_equal(opt, opt_b)

    p2 = str(tmp_path / "v2.ckpt")
    ckpt_mod.save_training_state(p2, optimizer=opt, step=2, format="v2")
    opt_pl = FusedAdam(_mixed_tree(), lr=1e-2, fuse_buckets=False)
    assert opt_pl._plan is None
    params, _, step = ckpt_mod.load_training_state(
        p2, jax.tree_util.tree_map(jnp.zeros_like, tree), opt_pl)
    assert step == 2
    _opt_states_equal(opt, opt_pl)


def test_v2_requires_bucketed_optimizer(tmp_path):
    opt = FusedAdam(_mixed_tree(), lr=1e-2, fuse_buckets=False)
    with pytest.raises(ValueError, match="bucketed"):
        ckpt_mod.save_training_state(str(tmp_path / "x.ckpt"),
                                     optimizer=opt, format="v2")


@pytest.mark.parametrize("ndev", [1, 2, 8])
def test_v2_reshard_restore_onto_different_device_count(tmp_path, ndev):
    """A v2 checkpoint restores onto a different mesh size via
    ``sharding=`` (conftest forces 8 faked CPU devices)."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec

    tree = _mixed_tree()
    opt = FusedAdam(tree, lr=1e-2)
    opt.step(_grads_for(tree))
    p = str(tmp_path / "v2.ckpt")
    ckpt_mod.save_training_state(p, optimizer=opt, step=1)

    devs = jax.devices()[:ndev]
    sharding = NamedSharding(Mesh(np.array(devs), ("x",)),
                             PartitionSpec())    # replicated over ndev
    opt2 = FusedAdam(_mixed_tree(), lr=1e-2)
    params, _, step = ckpt_mod.load_training_state(
        p, jax.tree_util.tree_map(jnp.zeros_like, tree), opt2,
        sharding=sharding)
    assert step == 1
    for leaf in jax.tree_util.tree_leaves(params):
        assert len(leaf.sharding.device_set) == ndev
    _assert_tree_equal(params, opt.params)
    _opt_states_equal(opt, opt2)


def test_v2_extra_section_roundtrip(tmp_path):
    tree = _mixed_tree()
    opt = FusedAdam(tree, lr=1e-2)
    opt.step(_grads_for(tree))
    extra = {"bn": {"mean": jnp.arange(4.0), "var": jnp.ones((4,))}}
    p = str(tmp_path / "v2.ckpt")
    ckpt_mod.save_training_state(p, optimizer=opt, step=1, extra=extra)
    out = ckpt_mod.load_training_state(
        p, jax.tree_util.tree_map(jnp.zeros_like, tree),
        FusedAdam(_mixed_tree(), lr=1e-2),
        extra_like=jax.tree_util.tree_map(jnp.zeros_like, extra))
    _assert_tree_equal(out[3], extra)


def test_v2_reshard_places_optimizer_state_on_sharding(tmp_path):
    """Flow (iii) reshards the WHOLE training state: optimizer moments
    must land on the requested sharding alongside params/masters (a
    model that only fits sharded would otherwise OOM device 0)."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec

    ndev = min(8, len(jax.devices()))
    if ndev < 2:
        pytest.skip("needs >= 2 devices")
    tree = _mixed_tree()
    opt = FusedAdam(tree, lr=1e-2)
    opt.step(_grads_for(tree))
    p = str(tmp_path / "v2.ckpt")
    ckpt_mod.save_training_state(p, optimizer=opt, step=1)

    sharding = NamedSharding(Mesh(np.array(jax.devices()[:ndev]), ("x",)),
                             PartitionSpec())    # replicated over ndev
    opt2 = FusedAdam(_mixed_tree(), lr=1e-2, fuse_buckets=False)
    ckpt_mod.load_training_state(
        p, jax.tree_util.tree_map(jnp.zeros_like, tree), opt2,
        sharding=sharding)
    for field, leaves in opt2.opt_state.items():
        for leaf in jax.tree_util.tree_leaves(leaves):
            assert len(leaf.sharding.device_set) == ndev, field
    _opt_states_equal(opt, opt2)


def test_explicit_params_are_honored_not_dropped(tmp_path):
    """``format='auto'`` with a caller-supplied params pytree (EMA /
    averaged weights distinct from the training weights) must save
    THOSE weights via per-leaf v1 — not silently snapshot the
    optimizer's packed training params; ``format='v2'`` rejects the
    combination loudly."""
    tree = _mixed_tree()
    opt = FusedAdam(tree, lr=1e-2)
    opt.step(_grads_for(tree))
    ema = jax.tree_util.tree_map(
        lambda p: (p.astype(jnp.float32) * 0.5).astype(p.dtype), tree)
    p = str(tmp_path / "ema.ckpt")
    ckpt_mod.save_training_state(p, ema, opt, step=1)
    out = ckpt_mod.load_training_state(
        p, jax.tree_util.tree_map(jnp.zeros_like, tree),
        FusedAdam(_mixed_tree(), lr=1e-2))
    _assert_tree_equal(out[0], ema)
    with pytest.raises(ValueError):
        ckpt_mod.save_training_state(p, ema, opt, step=1, format="v2")


def test_v2_extra_python_scalar_leaves_roundtrip(tmp_path):
    """Python int/float extra leaves must round-trip: the header dtype
    has to match the bytes numpy actually writes (a float32 default
    would shift every later extra leaf's payload offset)."""
    tree = _mixed_tree()
    opt = FusedAdam(tree, lr=1e-2)
    opt.step(_grads_for(tree))
    extra = {"epoch": 3, "best_loss": 0.125,
             "bn_mean": jnp.arange(4.0)}
    p = str(tmp_path / "v2.ckpt")
    ckpt_mod.save_training_state(p, optimizer=opt, step=1, extra=extra)
    out = ckpt_mod.load_training_state(
        p, jax.tree_util.tree_map(jnp.zeros_like, tree),
        FusedAdam(_mixed_tree(), lr=1e-2),
        extra_like={"epoch": 0, "best_loss": 0.0,
                    "bn_mean": jnp.zeros((4,))})
    got = out[3]
    assert int(got["epoch"]) == 3
    assert float(got["best_loss"]) == 0.125
    np.testing.assert_array_equal(np.asarray(got["bn_mean"]),
                                  np.arange(4.0))


def test_v2_masters_presence_mismatch_raises_not_partial_load(tmp_path):
    """A checkpoint without master weights must NOT load into an
    optimizer that keeps them (or vice versa): load_state_dict would
    keep the freshly-initialized masters while params restore —
    silent divergence on the next step.  Fail loudly instead."""
    from apex_tpu.checkpoint import TemplateMismatchError
    tree = _mixed_tree()
    opt_nomaster = FusedAdam(tree, lr=1e-2, master_weights=False)
    opt_nomaster.step(_grads_for(tree))
    p = str(tmp_path / "nm.ckpt")
    ckpt_mod.save_training_state(p, optimizer=opt_nomaster, step=1)
    with pytest.raises(TemplateMismatchError, match="master"):
        ckpt_mod.load_training_state(
            p, jax.tree_util.tree_map(jnp.zeros_like, tree),
            FusedAdam(_mixed_tree(), lr=1e-2))    # auto-masters
    with pytest.raises(TemplateMismatchError, match="master"):
        ckpt_mod.load_training_state(
            p, jax.tree_util.tree_map(jnp.zeros_like, tree),
            FusedAdam(_mixed_tree(), lr=1e-2, fuse_buckets=False))


def test_v2_template_mismatch_raises(tmp_path):
    from apex_tpu.checkpoint import TemplateMismatchError
    tree = _mixed_tree()
    opt = FusedAdam(tree, lr=1e-2)
    opt.step(_grads_for(tree))
    p = str(tmp_path / "v2.ckpt")
    ckpt_mod.save_training_state(p, optimizer=opt, step=1)
    bad = dict(tree)
    bad["b1"] = jnp.zeros((99,), jnp.float32)     # wrong shape
    with pytest.raises(TemplateMismatchError):
        ckpt_mod.load_training_state(p, bad)


def test_v2_async_double_buffer_survives_next_step(tmp_path):
    """The async packed save must capture the state as of schedule
    time: stepping the optimizer right after scheduling (donating the
    old opt_state buffers) must not corrupt the in-flight write."""
    tree = _mixed_tree()
    opt = FusedAdam(tree, lr=1e-2)
    g = _grads_for(tree)
    opt.step(g)
    want = {k: [np.asarray(b) for b in v]
            for k, v in opt.opt_state.items()}
    want_params = [np.asarray(b) for b in opt._param_bufs]
    p = str(tmp_path / "v2.ckpt")
    with ckpt_mod.AsyncCheckpointer() as ac:
        ac.save_training_state(p, optimizer=opt, step=1)
        for _ in range(3):                 # donates old opt_state
            opt.step(g)
        ac.wait_until_finished()
    opt2 = FusedAdam(_mixed_tree(), lr=1e-2)
    ckpt_mod.load_training_state(
        p, jax.tree_util.tree_map(jnp.zeros_like, tree), opt2)
    for k, v in opt2.opt_state.items():
        for got, exp in zip(v, want[k]):
            np.testing.assert_array_equal(np.asarray(got), exp)
    for got, exp in zip(opt2._param_bufs, want_params):
        np.testing.assert_array_equal(np.asarray(got), exp)


def test_manager_v2_auto_and_packed_restore(tmp_path):
    """CheckpointManager writes v2 for a bucketed optimizer with
    params=None (no lazy unpack touched) and restores it packed."""
    tree = _mixed_tree()
    opt = FusedAdam(tree, lr=1e-2)
    g = _grads_for(tree)
    with CheckpointManager(str(tmp_path), keep=2, every=2) as mgr:
        for step in range(1, 5):
            opt.step(g)
            mgr.maybe_save(step, optimizer=opt)
        mgr.wait()
        newest = max(mgr.steps_on_disk())
        assert ckpt_mod.read_checkpoint_header(
            mgr._path(newest))["magic"] == "APEX_TPU_CKPT_V2"
        opt2 = FusedAdam(_mixed_tree(), lr=1e-2)
        out = mgr.restore_latest(
            jax.tree_util.tree_map(jnp.zeros_like, tree), opt2)
        assert out is not None and out[2] == 4
        _opt_states_equal(opt, opt2)


# ---------------------------------------------------------------------
# AsyncCheckpointer._join failure context (satellite)
# ---------------------------------------------------------------------

def test_async_join_attaches_failed_save_identity(tmp_path):
    """A worker failure surfaces at the NEXT call — the re-raised
    exception must carry the FAILED write's path and step so the
    traceback is attributable."""
    tree = _mixed_tree()
    opt = FusedAdam(tree, lr=1e-2)
    opt.step(_grads_for(tree))
    bad = str(tmp_path / "bad.ckpt")
    good = str(tmp_path / "good.ckpt")
    with FaultInjector([FaultSpec("fsync_error", at_save=0)]):
        ac = ckpt_mod.AsyncCheckpointer()
        ac.save_training_state(bad, optimizer=opt, step=7)
        with pytest.raises(OSError) as ei:
            ac.save_training_state(good, optimizer=opt, step=8)
        assert ei.value.checkpoint_path == bad
        assert ei.value.checkpoint_step == 7
        text = "".join(getattr(ei.value, "__notes__", [])) \
            or " ".join(str(a) for a in ei.value.args)
        assert "bad.ckpt" in text and "step 7" in text
        ac.close()


def test_packed_snapshot_of_offloaded_state_stays_on_host(tmp_path):
    """``offload_state=True`` exists because the moments don't fit in
    HBM — the bucket-native snapshot must copy them IN PLACE on the
    host, never stage them through device memory."""
    tree = _mixed_tree()
    opt = FusedAdam(tree, lr=1e-2, offload_state=True)
    opt.step(_grads_for(tree))
    snap = opt.packed_snapshot()
    for k, bufs in snap["state"].items():
        for b in bufs:
            assert b.sharding.memory_kind in (
                "pinned_host", "unpinned_host"), k
    # and the snapshot still round-trips through the v2 file
    p = str(tmp_path / "off.ckpt")
    ckpt_mod.save_training_state(p, optimizer=opt, step=1)
    opt2 = FusedAdam(_mixed_tree(), lr=1e-2, offload_state=True)
    ckpt_mod.load_training_state(
        p, jax.tree_util.tree_map(jnp.zeros_like, tree), opt2)
    _opt_states_equal(opt, opt2)


def test_run_elastic_retries_deferred_final_save_failure(tmp_path):
    """A transient failure of the LAST cadence save surfaces at the
    supervisor's final durability wait — it must be retried under the
    same bounded contract, not propagated after all work completed."""
    tree = _mixed_tree()
    opt = FusedAdam(tree, lr=1e-2)
    g = _grads_for(tree)
    mgr = CheckpointManager(str(tmp_path), keep=3, every=5)
    # save ordinals: steps 5 and 10 -> the final (2nd) write fails once
    with FaultInjector([FaultSpec("fsync_error", at_save=1)]):
        res = run_elastic(lambda step: opt.step(g), mgr, opt,
                          total_steps=10, backoff_s=0.0)
    assert not res.preempted and res.step == 10
    assert 10 in mgr.steps_on_disk()     # retried write is durable
    opt2 = FusedAdam(_mixed_tree(), lr=1e-2)
    out = mgr.restore_latest(
        jax.tree_util.tree_map(jnp.zeros_like, tree), opt2)
    assert out is not None and out[2] == 10
    _opt_states_equal(opt, opt2)
    mgr.close()


def test_preemption_on_cadence_step_writes_once(tmp_path):
    """A preemption notice landing on a cadence-aligned step must wait
    on the just-scheduled save, not write the identical checkpoint a
    second time — 2x write time inside the eviction grace window."""
    from apex_tpu.telemetry import hostmetrics

    writes = []
    sink = lambda name, v: name == "ckpt/save_ms" and writes.append(v)
    hostmetrics.add_sink(sink)
    try:
        tree = _mixed_tree()
        opt = FusedAdam(tree, lr=1e-2)
        g = _grads_for(tree)
        mgr = CheckpointManager(str(tmp_path), keep=3, every=2)
        res = run_elastic(lambda step: opt.step(g), mgr, opt,
                          total_steps=10,
                          guard=PreemptionGuard(preempt_at_step=4))
        mgr.close()
        assert res.preempted and res.step == 4
        assert mgr.steps_on_disk() == [2, 4]
        assert len(writes) == 2          # steps 2 and 4, each ONCE
    finally:
        hostmetrics.remove_sink(sink)


def test_reshard_with_params_shaped_sharding_pytree(tmp_path):
    """A PYTREE of per-param shardings must align with the params
    subtree in both formats — never be zipped across the optimizer
    state or the extra section (whose trees it does not match)."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec

    ndev = min(8, len(jax.devices()))
    if ndev < 2:
        pytest.skip("needs >= 2 devices")
    mesh = Mesh(np.array(jax.devices()[:ndev]), ("x",))
    repl = NamedSharding(mesh, PartitionSpec())
    tree = _mixed_tree()
    shardings = jax.tree_util.tree_map(lambda _: repl, tree)
    extra = {"bn": jnp.arange(4.0)}
    opt = FusedAdam(tree, lr=1e-2)
    opt.step(_grads_for(tree))
    for fmt in ("v2", "v1"):
        p = str(tmp_path / f"{fmt}.ckpt")
        ckpt_mod.save_training_state(
            p, None if fmt == "v2" else opt.params, opt, step=1,
            extra=extra, format=fmt)
        opt2 = FusedAdam(_mixed_tree(), lr=1e-2)
        out = ckpt_mod.load_training_state(
            p, jax.tree_util.tree_map(jnp.zeros_like, tree), opt2,
            extra_like={"bn": jnp.zeros((4,))}, sharding=shardings)
        for leaf in jax.tree_util.tree_leaves(out[0]):
            assert len(leaf.sharding.device_set) == ndev, fmt
        np.testing.assert_array_equal(np.asarray(out[3]["bn"]),
                                      np.arange(4.0))
        _opt_states_equal(opt, opt2)


def test_run_elastic_optimizer_free_mode_restores_params(tmp_path):
    """``optimizer=None``: params live in the caller's closure — saves
    flow through ``save_extras()['params']`` and restores come back
    through the 4-arg ``on_restore``, without which run_elastic must
    refuse to start (a resume would silently keep fresh weights)."""
    like = {"w": jax.ShapeDtypeStruct((8,), jnp.float32)}

    def job(ckpt_dir, total):
        box = {"w": jnp.zeros((8,))}
        mgr = CheckpointManager(ckpt_dir, keep=3, every=2)
        res = run_elastic(
            lambda step: box.update(w=box["w"] + 1.0), mgr, None,
            total_steps=total, params_like=like,
            save_extras=lambda: {"params": dict(box)},
            on_restore=lambda amp_sd, extra, step, params:
                box.update(params))
        mgr.close()
        return res, box

    with pytest.raises(ValueError):    # 3-arg on_restore can't work
        run_elastic(lambda s: None,
                    CheckpointManager(str(tmp_path), keep=1, every=2),
                    None, total_steps=1, params_like=like,
                    on_restore=lambda amp_sd, extra, step: None)

    res, _ = job(str(tmp_path), 4)
    assert res.step == 4 and res.restored_from is None
    res2, box2 = job(str(tmp_path), 6)
    assert res2.restored_from == 4 and res2.step == 6
    np.testing.assert_array_equal(np.asarray(box2["w"]),
                                  np.full((8,), 6.0))


def test_blocked_ms_only_on_save_backpressure(tmp_path):
    """``ckpt/blocked_ms`` is the SAVE-path backpressure signal: a
    deliberate durability wait (``wait_until_finished``/``close``) must
    not emit it, or every run's summarize shows phantom stalls."""
    from apex_tpu.telemetry import hostmetrics

    class SlowIO(ckpt_mod.CheckpointIO):
        def write_array(self, f, arr):
            time.sleep(0.05)
            super().write_array(f, arr)

    got = []
    sink = lambda name, value: got.append(name)
    hostmetrics.add_sink(sink)
    prev = ckpt_mod.set_io(SlowIO())
    try:
        tree = _mixed_tree()
        opt = FusedAdam(tree, lr=1e-2)
        opt.step(_grads_for(tree))
        with ckpt_mod.AsyncCheckpointer() as ac:
            ac.save_training_state(str(tmp_path / "a.ckpt"),
                                   optimizer=opt, step=1)
            ac.wait_until_finished()       # durability wait: NOT blocked
        assert "ckpt/blocked_ms" not in got
        with ckpt_mod.AsyncCheckpointer() as ac:
            ac.save_training_state(str(tmp_path / "b.ckpt"),
                                   optimizer=opt, step=2)
            ac.save_training_state(str(tmp_path / "c.ckpt"),
                                   optimizer=opt, step=3)   # backpressure
        assert "ckpt/blocked_ms" in got
    finally:
        ckpt_mod.set_io(prev)
        hostmetrics.remove_sink(sink)


# ---------------------------------------------------------------------
# PreemptionGuard
# ---------------------------------------------------------------------

def test_preemption_guard_sigterm_surfaces_at_step_boundary():
    before = signal.getsignal(signal.SIGTERM)
    with PreemptionGuard() as guard:
        assert not guard.check(1)
        os.kill(os.getpid(), signal.SIGTERM)
        # handler sets the flag; check at the next boundary sees it
        deadline = time.time() + 5
        while not guard.preempted and time.time() < deadline:
            time.sleep(0.01)
        assert guard.check(2)
    # the EXACT previous handler restored after uninstall (`is not
    # guard._on_signal` would be vacuous: attribute access mints a
    # fresh bound-method object every time)
    assert signal.getsignal(signal.SIGTERM) == before


def test_preemption_guard_partial_install_rolls_back():
    """One invalid entry in a custom signal set must not leave the
    guard's handler installed on the valid ones — uninstall() would
    never touch a guard that reports not-installed."""
    before = signal.getsignal(signal.SIGTERM)
    guard = PreemptionGuard(signals=(signal.SIGTERM, -1))
    with pytest.raises(ValueError):
        guard.install()
    assert signal.getsignal(signal.SIGTERM) == before
    assert not guard._installed and not guard._old


def test_preemption_guard_at_step_deterministic():
    guard = PreemptionGuard(preempt_at_step=5)
    assert not guard.check(4)
    assert guard.check(5) and guard.check(6)


def test_preemption_guard_programmatic_notice():
    guard = PreemptionGuard()
    guard.notice()
    assert guard.check(1)


# ---------------------------------------------------------------------
# run_elastic + the chaos matrix: every fault kind x {single-host,
# faked multi-host} must resume from the newest valid step with
# params/optimizer/AMP state bit-identical to an uninterrupted run.
# ---------------------------------------------------------------------

_TOTAL, _EVERY = 12, 3


def _mirror_peer(mgr):
    """Fake a 2-host cluster whose peer always mirrors this host
    (shared filesystem): drives the full lockstep agreement code."""
    def allgather(arr):
        arr = np.asarray(arr)
        return np.stack([arr, arr])
    mgr._allgather = allgather
    mgr._process_count = lambda: 2


class _Job:
    """One 'process lifetime': freshly built optimizer + scaler + loop
    state, the way a real restart reconstructs everything."""

    def __init__(self, ckpt_dir, multihost):
        tree = _mixed_tree()
        self.opt = FusedAdam(tree, lr=1e-2)
        self.scaler = LossScaler(loss_scale="dynamic",
                                 init_scale=2.0 ** 4, scale_window=4)
        self.g = _grads_for(tree)
        self.mgr = CheckpointManager(ckpt_dir, keep=3, every=_EVERY)
        if multihost:
            _mirror_peer(self.mgr)
        self.template = jax.tree_util.tree_map(jnp.zeros_like, tree)

    def step_fn(self, step):
        self.opt.step(self.g)
        self.scaler.update_scale(0)

    def run(self, guard=None):
        return run_elastic(
            self.step_fn, self.mgr, self.opt, total_steps=_TOTAL,
            params_like=self.template, guard=guard,
            save_extras=lambda: {"amp_state": self.scaler.state_dict()},
            on_restore=lambda amp_sd, extra, step:
                self.scaler.load_state_dict(amp_sd) if amp_sd else None,
            backoff_s=0.0)


def _drive_to_completion(ckpt_dir, multihost):
    """External-supervisor loop: rebuild the whole job after any crash
    or preemption (a restarted process has no in-memory state) until
    run_elastic completes all steps."""
    for _ in range(6):
        job = _Job(ckpt_dir, multihost)
        guard = PreemptionGuard()
        try:
            res = job.run(guard=guard)
        except InjectedCrash:
            job.mgr.close()
            continue                     # "process died"; restart
        if res.preempted:
            job.mgr.close()              # evicted; scheduler restarts
            continue
        job.mgr.close()
        assert res.step == _TOTAL
        return job
    raise AssertionError("chaos run never completed")


@pytest.fixture(scope="module")
def _uninterrupted(tmp_path_factory):
    d = tmp_path_factory.mktemp("ref")
    return _drive_to_completion(str(d), multihost=False)


_CHAOS = {
    "truncate": [FaultSpec("truncate", at_save=1)],
    "fsync_error": [FaultSpec("fsync_error", at_save=1)],
    "slow_disk": [FaultSpec("slow_disk", at_save=1, delay_s=0.05)],
    "crash_before_publish": [FaultSpec("crash_before_publish",
                                       at_save=1)],
    "preempt": [FaultSpec("preempt", at_step=5)],
}


@pytest.mark.parametrize("multihost", [False, True],
                         ids=["singlehost", "multihost"])
@pytest.mark.parametrize("kind", sorted(_CHAOS))
def test_chaos_resumes_bit_exact(tmp_path, kind, multihost,
                                 _uninterrupted):
    with FaultInjector(_CHAOS[kind]) as inj:
        job = _drive_to_completion(str(tmp_path), multihost)
        assert inj.fired, "the scheduled fault never fired"
    ref = _uninterrupted
    _assert_tree_equal(job.opt.params, ref.opt.params)
    _opt_states_equal(job.opt, ref.opt)
    assert job.scaler.state_dict() == ref.scaler.state_dict()


def test_preemption_notice_produces_valid_final_checkpoint(tmp_path):
    """Acceptance: a preemption notice ends the run with a durable,
    loadable checkpoint at the preempted step."""
    job = _Job(str(tmp_path), multihost=False)
    guard = PreemptionGuard(preempt_at_step=5)
    res = job.run(guard=guard)
    assert res.preempted and res.step == 5
    job.mgr.close()
    # the final checkpoint is valid and newest
    mgr = CheckpointManager(str(tmp_path), keep=3, every=_EVERY)
    opt2 = FusedAdam(_mixed_tree(), lr=1e-2)
    out = mgr.restore_latest(job.template, opt2)
    assert out is not None and out[2] == 5
    _opt_states_equal(job.opt, opt2)
    mgr.close()


def test_run_elastic_fresh_and_resumed_runs_match(tmp_path):
    """Kill (preempt) + restart resumes from the preempt step, and the
    final state matches a run that was never interrupted."""
    d1, d2 = str(tmp_path / "a"), str(tmp_path / "b")
    ref = _drive_to_completion(d2, multihost=False)

    job = _Job(d1, multihost=False)
    res = job.run(guard=PreemptionGuard(preempt_at_step=7))
    assert res.preempted
    job.mgr.close()
    job2 = _Job(d1, multihost=False)
    res2 = job2.run()
    assert res2.restored_from == 7 and res2.step == _TOTAL
    job2.mgr.close()
    _assert_tree_equal(job2.opt.params, ref.opt.params)
    assert job2.scaler.state_dict() == ref.scaler.state_dict()


def test_run_elastic_exhausts_restarts_and_raises(tmp_path):
    """More transient failures than max_restarts must propagate, not
    loop forever."""
    seed = _Job(str(tmp_path), multihost=False)
    seed.opt.step(seed.g)
    seed.mgr.save(3, optimizer=seed.opt)   # something valid to restore
    seed.mgr.wait()
    seed.mgr.close()

    job = _Job(str(tmp_path), multihost=False)
    calls = []

    def bad_step(step):
        calls.append(step)
        raise OSError("flaky disk, forever")

    with pytest.raises(OSError):
        run_elastic(bad_step, job.mgr, job.opt, total_steps=_TOTAL,
                    params_like=job.template, max_restarts=2,
                    backoff_s=0.0)
    # initial attempt + max_restarts recoveries, then give up
    assert len(calls) == 3
    job.mgr.close()


def test_run_elastic_nothing_to_restore_after_failure_raises(tmp_path):
    """A retryable failure with NO valid checkpoint to restore onto
    must raise (restarting 'fresh' would train from a dirty
    midpoint)."""
    job = _Job(str(tmp_path), multihost=False)
    calls = []

    def bad_step(step):
        calls.append(step)
        raise OSError("flaky")

    with pytest.raises(OSError):
        run_elastic(bad_step, job.mgr, job.opt, total_steps=_TOTAL,
                    params_like=job.template, max_restarts=2,
                    backoff_s=0.0)
    assert len(calls) == 1
    job.mgr.close()


def test_run_elastic_nonretryable_propagates(tmp_path):
    job = _Job(str(tmp_path), multihost=False)

    def bad_step(step):
        raise RuntimeError("a real bug")

    with pytest.raises(RuntimeError, match="a real bug"):
        run_elastic(bad_step, job.mgr, job.opt, total_steps=_TOTAL,
                    params_like=job.template, backoff_s=0.0)
    job.mgr.close()


def test_run_elastic_injob_recovery_counts_restarts(tmp_path):
    """A transient OSError mid-run is recovered IN-JOB (restore newest
    valid + resume) and reported in ElasticResult.restarts."""
    job = _Job(str(tmp_path), multihost=False)
    failed = []

    real_step = job.step_fn

    def flaky_step(step):
        if step == 8 and not failed:
            failed.append(step)
            raise OSError("transient")
        real_step(step)

    res = run_elastic(
        flaky_step, job.mgr, job.opt, total_steps=_TOTAL,
        params_like=job.template,
        save_extras=lambda: {"amp_state": job.scaler.state_dict()},
        on_restore=lambda amp_sd, extra, step:
            job.scaler.load_state_dict(amp_sd) if amp_sd else None,
        backoff_s=0.0)
    assert res.restarts == 1 and res.step == _TOTAL
    job.mgr.close()
    ref = _Job(str(tmp_path / "ref"), multihost=False)
    ref_res = ref.run()
    assert ref_res.step == _TOTAL
    ref.mgr.close()
    _assert_tree_equal(job.opt.params, ref.opt.params)


# ---------------------------------------------------------------------
# checkpoint_snapshot bench smoke (tier-1: proves the harness)
# ---------------------------------------------------------------------

def test_checkpoint_snapshot_bench_smoke():
    from apex_tpu.optimizers.bucketing_bench import \
        bench_checkpoint_snapshot
    r = bench_checkpoint_snapshot(layers=2, hidden=16, reps=1)
    assert r["ckpt_snapshot_bucketed_ms"] > 0
    assert r["ckpt_snapshot_perleaf_ms"] > 0
    assert r["ckpt_bytes_bucketed"] > 0 and r["ckpt_bytes_perleaf"] > 0


# ---------------------------------------------------------------------
# review-hardening regressions (round 6)
# ---------------------------------------------------------------------

def test_manager_due_is_the_maybe_save_cadence(tmp_path):
    """``due(step)`` is THE cadence predicate — callers gate expensive
    state_dict() capture on it, so it must agree with maybe_save."""
    mgr = CheckpointManager(str(tmp_path), every=4)
    assert [s for s in range(1, 13) if mgr.due(s)] == [4, 8, 12]
    # off-cadence maybe_save returns False without requiring any
    # checkpoint arguments at all
    assert not mgr.maybe_save(3)
    mgr.close()


def test_v1_reshard_places_optimizer_state_on_sharding(tmp_path):
    """The v1 (per-leaf) restore honors a params-shaped sharding
    pytree across the WHOLE bundle: optimizer moments land on the
    requested mesh straight from host (staging the bundle on the
    default device first would OOM exactly the model that only fits
    sharded); per-tensor scalar state replicates."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec

    ndev = min(8, len(jax.devices()))
    if ndev < 2:
        pytest.skip("needs >= 2 devices")
    tree = _mixed_tree()
    opt = FusedAdam(tree, lr=1e-2, fuse_buckets=False)
    opt.step(_grads_for(tree))
    p = str(tmp_path / "v1.ckpt")
    ckpt_mod.save_training_state(p, opt.params, opt, step=1,
                                 format="v1")

    mesh = Mesh(np.array(jax.devices()[:ndev]), ("x",))
    repl = NamedSharding(mesh, PartitionSpec())
    shardings = jax.tree_util.tree_map(lambda _: repl, tree)
    opt2 = FusedAdam(_mixed_tree(), lr=1e-2, fuse_buckets=False)
    params, _, step = ckpt_mod.load_training_state(
        p, jax.tree_util.tree_map(jnp.zeros_like, tree), opt2,
        sharding=shardings)
    assert step == 1
    for leaf in jax.tree_util.tree_leaves(params):
        assert len(leaf.sharding.device_set) == ndev
    for field, leaves in opt2.opt_state.items():
        for leaf in jax.tree_util.tree_leaves(leaves):
            assert len(leaf.sharding.device_set) == ndev, field
    _opt_states_equal(opt, opt2)


def test_load_packed_snapshot_offload_adopts_on_host(tmp_path,
                                                     monkeypatch):
    """Restoring v2 into an ``offload_state=True`` optimizer commits
    each state buffer straight onto the host placement — no
    asarray-to-HBM staging and no place_on_host fixup pass (the
    state-size spike offloading exists to avoid)."""
    import apex_tpu.optimizers._base as base_mod

    tree = _mixed_tree()
    opt = FusedAdam(tree, lr=1e-2, offload_state=True)
    opt.step(_grads_for(tree))
    p = str(tmp_path / "off.ckpt")
    ckpt_mod.save_training_state(p, optimizer=opt, step=1)

    opt2 = FusedAdam(_mixed_tree(), lr=1e-2, offload_state=True)

    def _trap(_tree):
        raise AssertionError(
            "place_on_host fixup on the packed restore path")

    monkeypatch.setattr(base_mod, "place_on_host", _trap)
    ckpt_mod.load_training_state(
        p, jax.tree_util.tree_map(jnp.zeros_like, tree), opt2)
    monkeypatch.undo()
    for field, bufs in opt2.opt_state.items():
        for b in bufs:
            assert b.sharding.memory_kind in (
                "pinned_host", "unpinned_host"), field
    _opt_states_equal(opt, opt2)


def test_v2_extra_restores_with_shapedtypestruct_template(tmp_path):
    """``extra_like`` may be ShapeDtypeStructs — the template style
    run_elastic itself builds for params_like; the extra-section
    check must read shape/dtype attributes like every other template
    check (np.asarray on a struct template raised a spurious
    TemplateMismatchError, and on a device-array template paid a d2h
    per leaf just to compare dtypes)."""
    tree = _mixed_tree()
    opt = FusedAdam(tree, lr=1e-2)
    opt.step(_grads_for(tree))
    extra = {"bn": {"mean": jnp.arange(4.0), "var": jnp.ones((4,))}}
    p = str(tmp_path / "v2.ckpt")
    ckpt_mod.save_training_state(p, optimizer=opt, step=1, extra=extra)
    like = jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), extra)
    out = ckpt_mod.load_training_state(
        p, jax.tree_util.tree_map(jnp.zeros_like, tree),
        FusedAdam(_mixed_tree(), lr=1e-2), extra_like=like)
    _assert_tree_equal(out[3], extra)


# =====================================================================
# ISSUE 7: self-healing — anomaly watchdog, LKG rollback-and-replay,
# RetryPolicy, training-state chaos.
# =====================================================================

from apex_tpu import telemetry as telemetry_mod
from apex_tpu.resilience import (RetryPolicy, Watchdog, WatchdogAbort,
                                 WatchdogPolicy)
from apex_tpu.resilience import watchdog as wd_mod
from apex_tpu.resilience.watchdog import (GradNormDetector,
                                          LossSpikeDetector,
                                          NanStreakDetector,
                                          ScaleCollapseDetector,
                                          StepTimeDetector)


# ---------------------------------------------------------------------
# RetryPolicy (satellite: configurable run_elastic backoff)
# ---------------------------------------------------------------------

def test_retry_policy_delays_widen_and_cap():
    p = RetryPolicy(max_retries=5, base_delay_s=0.1, max_delay_s=0.5)
    assert [p.delay_s(i) for i in (1, 2, 3, 4)] == \
        [0.1, 0.2, 0.4, 0.5]                       # doubles, then caps
    assert not p.exhausted(5) and p.exhausted(6)


def test_retry_policy_jitter_deterministic_with_rng():
    import random
    p = RetryPolicy(base_delay_s=1.0, jitter=0.5)
    a = p.delay_s(1, rng=random.Random(7))
    b = p.delay_s(1, rng=random.Random(7))
    assert a == b and 1.0 <= a < 1.5


def test_retry_policy_validation():
    with pytest.raises(ValueError):
        RetryPolicy(max_retries=-1)
    with pytest.raises(ValueError):
        RetryPolicy(jitter=1.0)
    with pytest.raises(ValueError):
        RetryPolicy(base_delay_s=-0.1)
    with pytest.raises(ValueError):
        RetryPolicy().delay_s(0)                   # attempts are 1-based


def test_run_elastic_honors_retry_policy_fake_clock(tmp_path):
    """run_elastic's transient-failure backoff comes from the policy:
    a fake clock records the exact widening-then-capped delays."""
    job = _Job(str(tmp_path), multihost=False)
    job.opt.step(job.g)
    job.mgr.save(3, optimizer=job.opt)             # valid restore target
    job.mgr.wait()

    fails = []

    def flaky(step):
        if len(fails) < 3:
            fails.append(step)
            raise OSError("transient")
        job.step_fn(step)

    slept = []
    res = run_elastic(
        flaky, job.mgr, job.opt, total_steps=6,
        params_like=job.template,
        retry=RetryPolicy(max_retries=3, base_delay_s=1.0,
                          max_delay_s=2.5),
        sleep=slept.append)
    assert res.restarts == 3 and res.step == 6
    assert slept == [1.0, 2.0, 2.5]                # widened, then capped
    job.mgr.close()


# ---------------------------------------------------------------------
# CheckpointManager: LKG tagging + retention pinning
# ---------------------------------------------------------------------

def test_lkg_survives_rotation_and_manager_restart(tmp_path):
    tree = _mixed_tree()
    opt = FusedAdam(tree, lr=1e-2)
    g = _grads_for(tree)
    with CheckpointManager(str(tmp_path), keep=2, every=2) as mgr:
        for step in range(1, 5):
            opt.step(g)
            mgr.maybe_save(step, optimizer=opt)
        mgr.wait()
        mgr.mark_good(2)
        for step in range(5, 11):
            opt.step(g)
            mgr.maybe_save(step, optimizer=opt)
        mgr.wait()
        # keep=2 newest + the pinned LKG
        assert 2 in mgr.steps_on_disk()
        assert mgr.steps_on_disk()[-2:] == [8, 10]
        assert mgr.lkg_step() == 2
    # a restarted manager inherits the persisted stamp
    mgr2 = CheckpointManager(str(tmp_path), keep=2, every=2)
    assert mgr2.lkg_step() == 2
    mgr2.close()


def test_pin_exempts_from_rotation_until_unpinned(tmp_path):
    tree = _mixed_tree()
    opt = FusedAdam(tree, lr=1e-2)
    g = _grads_for(tree)
    with CheckpointManager(str(tmp_path), keep=1, every=1) as mgr:
        opt.step(g)
        mgr.maybe_save(1, optimizer=opt)
        mgr.wait()
        mgr.pin(1)
        for step in (2, 3, 4):
            opt.step(g)
            mgr.maybe_save(step, optimizer=opt)
            mgr.wait()
        assert 1 in mgr.steps_on_disk()            # pinned: survives
        mgr.unpin(1)
        opt.step(g)
        mgr.maybe_save(5, optimizer=opt)
        mgr.wait()
        assert 1 not in mgr.steps_on_disk()        # unpinned: rotated


def test_restore_good_walks_from_lkg_not_newest(tmp_path):
    """Rollback must not land on a checkpoint newer than the LKG —
    those may hold the very state being rolled away from."""
    tree = _mixed_tree()
    opt = FusedAdam(tree, lr=1e-2)
    g = _grads_for(tree)
    with CheckpointManager(str(tmp_path), keep=5, every=2) as mgr:
        snapshots = {}
        for step in range(1, 9):
            opt.step(g)
            if mgr.due(step):
                snapshots[step] = [np.asarray(b)
                                   for b in opt._param_bufs]
            mgr.maybe_save(step, optimizer=opt)
        mgr.wait()
        mgr.mark_good(4)
        opt2 = FusedAdam(_mixed_tree(), lr=1e-2)
        out = mgr.restore_good(
            jax.tree_util.tree_map(jnp.zeros_like, tree), opt2)
        assert out is not None and out[2] == 4     # LKG, not 8
        for got, exp in zip(opt2._param_bufs, snapshots[4]):
            np.testing.assert_array_equal(np.asarray(got), exp)


def test_restore_latest_max_step_filters_and_falls_back(tmp_path):
    tree = _mixed_tree()
    opt = FusedAdam(tree, lr=1e-2)
    g = _grads_for(tree)
    with CheckpointManager(str(tmp_path), keep=5, every=2) as mgr:
        for step in range(1, 9):
            opt.step(g)
            mgr.maybe_save(step, optimizer=opt)
        mgr.wait()
        # corrupt step-4 so the bounded walk must fall back to 2
        p4 = mgr._path(4)
        open(p4, "wb").write(open(p4, "rb").read()[:30])
        opt2 = FusedAdam(_mixed_tree(), lr=1e-2)
        with pytest.warns(UserWarning, match="skipping"):
            out = mgr.restore_latest(
                jax.tree_util.tree_map(jnp.zeros_like, tree), opt2,
                max_step=4)
        assert out is not None and out[2] == 2


def test_restore_good_without_stamp_degrades_to_latest(tmp_path):
    tree = _mixed_tree()
    opt = FusedAdam(tree, lr=1e-2)
    with CheckpointManager(str(tmp_path), keep=2, every=1) as mgr:
        opt.step(_grads_for(tree))
        mgr.maybe_save(1, optimizer=opt)
        mgr.wait()
        assert mgr.lkg_step() is None
        out = mgr.restore_good(
            jax.tree_util.tree_map(jnp.zeros_like, tree),
            FusedAdam(_mixed_tree(), lr=1e-2))
        assert out is not None and out[2] == 1


# ---------------------------------------------------------------------
# Detectors
# ---------------------------------------------------------------------

def _steps(vals, metric, start=0):
    return [{"step": start + i, metric: v}
            for i, v in enumerate(vals)]


def test_nan_streak_fires_once_per_streak_and_resets():
    d = NanStreakDetector(streak=3)
    a = d.observe(_steps([1, 1], "amp/found_inf"))
    assert a == []                                 # below threshold
    a = d.observe(_steps([1, 1, 1], "amp/found_inf", start=2))
    assert len(a) == 1 and a[0].kind == "nan_streak"
    assert a[0].severity == "critical"
    # 3rd consecutive overflow is step 2; streak anchored at step 0
    assert a[0].first_step == 0 and a[0].step == 2
    assert a[0].evidence["consecutive_overflows"] == 3
    # continuing the SAME streak does not re-fire ...
    assert d.observe(_steps([1, 1], "amp/found_inf", start=5)) == []
    # ... a clean step re-arms, and a fresh streak fires again
    assert d.observe(_steps([0, 1, 1, 1], "amp/found_inf",
                            start=7)) != []


def test_nan_streak_ignores_unrecorded_steps():
    d = NanStreakDetector(streak=2)
    recs = [{"step": 0, "amp/found_inf": 1.0},
            {"step": 1, "amp/found_inf": None},    # metric not recorded
            {"step": 2, "amp/found_inf": 1.0}]
    assert len(d.observe(recs)) == 1               # None is not a reset


def test_loss_spike_zscore_and_baseline_not_poisoned():
    d = LossSpikeDetector(zscore=6.0, min_history=8)
    base = [1.0 + 0.01 * (i % 5) for i in range(16)]
    assert d.observe(_steps(base, "loss")) == []
    a = d.observe(_steps([50.0], "loss", start=16))
    assert len(a) == 1 and a[0].kind == "loss_spike"
    assert a[0].evidence["zscore"] >= 6.0
    # the spike was excluded from the history: the baseline still
    # fires on the next spike instead of having absorbed the outlier
    a2 = d.observe(_steps([50.0], "loss", start=17))
    assert len(a2) == 1


def test_loss_spike_flat_baseline_still_detects():
    """A noiseless baseline (std == 0) must not divide by zero NOR go
    blind — the relative-std floor keeps genuine spikes detectable."""
    d = LossSpikeDetector(zscore=8.0, min_history=8)
    d.observe(_steps([1.0] * 12, "loss"))
    a = d.observe(_steps([100.0], "loss", start=12))
    assert len(a) == 1


def test_grad_norm_explosion_detector():
    d = GradNormDetector(zscore=6.0, min_history=8)
    d.observe(_steps([0.5 + 0.01 * (i % 3) for i in range(12)],
                     "amp/grad_norm"))
    a = d.observe(_steps([1e4], "amp/grad_norm", start=12))
    assert len(a) == 1 and a[0].kind == "grad_norm_explosion"


def test_scale_collapse_needs_consecutive_floored_windows():
    d = ScaleCollapseDetector(floor=1.0, windows=2)
    assert d.observe(_steps([1.0, 1.0], "amp/loss_scale")) == []
    a = d.observe(_steps([1.0, 1.0], "amp/loss_scale", start=2))
    assert len(a) == 1 and a[0].kind == "scale_collapse"
    assert a[0].evidence["windows_at_floor"] == 2
    # recovery above the floor re-arms
    d.observe(_steps([2.0], "amp/loss_scale", start=4))
    assert d.observe(_steps([1.0, 1.0], "amp/loss_scale",
                            start=5)) == []


def test_step_time_detector_flags_straggler_not_baseline():
    d = StepTimeDetector(factor=3.0, min_history=4)
    for i in range(8):
        assert d.observe_time(i, 0.1) is None
    a = d.observe_time(8, 0.5)
    assert a is not None and a.kind == "straggler"
    assert a.evidence["slowdown"] >= 3.0
    # the stall was excluded from the history: baseline stays 0.1
    assert d.observe_time(9, 0.1) is None


# ---------------------------------------------------------------------
# Watchdog escalation policy
# ---------------------------------------------------------------------

def _loss_window(wd, start, n, loss=1.0, **extra):
    recs = []
    for i in range(n):
        r = {"kind": "step", "step": start + i, "loss": loss,
             "amp/found_inf": 0.0, "amp/loss_scale": 1024.0}
        r.update(extra)
        recs.append(r)
    wd.observe(recs)
    return start + n


def test_quarantine_budget_escalates_to_rollback():
    wd = Watchdog(detectors=[LossSpikeDetector(min_history=4)],
                  policy=WatchdogPolicy(quarantine_budget=1),
                  clean_window=4)
    step = _loss_window(wd, 0, 8)
    # spike 1: quarantine; spike 2 (same kind): over budget -> rollback
    wd.observe([{"kind": "step", "step": step, "loss": 1e5}])
    assert wd.check(step).action == "quarantine"
    wd.observe([{"kind": "step", "step": step + 1, "loss": 1e5}])
    assert wd.check(step + 1).action == "rollback"


def test_rollback_budget_exhaustion_aborts():
    wd = Watchdog(detectors=[NanStreakDetector(streak=2)],
                  policy=WatchdogPolicy(rollback=RetryPolicy(
                      max_retries=1, base_delay_s=0.0)),
                  clean_window=4)
    wd.observe(_steps([1, 1], "amp/found_inf"))
    assert wd.check(2).action == "rollback"
    wd.note_rollback(0, 2, None)                   # detectors reset
    wd.observe(_steps([1, 1], "amp/found_inf", start=3))
    assert wd.check(5).action == "abort"           # budget spent


def test_warn_kind_takes_no_action_but_lands_in_timeline():
    wd = Watchdog(detectors=[StepTimeDetector(factor=2.0,
                                              min_history=2)],
                  clean_window=4)
    t = [0.0]
    wd._clock = lambda: t[0]
    for i in range(6):
        t[0] += 0.1
        assert wd.check(i).action == "none"
    t[0] += 5.0                                    # the straggler step
    assert wd.check(6).action == "warn"
    assert [a.kind for a in wd.timeline] == ["straggler"]


def test_lkg_stamping_requires_full_clean_window():
    wd = Watchdog(detectors=[NanStreakDetector(streak=2)],
                  clean_window=8)
    wd.note_save(3)
    _loss_window(wd, 0, 8)                         # newest == 7 < 3+8
    assert wd.resolved_saves() == []
    _loss_window(wd, 8, 8)                         # newest == 15 >= 11
    assert wd.resolved_saves() == [(3, True)]


def test_anomaly_voids_aging_save_candidates():
    wd = Watchdog(detectors=[NanStreakDetector(streak=2)],
                  clean_window=8)
    wd.note_save(3)
    wd.note_save(6)
    recs = _steps([0, 0, 1, 1], "amp/found_inf", start=4)
    for r in recs:
        r["kind"] = "step"
    wd.observe(recs)
    assert sorted(wd.resolved_saves()) == [(3, False), (6, False)]


def test_warn_anomaly_does_not_void_candidates():
    wd = Watchdog(detectors=[StepTimeDetector(factor=2.0,
                                              min_history=2)],
                  clean_window=4)
    t = [0.0]
    wd._clock = lambda: t[0]
    wd.note_save(1)
    for i in range(4):
        t[0] += 0.1
        wd.check(i)
    t[0] += 5.0
    assert wd.check(4).action == "warn"
    _loss_window(wd, 0, 8)                         # ages past 1+4
    assert wd.resolved_saves() == [(1, True)]


def test_postmortem_bundle_contents(tmp_path):
    wd = Watchdog(detectors=[NanStreakDetector(streak=2)],
                  clean_window=4, postmortem_dir=str(tmp_path))
    recs = _steps([1, 1], "amp/found_inf")
    for r in recs:
        r["kind"] = "step"
    wd.observe(recs)
    v = wd.check(2)
    pm = wd.write_postmortem(2, v.anomaly)
    assert pm == str(tmp_path / "postmortem-step2")
    import json as _json
    lines = [_json.loads(l) for l in
             open(os.path.join(pm, "anomalies.jsonl"))]
    assert any(r.get("anomaly") == "nan_streak" for r in lines)
    dump = [_json.loads(l) for l in
            open(os.path.join(pm, "ring_dump.jsonl"))]
    assert [r["step"] for r in dump] == [0, 1]
    cfg = _json.load(open(os.path.join(pm, "config.json")))
    assert cfg["detectors"]["nan_streak"]["streak"] == 2
    assert "policy" in cfg and "rollback" in cfg["policy"]
    assert cfg["topology"].get("backend") == "cpu"


# ---------------------------------------------------------------------
# Self-healing chaos matrix: every training-state fault kind x
# {single-host, faked multi-host} must end in the DOCUMENTED action
# (quarantine / rollback-to-LKG / warn), training must run to
# completion, and post-recovery state matches an uninterrupted run
# bit-exactly where determinism allows (nan storm and loss-spike
# rollbacks replay clean; a scale collapse rolls back to a mid-storm
# LKG by design — metrics before detection are not anomalies — so
# that case asserts recovery, not bit-exactness).
# ---------------------------------------------------------------------

from apex_tpu.resilience import faults as faults_mod

_WD_TOTAL, _WD_EVERY = 24, 3


def _fast_rollback_policy(**kw):
    return WatchdogPolicy(rollback=RetryPolicy(max_retries=2,
                                               base_delay_s=0.0), **kw)


class _WdJob:
    """One self-healing 'process lifetime': telemetry session (window
    4 -> flush every 4 recorded steps) + watchdog + manager, wired the
    way train_toy wires them."""

    def __init__(self, ckpt_dir, multihost, policy=None,
                 scale_window=4, straggler_factor=50.0):
        tree = _mixed_tree()
        self.opt = FusedAdam(tree, lr=1e-2)
        self.scaler = LossScaler(loss_scale="dynamic",
                                 init_scale=2.0 ** 2,
                                 scale_window=scale_window)
        self.g = _grads_for(tree)
        self.mgr = CheckpointManager(ckpt_dir, keep=3, every=_WD_EVERY)
        if multihost:
            _mirror_peer(self.mgr)
        self.template = jax.tree_util.tree_map(jnp.zeros_like, tree)
        self.tel = telemetry_mod.Telemetry(run_dir=None, window=4,
                                           retrace=False)
        self.wd = Watchdog(
            detectors=[NanStreakDetector(streak=3),
                       LossSpikeDetector(min_history=6, zscore=6.0),
                       ScaleCollapseDetector(floor=1.0, windows=2),
                       StepTimeDetector(factor=straggler_factor,
                                        min_history=6)],
            policy=policy or _fast_rollback_policy(),
            telemetry=self.tel, clean_window=4)
        self.quarantined = []

    def step_fn(self, step):
        f = faults_mod.training_fault(step)
        kind = f.kind if f is not None else None
        bad = 0
        loss = 1.0 + 0.001 * step
        if kind == "nan_grads":
            bad = 1
        elif kind == "scale_collapse":
            bad = 1 if step % 2 == 0 else 0   # intermittent: no streak
        elif kind == "loss_spike":
            loss = 1e4
        if not bad:
            self.opt.step(self.g)
        self.scaler.update_scale(bad)
        # eager host loop: bad/loss_scale are host floats, not tracers
        self.tel.record(
            {"loss": loss, "amp/found_inf": float(bad),   # apexlint: disable=APX101
             "amp/loss_scale": self.scaler.loss_scale()}, step)

    def on_quarantine(self, anomaly):
        self.quarantined.append(anomaly.kind)
        self.scaler.state = amp.re_anchor(self.scaler.state,
                                          self.scaler.config)

    def run(self):
        return run_elastic(
            self.step_fn, self.mgr, self.opt, total_steps=_WD_TOTAL,
            params_like=self.template, watchdog=self.wd,
            on_quarantine=self.on_quarantine,
            save_extras=lambda: {"amp_state": self.scaler.state_dict()},
            on_restore=lambda amp_sd, extra, step:
                self.scaler.load_state_dict(amp_sd) if amp_sd else None,
            backoff_s=0.0)

    def close(self):
        self.wd.close()
        self.tel.close()
        self.mgr.close()


from apex_tpu import amp  # noqa: E402  (re_anchor in on_quarantine)


@pytest.fixture(scope="module")
def _wd_reference(tmp_path_factory):
    """The uninterrupted run every healed run must match."""
    job = _WdJob(str(tmp_path_factory.mktemp("wd_ref")),
                 multihost=False)
    res = job.run()
    assert res.step == _WD_TOTAL and res.rollbacks == 0
    job.close()
    return job


@pytest.mark.parametrize("multihost", [False, True],
                         ids=["singlehost", "multihost"])
def test_nan_storm_rolls_back_to_lkg_and_replays_bit_exact(
        tmp_path, multihost, _wd_reference):
    """Acceptance: an injected NaN storm (outlasting the scaler's
    backoff) triggers detection, multi-host-agreed rollback to the
    last-known-good checkpoint, and the replayed run completes
    bit-identical to an uninterrupted one."""
    with FaultInjector([FaultSpec("nan_grads", at_step=10,
                                  n_steps=4)]) as inj:
        job = _WdJob(str(tmp_path), multihost)
        with pytest.warns(UserWarning, match="watchdog rollback"):
            res = job.run()
        assert inj.fired
    assert res.step == _WD_TOTAL and res.rollbacks == 1
    assert "nan_streak" in [a.kind for a in job.wd.timeline]
    rb = [e for e in job.wd.events if e["action"] == "rollback"]
    assert rb and rb[0]["to_step"] < 10        # LKG is pre-storm
    assert job.mgr.lkg_step() is not None
    ref = _wd_reference
    _assert_tree_equal(job.opt.params, ref.opt.params)
    _opt_states_equal(job.opt, ref.opt)
    assert job.scaler.state_dict() == ref.scaler.state_dict()
    job.close()


@pytest.mark.parametrize("multihost", [False, True],
                         ids=["singlehost", "multihost"])
def test_single_loss_spike_is_quarantined_not_rolled_back(
        tmp_path, multihost):
    """A one-off loss spike stays at the quarantine rung: the
    on_quarantine hook re-anchors the scaler, training continues, no
    checkpoint is touched."""
    with FaultInjector([FaultSpec("loss_spike", at_step=10,
                                  n_steps=1)]) as inj:
        job = _WdJob(str(tmp_path), multihost)
        with pytest.warns(UserWarning, match="watchdog quarantined"):
            res = job.run()
        assert inj.fired
    assert res.step == _WD_TOTAL and res.rollbacks == 0
    assert job.quarantined == ["loss_spike"]
    # the quarantine opens an incident; surviving its clean window
    # closes it with an incident_resolved event sharing the same id
    assert [e["action"] for e in job.wd.events] == \
        ["quarantine", "incident_resolved"]
    iids = {e.get("incident_id") for e in job.wd.events}
    assert len(iids) == 1 and iids == {job.wd.incidents.history[0]}
    assert job.wd.incidents.current is None   # closed
    # re-anchor happened: scale back at the configured operating point
    # at quarantine time (and grows normally afterwards)
    assert float(job.scaler.loss_scale()) >= 2.0 ** 2
    job.close()


@pytest.mark.parametrize("multihost", [False, True],
                         ids=["singlehost", "multihost"])
def test_persistent_loss_spikes_escalate_to_rollback_bit_exact(
        tmp_path, multihost, _wd_reference):
    """Acceptance: a persistent loss-spike fault exhausts the
    quarantine budget, escalates to a multi-host-agreed rollback to
    LKG, and the replayed run completes bit-identical to an
    uninterrupted one (the spike only poisoned the METRIC stream; the
    optimizer path is deterministic, so replay heals exactly)."""
    with FaultInjector([FaultSpec("loss_spike", at_step=10,
                                  n_steps=2)]) as inj:
        job = _WdJob(str(tmp_path), multihost,
                     policy=_fast_rollback_policy(quarantine_budget=0))
        with pytest.warns(UserWarning, match="watchdog rollback"):
            res = job.run()
        assert inj.fired
    assert res.step == _WD_TOTAL and res.rollbacks == 1
    assert "loss_spike" in [a.kind for a in job.wd.timeline]
    ref = _wd_reference
    _assert_tree_equal(job.opt.params, ref.opt.params)
    _opt_states_equal(job.opt, ref.opt)
    assert job.scaler.state_dict() == ref.scaler.state_dict()
    job.close()


@pytest.mark.parametrize("multihost", [False, True],
                         ids=["singlehost", "multihost"])
def test_scale_collapse_storm_rolls_back_and_recovers(
        tmp_path, multihost):
    """Intermittent overflows pin the scale at the floor without ever
    forming a NaN streak; the collapse detector fires after N floored
    windows and the rollback-and-replay recovers the scale."""
    with FaultInjector([FaultSpec("scale_collapse", at_step=8,
                                  n_steps=8)]) as inj:
        job = _WdJob(str(tmp_path), multihost, scale_window=8)
        with pytest.warns(UserWarning, match="watchdog rollback"):
            res = job.run()
        assert inj.fired
    assert res.step == _WD_TOTAL and res.rollbacks >= 1
    assert "scale_collapse" in [a.kind for a in job.wd.timeline]
    # recovered: the replayed run ends with the scale off the floor
    assert job.scaler.loss_scale() > 1.0
    job.close()


@pytest.mark.parametrize("multihost", [False, True],
                         ids=["singlehost", "multihost"])
def test_straggler_stall_warns_without_state_action(
        tmp_path, multihost, _wd_reference):
    """A straggling step is an infrastructure signal, not a state
    corruption: the watchdog records the anomaly and takes NO
    state-changing action — and the run still matches the reference
    bit-exactly (the fault only burned wall time)."""
    with FaultInjector([FaultSpec("straggler", at_step=12, n_steps=1,
                                  delay_s=2.0)]) as inj:
        job = _WdJob(str(tmp_path), multihost, straggler_factor=8.0)
        res = job.run()
        assert inj.fired
    assert res.step == _WD_TOTAL and res.rollbacks == 0
    assert "straggler" in [a.kind for a in job.wd.timeline]
    assert all(e["action"] not in ("rollback", "quarantine")
               for e in job.wd.events)
    ref = _wd_reference
    _assert_tree_equal(job.opt.params, ref.opt.params)
    _opt_states_equal(job.opt, ref.opt)
    job.close()


def test_rollback_exhaustion_aborts_with_postmortem(tmp_path):
    """A PERSISTENT fault (never spent) exhausts the rollback budget;
    the abort raises WatchdogAbort after writing the post-mortem
    bundle — the anomaly timeline and ring dump are on disk."""
    pm_dir = str(tmp_path / "pm")
    with FaultInjector([FaultSpec("nan_grads", at_step=6,
                                  n_steps=10_000)]):
        job = _WdJob(str(tmp_path / "ckpt"), multihost=False,
                     policy=_fast_rollback_policy())
        job.wd.postmortem_dir = pm_dir
        with pytest.raises(WatchdogAbort) as ei:
            with pytest.warns(UserWarning, match="watchdog rollback"):
                job.run()
    assert ei.value.postmortem and os.path.isdir(ei.value.postmortem)
    assert os.path.exists(os.path.join(ei.value.postmortem,
                                       "anomalies.jsonl"))
    assert os.path.exists(os.path.join(ei.value.postmortem,
                                       "ring_dump.jsonl"))
    assert os.path.exists(os.path.join(ei.value.postmortem,
                                       "config.json"))
    job.close()


def test_watchdog_overhead_bench_smoke():
    from apex_tpu.telemetry.bench import bench_watchdog_overhead
    r = bench_watchdog_overhead(layers=2, hidden=16, window=8,
                                iters=2, reps=1)
    assert r["watchdog_on_ms"] > 0 and r["watchdog_off_ms"] > 0
    assert r["watchdog_observe_ms"] >= 0
    assert r["watchdog_detectors"] >= 4


def test_quarantine_counts_forgiven_after_clean_window():
    """Isolated same-kind spikes separated by a full clean window must
    each stay at the quarantine rung — escalation is per incident, not
    per lifetime."""
    wd = Watchdog(detectors=[LossSpikeDetector(min_history=4)],
                  policy=WatchdogPolicy(quarantine_budget=1),
                  clean_window=4)
    step = _loss_window(wd, 0, 8)
    wd.observe([{"kind": "step", "step": step, "loss": 1e5}])
    assert wd.check(step).action == "quarantine"
    step = _loss_window(wd, step + 1, 6)       # clean window: forgiven
    wd.observe([{"kind": "step", "step": step, "loss": 1e5}])
    assert wd.check(step).action == "quarantine"   # not rollback


# ---------------------------------------------------------------------
# review-hardening regressions
# ---------------------------------------------------------------------

def test_save_inside_open_incident_never_ages_into_lkg():
    """A cadence save taken at the same boundary an anomaly is awaiting
    its verdict (or within a clean window of the last serious anomaly)
    snapshots state that went through the anomalous window — it must
    be rejected as an LKG candidate immediately, not aged."""
    wd = Watchdog(detectors=[NanStreakDetector(streak=2)],
                  clean_window=4)
    wd.observe(_steps([1, 1], "amp/found_inf"))    # anomaly pending
    wd.note_save(2)                                # same boundary
    assert wd.resolved_saves() == [(2, False)]
    assert wd.check(2).action == "rollback"
    # still inside the incident window after the verdict drained
    wd.note_save(4)
    assert wd.resolved_saves() == [(4, False)]
    # after the rollback the restored state predates the incident:
    # replayed saves are candidates again and age normally
    wd.note_rollback(0, 4, None)
    wd.note_save(3)
    _loss_window(wd, 1, 8)                         # newest 8 >= 3+4
    assert wd.resolved_saves() == [(3, True)]


def test_straggler_fires_once_per_episode():
    """A sustained slowdown (or naturally slower cadence steps) must
    not flood the timeline: one anomaly per episode, re-armed by a
    normal-speed step — and suppressed samples stay out of the
    baseline."""
    d = StepTimeDetector(factor=3.0, min_history=4)
    for i in range(6):
        d.observe_time(i, 0.1)
    assert d.observe_time(6, 1.0) is not None
    assert d.observe_time(7, 1.0) is None          # same episode
    assert d.observe_time(8, 0.1) is None          # re-arms
    assert d.observe_time(9, 1.0) is not None      # new episode


def test_warn_anomaly_does_not_hold_incident_open():
    """Straggler warns between quarantines must not block the
    per-incident forgiveness of quarantine counts."""
    wd = Watchdog(detectors=[LossSpikeDetector(min_history=4),
                             StepTimeDetector(factor=2.0,
                                              min_history=2)],
                  policy=WatchdogPolicy(quarantine_budget=1),
                  clean_window=4)
    t = [0.0]
    wd._clock = lambda: t[0]
    step = _loss_window(wd, 0, 8)
    wd.observe([{"kind": "step", "step": step, "loss": 1e5}])
    assert wd.check(step).action == "quarantine"
    # keep the straggler detector firing warns through the clean window
    for i in range(4):
        t[0] += 0.1 if i else 10.0                 # one stall, then ok
        wd.check(step + 1 + i)
    step = _loss_window(wd, step + 1, 6)           # clean window passes
    wd.observe([{"kind": "step", "step": step, "loss": 1e5}])
    assert wd.check(step).action == "quarantine"   # forgiven, not
    #                                                escalated


def test_direct_abort_mapping_reports_zero_rollbacks():
    """An anomaly kind mapped straight to abort must not claim a
    negative rollback count — `rollbacks` reads as rollbacks
    EXECUTED."""
    wd = Watchdog(detectors=[NanStreakDetector(streak=2)],
                  policy=WatchdogPolicy(
                      actions={"nan_streak": wd_mod.ACTION_ABORT}),
                  clean_window=4)
    wd.observe(_steps([1, 1], "amp/found_inf"))
    assert wd.check(2).action == "abort"
    assert wd.rollbacks == 0


def test_identical_duplicate_fault_specs_both_fire():
    """fired is index-keyed: two IDENTICAL scheduled specs must both
    appear once applied (NamedTuple equality would alias them)."""
    spec = FaultSpec("nan_grads", at_step=1, n_steps=1)
    inj = FaultInjector([spec, spec])
    assert inj.training_fault(1) is not None       # spends spec #0
    assert inj.training_fault(2) is not None       # spends spec #1
    assert inj.training_fault(3) is None           # both budgets spent
    assert len(inj.fired) == 2
