"""apex_tpu.resilience — crash-safe checkpoint rotation + resume
(SURVEY.md §5: the TPU recovery story the reference lacks)."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.resilience import CheckpointManager


def _train(mgr, steps, start=0):
    from apex_tpu.optimizers import FusedSGD
    params = {"w": jnp.ones((64,))}
    opt = FusedSGD(params, lr=0.1)
    g = {"w": jnp.full((64,), 0.01)}
    restored = mgr.restore_latest({"w": jnp.zeros((64,))}, opt)
    s0 = 0
    if restored is not None:
        _, _, s0 = restored
    for step in range(s0 + 1, steps + 1):
        opt.step(g)
        mgr.maybe_save(step, opt.params, opt)
    mgr.wait()
    return opt, s0


def test_rotation_keeps_newest_k(tmp_path):
    with CheckpointManager(str(tmp_path), keep=2, every=5) as mgr:
        _train(mgr, 30)
        assert mgr.steps_on_disk() == [25, 30]


def test_resume_continues_from_latest(tmp_path):
    with CheckpointManager(str(tmp_path), keep=3, every=5) as mgr:
        opt1, s0 = _train(mgr, 20)
        assert s0 == 0
    with CheckpointManager(str(tmp_path), keep=3, every=5) as mgr:
        opt2, s0 = _train(mgr, 20)   # "crash" and restart at 20
        assert s0 == 20              # no extra steps run
    np.testing.assert_array_equal(np.asarray(opt1.params["w"]),
                                  np.asarray(opt2.params["w"]))


def test_corrupt_newest_falls_back_to_previous(tmp_path):
    with CheckpointManager(str(tmp_path), keep=3, every=5) as mgr:
        _train(mgr, 15)
        steps = mgr.steps_on_disk()
        assert steps == [5, 10, 15]
        # truncate the newest (mid-write crash artifact)
        p = os.path.join(str(tmp_path), "step-15.ckpt")
        data = open(p, "rb").read()
        open(p, "wb").write(data[:len(data) // 2])
        from apex_tpu.optimizers import FusedSGD
        opt = FusedSGD({"w": jnp.zeros((64,))}, lr=0.1)
        restored = mgr.restore_latest({"w": jnp.zeros((64,))}, opt)
        assert restored is not None
        _, _, step = restored
        assert step == 10            # newest VALID


def test_empty_dir_returns_none(tmp_path):
    with CheckpointManager(str(tmp_path / "fresh"), every=5) as mgr:
        assert mgr.restore_latest({"w": jnp.zeros((4,))}) is None


def test_bad_config_rejected(tmp_path):
    with pytest.raises(ValueError):
        CheckpointManager(str(tmp_path), keep=0)
    with pytest.raises(ValueError):
        CheckpointManager(str(tmp_path), every=0)


def test_template_mismatch_raises_not_skips(tmp_path):
    """A wrong restore template is a caller bug (code-review r2): it
    must raise, not silently restart from step 0."""
    from apex_tpu.checkpoint import TemplateMismatchError
    with CheckpointManager(str(tmp_path), keep=3, every=5) as mgr:
        _train(mgr, 10)
        with pytest.raises(TemplateMismatchError):
            mgr.restore_latest({"w": jnp.zeros((8,))})   # wrong shape


def test_gc_never_drops_below_keep_durable(tmp_path):
    """While a save is in flight, the durable window stays intact
    (keep=1 regression: a failed in-flight write must not leave zero)."""
    with CheckpointManager(str(tmp_path), keep=1, every=5) as mgr:
        from apex_tpu.optimizers import FusedSGD
        opt = FusedSGD({"w": jnp.ones((64,))}, lr=0.1)
        g = {"w": jnp.full((64,), 0.01)}
        for _ in range(5):
            opt.step(g)
        mgr.maybe_save(5, opt.params, opt)
        mgr.wait()                            # step-5 durable
        assert mgr.steps_on_disk() == [5]
        for _ in range(5):
            opt.step(g)
        mgr.maybe_save(10, opt.params, opt)   # step-10 in flight
        # the one durable checkpoint must still exist right after the
        # new save was scheduled and _gc ran
        assert 5 in mgr.steps_on_disk()
        mgr.wait()
        assert mgr.steps_on_disk() == [10]    # trimmed to keep


def test_orphaned_tmp_cleared_on_init(tmp_path):
    """A crash mid-write leaves step-N.ckpt.tmp behind; a new manager
    in the same directory must clear it (advisor r2)."""
    orphan = tmp_path / "step-5.ckpt.tmp"
    orphan.write_bytes(b"garbage from a dead process")
    with CheckpointManager(str(tmp_path), keep=3, every=5):
        assert not orphan.exists()


def test_corrupt_skip_emits_warning(tmp_path):
    """Skipping a corrupt checkpoint at restore must be observable
    (advisor r2): silence here means an unexplained restart-from-
    scratch."""
    with CheckpointManager(str(tmp_path), keep=3, every=5) as mgr:
        _train(mgr, 10)
        mgr.wait()
        newest = max(mgr.steps_on_disk())
        p = tmp_path / f"step-{newest}.ckpt"
        p.write_bytes(p.read_bytes()[:20])    # truncate = crash artifact
        from apex_tpu.optimizers import FusedSGD
        opt = FusedSGD({"w": jnp.zeros((64,))}, lr=0.1)
        with pytest.warns(UserWarning, match="skipping .*step-%d" % newest):
            out = mgr.restore_latest({"w": jnp.zeros((64,))}, opt)
        assert out is not None                # fell back to older step
