"""Worker body for tests/test_distributed_launch.py — one OS process
per rank, the reference's `torch.distributed.launch` child shape
(SURVEY.md §2.6; the reference idiom is init_process_group(backend=
"nccl") inside each launched process).

Run:  python _dist_worker.py <rank> <world> <port>
or (launcher mode — rendezvous already in the env, the way
`python -m apex_tpu.launch` spawns workers):  python _dist_worker.py

Pins the CPU platform BEFORE first backend use (sitecustomize registers
the axon TPU plugin in every python process; a test worker must never
touch the tunnel), enables the gloo CPU collectives implementation,
then goes through the REAL `comm.initialize_distributed()` →
`jax.distributed.initialize()` handshake from the launcher env
contract (WORLD_SIZE/RANK/JAX_COORDINATOR_ADDRESS), builds the global
mesh, and runs one cross-process psum.  Prints "DIST_OK <rank>" only
if the reduced value is exactly the closed-form sum over ranks.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))     # repo root: apex_tpu is not installed


def main() -> int:
    if len(sys.argv) > 1:
        rank, world, port = (int(sys.argv[1]), int(sys.argv[2]),
                             sys.argv[3])
        # launcher env contract (what comm.initialize_distributed
        # parses)
        os.environ["JAX_COORDINATOR_ADDRESS"] = f"127.0.0.1:{port}"
        os.environ["WORLD_SIZE"] = str(world)
        os.environ["RANK"] = str(rank)
    else:                       # apex_tpu.launch already set the env
        rank = int(os.environ["RANK"])
        world = int(os.environ["WORLD_SIZE"])
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"

    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_cpu_collectives_implementation", "gloo")

    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from apex_tpu import comm

    timeout = os.environ.get("APEX_DIST_INIT_TIMEOUT")
    mesh = comm.initialize_distributed(      # coords come from env
        timeout=float(timeout) if timeout else None)
    assert jax.process_count() == world, jax.process_count()
    assert jax.process_index() == rank, jax.process_index()
    n = world * 2                            # 2 local devices per rank
    assert len(mesh.devices.flatten()) == n

    # one shard per GLOBAL device, value = global row + 1 (assigned by
    # global index, so no assumption about rank-to-slot order); the
    # jitted sum is a cross-process all-reduce on the gloo backend
    sharding = NamedSharding(mesh, P(("data", "pipe", "ctx", "model")))

    def shard_for(idx):
        rows = np.arange(n, dtype=np.float32)[idx[0]]
        return np.broadcast_to((rows + 1.0)[:, None], (len(rows), 4))

    arr = jax.make_array_from_callback((n, 4), sharding, shard_for)
    total = jax.jit(jnp.sum,
                    out_shardings=NamedSharding(mesh, P()))(arr)
    want = 4.0 * n * (n + 1) / 2.0
    got = float(np.asarray(total))
    assert got == want, (got, want)
    print(f"DIST_OK {rank}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
