# apexlint fixture: the negative twin of bad_telemetry_sync — metrics
# stay on device through the loop (MetricRing slot writes), the host
# reads ONCE per window at the flush boundary; non-metric host math in
# a loop is none of APX102's business.
from apex_tpu import telemetry


def run_training(step, state, tel, n):
    for i in range(n):
        state, metrics = step(state)
        tel.record(metrics, i)           # device-side ring write
    records = tel.flush()                # ONE device_get per window
    return records


def aggregate(rows):
    total = 0.0
    for row in rows:
        total += float(row.count)        # not a metric value: quiet
    return total


def report(last_record):
    # syncing OUTSIDE the loop is exactly where syncing belongs
    return float(last_record["amp/grad_norm"] or 0.0)
