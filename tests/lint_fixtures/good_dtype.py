# apexlint fixture: dtype-disciplined twin of bad_dtype.
import jax
import jax.numpy as jnp


def matmul_kernel(x_ref, w_ref, o_ref):
    x = x_ref[...]
    w = w_ref[...]
    acc = jnp.dot(x, w, preferred_element_type=jnp.float32)
    # bare Python literal: weakly typed, keeps the bf16 path bf16
    o_ref[...] = (acc * 0.5).astype(o_ref.dtype)


@jax.jit
def upcast(x):
    return x.astype(jnp.float32)


def host_norm(x):
    """Host-side numpy f64 is fine — not device-reachable."""
    import numpy as np
    return float(np.linalg.norm(np.asarray(x, np.float64)))
