# apexlint fixture: every per-iteration telemetry pull below must trip
# APX102 (and only APX102 — nothing here is jit-reachable, so APX101
# stays quiet and the families stay isolated).
# These files are linted as TEXT, never imported.
import jax


def run_training(step, state, scaler, n):
    history = []
    for i in range(n):
        state, metrics = step(state)
        history.append(float(metrics["grad_norm"]))      # APX102: float()
        scale = jax.device_get(scaler.loss_scale)        # APX102: device_get
        if metrics["found_inf"].item():                  # APX102: .item()
            print("overflow at", i, scale)
        metrics["update_norm"].block_until_ready()       # APX102: stall
    return history


def watch(stream):
    while True:
        rec = next(stream)
        trust = float(rec.max_trust_ratio)               # APX102: float()
        if trust > 10:
            break
