# apexlint fixture: every per-microbatch unpack / per-leaf tree-map
# add below must trip APX103 (and only APX103 — nothing here is a host
# sync or jit-reachable, so the families stay isolated).
# These files are linted as TEXT, never imported.
import jax


def accumulate_microbatches(plan, micro_grad_bufs, params):
    acc = None
    for bufs in micro_grad_bufs:
        grads = plan.unpack_grads(bufs)                  # APX103: unpack
        if acc is None:
            acc = grads
        else:
            acc = jax.tree_util.tree_map(                # APX103: tree add
                lambda a, g: a + g, acc, grads)
    return acc


def accumulate_trees(micro_grads, accum):
    step = 0
    while step < len(micro_grads):
        accum = jax.tree_util.tree_map(                  # APX103: tree add
            lambda a, g: a + g, accum, micro_grads[step])
        step += 1
    return accum
