# apexlint fixture: the clean twins — scale unapplied before every
# reduction, fp8 dots with post-hoc unscale, non-fp8 casts.  Must lint
# clean.  These files are linted as TEXT, never imported.
import jax
import jax.numpy as jnp


@jax.jit
def norm_after_dequant(g, scale):
    q = (g * scale).astype(jnp.float8_e4m3fn)
    f = q.astype(jnp.float32) / scale           # scale unapplied
    return jnp.linalg.norm(f)


@jax.jit
def sum_after_inverse_scale(g, scale):
    q = (g * scale).astype(jnp.float8_e5m2)
    deq = q.astype(jnp.float32) * (1.0 / scale)
    return jnp.sum(deq)


@jax.jit
def fp8_dot_then_unscale(x, w, sx, sw):
    qx = (x * sx).astype(jnp.float8_e4m3fn)
    qw = (w * sw).astype(jnp.float8_e4m3fn)
    # the legitimate fp8 matmul shape: dot over scaled operands,
    # unscaled afterwards — not a reduction hazard
    acc = jax.lax.dot_general(qx, qw, (((1,), (0,)), ((), ())),
                              preferred_element_type=jnp.float32)
    return acc / (sx * sw)


@jax.jit
def bf16_cast_is_not_fp8(x):
    h = x.astype(jnp.bfloat16)
    return jnp.sum(h)                           # plain cast: no scale
