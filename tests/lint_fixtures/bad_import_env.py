# apexlint fixture: import-time environment family (APX601).
import os

DEBUG = os.environ.get("APEX_FIXTURE_DEBUG", "") == "1"    # APX601
LEVEL = os.environ["APEX_FIXTURE_LEVEL"]                   # APX601
ALT = os.getenv("APEX_FIXTURE_ALT", "fallback")            # APX601
