# apexlint fixture: Pallas geometry family (APX501/APX502).
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def shift_kernel(x_ref, o_ref):
    i = pl.program_id(0)
    o_ref[...] = x_ref[i + 1]                  # APX502: unguarded edge


def shifted(x):
    return pl.pallas_call(
        shift_kernel,
        grid=(4,),
        in_specs=[pl.BlockSpec((7, 100), lambda i: (i, 0))],   # APX501
        out_specs=pl.BlockSpec((8, 100), lambda i: (i, 0)),    # APX501
        out_shape=jax.ShapeDtypeStruct((28, 100), jnp.float32),
    )(x)
