"""APX1002: ``_a`` then ``_b`` on the worker, ``_b`` then ``_a`` on
the main path — a lock-order inversion that deadlocks under load."""
import threading

_a = threading.Lock()
_b = threading.Lock()


def _worker():
    with _a:
        with _b:
            pass


def main_path():
    with _b:
        with _a:
            pass


def start():
    t = threading.Thread(target=_worker)
    t.start()
    return t
