"""APX1003: ``time.sleep`` inside the critical section — every other
flush waits out the nap."""
import threading
import time

_lock = threading.Lock()
_pending = []


def flush():
    with _lock:
        time.sleep(0.1)
        _pending.clear()
