"""Clean twin: both paths acquire in the same ``_a`` -> ``_b``
order, so the wait-for graph stays acyclic."""
import threading

_a = threading.Lock()
_b = threading.Lock()


def _worker():
    with _a:
        with _b:
            pass


def main_path():
    with _a:
        with _b:
            pass


def start():
    t = threading.Thread(target=_worker)
    t.start()
    return t
