"""APX1005: a registered callback calls back into the registry's own
dispatcher — re-entrant fan-out (and a deadlock if the dispatcher ever
takes a lock around the callback loop)."""
import threading


class Registry:
    def __init__(self):
        self._subs = []
        self._lock = threading.Lock()

    def add(self, fn):
        with self._lock:
            self._subs.append(fn)

    def emit(self, value):
        with self._lock:
            subs = list(self._subs)
        for fn in subs:
            fn(value)


broadcast = Registry()


def naughty_cb(value):
    broadcast.emit(value)


broadcast.add(naughty_cb)
