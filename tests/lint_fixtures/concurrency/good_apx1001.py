"""Clean twin: every ``self.total`` touch holds ``self._lock``."""
import threading


class Accumulator:
    def __init__(self):
        self.total = 0
        self._lock = threading.Lock()

    def _work(self):
        for _ in range(100):
            with self._lock:
                self.total += 1

    def start(self):
        t = threading.Thread(target=self._work)
        t.start()
        return t

    def report(self):
        with self._lock:
            return self.total
