"""Clean twin: the blocking call runs before the lock is taken; the
critical section only mutates the shared list."""
import threading
import time

_lock = threading.Lock()
_pending = []


def flush():
    time.sleep(0.1)
    with _lock:
        _pending.clear()
