"""Clean twin: the callback observes the value and returns — it never
re-enters the registry that dispatched it."""
import threading


class Registry:
    def __init__(self):
        self._subs = []
        self._lock = threading.Lock()

    def add(self, fn):
        with self._lock:
            self._subs.append(fn)

    def emit(self, value):
        with self._lock:
            subs = list(self._subs)
        for fn in subs:
            fn(value)


broadcast = Registry()


def polite_cb(value):
    return value + 1


broadcast.add(polite_cb)
