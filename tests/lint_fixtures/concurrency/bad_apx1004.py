"""APX1004: the SIGTERM handler does I/O — ``open`` is not
async-signal-safe and can re-enter malloc mid-interrupt."""
import signal


def _on_term(signum, frame):
    with open("/tmp/dying", "w") as fh:
        fh.write("terminated\n")


def install():
    signal.signal(signal.SIGTERM, _on_term)
