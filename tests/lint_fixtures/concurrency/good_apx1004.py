"""Clean twin: the handler only sets an Event; the main loop does the
I/O at its next safe point."""
import signal
import threading

_stop = threading.Event()


def _on_term(signum, frame):
    _stop.set()


def install():
    signal.signal(signal.SIGTERM, _on_term)
