"""APX1001: the worker thread and the main path both touch
``self.total`` with no common lock."""
import threading


class Accumulator:
    def __init__(self):
        self.total = 0

    def _work(self):
        for _ in range(100):
            self.total += 1

    def start(self):
        t = threading.Thread(target=self._work)
        t.start()
        return t

    def report(self):
        return self.total
