# apexlint fixture: donation family (APX401) — a step jit threading
# state without donate_argnums keeps two state generations in HBM.
import jax


def train_step(params, opt_state, batch):
    grads = jax.grad(lambda p: (p * batch).sum())(params)
    new_params = params - 1e-3 * grads
    return new_params, opt_state


update = jax.jit(train_step)                   # APX401


@jax.jit
def ema_update(ema_state, value):               # APX401 (decorator form)
    return 0.9 * ema_state + 0.1 * value
