"""APX402 fixture: donated buffers read after the donating call."""
import jax
import jax.numpy as jnp


def advance(ring, value):
    return ring.at[0].set(value)


commit = jax.jit(advance, donate_argnums=(0,))


def reuse_positional():
    ring = jnp.zeros((8,))
    out = commit(ring, 1.0)
    return ring + out          # APX402: ring was donated, not rebound


def make_apply(fn):
    return jax.jit(fn, donate_argnames=("carry",))


refresh = jax.jit(advance, donate_argnums=(0,))


def reuse_keyword():
    apply = jax.jit(advance, donate_argnames=("ring",))
    buf = jnp.ones((4,))
    apply(value=0.0, ring=buf)
    return buf.sum()           # APX402: buf donated by name
