# apexlint fixture: trace-safe twin of bad_retrace — lax control flow
# for traced values, statics marked static, jit bound once.
import functools

import jax
import jax.numpy as jnp
from jax import lax


@functools.partial(jax.jit, static_argnums=(2,), donate_argnums=(0,))
def clipped_update(params, grad_norm, n):
    params = jnp.where(grad_norm > 1.0, params / grad_norm, params)
    if n > 4:        # fine: n is static_argnums
        params = params * 2.0
    return lax.fori_loop(0, n, lambda i, p: p * 0.5, params)


_step = jax.jit(lambda v: v + 1)


def relaunch(xs):
    return [_step(x) for x in xs]


@jax.jit
def masked(x, mask):
    if mask is None:         # fine: trace-time shape-level branch
        return x
    return jnp.where(mask, x, 0.0)
