"""APX402 negative fixture: the carry idiom and copies stay clean."""
import jax
import jax.numpy as jnp


def advance(ring, value):
    return ring.at[0].set(value)


commit = jax.jit(advance, donate_argnums=(0,))


def carry_idiom():
    ring = jnp.zeros((8,))
    ring = commit(ring, 1.0)   # rebound by the donating call itself
    return ring + 1.0


def copy_before_donate():
    ring = jnp.zeros((8,))
    snapshot = jnp.array(ring, copy=True)
    commit(ring, 2.0)
    return snapshot.sum()      # the copy, not the donated buffer


def fresh_value_each_call():
    acc = jnp.float32(0.0)
    for i in range(3):
        ring = jnp.zeros((8,))
        acc = acc + commit(ring, float(i)).sum()
    return acc
