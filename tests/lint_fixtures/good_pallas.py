# apexlint fixture: geometry-clean twin of bad_pallas — (8, 128)-tiled
# blocks, grid edges guarded by pl.when or a modulo wrap.
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def shift_kernel(x_ref, o_ref):
    i = pl.program_id(0)

    @pl.when(i > 0)
    def _():
        o_ref[...] = x_ref[...] + (i - 1)      # guarded by pl.when


def rotate_kernel(x_ref, o_ref):
    i = pl.program_id(0)
    n = pl.num_programs(0)
    o_ref[...] = x_ref[...] * ((i + 1) % n)    # modulo wrap


def shifted(x):
    return pl.pallas_call(
        shift_kernel,
        grid=(4,),
        in_specs=[pl.BlockSpec((8, 128), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((1, 128), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((32, 128), jnp.float32),
    )(x)
