"""APX801 fixture: module-level mutables written under trace."""
import jax
import jax.numpy as jnp

_SEEN_LOSSES = []
_STATS = {}


@jax.jit
def accumulate(w, x):
    loss = jnp.mean((w * x) ** 2)
    _SEEN_LOSSES.append(loss)      # APX801: trace-time append of a tracer
    _STATS["last"] = loss          # APX801: subscript store under trace
    return loss
