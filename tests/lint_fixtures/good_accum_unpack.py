# apexlint fixture: the clean twin of bad_accum_unpack.py — fused flat
# accumulation (no per-leaf work in the loop), unpacking OUTSIDE the
# loop, and tree-map adds on non-gradient data are all fine.
import jax

from apex_tpu import amp
from apex_tpu.ops import multi_tensor as mt


def accumulate_flat(pipe, micro_grad_bufs):
    acc = pipe.init_accum()
    for bufs in micro_grad_bufs:
        acc = pipe.accumulate(acc, bufs)     # fused: one RMW per bucket
    return pipe.finalize(acc, inv_scale=1.0)


def accumulate_kernel(acc_bufs, micro_grad_bufs):
    for bufs in micro_grad_bufs:
        acc_bufs = [mt.flat_accumulate(a, g)[0]
                    for a, g in zip(acc_bufs, bufs)]
    return acc_bufs


def inspect_after_the_loop(plan, acc_bufs):
    # unpacking once, outside any loop, is the documented
    # inspection/test path
    return plan.unpack_grads(acc_bufs)


def merge_metrics(windows):
    out = None
    for w in windows:
        # tree-map add on NON-gradient data: not this rule's business
        out = w if out is None else jax.tree_util.tree_map(
            lambda a, b: a + b, out, w)
    return out
