# apexlint fixture: every host sync below must trip APX101 (and only
# APX101 — donation is satisfied so families stay isolated).
# These files are linted as TEXT, never imported.
import functools

import jax
import jax.numpy as jnp
import numpy as np


@functools.partial(jax.jit, donate_argnums=(0,))
def train_step(state, batch):
    loss = jnp.mean(batch)
    scalar = loss.item()                 # APX101: .item()
    host = np.asarray(state)             # APX101: np.asarray
    f = float(loss)                      # APX101: float() concretizes
    fetched = jax.device_get(state)      # APX101: device_get
    state.block_until_ready()            # APX101: pipeline stall
    return state - loss, (scalar, host, f, fetched)


def log_metrics(state):
    # reached from train_step? no call edge — but this one IS called
    return summarize(state)


def summarize(state):
    return state


def hot_helper(state):
    """Called from train_step's callee chain: still jit-reachable."""
    return int(jnp.sum(state))           # APX101: int() concretizes


@functools.partial(jax.jit, donate_argnums=(0,))
def outer_step(state):
    return hot_helper(state)
