# apexlint fixture: retrace/concretization family (APX301/302/303).
import functools

import jax


@functools.partial(jax.jit, donate_argnums=(0,))
def clipped_update(params, grad_norm, n):
    if grad_norm > 1.0:                        # APX301: traced branch
        params = params / grad_norm
    while grad_norm > 2.0:                     # APX301: traced while
        grad_norm = grad_norm / 2.0
    for _ in range(n):                         # APX303: traced range
        params = params * 0.5
    return params


def relaunch(xs):
    out = []
    for x in xs:
        out.append(jax.jit(lambda v: v + 1)(x))    # APX302: per-call jit
    return out
