# apexlint fixture: donated twin of bad_donation.
import functools

import jax


def train_step(params, opt_state, batch):
    grads = jax.grad(lambda p: (p * batch).sum())(params)
    new_params = params - 1e-3 * grads
    return new_params, opt_state


update = jax.jit(train_step, donate_argnums=(0, 1))


@functools.partial(jax.jit, donate_argnames=("ema_state",))
def ema_update(ema_state, value):
    return 0.9 * ema_state + 0.1 * value


@jax.jit
def evaluate(params, batch):
    """No state threads through: nothing to donate, not step-named."""
    return (params * batch).sum()
