# apexlint fixture: every fp8-scaled reduction below must trip APX204
# (and only APX204 — no host syncs, no other dtype hazards).
# These files are linted as TEXT, never imported.
import jax
import jax.numpy as jnp


@jax.jit
def grad_norm_of_quantized(g, scale):
    q = (g * scale).astype(jnp.float8_e4m3fn)
    return jnp.sum(q)                           # APX204: scaled sum


@jax.jit
def upcast_does_not_unscale(g, scale):
    q = (g * scale).astype(jnp.float8_e5m2)
    f = q.astype(jnp.float32)                   # cast keeps the scale
    return jnp.linalg.norm(f)                   # APX204: scaled norm


@jax.jit
def mean_of_fp8(x, scale):
    q = jnp.clip(x * scale, -448.0, 448.0).astype(jnp.float8_e4m3fn)
    return jnp.mean(q.astype(jnp.bfloat16))     # APX204: scaled mean
