"""APX801 negative fixture: functional carry, thread-local holder,
and host-side (non-jit-reachable) bookkeeping all stay clean."""
import threading

import jax
import jax.numpy as jnp

_TLS = threading.local()       # sanctioned holder (telemetry._tape idiom)
_LIMITS = {"max_norm": 10.0}   # module dict only ever READ under trace
_HISTORY = []


@jax.jit
def accumulate(w, x, history):
    loss = jnp.mean((w * x) ** 2)
    capped = jnp.minimum(loss, _LIMITS["max_norm"])
    history = history.at[0].set(capped)     # carried functionally
    return loss, history


def record_host(loss_value):
    # host-side bookkeeping outside the jit-reachable set is fine
    _HISTORY.append(loss_value)
    _TLS.last = loss_value
    return len(_HISTORY)
