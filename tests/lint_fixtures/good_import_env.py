# apexlint fixture: env reads deferred to call time, plus one
# deliberate import-time knob behind the documented allowlist pragma.
import os


def debug_enabled() -> bool:
    return os.environ.get("APEX_FIXTURE_DEBUG", "") == "1"


def level() -> str:
    return os.environ["APEX_FIXTURE_LEVEL"]


KNOB = os.environ.get(  # apexlint: disable=APX601
    "APEX_FIXTURE_IMPORT_KNOB")
