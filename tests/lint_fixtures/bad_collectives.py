"""APX7xx fixture: unbound axis, mesh mismatch, dead collectives."""
import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec

mesh = Mesh(np.array(jax.devices()).reshape(-1), axis_names=("data",))


def mean_grads(g):
    # APX701 + APX702: nothing binds "batch" and the mesh declares
    # only ("data",) — stale axis name from a rename
    return jax.lax.pmean(g, "batch")


def reduce_loss(x):
    def body(x):
        jax.lax.psum(jnp.ones(()), "data")      # APX703: result discarded
        idx = jax.lax.axis_index("data")        # APX703: never read
        return jax.lax.psum(x, "data")
    return shard_map(body, mesh=mesh, in_specs=PartitionSpec("data"),
                     out_specs=PartitionSpec())(x)
