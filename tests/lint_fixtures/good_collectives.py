"""APX7xx negative fixture: bound axes, matched mesh, live results."""
import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec

mesh = Mesh(np.array(jax.devices()).reshape(-1), axis_names=("data",))


def reduce_mean(x, axis_name):
    # variable axis: the caller owns the binding (library idiom)
    return jax.lax.pmean(x, axis_name)


def body(x):
    idx = jax.lax.axis_index("data")
    total = jax.lax.psum(x, "data")
    return total + jnp.asarray(idx, total.dtype)


def reduce_loss(x):
    return shard_map(body, mesh=mesh, in_specs=PartitionSpec("data"),
                     out_specs=PartitionSpec())(x)
