# apexlint fixture: dtype-promotion family (APX201/APX202/APX203).
import jax
import jax.numpy as jnp


def matmul_kernel(x_ref, w_ref, o_ref):
    x = x_ref[...]
    w = w_ref[...]
    acc = jnp.dot(x, w)                        # APX201: bf16 partials
    o_ref[...] = acc * jnp.float32(0.5)        # APX203: strong scalar


@jax.jit
def upcast(x):
    return x.astype(jnp.float64)               # APX202: f64 on TPU
