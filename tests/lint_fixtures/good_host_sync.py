# apexlint fixture: the negative twin of bad_host_sync — device math
# stays on device, host syncs live outside the jit-reachable set.
import functools

import jax
import jax.numpy as jnp
import numpy as np


@functools.partial(jax.jit, donate_argnums=(0,))
def train_step(state, batch):
    loss = jnp.mean(batch)
    return state - loss


def report(state):
    """Host-side reporting: nothing jitted reaches this, so syncing
    here is fine (and the right place for it)."""
    arr = np.asarray(state)
    return float(arr.mean()), int(arr.size)
