"""Microbatch calculator parity (reference:
apex/transformer/microbatches.py — constant and batch-size-rampup
calculators behind build_num_microbatches_calculator)."""

import pytest

from apex_tpu.transformer.microbatches import (
    ConstantNumMicroBatches,
    RampupBatchsizeNumMicroBatches,
    build_num_microbatches_calculator,
)


def test_constant_calculator():
    c = ConstantNumMicroBatches(global_batch_size=64, micro_batch_size=4,
                                data_parallel_size=2)
    # 64 global / (4 micro * 2 dp) = 8 microbatches
    assert c.get() == 8
    assert c.get_current_global_batch_size() == 64
    c.update(consumed_samples=1024, consistency_check=True)
    assert c.get() == 8                       # constant stays constant


def test_constant_requires_divisibility():
    with pytest.raises(Exception):
        ConstantNumMicroBatches(global_batch_size=65, micro_batch_size=4,
                                data_parallel_size=2)


def test_rampup_calculator_grows_with_consumed_samples():
    c = RampupBatchsizeNumMicroBatches(
        start_batch_size=16, batch_size_increment=16,
        ramup_samples=1000, global_batch_size=64,
        micro_batch_size=4, data_parallel_size=2)
    c.update(0, False)
    assert c.get_current_global_batch_size() == 16
    first = c.get()
    c.update(500, False)
    mid = c.get_current_global_batch_size()
    assert 16 <= mid <= 64
    c.update(2000, False)                     # past the ramp
    assert c.get_current_global_batch_size() == 64
    assert c.get() == 64 // (4 * 2)
    assert first <= c.get()


def test_builder_dispatch():
    c = build_num_microbatches_calculator(
        rank=0, rampup_batch_size=None, global_batch_size=32,
        micro_batch_size=4, data_parallel_size=1)
    assert isinstance(c, ConstantNumMicroBatches)
    assert c.get() == 8
    c = build_num_microbatches_calculator(
        rank=0, rampup_batch_size=[16, 8, 1000], global_batch_size=32,
        micro_batch_size=4, data_parallel_size=1)
    assert isinstance(c, RampupBatchsizeNumMicroBatches)


def test_accumulate_gradients_matches_full_batch():
    """Mean of microbatch grads == grad of the full-batch mean loss."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from apex_tpu.transformer.microbatches import accumulate_gradients
    w = {"w": jax.random.normal(jax.random.key(0), (16, 1)) * 0.1}
    x = jax.random.normal(jax.random.key(1), (32, 16))
    y = jnp.sum(x[:, :3], axis=1, keepdims=True)

    def loss_fn(p, mb):
        xx, yy = mb
        return jnp.mean((xx @ p["w"] - yy) ** 2)

    full_loss, full_g = jax.value_and_grad(loss_fn)(w, (x, y))
    mb = (x.reshape(4, 8, 16), y.reshape(4, 8, 1))
    acc_loss, acc_g = jax.jit(
        lambda p, mb: accumulate_gradients(loss_fn, p, mb))(w, mb)
    np.testing.assert_allclose(float(acc_loss), float(full_loss),
                               rtol=1e-6)
    np.testing.assert_allclose(np.asarray(acc_g["w"]),
                               np.asarray(full_g["w"]), rtol=1e-5,
                               atol=1e-7)


def test_accumulate_gradients_empty_rejected():
    import jax.numpy as jnp
    import pytest
    from apex_tpu.transformer.microbatches import accumulate_gradients
    with pytest.raises(ValueError, match="empty"):
        accumulate_gradients(lambda p, m: 0.0, {"w": jnp.ones((2,))}, {})
