"""AOT TPU lowering of every Pallas kernel — no TPU needed.

``jax.jit(f).trace(args).lower(lowering_platforms=("tpu",))`` runs the
Mosaic kernel serializer and its verifier on a CPU host.  This catches
the class of bug the round-2 hardware run surfaced (e.g. "Can only
store scalars to SMEM" in the Welford kernel — interpret mode accepts
it, Mosaic rejects it) **in CPU CI**, without claiming the single-client
TPU tunnel.  It does not replace tests/test_tpu_smoke.py (the backend
compile + numerics still need hardware); it front-runs it.

APEX_TPU_FORCE_MOSAIC=1 makes ops/_dispatch emit non-interpreted
pallas_calls off-TPU so the lowering actually contains Mosaic kernels.
"""

import functools

import jax
import jax.numpy as jnp
import pytest


@pytest.fixture(autouse=True)
def _force_mosaic(monkeypatch):
    monkeypatch.setenv("APEX_TPU_FORCE_MOSAIC", "1")


def lower_tpu(f, *args, static=()):
    jax.jit(f, static_argnums=static).trace(*args).lower(
        lowering_platforms=("tpu",))


def grad_of(f, n):
    return jax.grad(lambda *a: jnp.sum(f(*a).astype(jnp.float32) ** 2),
                    argnums=tuple(range(n)))


# --------------------------------------------------------------------------
# attention family
# --------------------------------------------------------------------------

@pytest.mark.parametrize("dtype", [jnp.bfloat16, jnp.float32])
@pytest.mark.parametrize("causal", [False, True])
def test_lower_flash_attention(causal, dtype):
    from apex_tpu.ops.attention import flash_attention
    q = jnp.zeros((1, 2, 1024, 64), dtype)
    f = functools.partial(flash_attention, causal=causal)
    lower_tpu(lambda q: f(q, q, q), q)
    lower_tpu(grad_of(lambda q: f(q, q, q), 1), q)


def test_lower_flash_attention_segments_and_longseq():
    from apex_tpu.ops.attention import flash_attention
    q = jnp.zeros((1, 1, 512, 64), jnp.bfloat16)
    seg = (jnp.zeros((1, 512), jnp.int32),) * 2
    lower_tpu(lambda q: flash_attention(q, q, q, segment_ids=seg), q)
    ql = jnp.zeros((1, 1, 8192, 128), jnp.bfloat16)
    lower_tpu(lambda q: flash_attention(q, q, q, True), ql)
    lower_tpu(grad_of(lambda q: flash_attention(q, q, q, True), 1), ql)


# --------------------------------------------------------------------------
# norm / softmax / xentropy / welford / wgrad
# --------------------------------------------------------------------------

def test_lower_flash_attention_dropout():
    """Fused hash-mask dropout (SMEM seed scalar + int vector hash in
    every kernel) must pass the Mosaic verifier, fwd and bwd."""
    from apex_tpu.ops.attention import flash_attention
    q = jnp.zeros((1, 2, 1024, 64), jnp.bfloat16)
    s = jnp.int32(7)

    def f(q, s):
        return flash_attention(q, q, q, True, dropout_rate=0.1,
                               dropout_seed=s)
    lower_tpu(f, q, s)
    lower_tpu(grad_of(lambda q, s: f(q, s), 1), q, s)


def test_lower_flash_attention_single_kv_block():
    """nk == 1 geometry takes the dedicated scratch-free fast-path
    body (_fwd_kernel_1kv) — its own Mosaic lowering, every variant:
    ± causal, ± lse (inference), fused dropout."""
    import functools

    from apex_tpu.ops.attention import flash_attention
    q = jnp.zeros((1, 2, 512, 64), jnp.bfloat16)
    for causal in (False, True):
        f = functools.partial(flash_attention, causal=causal)
        lower_tpu(lambda q, f=f: f(q, q, q), q)            # no-lse fwd
        lower_tpu(grad_of(lambda q, f=f: f(q, q, q), 1), q)  # lse fwd
    s = jnp.int32(3)
    lower_tpu(lambda q, s: flash_attention(
        q, q, q, True, dropout_rate=0.1, dropout_seed=s), q, s)


def test_lower_flash_attention_gqa():
    """GQA/MQA geometry (kv rows indexed through _kv_row, dkv grid
    folding the q group into its sequential axis) must pass the Mosaic
    verifier, fwd and bwd."""
    from apex_tpu.ops.attention import flash_attention
    q = jnp.zeros((1, 8, 1024, 64), jnp.bfloat16)
    kv = jnp.zeros((1, 2, 1024, 64), jnp.bfloat16)
    lower_tpu(lambda q, k, v: flash_attention(q, k, v, True), q, kv, kv)
    lower_tpu(grad_of(
        lambda q, k, v: flash_attention(q, k, v, True), 3), q, kv, kv)


@pytest.mark.parametrize("dtype", [jnp.bfloat16, jnp.float32])
@pytest.mark.parametrize("rms", [False, True])
def test_lower_norms(rms, dtype):
    from apex_tpu.ops import layer_norm as ln
    x = jnp.zeros((512, 1024), dtype)
    w = jnp.ones((1024,), dtype)
    b = jnp.zeros((1024,), dtype)
    if rms:
        lower_tpu(ln.fused_rms_norm, x, w)
        lower_tpu(grad_of(ln.fused_rms_norm, 2), x, w)
    else:
        lower_tpu(ln.fused_layer_norm, x, w, b)
        lower_tpu(grad_of(ln.fused_layer_norm, 3), x, w, b)


@pytest.mark.parametrize("dtype", [jnp.bfloat16, jnp.float32])
def test_lower_softmax_family(dtype):
    from apex_tpu.ops import softmax as sm
    x = jnp.zeros((2, 4, 256, 256), dtype)
    mask = jnp.zeros((2, 1, 256, 256), bool)
    lower_tpu(sm.scaled_masked_softmax, x, mask, 0.83, static=(2,))
    xt = jnp.zeros((8, 512, 512), dtype)
    lower_tpu(sm.scaled_upper_triang_masked_softmax, xt, 0.5, static=(1,))
    lower_tpu(grad_of(
        lambda t: sm.scaled_upper_triang_masked_softmax(t, 0.5), 1), xt)


def test_softmax_traced_scale_raises_clearly():
    """jitting the raw op with a traced scale must fail with guidance,
    not an opaque UnexpectedTracerError from custom_vjp internals (the
    round-2 TPU smoke failure mode)."""
    from apex_tpu.ops import softmax as sm
    x = jnp.zeros((2, 4, 256, 256), jnp.float32)
    mask = jnp.zeros((2, 1, 256, 256), bool)
    with pytest.raises(TypeError, match="static_argnums"):
        jax.jit(sm.scaled_masked_softmax)(x, mask, 0.83)
    with pytest.raises(TypeError, match="static_argnums"):
        jax.jit(sm.scaled_upper_triang_masked_softmax)(
            jnp.zeros((8, 128, 128)), 0.5)


def test_lower_xentropy_welford_wgrad():
    from apex_tpu.ops import welford as wf
    from apex_tpu.ops import wgrad as wg
    from apex_tpu.ops import xentropy as xe
    logits = jnp.zeros((1024, 32768), jnp.bfloat16)
    labels = jnp.zeros((1024,), jnp.int32)
    lower_tpu(lambda l: xe.softmax_cross_entropy(l, labels,
                                                 smoothing=0.1), logits)
    lower_tpu(grad_of(lambda l: xe.softmax_cross_entropy(
        l, labels, smoothing=0.1), 1), logits)
    lower_tpu(wf.welford_mean_var, jnp.zeros((4096, 256)))
    lower_tpu(wg.wgrad_gemm_accum_fp32,
              jnp.zeros((512, 1024), jnp.bfloat16),
              jnp.zeros((512, 2048), jnp.bfloat16),
              jnp.zeros((2048, 1024), jnp.float32))


# --------------------------------------------------------------------------
# multi-tensor substrate
# --------------------------------------------------------------------------

def test_lower_multi_tensor_family():
    from apex_tpu.ops import multi_tensor as mt
    n = (1 << 20) + 123
    p = jnp.zeros((n,), jnp.float32)
    lower_tpu(mt.flat_scale, p, jnp.float32(0.5))
    lower_tpu(lambda x, y: mt.flat_axpby(0.5, x, -0.25, y), p, p)
    lower_tpu(mt.flat_l2norm, p)
    lower_tpu(lambda a, g: mt.flat_accumulate(a, g, 0.5), p,
              p.astype(jnp.bfloat16))
    lower_tpu(lambda *a: mt.flat_adam(
        *a, lr=1e-3, beta1=0.9, beta2=0.999, eps=1e-8, weight_decay=0.01,
        step=3, adam_w_mode=True), p, p, p, p)
    lower_tpu(lambda *a: mt.flat_sgd(
        *a, lr=0.1, momentum=0.9, dampening=0.0, weight_decay=1e-4,
        nesterov=False, first_run=False), p, p, p)
    lower_tpu(lambda *a: mt.flat_adagrad(
        *a, lr=1e-2, eps=1e-10, weight_decay=0.01), p, p, p)
    # segmented family: per-tensor norms via bucket segment ids
    seg = jnp.zeros((n,), jnp.int32)
    lower_tpu(lambda p_, g_, m_, v_: mt.flat_lamb(
        p_, g_, m_, v_, seg, 1, lr=1e-3, beta1=0.9, beta2=0.999,
        eps=1e-6, weight_decay=0.01, step=3), p, p, p, p)
    vseg = jnp.zeros((1,), jnp.float32)
    lower_tpu(lambda p_, g_, m_: mt.flat_novograd(
        p_, g_, m_, vseg, seg, lr=1e-3, beta1=0.95, beta2=0.98,
        eps=1e-8, weight_decay=0.01, first_run=False), p, p, p)
