"""Elastic scale-UP: beacon-admitted host rejoin, grow-capable
resharding, and the load-driven fleet autoscaler (the inverse flow of
the failure-domain triad — ISSUE 12)."""

import io
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.optimizers import FusedAdam
from apex_tpu.resilience import (CheckpointManager, FleetController,
                                 FleetMonitor, FleetRecoveryFailed,
                                 ScaleDecision, Watchdog, run_elastic)
from apex_tpu.resilience import fleet as fleet_mod
from apex_tpu.resilience.faults import FaultInjector, FaultSpec
from apex_tpu.resilience.fleet import LocalChannel, SimulatedPeers


def _lag_monitor(ch, host=0, n_hosts=3, slow=2, dead=4, **kw):
    """A step-lag-only monitor (deterministic: no wall clock)."""
    return FleetMonitor(channel=ch, host=host, n_hosts=n_hosts,
                        slow_after_steps=slow, dead_after_steps=dead,
                        slow_after_s=None, dead_after_s=None,
                        agreement_timeout_s=0.2, **kw)


# ---------------------------------------------------------------------
# Monitor: (host, incarnation)-keyed sticky-dead + return candidates.
# ---------------------------------------------------------------------

def test_dead_host_with_fresh_incarnation_becomes_candidate():
    """The satellite fix: sticky-dead keys on (host, incarnation) —
    a host that dies and returns with a FRESH incarnation surfaces as
    a host_return candidate instead of staying dead forever."""
    ch = LocalChannel()
    mon = _lag_monitor(ch, slow=2, dead=4)
    sim = SimulatedPeers(ch, hosts=[1, 2]).attach(mon)
    for s in range(1, 4):
        mon.beat(s)
    sim.kill(2)
    events = []
    for s in range(4, 12):
        events += mon.beat(s)
    assert [(e.kind, e.host) for e in events] == \
        [("host_slow", 2), ("host_dead", 2)]
    assert mon.return_candidates() == {}
    sim.revive(2)                             # fresh incarnation
    events = mon.beat(12)
    assert [(e.kind, e.host) for e in events] == [("host_return", 2)]
    assert events[0].evidence["incarnation"] == \
        sim.incarnation_of(2) == 2
    assert mon.return_candidates() == {2: 2}
    # fires once per incarnation, and the host stays classified dead
    # until an admission round actually admits it
    assert mon.beat(13) == []
    assert mon.dead_hosts() == [2]
    assert mon.return_candidates() == {2: 2}


def test_stale_incarnation_beacon_stays_dead_zombie():
    """A dead host's OLD incarnation beaconing again (split-brain
    zombie: the process never died, its network partition healed) must
    stay ignored — no host_return, no candidate, still dead."""
    clk = [1000.0]
    ch = LocalChannel()
    mon = FleetMonitor(channel=ch, host=0, n_hosts=2,
                       slow_after_s=1.0, dead_after_s=3.0,
                       clock=lambda: clk[0])
    ch.put("beacon/1", {"host": 1, "step": 1, "wall_time": clk[0],
                        "incarnation": 7})
    assert mon.poll(1) == []
    clk[0] += 5.0
    assert [e.kind for e in mon.poll(2)] == ["host_dead"]
    # the zombie: same incarnation 7, suddenly fresh again
    ch.put("beacon/1", {"host": 1, "step": 3, "wall_time": clk[0],
                        "incarnation": 7})
    assert mon.poll(3) == []
    assert mon.return_candidates() == {}
    assert mon.dead_hosts() == [1]
    # a FRESH incarnation from the same host is a real return
    ch.put("beacon/1", {"host": 1, "step": 4, "wall_time": clk[0],
                        "incarnation": 8})
    evs = mon.poll(4)
    assert [e.kind for e in evs] == ["host_return"]
    assert mon.return_candidates() == {1: 8}


def test_candidate_drops_when_it_flaps_away_again():
    """Candidacy is re-validated every poll: a returned host that
    stops beaconing again (flapping) drops out before admission."""
    ch = LocalChannel()
    mon = _lag_monitor(ch, n_hosts=2, slow=2, dead=4)
    sim = SimulatedPeers(ch, hosts=[1]).attach(mon)
    mon.beat(1)
    sim.kill(1)
    for s in range(2, 8):
        mon.beat(s)
    assert mon.dead_hosts() == [1]
    sim.revive(1)
    mon.beat(8)
    assert mon.return_candidates() == {1: 2}
    sim.kill(1)                               # flaps away again
    for s in range(9, 14):
        mon.beat(s)
    assert mon.return_candidates() == {}      # stale: dropped
    assert mon.dead_hosts() == [1]


def test_evicted_nonmember_host_can_candidate_after_shrink():
    """After a shrink evicts the dead host from the member set, its
    fresh-incarnation beacons (a non-member now) still surface as a
    candidate — and its old incarnation's beacons do not."""
    ch = LocalChannel()
    mon = _lag_monitor(ch, slow=2, dead=4)
    sim = SimulatedPeers(ch, hosts=[1, 2]).attach(mon)
    for s in range(1, 4):
        mon.beat(s)
    sim.kill(2)
    for s in range(4, 10):
        mon.beat(s)
    epoch, survivors = mon.agree_survivors(10, timeout_s=0.2)
    assert survivors == [0, 1] and mon.hosts == [0, 1]
    # the dead host's LAST beacon is still on the channel (stale
    # incarnation): not a candidate
    mon.beat(11)
    assert mon.return_candidates() == {}
    sim.revive(2)
    events = mon.beat(12)
    assert [(e.kind, e.host) for e in events] == [("host_return", 2)]
    assert mon.return_candidates() == {2: 2}


def test_agree_admission_grows_members_under_fresh_epoch():
    ch = LocalChannel()
    mon = _lag_monitor(ch, slow=2, dead=4)
    sim = SimulatedPeers(ch, hosts=[1, 2]).attach(mon)
    for s in range(1, 4):
        mon.beat(s)
    sim.kill(2)
    for s in range(4, 10):
        mon.beat(s)
    e1, survivors = mon.agree_survivors(10, timeout_s=0.2)
    assert survivors == [0, 1]
    sim.revive(2)
    mon.beat(11)
    cands = mon.return_candidates()
    e2, members = mon.agree_admission(11, cands, timeout_s=2.0)
    assert members == [0, 1, 2] and e2 == e1 + 1
    assert mon.hosts == [0, 1, 2]
    assert mon.epoch == e2
    assert mon.status(2) == fleet_mod.HOST_LIVE
    assert mon.return_candidates() == {}      # consumed by admission


def test_agree_admission_without_joiner_response_is_noop():
    """A joiner that never answers the round (went silent between the
    candidate poll and the agreement) drops out of the intersection:
    the round degrades to a no-op, not a phantom admission."""
    ch = LocalChannel()
    mon = _lag_monitor(ch, n_hosts=2, slow=2, dead=50)
    # peer 1 answers agreement rounds; joiner 3 never does
    mon.add_spin_hook(lambda epoch: ch.put(
        f"verdict/{epoch}/1", {"host": 1, "epoch": epoch,
                               "survivors": [0, 1, 3]}))
    epoch, members = mon.agree_admission(5, {3: 9}, timeout_s=0.05)
    assert members == [0, 1]
    assert mon.hosts == [0, 1]


def test_agree_survivors_exclude_releases_live_host():
    """The autoscaler's voluntary release: exclude= drops the victim
    from this host's proposal, the intersection rule evicts it, and
    its unchanged incarnation cannot immediately re-candidate."""
    ch = LocalChannel()
    mon = _lag_monitor(ch, slow=2, dead=50)
    sim = SimulatedPeers(ch, hosts=[1, 2]).attach(mon)
    mon.beat(1)
    epoch, survivors = mon.agree_survivors(2, timeout_s=2.0,
                                           exclude=(2,))
    assert survivors == [0, 1] and mon.hosts == [0, 1]
    # the released host keeps beaconing under the SAME incarnation —
    # stale by the (host, incarnation) rule, so no rejoin candidate
    for s in range(3, 6):
        mon.beat(s)
    assert mon.return_candidates() == {}
    # a restart (fresh incarnation) is what re-candidates it
    sim.kill(2)
    sim.revive(2)
    mon.beat(6)
    assert mon.return_candidates() == {2: 2}


# ---------------------------------------------------------------------
# run_elastic chaos: the grow matrix.
# ---------------------------------------------------------------------

_TOTAL, _EVERY = 20, 3


def _mixed_tree():
    return {
        "w1": jnp.linspace(-1.0, 1.0, 256).astype(jnp.bfloat16
                                                  ).reshape(16, 16),
        "b1": jnp.linspace(0.0, 1.0, 16).astype(jnp.float32),
    }


def _grads_for(tree):
    return jax.tree_util.tree_map(
        lambda p: (p.astype(jnp.float32) * 1e-2 + 1e-3).astype(p.dtype),
        tree)


def _many_tree():
    """Several same-dtype leaves, so a max_bucket_bytes cap genuinely
    splits the dtype group into multiple buckets (chunk boundaries
    fall on leaf boundaries — a 2-leaf tree cannot re-chunk)."""
    return {f"w{i}": jnp.linspace(-1.0 + i, 1.0 + i, 64
                                  ).astype(jnp.float32)
            for i in range(4)}


def _assert_tree_equal(a, b):
    la, lb = jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


class _GrowJob:
    """One faked-multi-host job: optimizer + manager + FleetMonitor
    over simulated peers (the test_fleet.py _FleetJob shape, grown)."""

    def __init__(self, ckpt_dir, n_hosts=3, slow=2, dead=4,
                 total=_TOTAL, tree_fn=_mixed_tree, **opt_kw):
        tree = tree_fn()
        self.opt = FusedAdam(tree, lr=1e-2, **opt_kw)
        self.g = _grads_for(tree)
        self.total = total
        self.mgr = CheckpointManager(ckpt_dir, keep=3, every=_EVERY)
        self.template = jax.tree_util.tree_map(jnp.zeros_like, tree)
        self.channel = LocalChannel()
        self.mon = _lag_monitor(self.channel, n_hosts=n_hosts,
                                slow=slow, dead=dead)
        self.sim = SimulatedPeers(self.channel,
                                  hosts=list(range(1, n_hosts)))
        self.sim.attach(self.mon)
        self.shrinks = []
        self.grows = []

    def step_fn(self, step):
        self.opt.step(self.g)

    def run(self, **kw):
        kw.setdefault("backoff_s", 0.0)
        return run_elastic(
            self.step_fn, self.mgr, self.opt, total_steps=self.total,
            params_like=self.template, fleet=self.mon,
            on_shrink=lambda survivors, epoch:
                self.shrinks.append((epoch, tuple(survivors))),
            on_grow=lambda members, epoch:
                self.grows.append((epoch, tuple(members))), **kw)

    def close(self):
        self.mon.close()
        self.mgr.close()


@pytest.fixture(scope="module")
def _grow_reference(tmp_path_factory):
    """The uninterrupted run every recovered run must match bit-exactly
    (the step math is mesh-size-independent, so one reference serves
    shrink AND grow recoveries)."""
    job = _GrowJob(str(tmp_path_factory.mktemp("grow_ref")))
    res = job.run()
    assert res.step == _TOTAL
    assert res.mesh_shrinks == 0 and res.mesh_grows == 0
    job.close()
    return job


def test_kill_shrink_return_admit_grow_replays_bit_exact(
        tmp_path, _grow_reference):
    """THE acceptance flow: a 3-host fleet loses a host (shrink),
    re-admits it on return under a fresh incarnation and epoch (grow),
    resumes on the full mesh and replays bit-exactly vs an
    uninterrupted run."""
    with FaultInjector([
            FaultSpec("peer_death", at_step=4, target=2),
            FaultSpec("host_return", at_step=12, target=2)]) as inj:
        job = _GrowJob(str(tmp_path))
        with pytest.warns(UserWarning, match="admitting host"):
            res = job.run()
        assert len(inj.fired) == 2
    assert res.step == _TOTAL
    assert res.mesh_shrinks == 1 and res.mesh_grows == 1
    assert job.shrinks and job.shrinks[0][1] == (0, 1)
    assert job.grows and job.grows[0][1] == (0, 1, 2)
    assert job.mon.hosts == [0, 1, 2]         # back to full strength
    assert job.mon.epoch == 2                 # shrink + grow epochs
    kinds = [f.kind for f in job.mon.timeline]
    assert "host_dead" in kinds and "host_return" in kinds
    events = [(e.get("event"), e.get("step")) for e in job.mon.events]
    # each resize's replay closes its incident: two causal chains,
    # each ending in a replay_complete carrying the chain's id
    assert [ev for ev, _ in events] == \
        ["shrink", "replay_complete", "grow", "replay_complete"]
    chains = [e.get("incident_id") for e in job.mon.events]
    assert chains[0] == chains[1] and chains[2] == chains[3]
    assert chains[0] != chains[2]             # two distinct incidents
    assert chains[0].startswith("inc-") and "host_dead" in chains[0]
    assert "host_return" in chains[2]
    grow = next(e for e in job.mon.events if e.get("event") == "grow")
    assert grow["admitted"] == [2] and grow["members"] == [0, 1, 2]
    assert grow["to_step"] is not None
    _assert_tree_equal(job.opt.params, _grow_reference.opt.params)
    job.close()


def test_flapping_host_one_shrink_zero_oscillation(tmp_path,
                                                   _grow_reference):
    """Hysteresis holds: the peer dies (one shrink), returns inside
    the admission cooldown (refused), dies again — zero grows, zero
    further shrinks, and the refusal is on the timeline."""
    with FaultInjector([
            FaultSpec("peer_death", at_step=4, target=2),
            FaultSpec("flapping_host", at_step=10, target=2,
                      n_steps=2)]) as inj:
        job = _GrowJob(str(tmp_path))
        with pytest.warns(UserWarning, match="returned with a fresh"):
            res = job.run(admission_cooldown_steps=15)
        assert len(inj.fired) == 2
    assert res.step == _TOTAL
    assert res.mesh_shrinks == 1 and res.mesh_grows == 0
    assert len(job.shrinks) == 1 and not job.grows
    assert job.mon.hosts == [0, 1]            # never re-admitted
    refused = [e for e in job.mon.events
               if e.get("event") == "admission_refused"]
    assert refused and refused[0]["reason"] == "cooldown"
    assert refused[0]["host"] == 2
    _assert_tree_equal(job.opt.params, _grow_reference.opt.params)
    job.close()


def test_grow_during_incident_refused_then_admitted(tmp_path,
                                                    _grow_reference):
    """An admission request while the watchdog has an OPEN incident
    must be refused; once the incident closes, the same candidate is
    admitted."""
    wd = Watchdog(detectors=[], clean_window=4)
    orig = wd.open_incident
    wd.open_incident = lambda step: step <= 14 or orig(step)
    with FaultInjector([
            FaultSpec("peer_death", at_step=4, target=2),
            FaultSpec("grow_during_incident", at_step=12, target=2)]):
        job = _GrowJob(str(tmp_path))
        import warnings as _w
        with _w.catch_warnings():
            _w.simplefilter("ignore")
            res = job.run(watchdog=wd)
    assert res.mesh_shrinks == 1 and res.mesh_grows == 1
    refused = [e for e in job.mon.events
               if e.get("event") == "admission_refused"]
    assert refused and refused[0]["reason"] == "open_incident"
    grow = next(e for e in job.mon.events if e.get("event") == "grow")
    assert grow["step"] > 14                  # only after it closed
    assert job.mon.hosts == [0, 1, 2]
    _assert_tree_equal(job.opt.params, _grow_reference.opt.params)
    wd.close()
    job.close()


def test_grow_without_any_checkpoint_raises_typed(tmp_path):
    """An admission that finds nothing to reshard onto the grown mesh
    is a typed failure: the mesh already grew, so continuing without
    the restore would leave the new host incoherent."""
    with FaultInjector([
            FaultSpec("peer_death", at_step=4, target=2),
            FaultSpec("host_return", at_step=12, target=2)]):
        job = _GrowJob(str(tmp_path))
        job.mgr.every = 10_000                # no cadence save ever
        with pytest.raises(FleetRecoveryFailed):
            with pytest.warns(UserWarning):
                job.run()
    job.close()


def test_grow_sharding_reshards_onto_grown_device_set(
        tmp_path, _grow_reference):
    """The grow restore rides the existing ``sharding=`` reshard flow:
    ``grow_sharding`` (evaluated AFTER the mesh re-init) lands the
    restored state on the LARGER device set, and the replay still
    matches bit-exact."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec

    ndev = min(8, len(jax.devices()))
    if ndev < 2:
        pytest.skip("needs >= 2 devices")
    evaluated = []

    def grow_sharding():
        s = NamedSharding(Mesh(np.array(jax.devices()[:ndev]), ("x",)),
                          PartitionSpec())
        evaluated.append(s)
        return s

    with FaultInjector([
            FaultSpec("peer_death", at_step=4, target=2),
            FaultSpec("host_return", at_step=12, target=2)]):
        job = _GrowJob(str(tmp_path))
        import warnings as _w
        with _w.catch_warnings():
            _w.simplefilter("ignore")
            res = job.run(grow_sharding=grow_sharding)
    assert res.mesh_grows == 1 and len(evaluated) == 1
    for buf in job.opt._param_bufs:
        assert len(buf.sharding.device_set) == ndev
    _assert_tree_equal(job.opt.params, _grow_reference.opt.params)
    job.close()


def test_grow_recovery_rewinds_telemetry_and_resets_watchdog(
        tmp_path):
    """Replay parity with shrink recovery: the grow restore rewinds
    the telemetry session and resets watchdog detector state so the
    replayed steps re-record and stale history cannot re-trigger."""
    from apex_tpu import telemetry as telemetry_mod
    from apex_tpu.resilience.watchdog import Detector

    class _ResetSpy(Detector):
        name = "spy"
        resets = 0

        def observe(self, records):
            return []

        def reset(self):
            self.resets += 1

    tel = telemetry_mod.Telemetry(run_dir=None, window=4,
                                  retrace=False)
    spy = _ResetSpy()
    wd = Watchdog(detectors=[spy], telemetry=tel, clean_window=2)
    job = _GrowJob(str(tmp_path))
    job.mon.telemetry = tel
    rewinds = []
    orig_rewind = tel.rewind
    tel.rewind = lambda s: (rewinds.append(s), orig_rewind(s))[1]
    with FaultInjector([
            FaultSpec("peer_death", at_step=4, target=2),
            FaultSpec("host_return", at_step=12, target=2)]):
        import warnings as _w
        with _w.catch_warnings():
            _w.simplefilter("ignore")
            res = job.run(watchdog=wd)
    assert res.mesh_shrinks == 1 and res.mesh_grows == 1
    grow = next(e for e in job.mon.events if e.get("event") == "grow")
    assert rewinds[-1] == grow["to_step"]     # rewound to the restore
    assert spy.resets >= 2                    # shrink AND grow reset
    wd.close()
    tel.close()
    job.close()


def test_grow_rechunks_bucket_plan_and_replays_bit_exact(tmp_path):
    """``grow_max_bucket_bytes``: per-host HBM changed with the fleet
    size, so the BucketPlan re-chunks on admission and the restore
    lands in the new layout through the reconstruct path — still
    bit-exact (chunk boundaries fall on leaf boundaries)."""
    ref = _GrowJob(str(tmp_path / "ref"), tree_fn=_many_tree)
    assert ref.run().step == _TOTAL
    with FaultInjector([
            FaultSpec("peer_death", at_step=4, target=2),
            FaultSpec("host_return", at_step=12, target=2)]):
        job = _GrowJob(str(tmp_path / "job"), tree_fn=_many_tree)
        nb0 = len(job.opt._plan.buckets)
        caps = []

        def cap_for(members):
            caps.append(tuple(members))
            return 256                        # tiny: forces chunking

        import warnings as _w
        with _w.catch_warnings():
            _w.simplefilter("ignore")
            res = job.run(grow_max_bucket_bytes=cap_for)
    assert res.mesh_grows == 1
    assert caps == [(0, 1, 2)]                # evaluated with members
    assert job.opt._plan.max_bucket_bytes == 256
    assert len(job.opt._plan.buckets) > nb0   # actually re-chunked
    _assert_tree_equal(job.opt.params, ref.opt.params)
    ref.close()
    job.close()


# ---------------------------------------------------------------------
# Reshard-on-grow at the checkpoint layer: {1 -> 2, 2 -> 8}, with and
# without offloaded optimizer state (conftest fakes 8 CPU devices).
# ---------------------------------------------------------------------

@pytest.mark.parametrize("offload", [False, True],
                         ids=["plain", "offloaded"])
def test_reshard_grow_1_to_2(tmp_path, offload):
    from jax.sharding import Mesh, NamedSharding, PartitionSpec
    from apex_tpu import checkpoint as ckpt_mod

    if len(jax.devices()) < 2:
        pytest.skip("needs >= 2 devices")
    tree = _mixed_tree()
    opt = FusedAdam(tree, lr=1e-2, offload_state=offload)
    opt.step(_grads_for(tree))
    p = str(tmp_path / "small.ckpt")
    ckpt_mod.save_training_state(p, optimizer=opt, step=1)

    sharding = NamedSharding(
        Mesh(np.array(jax.devices()[:2]), ("x",)), PartitionSpec())
    opt2 = FusedAdam(_mixed_tree(), lr=1e-2)
    params, _, step = ckpt_mod.load_training_state(
        p, jax.tree_util.tree_map(jnp.zeros_like, tree), opt2,
        sharding=sharding)
    assert step == 1
    for leaf in jax.tree_util.tree_leaves(params):
        assert len(leaf.sharding.device_set) == 2
    _assert_tree_equal(params, opt.params)
    # the grown-mesh replay matches the small-mesh one step for step
    opt.step(_grads_for(tree))
    opt2.step(_grads_for(tree))
    _assert_tree_equal(opt2.params, opt.params)


@pytest.mark.parametrize("offload", [False, True],
                         ids=["plain", "offloaded"])
def test_reshard_grow_2_to_8(tmp_path, offload):
    """A checkpoint genuinely WRITTEN from 2-device state restores
    onto 8 — the grow direction of the reshard flow (per-leaf state:
    the packer declines multi-device trees, exactly the real shape of
    an already-resharded optimizer)."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec
    from apex_tpu import checkpoint as ckpt_mod

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 devices")
    two = NamedSharding(Mesh(np.array(jax.devices()[:2]), ("x",)),
                        PartitionSpec())
    tree = jax.tree_util.tree_map(
        lambda l: jax.device_put(l, two), _mixed_tree())
    opt = FusedAdam(tree, lr=1e-2)
    assert opt._plan is None                  # multi-device: per-leaf
    opt.step(jax.tree_util.tree_map(
        lambda l: jax.device_put(l, two), _grads_for(_mixed_tree())))
    p = str(tmp_path / "two.ckpt")
    ckpt_mod.save_training_state(p, optimizer=opt, step=1)

    eight = NamedSharding(Mesh(np.array(jax.devices()[:8]), ("x",)),
                          PartitionSpec())
    opt2 = FusedAdam(_mixed_tree(), lr=1e-2,
                     offload_state=offload, fuse_buckets=not offload)
    params, _, step = ckpt_mod.load_training_state(
        p, jax.tree_util.tree_map(jnp.zeros_like, _mixed_tree()),
        opt2, sharding=eight)
    assert step == 1
    for leaf in jax.tree_util.tree_leaves(params):
        assert len(leaf.sharding.device_set) == 8
    _assert_tree_equal(params, opt.params)


# ---------------------------------------------------------------------
# Optimizer re-chunking (the max_bucket_bytes half of grow reshard).
# ---------------------------------------------------------------------

def test_rechunk_is_bit_exact_across_layout_change():
    """rechunk() mid-run changes only the packing: N steps monolithic
    + M steps chunked == N+M steps monolithic, bit for bit."""
    tree = _many_tree()
    a = FusedAdam(tree, lr=1e-2)
    b = FusedAdam(_many_tree(), lr=1e-2)
    g = _grads_for(tree)
    for _ in range(3):
        a.step(g)
        b.step(g)
    nb0 = len(a._plan.buckets)
    assert a.rechunk(256) is True
    assert len(a._plan.buckets) > nb0
    assert a.rechunk(256) is False            # idempotent no-op
    for _ in range(3):
        a.step(g)
        b.step(g)
    _assert_tree_equal(a.params, b.params)
    assert int(a.step_count) == int(b.step_count) == 6
    for k in a.opt_state:
        _assert_tree_equal(a._plan.unpack_state_field(a.opt_state[k]),
                           b._plan.unpack_state_field(b.opt_state[k]))


def test_rechunk_offloaded_state_stays_on_host():
    tree = _mixed_tree()
    opt = FusedAdam(tree, lr=1e-2, offload_state=True)
    g = _grads_for(tree)
    opt.step(g)
    assert opt.rechunk(256) is True
    for bufs in opt.opt_state.values():
        for b in bufs:
            assert b.sharding.memory_kind in ("pinned_host",
                                              "unpinned_host")
    ref = FusedAdam(_mixed_tree(), lr=1e-2)
    ref.step(g)
    opt.step(g)
    ref.step(g)
    _assert_tree_equal(opt.params, ref.params)


def test_rechunk_requires_bucketed_path():
    opt = FusedAdam(_mixed_tree(), lr=1e-2, fuse_buckets=False)
    with pytest.raises(RuntimeError, match="bucketed"):
        opt.rechunk(256)


def test_restore_into_rechunked_plan_reconstructs(tmp_path):
    """A checkpoint written under one chunking restores into a
    differently-chunked optimizer (the reconstruct path) — what the
    grow recovery does when grow_max_bucket_bytes changes the cap."""
    from apex_tpu import checkpoint as ckpt_mod

    tree = _many_tree()
    opt = FusedAdam(tree, lr=1e-2)
    g = _grads_for(tree)
    opt.step(g)
    p = str(tmp_path / "mono.ckpt")
    ckpt_mod.save_training_state(p, optimizer=opt, step=1)

    opt2 = FusedAdam(_many_tree(), lr=1e-2, max_bucket_bytes=256)
    assert len(opt2._plan.buckets) > len(opt._plan.buckets)
    params, _, step = ckpt_mod.load_training_state(
        p, jax.tree_util.tree_map(jnp.zeros_like, tree), opt2)
    assert step == 1
    _assert_tree_equal(params, opt.params)
    opt.step(g)
    opt2.step(g)
    _assert_tree_equal(opt2.params, opt.params)


# ---------------------------------------------------------------------
# FleetController decision units (synthetic counter streams).
# ---------------------------------------------------------------------

def _queue_records(value, n=8, start=0):
    return [{"step": start + i, "q": float(value)} for i in range(n)]


def test_controller_validation():
    with pytest.raises(ValueError, match="grow signal"):
        FleetController()
    with pytest.raises(ValueError, match="queue_metric"):
        FleetController(queue_high=10.0)
    with pytest.raises(ValueError, match="low < high"):
        FleetController(step_time_high_s=1.0, step_time_low_s=2.0)
    with pytest.raises(ValueError, match="patience"):
        FleetController(step_time_high_s=1.0, patience=0)


def test_controller_queue_grow_with_patience_and_candidates():
    c = FleetController(queue_metric="q", queue_high=100.0,
                        queue_low=5.0, patience=2, cooldown_steps=10)
    c.observe(_queue_records(500.0))
    d1 = c.decide(1, n_hosts=2, candidates=1)
    assert (d1.action, d1.reason) == ("stay", "patience")
    d2 = c.decide(2, n_hosts=2, candidates=1)
    assert d2.action == "grow" and d2.reason == "queue_depth"
    assert d2.signal == 500.0
    # without a candidate the demand is surfaced, not executed
    c2 = FleetController(queue_metric="q", queue_high=100.0,
                         patience=1)
    c2.observe(_queue_records(500.0))
    d = c2.decide(1, n_hosts=2, candidates=0)
    assert (d.action, d.reason) == ("stay", "grow_wanted_no_candidates")
    c.close()
    c2.close()


def test_controller_shrink_on_low_queue_respects_min_hosts():
    c = FleetController(queue_metric="q", queue_high=100.0,
                        queue_low=5.0, patience=2, min_hosts=2)
    c.observe(_queue_records(1.0))
    c.decide(1, n_hosts=3)
    d = c.decide(2, n_hosts=3)
    assert d.action == "shrink" and d.reason == "queue_depth"
    c.note_resize(2)
    c.observe(_queue_records(1.0))
    # at the floor: stay
    c3 = FleetController(queue_metric="q", queue_high=100.0,
                         queue_low=5.0, patience=1, min_hosts=2)
    c3.observe(_queue_records(1.0))
    assert c3.decide(1, n_hosts=2).reason == "at_min_hosts"
    c.close()
    c3.close()


def test_controller_step_time_signal():
    c = FleetController(step_time_high_s=1.0, step_time_low_s=0.01,
                        patience=1, window=8)
    for s in range(8):
        c.note_step(s, 5.0)
    assert c.decide(9, n_hosts=2, candidates=1).action == "grow"
    c2 = FleetController(step_time_high_s=1.0, step_time_low_s=0.01,
                         patience=1)
    for s in range(8):
        c2.note_step(s, 0.001)
    assert c2.decide(9, n_hosts=2).action == "shrink"
    c.close()
    c2.close()


def test_controller_cooldown_after_any_resize():
    """Hysteresis: note_resize (grow, voluntary shrink, OR a failure
    shrink) holds every decision for cooldown_steps."""
    c = FleetController(queue_metric="q", queue_high=100.0,
                        patience=1, cooldown_steps=10)
    c.observe(_queue_records(500.0))
    assert c.decide(1, n_hosts=2, candidates=1).action == "grow"
    c.note_resize(1)
    d = c.decide(5, n_hosts=3, candidates=1)
    assert (d.action, d.reason) == ("stay", "cooldown")
    d = c.decide(11, n_hosts=3, candidates=1)
    assert d.action == "grow"                 # cooldown expired
    c.close()


def test_controller_never_resizes_inside_open_incident():
    c = FleetController(queue_metric="q", queue_high=100.0,
                        patience=1)
    c.observe(_queue_records(500.0))
    d = c.decide(1, n_hosts=2, candidates=1, incident=True)
    assert (d.action, d.reason) == ("stay", "open_incident")
    # incident_source form (standalone use)
    c2 = FleetController(queue_metric="q", queue_high=100.0,
                         patience=1, incident_source=lambda: True)
    c2.observe(_queue_records(500.0))
    assert c2.decide(1, n_hosts=2, candidates=1).reason == \
        "open_incident"
    c.close()
    c2.close()


def test_controller_holds_while_fleet_degraded():
    """The fleet/hosts_slow counter (riding the hostmetrics sinks)
    parks the controller: never resize under an infrastructure
    wobble."""
    from apex_tpu.telemetry import hostmetrics
    c = FleetController(queue_metric="q", queue_high=100.0,
                        patience=1)
    try:
        c.observe(_queue_records(500.0))
        hostmetrics.emit("fleet/hosts_slow", 1)
        d = c.decide(1, n_hosts=2, candidates=1)
        assert (d.action, d.reason) == ("stay", "fleet_degraded")
        hostmetrics.emit("fleet/hosts_slow", 0)
        assert c.decide(2, n_hosts=2, candidates=1).action == "grow"
    finally:
        c.close()


def test_controller_max_hosts_caps_grow():
    c = FleetController(queue_metric="q", queue_high=100.0,
                        patience=1, max_hosts=3)
    c.observe(_queue_records(500.0))
    assert c.decide(1, n_hosts=3, candidates=1).reason == \
        "at_max_hosts"
    assert c.decide(2, n_hosts=2, candidates=1).action == "grow"
    c.close()


def test_controller_decisions_ride_session_flush(tmp_path):
    """grow/shrink decision events land in the JSONL through the
    session observer (the watchdog/fleet observer discipline)."""
    from apex_tpu import telemetry as telemetry_mod

    run_dir = str(tmp_path / "run")
    tel = telemetry_mod.Telemetry(run_dir, window=4, retrace=False,
                                  metrics=("loss", "q"))
    c = FleetController(telemetry=tel, queue_metric="q",
                        queue_high=100.0, patience=1)
    for s in range(1, 6):
        tel.record({"loss": 1.0, "q": 500.0}, s)
    tel.flush()                               # observer pulls q values
    d = c.decide(6, n_hosts=2, candidates=1)
    assert d.action == "grow"
    c.close()
    tel.close()
    recs = [json.loads(l) for l in
            open(os.path.join(run_dir, "telemetry.jsonl"))]
    autoscale = [r for r in recs if r.get("event") == "autoscale"]
    assert autoscale and autoscale[0]["action"] == "grow"
    assert autoscale[0]["reason"] == "queue_depth"


# ---------------------------------------------------------------------
# run_elastic(autoscale=): controller-driven grow and release.
# ---------------------------------------------------------------------

def test_autoscale_requires_fleet(tmp_path):
    c = FleetController(step_time_high_s=1.0)
    job = _GrowJob(str(tmp_path))
    with pytest.raises(ValueError, match="fleet"):
        run_elastic(job.step_fn, job.mgr, job.opt, total_steps=2,
                    params_like=job.template, autoscale=c)
    c.close()
    job.close()


def test_autoscale_grow_admits_returned_host(tmp_path,
                                             _grow_reference):
    """Controller-driven grow: load is high, a host returns, the grow
    decision executes the admission — and the failure shrink armed
    the controller's cooldown first (note_resize on EVERY resize)."""
    c = FleetController(queue_metric="q", queue_high=100.0,
                        patience=1, cooldown_steps=2)
    c.observe(_queue_records(500.0))          # standing high load
    with FaultInjector([
            FaultSpec("peer_death", at_step=4, target=2),
            FaultSpec("host_return", at_step=12, target=2)]):
        job = _GrowJob(str(tmp_path))
        import warnings as _w
        with _w.catch_warnings():
            _w.simplefilter("ignore")
            res = job.run(autoscale=c)
    assert res.mesh_shrinks == 1 and res.mesh_grows == 1
    assert job.mon.hosts == [0, 1, 2]
    grows = [d for d in c.decisions if d.action == "grow"]
    assert grows and grows[0].reason == "queue_depth"
    # the failure shrink armed the cooldown
    assert any(d.reason == "cooldown" for d in c.decisions)
    _assert_tree_equal(job.opt.params, _grow_reference.opt.params)
    c.close()
    job.close()


def test_autoscale_shrink_releases_highest_rank_peer(
        tmp_path, _grow_reference):
    """Controller-driven release: load is low, the highest-rank peer
    is excluded from the proposal, the mesh shrinks through the same
    machinery (reason=autoscale on the timeline), no retry budget is
    consumed, and the replay stays bit-exact."""
    c = FleetController(queue_metric="q", queue_high=1e9,
                        queue_low=5.0, patience=7, min_hosts=2)
    c.observe(_queue_records(1.0))            # standing low load
    job = _GrowJob(str(tmp_path))
    with pytest.warns(UserWarning, match="autoscaler releasing"):
        res = job.run(autoscale=c, max_restarts=0)
    assert res.step == _TOTAL
    assert res.mesh_shrinks == 1 and res.restarts == 0
    assert job.mon.hosts == [0, 1]            # host 2 released
    shrink = next(e for e in job.mon.events
                  if e.get("event") == "shrink")
    assert shrink["reason"] == "autoscale" and shrink["dead"] == [2]
    # cooldown: exactly one release, no drain-to-min loop
    assert [d.action for d in c.decisions].count("shrink") == 1
    _assert_tree_equal(job.opt.params, _grow_reference.opt.params)
    c.close()
    job.close()


# ---------------------------------------------------------------------
# Telemetry surface: grow/admission/autoscale rows render.
# ---------------------------------------------------------------------

def test_grow_events_land_in_session_jsonl_and_summarize(tmp_path):
    from apex_tpu import telemetry as telemetry_mod
    from apex_tpu.telemetry.cli import summarize

    run_dir = str(tmp_path / "run")
    tel = telemetry_mod.Telemetry(run_dir, window=4, retrace=False)
    ch = LocalChannel()
    mon = _lag_monitor(ch, slow=2, dead=4, telemetry=tel)
    sim = SimulatedPeers(ch, hosts=[1, 2]).attach(mon)
    for s in range(1, 4):
        tel.record({"loss": 1.0}, s)
        mon.beat(s)
    sim.kill(2)
    for s in range(4, 10):
        tel.record({"loss": 1.0}, s)
        mon.beat(s)
    epoch, survivors = mon.agree_survivors(9, timeout_s=0.2)
    mon.note_shrink(9, epoch, survivors, [2], restored_step=6)
    sim.revive(2)
    tel.record({"loss": 1.0}, 10)
    mon.beat(10)
    mon.note_admission_refused(10, mon.return_candidates(),
                               "open_incident")
    epoch, members = mon.agree_admission(11, mon.return_candidates(),
                                         timeout_s=2.0)
    mon.note_grow(11, epoch, members, [2], restored_step=9)
    mon.close()
    tel.close()

    recs = [json.loads(l) for l in
            open(os.path.join(run_dir, "telemetry.jsonl"))]
    fleet_recs = [r for r in recs if r.get("kind") == "fleet"]
    assert {"host_return", "grow", "admission_refused"} <= \
        {r["event"] for r in fleet_recs}
    counters = {r["name"] for r in recs if r.get("kind") == "counter"}
    assert "fleet/mesh_grows" in counters

    out = io.StringIO()
    assert summarize(run_dir, out=out) == 0
    text = out.getvalue()
    assert "host_return" in text and "incarnation=2" in text
    assert "grow" in text and "admitted=[2]" in text
    assert "admission_refused" in text
    assert "reason=open_incident" in text

    out = io.StringIO()
    assert summarize(run_dir, as_json=True, out=out) == 0
    doc = json.loads(out.getvalue())
    assert any(e["event"] == "grow" for e in doc["fleet"])


# ---------------------------------------------------------------------
# Faults, spec registry, bench smoke, result surface.
# ---------------------------------------------------------------------

def test_new_fault_kinds_validate_and_need_at_step():
    for kind in ("host_return", "flapping_host",
                 "grow_during_incident"):
        FaultInjector([FaultSpec(kind, at_step=3)])
        with pytest.raises(ValueError, match="at_step"):
            FaultInjector([FaultSpec(kind)])


def test_simulated_peers_consume_grow_faults():
    ch = LocalChannel()
    sim = SimulatedPeers(ch, hosts=[1, 2])
    sim.kill(2)
    with FaultInjector([FaultSpec("host_return", at_step=5,
                                  target=2)]) as inj:
        sim.beat(5)
        assert inj.fired
    assert 2 not in sim.killed
    assert sim.incarnation_of(2) == 2         # fresh incarnation


def test_simulated_peers_flapping_host_dies_when_budget_expires():
    ch = LocalChannel()
    sim = SimulatedPeers(ch, hosts=[1, 2])
    sim.kill(2)
    with FaultInjector([FaultSpec("flapping_host", at_step=5,
                                  target=2, n_steps=2)]):
        sim.beat(5)
        assert 2 not in sim.killed            # returned
        sim.beat(6)
        assert 2 not in sim.killed            # still alive (budget)
        sim.beat(7)
        assert 2 in sim.killed                # budget spent: flapped


def test_autoscaled_step_spec_registered():
    from apex_tpu.lint import semantic
    names = [s.name for s in semantic.all_specs()]
    assert "fleet.autoscaled_step" in names


def test_autoscaler_overhead_bench_smoke():
    from apex_tpu.telemetry.bench import bench_autoscaler_overhead
    r = bench_autoscaler_overhead(layers=2, hidden=16, window=8,
                                  n_hosts=3, iters=2, reps=1)
    assert r["autoscaler_on_ms"] > 0 and r["autoscaler_off_ms"] > 0
    assert r["autoscaler_decide_ms"] >= 0
    assert r["autoscaler_hosts"] == 3


def test_elastic_result_mesh_grows_defaults_zero():
    from apex_tpu.resilience import ElasticResult
    res = ElasticResult(step=1, preempted=False, restarts=0,
                        restored_from=None)
    assert res.mesh_grows == 0 and res.mesh_shrinks == 0


def test_scale_decision_record_shape():
    d = ScaleDecision("grow", 7, "queue_depth", 512.0)
    rec = d.record()
    assert rec["kind"] == "fleet" and rec["event"] == "autoscale"
    assert rec["action"] == "grow" and rec["signal"] == 512.0
    json.dumps(rec)


def test_grow_mesh_is_inverse_of_shrink_mesh():
    """comm.grow_mesh rebuilds the global mesh over the member set:
    data axis absorbs the growth, minor axes preserved while
    divisible."""
    from apex_tpu import comm
    ndev = len(jax.devices())
    if ndev < 2:
        pytest.skip("needs >= 2 devices")
    try:
        comm.initialize(devices=jax.devices())
        m = comm.grow_mesh([0])               # faked: same process
        assert m is comm.mesh()
        assert comm.config().data * comm.config().pipe * \
            comm.config().ctx * comm.config().model == ndev
    finally:
        comm.destroy()
