import jax
import numpy as np
import pytest

from apex_tpu import comm


def test_eight_virtual_devices():
    assert len(jax.devices()) == 8


def test_initialize_shapes():
    m = comm.initialize(data=2, pipe=2, ctx=1, model=2)
    assert m.devices.shape == (2, 2, 1, 2)
    assert comm.data_parallel_size() == 2
    assert comm.model_parallel_size() == 2
    assert comm.pipeline_parallel_size() == 2
    assert comm.num_devices() == 8


def test_auto_data_axis():
    comm.initialize(model=4)
    assert comm.data_parallel_size() == 2


def test_bad_shape_raises():
    with pytest.raises(ValueError):
        comm.initialize(data=3, model=3)


def test_psum_over_data_axis(mesh8):
    from jax.sharding import PartitionSpec as P
    from jax.experimental.shard_map import shard_map

    x = np.arange(8.0, dtype=np.float32)

    def f(x):
        return jax.lax.psum(x, comm.AXIS_MODEL)

    y = jax.jit(shard_map(
        f, mesh=mesh8,
        in_specs=P(comm.AXIS_MODEL),
        out_specs=P(comm.AXIS_MODEL)))(x)
    # model axis is 4 wide; groups (0..3) and (4..7) under dp=2 ordering
    assert y.shape == (8,)


def test_use_mesh_restores():
    m = comm.initialize(data=8)
    with comm.use_mesh(m):
        assert comm.data_parallel_size() == 8
    comm.destroy()
    assert not comm.is_initialized()


def test_initialize_distributed_single_host(monkeypatch):
    """SURVEY §2.6 multi-host entry: with no coordinator anywhere the
    handshake is skipped and the mesh covers local devices."""
    monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
    monkeypatch.delenv("COORDINATOR_ADDRESS", raising=False)
    called = {}
    monkeypatch.setattr(jax.distributed, "initialize",
                        lambda **kw: called.update(kw))
    m = comm.initialize_distributed(data=2, pipe=2, ctx=1, model=2)
    assert called == {}, "handshake must be skipped without a coordinator"
    assert m.devices.size == 8
    assert comm.process_count() == 1
    assert comm.process_index() == 0


def test_initialize_distributed_passes_coordinates(monkeypatch):
    called = {}
    monkeypatch.setattr(jax.distributed, "initialize",
                        lambda **kw: called.update(kw))
    comm.initialize_distributed(
        coordinator_address="10.0.0.1:1234", num_processes=1,
        process_id=0, data=8)
    assert called == {"coordinator_address": "10.0.0.1:1234",
                      "num_processes": 1, "process_id": 0}
    # timeout= (reference parity: init_process_group(timeout=...))
    # maps to jax's initialization_timeout and is never a mesh axis
    called.clear()
    comm.initialize_distributed(
        coordinator_address="10.0.0.1:1234", num_processes=1,
        process_id=0, timeout=5, data=8)
    assert called["initialization_timeout"] == 5


def test_initialize_distributed_env_var_triggers(monkeypatch):
    called = {}
    monkeypatch.setenv("JAX_COORDINATOR_ADDRESS", "10.0.0.2:999")
    monkeypatch.setattr(jax.distributed, "initialize",
                        lambda **kw: called.update(dict(kw, hit=True)))
    comm.initialize_distributed(data=8)
    assert called.get("hit"), "env coordinator must trigger the handshake"


def test_initialize_distributed_parses_world_size_rank(monkeypatch):
    """VERDICT r2 #8: the launcher env contract (torchrun-style
    WORLD_SIZE/RANK next to a coordinator) must parse to ints and land
    in the initialize() kwargs — a typo here only fails on a real pod."""
    called = {}
    monkeypatch.setenv("JAX_COORDINATOR_ADDRESS", "10.0.0.3:8476")
    monkeypatch.setenv("WORLD_SIZE", "16")
    monkeypatch.setenv("RANK", "3")
    monkeypatch.delenv("NUM_PROCESSES", raising=False)
    monkeypatch.delenv("PROCESS_ID", raising=False)
    monkeypatch.setattr(jax.distributed, "initialize",
                        lambda **kw: called.update(kw))
    comm.initialize_distributed(data=8)
    assert called == {"coordinator_address": "10.0.0.3:8476",
                      "num_processes": 16, "process_id": 3}
    assert isinstance(called["num_processes"], int)
    assert isinstance(called["process_id"], int)


def test_initialize_distributed_env_precedence(monkeypatch):
    """JAX_COORDINATOR_ADDRESS / NUM_PROCESSES / PROCESS_ID win over
    their COORDINATOR_ADDRESS / WORLD_SIZE / RANK fallbacks, and
    explicit arguments beat both."""
    called = {}
    monkeypatch.setenv("JAX_COORDINATOR_ADDRESS", "jax.addr:1")
    monkeypatch.setenv("COORDINATOR_ADDRESS", "plain.addr:2")
    monkeypatch.setenv("NUM_PROCESSES", "4")
    monkeypatch.setenv("WORLD_SIZE", "999")
    monkeypatch.setenv("PROCESS_ID", "2")
    monkeypatch.setenv("RANK", "998")
    monkeypatch.setattr(jax.distributed, "initialize",
                        lambda **kw: called.update(kw))
    comm.initialize_distributed(data=8)
    assert called == {"coordinator_address": "jax.addr:1",
                      "num_processes": 4, "process_id": 2}
    called.clear()
    comm.initialize_distributed(
        coordinator_address="arg.addr:3", num_processes=2, process_id=1,
        data=8)
    assert called == {"coordinator_address": "arg.addr:3",
                      "num_processes": 2, "process_id": 1}


def test_initialize_distributed_pod_markers_autodetect(monkeypatch):
    """A TPU pod runtime (TPU_WORKER_HOSTNAMES set, no explicit
    coordinator) triggers the ARGLESS jax.distributed.initialize()
    autodetect path."""
    for v in ("JAX_COORDINATOR_ADDRESS", "COORDINATOR_ADDRESS",
              "NUM_PROCESSES", "WORLD_SIZE", "PROCESS_ID", "RANK"):
        monkeypatch.delenv(v, raising=False)
    monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "host0,host1")
    called = {}
    monkeypatch.setattr(jax.distributed, "initialize",
                        lambda **kw: called.update(dict(kw, hit=True)))
    comm.initialize_distributed(data=8)
    assert called == {"hit": True}, \
        "pod markers must trigger argless autodetect"


def test_initialize_distributed_reentry_tolerated(monkeypatch):
    """A second handshake (RuntimeError 'already initialized') is
    swallowed; any OTHER RuntimeError propagates."""
    monkeypatch.setenv("JAX_COORDINATOR_ADDRESS", "10.0.0.4:1")

    def already(**kw):
        raise RuntimeError("jax.distributed is already initialized")

    monkeypatch.setattr(jax.distributed, "initialize", already)
    m = comm.initialize_distributed(data=8)     # must not raise
    assert m.devices.size == 8

    def broken(**kw):
        raise RuntimeError("coordinator unreachable")

    monkeypatch.setattr(jax.distributed, "initialize", broken)
    with pytest.raises(RuntimeError, match="unreachable"):
        comm.initialize_distributed(data=8)


def test_physical_mesh_layout_covers_all_devices():
    """physical=True routes through mesh_utils; every device appears
    exactly once and axis sizes match, on any backend."""
    mesh = comm.initialize(data=2, model=4, physical=True)
    assert dict(zip(mesh.axis_names, mesh.devices.shape)) == {
        "data": 2, "pipe": 1, "ctx": 1, "model": 4}
    ids = [d.id for d in mesh.devices.ravel()]
    assert sorted(ids) == sorted(d.id for d in jax.devices())
    comm.destroy()
    # the naive layout stays available
    mesh2 = comm.initialize(data=2, model=4, physical=False)
    assert sorted(d.id for d in mesh2.devices.ravel()) == sorted(ids)
