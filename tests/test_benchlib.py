"""apex_tpu.benchlib: amortized on-device timing must actually run the
measured body every iteration.

The failure modes these tests pin are silent and catastrophic for the
measurements built on top (kernel_bench speedups -> dispatch prefs):
XLA hoisting the loop-invariant body out of the fori_loop, CSE-ing
iterations together, or slicing the body down to the one element a
naive data dependence reads.  All three would make every kernel
"measure" near-zero time.
"""

import time

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu import benchlib


def test_loop_preserves_values_bit_exact():
    """The carried args come back bit-identical: the data coupling is
    a no-op select when outputs are finite, so iteration N sees
    iteration 0's inputs — including exact zeros and -0.0 (an
    epsilon-ADD coupling would fail both: f32 has no 1e-30 underflow,
    and -0.0 + 0.0 is +0.0)."""
    x = jax.random.normal(jax.random.key(0), (64, 64), jnp.float32)
    x = x.at[0, :3].set(jnp.asarray([0.0, -0.0, 1.0]))
    w = jax.random.normal(jax.random.key(1), (64, 64), jnp.bfloat16)
    g = benchlib.loop_on_device(lambda a, b: a @ b.astype(a.dtype), 4)
    ox, ow = g(x, w)
    np.testing.assert_array_equal(
        np.asarray(ox).view(np.uint32), np.asarray(x).view(np.uint32))
    np.testing.assert_array_equal(np.asarray(ow, np.float32),
                                  np.asarray(w, np.float32))


def test_loop_body_not_hoisted_or_dced():
    """Wall time must scale with the iteration count.  A compiler that
    hoists, CSEs, or slices the body runs it (at most) once regardless
    of n, and the n=12 loop times like the n=1 loop.

    CPU-only: through the TPU tunnel a dispatch round trip dwarfs this
    small body, so both loops would time ~one RTT and the ratio says
    nothing about the compiler (the property under test)."""
    if jax.default_backend() != "cpu":
        import pytest
        pytest.skip("timing-ratio assertion is meaningful on CPU only")
    m = 384
    a = jax.random.normal(jax.random.key(0), (m, m), jnp.float32)
    b = jax.random.normal(jax.random.key(1), (m, m), jnp.float32)

    def chain(a, b):
        # 8 chained matmuls: big enough to dwarf loop bookkeeping
        for _ in range(8):
            a = jnp.tanh(a @ b)
        return a

    def best_of(g, reps=5):
        benchlib.sync(g(a, b))
        ts = []
        for _ in range(reps):
            t0 = time.perf_counter()
            benchlib.sync(g(a, b))
            ts.append(time.perf_counter() - t0)
        return min(ts)

    t1 = best_of(benchlib.loop_on_device(chain, 1))
    t12 = best_of(benchlib.loop_on_device(chain, 12))
    assert t12 > 4 * t1, (
        f"n=12 loop took {t12:.4f}s vs n=1 {t1:.4f}s — body not "
        f"executed per iteration (hoisted/DCEd/sliced)")


def test_loop_multi_output_keeps_all_outputs_live():
    """A body returning several leaves (grad tuples) must keep every
    leaf's computation: check the loop still returns exact inputs and
    runs with a tuple-returning body."""
    q = jax.random.normal(jax.random.key(0), (8, 128), jnp.float32)

    def body(x):
        return (x @ x.T, jnp.sum(x, axis=0), x * 2.0)

    g = benchlib.loop_on_device(body, 3)
    (oq,) = g(q)
    np.testing.assert_array_equal(np.asarray(oq), np.asarray(q))


def test_timeit_and_overhead_smoke():
    ms = benchlib.timeit(lambda x: x * 2.0,
                         jnp.ones((128, 128), jnp.float32),
                         iters=4, reps=2)
    assert ms > 0
    assert benchlib.dispatch_overhead_ms(reps=3) > 0


def test_timeit_adaptive_converges_past_relay_share(monkeypatch):
    """ADVICE r4: a 50 µs body probed through a 10 ms RTT must re-loop
    until one dispatch runs ~200 ms of wall (relay share <= ~6%) — the
    old single re-loop capped at 500 iterations left ~28% relay share
    and biased every fast kernel's speedup toward 1.  Simulated clock:
    wall per dispatch = RTT + n * body."""
    body_ms, rtt_ms = 0.05, 10.0
    clock = [0.0]
    ns = []

    class FakeG:
        def __init__(self, n):
            self.n = n

        def __call__(self, *a):
            ns.append(self.n)
            clock[0] += (rtt_ms + self.n * body_ms) / 1e3
            return jnp.float32(0)

    monkeypatch.setattr(benchlib, "loop_on_device",
                        lambda f, n: FakeG(n))
    monkeypatch.setattr(benchlib, "sync", lambda o: None)
    monkeypatch.setattr(benchlib.time, "perf_counter",
                        lambda: clock[0])

    ms = benchlib.timeit(lambda x: x, None, iters=20, adaptive=True)
    n_final = ns[-1]
    assert n_final * body_ms + rtt_ms >= 180.0      # target body met
    assert ms <= body_ms * 1.06                     # <= ~6% residual
    assert len({n for n in ns}) >= 3                # probed, re-looped
    # non-adaptive keeps the probe's relay-dominated number
    clock[0] = 0.0
    ns.clear()
    ms_raw = benchlib.timeit(lambda x: x, None, iters=20,
                             adaptive=False)
    assert ms_raw > body_ms * 5                     # RTT-dominated


def test_int_only_args_still_loop():
    """No floating-point arg to perturb: the int fallback arm."""
    x = jnp.arange(256, dtype=jnp.int32)
    g = benchlib.loop_on_device(lambda a: a * 2, 3)
    (ox,) = g(x)
    np.testing.assert_array_equal(np.asarray(ox), np.asarray(x))


def test_chunked_train_bench_threads_state():
    """The chunked loop must run step_fn chunk*n_chunks times with the
    carry threaded exactly like a Python loop (same final state), and
    report a positive per-step time."""
    def step_fn(state, step, lr):
        w, loss = state
        w = w - lr * (w - 3.0)
        return (w, jnp.mean(w))

    w0 = jnp.full((8,), 10.0)
    lr = jnp.float32(0.5)
    r = benchlib.chunked_train_bench(
        step_fn, (w0, jnp.float32(0)), (lr,), steps=6, chunk=3,
        want_flops=False)
    assert r["step_ms"] > 0
    assert r["steps_per_dispatch"] == 3
    assert r["flops_per_step"] is None
    # warmup chunk + 2 timed chunks = 9 steps total
    w_ref = np.full((8,), 10.0, np.float32)
    for _ in range(9):
        w_ref = w_ref - 0.5 * (w_ref - 3.0)
    np.testing.assert_allclose(np.asarray(r["state"][0]), w_ref,
                               rtol=1e-6)
