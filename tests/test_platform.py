"""apex_tpu.platform: the backend-override and compile-cache knobs
every tool (bench.py, tools/*) depends on.  A regression here silently
turns 'run on CPU' into 'hang claiming the TPU tunnel' — the exact
failure mode select_platform exists to prevent under sitecustomize
hooks that override JAX_PLATFORMS."""

import jax

from apex_tpu import platform as plat


def _restore(key, value):
    jax.config.update(key, value)


def test_select_platform_env_and_arg(monkeypatch):
    orig = jax.config.jax_platforms
    try:
        monkeypatch.delenv("APEX_TPU_PLATFORM", raising=False)
        assert plat.select_platform() is None      # env default kept
        monkeypatch.setenv("APEX_TPU_PLATFORM", "cpu")
        assert plat.select_platform() == "cpu"     # env honored
        monkeypatch.setenv("APEX_TPU_PLATFORM", "something-else")
        assert plat.select_platform("cpu") == "cpu"  # arg beats env
        assert jax.config.jax_platforms == "cpu"
    finally:
        _restore("jax_platforms", orig)


def test_enable_compilation_cache_config(monkeypatch):
    orig_dir = jax.config.jax_compilation_cache_dir
    orig_min = jax.config.jax_persistent_cache_min_compile_time_secs
    try:
        plat.enable_compilation_cache(min_compile_secs=2.5)
        assert str(jax.config.jax_compilation_cache_dir).endswith(
            ".jax_cache")
        assert (jax.config.jax_persistent_cache_min_compile_time_secs
                == 2.5)
    finally:
        _restore("jax_compilation_cache_dir", orig_dir)
        _restore("jax_persistent_cache_min_compile_time_secs", orig_min)
