"""apex_tpu.parallel (reference: apex/parallel).

Data-parallel utilities over the global mesh's "data" axis: DDP-shaped
gradient reduction, SyncBatchNorm with cross-device Welford stats, LARC.
``multiproc`` has no TPU analog (SPMD replaces process-per-GPU launch);
``jax.distributed.initialize()`` is the multi-host entry point.
"""

from apex_tpu.parallel.distributed import (
    DistributedDataParallel,
    Reducer,
    all_reduce_flat_buffers,
    all_reduce_gradients,
    broadcast_params,
    flat_dist_call,
)
from apex_tpu.parallel.sync_batchnorm import (
    SyncBatchNorm,
    convert_syncbn_model,
    sync_batch_norm_stats,
)
from apex_tpu.parallel.LARC import LARC

__all__ = [
    "DistributedDataParallel", "Reducer", "all_reduce_gradients",
    "all_reduce_flat_buffers",
    "broadcast_params", "flat_dist_call",
    "SyncBatchNorm", "convert_syncbn_model", "sync_batch_norm_stats",
    "LARC",
]
