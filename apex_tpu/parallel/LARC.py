"""LARC — Layer-wise Adaptive Rate Clipping (reference:
apex/parallel/LARC.py).

Wraps any apex_tpu fused optimizer: before delegating to the inner
``step``, each leaf's gradient is rescaled by the layer's adaptive LR
  adaptive_lr = trust_coefficient * ||p|| / (||g|| + wd * ||p|| + eps)
clipped at the group LR when ``clip=True`` (so the effective LR never
exceeds the scheduled one).  Weight decay is folded into the gradient
here and zeroed in the inner optimizer for that step — the reference does
the same dance with param_groups.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


class LARC:
    def __init__(self, optimizer, trust_coefficient: float = 0.02,
                 clip: bool = True, eps: float = 1e-8):
        self.optim = optimizer
        self.trust_coefficient = trust_coefficient
        self.clip = clip
        self.eps = eps

    # delegate the optimizer surface
    @property
    def params(self):
        return self.optim.params

    def state_dict(self):
        return self.optim.state_dict()

    def load_state_dict(self, sd):
        self.optim.load_state_dict(sd)

    def zero_grad(self):
        self.optim.zero_grad()

    def _adapt(self, params, grads):
        lr = jnp.float32(self.optim.hypers["lr"])
        wd = jnp.float32(self.optim.hypers.get("weight_decay", 0.0))
        trust = jnp.float32(self.trust_coefficient)

        def leaf(p, g):
            pf = p.astype(jnp.float32)
            gf = g.astype(jnp.float32)
            p_norm = jnp.sqrt(jnp.sum(pf * pf))
            g_norm = jnp.sqrt(jnp.sum(gf * gf))
            adaptive = trust * p_norm / (g_norm + wd * p_norm + self.eps)
            # undefined ratio (zero norms) -> no adaptation, as reference
            adaptive = jnp.where((p_norm > 0) & (g_norm > 0), adaptive, 1.0)
            if self.clip:
                adaptive = jnp.minimum(adaptive / lr, 1.0)
            return ((gf + wd * pf) * adaptive).astype(g.dtype)

        return jax.tree_util.tree_map(leaf, params, grads)

    def step(self, grads, grad_scale=1.0):
        work = self.optim.masters if self.optim.masters is not None \
            else self.optim.params
        # Unscale BEFORE adapting: the trust ratio and the folded-in decay
        # must see true gradients, not loss-scaled ones.
        if grad_scale != 1.0:
            inv = 1.0 / jnp.float32(grad_scale)
            grads = jax.tree_util.tree_map(
                lambda g: (g.astype(jnp.float32) * inv).astype(g.dtype),
                grads)
        grads = self._adapt(work, grads)
        saved_wd = self.optim.hypers.get("weight_decay", 0.0)
        self.optim.hypers["weight_decay"] = 0.0
        try:
            return self.optim.step(grads, grad_scale=1.0)
        finally:
            self.optim.hypers["weight_decay"] = saved_wd
